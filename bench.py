"""Benchmark runner: one JSON line per suite mode; headline line LAST.

Runs the reference's benchmark suite (BASELINE.md / ref common/src/benchmark.rs
:40-76) end-to-end through the engine on the available accelerator and reports
numbers/sec/chip per mode. The final stdout line is the headline metric
(detailed extra-large — 1e9 @ base 40, one production server field) with the
whole suite embedded under "suite", so a driver that records only the last
JSON line still captures everything.

vs_baseline for detailed modes compares against the north-star per-chip target
of 1.25e8 numbers/sec/chip (BASELINE.json: 1e9 field in <1 s on a v5e-8, >50x
the reference CUDA client). Niceonly modes compare against 20x that, the
reference's measured niceonly-vs-detailed speedup (ref common/src/lib.rs:49-50).

TPU init is guarded: a transient backend failure (the axon tunnel is
occasionally unavailable) re-execs this process after a backoff so jax's
cached backend state is reset; after the final attempt a JSON line with an
"error" key is printed — never a bare traceback.

Variance note: modes finishing under ~0.3 s (msd-ineffective, msd-effective,
niceonly extra-large) are bounded by ONE device->host readback round-trip,
whose latency through the axon tunnel swings 30-110 ms hour to hour — their
lines jitter 2-3x run to run with no code change. Only modes >= ~2 s
(hi-base, massive, the detailed headline) are stable benchmarks of compute.

Env knobs:
  NICE_BENCH_MODE    run only this mode (e.g. "extra-large")
  NICE_BENCH_SUITE   comma-separated mode:kind list overriding the default
                     suite (kind = detailed|niceonly)
  NICE_BENCH_BATCH   lanes per dispatch (default: per-mode table below)
"""

from __future__ import annotations

import json
import os
import sys
import time

NORTH_STAR_DETAILED = 1.25e8  # numbers/sec/chip, BASELINE.json north star
NICEONLY_SPEEDUP = 20.0  # ref common/src/lib.rs:49-50, README.md:70
MAX_INIT_ATTEMPTS = 3

# (mode, kind): batch lanes on TPU. Large bases carry more u32 limbs per lane,
# so their per-batch VMEM/HBM footprint is bigger and the batch shrinks.
# Off-TPU the jnp fallback materializes per-lane intermediates in host RAM and
# every mode drops to 1<<20.
_TPU_BATCH = {
    # Committed sweep (scripts/tune_kernels.py, round 4, 1e9 slices on a
    # v5e chip, threaded collector + BLOCK_ROWS=128 + single-division digit
    # extraction with free chunk-final digits): extra-large
    # 2^27/2^28/2^29 -> 896/1454/1698 M n/s (2^29 best: fewest per-batch
    # dispatch round-trips; 2^30 pays 7% tail padding); hi-base
    # 2^25/2^26/2^27 -> 242/438/392 M n/s (2^26 best — compute-bound at
    # b80's 3-limb digit extraction).
    ("extra-large", "detailed"): 1 << 29,
    ("extra-large", "niceonly"): 1 << 20,  # strided path; batch is unused
    ("hi-base", "detailed"): 1 << 26,
    ("msd-ineffective", "niceonly"): 1 << 22,
    ("msd-effective", "niceonly"): 1 << 22,
    ("massive", "niceonly"): 1 << 22,
}

# Default suite: fast modes first, the headline (detailed extra-large) last so
# it is the final stdout line. The filter cascade makes even the huge niceonly
# modes cheap: msd-effective (1e12 @ b50) is FULLY killed by the host MSD
# prefix filter at its range start (0 surviving candidates, ~ms), and massive
# (1e13 @ b50) survives at ~11% into ~5e5 stride descriptors (measured; ~1.4 s
# host filter at floor 2^20 on one core).
DEFAULT_SUITE = (
    ("msd-ineffective", "niceonly"),
    ("msd-effective", "niceonly"),
    ("hi-base", "detailed"),
    ("extra-large", "niceonly"),
    ("massive", "niceonly"),
    ("extra-large", "detailed"),
)
HEADLINE = ("extra-large", "detailed")

# Natural kind for a bare NICE_BENCH_MODE: the msd-* and massive modes are
# niceonly benchmarks in the reference suite (benchmark.rs:40-76).
_MODE_KIND = {
    "massive": "niceonly",
    "msd-effective": "niceonly",
    "msd-ineffective": "niceonly",
}


def _init_jax():
    """Import jax and force backend init, re-exec'ing on transient failure.

    Two failure shapes are handled (both observed on the axon tunnel):
    an exception from backend init, and an indefinite HANG in jax.devices()
    (a wedged chip lease) — so the probe runs in a watchdog thread. jax
    caches a failed backend, so an in-process retry would see the same
    error; exec gives every attempt a clean process (the analog of the
    reference client's 10-retry exponential backoff around claim/submit,
    ref README.md:82-86, applied to device acquisition).

    NICE_BENCH_PLATFORM forces a platform (e.g. "cpu") AFTER import via
    jax.config.update — the env var alone is not enough because the axon
    PJRT plugin overrides JAX_PLATFORMS at import time (see
    nice_tpu/utils/platform.py).
    """
    from nice_tpu.utils.platform import probe_backend

    attempt = int(os.environ.get("NICE_BENCH_ATTEMPT", "1"))
    n_chips, exc = probe_backend(
        timeout_s=float(os.environ.get("NICE_BENCH_INIT_TIMEOUT", "180")),
        platform=os.environ.get("NICE_BENCH_PLATFORM"),
    )

    if exc is not None:
        if attempt < MAX_INIT_ATTEMPTS:
            time.sleep(10 * attempt)
            env = dict(os.environ, NICE_BENCH_ATTEMPT=str(attempt + 1))
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        print(
            json.dumps(
                {
                    "metric": "numbers/sec/chip (benchmark suite)",
                    "value": 0,
                    "unit": "numbers/sec/chip",
                    "vs_baseline": 0,
                    "error": (
                        f"jax backend init failed after {attempt} attempts: "
                        f"{exc!r}"
                    ),
                },
            ),
            flush=True,
        )
        os._exit(1)  # a hung init thread cannot be joined; exit hard

    import jax

    return jax, n_chips


def _run_mode(mode: str, kind: str, batch_size: int, n_chips: int) -> dict:
    from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine

    data = get_benchmark_field(BenchmarkMode(mode))
    batch_size = min(
        batch_size, max(1 << 18, 1 << (data.range_size - 1).bit_length())
    )

    if kind == "detailed":
        run = lambda rng: engine.process_range_detailed(  # noqa: E731
            rng, data.base, backend="jax", batch_size=batch_size
        )
    else:
        run = lambda rng: engine.process_range_niceonly(  # noqa: E731
            rng, data.base, backend="jax", batch_size=batch_size
        )

    # Warm-up compile with the SAME kernel shape so the timed run measures
    # throughput, not compile time. Detailed probes a 1-number field (stats
    # kernels are jitted per (base, batch)); niceonly warms via
    # engine.warm_niceonly with the REAL field size — a probe field would
    # compile a different kernel (the huge-field floor guard shapes the
    # strided kernel by field size) and leave the real one cold.
    import jax

    if kind == "niceonly" and jax.default_backend() == "tpu":
        engine.warm_niceonly(data.base, data.range_size)
    else:
        # Detailed modes probe a 1-number field; off-TPU niceonly takes the
        # dense jnp path (which warm_niceonly does not compile), so the
        # probe field warms whichever kernel the timed run will use.
        run(FieldSize(data.range_start, data.range_start + 1))

    rng = data.to_field_size()
    t0 = time.monotonic()
    results = run(rng)
    elapsed = time.monotonic() - t0

    if kind == "detailed":
        total = sum(d.count for d in results.distribution)
        assert total == data.range_size, (total, data.range_size)
        baseline = NORTH_STAR_DETAILED
    else:
        baseline = NORTH_STAR_DETAILED * NICEONLY_SPEEDUP
    value = data.range_size / elapsed / n_chips
    return {
        "metric": f"numbers/sec/chip {kind} ({mode}, base {data.base})",
        "value": round(value, 1),
        "unit": "numbers/sec/chip",
        "vs_baseline": round(value / baseline, 3),
        "elapsed_secs": round(elapsed, 3),
        "range_size": data.range_size,
        "n_chips": n_chips,
        "hits": len(results.nice_numbers),
    }


def _parse_suite(raw: str) -> tuple:
    suite = []
    for entry in raw.split(","):
        mode, sep, kind = entry.strip().partition(":")
        if not sep or kind not in ("detailed", "niceonly"):
            raise ValueError(
                f"NICE_BENCH_SUITE entry {entry!r} must be <mode>:detailed"
                f" or <mode>:niceonly"
            )
        suite.append((mode, kind))
    return tuple(suite)


def main() -> int:
    jax, n_chips = _init_jax()

    try:
        if os.environ.get("NICE_BENCH_SUITE"):
            suite = _parse_suite(os.environ["NICE_BENCH_SUITE"])
        elif os.environ.get("NICE_BENCH_MODE"):
            mode = os.environ["NICE_BENCH_MODE"]
            suite = tuple(
                (m, k) for (m, k) in DEFAULT_SUITE if m == mode
            ) or ((mode, _MODE_KIND.get(mode, "detailed")),)
        else:
            suite = DEFAULT_SUITE
    except ValueError as exc:
        # Still a JSON line, never a bare traceback (driver contract).
        print(
            json.dumps(
                {
                    "metric": "numbers/sec/chip (benchmark suite)",
                    "value": 0,
                    "unit": "numbers/sec/chip",
                    "vs_baseline": 0,
                    "error": str(exc),
                }
            ),
            flush=True,
        )
        return 1

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        # Off-TPU the Pallas kernels run in interpreter mode (tiny descriptor
        # groups), so the 1e13 massive field would take hours: real-chip only.
        suite = tuple((m, k) for (m, k) in suite if m != "massive") or suite
    results: dict[tuple, dict] = {}
    headline = None
    for mode, kind in suite:
        default_batch = _TPU_BATCH.get((mode, kind), 1 << 22) if on_tpu else 1 << 20
        batch = int(os.environ.get("NICE_BENCH_BATCH", default_batch))
        try:
            line = _run_mode(mode, kind, batch, n_chips)
        except Exception as exc:  # noqa: BLE001 — report and keep benching
            line = {
                "metric": f"numbers/sec/chip {kind} ({mode})",
                "value": 0,
                "unit": "numbers/sec/chip",
                "vs_baseline": 0,
                "error": repr(exc),
            }
        results[(mode, kind)] = line
        if (mode, kind) == HEADLINE:
            headline = line  # print last
        else:
            print(json.dumps(line), flush=True)

    if headline is None:
        # Single-mode run: re-print that mode's line last as the headline.
        headline = line
    headline = dict(headline)
    headline["suite"] = {
        f"{kind}/{mode}": {
            k: v
            for k, v in r.items()
            if k in ("value", "vs_baseline", "elapsed_secs", "error", "hits")
        }
        for (mode, kind), r in results.items()
    }
    print(json.dumps(headline), flush=True)
    return 1 if any("error" in r for r in results.values()) else 0


if __name__ == "__main__":
    sys.exit(main())
