"""Benchmark runner: one JSON line per suite mode; headline line FIRST and LAST.

Runs the reference's benchmark suite (BASELINE.md / ref common/src/benchmark.rs
:40-76) end-to-end through the engine on the available accelerator and reports
numbers/sec/chip per mode.

The record is designed to be UN-LOSABLE under a driver wall-clock kill:

- The headline mode (detailed extra-large — 1e9 @ base 40, one production
  server field) runs FIRST and its line is printed immediately as a
  provisional record, so even a kill one second later leaves a headline on
  stdout. It is printed again as the FINAL line with the whole suite embedded
  under "suite" (a driver that records only the last JSON line captures
  everything; a driver that kills mid-suite still has the provisional line).
- The whole process tree (init attempts included) runs under a wall budget
  (NICE_BENCH_BUDGET, default 480 s) measured from NICE_BENCH_T0 — set once
  and carried across init re-execs via the environment (CLOCK_MONOTONIC is
  boot-relative, so the value stays comparable across execve). A mode whose
  conservative cost estimate exceeds the remaining budget is skipped with an
  explicit {"skipped": "budget"} line instead of the process dying mid-mode.
- Every mode additionally runs under a PER-CASE wall budget in a worker
  thread, clipped so the cases still queued behind it keep their reserved
  share of the remaining budget (one slow case can no longer starve the
  suite into the driver's rc=124 kill — BENCH r04). A case that blows its
  budget but finishes within a short grace window is recorded with its real
  numbers and over_budget=true; only a worker still running after the grace
  is treated as wedged — recorded as an error line, the (possibly wedged)
  device is not handed the remaining modes ({"skipped": "timeout-wedge"}),
  and the final headline line is still printed. Every line (skips included)
  carries case_elapsed_secs, and executed cases case_budget_secs.
- TPU init is guarded with SHORT, budget-aware attempt timeouts (60/90/120 s,
  clamped to the remaining budget): a transient backend failure (the axon
  tunnel is occasionally unavailable) re-execs this process so jax's cached
  backend state is reset; after the final attempt a JSON line with an
  "error" key is printed — never a bare traceback, and never a silent
  budget-consuming hang.

vs_baseline for detailed modes compares against the north-star per-chip target
of 1.25e8 numbers/sec/chip (BASELINE.json: 1e9 field in <1 s on a v5e-8, >50x
the reference CUDA client). Niceonly modes compare against 20x that, the
reference's measured niceonly-vs-detailed speedup (ref common/src/lib.rs:49-50).

Per-field engine phase traces (floor, stride depth, descriptor count, per-stage
busy seconds — engine.py's niceonly trace) are emitted at INFO on stderr during
the run, so the driver artifact's tail carries the phase split of every mode.

Variance note: modes finishing under ~0.3 s (msd-effective, and
msd-ineffective before the round-5 host fast path) are bounded by ONE
device->host readback round-trip, whose latency through the axon tunnel swings
30-110 ms hour to hour — their lines jitter 2-3x run to run with no code
change. Only modes >= ~2 s are stable benchmarks of compute.

CLI:
  --only MODE        run only this mode (same semantics as NICE_BENCH_MODE,
                     but composable with a driver that passes argv — e.g.
                     `bench.py --only hi-base` for the CI perf-gate's short
                     hi-base case)

Env knobs:
  NICE_BENCH_MODE    run only this mode (e.g. "extra-large")
  NICE_BENCH_SUITE   comma-separated mode:kind list overriding the default
                     suite (kind = detailed|niceonly)
  NICE_BENCH_SIZE    clamp every case's field to at most this many numbers
                     (recorded as range_clamped=true; lets the CPU perf gate
                     EXECUTE the 1e9 hi-base case as a short slice instead of
                     budget-skipping it — BENCH r04 rc=124, r06 budget-skip)
  NICE_BENCH_BATCH   lanes per dispatch (default: per-mode table below)
  NICE_BENCH_BUDGET  wall budget in seconds for the whole run (default 480)
  NICE_BENCH_INIT_TIMEOUT  cap on EACH backend-init attempt (default 60/90/120
                     by attempt, always clamped to the remaining budget)
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time

NORTH_STAR_DETAILED = 1.25e8  # numbers/sec/chip, BASELINE.json north star
NICEONLY_SPEEDUP = 20.0  # ref common/src/lib.rs:49-50, README.md:70
DEFAULT_BUDGET = 480.0

# Init attempts keep re-execing until less than this much budget remains
# (enough for one short attempt plus the headline mode). There is NO attempt
# cap: a flaky tunnel that comes back on attempt 7 still produces a record
# (VERDICT task #1 — two whole rounds were blanked by a 3-attempt cap).
_INIT_RETRY_FLOOR = 120.0

# (mode, kind): batch lanes on TPU. Large bases carry more u32 limbs per lane,
# so their per-batch VMEM/HBM footprint is bigger and the batch shrinks.
# Off-TPU the jnp fallback materializes per-lane intermediates in host RAM and
# every mode drops to 1<<20.
_TPU_BATCH = {
    # Committed sweep (scripts/tune_kernels.py, round 4, 1e9 slices on a
    # v5e chip, threaded collector + BLOCK_ROWS=128 + single-division digit
    # extraction with free chunk-final digits): extra-large
    # 2^27/2^28/2^29 -> 896/1454/1698 M n/s (2^29 best: fewest per-batch
    # dispatch round-trips; 2^30 pays 7% tail padding); hi-base
    # 2^25/2^26/2^27 -> 242/438/392 M n/s (2^26 best — compute-bound at
    # b80's 3-limb digit extraction).
    ("extra-large", "detailed"): 1 << 29,
    ("extra-large", "niceonly"): 1 << 20,  # strided path; batch is unused
    ("hi-base", "detailed"): 1 << 26,
    ("msd-ineffective", "niceonly"): 1 << 22,
    ("msd-effective", "niceonly"): 1 << 22,
    ("massive", "niceonly"): 1 << 22,
}

# Conservative per-mode wall-cost estimates (first-run Mosaic/XLA compile
# INCLUDED — each distinct kernel shape costs ~20-40 s to compile in a fresh
# process). Used only for the skip-vs-run budget decision; the hard per-mode
# cap is separate (below). Measured landmarks: r3 driver artifact + round-4/5
# builder runs.
_EST_SECS = {
    ("extra-large", "detailed"): 75.0,
    ("msd-effective", "niceonly"): 45.0,
    ("msd-ineffective", "niceonly"): 20.0,
    ("extra-large", "niceonly"): 45.0,
    ("hi-base", "detailed"): 60.0,
    ("multi-tenant", "detailed"): 60.0,
    ("massive", "niceonly"): 230.0,
}
_EST_DEFAULT = 60.0

# Hard per-mode wall caps (worker-thread join timeout). A mode that blows its
# cap has almost certainly wedged on the device tunnel; the run is recorded
# as an error and the remaining non-headline modes are skipped.
_CAP_SECS = {
    ("massive", "niceonly"): 330.0,
}
_CAP_DEFAULT = 150.0

# Grace window after a case blows its per-case budget: a worker still making
# progress gets this long to finish and be recorded with over_budget=true
# instead of being discarded as wedged. (BENCH r04: one slow case rode the
# whole process into the driver's rc=124 kill, starving every later case of
# its record — the per-case budget + grace turns that into one over-budget
# line plus a full suite.)
_CASE_GRACE_SECS = 15.0

# Default suite: the HEADLINE (detailed extra-large) first so its provisional
# line exists from the first seconds of the run; cheap modes next; massive
# (the only multi-minute mode) last so a budget overrun can only ever cost
# massive itself. The filter cascade makes even the huge niceonly modes
# cheap: msd-effective (1e12 @ b50) is FULLY killed by the host MSD prefix
# filter at its range start (0 surviving candidates, ~ms), and massive
# (1e13 @ b50) survives at ~11% into ~4e5 stride descriptors.
DEFAULT_SUITE = (
    ("extra-large", "detailed"),
    ("msd-effective", "niceonly"),
    ("msd-ineffective", "niceonly"),
    ("extra-large", "niceonly"),
    ("hi-base", "detailed"),
    ("multi-tenant", "detailed"),
    ("massive", "niceonly"),
)
HEADLINE = ("extra-large", "detailed")

# Natural kind for a bare NICE_BENCH_MODE: the msd-* and massive modes are
# niceonly benchmarks in the reference suite (benchmark.rs:40-76).
_MODE_KIND = {
    "massive": "niceonly",
    "msd-effective": "niceonly",
    "msd-ineffective": "niceonly",
}

# Per-attempt init timeouts (VERDICT r4 weak #5: two judge-side runs spent
# their whole allocation inside 180 s init watchdogs). First attempt is
# short — a healthy tunnel initializes in ~15-40 s; a slow-but-alive chip
# gets progressively longer later attempts. Attempts past the table reuse its
# last entry, and EVERY attempt is clamped to the remaining budget, so late
# attempts shrink toward the 15 s floor as the budget drains — init can never
# eat the suite, and retries continue until _INIT_RETRY_FLOOR.
_INIT_TIMEOUTS = (60.0, 90.0, 120.0)


def _stale_reference():
    """Most recent committed driver-verified bench record (BENCH_r*.json with
    rc == 0 and a parsed value), for the stale_reference block: a tunnel
    outage must degrade the round to last round's verified numbers, never
    blank it."""
    import glob
    import re

    root = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
        if m is None:
            continue
        rnd = int(m.group(1))
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") or {}
        if rec.get("rc") != 0 or not parsed.get("value"):
            continue
        if best is None or rnd > best[0]:
            best = (rnd, parsed)
    if best is None:
        return None
    rnd, parsed = best
    out = {"round": rnd, "note": "last committed driver-verified record"}
    for k in ("metric", "value", "unit", "vs_baseline", "elapsed_secs",
              "range_size", "n_chips", "suite"):
        if k in parsed:
            out[k] = parsed[k]
    return out


def _budget_clock():
    """(remaining_fn, budget): wall budget accounting shared across re-execs."""
    t0 = os.environ.get("NICE_BENCH_T0")
    if t0 is None:
        t0 = repr(time.monotonic())
        os.environ["NICE_BENCH_T0"] = t0
    t0 = float(t0)
    _PHASE_T0[0] = t0  # phase-line timeline shares the budget clock origin
    budget = float(os.environ.get("NICE_BENCH_BUDGET", DEFAULT_BUDGET))
    return (lambda: budget - (time.monotonic() - t0)), budget


# Phase-stamped JSON progress lines (stderr, flushed): a killed or wedged run
# still leaves a parseable timeline saying which phase was in flight. The
# timeline clock t is seconds since NICE_BENCH_T0, so lines from re-exec'd
# init attempts stay on one monotonic axis (BENCH r4/r5 both captured zero
# numbers AND zero evidence of where init died; these lines are the fix).
_PHASE_T0 = [None]


def _phase(phase: str, event: str, **fields) -> None:
    t0 = _PHASE_T0[0]
    rec = {
        "bench_phase": phase,
        "event": event,
        "t": round(time.monotonic() - t0, 3) if t0 is not None else None,
    }
    rec.update(fields)
    print(json.dumps(rec), file=sys.stderr, flush=True)


def _error_line(metric: str, error: str) -> dict:
    return {
        "metric": metric,
        "value": 0,
        "unit": "numbers/sec/chip",
        "vs_baseline": 0,
        "error": error,
    }


def _span_sums() -> dict:
    """{span name: (wall_secs, count)} snapshot of the trace-span histogram.

    Differencing two snapshots yields a per-phase wall-time summary for the
    window between them — where inside the engine (dispatch vs collect vs
    stats readback) a mode's wall clock actually went, without needing the
    JSON trace sink enabled."""
    from nice_tpu.obs.trace import SPAN_SECONDS

    return {key[0]: (s, c) for key, (s, c) in SPAN_SECONDS.label_sums().items()}


def _span_delta(before: dict, after: dict) -> dict:
    """{span name: {"wall_secs": s, "count": n}} for spans that ran."""
    out = {}
    for name, (s1, c1) in after.items():
        s0, c0 = before.get(name, (0.0, 0))
        if c1 - c0:
            out[name] = {"wall_secs": round(s1 - s0, 3), "count": c1 - c0}
    return out


def _stepprof_sums() -> dict:
    """Snapshot of the device-step profiler's cumulative phase table
    (empty unless NICE_TPU_STEPPROF=1 — see nice_tpu/obs/stepprof.py)."""
    from nice_tpu.obs import stepprof

    return stepprof.cumulative()


def _stepprof_delta(before: dict, after: dict) -> dict:
    """Per-(mode|base|backend) phase-seconds delta between two snapshots —
    the same windowing idiom as _span_delta, over the profiler table."""
    out = {}
    for key, cur in after.items():
        prev = before.get(key, {})
        fields = int(cur.get("fields", 0)) - int(prev.get("fields", 0))
        if not fields:
            continue
        d = {
            k: round(float(v) - float(prev.get(k, 0.0)), 6)
            for k, v in cur.items() if k != "fields"
        }
        d["fields"] = fields
        out[key] = d
    return out


def _mem_snapshot() -> dict:
    """Host RSS / peak RSS (utils/resources backend ladder) plus the max
    per-device peak bytes when the runtime reports memory_stats — the
    bench record's memory axis."""
    from nice_tpu.obs import memwatch
    from nice_tpu.utils import resources

    out = {
        "rss_bytes": resources.rss_bytes() or 0,
        "peak_rss_bytes": resources.peak_rss_bytes() or 0,
    }
    dev = memwatch._device_memory()
    peaks = [e["peak"] for e in dev["devices"].values() if "peak" in e]
    if peaks:
        out["device_peak_bytes"] = max(peaks)
    return out


def _mem_delta(before: dict, after: dict) -> dict:
    """Per-window memory summary: the absolute peaks reached by the end of
    the window plus how much resident set the window itself added."""
    out = {
        "peak_rss_bytes": after["peak_rss_bytes"],
        "rss_delta_bytes": after["rss_bytes"] - before["rss_bytes"],
    }
    if "device_peak_bytes" in after:
        out["device_peak_bytes"] = after["device_peak_bytes"]
    return out


def _critpath_summary(prof_delta: dict) -> dict | None:
    """Dominant-segment summary of a stepprof delta window (obs/critpath.py's
    phase fold): where a mode's device wall actually went, in the same
    segment taxonomy the server's /critpath endpoint ranks. None when the
    profiler was off or recorded no wall."""
    from nice_tpu.obs import critpath

    return critpath.phase_shares(prof_delta)


def _init_jax(remaining):
    """Import jax and force backend init, retrying on transient failure.

    Two failure shapes are handled (both observed on the axon tunnel):
    an exception from backend init, and an indefinite HANG in jax.devices()
    (a wedged chip lease) — so the probe always runs under a watchdog.
    NICE_BENCH_PROBE picks which one:

    - "subprocess" (default): the probe child is SIGKILLed on timeout, so a
      wedged init can never outlive its watchdog, and the parent stays
      jax-clean — retries loop in-process, no re-exec.
    - "thread": the legacy daemon-thread probe. A hung thread is unjoinable
      and jax caches the failed backend, so each retry must re-exec the
      whole process to get a clean slate (the analog of the reference
      client's 10-retry exponential backoff around claim/submit, ref
      README.md:82-86, applied to device acquisition).

    NICE_BENCH_PLATFORM forces a platform (e.g. "cpu") AFTER import via
    jax.config.update — the env var alone is not enough because the axon
    PJRT plugin overrides JAX_PLATFORMS at import time (see
    nice_tpu/utils/platform.py).
    """
    from nice_tpu.utils.platform import (
        probe_backend,
        probe_backend_subprocess,
    )

    probe_mode = os.environ.get("NICE_BENCH_PROBE", "subprocess")
    probe = probe_backend if probe_mode == "thread" else (
        probe_backend_subprocess
    )
    while True:
        attempt = int(os.environ.get("NICE_BENCH_ATTEMPT", "1"))
        default_timeout = _INIT_TIMEOUTS[
            min(attempt - 1, len(_INIT_TIMEOUTS) - 1)
        ]
        timeout = float(
            os.environ.get("NICE_BENCH_INIT_TIMEOUT", default_timeout)
        )
        # Leave enough budget after init for at least the headline mode.
        timeout = max(15.0, min(timeout, remaining() - 90.0))
        _phase(
            "backend-init", "begin", attempt=attempt, timeout_s=timeout,
            probe=probe_mode,
        )
        n_chips, exc = probe(
            timeout_s=timeout,
            platform=os.environ.get("NICE_BENCH_PLATFORM"),
        )
        if exc is None:
            break

        # The probe's TimeoutError message names where init stalled (the
        # thread probe's phase, or the killed subprocess) — carry it into
        # the timeline so a wedged device lease is diagnosable from the
        # phase lines alone.
        _phase("backend-init", "error", attempt=attempt, error=repr(exc))
        # No attempt cap: keep retrying (each attempt's timeout shrinks
        # with the remaining budget) until there is no longer room for one
        # more attempt plus the headline mode.
        if remaining() > _INIT_RETRY_FLOOR:
            time.sleep(min(5 * attempt, 30))
            os.environ["NICE_BENCH_ATTEMPT"] = str(attempt + 1)
            if probe_mode == "thread":
                # Hung watchdog thread + cached failed backend poison this
                # process; only exec gives the next attempt a clean slate.
                os.execve(
                    sys.executable, [sys.executable] + sys.argv,
                    dict(os.environ),
                )
            continue  # subprocess probe left this process jax-clean
        err = _error_line(
            "numbers/sec/chip (benchmark suite)",
            f"jax backend init failed after {attempt} attempts "
            f"(last timeout {timeout:.0f}s, budget exhausted): {exc!r}",
        )
        stale = _stale_reference()
        if stale is not None:
            # Degrade to last round's driver-verified numbers rather than
            # blanking the round: the consumer can tell (stale_reference is
            # explicit) but is never left with nothing.
            err["stale_reference"] = stale
        print(json.dumps(err), flush=True)
        os._exit(1)  # a hung init thread (thread probe) cannot be joined

    _phase("backend-init", "end", attempt=attempt, n_chips=n_chips)
    import jax

    return jax, n_chips


def _run_mode(mode: str, kind: str, batch_size: int, n_chips: int) -> dict:
    from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine

    if mode == "multi-tenant":
        # Synthetic scheduler case, not a reference benchmark field: runs
        # its own A/B and returns before the single-workload machinery.
        return _run_multi_tenant(batch_size, n_chips)

    data = get_benchmark_field(BenchmarkMode(mode))
    # NICE_BENCH_SIZE clamps the field so huge cases (hi-base: 1e9 @ b80) can
    # EXECUTE as a short slice on CPU instead of budget-skipping: the line is
    # then a real measurement of the same kernels, flagged range_clamped.
    size_cap = int(os.environ.get("NICE_BENCH_SIZE", "0"))
    range_size = data.range_size
    range_clamped = 0 < size_cap < range_size
    if range_clamped:
        range_size = size_cap
    batch_size = min(
        batch_size, max(1 << 18, 1 << (range_size - 1).bit_length())
    )

    if kind == "detailed":
        run = lambda rng: engine.process_range_detailed(  # noqa: E731
            rng, data.base, backend="jax", batch_size=batch_size
        )
    else:
        run = lambda rng: engine.process_range_niceonly(  # noqa: E731
            rng, data.base, backend="jax", batch_size=batch_size
        )

    # Warm-up compile with the SAME kernel shape so the timed run measures
    # throughput, not compile time. Detailed probes a 1-number field (stats
    # kernels are jitted per (base, batch)); niceonly warms via
    # engine.warm_niceonly with the REAL field size — a probe field would
    # compile a different kernel (the huge-field floor guard shapes the
    # strided kernel by field size) and leave the real one cold.
    import jax

    if kind == "niceonly" and jax.default_backend() == "tpu":
        engine.warm_niceonly(data.base, data.range_size, data.range_start)
    else:
        # Detailed modes probe a 1-number field; off-TPU niceonly takes the
        # dense jnp path (which warm_niceonly does not compile), so the
        # probe field warms whichever kernel the timed run will use.
        if kind == "detailed":
            engine.warm_detailed(data.base, batch_size=batch_size)
        run(FieldSize(data.range_start, data.range_start + 1))

    from nice_tpu.obs.series import (
        ENGINE_READBACK_BYTES,
        ENGINE_STATS_TRANSFERS,
    )
    from nice_tpu.ops import compile_cache

    _RB_KINDS = ("nm", "count", "survivors", "survivors-dense", "stats",
                 "strided-counts")

    def _readback():
        return {k: int(ENGINE_READBACK_BYTES.value((k,))) for k in _RB_KINDS}

    rb0 = _readback()
    st0 = int(ENGINE_STATS_TRANSFERS.value(("detailed",)))
    cc0 = compile_cache.counts()

    rng = (
        FieldSize(data.range_start, data.range_start + range_size)
        if range_clamped else data.to_field_size()
    )
    t0 = time.monotonic()
    results = run(rng)
    elapsed = time.monotonic() - t0

    readback = {k: v - rb0[k] for k, v in _readback().items() if v - rb0[k]}
    stats_transfers = int(ENGINE_STATS_TRANSFERS.value(("detailed",))) - st0
    cc1 = compile_cache.counts()
    cache_delta = {k: cc1[k] - cc0[k] for k in cc1 if cc1[k] - cc0[k]}

    if kind == "detailed":
        total = sum(d.count for d in results.distribution)
        assert total == range_size, (total, range_size)
        baseline = NORTH_STAR_DETAILED
    else:
        baseline = NORTH_STAR_DETAILED * NICEONLY_SPEEDUP
    value = range_size / elapsed / n_chips
    line = {
        "metric": f"numbers/sec/chip {kind} ({mode}, base {data.base})",
        "value": round(value, 1),
        "unit": "numbers/sec/chip",
        "vs_baseline": round(value / baseline, 3),
        "elapsed_secs": round(elapsed, 3),
        "range_size": range_size,
        "n_chips": n_chips,
        "hits": len(results.nice_numbers),
    }
    if range_clamped:
        line["range_clamped"] = True
    if mode == "hi-base" and kind == "detailed":
        line.update(_hi_base_extras(data, batch_size))
    if mode == "extra-large":
        line["megaloop_ab"] = _megaloop_extras(data, kind, batch_size)
    # Transfer/cache telemetry for the timed run only (warm-up excluded):
    # readback bytes by payload kind proves the compaction win, and
    # stats_transfers==1 proves the accumulator stayed device-resident.
    if readback:
        line["readback_bytes"] = readback
    if stats_transfers:
        line["stats_transfers"] = stats_transfers
    if cache_delta:
        line["compile_cache"] = cache_delta
    return line


def _run_multi_tenant(batch_size: int, n_chips: int) -> dict:
    """Aggregate-throughput A/B for the multi-tenant scheduler: a detailed
    and a niceonly tenant interleaved page-by-page on one mesh vs the same
    two workloads run back-to-back. Both arms run warm (compiles excluded),
    so vs_sequential isolates the scheduler's switching overhead — the
    zero-recompile-stall design predicts ~1.0. Results are also checked
    byte-identical across arms (the ledger-equivalence contract)."""
    from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine
    from nice_tpu.sched import (
        MultiTenantScheduler,
        StaticSource,
        TenantRegistry,
        TenantSpec,
    )

    data = get_benchmark_field(BenchmarkMode("extra-large"))  # base 40
    base = data.base
    slice_size = max(4 * batch_size, 1 << 20)
    size_cap = int(os.environ.get("NICE_BENCH_SIZE", "0"))
    if 0 < size_cap < slice_size:
        slice_size = size_cap
    det_rng = FieldSize(data.range_start, data.range_start + slice_size)
    nice_rng = FieldSize(
        data.range_start + slice_size, data.range_start + 2 * slice_size
    )

    # Warm both tenants' shapes out of both timed regions.
    engine.warm_detailed(base, batch_size=batch_size)
    engine.process_range_niceonly(
        FieldSize(data.range_start, data.range_start + 1), base,
        backend="jax", batch_size=batch_size,
    )

    t0 = time.monotonic()
    seq_det = engine.process_range_detailed(
        det_rng, base, backend="jax", batch_size=batch_size
    )
    seq_nice = engine.process_range_niceonly(
        nice_rng, base, backend="jax", batch_size=batch_size
    )
    seq_secs = time.monotonic() - t0

    registry = TenantRegistry([
        TenantSpec(name="det", mode="detailed", base=base, priority=2,
                   backend="jax", batch_size=batch_size),
        TenantSpec(name="nice", mode="niceonly", base=base, priority=1,
                   backend="jax", batch_size=batch_size),
    ])
    source = StaticSource({
        "det": [("det/f0", base, det_rng.start(), det_rng.end())],
        "nice": [("nice/f0", base, nice_rng.start(), nice_rng.end())],
    })
    sched = MultiTenantScheduler(
        registry, source, policy="deficit", page_batches=1,
        quantum_secs=1e-9,
    )
    t0 = time.monotonic()
    stats = sched.run()
    int_secs = time.monotonic() - t0

    got_det = source.results["det"]["det/f0"]
    got_nice = source.results["nice"]["nice/f0"]
    equal = (
        got_det.distribution == seq_det.distribution
        and got_det.nice_numbers == seq_det.nice_numbers
        and got_nice.nice_numbers == seq_nice.nice_numbers
    )
    total = 2 * slice_size
    value = total / int_secs / n_chips
    return {
        "metric": f"numbers/sec/chip sched (multi-tenant, base {base})",
        "value": round(value, 1),
        "unit": "numbers/sec/chip",
        "vs_sequential": round(seq_secs / int_secs, 3),
        "elapsed_secs": round(int_secs, 3),
        "sequential_secs": round(seq_secs, 3),
        "range_size": total,
        "n_chips": n_chips,
        "hits": len(got_det.nice_numbers) + len(got_nice.nice_numbers),
        "pages": {t: s["pages"] for t, s in stats["tenants"].items()},
        "preemptions": {
            t: s["preemptions"] for t, s in stats["tenants"].items()
        },
        "results_equal": equal,
    }


def _hi_base_extras(data, batch_size: int) -> dict:
    """MXU A/B + fused-filter prune probe riding the hi-base case.

    A short fixed slice of the hi-base field is timed twice through the
    detailed path with NICE_TPU_MXU pinned 0 (VPU carry-save) then 1 (banded
    Toeplitz dot_general), each after its own warm-up so the pair compares
    steady-state kernels, not compile time. A niceonly slice then reads the
    nice_engine_filter_pruned_total delta so the record proves the fused
    residue filter pruned candidates ON DEVICE (non-zero) rather than on the
    host. Off-TPU both arms are CPU emulation: the 8-bit digit split does
    ~4x the scalar work of the VPU's 16-bit schoolbook (the price of the
    provable i32 accumulator bound — free on a systolic array, real on a
    CPU), so expect mxu_secs to trail there; the A/B is a correctness
    anchor off-chip and a perf signal only on real MXU hardware."""
    from nice_tpu.core.types import FieldSize
    from nice_tpu.obs.series import ENGINE_FILTER_PRUNED
    from nice_tpu.ops import engine

    ab_size = min(data.range_size, max(batch_size, 1 << 18))
    rng = FieldSize(data.range_start, data.range_start + ab_size)
    out: dict = {}
    prev = os.environ.get("NICE_TPU_MXU")
    try:
        ab = {"slice": ab_size}
        for field, pin in (("vpu_secs", "0"), ("mxu_secs", "1")):
            os.environ["NICE_TPU_MXU"] = pin
            engine.process_range_detailed(
                rng, data.base, backend="jax", batch_size=batch_size
            )  # warm: compile the pinned variant before timing it
            t0 = time.monotonic()
            engine.process_range_detailed(
                rng, data.base, backend="jax", batch_size=batch_size
            )
            ab[field] = round(time.monotonic() - t0, 3)
        import jax

        if jax.default_backend() != "tpu":
            ab["note"] = "cpu-emulated: digit-split overhead, no MXU"
        out["mxu_ab"] = ab
    finally:
        if prev is None:
            os.environ.pop("NICE_TPU_MXU", None)
        else:
            os.environ["NICE_TPU_MXU"] = prev
    key = ("niceonly", str(data.base))
    pruned0 = int(ENGINE_FILTER_PRUNED.value(key))
    engine.process_range_niceonly(
        rng, data.base, backend="jax", batch_size=batch_size
    )
    out["filter_pruned"] = int(ENGINE_FILTER_PRUNED.value(key)) - pruned0
    return out


def _megaloop_extras(data, kind: str, batch_size: int) -> dict:
    """Megaloop-vs-feed A/B riding the extra-large cases (one per kind).

    The same short fixed slice is timed twice: NICE_TPU_MEGALOOP pinned 0
    (the per-batch feed loop) then 1 (the device-resident lax.scan segment
    loop), each after its own warm-up so the pair compares steady-state
    kernels. Per arm the record carries the timed run's
    nice_engine_dispatches_total delta and its readback-bytes-by-kind delta
    — the dispatch_collapse ratio is the megaloop's whole point (one
    dispatch and one readback per SEGMENT instead of per batch), and the
    h2d_feed/host_other shrink shows up in the stepprof gate report. The
    niceonly arm is meaningful off-TPU only (on TPU niceonly takes the
    strided pallas pipeline, which owns its own dispatch shape and ignores
    the megaloop; both arms then count 0 engine dispatches)."""
    from nice_tpu.core.types import FieldSize
    from nice_tpu.obs.series import ENGINE_DISPATCHES, ENGINE_READBACK_BYTES
    from nice_tpu.ops import engine

    ab_size = min(data.range_size, max(4 * batch_size, 1 << 20))
    rng = FieldSize(data.range_start, data.range_start + ab_size)
    run = (
        engine.process_range_detailed if kind == "detailed"
        else engine.process_range_niceonly
    )
    rb_kinds = ("nm", "count", "survivors", "survivors-dense", "stats",
                "strided-counts")

    def _rb():
        return {k: int(ENGINE_READBACK_BYTES.value((k,))) for k in rb_kinds}

    out: dict = {"slice": ab_size}
    prev = os.environ.get("NICE_TPU_MEGALOOP")
    try:
        for field, pin in (("feed", "0"), ("megaloop", "1")):
            os.environ["NICE_TPU_MEGALOOP"] = pin
            run(rng, data.base, backend="jax", batch_size=batch_size)  # warm
            d0 = int(ENGINE_DISPATCHES.value((kind,)))
            rb0 = _rb()
            t0 = time.monotonic()
            run(rng, data.base, backend="jax", batch_size=batch_size)
            out[field] = {
                "secs": round(time.monotonic() - t0, 3),
                "dispatches": int(ENGINE_DISPATCHES.value((kind,))) - d0,
                "readback_bytes": {
                    k: v - rb0[k] for k, v in _rb().items() if v - rb0[k]
                },
            }
    finally:
        if prev is None:
            os.environ.pop("NICE_TPU_MEGALOOP", None)
        else:
            os.environ["NICE_TPU_MEGALOOP"] = prev
    feed_d = out["feed"]["dispatches"]
    mega_d = out["megaloop"]["dispatches"]
    if mega_d > 0:
        out["dispatch_collapse"] = round(feed_d / mega_d, 2)
    elif feed_d == 0:
        out["note"] = "strided pipeline: engine dense loops not exercised"
    return out


def _run_mode_capped(
    mode: str, kind: str, batch_size: int, n_chips: int, cap: float
) -> tuple[dict, bool]:
    """Run one mode under a per-case wall budget in a worker thread.

    A worker that blows the budget gets a short grace join: if it finishes
    inside _CASE_GRACE_SECS its real line is recorded with over_budget=true
    (slow, but the numbers are good and later cases still run). Only a worker
    still running after the grace is treated as wedged.

    Returns (line, wedged): wedged=True means the worker is still running
    (almost certainly blocked on the device tunnel) — the device must not be
    handed further work this process."""
    box: dict = {}

    def work():
        try:
            box["line"] = _run_mode(mode, kind, batch_size, n_chips)
        except Exception as exc:  # noqa: BLE001 — reported as a JSON line
            box["exc"] = exc

    t = threading.Thread(target=work, name=f"bench-{mode}", daemon=True)
    t.start()
    t.join(cap)
    metric = f"numbers/sec/chip {kind} ({mode})"
    over_budget = False
    if t.is_alive():
        _phase(f"mode.{kind}.{mode}", "over-budget", cap_secs=cap,
               grace_secs=_CASE_GRACE_SECS)
        t.join(_CASE_GRACE_SECS)
        if t.is_alive():
            return (
                _error_line(
                    metric,
                    f"mode exceeded its {cap:.0f}s case budget plus "
                    f"{_CASE_GRACE_SECS:.0f}s grace (wedged?)",
                ),
                True,
            )
        over_budget = True
    if "exc" in box:
        return _error_line(metric, repr(box["exc"])), False
    line = box["line"]
    if over_budget:
        line["over_budget"] = True
    return line, False


def _parse_suite(raw: str) -> tuple:
    suite = []
    for entry in raw.split(","):
        mode, sep, kind = entry.strip().partition(":")
        if not sep or kind not in ("detailed", "niceonly"):
            raise ValueError(
                f"NICE_BENCH_SUITE entry {entry!r} must be <mode>:detailed"
                f" or <mode>:niceonly"
            )
        suite.append((mode, kind))
    return tuple(suite)


def _parse_only(argv: list) -> str | None:
    """`--only MODE` / `--only=MODE`: case filter, argparse-free so the
    driver's env-knob contract (no CLI required) stays intact."""
    only = None
    it = iter(argv)
    for arg in it:
        if arg == "--only":
            only = next(it, None)
        elif arg.startswith("--only="):
            only = arg.split("=", 1)[1]
    return only


def main() -> int:
    remaining, budget = _budget_clock()
    # Engine per-field phase traces (floor, stride depth, descriptors,
    # per-stage busy seconds) go to stderr so the driver tail records them.
    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stderr,
        format="INFO:%(name)s: %(message)s",
    )
    jax, n_chips = _init_jax(remaining)

    try:
        if os.environ.get("NICE_BENCH_SUITE"):
            suite = _parse_suite(os.environ["NICE_BENCH_SUITE"])
        elif os.environ.get("NICE_BENCH_MODE"):
            mode = os.environ["NICE_BENCH_MODE"]
            suite = tuple(
                (m, k) for (m, k) in DEFAULT_SUITE if m == mode
            ) or ((mode, _MODE_KIND.get(mode, "detailed")),)
        else:
            suite = DEFAULT_SUITE
        only = _parse_only(sys.argv[1:])
        if only:
            suite = tuple(
                (m, k) for (m, k) in suite if m == only
            ) or ((only, _MODE_KIND.get(only, "detailed")),)
    except ValueError as exc:
        # Still a JSON line, never a bare traceback (driver contract).
        print(
            json.dumps(
                _error_line("numbers/sec/chip (benchmark suite)", str(exc))
            ),
            flush=True,
        )
        return 1

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        # Off-TPU the Pallas kernels run in interpreter mode (tiny descriptor
        # groups), so the 1e13 massive field would take hours: real-chip only.
        suite = tuple((m, k) for (m, k) in suite if m != "massive") or suite
    results: dict[tuple, dict] = {}
    headline = None
    wedged = False
    suite_spans0 = _span_sums()
    suite_prof0 = _stepprof_sums()
    suite_mem0 = _mem_snapshot()
    _phase("suite", "begin", modes=[f"{k}/{m}" for m, k in suite],
           n_chips=n_chips, backend=jax.default_backend())
    for idx, (mode, kind) in enumerate(suite):
        metric = f"numbers/sec/chip {kind} ({mode})"
        t_case = time.monotonic()
        case_budget = None
        if wedged:
            line = dict(_error_line(metric, ""), skipped="timeout-wedge")
            del line["error"]
            _phase(f"mode.{kind}.{mode}", "skip", reason="timeout-wedge")
        elif (
            (mode, kind) != HEADLINE
            and _EST_SECS.get((mode, kind), _EST_DEFAULT) > remaining()
        ):
            line = dict(_error_line(metric, ""), skipped="budget")
            del line["error"]
            line["budget_remaining_secs"] = round(remaining(), 1)
            _phase(f"mode.{kind}.{mode}", "skip", reason="budget",
                   budget_remaining_secs=round(remaining(), 1))
        else:
            default_batch = (
                _TPU_BATCH.get((mode, kind), 1 << 22) if on_tpu else 1 << 20
            )
            batch = int(os.environ.get("NICE_BENCH_BATCH", default_batch))
            cap = _CAP_SECS.get((mode, kind), _CAP_DEFAULT)
            # Reserve wall for the cases still queued behind this one (at
            # their estimate, capped) so one slow case is budget-clipped and
            # recorded over_budget instead of starving the rest of the suite
            # into the driver's kill (BENCH r04: rc=124, one line).
            reserve = sum(
                min(_EST_SECS.get(c, _EST_DEFAULT),
                    _CAP_SECS.get(c, _CAP_DEFAULT))
                for c in suite[idx + 1:]
            )
            if (mode, kind) == HEADLINE:
                # The headline always gets a chance to run, but never more
                # wall than would erase the final print.
                cap = max(30.0, min(cap, remaining() - 10.0))
            else:
                cap = max(10.0, min(cap, remaining() - 15.0,
                                    remaining() - reserve - 10.0))
            case_budget = cap
            _phase(f"mode.{kind}.{mode}", "begin", batch=batch,
                   cap_secs=round(cap, 1), reserved_secs=round(reserve, 1))
            spans_before = _span_sums()
            prof_before = _stepprof_sums()
            mem_before = _mem_snapshot()
            line, wedged = _run_mode_capped(mode, kind, batch, n_chips, cap)
            line["peak_mem"] = _mem_delta(mem_before, _mem_snapshot())
            mode_spans = _span_delta(spans_before, _span_sums())
            if mode_spans:
                line["spans"] = mode_spans
            mode_prof = _stepprof_delta(prof_before, _stepprof_sums())
            if mode_prof:
                line["phase_breakdown"] = mode_prof
                cp = _critpath_summary(mode_prof)
                if cp is not None:
                    line["critpath"] = cp
            _phase(
                f"mode.{kind}.{mode}",
                "error" if ("error" in line or wedged) else "end",
                **{
                    k: line[k]
                    for k in ("value", "elapsed_secs", "error",
                              "over_budget")
                    if k in line
                },
            )
        # Per-case accounting on EVERY line (skips included): what this case
        # actually cost and what it was allowed — the committed bench record
        # carries the whole suite's wall split even when cases were clipped.
        line["case_elapsed_secs"] = round(time.monotonic() - t_case, 3)
        if case_budget is not None:
            line["case_budget_secs"] = round(case_budget, 1)
        results[(mode, kind)] = line
        print(json.dumps(line), flush=True)  # every mode flushes immediately
        if (mode, kind) == HEADLINE:
            headline = line  # provisional record; re-printed last with suite

    if headline is None:
        # Single-mode run: re-print that mode's line last as the headline.
        headline = line
    headline = dict(headline)
    headline["suite"] = {
        f"{kind}/{mode}": {
            k: v
            for k, v in r.items()
            if k
            in ("value", "vs_baseline", "elapsed_secs", "error", "hits",
                "skipped", "case_elapsed_secs", "case_budget_secs",
                "over_budget", "peak_mem")
        }
        for (mode, kind), r in results.items()
    }
    headline["budget_secs"] = budget
    headline["budget_used_secs"] = round(budget - remaining(), 1)
    # Suite-wide memory watermark (overwrites the headline case's own window
    # on purpose: the committed record carries the whole run's peak).
    headline["peak_mem"] = _mem_delta(suite_mem0, _mem_snapshot())
    # Per-phase wall-time across the whole suite (engine dispatch/collect/
    # stats spans + any server/client spans that ran in-process): the driver
    # artifact carries not just the throughput but where the wall went.
    headline["span_summary"] = _span_delta(suite_spans0, _span_sums())
    suite_prof = _stepprof_delta(suite_prof0, _stepprof_sums())
    if suite_prof:
        headline["phase_breakdown"] = suite_prof
        cp = _critpath_summary(suite_prof)
        if cp is not None:
            headline["critpath"] = cp
    _phase("suite", "end", budget_used_secs=round(budget - remaining(), 1))
    print(json.dumps(headline), flush=True)
    return 1 if any("error" in r for r in results.values()) else 0


if __name__ == "__main__":
    sys.exit(main())
