"""Benchmark runner: one JSON line for the driver.

Runs the reference's extra-large benchmark (1e9 @ base 40, detailed mode —
one production server field, BASELINE.md) end-to-end through the engine on
the available accelerator and reports numbers/sec/chip.

vs_baseline compares against the north-star per-chip target of 1.25e8
numbers/sec/chip (BASELINE.json: 1e9 field in <1 s on a v5e-8, >50x the
reference CUDA client).

Env knobs:
  NICE_BENCH_MODE   benchmark field (default: extra-large)
  NICE_BENCH_BATCH  lanes per dispatch (default: 1<<28)
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_NS_PER_CHIP = 1.25e8


def main() -> int:
    mode_name = os.environ.get("NICE_BENCH_MODE", "extra-large")

    import jax

    # 2^28 lanes is free on TPU (the Pallas kernel derives candidates
    # on-device, so a batch is just grid steps); the jnp fallback on other
    # platforms materializes per-lane intermediates and needs a smaller batch.
    default_batch = 1 << 28 if jax.default_backend() == "tpu" else 1 << 22
    batch_size = int(os.environ.get("NICE_BENCH_BATCH", default_batch))

    from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field
    from nice_tpu.ops import engine

    n_chips = len(jax.devices())
    data = get_benchmark_field(BenchmarkMode(mode_name))
    batch_size = min(batch_size, max(1 << 18, 1 << (data.range_size - 1).bit_length()))

    # Warm-up compile with the SAME batch shape so the timed run measures
    # throughput, not compile time (the kernel is jitted per (base, batch)).
    from nice_tpu.core.types import FieldSize

    warm = FieldSize(data.range_start, data.range_start + 1)
    engine.process_range_detailed(
        warm, data.base, backend="jax", batch_size=batch_size
    )
    rng = data.to_field_size()
    t0 = time.monotonic()
    results = engine.process_range_detailed(
        rng, data.base, backend="jax", batch_size=batch_size
    )
    elapsed = time.monotonic() - t0

    total = sum(d.count for d in results.distribution)
    assert total == data.range_size, (total, data.range_size)
    value = data.range_size / elapsed / n_chips

    print(
        json.dumps(
            {
                "metric": f"numbers/sec/chip detailed ({mode_name}, base {data.base})",
                "value": round(value, 1),
                "unit": "numbers/sec/chip",
                "vs_baseline": round(value / BASELINE_NS_PER_CHIP, 3),
                "elapsed_secs": round(elapsed, 3),
                "range_size": data.range_size,
                "n_chips": n_chips,
                "near_misses": len(results.nice_numbers),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
