"""Engine-level multi-chip dispatch tests on the virtual 8-device CPU mesh.

The conftest forces 8 virtual devices, so ops/engine.py's production dispatch
loops take the sharded super-batch path here — the same code the driver's
dryrun and a real v5e-8 client run (the analog of the reference's CPU-mirror
GPU differential tests, client_process_gpu.rs:1289-1324)."""

import jax
import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar


@pytest.fixture(autouse=True)
def _require_mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual CPU devices"
    assert engine._mesh_or_none() is not None


def test_sharded_detailed_matches_scalar_oracle():
    base = 40
    br = base_range.get_base_range(base)
    rng = FieldSize(br[0], br[0] + 3000)  # ragged: not a super-batch multiple
    got = engine.process_range_detailed(rng, base, backend="jax", batch_size=128)
    want = scalar.process_range_detailed(rng, base)
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers


def test_sharded_detailed_near_misses_extracted():
    # Base 10's tiny range has known near misses; the rare-path re-scan must
    # recover exact numbers through the sharded dispatch too.
    got = engine.process_range_detailed(
        FieldSize(47, 100), 10, backend="jax", batch_size=128
    )
    want = scalar.process_range_detailed(FieldSize(47, 100), 10)
    assert got.nice_numbers == want.nice_numbers
    assert any(n.number == 69 for n in got.nice_numbers)


def test_sharded_niceonly_dense_finds_69():
    got = engine.process_range_niceonly(
        FieldSize(47, 100), 10, backend="jnp", batch_size=128
    )
    assert [n.number for n in got.nice_numbers] == [69]


def test_sharded_niceonly_strided_matches_scalar():
    base = 40
    br = base_range.get_base_range(base)
    rng = FieldSize(br[0], br[0] + 200_000)
    got = engine.process_range_niceonly(rng, base, backend="pallas", batch_size=128)
    want = scalar.process_range_niceonly(rng, base)
    assert [n.number for n in got.nice_numbers] == [
        n.number for n in want.nice_numbers
    ]


def test_sharded_niceonly_strided_above_u64():
    """Bases 60-95 have range ends above 2^64: the descriptor columns must
    carry values as two u64 halves, not a single u64."""
    base = 60
    br = base_range.get_base_range(base)
    assert br[0] > 1 << 64  # the premise this test pins
    rng = FieldSize(br[0], br[0] + 40_000)
    got = engine.process_range_niceonly(rng, base, backend="pallas", batch_size=128)
    want = scalar.process_range_niceonly(rng, base)
    assert [n.number for n in got.nice_numbers] == [
        n.number for n in want.nice_numbers
    ]


def test_shard_disable_env(monkeypatch):
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    assert engine._mesh_or_none() is None
    # Single-device dispatch still agrees with the oracle.
    base = 40
    br = base_range.get_base_range(base)
    rng = FieldSize(br[0], br[0] + 1000)
    got = engine.process_range_detailed(rng, base, backend="jax", batch_size=128)
    want = scalar.process_range_detailed(rng, base)
    assert got.distribution == want.distribution


def test_shard_inputs_exact():
    from nice_tpu.ops.limbs import get_plan, limbs_to_int

    plan = get_plan(40)
    br = base_range.get_base_range(40)
    starts, valids = engine._shard_inputs(
        plan, br[0] + 10_000, br[0], 1000, 256, 8
    )
    assert starts.shape == (8, plan.limbs_n)
    assert [limbs_to_int(s) for s in starts] == [br[0] + d * 256 for d in range(8)]
    # 1000 valid lanes over 8x256: 3 full devices, 232 on the 4th, 0 after.
    assert valids.tolist() == [256, 256, 256, 232, 0, 0, 0, 0]


def test_shard_inputs_clamped_to_core_end():
    from nice_tpu.ops.limbs import get_plan, limbs_to_int

    plan = get_plan(40)
    br = base_range.get_base_range(40)
    core_end = br[0] + 300
    starts, valids = engine._shard_inputs(plan, core_end, br[0], 300, 256, 8)
    assert max(limbs_to_int(s) for s in starts) <= core_end
    assert valids.tolist()[:2] == [256, 44]
    assert sum(valids.tolist()) == 300
