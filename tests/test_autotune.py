"""Autotuner winners-table unit tests: precedence, persistence, invalidation.

The subprocess sweep itself is covered by scripts/autotune_smoke.py in CI;
these tests pin the table semantics the engine depends on — env > tuned >
default resolution, signature-checked lookups, and atomic persistence that a
fresh loader (simulating a process restart) reads back identically.
"""

import json

import pytest

from nice_tpu.obs.series import AUTOTUNE_EVENTS
from nice_tpu.ops import autotune, engine
from nice_tpu.ops import pallas_engine as pe


@pytest.fixture(autouse=True)
def _isolated_table(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "NICE_TPU_AUTOTUNE_FILE", str(tmp_path / "winners.json")
    )
    for var in ("NICE_TPU_BATCH", "NICE_TPU_BLOCK_ROWS",
                "NICE_TPU_CARRY_INTERVAL", "NICE_TPU_MXU",
                "NICE_TPU_MEGALOOP", "NICE_TPU_MEGALOOP_SEGMENT"):
        monkeypatch.delenv(var, raising=False)
    autotune.reset_for_tests()
    yield
    autotune.reset_for_tests()


def test_winners_path_precedence(tmp_path, monkeypatch):
    assert autotune.winners_path() == tmp_path / "winners.json"
    monkeypatch.delenv("NICE_TPU_AUTOTUNE_FILE")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path / "cc"))
    assert autotune.winners_path() == tmp_path / "cc" / "nice_autotune.json"
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
    assert autotune.winners_path().name == "nice_autotune.json"


def test_choose_defaults_when_untuned():
    assert autotune.choose("detailed", 40, "jax", "batch_size", 123) == 123
    assert AUTOTUNE_EVENTS.value(("miss",)) > 0


def test_record_then_choose_roundtrip():
    autotune.record(
        "detailed", 40, "jax",
        {"batch_size": 4096, "block_rows": 64, "carry_interval": 3},
        throughput=1e6,
    )
    assert autotune.choose("detailed", 40, "jax", "batch_size", 1) == 4096
    assert autotune.choose("detailed", 40, "jax", "block_rows", 1) == 64
    assert autotune.choose("detailed", 40, "jax", "carry_interval", 9) == 3
    # Other keys are unaffected.
    assert autotune.choose("niceonly", 40, "jax", "batch_size", 7) == 7
    assert autotune.choose("detailed", 40, "pallas", "batch_size", 7) == 7


def test_restart_persistence_hit_counter():
    """A fresh in-process loader (the restart analog; the true fresh-process
    check lives in scripts/autotune_smoke.py) reads the winner back from
    disk and counts a hit."""
    autotune.record("detailed", 40, "jax", {"batch_size": 2048})
    autotune.reset_for_tests()  # drop the in-memory table: force a re-read
    hits0 = AUTOTUNE_EVENTS.value(("hit",))
    assert autotune.choose("detailed", 40, "jax", "batch_size", 1) == 2048
    assert AUTOTUNE_EVENTS.value(("hit",)) == hits0 + 1


def test_env_overrides_tuned(monkeypatch):
    autotune.record("detailed", 40, "jax", {"carry_interval": 3})
    monkeypatch.setenv("NICE_TPU_CARRY_INTERVAL", "5")
    ov0 = AUTOTUNE_EVENTS.value(("env_override",))
    assert autotune.choose("detailed", 40, "jax", "carry_interval", 0) == 5
    assert AUTOTUNE_EVENTS.value(("env_override",)) == ov0 + 1


def test_signature_change_invalidates():
    autotune.record("detailed", 40, "jax", {"batch_size": 2048})
    path = autotune.winners_path()
    table = json.loads(path.read_text())
    table["detailed|b40|jax"]["signature"]["runtime"] = "jax-9.9.9-mars"
    path.write_text(json.dumps(table))
    autotune.reset_for_tests()
    inv0 = AUTOTUNE_EVENTS.value(("invalidated",))
    assert autotune.choose("detailed", 40, "jax", "batch_size", 55) == 55
    assert AUTOTUNE_EVENTS.value(("invalidated",)) == inv0 + 1


def test_plan_change_invalidates():
    """A limb-width drift (e.g. a base-range fix) must also refuse the
    winner, not just a jax upgrade."""
    autotune.record("detailed", 40, "jax", {"batch_size": 2048})
    path = autotune.winners_path()
    table = json.loads(path.read_text())
    table["detailed|b40|jax"]["signature"]["limbs"] = [9, 9, 9]
    path.write_text(json.dumps(table))
    autotune.reset_for_tests()
    assert autotune.choose("detailed", 40, "jax", "batch_size", 55) == 55


def test_corrupt_table_reads_as_empty():
    path = autotune.winners_path()
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("{not json")
    assert autotune.params("detailed", 40, "jax") is None
    assert autotune.choose("detailed", 40, "jax", "batch_size", 77) == 77


def test_resolve_tuning_precedence(monkeypatch):
    """The engine-facing resolver composes the four knobs: explicit batch
    pins batch (tuned ignored), env pins any knob, host backends bypass the
    table entirely."""
    autotune.record(
        "detailed", 40, "jax",
        {"batch_size": 4096, "block_rows": 32, "carry_interval": 2},
    )
    assert engine.resolve_tuning("detailed", 40, "jax") == (
        4096, 32, 2, 0, engine.MEGALOOP_SEGMENT_DEFAULT,
    )
    bs, br, ci, mxu, mega = engine.resolve_tuning("detailed", 40, "jax", 512)
    assert (bs, br, ci, mxu, mega) == (
        512, 32, 2, 0, engine.MEGALOOP_SEGMENT_DEFAULT,
    )
    monkeypatch.setenv("NICE_TPU_BLOCK_ROWS", "16")
    assert engine.resolve_tuning("detailed", 40, "jax")[1] == 16
    monkeypatch.delenv("NICE_TPU_BLOCK_ROWS")
    assert engine.resolve_tuning("detailed", 40, "scalar") == (
        engine.DEFAULT_BATCH_SIZE, pe.BLOCK_ROWS, 0, 0, 1,
    )
    assert engine.resolve_tuning("detailed", 40, "scalar", 64)[0] == 64


def test_megaloop_knob_precedence(monkeypatch):
    """The fifth tuning knob: segment length resolves env > tuned > default,
    and NICE_TPU_MEGALOOP=0 is an escape hatch that forces segment 1 (the
    per-batch feed loop) regardless of winner or env segment."""
    autotune.record("detailed", 40, "jax", {"batch_size": 4096, "megaloop": 4})
    autotune.reset_for_tests()
    assert engine.resolve_tuning("detailed", 40, "jax")[4] == 4
    monkeypatch.setenv("NICE_TPU_MEGALOOP_SEGMENT", "2")
    assert engine.resolve_tuning("detailed", 40, "jax")[4] == 2
    monkeypatch.delenv("NICE_TPU_MEGALOOP_SEGMENT")
    # Untuned key -> default segment.
    assert (
        engine.resolve_tuning("niceonly", 40, "jax")[4]
        == engine.MEGALOOP_SEGMENT_DEFAULT
    )
    # Escape hatch wins over everything.
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    assert engine.resolve_tuning("detailed", 40, "jax")[4] == 1
    # Host backends never megaloop.
    monkeypatch.delenv("NICE_TPU_MEGALOOP")
    assert engine.resolve_tuning("detailed", 40, "scalar")[4] == 1


def test_use_mxu_roundtrip_and_env_pin(monkeypatch):
    """The MXU arm persists like any other winner param, resolves through
    the same env > tuned > default precedence, and the resolver forces it
    off for plans past the i32 accumulator bound."""
    autotune.record(
        "detailed", 40, "jax",
        {"batch_size": 4096, "use_mxu": 1},
    )
    # Round-trip through a fresh loader (restart analog).
    autotune.reset_for_tests()
    assert autotune.choose("detailed", 40, "jax", "use_mxu", 0) == 1
    assert engine.resolve_tuning("detailed", 40, "jax")[3] == 1
    # Env pin beats the tuned winner.
    monkeypatch.setenv("NICE_TPU_MXU", "0")
    assert engine.resolve_tuning("detailed", 40, "jax")[3] == 0
    monkeypatch.setenv("NICE_TPU_MXU", "1")
    assert engine.resolve_tuning("detailed", 40, "jax")[3] == 1
    # Untuned + no env -> default off.
    monkeypatch.delenv("NICE_TPU_MXU")
    assert engine.resolve_tuning("niceonly", 40, "jax")[3] == 0


def test_use_mxu_forced_off_past_accum_bound(monkeypatch):
    """An env pin (or stale winner) cannot enable the MXU path for a plan
    whose contraction would overflow the declared i32 bound."""
    from nice_tpu.ops import mxu
    from nice_tpu.ops.limbs import get_plan

    monkeypatch.setenv("NICE_TPU_MXU", "1")
    plan = get_plan(40)
    assert mxu.supports_plan(plan)  # sanity: 40 is MXU-capable
    assert engine.resolve_tuning("detailed", 40, "jax")[3] == 1

    class _FatPlan:
        limbs_n = 1 << 20  # accum_bound far past 2**31

    monkeypatch.setattr(
        "nice_tpu.ops.engine.get_plan", lambda base: _FatPlan()
    )
    assert engine.resolve_tuning("detailed", 40, "jax")[3] == 0
