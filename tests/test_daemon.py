"""Daemon unit tests: CPU sampling math and client process lifecycle
(reference daemon/src/main.rs:39-215)."""

import os
import subprocess
import sys
import time

import pytest

from nice_tpu.daemon import main as daemon

# /proc/stat tests are Linux-only (same convention as test_native.py); the
# monkeypatched CpuMonitor math tests stub the reader so they run anywhere.
linux_only = pytest.mark.skipif(
    not os.path.exists("/proc/stat"), reason="needs /proc/stat (Linux)"
)


@linux_only
def test_read_cpu_times_shape():
    idle, total = daemon.read_cpu_times()
    assert 0 <= idle <= total


def test_cpu_monitor_usage_math(monkeypatch):
    # Deterministic /proc/stat: 100 jiffies pass, 25 idle -> 75% usage.
    readings = iter([(1000, 10_000), (1025, 10_100)])
    monkeypatch.setattr(daemon, "read_cpu_times", lambda: next(readings))
    monkeypatch.setattr(time, "sleep", lambda s: None)
    m = daemon.CpuMonitor(interval_secs=0)
    assert abs(m.sample() - 0.75) < 1e-9


def test_cpu_monitor_zero_delta(monkeypatch):
    readings = iter([(1000, 10_000), (1000, 10_000)])
    monkeypatch.setattr(daemon, "read_cpu_times", lambda: next(readings))
    monkeypatch.setattr(time, "sleep", lambda s: None)
    m = daemon.CpuMonitor(interval_secs=0)
    assert m.sample() == 0.0  # no jiffies elapsed: report idle, not NaN


def test_pick_cpu_backend_never_none_when_proc_exists():
    if os.path.exists("/proc/stat"):
        assert daemon.pick_cpu_backend() == "proc"
    else:
        assert daemon.pick_cpu_backend() in ("psutil", "loadavg", "none")


def test_cpu_monitor_psutil_backend(monkeypatch):
    psutil = pytest.importorskip("psutil")
    monkeypatch.setattr(time, "sleep", lambda s: None)
    m = daemon.CpuMonitor(interval_secs=0, backend="psutil")
    assert m.backend == "psutil"
    monkeypatch.setattr(psutil, "cpu_percent", lambda interval=None: 42.0)
    assert abs(m.sample() - 0.42) < 1e-9


def test_cpu_monitor_loadavg_backend(monkeypatch):
    monkeypatch.setattr(time, "sleep", lambda s: None)
    m = daemon.CpuMonitor(interval_secs=0, backend="loadavg")
    cores = os.cpu_count() or 1
    monkeypatch.setattr(os, "getloadavg", lambda: (cores / 2, 0.0, 0.0))
    assert abs(m.sample() - 0.5) < 1e-9
    # loadavg can exceed core count under overload; usage clips at 1.0.
    monkeypatch.setattr(os, "getloadavg", lambda: (cores * 3.0, 0.0, 0.0))
    assert m.sample() == 1.0


def test_cpu_monitor_none_backend_reports_idle(monkeypatch):
    monkeypatch.setattr(time, "sleep", lambda s: None)
    m = daemon.CpuMonitor(interval_secs=0, backend="none")
    assert m.sample() == 0.0


def test_process_manager_lifecycle(monkeypatch):
    # Substitute a trivial child so the test never launches a real client.
    calls = []

    real_popen = subprocess.Popen

    def fake_popen(cmd, *a, **k):
        calls.append(cmd)
        return real_popen([sys.executable, "-c", "import time; time.sleep(60)"])

    monkeypatch.setattr(subprocess, "Popen", fake_popen)
    pm = daemon.ProcessManager(["--repeat", "niceonly"])
    try:
        assert not pm.running()
        assert not pm.reap()
        pm.start()
        assert pm.running()
        assert calls and calls[0][-2:] == ["--repeat", "niceonly"]
        pm.start()  # idempotent while running
        assert len(calls) == 1
    finally:
        pm.stop()  # never leak the sleeper child, even on assert failure
    assert not pm.running()


def test_process_manager_reaps_exited_client(monkeypatch):
    real_popen = subprocess.Popen
    monkeypatch.setattr(
        subprocess,
        "Popen",
        lambda cmd, *a, **k: real_popen([sys.executable, "-c", "pass"]),
    )
    pm = daemon.ProcessManager([])
    pm.start()
    pm.proc.wait()
    assert pm.reap()
    assert pm.proc is None
    assert not pm.reap()  # second reap is a no-op
