"""Daemon unit tests: CPU sampling math and client process lifecycle
(reference daemon/src/main.rs:39-215)."""

import os
import subprocess
import sys
import time

import pytest

from nice_tpu.daemon import main as daemon

# /proc/stat tests are Linux-only (same convention as test_native.py); the
# monkeypatched CpuMonitor math tests stub the reader so they run anywhere.
linux_only = pytest.mark.skipif(
    not os.path.exists("/proc/stat"), reason="needs /proc/stat (Linux)"
)


@linux_only
def test_read_cpu_times_shape():
    idle, total = daemon.read_cpu_times()
    assert 0 <= idle <= total


def test_cpu_monitor_usage_math(monkeypatch):
    # Deterministic /proc/stat: 100 jiffies pass, 25 idle -> 75% usage.
    readings = iter([(1000, 10_000), (1025, 10_100)])
    monkeypatch.setattr(daemon, "read_cpu_times", lambda: next(readings))
    monkeypatch.setattr(time, "sleep", lambda s: None)
    m = daemon.CpuMonitor(interval_secs=0)
    assert abs(m.sample() - 0.75) < 1e-9


def test_cpu_monitor_zero_delta(monkeypatch):
    readings = iter([(1000, 10_000), (1000, 10_000)])
    monkeypatch.setattr(daemon, "read_cpu_times", lambda: next(readings))
    monkeypatch.setattr(time, "sleep", lambda s: None)
    m = daemon.CpuMonitor(interval_secs=0)
    assert m.sample() == 0.0  # no jiffies elapsed: report idle, not NaN


def test_process_manager_lifecycle(monkeypatch):
    # Substitute a trivial child so the test never launches a real client.
    calls = []

    real_popen = subprocess.Popen

    def fake_popen(cmd, *a, **k):
        calls.append(cmd)
        return real_popen([sys.executable, "-c", "import time; time.sleep(60)"])

    monkeypatch.setattr(subprocess, "Popen", fake_popen)
    pm = daemon.ProcessManager(["--repeat", "niceonly"])
    try:
        assert not pm.running()
        assert not pm.reap()
        pm.start()
        assert pm.running()
        assert calls and calls[0][-2:] == ["--repeat", "niceonly"]
        pm.start()  # idempotent while running
        assert len(calls) == 1
    finally:
        pm.stop()  # never leak the sleeper child, even on assert failure
    assert not pm.running()


def test_process_manager_reaps_exited_client(monkeypatch):
    real_popen = subprocess.Popen
    monkeypatch.setattr(
        subprocess,
        "Popen",
        lambda cmd, *a, **k: real_popen([sys.executable, "-c", "pass"]),
    )
    pm = daemon.ProcessManager([])
    pm.start()
    pm.proc.wait()
    assert pm.reap()
    assert pm.proc is None
    assert not pm.reap()  # second reap is a no-op
