"""Pallas kernel tests in interpreter mode (no TPU required) — the analog of
the reference's compile-only NVRTC tests + CPU mirrors of the kernel index
math (client_process_gpu.rs:988-1451). Tiny block_rows keep interpretation
fast; the arithmetic is identical at any block shape because every op is
elementwise over (rows, 128)."""

import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar
from nice_tpu.ops import pallas_engine as pe
from nice_tpu.ops import vector_engine as ve
from nice_tpu.ops.limbs import get_plan, int_to_limbs

BR = 8  # block_rows for interpreter-mode tests
BL = BR * 128  # lanes per block


@pytest.fixture(autouse=True)
def _per_batch_pallas(monkeypatch):
    # These tests pin the per-BATCH pallas kernels; the default megaloop
    # would wrap every engine dispatch in a scanned pallas callable whose
    # interpreter-mode compile runs minutes per shape. The scanned path is
    # covered by tests/test_megaloop.py.
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")


def test_detailed_kernel_b10_golden():
    plan = get_plan(10)
    h, nm = pe.detailed_batch(
        plan, BL, int_to_limbs(47, plan.limbs_n), np.int32(53), block_rows=BR
    )
    h = np.asarray(h)
    want = scalar.process_range_detailed(FieldSize(47, 100), 10)
    for d in want.distribution:
        assert h[d.num_uniques] == d.count, d
    assert h[0] == BL - 53  # padding lanes masked into bin 0
    assert h[plan.base + 2 :].sum() == 0
    assert int(nm) == 1  # 69 is nice, hence also a near miss


def test_detailed_kernel_multiblock_accumulation_b40():
    plan = get_plan(40)
    br = base_range.get_base_range(40)
    batch = 3 * BL
    sl = int_to_limbs(br[0], plan.limbs_n)
    h, nm = pe.detailed_batch(plan, batch, sl, np.int32(batch - 57), block_rows=BR)
    hj, nmj = ve.detailed_batch(plan, batch, sl, np.int32(batch - 57))
    assert np.array_equal(np.asarray(h)[: plan.base + 2], np.asarray(hj))
    assert int(nm) == int(nmj)


def test_niceonly_kernel_b10_finds_69():
    plan = get_plan(10)
    c = pe.niceonly_dense_batch(
        plan, BL, int_to_limbs(47, plan.limbs_n), np.int32(53), block_rows=BR
    )
    assert int(c) == 1


def test_uniques_kernel_matches_scalar_b40():
    plan = get_plan(40)
    br = base_range.get_base_range(40)
    u = np.asarray(
        pe.uniques_batch(plan, BL, int_to_limbs(br[0], plan.limbs_n), block_rows=BR)
    )
    for i in range(0, BL, 97):  # sample lanes
        assert int(u[i]) == scalar.get_num_unique_digits(br[0] + i, 40)


def test_detailed_kernel_matches_jnp_b17():
    """A b17 slice that contains near misses."""
    plan = get_plan(17)
    br = base_range.get_base_range(17)
    sl = int_to_limbs(br[0], plan.limbs_n)
    h, nm = pe.detailed_batch(plan, BL, sl, np.int32(BL), block_rows=BR)
    hj, nmj = ve.detailed_batch(plan, BL, sl, np.int32(BL))
    assert np.array_equal(np.asarray(h)[: plan.base + 2], np.asarray(hj))
    assert int(nm) == int(nmj)


def test_detailed_kernel_matches_scalar_b80():
    """b80 exercises 3 mask words + u128-wide limbs (the jnp comparison graph
    is too slow to compile on CPU, so diff against the scalar oracle)."""
    base, batch = 80, 256
    plan = get_plan(base)
    br = base_range.get_base_range(base)
    sl = int_to_limbs(br[0], plan.limbs_n)
    h, nm = pe.detailed_batch(plan, batch, sl, np.int32(batch), block_rows=2)
    h = np.asarray(h)
    want = np.zeros(plan.base + 2, dtype=np.int64)
    want_nm = 0
    for n in range(br[0], br[0] + batch):
        u = scalar.get_num_unique_digits(n, base)
        want[u] += 1
        want_nm += u > plan.near_miss_cutoff
    assert np.array_equal(h[: plan.base + 2], want)
    assert int(nm) == want_nm


def test_widened_hist_layout():
    """Bases past 126 need a multi-row histogram tile (base+2 bins > 128
    lanes). supports_base previously rejected every such plan, silently
    demoting hi-base detailed scans to jnp; it now admits anything within
    _HIST_ROWS_MAX rows — lifted from 4 to the plan-derived 16-row cap
    (kernelspec.MAX_HIST_ROWS), so 5-row bases past 510 are in. Pure
    layout math — the kernel itself is diffed against the oracle in the
    slow tests (interpreter-mode XLA compiles of multi-row plans take
    minutes on CPU; b127 below, b513 in test_property_differential)."""
    for base, rows, ok in [
        (80, 1, True), (125, 1, True), (127, 2, True), (150, 2, True),
        (510, 4, True), (512, 5, True), (513, 5, True), (2045, 16, True),
    ]:
        plan = get_plan(base)
        assert pe._hist_rows(plan) == rows, base
        assert pe.supports_base(plan) is ok, base
    # Above the contract cap: a 17-row plan must still be rejected.
    import dataclasses

    fat = dataclasses.replace(get_plan(513), base=2100)
    assert pe.supports_base(fat) is False


@pytest.mark.slow
def test_detailed_kernel_widened_hist_b127():
    """Multi-row histogram correctness: b127 is the smallest hist_rows=2
    plan (cheapest interpreter-mode compile of the widened tile). Diff
    against the scalar oracle, and prove the carry-resolution interval is
    bit-invisible on the Pallas path too. Marked slow: the interpreter-mode
    XLA compile of a 2-row plan runs minutes on CPU."""
    base, batch = 127, 256
    plan = get_plan(base)
    assert pe._hist_rows(plan) == 2
    br = base_range.get_base_range(base)
    sl = int_to_limbs(br[0], plan.limbs_n)
    h, nm = pe.detailed_batch(plan, batch, sl, np.int32(batch), block_rows=2)
    h = np.asarray(h)
    want = np.zeros(plan.base + 2, dtype=np.int64)
    want_nm = 0
    for n in range(br[0], br[0] + batch):
        u = scalar.get_num_unique_digits(n, base)
        want[u] += 1
        want_nm += u > plan.near_miss_cutoff
    assert np.array_equal(h[: plan.base + 2], want)
    assert int(nm) == want_nm
    h2, nm2 = pe.detailed_batch(
        plan, batch, sl, np.int32(batch), block_rows=2, carry_interval=2
    )
    assert np.array_equal(np.asarray(h2), h)
    assert int(nm2) == int(nm)


def _stride_spec(base):
    from nice_tpu.ops import stride_filter

    t = stride_filter.get_stride_table(base, 1)
    return t, pe.StrideSpec(t.modulus, tuple(t.valid_residues))


def test_strided_kernel_b10_finds_69():
    plan = get_plan(10)
    table, spec = _stride_spec(10)
    periods = 4
    desc = np.zeros((2, 12), dtype=np.uint32)
    # descriptor 0 covers [47, 100): n0 = floor(47/M)*M
    n0 = (47 // spec.modulus) * spec.modulus
    from nice_tpu.ops.limbs import int_to_limbs as itl

    desc[0, 0:4] = itl(n0, 4)
    desc[0, 4:8] = itl(47, 4)
    desc[0, 8:12] = itl(100, 4)
    counts = np.asarray(
        pe.niceonly_strided_batch(plan, spec, desc, periods=periods)
    ).reshape(-1)
    assert counts[0] == 1  # 69
    assert counts[1:].sum() == 0  # empty descriptor contributes nothing


@pytest.mark.parametrize("base", [20, 40])
def test_strided_kernel_counts_match_host(base):
    """Device per-descriptor counts == host stride-table scan, including
    range-edge masking and period padding (the mirror-test pattern,
    client_process_gpu.rs:988-1075)."""
    plan = get_plan(base)
    table, spec = _stride_spec(base)
    br = base_range.get_base_range(base)
    periods = 4
    span = periods * spec.modulus
    from nice_tpu.ops.limbs import int_to_limbs as itl

    # ragged range: starts/ends mid-period
    lo = br[0] + 7
    hi = lo + 2 * span + 311
    desc_rows = []
    n0 = (lo // spec.modulus) * spec.modulus
    while n0 < hi:
        desc_rows.append((n0, lo, hi))
        n0 += span
    desc = np.zeros((len(desc_rows), 12), dtype=np.uint32)
    for i, (n0_, lo_, hi_) in enumerate(desc_rows):
        desc[i, 0:4] = itl(n0_, 4)
        desc[i, 4:8] = itl(lo_, 4)
        desc[i, 8:12] = itl(hi_, 4)
    counts = np.asarray(
        pe.niceonly_strided_batch(plan, spec, desc, periods=periods)
    ).reshape(-1)
    for i, (n0_, lo_, hi_) in enumerate(desc_rows):
        s, e = max(lo_, n0_), min(hi_, n0_ + span)
        want = sum(
            1
            for n in table.iterate_range(FieldSize(s, e), base)
        )
        # count candidates that are nice
        assert counts[i] == want, (base, i, desc_rows[i])


def test_engine_pallas_niceonly_matches_scalar_b20():
    base = 20
    br = base_range.get_base_range_field(base)
    fs = FieldSize(br.start(), min(br.end(), br.start() + 9_000))
    got = engine.process_range_niceonly(fs, base, backend="pallas", batch_size=BL)
    want = scalar.process_range_niceonly(fs, base)
    assert sorted(n.number for n in got.nice_numbers) == sorted(
        n.number for n in want.nice_numbers
    )


def test_engine_explicit_pallas_backend_b10():
    """End-to-end engine run through the Pallas path (interpreted), including
    the rare-path near-miss extraction."""
    br = base_range.get_base_range_field(10)
    got = engine.process_range_detailed(br, 10, backend="pallas", batch_size=BL)
    want = scalar.process_range_detailed(br, 10)
    assert got == want
    assert [(n.number, n.num_uniques) for n in got.nice_numbers] == [(69, 10)]


def test_zero_count_audit_catches_device_undercount(monkeypatch):
    """The sampled audit must turn a silent device undercount into a hard
    error: zero the kernel's counts over a range known to contain 69 and
    audit every zero-count descriptor."""
    import numpy as np

    monkeypatch.setenv("NICE_TPU_AUDIT_EVERY", "1")
    # Single-device path: the sharded step calls the kernel callable
    # directly, bypassing the patched batch entry point.
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    # Without this, the audit failure would (correctly) degrade to jnp and
    # heal; this test pins the detection itself.
    monkeypatch.setenv("NICE_TPU_NO_FALLBACK", "1")

    def zeroed(plan, spec, desc, periods=pe.STRIDED_PERIODS, n_real=None):
        return np.zeros((8, 128), dtype=np.int32)

    monkeypatch.setattr(pe, "niceonly_strided_batch", zeroed)
    br = base_range.get_base_range_field(10)
    with pytest.raises(RuntimeError, match="undercount"):
        engine.process_range_niceonly(br, 10, backend="pallas", batch_size=BL)


def test_zero_count_audit_passes_on_honest_counts(monkeypatch):
    monkeypatch.setenv("NICE_TPU_AUDIT_EVERY", "1")
    br = base_range.get_base_range_field(10)
    got = engine.process_range_niceonly(br, 10, backend="pallas", batch_size=BL)
    assert [n.number for n in got.nice_numbers] == [69]


def test_pipeline_propagates_producer_failure(monkeypatch):
    """An MSD-filter crash in the producer thread must surface on the caller
    (and never deadlock the dispatcher on a queue that stops filling)."""
    from nice_tpu.ops import msd_filter

    monkeypatch.setenv("NICE_TPU_SHARD", "0")

    def boom(*a, **k):
        raise RuntimeError("filter exploded")

    monkeypatch.setattr(msd_filter, "get_valid_ranges", boom)
    br = base_range.get_base_range_field(10)
    with pytest.raises(RuntimeError, match="filter exploded"):
        engine.process_range_niceonly(br, 10, backend="pallas", batch_size=BL)


def test_pipeline_propagates_dispatch_failure(monkeypatch):
    """A device-dispatch crash must shut down producer and collector cleanly
    and re-raise on the caller (fallback disabled; with it on, the same
    crash degrades to jnp instead — tests/test_faults.py covers that)."""
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    monkeypatch.setenv("NICE_TPU_NO_FALLBACK", "1")

    def boom(*a, **k):
        raise RuntimeError("dispatch exploded")

    monkeypatch.setattr(pe, "niceonly_strided_batch", boom)
    br = base_range.get_base_range_field(10)
    with pytest.raises(RuntimeError, match="dispatch exploded"):
        engine.process_range_niceonly(br, 10, backend="pallas", batch_size=BL)


def test_detailed_collector_propagates_failure(monkeypatch):
    """A rare-path re-scan crash inside the detailed collector thread must
    re-raise on the caller."""
    monkeypatch.setenv("NICE_TPU_SHARD", "0")

    def boom(*a, **k):
        raise RuntimeError("rare path exploded")

    monkeypatch.setattr(engine, "_rare_scan_survivors", boom)
    br = base_range.get_base_range_field(10)  # contains 69 -> rare path fires
    with pytest.raises(RuntimeError, match="rare path exploded"):
        engine.process_range_detailed(br, 10, backend="pallas", batch_size=BL)


def test_producer_fans_msd_filter_across_threads(monkeypatch):
    """The niceonly producer must run MSD filter calls CONCURRENTLY (the
    reference fans its filter across N CPU threads feeding the GPU,
    client_process_gpu.rs:624-660): with NICE_THREADS=4 and a filter stub
    that blocks until two calls are in flight, the field only completes if
    real fan-out happens — and chunk results must still come out in order."""
    import threading as th

    from nice_tpu.ops import msd_filter

    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    monkeypatch.setenv("NICE_THREADS", "4")
    # A b20 field big enough for >= 4 producer chunks at the pinned floor.
    monkeypatch.setenv("NICE_TPU_MSD_FLOOR", "256")
    from nice_tpu.ops import adaptive_floor

    adaptive_floor.reset_for_tests()

    real = msd_filter.get_valid_ranges
    barrier = th.Barrier(2)
    overlapped = th.Event()
    seen_starts = []
    lock = th.Lock()

    def instrumented(range_, base, **kw):
        if not overlapped.is_set():
            try:
                barrier.wait(timeout=10)
                overlapped.set()
            except th.BrokenBarrierError:
                pass  # < 2 concurrent calls: overlapped stays unset
        with lock:
            seen_starts.append(range_.start())
        return real(range_, base, **kw)

    monkeypatch.setattr(msd_filter, "get_valid_ranges", instrumented)
    base = 40  # range is ~6.5e12 wide: the 600k slice spans ~9 producer chunks
    br = base_range.get_base_range_field(base)
    fs = FieldSize(br.start(), min(br.end(), br.start() + 600_000))
    got = engine.process_range_niceonly(fs, base, backend="pallas", batch_size=BL)
    want = scalar.process_range_niceonly(fs, base)
    assert sorted(n.number for n in got.nice_numbers) == sorted(
        n.number for n in want.nice_numbers
    )
    assert overlapped.is_set(), "filter calls never overlapped"
    assert len(seen_starts) >= 4
