"""Pallas kernel tests in interpreter mode (no TPU required) — the analog of
the reference's compile-only NVRTC tests + CPU mirrors of the kernel index
math (client_process_gpu.rs:988-1451). Tiny block_rows keep interpretation
fast; the arithmetic is identical at any block shape because every op is
elementwise over (rows, 128)."""

import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar
from nice_tpu.ops import pallas_engine as pe
from nice_tpu.ops import vector_engine as ve
from nice_tpu.ops.limbs import get_plan, int_to_limbs

BR = 8  # block_rows for interpreter-mode tests
BL = BR * 128  # lanes per block


def test_detailed_kernel_b10_golden():
    plan = get_plan(10)
    h, nm = pe.detailed_batch(
        plan, BL, int_to_limbs(47, plan.limbs_n), np.int32(53), block_rows=BR
    )
    h = np.asarray(h)
    want = scalar.process_range_detailed(FieldSize(47, 100), 10)
    for d in want.distribution:
        assert h[d.num_uniques] == d.count, d
    assert h[0] == BL - 53  # padding lanes masked into bin 0
    assert h[plan.base + 2 :].sum() == 0
    assert int(nm) == 1  # 69 is nice, hence also a near miss


def test_detailed_kernel_multiblock_accumulation_b40():
    plan = get_plan(40)
    br = base_range.get_base_range(40)
    batch = 3 * BL
    sl = int_to_limbs(br[0], plan.limbs_n)
    h, nm = pe.detailed_batch(plan, batch, sl, np.int32(batch - 57), block_rows=BR)
    hj, nmj = ve.detailed_batch(plan, batch, sl, np.int32(batch - 57))
    assert np.array_equal(np.asarray(h)[: plan.base + 2], np.asarray(hj))
    assert int(nm) == int(nmj)


def test_niceonly_kernel_b10_finds_69():
    plan = get_plan(10)
    c = pe.niceonly_dense_batch(
        plan, BL, int_to_limbs(47, plan.limbs_n), np.int32(53), block_rows=BR
    )
    assert int(c) == 1


def test_uniques_kernel_matches_scalar_b40():
    plan = get_plan(40)
    br = base_range.get_base_range(40)
    u = np.asarray(
        pe.uniques_batch(plan, BL, int_to_limbs(br[0], plan.limbs_n), block_rows=BR)
    )
    for i in range(0, BL, 97):  # sample lanes
        assert int(u[i]) == scalar.get_num_unique_digits(br[0] + i, 40)


def test_detailed_kernel_matches_jnp_b17():
    """A b17 slice that contains near misses."""
    plan = get_plan(17)
    br = base_range.get_base_range(17)
    sl = int_to_limbs(br[0], plan.limbs_n)
    h, nm = pe.detailed_batch(plan, BL, sl, np.int32(BL), block_rows=BR)
    hj, nmj = ve.detailed_batch(plan, BL, sl, np.int32(BL))
    assert np.array_equal(np.asarray(h)[: plan.base + 2], np.asarray(hj))
    assert int(nm) == int(nmj)


def test_detailed_kernel_matches_scalar_b80():
    """b80 exercises 3 mask words + u128-wide limbs (the jnp comparison graph
    is too slow to compile on CPU, so diff against the scalar oracle)."""
    base, batch = 80, 256
    plan = get_plan(base)
    br = base_range.get_base_range(base)
    sl = int_to_limbs(br[0], plan.limbs_n)
    h, nm = pe.detailed_batch(plan, batch, sl, np.int32(batch), block_rows=2)
    h = np.asarray(h)
    want = np.zeros(plan.base + 2, dtype=np.int64)
    want_nm = 0
    for n in range(br[0], br[0] + batch):
        u = scalar.get_num_unique_digits(n, base)
        want[u] += 1
        want_nm += u > plan.near_miss_cutoff
    assert np.array_equal(h[: plan.base + 2], want)
    assert int(nm) == want_nm


def test_engine_explicit_pallas_backend_b10():
    """End-to-end engine run through the Pallas path (interpreted), including
    the rare-path near-miss extraction."""
    br = base_range.get_base_range_field(10)
    got = engine.process_range_detailed(br, 10, backend="pallas", batch_size=BL)
    want = scalar.process_range_detailed(br, 10)
    assert got == want
    assert [(n.number, n.num_uniques) for n in got.nice_numbers] == [(69, 10)]
