"""Block claim leases: N fields per round-trip under one lease.

Covers the /claim_block + /submit_block + block-aware /renew_claim surface:
partial submits, whole-block expiry and renewal, duplicate block replay
(exactly-once submit_id semantics per field inside a block), and the
client's block-mode loop end to end.
"""

import hashlib
import json
import sqlite3
import threading
from datetime import datetime, timezone

import pytest

from nice_tpu import CLIENT_VERSION
from nice_tpu.client import api_client
from nice_tpu.client import main as client_main
from nice_tpu.core.types import DataToServer, FieldClaimStrategy, SearchMode
from nice_tpu.server import app as server_app
from nice_tpu.server.db import Db, ts
from nice_tpu.server.field_queue import U128_MAX


@pytest.fixture()
def server(tmp_path):
    db_path = str(tmp_path / "nice-block.db")
    db = Db(db_path)
    db.seed_base(10, field_size=5)  # [47,100) -> 11 fields
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    base_url = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base_url, db_path
    srv.shutdown()
    api_client.close_connections()


def _niceonly_submission(data, username="blocky"):
    payload = DataToServer(
        claim_id=data.claim_id,
        username=username,
        client_version=CLIENT_VERSION,
        unique_distribution=None,
        nice_numbers=[],
    )
    content = json.dumps(payload.to_json(), sort_keys=True).encode()
    payload.submit_id = (
        f"{data.claim_id}-{hashlib.sha256(content).hexdigest()[:16]}"
    )
    return payload


def _query(db_path, sql, params=()):
    conn = sqlite3.connect(db_path)
    conn.row_factory = sqlite3.Row
    try:
        return conn.execute(sql, params).fetchall()
    finally:
        conn.close()


def test_claim_block_hands_out_n_fields_under_one_lease(server):
    base_url, db_path = server
    block_id, fields = api_client.claim_block_from_server(
        SearchMode.NICEONLY, base_url, "blocky", count=8, max_retries=0
    )
    # The acceptance bar for block mode: >= 8 fields per HTTP round-trip.
    assert len(fields) == 8
    assert len({f.claim_id for f in fields}) == 8
    assert len({(f.range_start, f.range_end) for f in fields}) == 8
    rows = _query(
        db_path, "SELECT field_id FROM claims WHERE block_id = ?", (block_id,)
    )
    assert len(rows) == 8


def test_partial_submit_then_rest_and_duplicate_replay(server):
    base_url, db_path = server
    block_id, fields = api_client.claim_block_from_server(
        SearchMode.NICEONLY, base_url, "blocky", count=4, max_retries=0
    )
    subs = [_niceonly_submission(f) for f in fields]

    # Partial submit: 2 of 4 members. The other two stay claimable work.
    resp = api_client.submit_block_to_server(
        base_url, block_id, subs[:2], max_retries=0
    )
    assert resp["accepted"] == 2
    assert resp["duplicates"] == 0 and resp["rejected"] == 0

    # The rest lands later under the same block.
    resp = api_client.submit_block_to_server(
        base_url, block_id, subs[2:], max_retries=0
    )
    assert resp["accepted"] == 2

    # Whole-block replay (client never saw the 200s): every member answers
    # duplicate, no new rows — exactly-once per field inside the block.
    resp = api_client.submit_block_to_server(
        base_url, block_id, subs, max_retries=0
    )
    assert resp["accepted"] == 0
    assert resp["duplicates"] == 4
    assert all(r.get("duplicate") for r in resp["results"])
    rows = _query(
        db_path,
        "SELECT COUNT(*) AS n FROM submissions WHERE claim_id IN"
        " (SELECT id FROM claims WHERE block_id = ?)",
        (block_id,),
    )
    assert rows[0]["n"] == 4


def test_block_mixed_submit_reports_per_item_results(server):
    base_url, _ = server
    block_id, fields = api_client.claim_block_from_server(
        SearchMode.NICEONLY, base_url, "blocky", count=3, max_retries=0
    )
    subs = [_niceonly_submission(f) for f in fields]
    bad = _niceonly_submission(fields[0])
    bad.claim_id = 999_999  # unknown claim -> per-item rejection
    bad.submit_id = None
    resp = api_client.submit_block_to_server(
        base_url, block_id, [subs[0], bad, subs[2]], max_retries=0
    )
    assert resp["accepted"] == 2
    assert resp["rejected"] == 1
    assert resp["results"][1]["status"] == "error"
    assert resp["results"][1]["code"] == 400


def test_renew_block_bumps_every_member(server):
    base_url, db_path = server
    block_id, fields = api_client.claim_block_from_server(
        SearchMode.NICEONLY, base_url, "blocky", count=3, max_retries=0
    )
    api_client.renew_block(base_url, block_id, max_retries=0)
    rows = _query(
        db_path,
        "SELECT f.last_claim_time AS t FROM fields f JOIN claims c"
        " ON c.field_id = f.id WHERE c.block_id = ?",
        (block_id,),
    )
    assert len(rows) == 3
    # One heartbeat stamped every member with the SAME renewal time.
    assert len({r["t"] for r in rows}) == 1
    # The stamp moved past the claim-time stamp (renewal happened after).
    claim_rows = _query(
        db_path, "SELECT claim_time FROM claims WHERE block_id = ?", (block_id,)
    )
    assert all(r["t"] >= c["claim_time"] for r in rows for c in claim_rows)


def test_renew_unknown_block_is_404(server):
    base_url, _ = server
    with pytest.raises(api_client.ApiError) as err:
        api_client.renew_block(base_url, "no-such-block", max_retries=0)
    assert err.value.status == 404


def test_expiry_and_renewal_cover_the_whole_block(tmp_path):
    """Db-level lease lifecycle: an active block is invisible to the claim
    engine, renewal re-arms every member, expiry releases every member."""
    db = Db(str(tmp_path / "lease.db"))
    db.seed_base(10, field_size=5)
    got = db._claim_batch(
        FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, U128_MAX, 3
    )
    assert len(got) == 3
    member_ids = {f.field_id for f in got}
    db.insert_claims_block(
        sorted(member_ids), SearchMode.NICEONLY, "10.0.0.1", "blk-lease"
    )

    # Active lease: no member is re-claimable.
    visible = db._claim_batch(
        FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, U128_MAX, 50
    )
    assert member_ids.isdisjoint({f.field_id for f in visible})

    # Renewal bumps every member at once.
    when, count = db.renew_block("blk-lease")
    assert count == 3
    with db._read_conn() as conn:
        stamps = {
            r[0]
            for r in conn.execute(
                "SELECT last_claim_time FROM fields WHERE id IN"
                f" ({','.join('?' * len(member_ids))})",
                sorted(member_ids),
            )
        }
    assert stamps == {ts(when)}

    # Expire the whole block: every member becomes claimable again together.
    past = ts(datetime(2000, 1, 1, tzinfo=timezone.utc))
    with db._lock, db._txn():
        db._conn.executemany(
            "UPDATE fields SET last_claim_time = ? WHERE id = ?",
            [(past, fid) for fid in sorted(member_ids)],
        )
    reclaimed = db._claim_batch(
        FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, U128_MAX, 50
    )
    assert member_ids <= {f.field_id for f in reclaimed}
    db.close()


def test_client_block_iteration_end_to_end(server):
    base_url, db_path = server
    args = client_main.build_parser().parse_args(
        [
            "niceonly",
            "--api-base", base_url,
            "--username", "blockclient",
            "--backend", "scalar",
            "--claim-block", "3",
            "--renew-secs", "0",
            "--telemetry-secs", "0",
            "--max-retries", "0",
        ]
    )
    api = api_client.AsyncApi(base_url, "blockclient", max_retries=0)
    try:
        assert client_main.run_block_iteration(
            args, api, SearchMode.NICEONLY
        )
    finally:
        api.shutdown()
    rows = _query(
        db_path,
        "SELECT COUNT(*) AS n FROM submissions WHERE username = ?",
        ("blockclient",),
    )
    assert rows[0]["n"] == 3
