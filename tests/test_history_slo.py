"""Performance observatory tests: history tier math, writer-actor
persistence roundtrip, SLO burn-rate state transitions, device-step
profiler attribution, and the profiler-off no-extra-syncs guarantee."""

import json
import urllib.error
import urllib.parse
import urllib.request

import pytest

from nice_tpu import obs
from nice_tpu.obs import history, slo, stepprof
from nice_tpu.obs.history import HistoryStore, TieredSeries, handle_query
from nice_tpu.server.db import Db
from nice_tpu.server.writer import WriteActor


# -- ring downsampling math -------------------------------------------------


def test_coarse_tier_bucket_aggregates():
    """raw -> 1m -> 15m tier math: finalized buckets carry exact
    mean/min/max/last/n for the samples that fell inside them."""
    s = TieredSeries(tier1_secs=60.0, tier2_secs=900.0)
    t0 = 1_000_020.0  # mid-bucket start: bucket ts must still align to 60s
    # Four samples inside one 1m bucket, then one in the next bucket.
    for i, v in enumerate((2.0, 4.0, 6.0, 8.0)):
        assert s.add(t0 + i * 5, v) == []  # no rollover yet
    done = s.add(t0 + 60, 10.0)
    assert [tier for tier, _ in done] == ["1m"]
    bts, mean, vmin, vmax, last, n = done[0][1]
    assert bts == 1_000_020.0 - (1_000_020.0 % 60)
    assert mean == pytest.approx(5.0)
    assert (vmin, vmax, last, n) == (2.0, 8.0, 8.0, 4)
    # The in-progress bucket shows up in snapshots (short-run visibility).
    snap = s.snapshot(since=0.0, tiers=("raw", "1m", "15m"))
    assert len(snap["raw"]) == 5
    assert len(snap["1m"]) == 2  # finalized + in-progress
    assert snap["1m"][1][1] == pytest.approx(10.0)
    assert len(snap["15m"]) == 1  # single in-progress 15m bucket

    # 15m rollover after crossing a 900 s boundary.
    done = s.add(t0 + 900, 1.0)
    tiers = dict(done)
    assert "15m" in tiers and "1m" in tiers
    assert tiers["15m"][5] == 5  # all five earlier samples in one bucket


def test_raw_ring_is_bounded(monkeypatch):
    s = TieredSeries(60.0, 900.0)
    for i in range(history.RAW_CAP + 50):
        s.add(1_000_000.0 + i, float(i))
    assert len(s.raw) == history.RAW_CAP


def test_store_samples_counters_gauges_and_histograms():
    reg = obs.Registry()
    c = reg.counter("t_hist_ctr", "d", labelnames=("mode",))
    g = reg.gauge("t_hist_gauge", "d")
    h = reg.histogram("t_hist_lat", "d", buckets=(0.1, 0.5, 1.0))
    c.labels("detailed").inc(3)
    c.labels("niceonly").inc(1)
    g.set(7.5)
    h.observe(0.05)  # create the label state before the first snapshot
    store = HistoryStore(tier1_secs=60.0, tier2_secs=900.0)
    store.sample_registries([reg], ts=1_000_000.0)
    names = store.series_names()
    assert 't_hist_ctr{mode="detailed"}' in names
    assert "t_hist_ctr" in names  # aggregate sum across label combos
    assert "t_hist_gauge" in names
    agg = store.query("t_hist_ctr")
    assert agg["raw"][0][1] == pytest.approx(4.0)

    # Histogram quantiles are windowed: derived from bucket-count DELTAS
    # between consecutive samples, so they need a second sample.
    for _ in range(20):
        h.observe(0.3)
    store.sample_registries([reg], ts=1_000_015.0)
    q = store.query("t_hist_lat_p95")
    assert q is not None and q["raw"]
    # All 20 observations sit in the (0.1, 0.5] bucket: the interpolated
    # p95 must land inside it.
    assert 0.1 <= q["raw"][-1][1] <= 0.5


def test_handle_query_contract():
    store = HistoryStore(tier1_secs=60.0, tier2_secs=900.0)
    store.add("a_series", 1.0, ts=1_000_000.0)
    status, body = handle_query(store, "")
    assert status == 200 and body["series"] == ["a_series"]
    status, body = handle_query(store, "series=a_series&since=0")
    assert status == 200 and body["series"]["a_series"]["raw"]
    status, body = handle_query(store, "series=nope")
    assert status == 404
    assert body["unknown"] == ["nope"] and "a_series" in body["known_sample"]
    status, body = handle_query(store, "series=a_series&since=abc")
    assert status == 400
    status, body = handle_query(store, "series=a_series&tier=bogus")
    assert status == 400
    status, body = handle_query(store, "series=a_series&tier=raw")
    assert status == 200 and list(body["series"]["a_series"]) == ["raw"]


def test_handle_query_labeled_series_with_commas():
    """Commas inside {label="..."} sets belong to the series name; only
    top-level commas separate the requested list."""
    store = HistoryStore(tier1_secs=60.0, tier2_secs=900.0)
    multi = 'req_total{endpoint="/status",status="200"}'
    store.add(multi, 3.0, ts=1_000_000.0)
    store.add("plain", 1.0, ts=1_000_000.0)
    status, body = handle_query(
        store, "series=" + urllib.parse.quote(f"{multi},plain")
    )
    assert status == 200
    assert set(body["series"]) == {multi, "plain"}


# -- persistence through the writer actor ----------------------------------


def test_history_rows_roundtrip_through_writer_actor(tmp_path):
    store = HistoryStore(tier1_secs=60.0, tier2_secs=900.0)
    t0 = 2_000_000.0
    for i in range(8):
        store.add("rt_series", float(i), ts=t0 + i * 10)  # crosses one 1m edge
    rows = store.drain_rows()
    assert rows and store.drain_rows() == []  # drain empties the pending set
    tiers = {r[1] for r in rows}
    assert "raw" in tiers and "1m" in tiers

    db = Db(str(tmp_path / "hist.db"))
    try:
        w = WriteActor(db)
        try:
            n = w.submit(db.insert_metric_history, rows).result(timeout=10)
            assert n == len(rows)
            # Idempotent upsert: re-inserting the same rows cannot dup.
            w.submit(db.insert_metric_history, rows).result(timeout=10)
        finally:
            w.close()
        got = db.get_metric_history("rt_series", tier="raw")
        assert [r["value"] for r in got] == [float(i) for i in range(8)]
        assert db.get_metric_history_series() == ["rt_series"]
        coarse = db.get_metric_history("rt_series", tier="1m")
        assert coarse and coarse[0]["n"] >= 1
        # Retention prune drops everything before the cutoff.
        pruned = db.prune_metric_history(t0 + 35)
        assert pruned > 0
        left = db.get_metric_history("rt_series", tier="raw")
        assert all(r["ts"] >= t0 + 35 for r in left)
    finally:
        db.close()


# -- SLO burn-rate state machine -------------------------------------------


def _quantile_spec(**kw):
    base = dict(
        name="t_claim_p99", kind="quantile", series_prefix="t_lat_p99",
        threshold=0.5, objective=0.10, short_secs=300, long_secs=3600,
    )
    base.update(kw)
    return slo.SloSpec(**base)


def test_slo_transitions_ok_warn_page_ok():
    store = HistoryStore(tier1_secs=60.0, tier2_secs=900.0)
    spec = _quantile_spec()
    eng = slo.SloEngine(store, specs=[spec])
    now = 3_000_000.0

    # No data -> ok (explicitly flagged).
    res = eng.evaluate(now=now)[0]
    assert res["state"] == "ok" and res["no_data"]

    # All samples under threshold -> ok.
    for i in range(10):
        store.add("t_lat_p99", 0.1, ts=now - 200 + i * 10)
    assert eng.evaluate(now=now)[0]["state"] == "ok"
    t_before = eng.transitions

    # Breach a fraction of the window above warn burn but below page burn:
    # 2 of ~12 samples bad -> bad_fraction ~0.17, burn ~1.7x.
    store.add("t_lat_p99", 0.9, ts=now - 95)
    store.add("t_lat_p99", 0.9, ts=now - 90)
    res = eng.evaluate(now=now)[0]
    assert res["state"] == "warn"
    assert res["burn_short"] >= 1.0
    assert eng.transitions == t_before + 1

    # Saturate the window -> page on both windows.
    for i in range(40):
        store.add("t_lat_p99", 2.0, ts=now - 80 + i * 2)
    res = eng.evaluate(now=now)[0]
    assert res["state"] == "page"
    assert res["burn_short"] >= spec.page_burn

    # Recover: advance time so the bad samples age out of both windows.
    later = now + 3600 * 2
    for i in range(10):
        store.add("t_lat_p99", 0.1, ts=later - 100 + i * 10)
    res = eng.evaluate(now=later)[0]
    assert res["state"] == "ok"
    states = [s["slo"] for s in eng.last()]
    assert states == ["t_claim_p99"]


def test_slo_ratio_kind_uses_counter_deltas():
    store = HistoryStore(tier1_secs=60.0, tier2_secs=900.0)
    now = 4_000_000.0
    # Counters grow over the window: 100 total, 10 bad -> 10% bad.
    for i, (tot, bad) in enumerate(((0, 0), (50, 2), (100, 10))):
        ts = now - 200 + i * 60
        store.add('t_req{endpoint="/submit",status="200"}', tot - bad, ts=ts)
        store.add('t_req{endpoint="/submit",status="500"}', bad, ts=ts)
    spec = slo.SloSpec(
        name="t_submit", kind="ratio", series_prefix="t_req",
        label_filter='endpoint="/submit', bad_filter=lambda s: 'status="5' in s,
        objective=0.01, short_secs=300, long_secs=3600,
    )
    res = spec.evaluate(store, now)
    assert res["burn_long"] == pytest.approx(10.0, rel=0.01)
    assert res["state"] == "page"


def test_default_specs_cover_issue_slos():
    names = {s.name for s in slo.default_specs()}
    assert {"claim_p99", "submit_success", "feed_idle_p95",
            "spot_check_fail"} <= names


# -- device-step profiler ---------------------------------------------------


@pytest.fixture()
def _prof_reset():
    stepprof.reset()
    yield
    stepprof.reset()


def _run_small_detailed(base=30, size=300_000, batch=1 << 12):
    from nice_tpu.core.base_range import get_base_range
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine

    start, _end = get_base_range(base)
    return engine.process_range_detailed(
        FieldSize(start, start + size), base, batch_size=batch
    )


def test_stepprof_disabled_adds_zero_fences(monkeypatch, _prof_reset):
    monkeypatch.setenv("NICE_TPU_STEPPROF", "0")
    _run_small_detailed()
    assert stepprof.fence_count() == 0
    assert stepprof.cumulative() == {}
    assert stepprof.LAST_BREAKDOWN == {}


def test_stepprof_buckets_sum_to_wall(monkeypatch, _prof_reset):
    monkeypatch.setenv("NICE_TPU_STEPPROF", "1")
    _run_small_detailed()
    cum = stepprof.cumulative()
    assert len(cum) == 1
    (key, entry), = cum.items()
    assert key.startswith("detailed|b30|")
    assert entry["fields"] == 1
    bucket_sum = sum(entry[p] for p in stepprof.PHASES)
    # host_other is derived as wall - sum(attributed), so the total must
    # reconcile within 10% (the acceptance bound from the observatory spec).
    assert bucket_sum == pytest.approx(entry["wall"], rel=0.10)
    assert stepprof.fence_count() > 0
    assert entry["device_compute"] > 0
    # The phase histogram series observed at least one phase.
    from nice_tpu.obs.series import STEPPROF_PHASE_SECONDS

    sums = STEPPROF_PHASE_SECONDS.label_sums()
    assert any(k[0] == "detailed" for k in sums)


def test_stepprof_thread_local_compile_attribution(_prof_reset):
    prof = stepprof.StepProfiler("detailed", 99, "jnp", enabled_override=True)
    with prof:
        stepprof.note_compile(0.25)
    assert stepprof.cumulative()["detailed|b99|jnp"]["compile"] == (
        pytest.approx(0.25)
    )
    # Outside any profiler context, note_compile is a silent no-op.
    stepprof.note_compile(1.0)
    assert stepprof.cumulative()["detailed|b99|jnp"]["compile"] == (
        pytest.approx(0.25)
    )


# -- server wiring: /history endpoint + periodic tick -----------------------


@pytest.fixture()
def obs_server(tmp_path, monkeypatch):
    import threading

    from nice_tpu.server import app as server_app

    monkeypatch.setenv("NICE_TPU_HISTORY_SECS", "3600")  # tick manually
    db_path = str(tmp_path / "obs.db")
    db = Db(db_path)
    db.seed_base(10, field_size=20)
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv.context
    srv.shutdown()


def test_server_history_endpoint_and_slo_block(obs_server):
    base_url, ctx = obs_server
    # Generate some API traffic, then take two samples so histogram
    # quantile series (windowed) materialize.
    urllib.request.urlopen(f"{base_url}/status", timeout=10).read()
    ctx.history_tick()
    urllib.request.urlopen(f"{base_url}/status", timeout=10).read()
    ctx.history_tick()

    with urllib.request.urlopen(f"{base_url}/history", timeout=10) as r:
        assert r.headers.get("Content-Type", "").startswith(
            "application/json"
        )
        directory = json.loads(r.read())
    assert directory["count"] >= 5
    assert any(s.startswith("nice_api_request") for s in directory["series"])

    name = directory["series"][0]
    q = urllib.parse.quote(name)
    with urllib.request.urlopen(
        f"{base_url}/history?series={q}", timeout=10
    ) as r:
        body = json.loads(r.read())
    assert body["series"][name]["raw"]

    # Unknown series: real 404 with a JSON body.
    try:
        urllib.request.urlopen(
            f"{base_url}/history?series=definitely_not_a_series", timeout=10
        )
        raise AssertionError("expected HTTP 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert e.headers.get("Content-Type", "").startswith(
            "application/json"
        )
        err = json.loads(e.read())
        assert err["unknown"] == ["definitely_not_a_series"]
        assert err["known_count"] >= 5

    # Ticks persisted rows into metric_history via the writer path.
    rows = ctx.db.get_metric_history_series()
    assert rows, "history_tick persisted no rows"

    # /status carries the SLO block.
    with urllib.request.urlopen(f"{base_url}/status", timeout=10) as r:
        status = json.loads(r.read())
    assert isinstance(status.get("slo"), list) and status["slo"]
    assert {s["slo"] for s in status["slo"]} >= {"claim_p99"}
    assert all(s["state"] in ("ok", "warn", "page") for s in status["slo"])


def test_local_serve_history_route(monkeypatch):
    """The client metrics port serves /history from the module STORE and
    JSON 404s for unknown paths."""
    history.STORE.add("local_series", 42.0)
    srv = obs.serve_metrics(0)
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        with urllib.request.urlopen(
            f"{base}/history?series=local_series", timeout=10
        ) as r:
            assert r.headers.get("Content-Type", "").startswith(
                "application/json"
            )
            body = json.loads(r.read())
        assert body["series"]["local_series"]["raw"][-1][1] == 42.0
        try:
            urllib.request.urlopen(
                f"{base}/definitely-not-a-path", timeout=10
            )
            raise AssertionError("expected HTTP 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert e.headers.get("Content-Type", "").startswith(
                "application/json"
            )
            assert "/history" in json.loads(e.read())["known"]
    finally:
        srv.shutdown()


def test_flight_kinds_cover_observatory_events():
    for kind in ("mesh_reshard", "device_loss", "trust_slash",
                 "consensus_hold", "slo_transition", "spot_check_fail"):
        assert kind in obs.flight._KNOWN_KINDS
