"""Python twin of web/search/worker.js's FastEngine.

There is no JS runtime in this image, so the browser fast engine is pinned by
transliteration: this module re-implements FastEngine line-for-line (24-bit
f64-exact limbs, schoolbook mul, small-constant chunked-radix digit peel, two
u32 presence masks) and differential-tests it against the scalar oracle. The
JS side additionally self-tests against its BigInt oracle at runtime on every
field and falls back on mismatch (worker.js processRange)."""

import math

import pytest

from nice_tpu.core import base_range
from nice_tpu.ops import scalar

LIMB = 1 << 24
MASK32 = 0xFFFFFFFF


def popcount32(x: int) -> int:
    return bin(x & MASK32).count("1")


class FastEngineTwin:
    def __init__(self, base: int):
        self.base = base
        e = 1
        while base ** (e + 1) <= LIMB:
            e += 1
        self.chunk_e = e
        self.chunk_div = base**e

    @staticmethod
    def from_int(v: int) -> list[int]:
        limbs = []
        while v > 0:
            limbs.append(v & (LIMB - 1))
            v >>= 24
        return limbs or [0]

    @staticmethod
    def to_int(limbs: list[int]) -> int:
        v = 0
        for x in reversed(limbs):
            v = (v << 24) | x
        return v

    @staticmethod
    def add_one(limbs: list[int]) -> None:
        for i in range(len(limbs)):
            limbs[i] += 1
            if limbs[i] < LIMB:
                return
            limbs[i] = 0
        limbs.append(1)

    @staticmethod
    def mul(a: list[int], b: list[int]) -> list[int]:
        out = [0] * (len(a) + len(b))
        for i, ai in enumerate(a):
            carry = 0
            for j, bj in enumerate(b):
                t = out[i + j] + ai * bj + carry
                assert t < 1 << 53  # the f64-exactness contract of the JS
                carry = t // LIMB
                out[i + j] = t - carry * LIMB
            out[i + len(b)] += carry
        while len(out) > 1 and out[-1] == 0:
            out.pop()
        return out

    @staticmethod
    def divmod_small(limbs: list[int], c: int) -> int:
        rem = 0
        for i in range(len(limbs) - 1, -1, -1):
            cur = rem * LIMB + limbs[i]
            assert cur < 1 << 53
            q = cur // c
            limbs[i] = q
            rem = cur - q * c
        while len(limbs) > 1 and limbs[-1] == 0:
            limbs.pop()
        return rem

    @staticmethod
    def is_zero(limbs: list[int]) -> bool:
        return len(limbs) == 1 and limbs[0] == 0

    def or_digits(self, value: list[int], masks: list[int]) -> None:
        v = list(value)
        base = self.base
        while not self.is_zero(v):
            rem = self.divmod_small(v, self.chunk_div)
            last = self.is_zero(v)
            for _ in range(self.chunk_e):
                d = rem % base
                rem = rem // base
                if d < 32:
                    masks[0] |= 1 << d
                else:
                    masks[1] |= 1 << (d - 32)
                if last and rem == 0:
                    break

    def num_uniques(self, n_limbs: list[int]) -> int:
        sq = self.mul(n_limbs, n_limbs)
        cu = self.mul(sq, n_limbs)
        masks = [0, 0]
        self.or_digits(sq, masks)
        self.or_digits(cu, masks)
        return popcount32(masks[0]) + popcount32(masks[1])


@pytest.mark.parametrize("base", [10, 17, 33, 40, 50, 64])
def test_twin_matches_oracle_across_the_range(base):
    br = base_range.get_base_range(base)
    if br is None:
        pytest.skip("no valid range")
    eng = FastEngineTwin(base)
    # Sample the start, middle and end of the valid range, plus 2^24-limb
    # boundary crossers when the range contains one.
    points = {br[0], (br[0] + br[1]) // 2, br[1] - 65}
    boundary = ((br[0] >> 24) + 1) << 24
    if boundary < br[1] - 64:
        points.add(boundary - 3)
    for p in points:
        limbs = eng.from_int(p)
        for n in range(p, min(p + 64, br[1])):
            assert eng.num_uniques(limbs) == scalar.get_num_unique_digits(
                n, base
            ), (base, n)
            eng.add_one(limbs)
            assert eng.to_int(limbs) == n + 1


def test_twin_base_ten_finds_69():
    eng = FastEngineTwin(10)
    limbs = eng.from_int(47)
    found = []
    for n in range(47, 100):
        if eng.num_uniques(limbs) == 10:
            found.append(n)
        eng.add_one(limbs)
    assert found == [69]


def test_chunk_constants_match_js_f64_contract():
    # chunkDiv <= 2^24 so rem * 2^24 + limb < 2^48 stays exact in f64.
    for base in range(4, 65):
        eng = FastEngineTwin(base)
        assert eng.chunk_div <= LIMB
        assert eng.chunk_div * base > LIMB  # e is maximal
        assert eng.chunk_div == base**eng.chunk_e


def test_mul_column_sums_fit_f64_for_supported_bases():
    """The JS engine is gated at base <= 64: verify the worst-case cube
    column sums stay under 2^53 there (asserted inside mul)."""
    for base in (50, 60, 64):
        br = base_range.get_base_range(base)
        if br is None:
            continue
        eng = FastEngineTwin(base)
        n = eng.from_int(br[1] - 1)
        sq = eng.mul(n, n)
        eng.mul(sq, n)  # raises inside mul if any column overflows
