"""Load harness at test scale: a few hundred coroutine clients against a
real server subprocess, faults pinned to a seed, exactly-once audited from
the ledger afterwards. Full-fleet runs (10k+) produce LOAD_*.json via the
CLI; this keeps the same code path honest inside tier-1 time."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts")
)

from load_harness import run_load  # noqa: E402


@pytest.fixture(scope="module")
def report():
    return run_load(
        clients=240,
        block_share=0.8,
        block_size=8,
        rounds=1,
        concurrency=120,
        fault_spec=(
            "http.submit_block:drop_response@0.05,"
            "http.submit:drop_response@0.05,"
            "http.claim_block:conn_error@0.02,"
            "http.claim:conn_error@0.02"
        ),
        fault_seed=7,
        run_label="test",
    )


def test_no_submission_lost_and_none_double_canonicalized(report):
    audit = report["exactly_once"]
    assert audit["owned"] > 0
    assert audit["lost"] == 0
    assert audit["double_canonicalized"] == 0
    assert audit["violations"] == 0


def test_faults_actually_fired_and_deduplicated(report):
    # The pinned seed must inject at this population size, and the dropped
    # submit responses must surface as duplicate replies — not new rows.
    assert report["errors"]["injected_faults"] > 0
    assert report["duplicates"] > 0


def test_block_clients_amortize_the_round_trip(report):
    # Acceptance bar: block-mode clients get >= 8 fields per claim RTT.
    assert report["fields_per_rtt_block"] >= 8


def test_latency_and_throughput_sane(report):
    # Loose bound: local loopback p99 under 5s even with faults + retries.
    assert 0 < report["claim"]["p99_ms"] < 5_000
    assert 0 < report["submit"]["p99_ms"] < 5_000
    assert report["throughput"]["fields_per_sec"] > 0
    assert report["throughput"]["submissions_accepted"] > 0


def test_keepalive_beats_fresh_connections(report):
    probe = report["keepalive_probe"]
    assert probe["keepalive_ms_mean"] > 0
    assert probe["fresh_conn_ms_mean"] > 0
    # Persistent connections skip the TCP handshake; on loopback the delta
    # is small but should essentially never be negative.
    assert probe["keepalive_ms_mean"] <= probe["fresh_conn_ms_mean"] * 1.5
