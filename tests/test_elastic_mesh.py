"""Elastic pod execution layer tests (8-virtual-device CPU mesh).

Covers the double-buffered host->device feed (depth A/B equivalence + stats),
elastic mesh downshift (a device killed mid-field via the fault injector must
reshard onto survivors and stay byte-identical to the fault-free scalar
oracle, with NO whole-field jnp/scalar downgrade), per-slice checkpoint
cursors (remaining-segment states resume byte-identically and survive the
manager's snapshot roundtrip), the mesh step cache's device-id keying, and
partition_segments' slicing invariants.
"""

import json

import jax
import pytest

from nice_tpu import ckpt, faults
from nice_tpu.client.main import compile_results
from nice_tpu.core import base_range
from nice_tpu.core.types import DataToClient, FieldSize, SearchMode
from nice_tpu.ops import engine, scalar
from nice_tpu.parallel import mesh as pmesh

BASE = 17
RANGE = FieldSize(5541, 30941)  # full base-17 valid range: 25,400 candidates


@pytest.fixture(autouse=True)
def _mesh_and_cleanup(monkeypatch):
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual CPU devices"
    assert engine._mesh_or_none() is not None
    # These tests pin per-BATCH dispatch granularity (feed gaps, fault-at-
    # dispatch-N, checkpoint cadence); under the megaloop default one
    # dispatch covers a whole segment and the 25k-candidate field collapses
    # to 1-2 dispatches. The megaloop interactions (downshift mid-slice,
    # segment-granular resume) are covered by test_megaloop.py/test_ckpt.py.
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    yield
    # Every test that kills a device or configures faults must not leak the
    # degraded mesh into its neighbors.
    faults.reset()
    pmesh.heal_devices()


def _field(claim_id=1):
    return DataToClient(
        claim_id=claim_id,
        base=BASE,
        range_start=RANGE.start(),
        range_end=RANGE.end(),
        range_size=RANGE.size(),
    )


# -- elastic downshift -------------------------------------------------------


def test_downshift_detailed_byte_identical_to_oracle():
    """Kill the last mesh device on dispatch 3 of a detailed field: the
    engine must rebuild the mesh over the 7 survivors, re-slice the remaining
    range, fold the partial accumulators, and finish ON DEVICE — the result
    byte-identical to the fault-free scalar oracle with no whole-field
    jnp/scalar downgrade."""
    faults.configure("mesh.dispatch:dead@3")
    got = engine.process_range_detailed(RANGE, BASE, backend="jnp", batch_size=256)
    want = scalar.process_range_detailed(RANGE, BASE)
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers
    assert got.backend_downgrades == ()  # downshift, not fallback
    stats = engine.LAST_FEED_STATS
    assert stats["reshards"] == 1
    assert stats["n_dev_start"] == 8
    assert stats["n_dev_end"] == 7
    assert stats["reshard_secs"] > 0


def test_downshift_niceonly_byte_identical_to_oracle():
    faults.configure("mesh.dispatch:dead:0@3")  # kill device 0, 3rd dispatch
    got = engine.process_range_niceonly(RANGE, BASE, backend="jnp", batch_size=256)
    want = scalar.process_range_niceonly(RANGE, BASE, None)
    assert got.nice_numbers == want.nice_numbers
    assert got.backend_downgrades == ()
    stats = engine.LAST_FEED_STATS
    assert stats["mode"] == "niceonly"
    assert stats["reshards"] == 1
    assert stats["n_dev_end"] == 7


def test_downshift_multi_device_loss():
    """Losing several devices at once still reshards onto the remainder."""
    faults.configure("mesh.dispatch:dead:1+5+6@2")
    got = engine.process_range_detailed(RANGE, BASE, backend="jnp", batch_size=256)
    want = scalar.process_range_detailed(RANGE, BASE)
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers
    assert got.backend_downgrades == ()
    assert engine.LAST_FEED_STATS["n_dev_end"] == 5


def test_elastic_disabled_restores_fallback_chain(monkeypatch):
    """NICE_TPU_ELASTIC=0 is the PR 4 behavior: the device loss degrades the
    whole field down the backend chain (correct but downgraded) instead of
    resharding."""
    monkeypatch.setenv("NICE_TPU_ELASTIC", "0")
    faults.configure("mesh.dispatch:dead@3")
    got = engine.process_range_detailed(RANGE, BASE, backend="jnp", batch_size=256)
    want = scalar.process_range_detailed(RANGE, BASE)
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers
    assert got.backend_downgrades != ()  # the whole-field downgrade happened


# -- double-buffered feed ----------------------------------------------------


@pytest.mark.parametrize("depth", ["0", "2", "8"])
def test_feed_depth_ab_equivalence(monkeypatch, depth):
    """Synchronous (depth 0) and pipelined feeds produce identical results;
    LAST_FEED_STATS records the depth actually used and the idle-gap series
    the scaling harness reads."""
    monkeypatch.setenv("NICE_TPU_FEED_DEPTH", depth)
    got = engine.process_range_detailed(RANGE, BASE, backend="jnp", batch_size=256)
    want = scalar.process_range_detailed(RANGE, BASE)
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers
    stats = engine.LAST_FEED_STATS
    assert stats["feed_depth"] == int(depth)
    assert stats["dispatches"] > 0
    # One inter-dispatch gap per consecutive pair.
    assert 0 < stats["gaps"] <= stats["dispatches"]
    assert stats["idle_p95"] >= stats["idle_p50"] >= 0


# -- per-slice checkpoint cursors --------------------------------------------


def test_per_slice_ckpt_resume_byte_identical(tmp_path):
    """Mesh-path checkpoints carry per-slice remaining segments; resuming
    from a mid-field snapshot yields a byte-identical submission."""
    data = _field()
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), data, SearchMode.DETAILED, "jnp", 256
    )
    states = []
    uninterrupted = engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=256,
        checkpoint_cb=states.append, checkpoint_batches=1, checkpoint_secs=0,
    )
    mids = [s for s in states if s.get("remaining") and len(s["remaining"]) > 1]
    assert mids, "no mid-field multi-slice checkpoint fired"
    mid = mids[len(mids) // 2]
    # Every remaining segment is ascending, disjoint, and inside the field.
    prev_end = RANGE.start()
    for s, e in mid["remaining"]:
        assert RANGE.start() <= s < e <= RANGE.end()
        assert s >= prev_end
        prev_end = e
    ck.save(mid)
    resume = ck.load()
    assert resume is not None
    assert resume["remaining"] == [tuple(s) for s in mid["remaining"]]
    resumed = engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=256, resume=resume,
    )
    a = compile_results(data, uninterrupted, SearchMode.DETAILED, "t")
    b = compile_results(data, resumed, SearchMode.DETAILED, "t")
    assert json.dumps(a.to_json(), sort_keys=True) == json.dumps(
        b.to_json(), sort_keys=True
    )


def test_niceonly_remaining_resume_equivalence():
    states = []
    full = engine.process_range_niceonly(
        RANGE, BASE, backend="jnp", batch_size=256,
        checkpoint_cb=states.append, checkpoint_batches=1, checkpoint_secs=0,
    )
    mids = [s for s in states if s.get("remaining")]
    assert mids, "no remaining-segment checkpoints fired"
    resumed = engine.process_range_niceonly(
        RANGE, BASE, backend="jnp", batch_size=256,
        resume=mids[len(mids) // 2],
    )
    assert resumed.nice_numbers == full.nice_numbers
    ref = scalar.process_range_niceonly(RANGE, BASE, None)
    assert resumed.nice_numbers == ref.nice_numbers


def test_downshift_checkpoint_resume(tmp_path):
    """A field that downshifted mid-scan still checkpoints resumable states:
    kill a device AND a later abort, then resume from the last snapshot."""
    data = _field()
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), data, SearchMode.DETAILED, "jnp", 256
    )
    states = []

    def save_and_capture(state):
        ck.save(state)
        states.append(state)

    faults.configure("mesh.dispatch:dead@2")
    engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=256,
        checkpoint_cb=save_and_capture, checkpoint_batches=1,
        checkpoint_secs=0,
    )
    assert engine.LAST_FEED_STATS["reshards"] == 1
    assert states, "no checkpoints fired"
    faults.reset()
    pmesh.heal_devices()
    # Resume from the LAST post-downshift snapshot on the healed 8-dev mesh.
    resume = ck.load()
    assert resume is not None
    resumed = engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=256, resume=resume,
    )
    ref = scalar.process_range_detailed(RANGE, BASE)
    assert resumed.distribution == ref.distribution
    assert resumed.nice_numbers == ref.nice_numbers


def test_manager_remaining_roundtrip(tmp_path):
    """The remaining-segments state contract (+ filtered flag) survives the
    snapshot format, and the signature carries the state version (3 since
    the megaloop widened the remaining-set granularity to whole segments)."""
    data = _field()
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), data, SearchMode.NICEONLY, "jnp", 256
    )
    assert ck.signature["state"] == 3
    state = {
        "cursor": 6000,
        "hist": None,
        "nice_numbers": [(5541, 12)],
        "remaining": [(6000, 7000), (9000, 30941)],
        "filtered": True,
    }
    ck.save(state)
    got = ck.load()
    assert got["remaining"] == [(6000, 7000), (9000, 30941)]
    assert got["filtered"] is True
    assert got["cursor"] == 6000
    assert got["nice_numbers"] == [(5541, 12)]


# -- mesh step cache ---------------------------------------------------------


def test_step_cache_keyed_on_device_ids():
    from nice_tpu.ops.limbs import get_plan

    pmesh.clear_step_cache()
    devices = jax.devices()[:4]
    plan = get_plan(BASE)
    m1 = pmesh.make_mesh(devices)
    m2 = pmesh.make_mesh(devices)  # distinct Mesh object, same devices
    s1 = pmesh.make_sharded_stats_step(plan, 128, m1, "detailed")
    s2 = pmesh.make_sharded_stats_step(plan, 128, m2, "detailed")
    assert s1 is s2  # dead-Mesh leak fix: keyed on device ids, not identity
    # Evicting an id used by the entry drops it; a rebuild recompiles.
    ids = pmesh.mesh_device_ids(m1)
    assert pmesh.clear_step_cache([ids[0]]) >= 1
    s3 = pmesh.make_sharded_stats_step(plan, 128, m1, "detailed")
    assert s3 is not s1
    # Clearing an id the entry does NOT contain leaves it cached.
    assert pmesh.clear_step_cache([10_000]) == 0
    assert pmesh.make_sharded_stats_step(plan, 128, m1, "detailed") is s3
    pmesh.clear_step_cache()


# -- partition_segments ------------------------------------------------------


def _covered(queues):
    segs = sorted(s for q in queues for s in q)
    for a, b in zip(segs, segs[1:]):
        assert a[1] <= b[0], f"overlap: {a} {b}"
    return sum(e - s for s, e in segs)


def test_partition_segments_covers_exactly():
    segs = [(0, 1000), (5000, 5300)]
    queues = pmesh.partition_segments(segs, 4, 128)
    assert len(queues) == 4
    assert _covered(queues) == 1300
    # Every slice's TOTAL is cut at a batch multiple (here ceil(1300/4)
    # rounded up to 128 -> 384) so slices dispatch whole batches until the
    # tail; a slice may span a segment boundary after a reshard.
    for q in queues[:-1]:
        assert sum(e - s for s, e in q) == 384
    assert queues[2] == [(768, 1000), (5000, 5152)]


def test_partition_segments_fewer_than_slices():
    queues = pmesh.partition_segments([(10, 20)], 8, 256)
    assert len(queues) == 8
    assert _covered(queues) == 10


def test_partition_segments_empty():
    assert pmesh.partition_segments([], 4, 128) == [[], [], [], []]


def test_partition_segments_single_slice():
    segs = [(0, 999), (2000, 2001)]
    assert pmesh.partition_segments(segs, 1, 128) == [[(0, 999), (2000, 2001)]]


# -- device-loss simulation helpers ------------------------------------------


def test_simulated_loss_filters_live_devices():
    devs = jax.devices()
    pmesh.simulate_device_loss([devs[2].id, devs[5].id])
    live = pmesh.live_devices(devs)
    assert len(live) == len(devs) - 2
    assert devs[2] not in live and devs[5] not in live
    # _mesh_or_none builds over the survivors until heal_devices().
    mesh = engine._mesh_or_none()
    assert mesh is not None and mesh.devices.size == len(live)
    pmesh.heal_devices()
    assert len(pmesh.live_devices(devs)) == len(devs)
