"""Golden-value tests for base ranges, transcribed from the reference
(common/src/base_range.rs:62-224)."""

from nice_tpu.core.base_range import (
    ceiling_root,
    floor_root,
    get_base_range,
    get_base_range_field,
    sqube_digit_counts,
)


def test_roots_exact():
    assert floor_root(0, 3) == 0
    assert floor_root(1, 3) == 1
    assert floor_root(7, 3) == 1
    assert floor_root(8, 3) == 2
    assert floor_root(26, 3) == 2
    assert floor_root(27, 3) == 3
    big = 10**60 + 12345
    r = floor_root(big, 3)
    assert r**3 <= big < (r + 1) ** 3
    assert ceiling_root(27, 3) == 3
    assert ceiling_root(28, 3) == 4
    for n in (2, 3, 5, 7):
        for x in (10**30 + 7, 2**127 - 1, 40**24, 3):
            r = floor_root(x, n)
            assert r**n <= x < (r + 1) ** n


def test_base_range_small():
    assert get_base_range(5) == (3, 5)
    assert get_base_range(6) is None
    assert get_base_range(7) == (7, 14)
    assert get_base_range(8) == (16, 23)
    assert get_base_range(9) == (27, 39)
    assert get_base_range(10) == (47, 100)
    assert get_base_range(20) == (58_945, 160_000)
    assert get_base_range(30) == (234_613_921, 729_000_000)


def test_base_range_production():
    assert get_base_range(40) == (1_916_284_264_916, 6_553_600_000_000)
    assert get_base_range(50) == (26_507_984_537_059_635, 97_656_250_000_000_000)
    assert get_base_range(60) == (
        556_029_612_114_824_200_908,
        2_176_782_336_000_000_000_000,
    )
    assert get_base_range(70) == (
        16_456_591_172_673_850_596_148_008,
        67_822_307_284_900_000_000_000_000,
    )
    assert get_base_range(80) == (
        653_245_554_420_798_943_087_177_909_799,
        2_814_749_767_106_560_000_000_000_000_000,
    )
    assert get_base_range(90) == (
        33_492_764_832_792_484_045_981_163_311_105_668,
        150_094_635_296_999_121_000_000_000_000_000_000,
    )


def test_base_range_beyond_u128():
    assert get_base_range(100) == (
        2154434690031883721759293566519350495260,
        10000000000000000000000000000000000000000,
    )
    assert get_base_range(110) == (
        169892749571608053239273597713205371466519752,
        814027493868397611133210000000000000000000000,
    )
    assert get_base_range(120) == (
        16117196090075248994613996554363597629408239219454,
        79496847203390844133441536000000000000000000000000,
    )
    assert get_base_range(121) is None
    assert get_base_range(122) == (
        118205024187370033135932935819405317049548439289856,
        586258581805989694050980431834549184603056531020211,
    )
    assert get_base_range(123) == (
        715085071699820536699499456671007010425915160419662,
        1594686179043939546502781159240976178904795301633108,
    )
    assert get_base_range(124) == (
        1944604500263970232242123784503740458789493393829926,
        4342450740818512904293955173690913927483946149220889,
    )
    assert get_base_range(125) == (
        5293955920339377119177015629247762262821197509765625,
        26469779601696885595885078146238811314105987548828125,
    )


def test_field_variant():
    f = get_base_range_field(10)
    assert f is not None
    assert (f.range_start, f.range_end) == (47, 100)
    assert get_base_range_field(6) is None


def test_sqube_digit_counts_exact():
    """Verify the exact-digit-count theorem (the TPU kernel's contract) by
    brute force at range edges for many bases."""

    def ndigits(x, b):
        n = 0
        while x:
            x //= b
            n += 1
        return n

    for base in list(range(5, 45)) + [50, 62, 64, 80, 97]:
        r = get_base_range(base)
        if r is None:
            continue
        d2, d3 = sqube_digit_counts(base)
        assert d2 + d3 == base
        for n in (r[0], r[0] + 1, (r[0] + r[1]) // 2, r[1] - 1):
            assert ndigits(n * n, base) == d2, (base, n)
            assert ndigits(n * n * n, base) == d3, (base, n)
