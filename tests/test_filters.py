"""Golden-value tests for the filter cascade, transcribed from the reference
(residue_filter.rs:27-76, lsd_filter.rs:244-331, stride_filter.rs:162-246)."""

import numpy as np
import pytest

from nice_tpu.core.types import FieldSize
from nice_tpu.ops import lsd_filter, msd_filter, residue_filter
from nice_tpu.ops.stride_filter import StrideTable


def test_residue_filter_goldens():
    f = residue_filter.get_residue_filter
    assert f(10) == (0, 3, 6, 8)
    assert f(11) == ()
    assert f(12) == (0, 10)
    assert f(13) == (5, 9)
    assert f(14) == (0, 12)
    assert f(15) == ()
    assert f(16) == (0, 5, 9, 14)
    assert f(17) == (7,)
    assert f(18) == (0, 16)
    assert f(19) == ()
    assert f(20) == (0, 18)
    assert f(21) == (5, 9)
    assert f(22) == (0, 6, 14, 20)
    assert f(23) == ()
    assert f(24) == (0, 22)
    assert f(25) == (2, 3, 6, 11, 14, 18)
    assert f(26) == (0, 5, 10, 15, 20, 24)
    assert f(27) == ()
    assert f(28) == (0, 9, 18, 26)
    assert f(29) == (13, 21)
    assert f(30) == (0, 28)
    assert f(40) == (0, 12, 26, 38)
    assert f(50) == (0, 7, 14, 21, 28, 35, 42, 48)
    assert f(60) == (0, 58)
    assert f(70) == (0, 23, 45, 68)
    assert f(80) == (0, 78)
    assert f(90) == (0, 88)
    assert f(100) == (0, 21, 33, 44, 54, 66, 87, 98)
    assert f(110) == (0, 108)
    assert f(111) == ()
    assert f(112) == (0, 36, 74, 110)
    assert f(113) == (7, 55)
    assert f(114) == (0, 112)
    assert f(115) == ()
    assert f(116) == (0, 45, 69, 114)
    assert f(117) == (29, 57)
    assert f(118) == (0, 12, 26, 39, 51, 78, 90, 116)
    assert f(119) == ()
    assert f(120) == (0, 34, 84, 118)


def test_lsd_filter_base10():
    assert lsd_filter.get_valid_lsds(10) == (2, 3, 4, 7, 8, 9)


def test_lsd_bitmap_k1_matches_single_digit():
    for base in (10, 13, 17, 40, 50, 80):
        bitmap = lsd_filter.get_valid_multi_lsd_bitmap(base, 1)
        valid = tuple(i for i, v in enumerate(bitmap) if v)
        assert valid == lsd_filter.get_valid_lsds(base)


def test_lsd_bitmap_k2_sound():
    """Every k=2-valid suffix must also be k=1-valid mod b, and 69's suffix
    must survive in base 10."""
    base = 10
    bitmap2 = lsd_filter.get_valid_multi_lsd_bitmap(base, 2)
    valid1 = set(lsd_filter.get_valid_lsds(base))
    for s, ok in enumerate(bitmap2):
        if ok:
            assert s % base in valid1
    assert bitmap2[69]


def test_stride_table_base10_k1():
    t = StrideTable(10, 1)
    assert t.modulus == 90
    assert len(t.valid_residues) == len(t.gap_table) > 0
    assert sum(t.gap_table) == t.modulus


def test_stride_table_base40_k2():
    t = StrideTable(40, 2)
    assert t.modulus == 62_400
    assert 0 < len(t.valid_residues) < t.modulus
    assert sum(t.gap_table) == t.modulus


def test_first_valid_at_or_after():
    t = StrideTable(10, 1)
    n, idx = t.first_valid_at_or_after(0)
    assert n == t.valid_residues[idx]
    first = t.valid_residues[0]
    n, idx = t.first_valid_at_or_after(first)
    assert (n, idx) == (first, 0)
    n, idx = t.first_valid_at_or_after(t.modulus + 5)
    assert n >= t.modulus + 5
    assert n % t.modulus == t.valid_residues[idx]


def test_stride_iteration_finds_69():
    t = StrideTable(10, 1)
    results = t.iterate_range(FieldSize(60, 80), 10)
    assert any(r.number == 69 for r in results)


def test_candidate_index_roundtrip():
    for base, k in ((10, 1), (40, 2), (50, 1)):
        t = StrideTable(base, k)
        start = 10**6 + 1
        n, idx = t.first_valid_at_or_after(start)
        g = t.candidate_index(n)
        assert t.candidate_at(g) == n
        # consecutive g enumerate the same sequence as gap jumps
        m = n
        for step in range(25):
            assert t.candidate_at(g + step) == m
            m += t.gap_table[(idx + step) % len(t.gap_table)]


def test_count_candidates_matches_iteration():
    t = StrideTable(10, 1)
    rng = FieldSize(47, 1000)
    count = t.count_candidates(rng)
    n, idx = t.first_valid_at_or_after(47)
    seen = 0
    while n < 1000:
        seen += 1
        n += t.gap_table[idx]
        idx = (idx + 1) % len(t.gap_table)
    assert count == seen


def test_msd_filter_single_value_not_skipped():
    assert not msd_filter.has_duplicate_msd_prefix(FieldSize(69, 70), 10)


def test_msd_filter_soundness_b10():
    """Any range the filter skips must contain no nice numbers (69 is the only
    nice number in base 10)."""
    for lo in range(47, 95, 3):
        for hi in (lo + 2, lo + 7, lo + 20):
            hi = min(hi, 100)
            if lo >= hi:
                continue
            if msd_filter.has_duplicate_msd_prefix(FieldSize(lo, hi), 10):
                assert not (lo <= 69 < hi)


def test_msd_recursive_covers_69():
    ranges = msd_filter.get_valid_ranges(FieldSize(47, 100), 10)
    assert any(r.range_start <= 69 < r.range_end for r in ranges)
    # Output ranges are disjoint, ordered, within bounds.
    prev_end = 47
    for r in ranges:
        assert r.range_start >= prev_end
        assert r.range_end <= 100
        prev_end = r.range_end


@pytest.mark.parametrize(
    "base,k",
    [(10, 1), (10, 3), (40, 2), (50, 3), (96, 2), (130, 2), (150, 2), (200, 2)],
)
def test_lsd_bitmap_matches_scalar_oracle(base, k):
    # Differential test of the vectorized bitmap against the direct
    # transcription of the definition. Bases above 128 exercise the 3rd/4th
    # digit-presence mask words (advisor finding, round 3: the old two-word
    # layout shifted by >= 64 bits — numpy UB — and produced wrong bitmaps at
    # bases 130/150/200).
    assert np.array_equal(
        lsd_filter._bitmap_scalar(base, k),
        lsd_filter.get_valid_multi_lsd_bitmap(base, k),
    )
