"""Multi-tenant scheduler tests (nice_tpu/sched/).

Covers the acceptance contract from the subsystem's design: a two-tenant
interleaved run assembles byte-identical field results vs each tenant run
alone; a preemption at a page boundary exports the engine's checkpoint
contract and resumes byte-identically; the anti-starvation bound holds
against a greedy high-priority tenant; PageTable packing invariants (one
limb plan per page, segment-quantum alignment); SLO-burn priority boosts;
and an elastic downshift landing mid-multi-tenant-run.
"""

import jax
import pytest

from nice_tpu import faults
from nice_tpu.core.types import FieldSize
from nice_tpu.obs.history import HistoryStore
from nice_tpu.ops import engine
from nice_tpu.parallel import mesh as pmesh
from nice_tpu.sched import (
    MultiTenantScheduler,
    PageTable,
    StaticSource,
    TenantRegistry,
    TenantSpec,
)

BASE = 17
# Two disjoint sub-ranges of base 17's valid range (base_range lower bound
# 5541): one per tenant, small enough for fast jnp-backend CPU runs but
# several pages long at the pinned 512-number quantum.
RANGE_A = FieldSize(5541, 9541)
RANGE_B = FieldSize(9541, 13541)


@pytest.fixture(autouse=True)
def _small_pages(monkeypatch):
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual CPU devices"
    # Pin the segment quantum: batch 256 x megaloop 2 = 512 numbers, so a
    # page_batches=1 table cuts 512-number pages and a 4000-number field is
    # 8 pages. warm() is patched out — it AOT-compiles the jax backend,
    # which is the slow path these jnp-backend tests do not dispatch.
    monkeypatch.setenv("NICE_TPU_MEGALOOP_SEGMENT", "2")
    monkeypatch.setattr(MultiTenantScheduler, "warm", lambda self: None)
    yield
    faults.reset()
    pmesh.heal_devices()


def _spec(name, mode, priority=1, slo=0.0):
    return TenantSpec(
        name=name, mode=mode, base=BASE, priority=priority,
        slo_page_secs=slo, backend="jnp", batch_size=256,
    )


def _sched(registry, source, **kw):
    kw.setdefault("policy", "deficit")
    kw.setdefault("page_batches", 1)
    # An always-elapsed quantum preempts at EVERY page boundary — maximal
    # interleaving, deterministic without a fake clock.
    kw.setdefault("quantum_secs", 1e-9)
    return MultiTenantScheduler(registry, source, **kw)


# -- two-tenant byte-equivalence ---------------------------------------------


def test_two_tenant_interleaved_byte_identical_to_solo_runs():
    """A detailed and a niceonly tenant interleaved page-by-page on one
    mesh assemble exactly the results each would produce running alone."""
    reg = TenantRegistry([
        _spec("det", "detailed", priority=2),
        _spec("nice", "niceonly", priority=1),
    ])
    source = StaticSource({
        "det": [("det/f0", BASE, RANGE_A.start(), RANGE_A.end())],
        "nice": [("nice/f0", BASE, RANGE_B.start(), RANGE_B.end())],
    })
    sched = _sched(reg, source)
    stats = sched.run()

    want_det = engine.process_range_detailed(
        RANGE_A, BASE, backend="jnp", batch_size=256
    )
    want_nice = engine.process_range_niceonly(
        RANGE_B, BASE, backend="jnp", batch_size=256
    )
    got_det = source.results["det"]["det/f0"]
    got_nice = source.results["nice"]["nice/f0"]
    assert got_det.distribution == want_det.distribution
    assert got_det.nice_numbers == want_det.nice_numbers
    assert got_nice.distribution == ()
    assert got_nice.nice_numbers == want_nice.nice_numbers
    # The run really interleaved: both tenants were preempted at page
    # boundaries mid-field, and the table's packing held throughout.
    assert stats["tenants"]["det"]["preemptions"] > 0
    assert stats["tenants"]["nice"]["preemptions"] > 0
    assert sched.table.check_invariants() == []


def test_round_robin_policy_also_byte_identical():
    reg = TenantRegistry([
        _spec("a", "detailed"), _spec("b", "detailed"),
    ])
    source = StaticSource({
        "a": [("a/f0", BASE, RANGE_A.start(), RANGE_A.end())],
        "b": [("b/f0", BASE, RANGE_B.start(), RANGE_B.end())],
    })
    _sched(reg, source, policy="rr").run()
    for name, rng in (("a", RANGE_A), ("b", RANGE_B)):
        want = engine.process_range_detailed(
            rng, BASE, backend="jnp", batch_size=256
        )
        got = source.results[name][f"{name}/f0"]
        assert got.distribution == want.distribution
        assert got.nice_numbers == want.nice_numbers


# -- preemption resume via the checkpoint contract ----------------------------


def test_preempted_field_resumes_byte_identical_via_ckpt_contract():
    """Fold a strict prefix of a field's pages, export resume_state(), and
    finish through the engine's standing resume= path: the stitched result
    must equal the uninterrupted run."""
    spec = _spec("det", "detailed")
    table = PageTable(page_batches=1)
    work = table.add_field(
        spec, "det/f0", BASE, RANGE_A.start(), RANGE_A.end()
    )
    assert len(work.pages) > 2
    for page in work.pages[:3]:  # run + fold a prefix, then "preempt"
        res = engine.process_range_detailed(
            FieldSize(page.start, page.end), BASE,
            backend="jnp", batch_size=256,
        )
        work.fold(page, res)
    state = work.resume_state()
    assert state["cursor"] == work.pages[2].end
    assert state["remaining"] == [[work.pages[2].end, RANGE_A.end()]]
    got = engine.process_range_detailed(
        RANGE_A, BASE, backend="jnp", batch_size=256, resume=state
    )
    want = engine.process_range_detailed(
        RANGE_A, BASE, backend="jnp", batch_size=256
    )
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers


def test_preempted_niceonly_resume():
    spec = _spec("nice", "niceonly")
    table = PageTable(page_batches=1)
    work = table.add_field(
        spec, "nice/f0", BASE, RANGE_B.start(), RANGE_B.end()
    )
    page = work.pages[0]
    work.fold(page, engine.process_range_niceonly(
        FieldSize(page.start, page.end), BASE, backend="jnp", batch_size=256,
    ))
    got = engine.process_range_niceonly(
        RANGE_B, BASE, backend="jnp", batch_size=256,
        resume=work.resume_state(),
    )
    want = engine.process_range_niceonly(
        RANGE_B, BASE, backend="jnp", batch_size=256
    )
    assert got.nice_numbers == want.nice_numbers


# -- starvation bound ---------------------------------------------------------


def test_starvation_bound_under_greedy_high_priority_tenant():
    """Pure priority policy + a priority-5 tenant with a deep field queue:
    the priority-0 tenant still finishes its field because the skipped-
    rounds bound forces it onto the mesh."""
    reg = TenantRegistry([
        _spec("greedy", "detailed", priority=5),
        _spec("meek", "niceonly", priority=0),
    ])
    step = 1024
    greedy_fields = [
        (f"greedy/f{i}", BASE, RANGE_A.start() + i * step,
         RANGE_A.start() + (i + 1) * step)
        for i in range(6)
    ]
    source = StaticSource({
        "greedy": greedy_fields,
        "meek": [("meek/f0", BASE, RANGE_B.start(), RANGE_B.start() + 1024)],
    })
    sched = _sched(reg, source, policy="priority", starvation_rounds=2)
    stats = sched.run()
    assert stats["tenants"]["meek"]["fields"] == 1
    assert stats["tenants"]["meek"]["starved"] > 0
    assert stats["tenants"]["greedy"]["fields"] == len(greedy_fields)


def test_starvation_bound_disabled_priority_runs_greedy_first():
    """With the bound off, pure priority drains the high-priority tenant
    completely before the low one runs at all — the behavior the bound
    exists to cap."""
    reg = TenantRegistry([
        _spec("greedy", "detailed", priority=5),
        _spec("meek", "niceonly", priority=0),
    ])
    source = StaticSource({
        "greedy": [("greedy/f0", BASE, RANGE_A.start(), RANGE_A.start() + 2048)],
        "meek": [("meek/f0", BASE, RANGE_B.start(), RANGE_B.start() + 1024)],
    })
    sched = _sched(reg, source, policy="priority", starvation_rounds=0)
    stats = sched.run()
    assert stats["tenants"]["meek"]["starved"] == 0
    assert stats["tenants"]["meek"]["fields"] == 1  # still drains at the end


# -- page-table packing invariants -------------------------------------------


def test_pagetable_packing_invariants():
    """Pages align to each tenant's own segment quantum, cover fields
    exactly, and never mix limb plans — two tenants with different bases
    and batch shapes pack side by side."""
    table = PageTable(page_batches=2)
    lo = TenantSpec(name="lo", mode="detailed", base=10,
                    backend="jnp", batch_size=256)
    hi = TenantSpec(name="hi", mode="detailed", base=40,
                    backend="jnp", batch_size=128)
    w1 = table.add_field(lo, "lo/f0", 10, 1000, 6000)
    w2 = table.add_field(hi, "hi/f0", 40, 7000, 8000)
    assert table.check_invariants() == []
    # quantum = page_batches * batch * megaloop (2 * 256 * 2 / 2 * 128 * 2).
    assert table.quantum_for(lo) == 1024
    assert table.quantum_for(hi) == 512
    assert all(p.size == 1024 for p in w1.pages[:-1])
    assert all(p.tenant == "lo" and p.base == 10 for p in w1.pages)
    assert all(p.tenant == "hi" and p.base == 40 for p in w2.pages)
    assert w1.pages[0].start == 1000 and w1.pages[-1].end == 6000
    # A field never pages twice, and folds never run out of order.
    with pytest.raises(ValueError, match="already paged"):
        table.add_field(lo, "lo/f0", 10, 1000, 6000)
    with pytest.raises(ValueError, match="out of order"):
        from nice_tpu.core.types import FieldResults
        w1.fold(w1.pages[1], FieldResults(
            distribution=(), nice_numbers=(), backend_downgrades=(),
        ))


def test_pagetable_rejects_empty_field():
    table = PageTable(page_batches=1)
    with pytest.raises(ValueError, match="empty field"):
        table.add_field(_spec("t", "detailed"), "t/f0", BASE, 100, 100)


# -- SLO-burn priority boost --------------------------------------------------


def test_slo_burn_boosts_priority_and_preempts():
    """A tenant blowing its page budget earns a warn-level boost that (a)
    raises its effective priority above an idle incumbent and (b) surfaces
    as a slo_boost preemption reason at the incumbent's next boundary."""
    now = 1_000_000.0
    slow = _spec("slow", "detailed", priority=0, slo=0.01)
    calm = _spec("calm", "detailed", priority=1)
    reg = TenantRegistry([slow, calm])
    source = StaticSource({
        "slow": [("slow/f0", BASE, RANGE_A.start(), RANGE_A.start() + 1024)],
        "calm": [("calm/f0", BASE, RANGE_B.start(), RANGE_B.start() + 1024)],
    })
    hist = HistoryStore()
    # quantum_secs=0 disables the time quantum so the slo_boost preemption
    # reason is the one that fires.
    sched = _sched(
        reg, source, slo_boost=2, history=hist, wall=lambda: now,
        quantum_secs=0.0,
    )
    # Every recent page blew the 10ms budget: bad_fraction 1.0 against a
    # 0.25 objective burns at 4x on both windows -> warn -> boost 1 * 2.
    for i in range(10):
        hist.add('nice_sched_page_seconds{tenant="slow"}', 1.0, ts=now - i)
    sched._slo_tick(now=now)
    assert sched.effective_priority(slow) == 0 + 2
    assert sched.effective_priority(calm) == 1
    # The burning tenant now outranks the incumbent: the incumbent's next
    # page boundary reports a slo_boost preemption (it has queued pages).
    assert sched._ensure_work(slow)
    assert sched._preempt_reason(calm, turn_started=0.0) == "slo_boost"


def test_no_budget_no_boost():
    spec = _spec("free", "detailed")  # slo_page_secs=0: no SLO spec at all
    reg = TenantRegistry([spec])
    sched = _sched(reg, StaticSource({"free": []}), slo_boost=2)
    sched._slo_tick(now=123.0)
    assert sched.effective_priority(spec) == spec.priority


# -- elastic downshift mid-multi-tenant run -----------------------------------


def test_elastic_downshift_mid_multi_tenant_run(monkeypatch):
    """Kill a mesh device during an interleaved two-tenant run: the elastic
    layer reshards under the scheduler's feet and every assembled field is
    still byte-identical to the fault-free oracle, with no whole-field
    backend downgrade recorded."""
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")  # per-batch dispatch
    reg = TenantRegistry([
        _spec("det", "detailed", priority=2),
        _spec("nice", "niceonly", priority=1),
    ])
    source = StaticSource({
        "det": [("det/f0", BASE, RANGE_A.start(), RANGE_A.end())],
        "nice": [("nice/f0", BASE, RANGE_B.start(), RANGE_B.end())],
    })
    faults.configure("mesh.dispatch:dead@3")
    sched = _sched(reg, source, page_batches=4)
    sched.run()
    faults.reset()
    pmesh.heal_devices()
    want_det = engine.process_range_detailed(
        RANGE_A, BASE, backend="jnp", batch_size=256
    )
    want_nice = engine.process_range_niceonly(
        RANGE_B, BASE, backend="jnp", batch_size=256
    )
    got_det = source.results["det"]["det/f0"]
    got_nice = source.results["nice"]["nice/f0"]
    assert got_det.distribution == want_det.distribution
    assert got_det.nice_numbers == want_det.nice_numbers
    assert got_nice.nice_numbers == want_nice.nice_numbers
    assert got_det.backend_downgrades == ()
    assert got_nice.backend_downgrades == ()
