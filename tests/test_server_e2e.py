"""End-to-end control-plane test: seed ledger -> serve -> claim -> process ->
submit -> consensus -> validate.

The reference has no integration harness (its --validate runs against prod,
SURVEY.md section 4.7); here the whole loop runs against a local server +
sqlite ledger in-process.
"""

import json
import threading
import urllib.request

import pytest

from nice_tpu.client import api_client
from nice_tpu.client.main import compile_results, process_field
from nice_tpu.core.types import DataToClient, SearchMode
from nice_tpu.jobs import main as jobs_main
from nice_tpu.server import app as server_app
from nice_tpu.server.db import Db


@pytest.fixture()
def server(tmp_path):
    db_path = str(tmp_path / "nice-test.db")
    db = Db(db_path)
    db.seed_base(10, field_size=20)  # [47,100) -> 3 fields
    db.seed_base(17, field_size=30_000)
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    base_url = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base_url, db_path
    srv.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_full_claim_process_submit_loop(server):
    base_url, db_path = server

    # status shows prefilled queues
    status = _get(f"{base_url}/status")
    assert status["status"] == "ok"
    assert status["niceonly_queue_size"] > 0

    # claim + process + submit until some field has two agreeing detailed
    # submissions (-> consensus CL3). Once every field is CL2, most strategy
    # rolls return 500 "could not find any field" (reference parity: only the
    # 4% recheck roll uses max_check_level=2) — tolerate those and keep going.
    # Once every field is CL2 only the 4% recheck roll can claim, so the
    # attempt budget must be large enough that missing it is negligible
    # (0.96^200 ~ 3e-4; 60 attempts flaked at ~13%).
    submissions_per_field: dict[int, int] = {}
    for _ in range(220):
        try:
            data = api_client.get_field_from_server(
                SearchMode.DETAILED, base_url, "tester", max_retries=0
            )
        except api_client.ApiError:
            continue  # claim exhaustion roll; try another strategy roll
        results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
        submission = compile_results(data, results, SearchMode.DETAILED, "tester")
        api_client.submit_field_to_server(base_url, submission, max_retries=0)
        key = (data.range_start, data.range_end)
        submissions_per_field[key] = submissions_per_field.get(key, 0) + 1
        if max(submissions_per_field.values()) >= 2:
            break
    assert max(submissions_per_field.values()) >= 2

    # niceonly claim + submit (honor system)
    data = api_client.get_field_from_server(
        SearchMode.NICEONLY, base_url, "tester", max_retries=0
    )
    results, _ = process_field(data, SearchMode.NICEONLY, "scalar", 1024)
    submission = compile_results(data, results, SearchMode.NICEONLY, "tester")
    api_client.submit_field_to_server(base_url, submission, max_retries=0)

    # run the consensus + downsampling jobs
    db = Db(db_path)
    jobs_main.run_all(db)

    # after consensus, some base-10 field must be double-checked with a canon
    fields = db.get_fields_in_base(10)
    assert any(
        f.check_level >= 3 and f.canon_submission_id is not None for f in fields
    )
    db.close()

    # validation endpoint serves a canonical field the client can check
    vdata = api_client.get_validation_data_from_server(base_url, "tester")
    assert vdata.range_size == vdata.range_end - vdata.range_start
    assert sum(d.count for d in vdata.unique_distribution) == vdata.range_size

    # metrics exporter exposes request counters
    with urllib.request.urlopen(f"{base_url}/metrics", timeout=10) as r:
        metrics = r.read().decode()
    assert "nice_api_requests_total" in metrics
    assert 'endpoint="/submit"' in metrics
    # latency histogram (reference api/src/main.rs:438-459): bucket series,
    # +Inf terminal bucket, and count/sum pairs per endpoint
    assert "# TYPE nice_api_request_seconds histogram" in metrics
    assert 'nice_api_request_seconds_bucket{endpoint="/submit",le="0.005"}' in metrics
    assert 'nice_api_request_seconds_bucket{endpoint="/submit",le="+Inf"}' in metrics
    assert 'nice_api_request_seconds_count{endpoint="/submit"}' in metrics
    assert 'nice_api_request_seconds_sum{endpoint="/submit"}' in metrics


def test_submit_verification_rejects_bad_distribution(server):
    base_url, _ = server
    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "cheater", max_retries=0
    )
    results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
    submission = compile_results(data, results, SearchMode.DETAILED, "cheater")
    # corrupt the distribution: change one bucket count
    bad = submission.to_json()
    bad["unique_distribution"][3]["count"] += 1
    with pytest.raises(api_client.ApiError) as err:
        api_client.retry_request(f"{base_url}/submit", bad, max_retries=0)
    assert "422" in str(err.value)


def test_submit_verification_rejects_fake_nice_number(server):
    base_url, _ = server
    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "cheater", max_retries=0
    )
    results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
    submission = compile_results(data, results, SearchMode.DETAILED, "cheater")
    bad = submission.to_json()
    # claim an extra fake near-miss and bump the matching bucket so totals agree
    fake_uniques = data.base  # pretend a number is perfectly nice
    bad["nice_numbers"].append(
        {"number": data.range_start, "num_uniques": fake_uniques}
    )
    for d in bad["unique_distribution"]:
        if d["num_uniques"] == fake_uniques:
            d["count"] += 1
        # keep total equal to range_size by decrementing the fullest bucket
    fullest = max(bad["unique_distribution"], key=lambda d: d["count"])
    fullest["count"] -= 1
    with pytest.raises(api_client.ApiError) as err:
        api_client.retry_request(f"{base_url}/submit", bad, max_retries=0)
    assert "422" in str(err.value)


def test_stats_endpoints_and_static_web(server):
    base_url, db_path = server

    bases = _get(f"{base_url}/stats/bases")
    assert {b["base"] for b in bases} == {10, 17}
    assert bases[0]["range_start"] == "47"

    # leaderboard/search_rate serve (possibly empty) lists
    assert isinstance(_get(f"{base_url}/stats/leaderboard"), list)
    assert isinstance(_get(f"{base_url}/stats/search_rate"), list)

    # the analytics dashboard and browser search client are served from web/
    with urllib.request.urlopen(f"{base_url}/", timeout=10) as r:
        assert b"nice numbers" in r.read()
    with urllib.request.urlopen(f"{base_url}/search/", timeout=10) as r:
        assert b"worker-pool.js" in r.read()
    with urllib.request.urlopen(f"{base_url}/search/worker.js", timeout=10) as r:
        body = r.read()
        # the reference's distribution_updates/distribution field-name
        # mismatch (web/search/worker.js:83) must not be replicated
        assert b"distribution" in body and b"distribution_updates" not in body
    # path traversal is rejected
    try:
        urllib.request.urlopen(f"{base_url}/search/../../SURVEY.md", timeout=10)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_unknown_route_and_bad_claim(server):
    base_url, _ = server
    try:
        _get(f"{base_url}/nope")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        body = json.loads(e.read())
        assert "error" in body
    # submit against a bogus claim id -> 400
    payload = {
        "claim_id": 999999,
        "username": "x",
        "client_version": "0",
        "unique_distribution": None,
        "nice_numbers": [],
    }
    with pytest.raises(api_client.ApiError) as err:
        api_client.retry_request(f"{base_url}/submit", payload, max_retries=0)
    assert "400" in str(err.value)


def test_lease_recovery(tmp_path):
    """A claimed field becomes claimable again once the lease expires
    (reference recovery model: no heartbeats, CLAIM_DURATION_HOURS lease)."""
    from datetime import timedelta

    from nice_tpu.core.types import FieldClaimStrategy
    from nice_tpu.server import db as db_mod

    db = Db(str(tmp_path / "lease.db"))
    db.seed_base(10, field_size=100)  # single field
    f1 = db.try_claim_field(
        FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, 1 << 100
    )
    assert f1 is not None
    # immediately: no expired field available
    f2 = db.try_claim_field(
        FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, 1 << 100
    )
    assert f2 is None
    # backdate the claim past the lease window: the field is claimable again
    stale = db_mod.ts(db_mod.now_utc() - timedelta(hours=2))
    with db._lock, db._txn():
        db._conn.execute("UPDATE fields SET last_claim_time = ?", (stale,))
    f3 = db.try_claim_field(
        FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, 1 << 100
    )
    assert f3 is not None and f3.field_id == f1.field_id
    db.close()


def test_lease_recovery_semantics(tmp_path):
    from nice_tpu.core.types import FieldClaimStrategy
    from nice_tpu.server import db as db_mod

    db = Db(str(tmp_path / "lease2.db"))
    db.seed_base(10, field_size=100)
    assert (
        db.try_claim_field(
            FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, 1 << 100
        )
        is not None
    )
    # with maximum_timestamp = now (the API's last-resort fallback), the
    # recently-claimed field is handed out again
    assert (
        db.try_claim_field(
            FieldClaimStrategy.NEXT, db_mod.now_utc(), 0, 1 << 100
        )
        is not None
    )
    db.close()
