"""nicelint + lockdep tests: every rule has a good/bad fixture pair (the
seeded regression must be caught; the disciplined version must pass), the
ratchet baseline has add/burn-down semantics, and runtime lockdep catches
an ABBA ordering deterministically without ever deadlocking."""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from nice_tpu.analysis import core  # noqa: E402
from nice_tpu.utils import knobs, lockdep  # noqa: E402

NICELINT = os.path.join(REPO, "scripts", "nicelint.py")


def project(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content), encoding="utf-8")
    return core.Project(str(tmp_path))


def run_rule(tmp_path, files, rule_id):
    return core.run_rules(project(tmp_path, files), only=[rule_id])


DB_FIXTURE = """
    class Db:
        def _txn(self):
            pass

        def add_row(self, x):
            with self._txn():
                pass

        def bump(self, x):
            self.add_row(x)

        def read_rows(self):
            return []
"""


# ---------------------------------------------------------------------------
# W1: writer-actor discipline


def test_w1_flags_mutating_call_outside_writer(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/db.py": DB_FIXTURE,
        "nice_tpu/server/handlers.py": """
            def handle(db):
                db.add_row(1)
        """,
    }, "W1")
    assert [v.rule for v in vs] == ["W1"]
    assert "add_row" in vs[0].message


def test_w1_transitive_mutator_counts(tmp_path):
    # bump() only calls add_row(); it must still count as mutating.
    vs = run_rule(tmp_path, {
        "nice_tpu/server/db.py": DB_FIXTURE,
        "nice_tpu/server/handlers.py": """
            def handle(db):
                db.bump(1)
        """,
    }, "W1")
    assert len(vs) == 1 and "bump" in vs[0].message


def test_w1_writer_dispatch_and_reads_are_clean(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/db.py": DB_FIXTURE,
        "nice_tpu/server/handlers.py": """
            def init(writer, db):
                writer.call(do_add)
                writer.submit(lambda: db.add_row(2))

            def do_add(db):
                db.add_row(1)
                helper(db)

            def helper(db):
                db.bump(3)

            def reads(db):
                return db.read_rows()
        """,
    }, "W1")
    assert vs == []


def test_w1_inline_allow_sanctions_init_paths(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/db.py": DB_FIXTURE,
        "nice_tpu/server/handlers.py": """
            def boot(db):
                # nicelint: allow W1 (crash recovery runs before the writer)
                db.add_row(1)
        """,
    }, "W1")
    assert vs == []


# ---------------------------------------------------------------------------
# L1: event-loop purity


def test_l1_flags_blocking_call_reachable_from_async_root(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/async_core.py": """
            import time

            async def handle(self):
                self._work()

            def _work(self):
                time.sleep(1)
        """,
    }, "L1")
    assert len(vs) == 1 and "time.sleep" in vs[0].message


def test_l1_run_in_executor_offload_is_sanctioned(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/async_core.py": """
            import time

            async def handle(self, loop):
                await loop.run_in_executor(None, _work)

            def _work():
                time.sleep(1)
        """,
    }, "L1")
    assert vs == []


def test_l1_loop_thread_marker_extends_roots(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/shed.py": """
            import time

            # nicelint: loop-thread
            def multiplier():
                time.sleep(0.1)
        """,
    }, "L1")
    assert len(vs) == 1 and vs[0].path.endswith("shed.py")


# ---------------------------------------------------------------------------
# D1: device-sync fences


def test_d1_flags_unfenced_readback(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/ops/engine.py": """
            import numpy as np

            def readback(dev_array):
                return int(np.asarray(dev_array))
        """,
    }, "D1")
    assert len(vs) == 1 and "np.asarray" in vs[0].message


def test_d1_fence_marker_and_host_literals_are_clean(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/ops/engine.py": """
            import numpy as np

            def readback(dev_array):
                # nicelint: fence (survivor-count readback)
                return int(np.asarray(dev_array))

            def host_side():
                return np.asarray([1, 2, 3])
        """,
    }, "D1")
    assert vs == []


def test_d1_outside_hot_modules_is_out_of_scope(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/obs/stats.py": """
            import numpy as np

            def f(x):
                return np.asarray(x)
        """,
    }, "D1")
    assert vs == []


# ---------------------------------------------------------------------------
# M1: metrics discipline

SERIES_FIXTURE = """
    from nice_tpu.obs import metrics

    REQS = metrics.counter("nice_reqs_total", "requests",
                           labelnames=("code",))
"""


def test_m1_flags_global_decl_outside_series(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/obs/series.py": SERIES_FIXTURE,
        "nice_tpu/server/app.py": """
            from nice_tpu.obs import metrics

            ROGUE = metrics.counter("nice_rogue_total", "rogue")
        """,
    }, "M1")
    assert any(v.detail.startswith("global-decl:nice_rogue_total")
               for v in vs)


def test_m1_flags_undeclared_usage_and_computed_labels(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/obs/series.py": SERIES_FIXTURE,
        "nice_tpu/server/app.py": """
            NAME = "nice_missing_total"
        """,
        "web/dash.js": """
            fetch("/metrics").then(t => t.includes("nice_ghost_total"));
        """,
    }, "M1")
    details = {v.detail for v in vs}
    assert "undeclared:nice_missing_total" in details
    assert "undeclared:nice_ghost_total" in details


def test_m1_computed_labelnames_are_flagged(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/obs/series.py": SERIES_FIXTURE + """
    BAD = metrics.gauge("nice_bad", "bad", labelnames=tuple(REQS))
        """,
    }, "M1")
    assert any(v.detail == "labels:nice_bad" for v in vs)


def test_m1_derived_suffixes_and_prefixes_resolve(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/obs/series.py": SERIES_FIXTURE + """
    WAIT = metrics.histogram("nice_wait_seconds", "wait")
        """,
        "web/dash.js": """
            rows.filter(r => r.startsWith("nice_reqs_"));
            plot("nice_wait_seconds_p99");
        """,
    }, "M1")
    assert vs == []


# ---------------------------------------------------------------------------
# K1: knob discipline (declaration checks run against the real registry)


def test_k1_flags_direct_env_read_in_package(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/app.py": """
            import os

            WRITER = os.environ.get("NICE_TPU_WRITER", "1")
            CORE = os.environ["NICE_TPU_SERVER_CORE"]
        """,
    }, "K1")
    details = {v.detail for v in vs}
    assert "direct-read:NICE_TPU_WRITER" in details
    assert "direct-read:NICE_TPU_SERVER_CORE" in details


def test_k1_flags_undeclared_knob_everywhere(tmp_path):
    vs = run_rule(tmp_path, {
        "scripts/tool.py": """
            KNOB = "NICE_TPU_TOTALLY_BOGUS_KNOB"
        """,
    }, "K1")
    assert [v.detail for v in vs] == \
        ["undeclared:NICE_TPU_TOTALLY_BOGUS_KNOB"]


def test_k1_declared_knobs_and_prefix_families_are_clean(tmp_path):
    vs = run_rule(tmp_path, {
        "scripts/tool.py": """
            A = "NICE_TPU_WRITER"
            B = "NICE_TPU_SLO_CLAIM_P99_THRESHOLD"
        """,
    }, "K1")
    assert vs == []


# ---------------------------------------------------------------------------
# A1: atomic writes


def test_a1_flags_raw_write_open(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/ckpt/writer.py": """
            def save(path, blob):
                with open(path, "w") as f:
                    f.write(blob)
        """,
    }, "A1")
    assert len(vs) == 1 and "fsio" in vs[0].message


def test_a1_reads_fsio_and_allows_are_clean(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/ckpt/writer.py": """
            def load(path):
                with open(path) as f:
                    return f.read()

            def stream(path):
                # nicelint: allow A1 (append-only log sink)
                return open(path, "a")
        """,
        "nice_tpu/utils/fsio.py": """
            def atomic_write_bytes(path, blob):
                with open(path + ".tmp", "wb") as f:
                    f.write(blob)
        """,
    }, "A1")
    assert vs == []


# ---------------------------------------------------------------------------
# X1: static lock order


def test_x1_flags_bare_lock(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/cache.py": """
            import threading

            _lock = threading.Lock()
        """,
    }, "X1")
    assert len(vs) == 1 and vs[0].detail.startswith("bare-lock")


def test_x1_detects_static_abba_cycle(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/cache.py": """
            from nice_tpu.utils import lockdep

            A = lockdep.make_lock("cache.A")
            B = lockdep.make_lock("cache.B")

            def f():
                with A:
                    with B:
                        pass

            def g():
                with B:
                    with A:
                        pass
        """,
    }, "X1")
    assert any(v.detail.startswith("cycle:") for v in vs)
    assert any("cache.A" in v.message and "cache.B" in v.message
               for v in vs)


def test_x1_consistent_order_is_clean(tmp_path):
    vs = run_rule(tmp_path, {
        "nice_tpu/server/cache.py": """
            from nice_tpu.utils import lockdep

            A = lockdep.make_lock("cache.A")
            B = lockdep.make_lock("cache.B")

            def f():
                with A:
                    with B:
                        pass

            def g():
                with A:
                    with B:
                        pass
        """,
    }, "X1")
    assert vs == []


def test_x1_cross_module_attr_resolution(tmp_path):
    # self.db._lock in another module resolves through the attribute table;
    # a consistent db-inside-writer order stays clean.
    vs = run_rule(tmp_path, {
        "nice_tpu/server/db.py": """
            from nice_tpu.utils import lockdep

            class Db:
                def __init__(self):
                    self._lock = lockdep.make_lock("server.db.Db._lock")
        """,
        "nice_tpu/server/writer.py": """
            from nice_tpu.utils import lockdep

            class Writer:
                def __init__(self, db):
                    self._lock = lockdep.make_lock("server.writer._lock")
                    self.db = db

                def flush(self):
                    with self._lock:
                        with self.db._lock:
                            pass
        """,
    }, "X1")
    assert vs == []


# ---------------------------------------------------------------------------
# Ratchet baseline semantics (through the CLI, end to end)

BAD_TREE = {
    "nice_tpu/ckpt/writer.py": """
        def save(path, blob):
            with open(path, "w") as f:
                f.write(blob)
    """,
}


def nicelint(root, *args):
    return subprocess.run(
        [sys.executable, NICELINT, "--root", str(root), *args],
        capture_output=True, text=True, cwd=REPO,
    )


def test_ratchet_new_violation_fails_then_baselines(tmp_path):
    project(tmp_path, BAD_TREE)
    r = nicelint(tmp_path)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "1 new" in r.stdout

    r = nicelint(tmp_path, "--update-baseline")
    assert r.returncode == 0
    baseline = json.loads(
        (tmp_path / "nice_tpu/analysis/baseline.json").read_text()
    )
    assert len(baseline["entries"]) == 1

    r = nicelint(tmp_path)
    assert r.returncode == 0
    assert "0 new, 1 baselined, 0 stale" in r.stdout


def test_ratchet_stale_entry_fails_only_strict(tmp_path):
    project(tmp_path, BAD_TREE)
    assert nicelint(tmp_path, "--update-baseline").returncode == 0
    # Fix the violation: the baseline entry goes stale.
    (tmp_path / "nice_tpu/ckpt/writer.py").write_text(
        "def save(path, blob):\n    return None\n"
    )
    r = nicelint(tmp_path)
    assert r.returncode == 0 and "1 stale" in r.stdout
    r = nicelint(tmp_path, "--strict")
    assert r.returncode == 1 and "stale" in r.stdout


def test_ratchet_json_report(tmp_path):
    project(tmp_path, BAD_TREE)
    out = tmp_path / "report.json"
    r = nicelint(tmp_path, "--json", str(out))
    assert r.returncode == 1
    report = json.loads(out.read_text())
    assert report["new"] and report["new"][0]["rule"] == "A1"
    assert report["baselined"] == 0


def test_repo_tree_is_clean_strict():
    r = nicelint(REPO, "--strict")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in r.stdout


# ---------------------------------------------------------------------------
# Runtime lockdep


@pytest.fixture
def lockdep_on(monkeypatch):
    monkeypatch.setenv("NICE_TPU_LOCKDEP", "1")
    lockdep.reset()
    yield
    lockdep.reset()


def test_lockdep_disabled_returns_plain_locks(monkeypatch):
    monkeypatch.delenv("NICE_TPU_LOCKDEP", raising=False)
    lock = lockdep.make_lock("test.plain")
    assert not hasattr(lock, "name")


def test_lockdep_records_order_edges(lockdep_on):
    a = lockdep.make_lock("test.A")
    b = lockdep.make_lock("test.B")
    with a:
        with b:
            pass
    assert "test.B" in lockdep.order_edges().get("test.A", set())
    assert lockdep.violation_count() == 0


def test_lockdep_catches_abba_without_deadlocking(lockdep_on):
    # Two threads acquire in opposite orders SEQUENTIALLY (the second
    # starts after the first finished) — no wall-clock deadlock is
    # possible, yet the name-level order graph still closes the A->B->A
    # cycle. This is exactly how CI catches ABBA deterministically.
    a = lockdep.make_lock("test.abba.A")
    b = lockdep.make_lock("test.abba.B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()

    cycles = [v for v in lockdep.violations() if v["kind"] == "order-cycle"]
    assert len(cycles) == 1
    assert set(cycles[0]["edge"]) == {"test.abba.A", "test.abba.B"}
    assert cycles[0]["site"]  # acquisition site is attributed
    lockdep.reset()  # clean slate so the conftest guard stays green


def test_lockdep_rlock_reentrancy_is_not_a_cycle(lockdep_on):
    r = lockdep.make_rlock("test.re.R")
    with r:
        with r:
            pass
    assert lockdep.violation_count() == 0


def test_lockdep_long_hold_on_loop_thread(lockdep_on, monkeypatch):
    monkeypatch.setenv("NICE_TPU_LOCKDEP_HOLD_SECS", "0.01")
    lock = lockdep.make_lock("test.hold.L")
    lockdep.mark_loop_thread()
    with lock:
        time.sleep(0.05)
    holds = [v for v in lockdep.violations() if v["kind"] == "long-hold"]
    assert len(holds) == 1 and holds[0]["lock"] == "test.hold.L"
    lockdep.reset()


def test_lockdep_long_hold_ignores_worker_threads(lockdep_on, monkeypatch):
    monkeypatch.setenv("NICE_TPU_LOCKDEP_HOLD_SECS", "0.01")
    lock = lockdep.make_lock("test.hold.W")
    with lock:  # this thread is NOT marked as a loop thread
        time.sleep(0.05)
    assert lockdep.violation_count() == 0


# ---------------------------------------------------------------------------
# Knob registry


def test_knobs_typed_get_and_bool_semantics(monkeypatch):
    monkeypatch.delenv("NICE_TPU_WRITER_MAX_BATCH", raising=False)
    assert knobs.WRITER_MAX_BATCH.get() == knobs.WRITER_MAX_BATCH.default
    monkeypatch.setenv("NICE_TPU_WRITER_MAX_BATCH", "96")
    assert knobs.WRITER_MAX_BATCH.get() == 96
    assert knobs.WRITER_MAX_BATCH.get(default=7) == 96

    monkeypatch.setenv("NICE_TPU_WRITER", "off")
    assert knobs.WRITER.get_bool() is False
    monkeypatch.setenv("NICE_TPU_WRITER", "yes")
    assert knobs.WRITER.get_bool() is True
    # Empty/unrecognized strings fall back to the default, matching the
    # pre-registry call sites ('not in ("0","false","off")' style).
    monkeypatch.setenv("NICE_TPU_WRITER", "")
    assert knobs.WRITER.get_bool() is True


def test_knobs_lookup_and_prefix_family(monkeypatch):
    assert knobs.lookup("NICE_TPU_WRITER") is knobs.WRITER
    assert knobs.is_declared("NICE_TPU_LOCKDEP")
    # nicelint: allow K1 (intentionally-undeclared probe name)
    assert not knobs.is_declared("NICE_TPU_NO_SUCH_KNOB")
    monkeypatch.setenv("NICE_TPU_SLO_CLAIM_P99_THRESHOLD", "0.5")
    got = knobs.SLO_OVERRIDES.get_float(
        "NICE_TPU_SLO_CLAIM_P99_THRESHOLD", 1.0
    )
    assert got == 0.5


def test_knobs_render_markdown_covers_registry():
    md = knobs.render_markdown()
    assert "NICE_TPU_LOCKDEP" in md
    assert "NICE_TPU_WRITER_MAX_BATCH" in md
    # docs/KNOBS.md in the tree matches the registry (K1 drift gate).
    with open(os.path.join(REPO, "docs", "KNOBS.md"), encoding="utf-8") as f:
        assert f.read() == md
