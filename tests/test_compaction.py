"""Differential tests for the compacted-readback pipeline (PR 2).

Three equivalences, each asserted non-vacuously (the accept path must fire):

1. compacted survivor readback == dense per-lane scan, for both the detailed
   threshold (near_miss_cutoff) and the niceonly threshold (base - 1), on
   ranges that actually contain accepts — plus a lowered-threshold rich range
   so compaction is exercised with many survivors, and the overflow path.
2. device-resident histogram accumulation across a multi-batch field == the
   old per-batch host fold, for the jnp graph and the Pallas twin.
3. the sharded accumulate-then-fold step pair == the per-batch psum step on
   the virtual 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.obs.series import ENGINE_SURVIVOR_OVERFLOW
from nice_tpu.ops import engine
from nice_tpu.ops import pallas_engine as pe
from nice_tpu.ops import vector_engine as ve
from nice_tpu.ops.limbs import get_plan, int_to_limbs

MODS = pytest.mark.parametrize("mod", [ve, pe], ids=["jnp", "pallas"])


def _dense_survivors(plan, batch_size, start, valid, thresh):
    """Oracle: full per-lane uniques readback, host-side filter."""
    u = np.asarray(
        ve.uniques_batch(plan, batch_size, int_to_limbs(start, plan.limbs_n))
    )[:valid]
    lanes = np.nonzero(u > thresh)[0]
    return lanes, u[lanes]


@MODS
@pytest.mark.parametrize("thresh_kind", ["near_miss", "nice"])
def test_survivors_match_dense_b10(mod, thresh_kind):
    # b10's [47, 100) holds exactly one accept at either threshold: 69
    # (num_uniques == 10 > cutoff 9 and > base-1 9) — sparse but non-vacuous.
    plan = get_plan(10)
    batch_size, start, valid = 128, 47, 53  # pallas blocks need %128 == 0
    thresh = (
        plan.near_miss_cutoff if thresh_kind == "near_miss" else plan.base - 1
    )
    count, idx, uniq = mod.survivors_batch(
        plan, batch_size, thresh, 16, int_to_limbs(start, plan.limbs_n),
        np.int32(valid),
    )
    count = int(np.asarray(count))
    lanes, dense_u = _dense_survivors(plan, batch_size, start, valid, thresh)
    assert count == len(lanes) > 0  # the accept path fired
    np.testing.assert_array_equal(np.asarray(idx)[:count], lanes)
    np.testing.assert_array_equal(np.asarray(uniq)[:count], dense_u)
    assert start + int(np.asarray(idx)[0]) == 69


@MODS
def test_survivors_match_dense_rich_range(mod):
    # Lowered threshold => many survivors per batch: compaction is exercised
    # with a dense scatter, not just a single hit.
    plan = get_plan(17)
    start = base_range.get_base_range(17)[0]
    batch_size, valid, thresh = 512, 500, plan.base - 6
    lanes, dense_u = _dense_survivors(plan, batch_size, start, valid, thresh)
    assert len(lanes) > 50, "range not accept-rich; test is vacuous"
    count, idx, uniq = mod.survivors_batch(
        plan, batch_size, thresh, batch_size,
        int_to_limbs(start, plan.limbs_n), np.int32(valid),
    )
    count = int(np.asarray(count))
    assert count == len(lanes)
    np.testing.assert_array_equal(np.asarray(idx)[:count], lanes)
    np.testing.assert_array_equal(np.asarray(uniq)[:count], dense_u)


def test_survivors_overflow_keeps_ordered_prefix():
    # Survivors past cap are dropped in-graph; the returned count still
    # reports the true total so callers can detect the overflow.
    plan = get_plan(17)
    start = base_range.get_base_range(17)[0]
    batch_size, valid, thresh, cap = 512, 500, plan.base - 6, 4
    lanes, dense_u = _dense_survivors(plan, batch_size, start, valid, thresh)
    assert len(lanes) > cap
    count, idx, uniq = ve.survivors_batch(
        plan, batch_size, thresh, cap, int_to_limbs(start, plan.limbs_n),
        np.int32(valid),
    )
    assert int(np.asarray(count)) == len(lanes)
    np.testing.assert_array_equal(np.asarray(idx), lanes[:cap])
    np.testing.assert_array_equal(np.asarray(uniq), dense_u[:cap])


def test_rare_scan_overflow_falls_back_dense(monkeypatch):
    # When a sub-batch's survivor count overflows the cap, the engine re-runs
    # that sub-batch dense — results identical, overflow counter ticked.
    plan = get_plan(17)
    start = base_range.get_base_range(17)[0]
    batch_size, valid, thresh = 512, 500, plan.base - 6
    monkeypatch.setattr(engine, "SURVIVOR_CAP", 2)
    before = ENGINE_SURVIVOR_OVERFLOW.value()
    got = list(
        engine._rare_scan_survivors(plan, start, valid, batch_size, "jax",
                                    thresh)
    )
    lanes, dense_u = _dense_survivors(plan, batch_size, start, valid, thresh)
    assert got == [
        (start + int(i), int(u)) for i, u in zip(lanes, dense_u)
    ]
    assert len(got) > 2  # overflowed the patched cap
    assert ENGINE_SURVIVOR_OVERFLOW.value() > before


@MODS
def test_detailed_accum_matches_per_batch_fold(mod):
    # Chain the donated device-resident accumulator across a multi-batch
    # field (ragged tail included) and compare against per-batch
    # detailed_batch readbacks folded on the host — the pre-PR shape.
    plan = get_plan(17)
    start0 = base_range.get_base_range(17)[0]
    batch_size, n_batches, width = 256, 5, plan.base + 2
    acc = jnp.zeros(width, jnp.int32)
    host = np.zeros(width, np.int64)
    nm_accum, nm_ref = [], []
    total_valid = 0
    for k in range(n_batches):
        limbs = int_to_limbs(start0 + k * batch_size, plan.limbs_n)
        valid = np.int32(batch_size - (37 if k == n_batches - 1 else 0))
        total_valid += int(valid)
        acc, nm = mod.detailed_accum_batch(plan, batch_size, acc, limbs, valid)
        nm_accum.append(int(np.asarray(nm)))
        hist, nm2 = ve.detailed_batch(plan, batch_size, limbs, valid)
        host += np.asarray(hist)[:width].astype(np.int64)
        nm_ref.append(int(np.asarray(nm2)))
    assert nm_accum == nm_ref
    got = np.asarray(acc, dtype=np.int64)
    np.testing.assert_array_equal(got, host)
    # Non-vacuous: every valid lane landed in a real bin (1..base).
    assert int(got[1: plan.base + 1].sum()) == total_valid


def test_sharded_accum_fold_matches_psum_step():
    # Tentpole 2 on the mesh: N batches through the accumulate step + ONE
    # fold == N batches through the old per-batch-psum step.
    from nice_tpu.parallel import mesh as pmesh

    n_dev = len(jax.devices())
    assert n_dev >= 8, "conftest must force 8 virtual CPU devices"
    mesh = pmesh.make_mesh()
    plan = get_plan(17)
    start0 = base_range.get_base_range(17)[0]
    per_dev, n_batches, width = 64, 4, plan.base + 2
    lanes = per_dev * n_dev
    end = start0 + n_batches * lanes

    accum = pmesh.make_sharded_stats_accum_step(plan, per_dev, mesh,
                                                kernel="jnp")
    fold = pmesh.make_sharded_stats_fold(mesh)
    ref = pmesh.make_sharded_stats_step(plan, per_dev, mesh, "detailed",
                                        kernel="jnp")

    acc = np.zeros((n_dev, width), dtype=np.int32)
    ref_hist = np.zeros(width, np.int64)
    nm_accum, nm_ref = [], []
    for k in range(n_batches):
        batch_start = start0 + k * lanes
        valid = lanes - (29 if k == n_batches - 1 else 0)  # ragged tail
        starts, valids = engine._shard_inputs(
            plan, end, batch_start, valid, per_dev, n_dev
        )
        acc, nm = accum(acc, starts, valids)
        nm_accum.append(int(np.asarray(nm)))
        hist, nm2 = ref(starts, valids)
        ref_hist += np.asarray(hist)[:width].astype(np.int64)
        nm_ref.append(int(np.asarray(nm2)))
    assert nm_accum == nm_ref
    folded = np.asarray(fold(acc), dtype=np.int64)
    np.testing.assert_array_equal(folded, ref_hist)
    assert int(folded[1: plan.base + 1].sum()) > 0
