"""Megaloop equivalence tests: the device-resident lax.scan batch fusion.

The megaloop (NICE_TPU_MEGALOOP / NICE_TPU_MEGALOOP_SEGMENT) folds segments
of batch iterations into ONE dispatch with an in-program field cursor; its
results must be byte-identical to the per-batch feed loop it replaces —
across modes (detailed / niceonly dense / niceonly fused-filtered), kernels
(jnp + pallas), shard layouts, segment lengths {1, 3, default}, and an
elastic downshift that lands mid-slice.

The conftest forces 8 virtual CPU devices, so unqualified runs exercise the
sharded per-device megaloops (parallel/mesh.py); NICE_TPU_SHARD=0 runs pin
the single-device executables (ops/vector_engine.py / ops/pallas_engine.py
through ops/engine.py's compile cache).
"""

import jax
import pytest

from nice_tpu import faults
from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.obs.series import ENGINE_DISPATCHES
from nice_tpu.ops import engine, scalar
from nice_tpu.parallel import mesh as pmesh

# None = default cadence (MEGALOOP_SEGMENT_DEFAULT); "1" pins the degenerate
# one-iteration scan, which must route through the per-batch executables.
SEGMENTS = ("1", "3", None)


@pytest.fixture(autouse=True)
def _mesh_and_cleanup(monkeypatch):
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual CPU devices"
    for var in ("NICE_TPU_MEGALOOP", "NICE_TPU_MEGALOOP_SEGMENT"):
        monkeypatch.delenv(var, raising=False)
    yield
    faults.reset()
    pmesh.heal_devices()


def _rng(base: int, count: int) -> FieldSize:
    lo, _hi = base_range.get_base_range(base)
    return FieldSize(lo, lo + count)


def _pin_segment(monkeypatch, seg):
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "1")
    if seg is None:
        monkeypatch.delenv("NICE_TPU_MEGALOOP_SEGMENT", raising=False)
    else:
        monkeypatch.setenv("NICE_TPU_MEGALOOP_SEGMENT", seg)


# -- detailed ---------------------------------------------------------------


@pytest.mark.parametrize("seg", SEGMENTS)
def test_sharded_detailed_megaloop_matches_feed_loop(monkeypatch, seg):
    """Sharded detailed at base 40: megaloop == per-batch feed == scalar
    oracle on a ragged field (not a super-batch multiple, so the in-program
    tail masking is exercised on the last segment)."""
    base, rng = 40, _rng(40, 3000)
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    want = engine.process_range_detailed(
        rng, base, backend="jax", batch_size=128
    )
    _pin_segment(monkeypatch, seg)
    got = engine.process_range_detailed(
        rng, base, backend="jax", batch_size=128
    )
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers
    oracle = scalar.process_range_detailed(rng, base)
    assert got.distribution == oracle.distribution
    assert got.nice_numbers == oracle.nice_numbers


@pytest.mark.slow  # XLA compile of the 29-limb plan runs multi-minute on CPU
@pytest.mark.parametrize("seg", ("3", None))
def test_sharded_detailed_megaloop_base510(monkeypatch, seg):
    """Base 510 is the widest sweep plan (29 u32 limbs): the in-program
    cursor advance must carry-propagate across every limb identically to the
    host-side advance of the feed loop."""
    base, rng = 510, _rng(510, 1500)
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    want = engine.process_range_detailed(
        rng, base, backend="jax", batch_size=128
    )
    _pin_segment(monkeypatch, seg)
    got = engine.process_range_detailed(
        rng, base, backend="jax", batch_size=128
    )
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers


@pytest.mark.slow  # interpreter-mode compile of the scanned pallas callable
def test_single_device_detailed_pallas_megaloop(monkeypatch):
    """NICE_TPU_SHARD=0 + backend=pallas: the scanned _stats_callable
    (pallas_engine megaloop) against the per-batch pallas path."""
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    base, rng = 40, _rng(40, 2000)
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    want = engine.process_range_detailed(
        rng, base, backend="pallas", batch_size=256
    )
    _pin_segment(monkeypatch, "3")
    got = engine.process_range_detailed(
        rng, base, backend="pallas", batch_size=256
    )
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers


def test_megaloop_near_misses_extracted(monkeypatch):
    """The rare-path survivor re-scan spans whole segments: base 10's known
    near misses (incl. 69) must come back exactly through the megaloop."""
    _pin_segment(monkeypatch, "3")
    got = engine.process_range_detailed(
        FieldSize(47, 100), 10, backend="jax", batch_size=16
    )
    want = scalar.process_range_detailed(FieldSize(47, 100), 10)
    assert got.nice_numbers == want.nice_numbers
    assert any(n.number == 69 for n in got.nice_numbers)


def test_cursor_advance_b510_carry_propagation():
    """Tier-1 witness for the in-program cursor at the widest plan (the full
    b510 engine runs above are slow-marked: XLA's compile of the 29-limb
    digit kernels is multi-minute on CPU). The scanned advance must match
    host big-int addition across multi-limb carry chains."""
    import jax.numpy as jnp
    import numpy as np

    from nice_tpu.ops import vector_engine as ve
    from nice_tpu.ops.limbs import get_plan, int_to_limbs, limbs_to_int

    plan = get_plan(510)
    lo, _hi = base_range.get_base_range(510)
    # Engineered carry edges: range start, an all-ones low-limb block (the
    # +batch carry ripples through every saturated limb), and a mid chain.
    for start in (lo, lo | ((1 << 96) - 1), lo + (1 << 64) - 1):
        cur = jnp.asarray(
            np.array(int_to_limbs(start, plan.limbs_n), dtype=np.uint32)
        )
        for step in (1, 4096, (1 << 28)):
            adv = ve._advance_cursor(plan, cur, step)
            assert limbs_to_int(list(np.asarray(adv))) == start + step, (
                start, step,
            )


# -- niceonly ---------------------------------------------------------------


@pytest.mark.parametrize("seg", SEGMENTS)
def test_sharded_niceonly_megaloop_matches_feed_loop(monkeypatch, seg):
    base, rng = 40, _rng(40, 30_000)
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    want = engine.process_range_niceonly(
        rng, base, backend="jnp", batch_size=128
    )
    _pin_segment(monkeypatch, seg)
    got = engine.process_range_niceonly(
        rng, base, backend="jnp", batch_size=128
    )
    assert got.nice_numbers == want.nice_numbers
    oracle = scalar.process_range_niceonly(rng, base)
    assert got.nice_numbers == oracle.nice_numbers


def test_sharded_niceonly_megaloop_finds_69(monkeypatch):
    """Positive-signal check: the aggregate per-segment count gates the
    survivor extraction, which must still surface base 10's single nice
    number through a multi-iteration scan."""
    _pin_segment(monkeypatch, "3")
    got = engine.process_range_niceonly(
        FieldSize(47, 100), 10, backend="jnp", batch_size=16
    )
    assert [n.number for n in got.nice_numbers] == [69]


@pytest.mark.slow  # XLA compile of the 29-limb plan runs multi-minute on CPU
@pytest.mark.parametrize("seg", ("3", None))
def test_sharded_niceonly_megaloop_base510(monkeypatch, seg):
    base, rng = 510, _rng(510, 1500)
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    want = engine.process_range_niceonly(
        rng, base, backend="jnp", batch_size=128
    )
    _pin_segment(monkeypatch, seg)
    got = engine.process_range_niceonly(
        rng, base, backend="jnp", batch_size=128
    )
    assert got.nice_numbers == want.nice_numbers


@pytest.mark.parametrize("fused", ("0", "1"))
def test_single_device_niceonly_megaloop_fused_and_dense(monkeypatch, fused):
    """NICE_TPU_SHARD=0 exercises the single-device niceonly megaloops:
    fused=1 scans ve.niceonly_filtered_megaloop (residue filter + pruned
    tally in the carry), fused=0 the dense kernel."""
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    monkeypatch.setenv("NICE_TPU_FUSED_FILTER", fused)
    base, rng = 40, _rng(40, 30_000)
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    want = engine.process_range_niceonly(
        rng, base, backend="jnp", batch_size=256
    )
    _pin_segment(monkeypatch, "3")
    got = engine.process_range_niceonly(
        rng, base, backend="jnp", batch_size=256
    )
    assert got.nice_numbers == want.nice_numbers
    oracle = scalar.process_range_niceonly(rng, base)
    assert got.nice_numbers == oracle.nice_numbers


# -- elastic downshift mid-slice --------------------------------------------


def test_downshift_mid_megaloop_slice(monkeypatch):
    """Kill a mesh device on segment-dispatch 3 with the megaloop ON: the
    downshift reslices the un-dispatched remainder at the SAME segment
    length over the survivors and the result stays byte-identical to the
    fault-free scalar oracle — no whole-field downgrade."""
    _pin_segment(monkeypatch, "2")
    faults.configure("mesh.dispatch:dead@3")
    rng = FieldSize(5541, 30941)  # full base-17 range: 25,400 candidates
    got = engine.process_range_detailed(
        rng, 17, backend="jnp", batch_size=128
    )
    want = scalar.process_range_detailed(rng, 17)
    assert got.distribution == want.distribution
    assert got.nice_numbers == want.nice_numbers
    assert got.backend_downgrades == ()
    stats = engine.LAST_FEED_STATS
    assert stats["reshards"] == 1
    assert stats["n_dev_start"] == 8
    assert stats["n_dev_end"] == 7


def test_downshift_mid_megaloop_niceonly(monkeypatch):
    _pin_segment(monkeypatch, "2")
    faults.configure("mesh.dispatch:dead:0@2")
    rng = FieldSize(5541, 30941)
    got = engine.process_range_niceonly(
        rng, 17, backend="jnp", batch_size=128
    )
    want = scalar.process_range_niceonly(rng, 17, None)
    assert got.nice_numbers == want.nice_numbers
    assert got.backend_downgrades == ()
    assert engine.LAST_FEED_STATS["n_dev_end"] == 7


# -- dispatch collapse ------------------------------------------------------


def test_dispatch_counter_collapses_by_segment_factor(monkeypatch):
    """The point of the megaloop: dispatches-per-slice drop by the segment
    factor (nice_engine_dispatches_total{mode} — the counter bench.py and
    the fleet page read)."""
    base, rng = 40, _rng(40, 8192)
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    d0 = ENGINE_DISPATCHES.value(("detailed",))
    engine.process_range_detailed(rng, base, backend="jax", batch_size=128)
    feed = ENGINE_DISPATCHES.value(("detailed",)) - d0
    _pin_segment(monkeypatch, "4")
    d1 = ENGINE_DISPATCHES.value(("detailed",))
    engine.process_range_detailed(rng, base, backend="jax", batch_size=128)
    mega = ENGINE_DISPATCHES.value(("detailed",)) - d1
    # 8192 lanes over 128*8 per feed dispatch = 8; over 128*4*8 = 2.
    assert feed == 8
    assert mega == 2
