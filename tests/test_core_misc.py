"""Tests for stats, consensus, field/chunk generation, and benchmark configs."""

from datetime import datetime, timedelta, timezone

import pytest

from nice_tpu.core import (
    base_range,
    consensus,
    distribution_stats,
    generate_chunks,
    generate_fields,
    number_stats,
)
from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field
from nice_tpu.core.types import (
    FieldRecord,
    FieldSize,
    NiceNumberSimple,
    SearchMode,
    SubmissionRecord,
    UniquesDistributionSimple,
)


def make_submission(sub_id, distribution, numbers, when=None):
    dist = (
        None
        if not distribution
        else distribution_stats.expand_distribution(distribution, 10)
    )
    return SubmissionRecord(
        submission_id=sub_id,
        claim_id=sub_id,
        field_id=1,
        search_mode=SearchMode.DETAILED,
        submit_time=when or datetime.now(timezone.utc),
        elapsed_secs=10.0,
        username=f"user{sub_id}",
        user_ip="127.0.0.1",
        client_version="1.0.0",
        disqualified=False,
        distribution=dist,
        numbers=number_stats.expand_numbers(numbers, 10),
    )


def make_field(check_level=1):
    return FieldRecord(
        field_id=1,
        base=10,
        chunk_id=1,
        range_start=100,
        range_end=200,
        range_size=100,
        last_claim_time=None,
        canon_submission_id=None,
        check_level=check_level,
        prioritize=False,
    )


DIST_A = [
    UniquesDistributionSimple(num_uniques=i, count=c)
    for i, c in [(1, 50), (2, 50)]
]
DIST_B = [
    UniquesDistributionSimple(num_uniques=i, count=c)
    for i, c in [(1, 60), (2, 40)]
]
NUMS_A = [NiceNumberSimple(number=69, num_uniques=10)]


def test_consensus_no_submissions():
    canon, cl = consensus.evaluate_consensus(make_field(check_level=5), [])
    assert canon is None
    assert cl == 1


def test_consensus_single_submission():
    sub = make_submission(1, DIST_A, NUMS_A)
    canon, cl = consensus.evaluate_consensus(make_field(), [sub])
    assert canon is sub
    assert cl == 2


def test_consensus_majority_and_earliest_wins():
    t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
    subs = [
        make_submission(1, DIST_A, NUMS_A, t0 + timedelta(hours=2)),
        make_submission(2, DIST_A, NUMS_A, t0),
        make_submission(3, DIST_B, NUMS_A, t0 + timedelta(hours=1)),
    ]
    canon, cl = consensus.evaluate_consensus(make_field(), subs)
    assert canon is not None and canon.submission_id == 2  # earliest in majority
    assert cl == 3  # group size 2 + 1


def test_consensus_check_level_cap():
    t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
    subs = [
        make_submission(i, DIST_A, NUMS_A, t0 + timedelta(seconds=i))
        for i in range(300)
    ]
    _, cl = consensus.evaluate_consensus(make_field(), subs)
    assert cl == 255


def test_consensus_missing_distribution_raises():
    subs = [make_submission(1, [], NUMS_A), make_submission(2, [], NUMS_A)]
    with pytest.raises(ValueError):
        consensus.evaluate_consensus(make_field(), subs)


def test_expand_distribution():
    out = distribution_stats.expand_distribution(DIST_A, 10)
    assert out[0].niceness == pytest.approx(0.1)
    assert out[0].density == pytest.approx(0.5)
    total = sum(d.count for d in out)
    assert total == 100


def test_mean_stdev():
    dist = distribution_stats.expand_distribution(DIST_A, 10)
    mean, stdev = distribution_stats.mean_stdev_from_distribution(dist)
    assert mean == pytest.approx(0.15, abs=1e-6)
    assert stdev == pytest.approx(0.05, abs=1e-6)


def test_downsample_numbers_top_n():
    n_over = number_stats.SAVE_TOP_N_NUMBERS + 100
    many = [NiceNumberSimple(number=i, num_uniques=3) for i in range(1, n_over + 1)]
    best = NiceNumberSimple(number=n_over + 1, num_uniques=9)
    sub = make_submission(1, DIST_A, many + [best])
    out = number_stats.downsample_numbers([sub])
    assert len(out) == number_stats.SAVE_TOP_N_NUMBERS
    assert out[0].number == best.number


def test_downsample_distributions():
    subs = [make_submission(1, DIST_A, []), make_submission(2, DIST_B, [])]
    out = distribution_stats.downsample_distributions(subs, 10)
    assert len(out) == 10
    by_uniques = {d.num_uniques: d.count for d in out}
    assert by_uniques[1] == 110
    assert by_uniques[2] == 90


def test_break_range_into_fields():
    fields = generate_fields.break_range_into_fields(0, 100, 30)
    assert [(f.range_start, f.range_end) for f in fields] == [
        (0, 30), (30, 60), (60, 90), (90, 100),
    ]
    one = generate_fields.break_range_into_fields(5, 10, 100)
    assert [(f.range_start, f.range_end) for f in one] == [(5, 10)]


def test_group_fields_into_chunks():
    fields = generate_fields.break_range_into_fields(0, 1000, 1)
    chunks = generate_chunks.group_fields_into_chunks(list(fields))
    assert len(chunks) == 100
    assert chunks[0].range_start == 0
    assert chunks[-1].range_end == 1000
    # Contiguous cover
    for a, b in zip(chunks, chunks[1:]):
        assert a.range_end == b.range_start
    few = generate_fields.break_range_into_fields(0, 10, 1)
    assert len(generate_chunks.group_fields_into_chunks(list(few))) == 10


def test_benchmark_fields():
    f = get_benchmark_field(BenchmarkMode.BASE_TEN)
    assert (f.base, f.range_start, f.range_end) == (10, 47, 100)
    f = get_benchmark_field(BenchmarkMode.DEFAULT)
    assert (f.base, f.range_start, f.range_size) == (40, 1_916_284_264_916, 10**6)
    f = get_benchmark_field(BenchmarkMode.EXTRA_LARGE)
    assert (f.base, f.range_size) == (40, 10**9)
    f = get_benchmark_field(BenchmarkMode.MASSIVE)
    assert (f.base, f.range_size) == (50, 10**13)
    f = get_benchmark_field(BenchmarkMode.HI_BASE)
    assert (f.base, f.range_size) == (80, 10**9)
    f = get_benchmark_field(BenchmarkMode.MSD_EFFECTIVE)
    assert (f.base, f.range_start) == (50, 26_507_984_537_059_635)
    f = get_benchmark_field(BenchmarkMode.MSD_INEFFECTIVE)
    assert (f.base, f.range_start, f.range_size) == (
        50, 94_760_515_586_064_977, 10**7,
    )


def test_field_size_chunks():
    fs = FieldSize(0, 10)
    assert [(c.range_start, c.range_end) for c in fs.chunks(4)] == [
        (0, 4), (4, 8), (8, 10),
    ]
    base = base_range.get_base_range_field(10)
    assert base.size() == 53
