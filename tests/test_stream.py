"""SSE stream hub + responder tests: wire framing, bounded-queue lag
accounting, slow-consumer eviction, the subscriber cap, heartbeat cadence,
and the Last-Event-ID resume contract (replay-then-live with no duplicate
and no missing journal ids, even when publishes race the replay)."""

import asyncio
import contextlib
import json

from nice_tpu.obs import stream


# -- framing ----------------------------------------------------------------


def test_sse_frame_carries_journal_id_and_event_name():
    frame = stream.sse_frame(
        stream.StreamEvent("journal", {"kind": "claimed"}, event_id=42)
    ).decode()
    assert frame == 'id: 42\nevent: journal\ndata: {"kind":"claimed"}\n\n'
    # Non-journal events carry no id: they are not resume cursors.
    hello = stream.sse_frame(
        stream.StreamEvent("hello", {"cursor": 0})
    ).decode()
    assert hello.startswith("event: hello\n")
    assert "id:" not in hello


# -- hub: bounded queues, drops, eviction, cap ------------------------------


def test_publish_never_grows_a_full_queue(monkeypatch):
    monkeypatch.setenv("NICE_TPU_STREAM_QUEUE", "4")
    monkeypatch.setenv("NICE_TPU_STREAM_MAX_DROPS", "100")
    hub = stream.StreamHub()
    sub = hub.subscribe()
    for i in range(10):
        hub.publish("journal", {"i": i}, event_id=i + 1)
    assert len(sub.queue) == 4
    assert sub.dropped == 6
    assert not sub.evicted
    # The oldest events dropped first: the survivors are the newest four.
    assert [e.event_id for e in sub.pop_all()] == [7, 8, 9, 10]


def test_slow_consumer_evicted_past_max_drops(monkeypatch):
    monkeypatch.setenv("NICE_TPU_STREAM_QUEUE", "2")
    monkeypatch.setenv("NICE_TPU_STREAM_MAX_DROPS", "3")
    hub = stream.StreamHub()
    sub = hub.subscribe()
    for i in range(5):  # 2 buffered + 3 drops -> eviction threshold
        hub.publish("anomaly", {"i": i})
    assert sub.dropped == 3
    assert sub.evicted
    # Evicted subscribers stop accumulating entirely.
    hub.publish("anomaly", {"i": 99})
    assert sub.dropped == 3


def test_subscriber_cap(monkeypatch):
    monkeypatch.setenv("NICE_TPU_STREAM_MAX_SUBSCRIBERS", "2")
    hub = stream.StreamHub()
    a, b = hub.subscribe(), hub.subscribe()
    assert a is not None and b is not None
    assert hub.subscribe() is None
    hub.unsubscribe(a)
    assert hub.subscribe() is not None
    assert hub.subscriber_count() == 2


def test_publish_suppresses_ids_covered_by_replay_cursor():
    hub = stream.StreamHub()
    sub = hub.subscribe()
    sub.last_sent_id = 10
    hub.publish("journal", {"k": "old"}, event_id=5)
    hub.publish("journal", {"k": "new"}, event_id=11)
    hub.publish("slo", {"k": "non-journal"})  # no id -> always delivered
    assert [e.event_id for e in sub.pop_all()] == [11, None]


# -- responder: replay, hello, live, heartbeat, lag -------------------------


class _FakeWriter:
    """Collects the responder's frames; drain() yields to the loop."""

    def __init__(self):
        self.buf = b""

    def write(self, data: bytes):
        self.buf += data

    async def drain(self):
        await asyncio.sleep(0)

    def frames(self):
        """Parse the SSE byte stream into (id, event, data) tuples;
        comment frames count separately as heartbeats."""
        out, heartbeats = [], 0
        for block in self.buf.decode().split("\n\n"):
            if not block:
                continue
            if block.startswith(":"):
                heartbeats += 1
                continue
            fid, event, data = None, "message", []
            for line in block.splitlines():
                if line.startswith("id:"):
                    fid = int(line[3:].strip())
                elif line.startswith("event:"):
                    event = line[6:].strip()
                elif line.startswith("data:"):
                    data.append(line[5:].strip())
            out.append((fid, event, "\n".join(data)))
        return out, heartbeats


async def _run_responder(hub, replay, since, scenario, heartbeat=None,
                         monkeypatch=None):
    if heartbeat is not None:
        monkeypatch.setenv("NICE_TPU_STREAM_HEARTBEAT_SECS", str(heartbeat))
    writer = _FakeWriter()
    respond = stream.make_sse_responder(hub, replay, since)
    task = asyncio.ensure_future(respond(writer))
    try:
        await scenario(writer, task)
    finally:
        task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await task
    return writer


def _journal_rows(lo, hi):
    return [{"id": i, "kind": "claimed", "field_id": i} for i in
            range(lo, hi + 1)]


def test_resume_replays_then_goes_live_no_dup_no_miss(monkeypatch):
    """since=2 over a 5-row journal: rows 3..5 replay from the table, the
    hello carries the advanced cursor, then live publishes 6..7 arrive —
    and a racing re-publish of replayed ids is suppressed twice over."""
    hub = stream.StreamHub()
    table = _journal_rows(1, 5)

    def replay(since, limit):
        return [r for r in table if r["id"] > since][:limit]

    async def scenario(writer, task):
        await asyncio.sleep(0.05)  # replay + hello
        # Race: the publisher re-announces replayed ids and new ones.
        for row in _journal_rows(4, 7):
            hub.publish("journal", row, event_id=row["id"])
        await asyncio.sleep(0.05)  # drain

    writer = asyncio.run(
        _run_responder(hub, replay, 2, scenario, heartbeat=30,
                       monkeypatch=monkeypatch)
    )
    frames, _ = writer.frames()
    journal_ids = [f[0] for f in frames if f[1] == "journal"]
    assert journal_ids == [3, 4, 5, 6, 7]  # no dup, no miss, in order
    hellos = [f for f in frames if f[1] == "hello"]
    assert len(hellos) == 1
    assert json.loads(hellos[0][2])["cursor"] == 5
    # Clean teardown unsubscribed the consumer.
    assert hub.subscriber_count() == 0


def test_heartbeats_bound_silence(monkeypatch):
    hub = stream.StreamHub()

    async def scenario(writer, task):
        await asyncio.sleep(0.5)

    writer = asyncio.run(
        _run_responder(hub, None, 0, scenario, heartbeat=0.12,
                       monkeypatch=monkeypatch)
    )
    frames, heartbeats = writer.frames()
    assert [f[1] for f in frames] == ["hello"]
    assert heartbeats >= 2  # ~4 intervals in 0.5 s; timing slack for CI


def test_lagged_event_reports_gap_and_eviction_closes(monkeypatch):
    """Overflow a tiny queue while the consumer sleeps: on drain it must
    learn about the gap (lagged event with the drop count) and, once past
    the eviction threshold, the responder must close the connection."""
    monkeypatch.setenv("NICE_TPU_STREAM_QUEUE", "2")
    monkeypatch.setenv("NICE_TPU_STREAM_MAX_DROPS", "3")
    hub = stream.StreamHub()

    async def scenario(writer, task):
        await asyncio.sleep(0.05)  # hello
        for row in _journal_rows(1, 5):  # 2 buffered + 3 dropped -> evict
            hub.publish("journal", row, event_id=row["id"])
        await asyncio.wait_for(task, timeout=2)  # eviction ends the stream

    writer = asyncio.run(
        _run_responder(hub, None, 0, scenario, heartbeat=30,
                       monkeypatch=monkeypatch)
    )
    frames, _ = writer.frames()
    lagged = [f for f in frames if f[1] == "lagged"]
    assert len(lagged) == 1
    info = json.loads(lagged[0][2])
    assert info["dropped"] == 3
    assert info["evicted"] is True
    # The survivors (newest two) were still delivered before the close,
    # and the lagged cursor tells the consumer where to resume from.
    journal_ids = [f[0] for f in frames if f[1] == "journal"]
    assert journal_ids == [4, 5]
    assert info["cursor"] == 5
    assert hub.subscriber_count() == 0


def test_hub_memory_bounded_under_subscriber_churn(monkeypatch):
    """100 subscribe/overflow/evict/unsubscribe cycles leave the hub with
    an empty subscriber table and no retained Subscriber objects — the SSE
    hub must be memory-bounded under connection churn (dashboards reconnect
    forever; the server process does not restart)."""
    import gc

    def live_subscribers():
        gc.collect()
        return sum(
            1 for o in gc.get_objects()
            if isinstance(o, stream.Subscriber)
        )

    monkeypatch.setenv("NICE_TPU_STREAM_QUEUE", "4")
    monkeypatch.setenv("NICE_TPU_STREAM_MAX_DROPS", "2")
    hub = stream.StreamHub()
    baseline = live_subscribers()
    for cycle in range(100):
        polite = hub.subscribe()
        rude = hub.subscribe()
        # 4 buffered + 8 dropped on each queue: both subscribers blow past
        # the drop cap and get marked evicted mid-cycle.
        for i in range(12):
            hub.publish(
                "journal", {"cycle": cycle, "i": i},
                event_id=cycle * 12 + i + 1,
            )
        assert rude.evicted
        hub.unsubscribe(polite)
        hub.unsubscribe(rude)
        assert hub.subscriber_count() == 0
    assert hub._subs == []
    del polite, rude
    alive = live_subscribers()
    assert alive <= baseline, (
        f"{alive - baseline} churned subscribers still referenced "
        f"after 100 cycles"
    )
