"""Checkpoint/resume subsystem tests.

Covers the on-disk snapshot format (CRC/version/atomicity), the per-field
manager (plan-signature validation, startup resume scan), engine kill-resume
equivalence (a scan resumed from a mid-field snapshot must produce a
byte-identical submission to an uninterrupted one), and the server-side claim
lifecycle additions (/renew_claim, lease release on queue close, configurable
expiry window).
"""

import json
import os

import numpy as np
import pytest

from nice_tpu import ckpt
from nice_tpu.ckpt import snapshot as snap
from nice_tpu.client.main import compile_results
from nice_tpu.core.types import DataToClient, FieldSize, SearchMode
from nice_tpu.obs.series import (
    CKPT_BATCHES_SKIPPED,
    CKPT_REJECTED,
    CKPT_RESTORES,
    CKPT_WRITES,
    SERVER_FIELDS_RELEASED,
)
from nice_tpu.ops import engine, scalar
from nice_tpu.server.db import Db
from nice_tpu.server.field_queue import FieldQueue

BASE = 17
RANGE = FieldSize(5541, 30941)  # full base-17 valid range: 25,400 candidates


def _field(claim_id=1):
    return DataToClient(
        claim_id=claim_id,
        base=BASE,
        range_start=RANGE.start(),
        range_end=RANGE.end(),
        range_size=RANGE.size(),
    )


# -- snapshot format ---------------------------------------------------------


def test_snapshot_roundtrip(tmp_path):
    path = str(tmp_path / "s.ckpt")
    manifest = {"cursor": "123", "nested": {"a": [1, 2]}}
    arrays = {"hist": np.arange(19, dtype=np.int64)}
    nbytes = snap.write_snapshot(path, manifest, arrays)
    assert nbytes == os.path.getsize(path)
    got_m, got_a = snap.read_snapshot(path)
    assert got_m["cursor"] == "123"
    assert got_m["nested"] == {"a": [1, 2]}
    assert got_m["format_version"] == snap.FORMAT_VERSION
    assert np.array_equal(got_a["hist"], arrays["hist"])


def test_snapshot_rejects_corruption(tmp_path):
    path = str(tmp_path / "s.ckpt")
    snap.write_snapshot(path, {"cursor": "1"}, {})
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(snap.SnapshotError) as ei:
        snap.read_snapshot(path)
    assert ei.value.reason == "corrupt"
    # Truncation (a crash mid-write would be caught by the rename, but a
    # truncated copy must still fail closed).
    snap.write_snapshot(path, {"cursor": "1"}, {})
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) - 7])
    with pytest.raises(snap.SnapshotError):
        snap.read_snapshot(path)
    # Garbage file.
    open(path, "wb").write(b"not a snapshot at all")
    with pytest.raises(snap.SnapshotError):
        snap.read_snapshot(path)


def test_snapshot_rejects_unknown_version(tmp_path):
    path = str(tmp_path / "s.ckpt")
    snap.write_snapshot(path, {"cursor": "1"}, {})
    blob = bytearray(open(path, "rb").read())
    # Patch the header version and re-stamp the CRC so ONLY the version is
    # wrong (a bad CRC would mask the version check).
    import struct
    import zlib

    off = len(snap.MAGIC)
    blob[off:off + 4] = struct.pack("<I", snap.FORMAT_VERSION + 1)
    body = bytes(blob[off:-4])
    blob[-4:] = struct.pack("<I", zlib.crc32(body))
    open(path, "wb").write(bytes(blob))
    with pytest.raises(snap.SnapshotError) as ei:
        snap.read_snapshot(path)
    assert ei.value.reason == "version"


# -- manager -----------------------------------------------------------------


def _state(cursor=11685):
    return {
        "cursor": cursor,
        "hist": np.arange(BASE + 2, dtype=np.int64),
        "nice_numbers": [(6864, 12), (6865, 13)],
    }


def test_manager_save_load_roundtrip(tmp_path):
    writes0 = CKPT_WRITES.value()
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), _field(), SearchMode.DETAILED, "jnp", 1024
    )
    ck.save(_state())
    assert CKPT_WRITES.value() == writes0 + 1
    got = ck.load()
    assert got["cursor"] == 11685
    assert got["nice_numbers"] == [(6864, 12), (6865, 13)]
    assert np.array_equal(got["hist"], np.arange(BASE + 2, dtype=np.int64))
    ck.delete()
    assert ck.load() is None
    ck.delete()  # idempotent


def test_manager_rejects_signature_mismatch(tmp_path):
    rejected0 = CKPT_REJECTED.value(("signature",))
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), _field(), SearchMode.DETAILED, "jnp", 1024
    )
    ck.save(_state())
    # Same field, different batch size: the cursor means something else now.
    other = ckpt.FieldCheckpointer(
        str(tmp_path), _field(), SearchMode.DETAILED, "jnp", 2048
    )
    assert other.load() is None
    assert CKPT_REJECTED.value(("signature",)) == rejected0 + 1
    assert not os.path.exists(ck.path)  # rejected snapshots are removed


def test_manager_rejects_corrupt_snapshot(tmp_path):
    rejected0 = CKPT_REJECTED.value(("corrupt",))
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), _field(), SearchMode.DETAILED, "jnp", 1024
    )
    ck.save(_state())
    blob = bytearray(open(ck.path, "rb").read())
    blob[-10] ^= 0xFF
    open(ck.path, "wb").write(bytes(blob))
    assert ck.load() is None
    assert CKPT_REJECTED.value(("corrupt",)) == rejected0 + 1
    assert not os.path.exists(ck.path)
    # A clean restart after rejection checkpoints normally again.
    ck.save(_state())
    assert ck.load() is not None


def test_find_resumable(tmp_path):
    assert (
        ckpt.find_resumable(str(tmp_path), SearchMode.DETAILED, "jnp", 1024)
        is None
    )
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), _field(claim_id=42), SearchMode.DETAILED, "jnp", 1024
    )
    ck.save(_state())
    found = ckpt.find_resumable(str(tmp_path), SearchMode.DETAILED, "jnp", 1024)
    assert found is not None
    data, state, ckptr = found
    assert data.claim_id == 42
    assert state["cursor"] == 11685
    assert ckptr.path == ck.path
    # A different configuration must NOT resume it (and must leave the file
    # for the configuration that can).
    assert (
        ckpt.find_resumable(str(tmp_path), SearchMode.NICEONLY, "jnp", 1024)
        is None
    )
    assert (
        ckpt.find_resumable(str(tmp_path), SearchMode.DETAILED, "jnp", 512)
        is None
    )
    assert os.path.exists(ck.path)


# -- engine kill-resume equivalence -----------------------------------------


def test_detailed_kill_resume_byte_identical(tmp_path, monkeypatch):
    """The acceptance scenario: run a detailed scan checkpointing to disk,
    'kill' it by discarding the in-memory run at a mid-field snapshot, restart
    from the snapshot on disk, and require the submission payload to be
    byte-identical to an uninterrupted run's."""
    data = _field()
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), data, SearchMode.DETAILED, "jnp", 256
    )
    states = []

    def save_and_capture(state):
        ck.save(state)
        states.append(state)

    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")  # per-batch ckpt cadence;
    # the megaloop cadence is covered by the mid-megaloop test below.
    uninterrupted = engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=256,
        checkpoint_cb=save_and_capture, checkpoint_batches=2,
        checkpoint_secs=0,
    )
    assert len(states) >= 2, "range too small to exercise checkpointing"
    # The snapshot on disk is the LAST one; rewrite a mid-field one to model
    # a crash partway through.
    mid = states[len(states) // 2]
    ck.save(mid)

    restores0 = CKPT_RESTORES.value()
    skipped0 = CKPT_BATCHES_SKIPPED.value()
    resume = ck.load()
    assert resume is not None and resume["cursor"] == mid["cursor"]
    resumed = engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=256, resume=resume,
    )
    assert CKPT_RESTORES.value() == restores0 + 1
    assert CKPT_BATCHES_SKIPPED.value() > skipped0

    a = compile_results(data, uninterrupted, SearchMode.DETAILED, "t")
    b = compile_results(data, resumed, SearchMode.DETAILED, "t")
    assert json.dumps(a.to_json(), sort_keys=True) == json.dumps(
        b.to_json(), sort_keys=True
    )
    # And both match the scalar oracle.
    ref = scalar.process_range_detailed(RANGE, BASE)
    assert resumed.distribution == ref.distribution
    assert resumed.nice_numbers == ref.nice_numbers


def test_niceonly_dense_resume_equivalence(monkeypatch):
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")  # per-batch ckpt cadence
    states = []
    full = engine.process_range_niceonly(
        RANGE, BASE, backend="jnp", batch_size=256,
        checkpoint_cb=states.append, checkpoint_batches=2, checkpoint_secs=0,
    )
    assert states, "no checkpoints fired"
    mid = states[len(states) // 2]
    resumed = engine.process_range_niceonly(
        RANGE, BASE, backend="jnp", batch_size=256, resume=mid,
    )
    assert resumed.nice_numbers == full.nice_numbers
    ref = scalar.process_range_niceonly(RANGE, BASE, None)
    assert resumed.nice_numbers == ref.nice_numbers


def test_detailed_mid_megaloop_kill_resume_byte_identical(tmp_path, monkeypatch):
    """Kill-resume with the megaloop ON: checkpoints fire between segment
    dispatches (the readback cadence is batch_size * NICE_TPU_MEGALOOP_SEGMENT
    lanes per device), and a run restarted from a between-segments snapshot
    must submit byte-identically to an uninterrupted one."""
    monkeypatch.setenv("NICE_TPU_MEGALOOP_SEGMENT", "2")
    data = _field()
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), data, SearchMode.DETAILED, "jnp", 128
    )
    states = []

    def save_and_capture(state):
        ck.save(state)
        states.append(state)

    uninterrupted = engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=128,
        checkpoint_cb=save_and_capture, checkpoint_batches=1,
        checkpoint_secs=0,
    )
    assert len(states) >= 2, "range too small to checkpoint between segments"
    mid = states[len(states) // 2]
    ck.save(mid)
    resume = ck.load()
    assert resume is not None
    # Resume at a DIFFERENT segment length: the snapshot's remaining set is
    # segment-granular but position-absolute, so cadence is not part of the
    # signature and the resumed scan re-slices it.
    monkeypatch.setenv("NICE_TPU_MEGALOOP_SEGMENT", "3")
    resumed = engine.process_range_detailed(
        RANGE, BASE, backend="jnp", batch_size=128, resume=resume,
    )
    a = compile_results(data, uninterrupted, SearchMode.DETAILED, "t")
    b = compile_results(data, resumed, SearchMode.DETAILED, "t")
    assert json.dumps(a.to_json(), sort_keys=True) == json.dumps(
        b.to_json(), sort_keys=True
    )
    ref = scalar.process_range_detailed(RANGE, BASE)
    assert resumed.distribution == ref.distribution
    assert resumed.nice_numbers == ref.nice_numbers


def test_manager_rejects_state_version_drift(tmp_path):
    """A snapshot whose signature differs ONLY in the state-contract version
    (e.g. a pre-megaloop v2 snapshot under the v3 engine) is rejected with
    the dedicated 'state_version' reason — a fleet upgrade's restart cost is
    visible as such, not lumped under generic signature drift."""
    from nice_tpu.ckpt.snapshot import write_snapshot

    rejected0 = CKPT_REJECTED.value(("state_version",))
    sig_rejected0 = CKPT_REJECTED.value(("signature",))
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), _field(), SearchMode.DETAILED, "jnp", 1024
    )
    assert ck.signature["state"] == 3
    manifest, arrays = ckpt.manager._state_to_snapshot(_state())
    manifest["signature"] = {**ck.signature, "state": 2}
    manifest["field"] = ck.data.to_json()
    write_snapshot(ck.path, manifest, arrays)
    assert ck.load() is None
    assert CKPT_REJECTED.value(("state_version",)) == rejected0 + 1
    # Not double-counted under the generic reason, and the file is removed.
    assert CKPT_REJECTED.value(("signature",)) == sig_rejected0
    assert not os.path.exists(ck.path)


def test_scalar_chunked_resume_equivalence():
    ref = scalar.process_range_detailed(RANGE, BASE)
    states = []
    full = engine.process_range_detailed(
        RANGE, BASE, backend="scalar", batch_size=1024,
        checkpoint_cb=states.append, checkpoint_batches=3, checkpoint_secs=0,
    )
    assert full.distribution == ref.distribution
    assert full.nice_numbers == ref.nice_numbers
    for state in states:
        resumed = engine.process_range_detailed(
            RANGE, BASE, backend="scalar", batch_size=1024, resume=state,
        )
        assert resumed.distribution == ref.distribution
        assert resumed.nice_numbers == ref.nice_numbers


def test_resume_past_end_returns_complete_state():
    ref = scalar.process_range_niceonly(RANGE, BASE, None)
    done = {
        "cursor": RANGE.end(),
        "hist": None,
        "nice_numbers": [(n.number, n.num_uniques) for n in ref.nice_numbers],
    }
    resumed = engine.process_range_niceonly(
        RANGE, BASE, backend="jnp", batch_size=256, resume=done,
    )
    assert resumed.nice_numbers == ref.nice_numbers


def test_native_backend_rejects_resume():
    with pytest.raises(ValueError, match="native"):
        engine.process_range_detailed(
            RANGE, BASE, backend="native", resume=_state(),
        )
    with pytest.raises(ValueError, match="native"):
        engine.process_range_niceonly(
            RANGE, BASE, backend="native", resume=_state(),
        )


# -- server: renewal, lease release, expiry window ---------------------------


def test_renew_claim_bumps_lease_not_claim_time(tmp_path):
    from nice_tpu.core.types import FieldClaimStrategy

    db = Db(str(tmp_path / "t.db"))
    try:
        db.seed_base(10, field_size=20)
        # Claim through the same path the API uses.
        field = db.try_claim_field(
            FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, (1 << 128) - 1
        )
        assert field is not None
        claim = db.insert_claim(field.field_id, SearchMode.NICEONLY, "127.0.0.1")
        before = db.get_field_by_id(field.field_id).last_claim_time
        renewed_at = db.renew_claim(claim.claim_id)
        after = db.get_field_by_id(field.field_id).last_claim_time
        assert after >= before
        assert after == renewed_at
        # claims.claim_time is untouched (submission elapsed accounting).
        assert db.get_claim_by_id(claim.claim_id).claim_time == claim.claim_time
        with pytest.raises(KeyError):
            db.renew_claim(999999)
    finally:
        db.close()


def test_field_queue_close_releases_leases(tmp_path):
    db = Db(str(tmp_path / "t.db"))
    try:
        db.seed_base(10, field_size=20)  # 3 fields
        q = FieldQueue(db, start_thread=False)
        q.refill_niceonly()
        assert q.niceonly_queue_size() == 3
        leased = [
            f for f in db.get_fields_in_base(10)
            if f.last_claim_time is not None
        ]
        assert len(leased) == 3
        released0 = SERVER_FIELDS_RELEASED.value()
        q.close()
        assert q.niceonly_queue_size() == 0
        assert SERVER_FIELDS_RELEASED.value() == released0 + 3
        leased = [
            f for f in db.get_fields_in_base(10)
            if f.last_claim_time is not None
        ]
        assert leased == []  # immediately re-claimable
    finally:
        db.close()


def test_claim_expiry_env_override(tmp_path, monkeypatch):
    from nice_tpu.obs.series import SERVER_CLAIM_EXPIRY
    from nice_tpu.server.db import now_utc

    db = Db(str(tmp_path / "t.db"))
    try:
        monkeypatch.delenv("NICE_TPU_CLAIM_EXPIRY_SECS", raising=False)
        default_cutoff = db.claim_expiry_cutoff()
        assert SERVER_CLAIM_EXPIRY.value() == 3600.0
        monkeypatch.setenv("NICE_TPU_CLAIM_EXPIRY_SECS", "120")
        cutoff = db.claim_expiry_cutoff()
        assert SERVER_CLAIM_EXPIRY.value() == 120.0
        delta = (now_utc() - cutoff).total_seconds()
        assert 119 < delta < 125
        assert cutoff > default_cutoff
    finally:
        db.close()


# -- client resume integration ----------------------------------------------


def test_client_resume_single_iteration(tmp_path):
    """A restarted client finds the snapshot, resumes the SAME claim without
    re-claiming, and deletes the snapshot only after the submit succeeds."""
    from types import SimpleNamespace

    from nice_tpu.client import main as client_main

    data = _field(claim_id=42)
    ck = ckpt.FieldCheckpointer(
        str(tmp_path), data, SearchMode.DETAILED, "scalar", 4096
    )
    # Build a genuine mid-scan state with the scalar oracle so the resumed
    # half plus the prefix must reproduce the full-field results.
    cut = RANGE.start() + 9000
    prefix = scalar.process_range_detailed(FieldSize(RANGE.start(), cut), BASE)
    hist = np.zeros(BASE + 2, dtype=np.int64)
    for d in prefix.distribution:
        hist[d.num_uniques] += d.count
    ck.save({
        "cursor": cut,
        "hist": hist,
        "nice_numbers": [
            (n.number, n.num_uniques) for n in prefix.nice_numbers
        ],
    })

    submitted = []

    class FakeFuture:
        def __init__(self, value=None):
            self.value = value

        def result(self):
            return self.value

    class FakeApi:
        def claim_async(self, mode):
            raise AssertionError("client re-claimed despite a resumable snapshot")

        def submit_async(self, submission):
            submitted.append(submission)
            return FakeFuture()

    args = SimpleNamespace(
        checkpoint_dir=str(tmp_path), backend="scalar", batch_size=4096,
        progress_secs=0.0, checkpoint_secs=0.0, renew_secs=0.0, username="t",
        api_base="http://unused",
    )
    restores0 = CKPT_RESTORES.value()
    client_main.run_single_iteration(args, FakeApi(), SearchMode.DETAILED)
    assert CKPT_RESTORES.value() == restores0 + 1
    assert len(submitted) == 1
    ref = scalar.process_range_detailed(RANGE, BASE)
    expect = compile_results(data, ref, SearchMode.DETAILED, "t")
    got = submitted[0].to_json()
    # The client piggybacks a fleet-telemetry snapshot on every submission;
    # it carries wall-clock fields, so compare it structurally and the rest
    # of the payload exactly.
    tele = got.pop("telemetry", None)
    assert tele is not None and tele["username"] == "t"
    assert json.dumps(got, sort_keys=True) == json.dumps(
        expect.to_json(), sort_keys=True
    )
    assert not os.path.exists(ck.path)  # retired after the confirmed submit
