"""Test configuration: force an 8-device virtual CPU mesh before JAX imports.

Tests never require real TPU hardware; sharding/collective tests run on the
virtual mesh (the analog of the reference's compile-only NVRTC device tests,
client_process_gpu.rs:1421-1451). bench.py, not the test suite, exercises the
real chip.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nice_tpu.utils.platform import force_virtual_cpu  # noqa: E402

# Force CPU: the session env pins JAX_PLATFORMS=axon (the real chip) which the
# test suite must never grab — bench.py owns the chip. The axon PJRT plugin
# overrides the JAX_PLATFORMS env var at import time, so the env var alone is
# not enough: jax.config.update after import is authoritative.
force_virtual_cpu(os.environ, 8)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Small niceonly fields route to the native host engine by default
# (engine._host_route_niceonly) — which would silently divert every
# backend="pallas" niceonly test off the device pipeline. Default the route
# OFF here so the suite keeps exercising the (scarcer) device path; tests
# that target the host route set this env explicitly.
os.environ.setdefault("NICE_TPU_HOST_NICEONLY_MAX", "0")

# ---------------------------------------------------------------------------
# Runtime lockdep guard: under NICE_TPU_LOCKDEP=1 every test fails if it
# recorded a lock-order cycle; long holds on marked loop threads only fail
# under NICE_TPU_LOCKDEP=strict (wall-time thresholds are load-sensitive).
import pytest  # noqa: E402

from nice_tpu.utils import lockdep  # noqa: E402


@pytest.fixture(autouse=True)
def _lockdep_guard():
    if not lockdep.enabled():
        yield
        return
    before = lockdep.violation_count()
    yield
    new = lockdep.violations()[before:]
    cycles = [v for v in new if v["kind"] == "order-cycle"]
    if cycles:
        pytest.fail(f"lockdep: lock-order cycle(s) during test: {cycles}")
    if lockdep.strict():
        holds = [v for v in new if v["kind"] == "long-hold"]
        if holds:
            pytest.fail(f"lockdep: long hold(s) on a loop thread: {holds}")
