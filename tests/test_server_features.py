"""Server feature tests: cache semantics, disqualification, elapsed_secs,
validate-by-base, background queue refill, cross-process claim safety."""

import json
import sqlite3
import threading
import time
import urllib.error
import urllib.request

import pytest

from nice_tpu.client import api_client
from nice_tpu.client.main import compile_results, process_field
from nice_tpu.core.types import SearchMode
from nice_tpu.server import app as server_app
from nice_tpu.server.db import Db
from nice_tpu.server.field_queue import FieldQueue


@pytest.fixture()
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("NICE_ADMIN_KEY", "sekrit")
    db_path = str(tmp_path / "nice-test.db")
    db = Db(db_path)
    db.seed_base(10, field_size=20)
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    base_url = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base_url, db_path
    srv.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url, body, headers=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _submit_one(base_url, username, mode=SearchMode.DETAILED):
    data = api_client.get_field_from_server(mode, base_url, username, max_retries=0)
    results, _ = process_field(data, mode, "scalar", 1024)
    submission = compile_results(data, results, mode, username)
    api_client.submit_field_to_server(base_url, submission, max_retries=0)
    return data


def test_cache_semantics_per_user_per_mode(server):
    base_url, db_path = server
    d = _submit_one(base_url, "alice", SearchMode.DETAILED)
    _submit_one(base_url, "alice", SearchMode.NICEONLY)
    _submit_one(base_url, "bob", SearchMode.NICEONLY)

    db = Db(db_path)
    db.refresh_search_caches()

    leaders = db.get_leaderboard()
    rows = {(r["search_mode"], r["username"]): r for r in leaders}
    assert ("detailed", "alice") in rows
    assert ("niceonly", "alice") in rows
    assert ("niceonly", "bob") in rows
    # total_range is numbers searched (field range sizes), not submissions
    assert int(rows[("detailed", "alice")]["total_range"]) == d.range_size
    assert rows[("detailed", "alice")]["submissions"] == 1

    # mode filter
    only_detailed = db.get_leaderboard("detailed")
    assert {r["search_mode"] for r in only_detailed} == {"detailed"}

    # daily rate rows carry (date, mode, user) totals
    rate = db.get_search_rate()
    assert any(
        r["search_mode"] == "niceonly"
        and r["username"] == "bob"
        and int(r["total_range"]) > 0
        for r in rate
    )
    db.close()

    # same shapes over HTTP, mode filter honored
    http_leaders = _get(f"{base_url}/stats/leaderboard?mode=niceonly")
    assert {r["search_mode"] for r in http_leaders} == {"niceonly"}
    assert isinstance(_get(f"{base_url}/stats/search_rate"), list)


def test_elapsed_secs_recorded(server):
    base_url, db_path = server
    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "slowpoke", max_retries=0
    )
    results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
    time.sleep(1.1)  # make the claim->submit delta visible at 1s resolution
    submission = compile_results(data, results, SearchMode.DETAILED, "slowpoke")
    api_client.submit_field_to_server(base_url, submission, max_retries=0)

    conn = sqlite3.connect(db_path)
    row = conn.execute(
        "SELECT elapsed_secs FROM submissions WHERE username = 'slowpoke'"
    ).fetchone()
    conn.close()
    assert row is not None and row[0] >= 1.0


def test_disqualification_path(server):
    base_url, db_path = server
    _submit_one(base_url, "mallory", SearchMode.NICEONLY)
    db = Db(db_path)
    db.refresh_search_caches()
    assert any(r["username"] == "mallory" for r in db.get_leaderboard())
    db.close()

    # wrong/missing key -> 403
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(f"{base_url}/admin/disqualify", {"username": "mallory"})
    assert err.value.code == 403

    out = _post(
        f"{base_url}/admin/disqualify",
        {"username": "mallory"},
        headers={"X-Admin-Key": "sekrit"},
    )
    assert out["disqualified"] == 1

    # caches were refreshed by the endpoint: mallory is gone but the audit
    # trail remains
    db = Db(db_path)
    assert not any(r["username"] == "mallory" for r in db.get_leaderboard())
    conn = sqlite3.connect(db_path)
    n = conn.execute(
        "SELECT COUNT(*) FROM submissions WHERE username='mallory'"
        " AND disqualified=1"
    ).fetchone()[0]
    conn.close()
    assert n == 1
    db.close()


def test_validate_honors_base(server, tmp_path):
    base_url, db_path = server
    # double-check one base-10 field so a canonical submission exists
    for _ in range(40):
        try:
            _submit_one(base_url, "v", SearchMode.DETAILED)
        except api_client.ApiError:
            break
    from nice_tpu.jobs import main as jobs_main

    db = Db(db_path)
    jobs_main.run_all(db)
    db.close()

    vdata = api_client.get_validation_data_from_server(base_url, "v", base=10)
    assert vdata.base == 10
    # a base with no canonical field -> 404, not a silently wrong base
    with pytest.raises(api_client.ApiError):
        api_client.get_validation_data_from_server(base_url, "v", base=17, max_retries=0)


class _SlowDb:
    """Db stub recording which thread runs bulk claims."""

    def __init__(self):
        self.bulk_threads = []

    def bulk_claim_fields(self, *a):
        self.bulk_threads.append(threading.current_thread().name)
        time.sleep(0.05)
        return []

    def bulk_claim_thin_fields(self, *a):
        self.bulk_threads.append(threading.current_thread().name)
        time.sleep(0.05)
        return []

    def claim_expiry_cutoff(self):
        return None


def test_reads_not_blocked_by_write_lock(tmp_path):
    """Analytics reads use the per-thread WAL read pool: a held write lock
    (mid-claim) must not stall them (the SQLite analog of the reference's
    r2d2 pool, db_util/mod.rs:39-61)."""
    db = Db(str(tmp_path / "pool.db"))
    db.seed_base(10, field_size=20)
    result = {}

    def reader():
        t0 = time.monotonic()
        result["bases"] = db.get_bases()
        result["secs"] = time.monotonic() - t0

    with db._lock:  # simulate a long write section on the claim path
        db._conn.execute("BEGIN IMMEDIATE")
        try:
            t = threading.Thread(target=reader)
            t.start()
            t.join(timeout=5)
            assert not t.is_alive(), "reader blocked behind the write lock"
        finally:
            db._conn.execute("ROLLBACK")
    assert result["bases"] == [10]
    assert result["secs"] < 1.0, result["secs"]
    db.close()


def test_read_pool_prunes_dead_threads(tmp_path):
    db = Db(str(tmp_path / "prune.db"))
    db.seed_base(10, field_size=20)

    def reader():
        db.get_bases()

    for _ in range(5):
        t = threading.Thread(target=reader)
        t.start()
        t.join()
    db.get_bases()  # current thread's read triggers pruning
    with db._pool_lock:
        live = [e for e in db._pool if e[0] is None or e[0].is_alive()]
        assert len(db._pool) == len(live)
        assert len(db._pool) <= 3  # write conn + this thread + at most 1 racer
    db.close()
    import sqlite3 as sq

    with pytest.raises(sq.ProgrammingError):
        db.get_bases()  # use-after-close raises, never silently reopens


def test_queue_refill_runs_off_the_claim_path():
    db = _SlowDb()
    q = FieldQueue(db, start_thread=True)
    try:
        t0 = time.monotonic()
        assert q.claim_niceonly() is None  # empty queue: pop is still instant
        claim_latency = time.monotonic() - t0
        assert claim_latency < 0.02, claim_latency
        deadline = time.monotonic() + 2
        while not db.bulk_threads and time.monotonic() < deadline:
            time.sleep(0.01)
        assert db.bulk_threads, "background refill never ran"
        assert all(t == "field-queue-refill" for t in db.bulk_threads)
    finally:
        q.close()


_CLAIM_WORKER_SRC = """
import json, sys
sys.path.insert(0, {repo!r})
from nice_tpu.core.types import FieldClaimStrategy
from nice_tpu.server.db import Db
from nice_tpu.server.field_queue import U128_MAX

db = Db({db_path!r})
got = []
for _ in range({n}):
    f = db.try_claim_field(
        FieldClaimStrategy.NEXT, db.claim_expiry_cutoff(), 0, U128_MAX
    )
    if f is not None:
        got.append(f.field_id)
db.close()
print(json.dumps(got))
"""


def test_two_process_concurrent_claims(tmp_path):
    """Two OS processes claiming from the same sqlite ledger never double-claim
    a field and never fail with 'database is locked' (busy_timeout + BEGIN
    IMMEDIATE; the SQLite analog of the reference's multi-worker FOR UPDATE
    SKIP LOCKED claims, db_util/fields.rs:204-536)."""
    import os
    import subprocess
    import sys

    db_path = str(tmp_path / "conc.db")
    db = Db(db_path)
    db.seed_base(17, field_size=100)  # plenty of fields
    db.close()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = _CLAIM_WORKER_SRC.format(repo=repo, db_path=db_path, n=8)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", src],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for _ in range(2)
    ]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err
        assert "database is locked" not in err
        results.append(json.loads(out.strip().splitlines()[-1]))
    a, b = results
    assert a and b, (a, b)
    assert not (set(a) & set(b)), f"double-claimed fields: {set(a) & set(b)}"


def test_public_query_surface(server):
    """/query: the PostgREST-equivalent read-only SQL surface (reference
    schema/schema.sql:82-87 web_anon role). Allowed SELECTs work with
    parameters; writes, non-public tables, and user_ip reads are sandboxed."""
    base_url, db_path = server
    from urllib.parse import quote

    # GET with ad-hoc SQL over a public table
    r = _get(base_url + "/query?sql=" + quote(
        "SELECT id, range_size FROM bases ORDER BY id"))
    assert r["columns"] == ["id", "range_size"]
    assert [row[0] for row in r["rows"]] == [10]
    assert r["truncated"] is False

    # POST with bound params
    r = _post(base_url + "/query", {
        "sql": "SELECT COUNT(*) AS n FROM fields WHERE base_id = ?",
        "params": [10],
    })
    assert r["columns"] == ["n"]
    assert r["rows"][0][0] > 0

    # schema discovery (PostgREST's OpenAPI-root analog)
    r = _post(base_url + "/query", {
        "sql": "SELECT name FROM sqlite_master WHERE type='table' ORDER BY name",
    })
    assert ["bases"] in r["rows"]

    # writes are rejected (query_only + authorizer)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base_url + "/query", {"sql": "DELETE FROM bases"})
    assert exc.value.code == 400

    # user_ip is redacted to NULL, not exposed
    _submit_one(base_url, "alice")
    r = _post(base_url + "/query", {
        "sql": "SELECT username, user_ip FROM submissions LIMIT 5"})
    assert r["rows"], "expected at least one submission row"
    assert all(row[1] is None for row in r["rows"])
    r2 = _post(base_url + "/query", {
        "sql": "SELECT COUNT(*) FROM submissions WHERE user_ip IS NOT NULL"})
    assert r2["rows"][0][0] == 0
