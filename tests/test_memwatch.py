"""Resource observatory tests: memwatch sampling + trend/forecast math,
pyprof attribution + bounded tables, the shared utils/resources backends,
the compile-cache LRU cap, and spool quarantine retention.

The zero-overhead-off contracts (knob=0 -> no thread created, counters
stay 0) are asserted here the same way stepprof's fence count is: the off
state must be provable, not assumed.
"""

import os
import threading
import time

import pytest

from nice_tpu.obs import memwatch, pyprof
from nice_tpu.obs.series import MEM_SAMPLES
from nice_tpu.ops import compile_cache
from nice_tpu.utils import resources


@pytest.fixture(autouse=True)
def _clean_state():
    memwatch.reset_for_tests()
    pyprof.reset_for_tests()
    yield
    memwatch.reset_for_tests()
    pyprof.reset_for_tests()


# -- zero-overhead off -------------------------------------------------------


def test_memwatch_off_means_no_thread_and_no_samples(monkeypatch):
    monkeypatch.setenv("NICE_TPU_MEMWATCH_SECS", "0")
    before_threads = {t.name for t in threading.enumerate()}
    before_samples = MEM_SAMPLES.value()
    assert memwatch.maybe_start_sampler() is False
    assert memwatch.maybe_sample() is None
    assert memwatch.summary() == {}
    assert MEM_SAMPLES.value() == before_samples
    after_threads = {t.name for t in threading.enumerate()}
    assert "nice-memwatch" not in after_threads - before_threads


def test_pyprof_off_means_no_thread_and_no_samples(monkeypatch):
    monkeypatch.setenv("NICE_TPU_PYPROF_HZ", "0")
    before_threads = {t.name for t in threading.enumerate()}
    before = pyprof.sample_count()
    assert pyprof.maybe_start() is False
    assert pyprof.sample_count() == before
    after_threads = {t.name for t in threading.enumerate()}
    assert "nice-pyprof" not in after_threads - before_threads


# -- memwatch sampling -------------------------------------------------------


def test_sample_reads_rss_and_watched_paths(tmp_path, monkeypatch):
    monkeypatch.setenv("NICE_TPU_MEMWATCH_SECS", "1")
    spool = tmp_path / "spool"
    spool.mkdir()
    (spool / "a.json").write_bytes(b"x" * 1000)
    (spool / "b.json.rejected").write_bytes(b"y" * 500)
    memwatch.watch_path("spool", str(spool))
    memwatch.watch_path("missing", str(tmp_path / "nope"))
    memwatch.watch_path("ckpt", None)  # ignored, not an error

    out = memwatch.sample()
    assert out["rss_bytes"] > 0
    # Peak comes from ru_maxrss, whose accounting can trail /proc VmRSS by
    # a little — same order of magnitude is the contract.
    assert out["rss_peak_bytes"] >= out["rss_bytes"] * 0.5
    # The .rejected entry counts in BOTH the spool footprint (it lives in
    # the dir) and its own quarantine watermark.
    assert out["disk_bytes"]["spool"] == 1500
    assert out["disk_bytes"]["quarantine"] == 500
    assert "missing" not in out["disk_bytes"]
    assert out["disk_free_bytes"] > 0
    assert memwatch.summary() == out


def test_maybe_sample_throttles_to_interval(monkeypatch):
    monkeypatch.setenv("NICE_TPU_MEMWATCH_SECS", "5")
    first = memwatch.maybe_sample()
    assert first is not None
    # Inside the interval: throttled.
    assert memwatch.maybe_sample() is None


# -- trend + forecast math ---------------------------------------------------


class FakeStore:
    """Minimal history-store stand-in: series -> [(unix_ts, value)]."""

    def __init__(self, series):
        self._series = series

    def series_names(self):
        return list(self._series)

    def query(self, name, since=0.0, tiers=("raw",)):
        pts = [(t, v) for t, v in self._series.get(name, []) if t >= since]
        return {"raw": pts}


def test_slope_per_sec_fits_a_line():
    pts = [(0.0, 10.0), (1.0, 20.0), (2.0, 30.0), (3.0, 40.0)]
    assert memwatch.slope_per_sec(pts) == pytest.approx(10.0)
    assert memwatch.slope_per_sec([(0.0, 1.0)]) is None
    assert memwatch.slope_per_sec([(5.0, 1.0), (5.0, 2.0)]) is None


def test_trend_reports_growing_series_only():
    now = time.time()
    grow = [(now - 30 + i * 10, 1000.0 * i) for i in range(4)]
    flat = [(now - 30 + i * 10, 5000.0) for i in range(4)]
    short = [(now - 10, 1.0), (now, 2.0)]
    store = FakeStore({
        "nice_mem_rss_bytes": grow,
        "nice_disk_usage_bytes": flat,
        "nice_disk_usage_bytes{what=\"spool\"}": short,  # < MIN_TREND_POINTS
        "nice_fleet_numbers_per_sec": grow,  # not a resource series
    })
    slopes = memwatch.trend(store, since=now - 60)
    assert slopes["nice_mem_rss_bytes"] == pytest.approx(100.0)
    assert slopes["nice_disk_usage_bytes"] == pytest.approx(0.0)
    assert "nice_disk_usage_bytes{what=\"spool\"}" not in slopes
    assert "nice_fleet_numbers_per_sec" not in slopes


def test_forecast_ratio_and_tte(monkeypatch):
    """Disk growing at a known rate against a deterministic capacity: the
    forecaster's tte must equal headroom/rate and the ratio must cross 1.0
    exactly when tte < horizon."""
    now = time.time()
    rate = 100.0  # bytes/sec
    pts = [(now - 30 + i * 10, 1000.0 + rate * (i * 10)) for i in range(4)]
    store = FakeStore({"nice_disk_usage_bytes": pts})
    last = pts[-1][1]
    monkeypatch.setenv(
        "NICE_TPU_MEMWATCH_DISK_CAPACITY", str(int(last + 50_000))
    )
    fc = memwatch.forecast(store, since=now - 60, horizon_secs=600.0)
    disk = fc["disk"]
    assert disk["slope_bytes_per_sec"] == pytest.approx(rate)
    assert disk["headroom_bytes"] == pytest.approx(50_000)
    assert disk["tte_secs"] == pytest.approx(50_000 / rate)
    # 600 s horizon, 500 s to exhaustion -> ratio 1.2 (pages at >= 1.0).
    assert disk["ratio"] == pytest.approx(600.0 * rate / 50_000)
    assert disk["ratio"] > 1.0


def test_forecast_not_growing_means_zero_ratio(monkeypatch):
    now = time.time()
    pts = [(now - 30 + i * 10, 9000.0 - i) for i in range(4)]
    store = FakeStore({"nice_disk_usage_bytes": pts})
    monkeypatch.setenv("NICE_TPU_MEMWATCH_DISK_CAPACITY", "1000000")
    fc = memwatch.forecast(store, since=now - 60, horizon_secs=600.0)
    assert fc["disk"]["ratio"] == 0.0
    assert fc["disk"]["tte_secs"] is None


def test_anomaly_detectors_ride_on_memwatch(monkeypatch):
    """mem_leak_trend and resource_exhaustion map the memwatch math onto
    the ok/warn/page ladder."""
    from nice_tpu.obs import anomaly

    now = time.time()
    # 3 MiB/s growth: past the 2 MiB/s page default.
    rate = 3 * 1024 * 1024.0
    pts = [(now - 30 + i * 10, rate * i * 10) for i in range(4)]
    store = FakeStore({"nice_mem_rss_bytes": pts})

    class FakeEngine:
        pass

    eng = FakeEngine()
    eng.store = store
    dets = {d.name: d for d in anomaly.default_detectors()}
    res = dets["mem_leak_trend"].evaluate(eng, now)
    assert res["state"] == "page"
    assert res["value"] == pytest.approx(rate, rel=0.01)
    # No resource series at all -> no_data -> ok.
    eng.store = FakeStore({})
    assert dets["mem_leak_trend"].evaluate(eng, now)["no_data"]
    assert dets["resource_exhaustion"].evaluate(eng, now)["state"] == "ok"


# -- pyprof ------------------------------------------------------------------


def test_attribute_maps_thread_names_to_roots():
    assert pyprof.attribute("MainThread") == "main"
    assert pyprof.attribute("db-writer") == "db-writer"
    # Pool workers spawn "<root>_N"-style names: prefix match.
    assert pyprof.attribute("nice-api-pool_3") == "nice-api-pool"
    # Executor prefixes that differ from their threadspec root go through
    # the runtime alias table.
    assert pyprof.attribute("nice-srv_2") == "async-workers"
    assert pyprof.attribute("nice-api_0") == "nice-api-pool"
    assert pyprof.attribute("Thread-7") is None


def test_take_sample_attributes_a_named_thread(monkeypatch):
    stop = threading.Event()

    def _spin():
        while not stop.is_set():
            time.sleep(0.01)

    t = threading.Thread(target=_spin, name="nice-memwatch", daemon=True)
    t.start()
    try:
        n = pyprof.take_sample()
    finally:
        stop.set()
        t.join(timeout=5)
    assert n >= 1
    snap = pyprof.snapshot()
    assert "nice-memwatch" in snap["roots"]
    stacks = snap["roots"]["nice-memwatch"]["stacks"]
    assert stacks and any("_spin" in s["stack"] for s in stacks)
    # Frames fold as basename:func with no line numbers.
    assert all(os.sep not in s["stack"] for s in stacks)
    assert pyprof.sample_count() == n


def test_folded_render_and_query_formats():
    with pyprof._lock:
        pyprof._tables["main"] = {"a.py:f;b.py:g": 3}
        pyprof._root_samples["main"] = 3
    folded = pyprof.render_folded()
    assert folded == "main;a.py:f;b.py:g 3\n"
    status, body, ctype = pyprof.handle_query("fmt=folded")
    assert (status, ctype) == (200, "text/plain")
    assert body.decode() == folded
    status, body, ctype = pyprof.handle_query("")
    assert (status, ctype) == (200, "application/json")
    status, body, _ = pyprof.handle_query("fmt=svg")
    assert status == 400
    assert b"folded" in body


def test_stack_table_is_bounded(monkeypatch):
    """Past NICE_TPU_PYPROF_MAX_STACKS distinct shapes, new stacks collapse
    into the per-root (other) bucket instead of growing the table. With the
    cap at 1 and a table pre-seeded to the cap, every stack a real sample
    sees is a NEW shape and must land in (other)."""
    from nice_tpu.obs.series import PYPROF_OVERFLOW

    monkeypatch.setenv("NICE_TPU_PYPROF_MAX_STACKS", "1")
    with pyprof._lock:
        pyprof._tables["main"] = {"pre.py:seeded": 1}
        pyprof._distinct_stacks = 1
    ov0 = PYPROF_OVERFLOW.value()
    stop = threading.Event()

    def _spin():
        while not stop.is_set():
            time.sleep(0.01)

    t = threading.Thread(target=_spin, name="nice-memwatch", daemon=True)
    t.start()
    time.sleep(0.05)
    try:
        n = pyprof.take_sample()
    finally:
        stop.set()
        t.join(timeout=5)
    assert n >= 1
    assert PYPROF_OVERFLOW.value() >= ov0 + 1
    with pyprof._lock:
        assert pyprof._distinct_stacks == 1  # table did not grow
        assert pyprof._tables["nice-memwatch"] == {
            pyprof._OTHER: pyprof._tables["nice-memwatch"][pyprof._OTHER]
        }
        assert pyprof._tables["nice-memwatch"][pyprof._OTHER] >= 1


def test_top_stacks_orders_hottest_first():
    with pyprof._lock:
        pyprof._tables["main"] = {"a.py:f": 5, "b.py:g": 9}
        pyprof._tables["db-writer"] = {"c.py:h": 7}
    top = pyprof.top_stacks(k=2)
    assert [e["count"] for e in top] == [9, 7]
    assert top[0]["root"] == "main"


# -- utils/resources ---------------------------------------------------------


def test_rss_backends_agree_on_this_process():
    backend = resources.pick_rss_backend()
    assert backend in ("proc", "psutil", "rusage")  # never "none" on linux/mac
    rss = resources.rss_bytes()
    assert rss is not None and rss > 1024 * 1024  # a python process is >1MB
    peak = resources.peak_rss_bytes()
    assert peak is not None and peak >= rss * 0.5  # peak from rusage scale
    total = resources.host_memory_total_bytes()
    assert total is not None and total > rss


def test_dir_bytes_and_fs_free(tmp_path):
    d = tmp_path / "d"
    d.mkdir()
    (d / "f1").write_bytes(b"a" * 100)
    sub = d / "sub"
    sub.mkdir()
    (sub / "f2").write_bytes(b"b" * 50)
    assert resources.dir_bytes(str(d)) >= 150  # dirs may add lstat size
    assert resources.dir_bytes(str(tmp_path / "missing")) is None
    # A file path counts as itself.
    assert resources.dir_bytes(str(d / "f1")) == 100
    assert resources.fs_free_bytes(str(d)) > 0


def test_cpu_monitor_moved_but_unchanged():
    """The daemon's CPU sampler now lives in utils/resources; the daemon
    re-exports it (tests/test_daemon.py covers the monkeypatch contract)."""
    from nice_tpu.daemon import main as daemon

    assert daemon.read_cpu_times is resources.read_cpu_times
    assert daemon.pick_cpu_backend is resources.pick_cpu_backend
    assert issubclass(daemon.CpuMonitor, resources.CpuMonitor)


# -- compile-cache LRU cap ---------------------------------------------------


def test_executable_cache_evicts_least_recently_hit(monkeypatch):
    monkeypatch.setenv("NICE_TPU_COMPILE_CACHE_MAX_EXECUTABLES", "2")
    compile_cache.reset_for_tests()
    ev0 = compile_cache.counts()["executable_evictions"]
    builds = []

    def build(name):
        def _b():
            builds.append(name)
            return name

        return _b

    assert compile_cache.executable(("a",), build("A")) == "A"
    assert compile_cache.executable(("b",), build("B")) == "B"
    # Hit "a" so it becomes most-recently-used; inserting "c" evicts "b".
    assert compile_cache.executable(("a",), build("A2")) == "A"
    assert compile_cache.executable(("c",), build("C")) == "C"
    assert compile_cache.counts()["executable_evictions"] == ev0 + 1
    assert compile_cache.executable(("a",), build("A3")) == "A"  # survived
    assert compile_cache.executable(("b",), build("B2")) == "B2"  # rebuilt
    assert builds == ["A", "B", "C", "B2"]
    compile_cache.reset_for_tests()


def test_executable_cache_unbounded_at_zero(monkeypatch):
    monkeypatch.setenv("NICE_TPU_COMPILE_CACHE_MAX_EXECUTABLES", "0")
    compile_cache.reset_for_tests()
    ev0 = compile_cache.counts()["executable_evictions"]
    for i in range(20):
        compile_cache.executable(("k", i), lambda i=i: i)
    assert compile_cache.counts()["executable_evictions"] == ev0
    assert compile_cache.footprint()["count"] == 20
    compile_cache.reset_for_tests()


def test_footprint_groups_by_kind_and_base():
    compile_cache.reset_for_tests()

    class Plan:
        base = 13

    compile_cache.executable(("detailed", Plan(), 64), lambda: object())
    compile_cache.executable(("niceonly", 1 << 20), lambda: object())
    fp = compile_cache.footprint()
    assert fp["count"] == 2
    assert set(fp["groups"]) == {"detailed|b13", "niceonly"}
    compile_cache.reset_for_tests()


# -- spool quarantine retention ----------------------------------------------


def _mk_rejected(spool_dir, name, size, age_secs):
    path = os.path.join(spool_dir, name + ".json.rejected")
    with open(path, "wb") as f:
        f.write(b"x" * size)
    old = time.time() - age_secs
    os.utime(path, (old, old))
    return path


def test_quarantine_prunes_by_age_then_size(tmp_path, monkeypatch):
    from nice_tpu.faults.spool import SubmissionSpool
    from nice_tpu.obs.series import SPOOL_QUARANTINE_PRUNED

    spool = SubmissionSpool(str(tmp_path))
    monkeypatch.setenv("NICE_TPU_SPOOL_QUARANTINE_MAX_BYTES", "250")
    monkeypatch.setenv("NICE_TPU_SPOOL_QUARANTINE_MAX_AGE_SECS", "3600")
    ancient = _mk_rejected(str(tmp_path), "ancient", 10, age_secs=7200)
    old = _mk_rejected(str(tmp_path), "old", 200, age_secs=300)
    new = _mk_rejected(str(tmp_path), "new", 200, age_secs=10)
    c0 = SPOOL_QUARANTINE_PRUNED.value()

    out = spool.prune_quarantine()
    # ancient violates the age bound; then old (oldest survivor) must go
    # for the remaining 400 bytes to fit the 250-byte cap.
    assert out == {"entries": 2, "bytes": 210}
    assert not os.path.exists(ancient)
    assert not os.path.exists(old)
    assert os.path.exists(new)
    assert SPOOL_QUARANTINE_PRUNED.value() == c0 + 210


def test_quarantine_retention_disabled_at_zero(tmp_path, monkeypatch):
    from nice_tpu.faults.spool import SubmissionSpool

    spool = SubmissionSpool(str(tmp_path))
    monkeypatch.setenv("NICE_TPU_SPOOL_QUARANTINE_MAX_BYTES", "0")
    monkeypatch.setenv("NICE_TPU_SPOOL_QUARANTINE_MAX_AGE_SECS", "0")
    path = _mk_rejected(str(tmp_path), "keep", 1 << 20, age_secs=10 ** 8)
    assert spool.prune_quarantine() == {"entries": 0, "bytes": 0}
    assert os.path.exists(path)
