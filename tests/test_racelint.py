"""racelint + schedex tests: the coverage gate must catch unregistered and
stale thread roots, every R-rule has a good/bad fixture pair (the seeded
race shape must be caught; the disciplined version must pass), the
interleaving explorer reproduces a known-racy fixture within the k<=2
preemption bound and replays it byte-for-byte from its schedule id, and
the schedex-off production path provably installs no wrapper."""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from nice_tpu.analysis import core, schedex, threadspec  # noqa: E402
from nice_tpu.analysis import scenarios as scen_mod  # noqa: E402
from nice_tpu.analysis.racerules import context, run_race_rules  # noqa: E402
from nice_tpu.utils import lockdep  # noqa: E402


def project(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content), encoding="utf-8")
    return core.Project(str(tmp_path))


def race_run(tmp_path, files, rule, monkeypatch,
             roots=(), locks=(), shared=(), lockorder=None):
    """Run one R-rule over a fixture project with a synthetic registry."""
    proj = project(tmp_path, files)
    monkeypatch.setattr(threadspec, "THREAD_ROOTS", tuple(roots))
    monkeypatch.setattr(threadspec, "LOCK_SPECS", tuple(locks))
    monkeypatch.setattr(threadspec, "SHARED_STATE", tuple(shared))
    ctx = context.build_context(
        str(tmp_path), proj,
        lockorder_path=lockorder or str(tmp_path / "no-lockorder.json"))
    vs, _used = run_race_rules(proj, ctx, only=[rule])
    return vs


def details(vs):
    return [v.detail for v in vs]


PUMP_ROOTS = (
    threadspec.ThreadRoot(
        name="pump-run", path="nice_tpu/pump.py",
        spawn_scope="Pump.__init__", entries=("Pump._run",), role="helper"),
    threadspec.ThreadRoot(
        name="pump-poke", path="nice_tpu/pump.py",
        spawn_scope="Pump.__init__", entries=("Pump.poke",), role="helper"),
)

PUMP_BAD = """
    import threading

    class Pump:
        def __init__(self):
            self._t = threading.Thread(target=self._run)
            self._u = threading.Thread(target=self.poke)

        def _run(self):
            self._count = 1

        def poke(self):
            self._count = 2
"""

PUMP_GOOD = """
    import threading
    from nice_tpu.utils import lockdep

    class Pump:
        def __init__(self):
            self._lock = lockdep.make_lock("test.pump")
            self._t = threading.Thread(target=self._run)
            self._u = threading.Thread(target=self.poke)

        def _run(self):
            with self._lock:
                self._count = 1

        def poke(self):
            with self._lock:
                self._count = 2
"""


# ---------------------------------------------------------------------------
# R1: coverage gate + multi-root unguarded mutation


def test_r1_unregistered_spawn_is_caught(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {
        "nice_tpu/foo.py": """
            import threading

            def boot():
                threading.Thread(target=print).start()
        """,
    }, "R1", monkeypatch)
    assert "unregistered-thread:boot" in details(vs)


def test_r1_stale_root_is_caught(tmp_path, monkeypatch):
    vs = race_run(
        tmp_path, {"nice_tpu/foo.py": "def f():\n    pass\n"},
        "R1", monkeypatch,
        roots=(threadspec.ThreadRoot(
            name="ghost", path="nice_tpu/foo.py", spawn_scope="gone",
            entries=(), role="helper"),))
    assert "stale-root:ghost" in details(vs)


def test_r1_multi_root_unguarded_write_caught(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {"nice_tpu/pump.py": PUMP_BAD},
                  "R1", monkeypatch, roots=PUMP_ROOTS)
    assert "shared:Pump._count" in details(vs)


def test_r1_common_lock_or_declaration_is_clean(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {"nice_tpu/pump.py": PUMP_GOOD},
                  "R1", monkeypatch, roots=PUMP_ROOTS)
    assert not [d for d in details(vs) if d.startswith("shared:")]
    # an ownership declaration routes it to R2 instead of R1
    vs = race_run(
        tmp_path, {"nice_tpu/pump.py": PUMP_BAD}, "R1", monkeypatch,
        roots=PUMP_ROOTS,
        shared=(threadspec.SharedState(
            path="nice_tpu/pump.py", scope="Pump", attr="_count",
            ownership="owner:pump-run"),))
    assert not [d for d in details(vs) if d.startswith("shared:")]


# ---------------------------------------------------------------------------
# R2: declared ownership discipline + lock inventory + order cross-check


def test_r2_unlocked_write_of_declared_state(tmp_path, monkeypatch):
    decl = threadspec.SharedState(
        path="nice_tpu/pump.py", scope="Pump", attr="_count",
        ownership="lock:test.pump")
    vs = race_run(tmp_path, {"nice_tpu/pump.py": PUMP_BAD},
                  "R2", monkeypatch, roots=PUMP_ROOTS, shared=(decl,))
    assert any(d.startswith("unlocked:Pump._count") for d in details(vs))
    vs = race_run(
        tmp_path, {"nice_tpu/pump.py": PUMP_GOOD}, "R2", monkeypatch,
        roots=PUMP_ROOTS, shared=(decl,),
        locks=(threadspec.LockSpec("test.pump", guards="fixture"),))
    assert not [d for d in details(vs) if d.startswith("unlocked:")]


def test_r2_owner_and_immutable_declarations(tmp_path, monkeypatch):
    owner = threadspec.SharedState(
        path="nice_tpu/pump.py", scope="Pump", attr="_count",
        ownership="owner:pump-run")
    vs = race_run(tmp_path, {"nice_tpu/pump.py": PUMP_BAD},
                  "R2", monkeypatch, roots=PUMP_ROOTS, shared=(owner,))
    # poke() is reachable from pump-poke, a foreign root for owner state
    assert any(d.startswith("foreign-write:Pump._count") for d in details(vs))
    frozen = threadspec.SharedState(
        path="nice_tpu/pump.py", scope="Pump", attr="_count",
        ownership="immutable-after-init")
    vs = race_run(tmp_path, {"nice_tpu/pump.py": PUMP_BAD},
                  "R2", monkeypatch, roots=PUMP_ROOTS, shared=(frozen,))
    assert any(d.startswith("mutated-immutable:") for d in details(vs))


def test_r2_lock_inventory_and_missing_lockorder(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {
        "nice_tpu/x.py": """
            from nice_tpu.utils import lockdep
            _L = lockdep.make_lock("t.mystery")
        """,
    }, "R2", monkeypatch,
        locks=(threadspec.LockSpec("t.gone", guards="nothing"),))
    ds = details(vs)
    assert "undeclared-lock:t.mystery" in ds
    assert "stale-lock:t.gone" in ds
    assert "missing-lockorder" in ds


def test_r2_static_runtime_order_divergence(tmp_path, monkeypatch):
    lockorder = tmp_path / "lockorder.json"
    lockorder.write_text(json.dumps({"edges": {"t.B": ["t.A"]}}))
    vs = race_run(tmp_path, {
        "nice_tpu/locks.py": """
            from nice_tpu.utils import lockdep
            A = lockdep.make_lock("t.A")
            B = lockdep.make_lock("t.B")

            def fwd():
                with A:
                    with B:
                        pass
        """,
    }, "R2", monkeypatch,
        locks=(threadspec.LockSpec("t.A", guards="a"),
               threadspec.LockSpec("t.B", guards="b")),
        lockorder=str(lockorder))
    # static says A->B, runtime observed B->A: jointly a deadlock
    assert any(d.startswith("order-divergence:") for d in details(vs))


# ---------------------------------------------------------------------------
# R3: blocking where blocking is forbidden


def test_r3_blocking_reachable_from_noblock_root(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {
        "nice_tpu/foo.py": """
            import threading
            import time

            def boot():
                threading.Thread(target=work).start()

            def work():
                time.sleep(1)
        """,
    }, "R3", monkeypatch,
        roots=(threadspec.ThreadRoot(
            name="no-sleeper", path="nice_tpu/foo.py", spawn_scope="boot",
            entries=("work",), role="helper", may_block=False),))
    assert any(d.startswith("noblock:no-sleeper:") for d in details(vs))


def test_r3_blocking_under_noblock_lock(tmp_path, monkeypatch):
    files = {
        "nice_tpu/foo.py": """
            import time
            from nice_tpu.utils import lockdep
            _L = lockdep.make_lock("t.cachelock")

            def f():
                with _L:
                    time.sleep(1)
        """,
    }
    vs = race_run(tmp_path, files, "R3", monkeypatch,
                  locks=(threadspec.LockSpec("t.cachelock", guards="c"),))
    assert "block-under:t.cachelock:time.sleep" in details(vs)
    # a lock declared as serializing a blocking resource is exempt
    vs = race_run(tmp_path, files, "R3", monkeypatch,
                  locks=(threadspec.LockSpec(
                      "t.cachelock", guards="c", may_block_under=True),))
    assert not details(vs)


# ---------------------------------------------------------------------------
# R4: writer-actor discipline


def test_r4_resolve_outside_writer_and_inside_txn(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {
        "nice_tpu/handlers.py": """
            def f(fut):
                fut.set_result(1)
        """,
        "nice_tpu/server/writer.py": """
            class W:
                def _txn(self):
                    pass

                def run(self, fut):
                    with self._txn():
                        fut.set_result(1)

                def ok(self, fut):
                    with self._txn():
                        pass
                    fut.set_result(2)
        """,
    }, "R4", monkeypatch)
    ds = details(vs)
    assert "resolve-outside-writer:f" in ds
    assert "resolve-inside-txn:W.run" in ds
    assert not any("W.ok" in d for d in ds)


# ---------------------------------------------------------------------------
# R5: check-then-act atomicity


CACHE_BAD = """
    from nice_tpu.utils import lockdep

    class Cache:
        def __init__(self):
            self._lock = lockdep.make_lock("t.cache")
            self._d = {}

        def get_or_build(self, k):
            with self._lock:
                v = self._d.get(k)
            if v is not None:
                return v
            v = object()
            with self._lock:
                self._d[k] = v
            return v
"""


def test_r5_check_then_act_caught(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {"nice_tpu/cache.py": CACHE_BAD},
                  "R5", monkeypatch)
    assert "check-then-act:get_or_build:self._d" in details(vs)


def test_r5_setdefault_and_allow_are_sanctioned(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {
        "nice_tpu/cache.py": CACHE_BAD.replace(
            "self._d[k] = v",
            "v = self._d.setdefault(k, v)"),
    }, "R5", monkeypatch)
    assert not details(vs)
    vs = race_run(tmp_path, {
        "nice_tpu/cache.py": CACHE_BAD.replace(
            "self._d[k] = v",
            "self._d[k] = v  # nicelint: allow R5 (fixture)"),
    }, "R5", monkeypatch)
    assert not details(vs)


def test_r5_lru_cache_clear_caught(tmp_path, monkeypatch):
    vs = race_run(tmp_path, {
        "nice_tpu/cache.py": """
            import functools

            @functools.lru_cache
            def build(x):
                return x

            def reset():
                build.cache_clear()
        """,
    }, "R5", monkeypatch)
    assert "lru-clear:build" in details(vs)


# ---------------------------------------------------------------------------
# schedex: determinism, bounded exploration, zero-cost off


def test_schedex_catches_racy_counter_within_bound():
    report = schedex.explore(scen_mod.RacyCounter,
                             seeds=0, preemptions=1, max_schedules=32)
    assert not report.ok
    first = report.first_failing()
    # caught by a single forced preemption, k=1
    assert first.schedule_id.startswith("pre:")


def test_schedex_replay_is_byte_for_byte():
    report = schedex.explore(scen_mod.RacyCounter,
                             seeds=2, preemptions=1, max_schedules=32,
                             stop_on_failure=True)
    first = report.first_failing()
    a = schedex.replay(scen_mod.RacyCounter, first.schedule_id)
    b = schedex.replay(scen_mod.RacyCounter, first.schedule_id)
    assert a.trace == first.trace == b.trace
    assert not a.ok and not b.ok


def test_schedex_random_seed_is_deterministic():
    a = schedex.run_schedule(scen_mod.RacyCounter, schedex.RandomPolicy(7))
    b = schedex.run_schedule(scen_mod.RacyCounter, schedex.RandomPolicy(7))
    c = schedex.run_schedule(scen_mod.RacyCounter, schedex.RandomPolicy(8))
    assert a.trace == b.trace and a.ok == b.ok
    assert c.schedule_id != a.schedule_id


def test_schedex_deadlock_is_detected():
    class Deadlock(scen_mod.Scenario):
        scenario_name = "deadlock_fixture"

        def build(self, sched):
            la = schedex.Lock(sched, "t.a")
            lb = schedex.Lock(sched, "t.b")

            def one():
                with la:
                    sched.yield_point("one:mid")
                    with lb:
                        pass

            def two():
                with lb:
                    sched.yield_point("two:mid")
                    with la:
                        pass

            return [("one", one), ("two", two)]

    res = schedex.run_schedule(Deadlock, schedex.PreemptPolicy((1,)))
    assert not res.ok
    assert any("deadlock" in f.lower() for f in res.failures)


def test_status_cache_fix_holds_and_prefix_twin_is_caught():
    good = schedex.explore(scen_mod.StatusCacheInvalidateVsRebuild,
                           seeds=4, preemptions=2, max_schedules=64)
    assert good.ok, [f.failures for f in good.failing]
    bad = schedex.explore(scen_mod.StatusCachePreFix,
                          seeds=4, preemptions=2, max_schedules=64,
                          stop_on_failure=True)
    assert not bad.ok


def test_lease_sweep_fix_holds_and_prefix_twin_is_caught():
    good = schedex.explore(scen_mod.LeaseSweepVsSubmit,
                           seeds=4, preemptions=2, max_schedules=64)
    assert good.ok, [f.failures for f in good.failing]
    bad = schedex.explore(scen_mod.LeaseSweepPreFix,
                          seeds=4, preemptions=2, max_schedules=64,
                          stop_on_failure=True)
    assert not bad.ok


def test_schedex_off_is_zero_cost(monkeypatch):
    # The production path with NICE_TPU_SCHEDEX off: no factory hook, and
    # make_lock (lockdep disabled) returns a plain threading primitive.
    monkeypatch.delenv("NICE_TPU_LOCKDEP", raising=False)
    monkeypatch.delenv("NICE_TPU_SCHEDEX", raising=False)
    assert lockdep.factory_hook() is None
    lock = lockdep.make_lock("zero.cost.fixture")
    assert type(lock) is type(threading.Lock())


def test_instrument_window_installs_and_restores_hook():
    sched = schedex.Scheduler(schedex.FIFOPolicy())
    assert lockdep.factory_hook() is None
    with schedex.instrument(sched):
        minted = lockdep.make_lock("windowed.fixture")
        assert isinstance(minted, schedex.Lock)
        rm = lockdep.make_rlock("windowed.rfixture")
        assert isinstance(rm, schedex.Lock) and rm._re
    assert lockdep.factory_hook() is None
    assert type(lockdep.make_lock("after.fixture")) is type(threading.Lock())


def test_lockdep_dump_graph_merges(tmp_path):
    path = tmp_path / "lockorder.json"
    path.write_text(json.dumps({"edges": {"t.outer": ["t.inner"]}}))
    edges = lockdep.dump_graph(str(path), merge=True)
    assert "t.inner" in edges.get("t.outer", [])
    data = json.loads(path.read_text())
    assert "t.inner" in data["edges"]["t.outer"]


def test_racecheck_smoke_cli_racy_counter(tmp_path):
    out = tmp_path / "racecheck.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "racecheck_smoke.py"),
         "--only", "racy_counter", "--only", "lease_sweep_prefix",
         "--only", "lease_sweep_vs_submit",
         "--seeds", "2", "--json", str(out)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["ok"]
    assert report["scenarios"]["racy_counter"]["verdict"] == "OK"
    assert report["scenarios"]["racy_counter"]["replay"]["trace_identical"]
    assert report["bench_schedex_off"]["hook_installed"] is False
