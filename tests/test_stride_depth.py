"""CRT stride-depth selection (the TPU re-design of the reference's fused
low-digit GPU prefilter, nice_kernels.cu:329-383 / client_process_gpu.rs:407-450)
and its soundness contract."""

import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, pallas_engine as pe, scalar, stride_filter
from nice_tpu.ops.limbs import get_plan, int_to_limbs


@pytest.mark.parametrize("base", [10, 40])
@pytest.mark.parametrize("k", [1, 2, 3])
def test_deeper_tables_never_reject_a_nice_number(base, k):
    """Soundness mirror (ref client_process_gpu.rs:1289-1324): every nice
    number is a stride candidate at EVERY depth k."""
    table = stride_filter.get_stride_table(base, k)
    br = base_range.get_base_range(base)
    rng = FieldSize(br[0], min(br[1], br[0] + 30_000))
    nice = scalar.process_range_niceonly(rng, base).nice_numbers
    if base == 10:
        assert [n.number for n in nice] == [69]
    residues = set(table.valid_residues)
    for n in nice:
        assert n.number % table.modulus in residues, (k, n.number)


@pytest.mark.parametrize("base", [30, 40, 50])
def test_deeper_tables_are_sparser(base):
    d = [
        stride_filter.get_stride_table(base, k).num_residues
        / ((base - 1) * base**k)
        for k in (1, 2, 3)
    ]
    assert d[0] >= d[1] >= d[2]


def test_pick_depth_narrow_ranges_stay_shallow():
    # Median surviving range far narrower than the k=2 modulus: deeper k
    # would waste masked lanes, so the gate keeps k=1.
    br = base_range.get_base_range(40)
    ranges = [FieldSize(br[0], br[0] + 4_000)] * 5
    k, periods = engine._pick_stride_depth(40, ranges)
    assert k == 1
    assert 1 <= periods <= pe.STRIDED_PERIODS


def test_pick_depth_wide_ranges_go_deeper():
    # Only when ranges dwarf the deep spans does the density gain beat the
    # tail-padding waste (at 50M-wide ranges the k=2 span of ~8M leaves
    # ~12% ceil padding, more than the ~8% density win — the gate correctly
    # stays at k=1 there; measured like the reference compiling its
    # prefilter out at b42+).
    br = base_range.get_base_range(40)
    width = 500_000_000
    ranges = [FieldSize(br[0], br[0] + width)] * 3
    k, periods = engine._pick_stride_depth(40, ranges)
    assert k == 2
    span = periods * (39 * 40**k)
    assert span <= width

    narrower = [FieldSize(br[0], br[0] + 50_000_000)] * 3
    k, _ = engine._pick_stride_depth(40, narrower)
    assert k == 1  # padding waste > density gain at this width


def test_pick_depth_respects_u32_contract():
    for base in (40, 50, 60):
        br = base_range.get_base_range(base)
        ranges = [FieldSize(br[0], br[0] + 10**9)]
        k, periods = engine._pick_stride_depth(base, ranges)
        modulus = (base - 1) * base**k
        assert pe.STRIDED_PERIODS * modulus < 1 << 32
        assert periods * modulus < 1 << 32


def test_strided_kernel_counts_match_host_at_k2():
    """The device kernel mirrors the host scan on a DEEP (k=2) table too."""
    base = 40
    plan = get_plan(base)
    table = stride_filter.get_stride_table(base, 2)
    spec = pe.StrideSpec(table.modulus, tuple(table.valid_residues))
    br = base_range.get_base_range(base)
    periods = 2
    span = periods * spec.modulus
    lo = br[0] + 11
    hi = lo + span + 5_000  # ragged: partial second descriptor
    rows = []
    n0 = (lo // spec.modulus) * spec.modulus
    while n0 < hi:
        rows.append((n0, lo, hi))
        n0 += span
    desc = np.zeros((len(rows), 12), dtype=np.uint32)
    for i, (n0_, lo_, hi_) in enumerate(rows):
        desc[i, 0:4] = int_to_limbs(n0_, 4)
        desc[i, 4:8] = int_to_limbs(lo_, 4)
        desc[i, 8:12] = int_to_limbs(hi_, 4)
    counts = np.asarray(
        pe.niceonly_strided_batch(plan, spec, desc, periods=periods)
    ).reshape(-1)
    for i, (n0_, lo_, hi_) in enumerate(rows):
        want = len(
            table.iterate_range(
                FieldSize(max(lo_, n0_), min(hi_, n0_ + span)), base
            )
        )
        assert counts[i] == want, (i, counts[i], want)
