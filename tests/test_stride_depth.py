"""CRT stride-depth selection (the TPU re-design of the reference's fused
low-digit GPU prefilter, nice_kernels.cu:329-383 / client_process_gpu.rs:407-450)
and its soundness contract."""

import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, pallas_engine as pe, scalar, stride_filter
from nice_tpu.ops.limbs import get_plan, int_to_limbs


@pytest.mark.parametrize("base", [10, 40])
@pytest.mark.parametrize("k", [1, 2, 3])
def test_deeper_tables_never_reject_a_nice_number(base, k):
    """Soundness mirror (ref client_process_gpu.rs:1289-1324): every nice
    number is a stride candidate at EVERY depth k."""
    table = stride_filter.get_stride_table(base, k)
    br = base_range.get_base_range(base)
    rng = FieldSize(br[0], min(br[1], br[0] + 30_000))
    nice = scalar.process_range_niceonly(rng, base).nice_numbers
    if base == 10:
        assert [n.number for n in nice] == [69]
    residues = set(table.valid_residues)
    for n in nice:
        assert n.number % table.modulus in residues, (k, n.number)


@pytest.mark.parametrize("base", [30, 40, 50])
def test_deeper_tables_are_sparser(base):
    d = [
        stride_filter.get_stride_table(base, k).num_residues
        / ((base - 1) * base**k)
        for k in (1, 2, 3)
    ]
    assert d[0] >= d[1] >= d[2]


def test_pick_depth_narrow_ranges_stay_shallow():
    # Typical surviving range far narrower than the k=2 modulus: deeper k
    # would waste masked lanes, so the gate keeps k=1.
    k, periods = engine._pick_stride_depth(40, 4_000)
    assert k == 1
    assert 1 <= periods <= pe.STRIDED_PERIODS_MAX
    assert periods & (periods - 1) == 0  # po2: shapes survive floor drift


def test_pick_depth_wide_ranges_go_deeper():
    # Only when ranges dwarf the deep spans does the density gain beat the
    # tail-padding waste (the reference's measured-win gate, which compiled
    # its prefilter out at b42+ where survival made it a loss).
    width = 500_000_000
    k, periods = engine._pick_stride_depth(40, width)
    assert k > 1
    span = periods * (39 * 40**k)
    assert span <= width

    k1, _ = engine._pick_stride_depth(40, 4_000)
    assert k1 == 1  # padding waste > density gain at narrow widths


def test_pick_depth_deterministic_per_floor():
    # The compiled kernel shape is a pure function of (base, typical): a
    # benchmark warm-up field at the same floor compiles the exact kernel
    # the timed field will run (no recompile inside the timed region).
    for base in (40, 50):
        typ = (1 << 20) * 3 // 2
        assert engine._pick_stride_depth(base, typ) == engine._pick_stride_depth(
            base, typ
        )


def test_pick_depth_respects_contracts():
    for base in (40, 50, 60):
        for typ in (10**6, 10**9):
            k, periods = engine._pick_stride_depth(base, typ)
            modulus = (base - 1) * base**k
            assert periods * modulus < 1 << 32  # u32 offset arithmetic
            num_res = stride_filter.stride_residue_count(base, k)
            assert periods * num_res <= pe.STRIDED_OFFS_LANES_MAX  # VMEM


def test_stride_residue_count_matches_table():
    # CRT product == materialized table size (the planner scores depths with
    # the product and must agree with the table it ultimately builds).
    for base, k in [(10, 1), (10, 3), (40, 1), (40, 2), (50, 2)]:
        assert (
            stride_filter.stride_residue_count(base, k)
            == stride_filter.get_stride_table(base, k).num_residues
        )


def test_strided_kernel_counts_match_host_at_k2():
    """The device kernel mirrors the host scan on a DEEP (k=2) table too."""
    base = 40
    plan = get_plan(base)
    table = stride_filter.get_stride_table(base, 2)
    spec = pe.StrideSpec(table.modulus, tuple(table.valid_residues))
    br = base_range.get_base_range(base)
    periods = 2
    span = periods * spec.modulus
    lo = br[0] + 11
    hi = lo + span + 5_000  # ragged: partial second descriptor
    rows = []
    n0 = (lo // spec.modulus) * spec.modulus
    while n0 < hi:
        rows.append((n0, lo, hi))
        n0 += span
    desc = np.zeros((len(rows), 12), dtype=np.uint32)
    for i, (n0_, lo_, hi_) in enumerate(rows):
        desc[i, 0:4] = int_to_limbs(n0_, 4)
        desc[i, 4:8] = int_to_limbs(lo_, 4)
        desc[i, 8:12] = int_to_limbs(hi_, 4)
    counts = np.asarray(
        pe.niceonly_strided_batch(plan, spec, desc, periods=periods)
    ).reshape(-1)
    for i, (n0_, lo_, hi_) in enumerate(rows):
        want = len(
            table.iterate_range(
                FieldSize(max(lo_, n0_), min(hi_, n0_ + span)), base
            )
        )
        assert counts[i] == want, (i, counts[i], want)


def test_pick_depth_skips_over_budget_residue_tables():
    # Advisor finding (round 3): base 73 at typical = 1.5 * FLOOR_MAX used to
    # pick k=3 whose residue table ALONE (~4M lanes) exceeds the offsets-VMEM
    # budget, deterministically tripping the kernel-build assert. The planner
    # must skip depths whose num_res exceeds the budget at periods=1.
    from nice_tpu.ops import adaptive_floor as af

    typ = af.FLOOR_MAX + af.FLOOR_MAX // 2
    for base in range(30, 97):
        if stride_filter.stride_residue_count(base, 1) == 0:
            continue
        k, periods = engine._pick_stride_depth(base, typ)
        num_res = stride_filter.stride_residue_count(base, k)
        if num_res == 0:
            continue
        assert periods * num_res <= pe.STRIDED_OFFS_LANES_MAX, (base, k)
