"""Critical-path attribution tests: waterfall composition + overlap
subtraction, reconciliation verdicts, the stepprof phase fold, the fleet
rollup, and the engine's bottleneck-shift detection."""

from datetime import timedelta, timezone

import pytest

from nice_tpu.obs import critpath
from nice_tpu.server.db import now_utc, ts

T0 = now_utc().replace(microsecond=0, tzinfo=timezone.utc)


def _evt(kind, offset_secs, field_id=1, **detail):
    return {
        "field_id": field_id,
        "kind": kind,
        "ts": ts(T0 + timedelta(seconds=offset_secs)),
        "detail": detail or None,
    }


def _canon_timeline(**overrides):
    """A well-formed 6 s end-to-end timeline whose segments account for
    (nearly) the whole wall clock. Layout:

      t=0  generated
      t=2  claimed        (writer_wait 0.5 stamped at the actor)
           client_claim_rtt 1.0   (contains the 0.5 writer wait)
      t=5  submit_accepted (writer_wait 0.8)
           client_submit_rtt 1.2  (contains the 0.8 writer wait)
           client_phases: device_compute 1.5, h2d_feed 0.2
      t=6  canon_promoted
    """
    vals = {
        "claim_writer_wait": 0.5,
        "submit_writer_wait": 0.8,
        "claim_rtt": 1.0,
        "submit_rtt": 1.2,
        "device_compute": 1.5,
        "h2d_feed": 0.2,
    }
    vals.update(overrides)
    return [
        _evt("generated", 0),
        _evt("claimed", 2, writer_wait=vals["claim_writer_wait"]),
        _evt("client_claim_rtt", 2, secs=vals["claim_rtt"]),
        _evt("submit_accepted", 5, writer_wait=vals["submit_writer_wait"]),
        _evt("client_submit_rtt", 5, secs=vals["submit_rtt"]),
        _evt("client_phases", 5,
             device_compute=vals["device_compute"],
             h2d_feed=vals["h2d_feed"]),
        _evt("canon_promoted", 6),
    ]


def test_waterfall_none_without_canon():
    events = [_evt("generated", 0), _evt("claimed", 1)]
    assert critpath.field_waterfall(events) is None
    assert critpath.field_waterfall([]) is None


def test_waterfall_overlap_subtraction_and_reconciliation():
    w = critpath.field_waterfall(_canon_timeline(), tolerance_frac=0.15)
    assert w is not None
    seg = w["segments"]
    # queue_wait: generated->claimed is 2 s, minus the in-flight claim
    # round-trip overlap max(claim_rtt=1.0, w_claim=0.5) = 1.0.
    assert seg["queue_wait"] == pytest.approx(1.0)
    # Client RTTs shed the writer waits they contain; the waits live in
    # writer_wait (measured at the actor).
    assert seg["claim_rtt"] == pytest.approx(0.5)
    assert seg["submit_rtt"] == pytest.approx(0.4)
    assert seg["writer_wait"] == pytest.approx(1.3)
    assert seg["canon_promotion"] == pytest.approx(1.0)
    assert seg["device_compute"] == pytest.approx(1.5)
    assert seg["h2d_feed"] == pytest.approx(0.2)
    # wall 6.0 vs accounted 5.9 -> 0.1 residual, inside
    # max(MIN_TOLERANCE_SECS, 0.15 * 6.0) = 0.9.
    assert w["wall_secs"] == pytest.approx(6.0)
    assert seg["unaccounted"] == pytest.approx(0.1)
    assert w["reconciled"] is True
    assert w["dominant"] == "device_compute"


def test_waterfall_writer_stall_dominates():
    # An injected writer stall shows up in the actor-measured waits, not
    # as inflated round-trips: the RTTs that contain it are clamped to 0.
    w = critpath.field_waterfall(
        _canon_timeline(
            claim_writer_wait=1.4, submit_writer_wait=1.6,
            claim_rtt=1.5, submit_rtt=1.7,
        ),
        tolerance_frac=0.15,
    )
    seg = w["segments"]
    assert seg["writer_wait"] == pytest.approx(3.0)
    assert seg["claim_rtt"] == pytest.approx(0.1)
    assert seg["submit_rtt"] == pytest.approx(0.1)
    assert w["dominant"] == "writer_wait"


def test_waterfall_overcounted_segments_fail_reconciliation():
    # A claim RTT wildly exceeding the wall clock drives the residual
    # negative past tolerance: flagged, never hidden (unaccounted stays 0,
    # the signed residual carries the evidence).
    w = critpath.field_waterfall(
        _canon_timeline(claim_rtt=30.0), tolerance_frac=0.15
    )
    assert w["segments"]["unaccounted"] == 0.0
    assert w["residual_secs"] < -1.0
    assert w["reconciled"] is False


def test_phase_shares_folds_stepprof_buckets():
    prof = {
        "detailed|b10|cpu": {
            "wall": 10.0, "device_compute": 4.0, "compile": 1.0,
            "h2d_feed": 2.0, "fold": 0.5, "readback": 0.5,
        },
        "junk": "not-a-dict",
    }
    out = critpath.phase_shares(prof)
    assert out["wall_secs"] == pytest.approx(10.0)
    # compile folds into device_compute, fold into readback.
    assert out["shares"]["device_compute"] == pytest.approx(0.5)
    assert out["shares"]["readback"] == pytest.approx(0.1)
    assert out["shares"]["h2d_feed"] == pytest.approx(0.2)
    assert out["shares"]["unaccounted"] == pytest.approx(0.2)
    assert out["dominant"] == "device_compute"
    assert critpath.phase_shares({}) is None
    assert critpath.phase_shares({"m": {"wall": 0.0}}) is None


def test_aggregate_rollup_shares_and_unreconciled():
    good = critpath.field_waterfall(_canon_timeline(), tolerance_frac=0.15)
    bad = critpath.field_waterfall(
        [dict(e, field_id=2) for e in _canon_timeline(claim_rtt=30.0)],
        tolerance_frac=0.15,
    )
    agg = critpath.aggregate([good, bad])
    assert agg["fields"] == 2
    assert agg["total_wall_secs"] == pytest.approx(12.0)
    assert agg["unreconciled_fields"] == [2]
    shares = {s: agg["segments"][s]["share"] for s in critpath.SEGMENTS}
    assert sum(shares.values()) > 0
    # The overcounted claim_rtt dominates the pooled wall.
    assert agg["dominant"] == "claim_rtt"
    assert agg["segments"]["claim_rtt"]["p95"] >= \
        agg["segments"]["claim_rtt"]["p50"]


class _FakeWriter:
    def __init__(self):
        self._busy = [(0.0, 0.0), (8.0, 10.0)]
        self._i = 0

    def busy_stats(self):
        stats = self._busy[min(self._i, len(self._busy) - 1)]
        self._i += 1
        return stats


class _FakeDb:
    def __init__(self):
        self.timelines = {}

    def get_recent_canon_fields(self, limit):
        return sorted(self.timelines)[:limit]

    def get_field_timeline(self, fid):
        return self.timelines[fid]

    def get_fleet_phase_totals(self, active_secs=900.0):
        return {"wall": 10.0, "device_compute": 4.0, "compile": 1.0,
                "h2d_feed": 2.0}


def test_engine_detects_bottleneck_shift():
    db = _FakeDb()
    events = []
    eng = critpath.CritpathEngine(
        db, writer=_FakeWriter(),
        on_event=lambda kind, payload: events.append((kind, payload)),
    )
    # Round 1: device_compute dominates. First evaluation establishes the
    # baseline — no shift event yet.
    db.timelines[1] = _canon_timeline()
    assert eng.evaluate() is None
    assert events == []

    # Round 2: the writer stalls; dominance flips to writer_wait.
    db.timelines[1] = _canon_timeline(
        claim_writer_wait=1.4, submit_writer_wait=1.6,
        claim_rtt=1.5, submit_rtt=1.7,
    )
    shift = eng.evaluate()
    assert shift is not None
    assert shift["previous"] == "device_compute"
    assert shift["dominant"] == "writer_wait"
    assert "writer_wait" in shift["moved_segments"]
    assert events and events[0][0] == "critpath"
    # Utilization: busy fraction diffs consecutive samples (8/10), device
    # busy folds compile into compute (5/10), feed idle 2/10.
    snap = eng.snapshot(max_age_secs=0.0)
    assert snap["utilization"]["writer_busy"] == pytest.approx(0.8)
    assert snap["utilization"]["device_busy"] == pytest.approx(0.5)
    assert snap["utilization"]["feed_idle"] == pytest.approx(0.2)


def test_engine_disabled_by_knob(monkeypatch):
    monkeypatch.setenv("NICE_TPU_CRITPATH", "0")
    eng = critpath.CritpathEngine(_FakeDb())
    assert eng.evaluate() is None
