"""Untrusted-client hardening: trust tiers, seeded spot verification,
micro-field leases, per-client rate limiting, and needs-consensus gating.

Each server test boots a real server (writer actor on, queue prefill off so
claim order is deterministic) with the hardening knobs set via env, drives
it with the real client API, and then audits the sqlite ledger directly.
"""

import json
import sqlite3
import threading
import urllib.error
import urllib.request
from contextlib import contextmanager
from datetime import datetime, timedelta, timezone

import pytest

from nice_tpu import CLIENT_VERSION
from nice_tpu.client import api_client
from nice_tpu.client.main import compile_results, process_field
from nice_tpu.core import consensus, distribution_stats, number_stats
from nice_tpu.core.types import (
    DataToServer,
    FieldRecord,
    NiceNumberSimple,
    SearchMode,
    SubmissionRecord,
    UniquesDistributionSimple,
)
from nice_tpu.obs.series import (
    SERVER_CONSENSUS_HOLDS,
    SERVER_LEASES_EXPIRED,
    SERVER_SPOT_CHECKS,
)
from nice_tpu.ops import scalar
from nice_tpu.server import app as server_app
from nice_tpu.server import trust
from nice_tpu.server.db import Db


@contextmanager
def _serve(tmp_path, monkeypatch, env=None, field_size=5, bases=(10,)):
    for key, value in (env or {}).items():
        monkeypatch.setenv(key, value)
    db_path = str(tmp_path / "nice-trust.db")
    db = Db(db_path)
    for base in bases:
        db.seed_base(base, field_size=field_size)
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=False)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", db_path
    finally:
        srv.shutdown()
        api_client.close_connections()


def _query(db_path, sql, params=()):
    conn = sqlite3.connect(db_path)
    conn.row_factory = sqlite3.Row
    try:
        return conn.execute(sql, params).fetchall()
    finally:
        conn.close()


def _empty_niceonly(claim_id, username):
    payload = DataToServer(
        claim_id=claim_id,
        username=username,
        client_version=CLIENT_VERSION,
        unique_distribution=None,
        nice_numbers=[],
    )
    payload.submit_id = f"{claim_id}-forged"
    return payload


# -- pure trust math ---------------------------------------------------------


def test_sample_rate_is_inverse_trust_with_floor(monkeypatch):
    monkeypatch.setenv("NICE_TPU_SPOT_RATE", "0.01")
    assert trust.sample_rate(0) == 1.0
    assert trust.sample_rate(1) == 0.5
    assert abs(trust.sample_rate(99) - 0.01) < 1e-9
    assert trust.sample_rate(10_000) == 0.01  # floored, never zero
    monkeypatch.setenv("NICE_TPU_SPOT_RATE", "0.25")
    assert trust.sample_rate(10_000) == 0.25


def test_submission_rng_is_deterministic(monkeypatch):
    monkeypatch.setenv("NICE_TPU_SPOT_SEED", "42")
    a = [trust.submission_rng("claim-7").random() for _ in range(4)]
    b = [trust.submission_rng("claim-7").random() for _ in range(4)]
    assert a == b
    assert trust.submission_rng("claim-8").random() != a[0]
    monkeypatch.setenv("NICE_TPU_SPOT_SEED", "43")
    assert trust.submission_rng("claim-7").random() != a[0]


def test_resolve_token_priority():
    headers = {"X-Client-Token": "anon-abc"}
    payload = {"telemetry": {"client_id": "cli-123"}}
    assert trust.resolve_token(payload, headers, "u", "1.2.3.4") == "anon-abc"
    assert trust.resolve_token(payload, {}, "u", "1.2.3.4") == "cli-123"
    assert trust.resolve_token({}, {}, "u", "1.2.3.4") == "u@1.2.3.4"
    assert trust.resolve_token({}, None, "", "") == "anon@unknown"


def test_resolve_token_requires_server_known_token():
    class _Store:
        def known(self, token):
            return token == "anon-minted"

    headers = {"X-Client-Token": "anon-minted"}
    payload = {"telemetry": {"client_id": "cli-123"}}
    store = _Store()
    # A server-minted token is honored as the trust identity...
    assert (
        trust.resolve_token(payload, headers, "u", "1.2.3.4", store=store)
        == "anon-minted"
    )
    # ...but an invented bearer string is not: identity falls back to the
    # telemetry client_id (then username@ip), so fresh tokens cannot reset
    # per-client claim caps, rate buckets, or the trust ledger.
    forged = {"X-Client-Token": "anon-i-made-this-up"}
    assert (
        trust.resolve_token(payload, forged, "u", "1.2.3.4", store=store)
        == "cli-123"
    )
    assert (
        trust.resolve_token({}, forged, "u", "1.2.3.4", store=store)
        == "u@1.2.3.4"
    )


def test_spot_seed_is_secret_by_default(monkeypatch):
    monkeypatch.delenv("NICE_TPU_SPOT_SEED", raising=False)
    seed = trust.spot_seed()
    # The submit key is client-chosen, so a predictable seed would make the
    # sampled slice precomputable: unset, the seed is a per-process secret
    # (stable within the process so replays stay deterministic).
    assert seed == trust.spot_seed()
    assert len(seed) == 32
    assert seed != "0"
    monkeypatch.setenv("NICE_TPU_SPOT_SEED", "7")
    assert trust.spot_seed() == "7"  # explicit test override still wins


def test_spot_check_catches_forged_niceonly(monkeypatch):
    # 69 is the only 100% nice number in base 10; a slice covering it must
    # find it in the claimed numbers.
    monkeypatch.setenv("NICE_TPU_SPOT_SLICE", "64")
    rng = trust.submission_rng("claim-1")
    ok, detail = trust.spot_check(10, 67, 72, None, [], rng)
    assert not ok
    assert "69" in detail
    # The honest claim passes the same seeded slice.
    from nice_tpu.core import number_stats
    from nice_tpu.core.types import NiceNumberSimple

    honest = number_stats.expand_numbers([NiceNumberSimple(69, 10)], 10)
    rng = trust.submission_rng("claim-1")
    ok, _ = trust.spot_check(10, 67, 72, None, honest, rng)
    assert ok
    # A fabricated uniques count on a claimed number is caught by the
    # recompute loop regardless of where the slice lands.
    fake = number_stats.expand_numbers([NiceNumberSimple(50, 10)], 10)
    rng = trust.submission_rng("claim-1")
    ok, detail = trust.spot_check(10, 47, 52, None, fake, rng)
    assert not ok and "50" in detail


def test_consensus_holds_lone_untrusted_submission():
    field = FieldRecord(
        field_id=1, base=10, chunk_id=None, range_start=47, range_end=100,
        range_size=53, last_claim_time=None, canon_submission_id=None,
        check_level=0, prioritize=False,
    )

    class _Sub:
        def __init__(self, sid):
            self.submission_id = sid
            self.submit_time = datetime(2026, 1, 1, tzinfo=timezone.utc)

    lone = _Sub(11)
    # Legacy behavior: one submission promotes straight to CL2.
    canon, cl = consensus.evaluate_consensus(field, [lone])
    assert canon is lone and cl == 2
    # Untrusted: the same lone submission is held at needs-consensus.
    canon, cl = consensus.evaluate_consensus(field, [lone], frozenset({11}))
    assert canon is None and cl == 1


def _detailed_sub(sub_id, token, when):
    return SubmissionRecord(
        submission_id=sub_id,
        claim_id=sub_id,
        field_id=1,
        search_mode=SearchMode.DETAILED,
        submit_time=when,
        elapsed_secs=1.0,
        username=f"user{sub_id}",
        user_ip="127.0.0.1",
        client_version=CLIENT_VERSION,
        disqualified=False,
        distribution=distribution_stats.expand_distribution(
            [
                UniquesDistributionSimple(num_uniques=i, count=c)
                for i, c in [(1, 50), (2, 50)]
            ],
            10,
        ),
        numbers=number_stats.expand_numbers(
            [NiceNumberSimple(number=69, num_uniques=10)], 10
        ),
        client_token=token,
    )


def test_consensus_same_token_duplicates_do_not_corroborate():
    field = FieldRecord(
        field_id=1, base=10, chunk_id=None, range_start=47, range_end=100,
        range_size=53, last_claim_time=None, canon_submission_id=None,
        check_level=0, prioritize=False,
    )
    t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
    dup_a = _detailed_sub(11, "mallory", t0)
    dup_b = _detailed_sub(12, "mallory", t0 + timedelta(seconds=5))
    # One untrusted client re-claiming its own released field and
    # re-submitting identical content is NOT corroboration: the winning
    # group holds two rows but one distinct client, so the field stays at
    # needs-consensus instead of promoting canon.
    canon, cl = consensus.evaluate_consensus(
        field, [dup_a, dup_b], frozenset({11, 12})
    )
    assert canon is None and cl == 1
    # A second, independent untrusted client with agreeing content IS
    # corroboration — and check_level counts distinct vouchers, not rows.
    other = _detailed_sub(13, "ivan", t0 + timedelta(seconds=9))
    canon, cl = consensus.evaluate_consensus(
        field, [dup_a, dup_b, other], frozenset({11, 12, 13})
    )
    assert canon is dup_a and cl == 3
    # Trusted-only groups keep the reference row-count semantics.
    canon, cl = consensus.evaluate_consensus(field, [dup_a, dup_b])
    assert canon is dup_a and cl == 3


# -- end-to-end: forged results, trust ledger, requeue -----------------------


def test_forged_submissions_slashed_disqualified_requeued(
    tmp_path, monkeypatch
):
    env = {"NICE_TPU_SPOT_RATE": "1.0", "NICE_TPU_SPOT_SEED": "0"}
    with _serve(tmp_path, monkeypatch, env) as (base_url, db_path):
        block_id, fields = api_client.claim_block_from_server(
            SearchMode.NICEONLY, base_url, "forgy", count=11, max_retries=0
        )
        assert len(fields) == 11
        # Which fields actually hold a 100% nice number (base 10: just 69)?
        bad_ranges = {
            (f.range_start, f.range_end)
            for f in fields
            if any(
                scalar.get_num_unique_digits(x, 10) == 10
                for x in range(f.range_start, f.range_end)
            )
        }
        assert bad_ranges  # the seeded range contains 69
        subs = [_empty_niceonly(f.claim_id, "forgy") for f in fields]
        resp = api_client.submit_block_to_server(
            base_url, block_id, subs, max_retries=0
        )
        assert resp["accepted"] == 11  # accept is still the honor system

        # ... but the spot check caught every forged field post-accept:
        # submission disqualified, trust slashed + suspect, field requeued.
        disq = _query(
            db_path,
            "SELECT c.field_id AS fid FROM submissions s JOIN claims c"
            " ON s.claim_id = c.id WHERE s.disqualified = 1",
        )
        assert len(disq) == len(bad_ranges)
        trust_row = _query(
            db_path,
            "SELECT * FROM client_trust WHERE client_token = ?",
            ("forgy@127.0.0.1",),
        )[0]
        assert trust_row["suspect"] == 1
        assert trust_row["spot_checks_failed"] == len(bad_ranges)
        assert trust_row["submissions_accepted"] == 11
        requeued = _query(
            db_path,
            "SELECT check_level, last_claim_time FROM fields WHERE id IN"
            " (SELECT c.field_id FROM submissions s JOIN claims c"
            "  ON s.claim_id = c.id WHERE s.disqualified = 1)",
        )
        for row in requeued:
            assert row["check_level"] == 0
            assert row["last_claim_time"] is None

        # The forged fields are claimable again and an honest client
        # completes them.
        spot_before = dict(SERVER_SPOT_CHECKS.values())
        for _ in bad_ranges:
            data = api_client.get_field_from_server(
                SearchMode.NICEONLY, base_url, "honest", max_retries=0
            )
            results, _ = process_field(
                data, SearchMode.NICEONLY, "scalar", 1024
            )
            sub = compile_results(data, results, SearchMode.NICEONLY, "honest")
            api_client.submit_field_to_server(base_url, sub, max_retries=0)
        spot_after = dict(SERVER_SPOT_CHECKS.values())
        assert (
            spot_after[("pass",)] - spot_before.get(("pass",), 0)
            >= len(bad_ranges)
        )
        clean = _query(
            db_path,
            "SELECT COUNT(*) AS n FROM submissions s JOIN claims c"
            " ON s.claim_id = c.id WHERE s.disqualified = 0"
            " AND s.username = 'honest'",
        )
        assert clean[0]["n"] == len(bad_ranges)


def test_needs_consensus_gate_promotes_on_agreement(tmp_path, monkeypatch):
    env = {
        "NICE_TPU_TRUST_THRESHOLD": "5",
        "NICE_TPU_SPOT_RATE": "1.0",
    }
    # One field covers the whole base, so both clients scan the same range.
    with _serve(tmp_path, monkeypatch, env, field_size=60) as (
        base_url, db_path,
    ):
        holds_before = SERVER_CONSENSUS_HOLDS.value()
        data = api_client.get_field_from_server(
            SearchMode.DETAILED, base_url, "alice", max_retries=0
        )
        results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
        sub_a = compile_results(data, results, SearchMode.DETAILED, "alice")
        api_client.submit_field_to_server(base_url, sub_a, max_retries=0)
        # An untrusted client alone never makes canon: held at CL1 with the
        # lease cleared so an independent client re-claims immediately (the
        # field-queue refill may already have vacuumed the released field
        # back into claim inventory, so the lease stamp itself is racy to
        # assert — the re-claim below is the real contract).
        row = _query(
            db_path,
            "SELECT check_level, canon_submission_id FROM fields",
        )[0]
        assert row["check_level"] == 1
        assert row["canon_submission_id"] is None
        assert SERVER_CONSENSUS_HOLDS.value() > holds_before

        data_b = api_client.get_field_from_server(
            SearchMode.DETAILED, base_url, "bob", max_retries=0
        )
        assert (data_b.range_start, data_b.range_end) == (
            data.range_start, data.range_end,
        )
        results_b, _ = process_field(
            data_b, SearchMode.DETAILED, "scalar", 1024
        )
        sub_b = compile_results(data_b, results_b, SearchMode.DETAILED, "bob")
        api_client.submit_field_to_server(base_url, sub_b, max_retries=0)
        # Two independent agreeing submissions -> streaming consensus
        # promotes canon without waiting for the jobs runner.
        row = _query(
            db_path,
            "SELECT check_level, canon_submission_id FROM fields",
        )[0]
        assert row["check_level"] == 3
        assert row["canon_submission_id"] is not None


def test_untrusted_claim_cap_and_block_clamp(tmp_path, monkeypatch):
    env = {
        "NICE_TPU_TRUST_THRESHOLD": "5",
        "NICE_TPU_UNTRUSTED_MAX_CLAIMS": "2",
        "NICE_TPU_SPOT_SLICE": "0",  # not under test here
    }
    with _serve(tmp_path, monkeypatch, env) as (base_url, _):
        for _ in range(2):
            api_client.get_field_from_server(
                SearchMode.NICEONLY, base_url, "hoarder", max_retries=0
            )
        with pytest.raises(api_client.ApiError) as err:
            api_client.get_field_from_server(
                SearchMode.NICEONLY, base_url, "hoarder", max_retries=0
            )
        assert err.value.status == 429
        # A block claim from a fresh untrusted client is clamped to the cap,
        # not rejected.
        _, fields = api_client.claim_block_from_server(
            SearchMode.NICEONLY, base_url, "hoarder2", count=8, max_retries=0
        )
        assert len(fields) == 2


def test_untrusted_claims_carry_micro_lease(tmp_path, monkeypatch):
    env = {
        "NICE_TPU_TRUST_THRESHOLD": "5",
        "NICE_TPU_UNTRUSTED_LEASE_SECS": "90",
        "NICE_TPU_SPOT_SLICE": "0",
    }
    with _serve(tmp_path, monkeypatch, env) as (base_url, db_path):
        api_client.get_field_from_server(
            SearchMode.NICEONLY, base_url, "micro", max_retries=0
        )
        row = _query(
            db_path, "SELECT lease_secs, lease_expiry FROM claims"
        )[0]
        assert row["lease_secs"] == 90
        assert row["lease_expiry"] is not None


# -- end-to-end: lease expiry lifecycle under the writer actor ---------------


def test_lease_expiry_sweep_reissue_and_late_submit_conflict(
    tmp_path, monkeypatch
):
    env = {
        "NICE_TPU_TRUST_THRESHOLD": "5",
        "NICE_TPU_UNTRUSTED_LEASE_SECS": "0.5",
        "NICE_TPU_LEASE_SWEEP_SECS": "0.1",
        "NICE_TPU_SPOT_RATE": "1.0",
    }
    # One field covers the whole base so the re-issue is unambiguous.
    with _serve(tmp_path, monkeypatch, env, field_size=60) as (
        base_url, db_path,
    ):
        expired_before = SERVER_LEASES_EXPIRED.value()
        data = api_client.get_field_from_server(
            SearchMode.NICEONLY, base_url, "abandoner", max_retries=0
        )
        # The abandoner walks away. The writer-actor sweep releases the
        # field once the 0.5s micro-lease expires.
        deadline = datetime.now(timezone.utc) + timedelta(seconds=10)
        while (
            SERVER_LEASES_EXPIRED.value() == expired_before
            and datetime.now(timezone.utc) < deadline
        ):
            threading.Event().wait(0.05)
        assert SERVER_LEASES_EXPIRED.value() > expired_before, (
            "sweep never released the abandoned lease"
        )

        # The field is re-issued to a second client, who completes it.
        data_b = api_client.get_field_from_server(
            SearchMode.NICEONLY, base_url, "rescuer", max_retries=0
        )
        assert (data_b.range_start, data_b.range_end) == (
            data.range_start, data.range_end,
        )
        results, _ = process_field(data_b, SearchMode.NICEONLY, "scalar", 1024)
        sub_b = compile_results(data_b, results, SearchMode.NICEONLY, "rescuer")
        api_client.submit_field_to_server(base_url, sub_b, max_retries=0)

        # The abandoner's zombie submit on the expired, re-issued lease is
        # rejected with 409 — accepting both would double-count the range.
        with pytest.raises(api_client.ApiError) as err:
            api_client.submit_field_to_server(
                base_url, _empty_niceonly(data.claim_id, "abandoner"),
                max_retries=0,
            )
        assert err.value.status == 409
        rows = _query(
            db_path,
            "SELECT username, disqualified FROM submissions",
        )
        assert [(r["username"], r["disqualified"]) for r in rows] == [
            ("rescuer", 0)
        ]


# -- end-to-end: per-client rate limiting ------------------------------------


def _mint_token(base_url):
    req = urllib.request.Request(f"{base_url}/token", method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())["client_token"]


def _claim_with_token(base_url, token):
    req = urllib.request.Request(
        f"{base_url}/claim/niceonly?username=u",
        headers={"X-Client-Token": token},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status


def test_rate_limit_flood_gets_429_honest_token_unaffected(
    tmp_path, monkeypatch
):
    env = {"NICE_TPU_RATE_BUCKET": "3:0.5", "NICE_TPU_SPOT_SLICE": "0"}
    with _serve(tmp_path, monkeypatch, env) as (base_url, _):
        # Budgets are keyed ip|token for server-minted tokens only; mint
        # both identities up front (minting itself spends from the shared
        # bare-IP bucket).
        flooder = _mint_token(base_url)
        honest = _mint_token(base_url)
        for _ in range(3):
            assert _claim_with_token(base_url, flooder) == 200
        with pytest.raises(urllib.error.HTTPError) as err:
            _claim_with_token(base_url, flooder)
        assert err.value.code == 429
        assert int(err.value.headers["Retry-After"]) >= 1
        body = json.loads(err.value.read())
        assert body["error"]["code"] == 429
        # Budgets are per minted token: an honest client is unaffected by
        # the flood, and read endpoints have their own (4x) bucket.
        assert _claim_with_token(base_url, honest) == 200
        with urllib.request.urlopen(f"{base_url}/status", timeout=10) as r:
            assert r.status == 200


def test_rate_limit_unknown_tokens_share_the_ip_bucket(tmp_path, monkeypatch):
    env = {"NICE_TPU_RATE_BUCKET": "3:0.5", "NICE_TPU_SPOT_SLICE": "0"}
    with _serve(tmp_path, monkeypatch, env) as (base_url, _):
        # Invented bearer strings are not separate limiter identities: they
        # all drain the one bare-IP bucket, so cycling fresh tokens per
        # request does not reset the limiter.
        for i in range(3):
            assert _claim_with_token(base_url, f"made-up-{i}") == 200
        with pytest.raises(urllib.error.HTTPError) as err:
            _claim_with_token(base_url, "made-up-fresh")
        assert err.value.code == 429


def test_client_retry_honors_429_retry_after(tmp_path, monkeypatch):
    env = {"NICE_TPU_RATE_BUCKET": "1:2", "NICE_TPU_SPOT_SLICE": "0"}
    with _serve(tmp_path, monkeypatch, env) as (base_url, _):
        # Drain the single-token burst, then let retry_request ride the 429
        # + Retry-After to success (a 429 backs off like a 5xx, it does not
        # raise like other 4xx).
        api_client.get_field_from_server(
            SearchMode.NICEONLY, base_url, "u", max_retries=0
        )
        data = api_client.get_field_from_server(
            SearchMode.NICEONLY, base_url, "u", max_retries=3
        )
        assert data.claim_id > 0


def test_anonymous_token_endpoint(tmp_path, monkeypatch):
    with _serve(tmp_path, monkeypatch, {}) as (base_url, db_path):
        token = _mint_token(base_url)
        assert token.startswith("anon-")
        assert len(token) > 20
        # Minting REGISTERS the token: a client_trust row exists, so the
        # server honors it as an identity (resolve_token only accepts
        # tokens it knows).
        rows = _query(
            db_path,
            "SELECT trust, suspect FROM client_trust WHERE client_token = ?",
            (token,),
        )
        assert len(rows) == 1 and rows[0]["suspect"] == 0


def test_per_ip_claim_ceiling_across_identities(tmp_path, monkeypatch):
    env = {
        "NICE_TPU_TRUST_THRESHOLD": "5",
        "NICE_TPU_UNTRUSTED_MAX_CLAIMS": "2",
        "NICE_TPU_UNTRUSTED_MAX_CLAIMS_PER_IP": "3",
        "NICE_TPU_SPOT_SLICE": "0",
    }
    with _serve(tmp_path, monkeypatch, env) as (base_url, _):
        # Two minted identities, each under the per-client cap, from one
        # address...
        sybil_a = _mint_token(base_url)
        sybil_b = _mint_token(base_url)
        assert _claim_with_token(base_url, sybil_a) == 200
        assert _claim_with_token(base_url, sybil_a) == 200
        assert _claim_with_token(base_url, sybil_b) == 200
        # ...reach the aggregate per-address ceiling: a THIRD fresh identity
        # is refused even though its own outstanding-claim count is zero.
        # Without the ceiling, minting identities would multiply the cap.
        sybil_c = _mint_token(base_url)
        with pytest.raises(urllib.error.HTTPError) as err:
            _claim_with_token(base_url, sybil_c)
        assert err.value.code == 429
        assert "address" in json.loads(err.value.read())["error"]["message"]


def test_release_orphaned_inventory_frees_dead_queue_stamps(tmp_path):
    """A SIGKILLed server's queue inventory is lease stamps with no claims
    rows; the startup sweep must free exactly those — fields actually issued
    to a client (claims row at the stamp) and long-running renewed claims
    (old claim_time, live lease) stay leased. Renewed LEGACY claims (NULL
    lease_expiry, pre-trust servers) stay leased while their claim_time is
    inside the global expiry window, and are freed once it is not."""
    from nice_tpu.core.types import FieldClaimStrategy
    from nice_tpu.server.db import now_utc, ts

    db = Db(str(tmp_path / "orphan.db"))
    try:
        db.seed_base(10, field_size=5)
        cutoff = db.claim_expiry_cutoff()

        # Dead server's inventory: bulk-claim stamps, no claims rows.
        inventory = db.bulk_claim_fields(2, cutoff, 0, (1 << 128) - 1)
        assert len(inventory) == 2

        # Properly issued field: claims row minted with the stamp.
        issued = db.try_claim_field(
            FieldClaimStrategy.NEXT, cutoff, 0, (1 << 128) - 1
        )
        db.insert_claim(
            issued.field_id, SearchMode.NICEONLY, "1.2.3.4",
            client_token="tok", lease_secs=3600.0,
        )

        # Renewed long-runner: claim_time far behind the field stamp, but
        # the lease is live and unsubmitted.
        renewed = db.try_claim_field(
            FieldClaimStrategy.NEXT, cutoff, 0, (1 << 128) - 1
        )
        claim = db.insert_claim(
            renewed.field_id, SearchMode.NICEONLY, "1.2.3.4",
            client_token="tok", lease_secs=3600.0,
        )
        with db._lock, db._txn():
            db._conn.execute(
                "UPDATE claims SET claim_time = ? WHERE id = ?",
                ("2000-01-01T00:00:00.000000Z", claim.claim_id),
            )
        db.renew_claim(claim.claim_id)

        # Renewed LEGACY long-runner: NULL lease_expiry (minted by a
        # pre-trust server), claim_time pushed outside the 2s stamp window
        # by a later renewal but still inside the global expiry window —
        # this is a LIVE lease, not an orphan.
        legacy = db.try_claim_field(
            FieldClaimStrategy.NEXT, cutoff, 0, (1 << 128) - 1
        )
        legacy_claim = db.insert_claim(
            legacy.field_id, SearchMode.NICEONLY, "1.2.3.4",
            client_token="tok",
        )
        with db._lock, db._txn():
            db._conn.execute(
                "UPDATE claims SET claim_time = ? WHERE id = ?",
                (
                    ts(now_utc() - timedelta(seconds=60)),
                    legacy_claim.claim_id,
                ),
            )
        db.renew_claim(legacy_claim.claim_id)

        # Renewed legacy claim whose claim_time fell OUT of the expiry
        # window: truly expired, so its field is freed.
        stale = db.try_claim_field(
            FieldClaimStrategy.NEXT, cutoff, 0, (1 << 128) - 1
        )
        stale_claim = db.insert_claim(
            stale.field_id, SearchMode.NICEONLY, "1.2.3.4",
            client_token="tok",
        )
        with db._lock, db._txn():
            db._conn.execute(
                "UPDATE claims SET claim_time = ? WHERE id = ?",
                ("2000-01-01T00:00:00.000000Z", stale_claim.claim_id),
            )
        db.renew_claim(stale_claim.claim_id)

        released = db.release_orphaned_inventory()
        assert released == 3
        rows = _query(
            db.path,
            "SELECT id, last_claim_time FROM fields WHERE id IN"
            " (?,?,?,?,?,?)",
            [f.field_id for f in inventory]
            + [
                issued.field_id, renewed.field_id, legacy.field_id,
                stale.field_id,
            ],
        )
        state = {r["id"]: r["last_claim_time"] for r in rows}
        for f in inventory:
            assert state[f.field_id] is None
        assert state[issued.field_id] is not None
        assert state[renewed.field_id] is not None
        assert state[legacy.field_id] is not None
        assert state[stale.field_id] is None
        # Idempotent: a second sweep finds nothing.
        assert db.release_orphaned_inventory() == 0
    finally:
        db.close()
