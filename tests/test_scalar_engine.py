"""Golden-value tests for the scalar oracle, transcribed from the reference
(client_process.rs:474-1168)."""

import pytest

from nice_tpu.core import base_range, number_stats
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import scalar
from nice_tpu.ops.stride_filter import StrideTable

# Nonzero histogram buckets from the reference goldens.
GOLDEN_B10 = {4: 4, 5: 5, 6: 15, 7: 20, 8: 7, 9: 1, 10: 1}
GOLDEN_B40_10K = {
    15: 1, 16: 2, 17: 15, 18: 68, 19: 190, 20: 423, 21: 959, 22: 1615,
    23: 1995, 24: 1982, 25: 1438, 26: 825, 27: 349, 28: 110, 29: 26, 30: 2,
}
GOLDEN_B80_10K = {
    36: 1, 37: 6, 38: 14, 39: 62, 40: 122, 41: 263, 42: 492, 43: 830,
    44: 1170, 45: 1392, 46: 1477, 47: 1427, 48: 1145, 49: 745, 50: 462,
    51: 242, 52: 88, 53: 35, 54: 19, 55: 7, 56: 1,
}


def expected_distribution(base, golden):
    return tuple(
        (i, golden.get(i, 0)) for i in range(1, base + 1)
    )


def as_tuples(distribution):
    return tuple((d.num_uniques, d.count) for d in distribution)


def test_get_num_unique_digits_69():
    # 69^2 = 4761, 69^3 = 328509: all ten digits exactly once.
    assert scalar.get_num_unique_digits(69, 10) == 10
    assert scalar.get_is_nice(69, 10)
    assert not scalar.get_is_nice(68, 10)


def test_near_miss_cutoff_f32_semantics():
    # f32(10) * f32(0.9) rounds to exactly 9.0 -> floor 9 (not 8).
    assert number_stats.get_near_miss_cutoff(10) == 9
    assert number_stats.get_near_miss_cutoff(40) == 36
    assert number_stats.get_near_miss_cutoff(50) == 45
    assert number_stats.get_near_miss_cutoff(80) == 72


def test_process_detailed_b10():
    br = base_range.get_base_range_field(10)
    res = scalar.process_range_detailed(br, 10)
    assert as_tuples(res.distribution) == expected_distribution(10, GOLDEN_B10)
    assert [(n.number, n.num_uniques) for n in res.nice_numbers] == [(69, 10)]


def test_process_detailed_b40_10k():
    br = base_range.get_base_range_field(40)
    rng = FieldSize(br.start(), br.start() + 10_000)
    res = scalar.process_range_detailed(rng, 40)
    assert as_tuples(res.distribution) == expected_distribution(40, GOLDEN_B40_10K)
    assert res.nice_numbers == ()


def test_process_detailed_b80_10k():
    br = base_range.get_base_range_field(80)
    rng = FieldSize(br.start(), br.start() + 10_000)
    res = scalar.process_range_detailed(rng, 80)
    assert as_tuples(res.distribution) == expected_distribution(80, GOLDEN_B80_10K)
    assert res.nice_numbers == ()


def test_process_niceonly_b10():
    br = base_range.get_base_range_field(10)
    res = scalar.process_range_niceonly(br, 10, StrideTable(10, 1))
    assert res.distribution == ()
    assert [(n.number, n.num_uniques) for n in res.nice_numbers] == [(69, 10)]


@pytest.mark.parametrize("base", [40, 80])
def test_process_niceonly_10k_empty(base):
    br = base_range.get_base_range_field(base)
    rng = FieldSize(br.start(), br.start() + 10_000)
    res = scalar.process_range_niceonly(rng, base, StrideTable(base, 1))
    assert res.nice_numbers == ()


def test_niceonly_chunked_consistency():
    # Processing [47, 147) must still find 69 (out-of-base-range tail included;
    # reference client_process.rs:1152-1168).
    res = scalar.process_range_niceonly(FieldSize(47, 147), 10, StrideTable(10, 1))
    assert any(n.number == 69 for n in res.nice_numbers)


def test_niceonly_matches_detailed_bruteforce_b20():
    """Differential: niceonly cascade vs brute-force detailed scan on a slice
    of base 20."""
    br = base_range.get_base_range_field(20)
    rng = FieldSize(br.start(), br.start() + 4_000)
    detailed = scalar.process_range_detailed(rng, 20)
    nice_from_detailed = sorted(
        n.number for n in detailed.nice_numbers if n.num_uniques == 20
    )
    niceonly = scalar.process_range_niceonly(rng, 20, StrideTable(20, 1))
    assert sorted(n.number for n in niceonly.nice_numbers) == nice_from_detailed
