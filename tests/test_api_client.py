"""Retry/backoff policy unit tests for the client HTTP transport
(reference client_api_sync.rs:37-89: 2^attempt backoff, 5xx/network
retryable, 4xx fail-fast)."""

import email.message
import io
import urllib.error

import pytest

from nice_tpu.client import api_client


def _http_error(code, body=b""):
    return urllib.error.HTTPError(
        "http://x/", code, "err", hdrs=None, fp=io.BytesIO(body)
    )


def test_4xx_fails_fast_with_server_detail(monkeypatch):
    calls = []

    def fake(url, body=None, timeout=None):
        calls.append(url)
        raise _http_error(422, b"bad distribution")

    monkeypatch.setattr(api_client, "_request_json", fake)
    with pytest.raises(api_client.ApiError, match="422.*bad distribution"):
        api_client.retry_request("http://x/submit", max_retries=5)
    assert len(calls) == 1  # no retries on client error


def test_5xx_retries_with_full_jitter_backoff(monkeypatch):
    delays = []
    monkeypatch.setattr(api_client.time, "sleep", delays.append)
    attempts = [0]

    def fake(url, body=None, timeout=None):
        attempts[0] += 1
        if attempts[0] <= 3:
            raise _http_error(503)
        return {"ok": True}

    monkeypatch.setattr(api_client, "_request_json", fake)
    api_client._backoff_rng.seed(1234)
    assert api_client.retry_request("http://x/claim", max_retries=5) == {"ok": True}
    # Full jitter: each delay uniform in [0, min(2^attempt, cap)).
    assert len(delays) == 3
    for attempt, delay in enumerate(delays):
        assert 0 <= delay <= min(2**attempt, api_client.MAX_BACKOFF_SECS)
    # Same seed, same sequence: the jitter source is deterministic on demand.
    api_client._backoff_rng.seed(1234)
    expected = [
        api_client._backoff_rng.uniform(0, 2**a) for a in range(3)
    ]
    assert delays == expected


def test_network_error_exhausts_retries(monkeypatch):
    monkeypatch.setattr(api_client.time, "sleep", lambda s: None)

    def fake(url, body=None, timeout=None):
        raise urllib.error.URLError("connection refused")

    monkeypatch.setattr(api_client, "_request_json", fake)
    with pytest.raises(api_client.ApiError, match="after 2 retries"):
        api_client.retry_request("http://x/claim", max_retries=2)


def test_backoff_is_capped(monkeypatch):
    delays = []
    monkeypatch.setattr(api_client.time, "sleep", delays.append)

    def fake(url, body=None, timeout=None):
        raise _http_error(500)

    monkeypatch.setattr(api_client, "_request_json", fake)
    with pytest.raises(api_client.ApiError):
        api_client.retry_request("http://x/", max_retries=12)
    # 2^11 > 512: every jittered draw stays inside the cap window.
    assert len(delays) == 12
    assert max(delays) <= api_client.MAX_BACKOFF_SECS


def test_retry_after_header_overrides_backoff(monkeypatch):
    delays = []
    monkeypatch.setattr(api_client.time, "sleep", delays.append)
    attempts = [0]

    def fake(url, body=None, timeout=None):
        attempts[0] += 1
        if attempts[0] == 1:
            hdrs = email.message.Message()
            hdrs["Retry-After"] = "7"
            raise urllib.error.HTTPError(
                "http://x/", 503, "overloaded", hdrs, io.BytesIO(b"")
            )
        return {"ok": True}

    monkeypatch.setattr(api_client, "_request_json", fake)
    assert api_client.retry_request("http://x/claim", max_retries=3) == {"ok": True}
    assert delays == [7.0]  # the server's hint, not the jittered window
