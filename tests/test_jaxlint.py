"""jaxlint tests: the interval interpreter proves/flags the right shapes of
arithmetic (including the carry-save wrap-check idiom and a headroom-
violating carry-save variant), every J-rule has a good/bad fixture pair, the
shared ratchet baseline splits cleanly between the nicelint and jaxlint
families, and the repo tree itself is jaxlint-clean."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
JAXLINT = os.path.join(REPO, "scripts", "jaxlint.py")

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nice_tpu.analysis import core, kernelspec  # noqa: E402
from nice_tpu.analysis.jaxrules import (  # noqa: E402
    interval, j1_dtype_flow, j3_donation, j4_transfer, j5_recompile,
    j6_kernelspec, tracer,
)

U32 = (0, 2**32 - 1)


# ---------------------------------------------------------------------------
# fixture plumbing

def project(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content), encoding="utf-8")
    return core.Project(str(tmp_path))


def sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def toy_spec(name="vector_engine.toy_batch", out_shapes=None,
             casts=kernelspec.CASTS_DEFAULT, max_const_elems=1 << 16):
    return kernelspec.KernelSpec(
        name=name, module="nice_tpu/ops/vector_engine.py", backend="jnp",
        kind="stats", sweep="full", build=None,
        out_shapes=out_shapes or (lambda plan, batch: ()),
        allowed_casts=casts, max_const_elems=max_const_elems,
    )


def toy_trace(fn, args, arg_bounds=None, donate=(), spec=None, base=40):
    target = kernelspec.TraceTarget(fn, tuple(args), dict(arg_bounds or {}),
                                    donate=tuple(donate))
    closed = jax.make_jaxpr(fn)(*args)
    return tracer.Trace(spec or toy_spec(), base, 256, 0, target, closed,
                        0.0)


def toy_ctx(*traces):
    ctx = tracer.TraceContext(REPO)
    ctx.traces.extend(traces)
    return ctx


def run_interval(fn, args, bounds, ref_bound=None):
    closed = jax.make_jaxpr(fn)(*args)
    interp = interval.IntervalInterpreter(ref_bound=ref_bound)
    interp.run(closed, bounds)
    return interp


# ---------------------------------------------------------------------------
# interval interpreter (the J2 engine)

def test_interval_proves_bounded_add():
    it = run_interval(lambda a, b: a + b,
                      (sds((8,), jnp.uint32), sds((8,), jnp.uint32)),
                      {0: (0, 1000), 1: (0, 1000)})
    assert it.obligations == []
    assert it.stats.proven >= 1


def test_interval_flags_unchecked_full_range_add():
    it = run_interval(lambda a, b: a + b,
                      (sds((8,), jnp.uint32), sds((8,), jnp.uint32)),
                      {0: U32, 1: U32})
    assert len(it.obligations) == 1
    assert it.obligations[0].prim == "add"
    assert it.obligations[0].math_range[1] > 2**32 - 1


def test_wrap_check_idiom_discharges_the_add():
    # the carry-save idiom: s = a + b; wrap = s < b recovers the 2**32 bit
    def f(a, b):
        s = a + b
        return s, (s < b)

    it = run_interval(f, (sds((8,), jnp.uint32), sds((8,), jnp.uint32)),
                      {0: U32, 1: U32})
    assert it.obligations == []
    assert it.stats.checked == 1


def test_headroom_violating_carry_save_variant_is_flagged():
    # a carry-save column summed WITHOUT its resolve step: each product of
    # 16-bit halves fits u32, but the unresolved column sum does not
    def bad_column(a, b, c, d):
        return a * b + c * d

    it = run_interval(
        bad_column, tuple(sds((8,), jnp.uint32) for _ in range(4)),
        {i: (0, 2**16 - 1) for i in range(4)})
    assert len(it.obligations) == 1
    assert it.obligations[0].prim == "add"


def test_divmod_peephole_through_floor_divide_wrapper():
    # x // c traces as pjit[floor_divide]; the remainder peephole must see
    # through the wrapper (digit extraction does this tens of times per limb)
    def digit(x):
        q = x // np.uint32(40)
        return x - q * np.uint32(40)

    it = run_interval(digit, (sds((8,), jnp.uint32),), {0: U32})
    assert it.obligations == []
    assert it.stats.rem_peephole == 1


def test_mul_has_no_wrap_idiom_and_must_be_proven():
    def f(a, b):
        p = a * b
        return p, (p < b)  # comparing a mul is NOT the carry idiom

    it = run_interval(f, (sds((8,), jnp.uint32), sds((8,), jnp.uint32)),
                      {0: U32, 1: U32})
    assert [ob.prim for ob in it.obligations] == ["mul"]


def test_scatter_add_headroom_is_add_aware():
    def hist(acc, idx, upd):
        return acc.at[idx].add(upd)

    args = (sds((8,), jnp.int32), sds((4,), jnp.int32),
            sds((4,), jnp.int32))
    ok = run_interval(hist, args, {0: (0, 1 << 30), 1: (0, 7), 2: (0, 1)})
    assert ok.obligations == []
    # near-saturated accumulator: 4 updates of 10 can push past i32 max
    bad = run_interval(hist, args,
                       {0: (0, 2**31 - 5), 1: (0, 7), 2: (0, 10)})
    assert [ob.prim for ob in bad.obligations] == ["scatter-add"]


def test_dot_general_declared_bound_discharges():
    # The MXU limb-multiply contraction: naive interval (n * max-product)
    # blows i32, but the digit-split theorem (mxu.accum_bound, declared via
    # TraceTarget.dot_bound) proves the accumulator fits. Without the
    # declared bound the dot is an obligation; with it, proven — a declared
    # bound, not a baseline allow.
    def contract(toe, h):
        return jax.lax.dot_general(
            toe, h, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

    args = (sds((16, 8), jnp.int32), sds((8,), jnp.int32))
    # The naive rule multiplies range maxima by the contraction depth and
    # cannot see the digit-split pairing, so these bounds overflow i32:
    wide = {0: (0, 2**20), 1: (0, 2**20)}
    flagged = run_interval(contract, args, wide)
    assert [ob.prim for ob in flagged.obligations] == ["dot_general"]

    closed = jax.make_jaxpr(contract)(*args)
    interp = interval.IntervalInterpreter(
        dot_bound=(0, 2 * 8 * 255 * 65535)
    )
    interp.run(closed, wide)
    assert interp.obligations == []


# ---------------------------------------------------------------------------
# J1: dtype flow

def test_j1_flags_undeclared_cast(tmp_path):
    tr = toy_trace(lambda a: a.astype(jnp.float32).sum(),
                   (sds((8,), jnp.uint32),))
    vs = j1_dtype_flow.check(core.Project(str(tmp_path)), toy_ctx(tr))
    assert len(vs) == 1 and "float32" in vs[0].message
    assert vs[0].detail.startswith("cast:uint32->float32")


def test_j1_declared_casts_are_clean(tmp_path):
    tr = toy_trace(lambda a: (a > 0).astype(jnp.int32),
                   (sds((8,), jnp.uint32),))
    assert j1_dtype_flow.check(core.Project(str(tmp_path)),
                               toy_ctx(tr)) == []


# ---------------------------------------------------------------------------
# J3: donation discipline

def _step(acc, x):
    return acc + x, x.sum()


def test_j3_traced_donation_present_is_clean():
    fn = jax.jit(_step, donate_argnums=(0,))
    tr = toy_trace(fn, (sds((8,), jnp.int32), sds((8,), jnp.int32)),
                   donate=(0,))
    assert j3_donation._check_traces(toy_ctx(tr)) == []


def test_j3_dropped_donation_is_flagged():
    fn = jax.jit(_step)  # donate_argnums lost in a refactor
    tr = toy_trace(fn, (sds((8,), jnp.int32), sds((8,), jnp.int32)),
                   donate=(0,))
    vs = j3_donation._check_traces(toy_ctx(tr))
    assert len(vs) == 1 and "donation-dropped:arg0" in vs[0].detail


READ_AFTER_DONATE = """
    from nice_tpu.ops.pallas_engine import _detailed_accum_callable

    def loop(plan, items):
        step = _detailed_accum_callable(plan, 256, 128, 0)
        acc = make_acc()
        for item in items:
            out = step(acc, item.starts, item.valids)
            total = acc.sum()  # acc was donated: this buffer is dead
            acc = out[0]
        return acc, total
"""

CLEAN_DONATE = """
    from nice_tpu.ops.pallas_engine import _detailed_accum_callable

    def loop(plan, items):
        step = _detailed_accum_callable(plan, 256, 128, 0)
        acc = make_acc()
        for item in items:
            acc, nm = step(acc, item.starts, item.valids)
        return acc
"""


def test_j3_read_after_donate_call_site(tmp_path):
    vs = j3_donation._check_call_sites(
        project(tmp_path, {"nice_tpu/ops/engine2.py": READ_AFTER_DONATE}))
    assert len(vs) == 1
    assert "read-after-donate" in vs[0].detail and "acc" in vs[0].detail


def test_j3_rebind_at_call_statement_is_clean(tmp_path):
    assert j3_donation._check_call_sites(
        project(tmp_path, {"nice_tpu/ops/engine2.py": CLEAN_DONATE})) == []


# ---------------------------------------------------------------------------
# J4: transfer purity

def test_j4_flags_host_callback(tmp_path):
    def f(x):
        jax.debug.print("x = {}", x)
        return x + 1

    tr = toy_trace(f, (sds((8,), jnp.int32),))
    vs = j4_transfer.check(core.Project(str(tmp_path)), toy_ctx(tr))
    assert len(vs) == 1 and "callback" in vs[0].detail


def test_j4_pure_plan_is_clean(tmp_path):
    tr = toy_trace(lambda x: x * 2 + 1, (sds((8,), jnp.int32),))
    assert j4_transfer.check(core.Project(str(tmp_path)),
                             toy_ctx(tr)) == []


# ---------------------------------------------------------------------------
# J5: recompile surface

ROGUE_JIT = """
    import jax

    @jax.jit
    def rogue_batch(x):
        return x
"""

DECLARED_JIT = """
    import jax

    @jax.jit
    def detailed_batch(x):
        return x
"""


def test_j5_unregistered_jit_site(tmp_path):
    vs = j5_recompile._check_jit_sites(
        project(tmp_path, {"nice_tpu/ops/vector_engine.py": ROGUE_JIT}))
    assert [v.detail for v in vs] == ["unregistered-jit:rogue_batch"]


def test_j5_declared_surface_is_clean(tmp_path):
    assert j5_recompile._check_jit_sites(
        project(tmp_path,
                {"nice_tpu/ops/vector_engine.py": DECLARED_JIT})) == []


def test_j5_burned_arg_detected():
    tr = toy_trace(lambda a: a + 1, (sds((8,), jnp.int32),))
    # the spec claims two dynamic args but the traced plan only has one —
    # the second was burned into the jaxpr as a Python constant
    tr.target = kernelspec.TraceTarget(
        tr.target.fn, tr.target.args + (sds((), jnp.int32),), {})
    vs = j5_recompile._check_burned_args(toy_ctx(tr))
    assert any("burned-arg" in v.detail for v in vs)


def test_j5_giant_closed_over_const():
    big = np.zeros((1 << 17,), dtype=np.int32)

    def f(x):
        return x + jnp.asarray(big)[: x.shape[0]]

    tr = toy_trace(f, (sds((8,), jnp.int32),))
    vs = j5_recompile._check_burned_args(toy_ctx(tr))
    assert any("giant-const" in v.detail for v in vs)


# ---------------------------------------------------------------------------
# J6: KernelSpec registry

def test_j6_public_op_without_spec(tmp_path):
    vs = j6_kernelspec._check_coverage(
        project(tmp_path, {"nice_tpu/ops/vector_engine.py": """
            def rogue_batch(plan, batch):
                return None
        """}))
    assert [v.detail for v in vs] == ["unspecced-op:rogue_batch"]


def test_j6_shape_drift():
    spec = toy_spec(out_shapes=lambda plan, batch: (((8,), "int32"),))
    tr = toy_trace(lambda a: a * 2, (sds((4,), jnp.uint32),), spec=spec)
    vs = j6_kernelspec._check_shapes(toy_ctx(tr))
    assert len(vs) == 1 and "shape-drift" in vs[0].detail


def test_j6_matching_shapes_are_clean():
    spec = toy_spec(out_shapes=lambda plan, batch: (((4,), "uint32"),))
    tr = toy_trace(lambda a: a * 2, (sds((4,), jnp.uint32),), spec=spec)
    assert j6_kernelspec._check_shapes(toy_ctx(tr)) == []


def test_j6_hist_rows_contract_holds_in_tree():
    # pallas_engine._HIST_ROWS_MAX == kernelspec.MAX_HIST_ROWS and
    # supports_base agrees with the contract over the probe sweep
    assert j6_kernelspec._check_hist_rows() == []


# ---------------------------------------------------------------------------
# S1: dead-suppression audit (shared core machinery)

def _dead_audit(proj):
    violations, used = core.run_rules_tracked(proj)
    return core.dead_suppressions(proj, set(core.all_rules()), used)


def test_s1_flags_dead_allow(tmp_path):
    dead = _dead_audit(project(tmp_path, {"nice_tpu/x.py": """
        def f(path):
            # nicelint: allow A1 (nothing here writes anymore)
            return path
    """}))
    assert [d.detail for d in dead] == ["dead:A1:f"]


def test_s1_live_allow_is_not_flagged(tmp_path):
    dead = _dead_audit(project(tmp_path, {"nice_tpu/x.py": """
        def save(path, blob):
            # nicelint: allow A1 (append-only sink)
            with open(path, "w") as f:
                f.write(blob)
    """}))
    assert dead == []


def test_s1_docstring_grammar_mention_is_not_a_marker(tmp_path):
    dead = _dead_audit(project(tmp_path, {"nice_tpu/x.py": '''
        def f():
            """Escape with ``# nicelint: allow A1 (reason)`` on the line."""
            return 1
    '''}))
    assert dead == []


# ---------------------------------------------------------------------------
# shared-baseline family split

def test_filter_baseline_splits_families():
    baseline = {
        "A1|nice_tpu/x.py|open-w": "",
        "J2|nice_tpu/ops/y.py|headroom:add:uint32:f": "",
        "S1|nice_tpu/x.py|dead:A1:f": "",
        "S1|nice_tpu/ops/y.py|dead:J2:g": "",
    }
    nice = core.filter_baseline(baseline, {"A1", "S1"})
    assert set(nice) == {"A1|nice_tpu/x.py|open-w",
                         "S1|nice_tpu/x.py|dead:A1:f"}
    jx = core.filter_baseline(baseline, {"J2", "S1"})
    assert set(jx) == {"J2|nice_tpu/ops/y.py|headroom:add:uint32:f",
                       "S1|nice_tpu/ops/y.py|dead:J2:g"}


# ---------------------------------------------------------------------------
# CLI end-to-end (traces the real kernels at the cheapest base)

def jaxlint(root, *args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, JAXLINT, "--root", str(root), "--bases", "40",
         *args],
        capture_output=True, text=True, cwd=REPO, env=env,
    )


def test_repo_tree_is_jaxlint_clean_strict():
    r = jaxlint(REPO, "--strict")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in r.stdout


def test_jaxlint_ratchet_and_family_preservation(tmp_path):
    project(tmp_path, {"nice_tpu/ops/vector_engine.py": ROGUE_JIT})
    # pre-seed a nicelint-family entry: jaxlint must never touch it
    (tmp_path / "nice_tpu/analysis").mkdir(parents=True)
    (tmp_path / "nice_tpu/analysis/baseline.json").write_text(json.dumps(
        {"entries": {"A1|nice_tpu/x.py|open-w": "keep me"}}))

    r = jaxlint(tmp_path)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "undeclared jit surface" in r.stdout
    assert "has no KernelSpec" in r.stdout

    r = jaxlint(tmp_path, "--update-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    entries = json.loads(
        (tmp_path / "nice_tpu/analysis/baseline.json").read_text()
    )["entries"]
    assert entries["A1|nice_tpu/x.py|open-w"] == "keep me"
    assert any(k.startswith("J5|") for k in entries)

    r = jaxlint(tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in r.stdout
