"""Differential tests: jnp vector engine vs the scalar oracle (the TPU-build
analog of the reference's fixed-width-vs-malachite cross-checks,
fixed_width.rs:259-335 and client_process_gpu.rs:988-1405)."""

import random

import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar
from nice_tpu.ops import vector_engine as ve
from nice_tpu.ops.limbs import get_plan, int_to_limbs, limbs_to_int

def fresh_rng():
    """Per-test deterministic stream: failures reproduce in isolation."""
    return random.Random(421)


def test_limb_packing_roundtrip():
    rng = fresh_rng()
    for bits in (1, 31, 32, 64, 100, 127, 128, 200):
        for _ in range(20):
            x = rng.getrandbits(bits)
            L = (bits + 31) // 32
            assert limbs_to_int(int_to_limbs(x, L)) == x


def test_mul32_exact():
    import jax.numpy as jnp

    rng = fresh_rng()
    cases = [(0, 0), (1, 1), (0xFFFFFFFF, 0xFFFFFFFF), (0x10000, 0x10000)]
    cases += [(rng.getrandbits(32), rng.getrandbits(32)) for _ in range(200)]
    a = jnp.array([c[0] for c in cases], dtype=jnp.uint32)
    b = jnp.array([c[1] for c in cases], dtype=jnp.uint32)
    lo, hi = ve.mul32(a, b)
    lo, hi = np.asarray(lo), np.asarray(hi)
    for i, (x, y) in enumerate(cases):
        p = x * y
        assert int(lo[i]) == p & 0xFFFFFFFF, (x, y)
        assert int(hi[i]) == p >> 32, (x, y)


def test_mul_limbs_exact():
    import jax.numpy as jnp

    rng = fresh_rng()

    for la, lb in [(1, 1), (2, 1), (2, 2), (3, 2), (4, 4)]:
        xs = [rng.getrandbits(32 * la) for _ in range(64)]
        ys = [rng.getrandbits(32 * lb) for _ in range(64)]
        out_len = la + lb
        a = [
            jnp.array([(x >> (32 * i)) & 0xFFFFFFFF for x in xs], dtype=jnp.uint32)
            for i in range(la)
        ]
        b = [
            jnp.array([(y >> (32 * i)) & 0xFFFFFFFF for y in ys], dtype=jnp.uint32)
            for i in range(lb)
        ]
        out = [np.asarray(o) for o in ve.mul_limbs(a, b, out_len)]
        for row in range(64):
            got = sum(int(out[i][row]) << (32 * i) for i in range(out_len))
            assert got == xs[row] * ys[row]
        # truncating variant
        out_t = [np.asarray(o) for o in ve.mul_limbs(a, b, max(1, out_len - 2))]
        for row in range(64):
            got = sum(int(out_t[i][row]) << (32 * i) for i in range(len(out_t)))
            assert got == (xs[row] * ys[row]) % (1 << (32 * len(out_t)))


@pytest.mark.parametrize("base", [10, 17, 40, 44, 50, 62, 80, 97])
def test_uniques_batch_matches_scalar(base):
    """Random in-range candidates: device pipeline == scalar oracle."""
    rng = fresh_rng()
    plan = get_plan(base)
    br = base_range.get_base_range(base)
    span = br[1] - br[0]
    starts = [br[0], max(br[0], br[1] - 257), br[0] + span // 2]
    if span > 256:
        starts += [br[0] + rng.randrange(span - 256) for _ in range(3)]
    for start in starts:
        batch = 256
        got = np.asarray(ve.uniques_batch(plan, batch, int_to_limbs(start, plan.limbs_n)))
        for i in range(batch):
            n = start + i
            if n >= br[1]:
                break
            assert int(got[i]) == scalar.get_num_unique_digits(n, base), (base, n)


def test_detailed_engine_b10_golden():
    br = base_range.get_base_range_field(10)
    got = engine.process_range_detailed(br, 10, backend="jax", batch_size=64)
    want = scalar.process_range_detailed(br, 10)
    assert got == want
    assert [(n.number, n.num_uniques) for n in got.nice_numbers] == [(69, 10)]


@pytest.mark.parametrize("base", [40, 80])
def test_detailed_engine_matches_scalar_10k(base):
    br = base_range.get_base_range_field(base)
    rng_ = FieldSize(br.start(), br.start() + 10_000)
    got = engine.process_range_detailed(rng_, base, backend="jax", batch_size=4096)
    want = scalar.process_range_detailed(rng_, base)
    assert got == want


def test_detailed_engine_near_misses_b17():
    """A b17 slice that contains near misses (6788 and 9278 have 16 uniques);
    the rare-path extraction must reproduce them exactly."""
    rng_ = FieldSize(4913, 9913)
    got = engine.process_range_detailed(rng_, 17, backend="jax", batch_size=2048)
    want = scalar.process_range_detailed(rng_, 17)
    assert got == want
    assert [(n.number, n.num_uniques) for n in want.nice_numbers] == [
        (6788, 16), (9278, 16),
    ]


def test_detailed_engine_out_of_range_fallback():
    """[47, 147) exceeds the b10 range end: scalar fallback handles the tail."""
    got = engine.process_range_detailed(FieldSize(47, 147), 10, backend="jax")
    want = scalar.process_range_detailed(FieldSize(47, 147), 10)
    assert got == want


def test_niceonly_engine_b10():
    br = base_range.get_base_range_field(10)
    got = engine.process_range_niceonly(br, 10, backend="jax", batch_size=64)
    assert [(n.number, n.num_uniques) for n in got.nice_numbers] == [(69, 10)]


def test_niceonly_engine_matches_scalar_b20():
    br = base_range.get_base_range_field(20)
    rng_ = FieldSize(br.start(), br.start() + 30_000)
    got = engine.process_range_niceonly(rng_, 20, backend="jax", batch_size=8192)
    want = scalar.process_range_niceonly(rng_, 20)
    assert sorted(n.number for n in got.nice_numbers) == sorted(
        n.number for n in want.nice_numbers
    )
