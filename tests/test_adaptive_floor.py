"""Adaptive MSD floor controller (ref client_process_gpu.rs:103-184 analog)."""

import pytest

from nice_tpu.ops import adaptive_floor as af


def make(seed=16000):
    return af.AdaptiveFloor(seed=seed)


def test_warmup_skips_adaptation():
    c = make()
    start = c.current()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(10.0, 0.1)
    assert c.current() == start  # warmup fields observed, no movement


def test_moves_toward_balance_and_clamps_step():
    c = make()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    start = c.current()
    c.observe(3.0, 1.0)  # host-dominated -> coarsen, but at most MAX_STEP
    assert c.current() == int(start * af.MAX_STEP)
    c.observe(0.5, 2.0)  # device-dominated -> refine
    assert c.current() < int(start * af.MAX_STEP)


def test_balanced_field_holds_floor():
    c = make()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    start = c.current()
    c.observe(1.0, 1.0)
    assert c.current() == start


def test_bounds():
    c = make(seed=af.FLOOR_MIN)
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    c.observe(0.001, 10.0)  # push down: already at min
    assert c.current() == af.FLOOR_MIN
    c2 = make(seed=af.FLOOR_MAX)
    for _ in range(af.WARMUP_FIELDS):
        c2.observe(1.0, 1.0)
    c2.observe(10.0, 0.001)  # push up: already at max
    assert c2.current() == af.FLOOR_MAX


def test_tiny_fields_ignored():
    c = make()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    start = c.current()
    c.observe(0.0001, 0.0001)  # both phases in the noise
    assert c.current() == start


def test_env_pin_disables_adaptation(monkeypatch):
    monkeypatch.setenv("NICE_TPU_MSD_FLOOR", "12345")
    af.reset_for_tests()
    c = af.get_floor_controller()
    assert c.current() == 12345
    c.observe(100.0, 0.001)
    assert c.current() == 12345
    af.reset_for_tests()


def test_env_invalid_falls_back_to_adaptive(monkeypatch):
    monkeypatch.setenv("NICE_TPU_MSD_FLOOR", "not-a-number")
    af.reset_for_tests()
    c = af.get_floor_controller()
    assert not c.pinned
    assert af.FLOOR_MIN <= c.current() <= af.FLOOR_MAX
    af.reset_for_tests()


@pytest.fixture(autouse=True)
def _clean_singleton():
    af.reset_for_tests()
    yield
    af.reset_for_tests()
