"""Adaptive MSD floor controller (ref client_process_gpu.rs:103-184 analog)."""

import pytest

from nice_tpu.ops import adaptive_floor as af


def make(seed=16000):
    return af.AdaptiveFloor(seed=seed)


def test_warmup_skips_adaptation():
    c = make()
    start = c.current()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(10.0, 0.1)
    assert c.current() == start  # warmup fields observed, no movement


def test_moves_toward_balance_and_clamps_step():
    c = make()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    start = c.current()
    c.observe(3.0, 1.0)  # host-dominated -> coarsen, but at most MAX_STEP
    assert c.current() == int(start * af.MAX_STEP)
    c.observe(0.5, 2.0)  # device-dominated -> refine
    assert c.current() < int(start * af.MAX_STEP)


def test_balanced_field_holds_floor():
    c = make()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    start = c.current()
    c.observe(1.0, 1.0)
    assert c.current() == start


def test_bounds():
    c = make(seed=af.FLOOR_MIN)
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    c.observe(0.001, 10.0)  # push down: already at min
    assert c.current() == af.FLOOR_MIN
    c2 = make(seed=af.FLOOR_MAX)
    for _ in range(af.WARMUP_FIELDS):
        c2.observe(1.0, 1.0)
    c2.observe(10.0, 0.001)  # push up: already at max
    assert c2.current() == af.FLOOR_MAX


def test_tiny_fields_ignored():
    c = make()
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0)
    start = c.current()
    c.observe(0.0001, 0.0001)  # both phases in the noise
    assert c.current() == start


def test_env_pin_disables_adaptation(monkeypatch):
    monkeypatch.setenv("NICE_TPU_MSD_FLOOR", "12345")
    af.reset_for_tests()
    c = af.get_floor_controller()
    assert c.current() == 12345
    c.observe(100.0, 0.001)
    assert c.current() == 12345
    af.reset_for_tests()


def test_env_invalid_falls_back_to_adaptive(monkeypatch):
    monkeypatch.setenv("NICE_TPU_MSD_FLOOR", "not-a-number")
    af.reset_for_tests()
    c = af.get_floor_controller()
    assert not c.pinned
    assert af.FLOOR_MIN <= c.current() <= af.FLOOR_MAX
    af.reset_for_tests()


@pytest.fixture(autouse=True)
def _clean_singleton():
    af.reset_for_tests()
    yield
    af.reset_for_tests()


def test_tiny_fields_carry_no_signal():
    # A 1-number warm-up field whose "device" time is pure kernel compile
    # must neither adapt the floor nor consume a warm-up slot (observed
    # failure: floor drift between warm-up and timed benchmark fields flipped
    # the stride plan and forced a recompile inside the timed region).
    c = make()
    start = c.current()
    for _ in range(10):
        c.observe(0.003, 4.7, numbers=1)
    assert c.current() == start
    assert c._warmup == af.WARMUP_FIELDS  # warm-up slots untouched
    # Signal-bearing fields still adapt after warm-up.
    big = af.SIGNAL_MIN_LEAVES * start * 2
    for _ in range(af.WARMUP_FIELDS):
        c.observe(1.0, 1.0, numbers=big)
    c.observe(3.0, 1.0, numbers=big)
    assert c.current() == int(start * af.MAX_STEP)


def test_signal_gate_scales_with_floor():
    c = make(seed=1 << 20)
    just_below = af.SIGNAL_MIN_LEAVES * c.current() - 1
    for _ in range(af.WARMUP_FIELDS + 1):
        c.observe(5.0, 1.0, numbers=just_below)
    assert c.current() == 1 << 20  # below the leaf gate: ignored


def test_upward_steps_cannot_outrun_the_leaf_gate():
    # Code-review finding (round 4): host-dominated fields must not ratchet
    # the floor past the point where the workload's own field size falls
    # below the leaf gate (a frozen controller with no recovery path).
    c = make(seed=65536)
    size = 4_000_000
    for _ in range(af.WARMUP_FIELDS + 20):
        c.observe(5.0, 1.0, numbers=size)
    assert af.SIGNAL_MIN_LEAVES * c.current() <= size
    # ...and device-dominated fields can still pull it back down.
    before = c.current()
    c.observe(0.01, 5.0, numbers=size)
    assert c.current() < before


def test_strided_floor_guard_scales_with_field_size():
    from nice_tpu.ops import engine

    c = make(seed=1 << 21)
    # Production-sized fields: adaptive floor wins.
    assert engine._strided_floor(c, 10**9) == 1 << 21
    # Huge fields: leaves capped at ~2^21 (massive = 1e13 -> floor ~2^22).
    assert engine._strided_floor(c, 10**13) == 10**13 >> 21
    # Pinned floors are always honored exactly.
    p = af.AdaptiveFloor(pinned=4096)
    assert engine._strided_floor(p, 10**13) == 4096


def test_sub_gate_fields_refine_but_never_coarsen():
    # Code-review finding (round 4): a workload whose fields all fall under
    # the leaf gate (e.g. 5e6-number fields against a coarse seed) must still
    # be able to pull a too-coarse floor DOWN — but may never push it up,
    # and probe-sized fields still carry no signal at all.
    c = make(seed=1 << 19)
    size = 5_000_000  # < 16 * 2^19 = 8.4M: under the gate, but not a probe
    for _ in range(af.WARMUP_FIELDS):
        c.observe(0.1, 3.0, numbers=size)
    start = c.current()
    c.observe(0.1, 3.0, numbers=size)  # device-dominated: refine allowed
    assert c.current() < start
    before = c.current()
    c.observe(5.0, 0.1, numbers=size)  # host-dominated: coarsen blocked
    assert c.current() == before
