"""Audit-journal + anomaly-engine tests: event-row building, the client-side
event buffer and telemetry merge, per-field seq contiguity at the Db layer,
the timeline / events-feed routes, anomaly state transitions (including the
forced stuck-field ok -> page -> ok round trip), and a genuine server
SIGKILL + restart asserting gap-free causally-ordered timelines."""

import json
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from nice_tpu import obs
from nice_tpu.obs import anomaly as anomaly_mod
from nice_tpu.obs import journal
from nice_tpu.server.db import Db, now_utc, ts


# -- event-row building -----------------------------------------------------


def test_event_row_derives_trace_from_claim():
    row = journal.event_row(7, "claimed", claim_id=42, client="tok",
                            tier="trusted", check_level=1, mode="Detailed")
    assert row["field_id"] == 7 and row["kind"] == "claimed"
    # Claim-derived trace id: client and server compute the same value, so
    # both sides' spans join the event.
    assert row["trace_id"] == obs.claim_trace_id(42)
    assert row["detail"]["claim_id"] == 42
    assert row["detail"]["mode"] == "Detailed"
    assert row["client"] == "tok" and row["tier"] == "trusted"
    assert row["check_level"] == 1


def test_event_row_falls_back_to_ambient_trace():
    with obs.trace_context(obs.claim_trace_id(99)):
        row = journal.event_row(1, "queued", queue="niceonly")
    assert row["trace_id"] == obs.claim_trace_id(99)
    assert journal.event_row(1, "queued")["trace_id"] is None


# -- client-side buffer -----------------------------------------------------


def test_client_event_buffer_drains_and_bounds():
    journal.drain_client_events()  # isolate from other tests
    journal.record_client_event("ckpt_save", claim_id=3, cursor="10")
    journal.record_client_event("downgrade", downgrades=["jnp->scalar"])
    events = journal.drain_client_events()
    assert [e["kind"] for e in events] == ["ckpt_save", "downgrade"]
    assert events[0]["claim_id"] == 3
    assert events[0]["detail"]["cursor"] == "10"
    assert journal.drain_client_events() == []
    # Bounded: oldest events drop first.
    for i in range(journal._CLIENT_BUFFER_CAP + 10):
        journal.record_client_event("ckpt_save", claim_id=i)
    events = journal.drain_client_events()
    assert len(events) == journal._CLIENT_BUFFER_CAP
    assert events[0]["claim_id"] == 10  # the first ten dropped


def test_client_event_rows_resolve_claims():
    snap = {"events": [
        {"kind": "ckpt_save", "claim_id": 5, "detail": {"cursor": "1"}},
        {"kind": "spool_replay", "claim_id": 6},   # unresolvable -> skipped
        {"kind": "downgrade"},                     # no claim -> skipped
        "garbage",
    ]}
    rows = journal.client_event_rows(
        snap, client="me@host/1",
        resolve_claim=lambda cid: 77 if cid == 5 else None,
    )
    assert len(rows) == 1
    assert rows[0]["field_id"] == 77
    assert rows[0]["kind"] == "client_ckpt_save"
    assert rows[0]["client"] == "me@host/1"
    assert rows[0]["detail"]["cursor"] == "1"


# -- Db layer ---------------------------------------------------------------


@pytest.fixture()
def db(tmp_path):
    d = Db(str(tmp_path / "journal.db"))
    yield d
    d.close()


def test_seed_base_journals_generated(db):
    db.seed_base(10, field_size=20)  # 3 fields
    for fid in (1, 2, 3):
        events = db.get_field_timeline(fid)
        assert [e["kind"] for e in events] == ["generated"]
        assert events[0]["seq"] == 1
    # Re-seeding must not duplicate the generated events.
    db.seed_base(10, field_size=20)
    assert len(db.get_field_timeline(1)) == 1


def test_append_assigns_contiguous_per_field_seq(db):
    db.seed_base(10, field_size=20)
    db.append_field_events([
        journal.event_row(1, "queued", queue="niceonly"),
        journal.event_row(2, "queued", queue="niceonly"),
        journal.event_row(1, "claimed", claim_id=11),
    ])
    db.append_field_events([journal.event_row(1, "submit_accepted",
                                              claim_id=11)])
    tl1 = db.get_field_timeline(1)
    assert [e["seq"] for e in tl1] == [1, 2, 3, 4]
    assert [e["kind"] for e in tl1] == [
        "generated", "queued", "claimed", "submit_accepted"]
    tl2 = db.get_field_timeline(2)
    assert [(e["seq"], e["kind"]) for e in tl2] == [
        (1, "generated"), (2, "queued")]
    # detail JSON round-trips.
    assert tl1[1]["detail"] == {"queue": "niceonly"}
    assert tl1[2]["detail"]["claim_id"] == 11


def test_events_feed_cursor_pagination(db):
    db.seed_base(10, field_size=20)  # 3 generated events
    db.append_field_events(
        [journal.event_row(1, "queued", queue="niceonly")])
    page1 = db.get_events_since(0, limit=2)
    assert len(page1) == 2
    page2 = db.get_events_since(page1[-1]["id"], limit=100)
    assert len(page2) == 2
    ids = [e["id"] for e in page1 + page2]
    assert ids == sorted(ids) and len(set(ids)) == 4


def test_prune_and_counts(db):
    db.seed_base(10, field_size=20)
    old = "2000-01-01T00:00:00.000000Z"
    db.append_field_events([
        journal.event_row(1, "claimed", claim_id=1, ts=old),
        journal.event_row(1, "lease_expired", ts=old),
        journal.event_row(1, "claimed", claim_id=2),
    ])
    assert db.count_field_events(("claimed", "block_claimed"),
                                 "1999-01-01T00:00:00.000000Z") == 2
    # Window excludes the old events.
    recent = ts(now_utc()).replace("T", "T")[:11] + "00:00:00.000000Z"
    assert db.count_field_events(("lease_expired",), recent) == 0
    # Field 1: two claims ever, no canon_promoted -> stuck at min_claims=2
    # over an all-time window, not stuck once canon lands.
    assert db.count_stuck_fields(2, old) == 1
    db.append_field_events(
        [journal.event_row(1, "canon_promoted", via="consensus")])
    assert db.count_stuck_fields(2, old) == 0
    # Retention pruning drops only the old rows.
    pruned = db.prune_field_events("2001-01-01T00:00:00.000000Z")
    assert pruned == 2
    kinds = [e["kind"] for e in db.get_field_timeline(1)]
    assert "lease_expired" not in kinds and "claimed" in kinds


# -- server integration -----------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture()
def server(tmp_path, monkeypatch):
    from nice_tpu.server import app as server_app

    monkeypatch.setenv("NICE_TPU_HISTORY_SECS", "3600")  # tick manually
    db_path = str(tmp_path / "srv.db")
    d = Db(db_path)
    d.seed_base(10, field_size=20)
    d.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv.context
    srv.shutdown()


def _claim_and_submit(base_url):
    from nice_tpu.client import api_client
    from nice_tpu.client.main import compile_results, process_field
    from nice_tpu.core.types import SearchMode

    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "tester", max_retries=0
    )
    results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
    sub = compile_results(data, results, SearchMode.DETAILED, "tester")
    api_client.submit_field_to_server(base_url, sub, max_retries=0)
    return data


def test_timeline_route_covers_lifecycle(server):
    base_url, ctx = server
    data = _claim_and_submit(base_url)
    # Writer-side events (queued) are async; flush via a blocking write.
    ctx.write(lambda: None)
    field_id = _find_field_id(ctx, data)
    tl = _get(f"{base_url}/fields/{field_id}/timeline")
    assert tl["field_id"] == field_id
    kinds = [e["kind"] for e in tl["events"]]
    assert kinds[0] == "generated"
    assert "claimed" in kinds and "submit_accepted" in kinds
    # Trusted detailed submit promotes straight to canon.
    assert "canon_promoted" in kinds
    assert kinds.index("claimed") < kinds.index("submit_accepted")
    assert kinds.index("submit_accepted") < kinds.index("canon_promoted")
    seqs = [e["seq"] for e in tl["events"]]
    assert seqs == list(range(1, len(seqs) + 1))
    # The claim events carry identity + trace.
    claimed = tl["events"][kinds.index("claimed")]
    assert claimed["tier"] == "trusted"
    assert claimed["trace_id"] == obs.claim_trace_id(data.claim_id)

    # Unknown field -> 404; bad id -> 400.
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(f"{base_url}/fields/999999/timeline")
    assert err.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(f"{base_url}/fields/bogus/timeline")
    assert err.value.code == 400


def _find_field_id(ctx, data):
    for f in ctx.db.get_fields_in_base(10):
        if (f.range_start, f.range_end) == (data.range_start, data.range_end):
            return f.field_id
    raise AssertionError("claimed field not found in base")


def test_events_feed_route_pagination(server):
    base_url, ctx = server
    _claim_and_submit(base_url)
    ctx.write(lambda: None)
    page = _get(f"{base_url}/events?since=0&limit=2")
    assert len(page["events"]) == 2 and page["more"] is True
    assert page["cursor"] == page["events"][-1]["id"]
    rest = _get(f"{base_url}/events?since={page['cursor']}&limit=500")
    ids = [e["id"] for e in page["events"] + rest["events"]]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    # Feed exhausted: cursor echoes back, more is False.
    tail = _get(f"{base_url}/events?since={rest['cursor']}")
    assert tail["events"] == [] and tail["cursor"] == rest["cursor"]
    assert tail["more"] is False


def test_telemetry_merges_client_events(server):
    base_url, ctx = server
    from nice_tpu.client import api_client
    from nice_tpu.core.types import SearchMode

    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "tester", max_retries=0
    )
    _post(f"{base_url}/telemetry", {
        "client_id": "tester@host/1",
        "events": [
            {"kind": "ckpt_save", "claim_id": data.claim_id,
             "detail": {"cursor": "123"}},
            {"kind": "ckpt_save", "claim_id": 999999},  # unresolvable
        ],
    })
    ctx.write(lambda: None)
    field_id = _find_field_id(ctx, data)
    tl = _get(f"{base_url}/fields/{field_id}/timeline")
    merged = [e for e in tl["events"] if e["kind"] == "client_ckpt_save"]
    assert len(merged) == 1
    assert merged[0]["client"] == "tester@host/1"
    assert merged[0]["detail"]["cursor"] == "123"
    assert merged[0]["trace_id"] == obs.claim_trace_id(data.claim_id)


def test_journal_write_failure_never_raises(server):
    _, ctx = server
    from nice_tpu.obs.series import SERVER_JOURNAL_WRITE_FAILURES

    before = SERVER_JOURNAL_WRITE_FAILURES.value()
    ctx.journal_now([{"malformed": True}])  # KeyError inside append
    assert SERVER_JOURNAL_WRITE_FAILURES.value() == before + 1


def test_lease_sweep_journals_expirations(server, monkeypatch):
    base_url, ctx = server
    from nice_tpu.client import api_client
    from nice_tpu.core.types import SearchMode

    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "tester", max_retries=0
    )
    field_id = _find_field_id(ctx, data)
    # Force the lease stale, then sweep.
    with ctx.db._lock, ctx.db._txn():
        ctx.db._conn.execute(
            "UPDATE claims SET claim_time = ?, lease_expiry = ? WHERE id = ?",
            ("2000-01-01T00:00:00.000000Z", "2000-01-01T00:00:00.000000Z",
             data.claim_id),
        )
        ctx.db._conn.execute(
            "UPDATE fields SET last_claim_time = ? WHERE id = ?",
            ("2000-01-01T00:00:00.000000Z", field_id),
        )
    ctx._sweep_leases()
    kinds = [e["kind"] for e in ctx.db.get_field_timeline(field_id)]
    assert "lease_expired" in kinds


# -- anomaly engine ---------------------------------------------------------


def test_detector_threshold_ladder(monkeypatch):
    values = iter([None, 0.0, 5.0, 50.0])
    det = anomaly_mod.AnomalyDetector(
        "testdet", lambda *_a: next(values), warn_at=5, page_at=50)
    states = [det.evaluate(None, 0.0) for _ in range(4)]
    assert [s["state"] for s in states] == ["ok", "ok", "warn", "page"]
    assert states[0]["no_data"] is True


def test_detector_env_overrides(monkeypatch):
    monkeypatch.setenv("NICE_TPU_ANOMALY_TESTDET_WARN", "100")
    monkeypatch.setenv("NICE_TPU_ANOMALY_TESTDET_PAGE", "200")
    det = anomaly_mod.AnomalyDetector(
        "testdet", lambda *_a: 150.0, warn_at=5, page_at=50)
    assert det.warn_at == 100 and det.page_at == 200
    assert det.evaluate(None, 0.0)["state"] == "warn"


def test_engine_records_transitions_and_gauges(tmp_path):
    from nice_tpu.obs.series import ANOMALY_STATE

    d = Db(str(tmp_path / "anom.db"))
    try:
        values = {"v": 0.0}
        det = anomaly_mod.AnomalyDetector(
            "testdet", lambda *_a: values["v"], warn_at=1, page_at=2)
        eng = anomaly_mod.AnomalyEngine(d, None, detectors=[det])
        assert eng.evaluate(now=1.0)[0]["state"] == "ok"
        values["v"] = 5.0
        res = eng.evaluate(now=2.0)
        assert res[0]["state"] == "page"
        assert eng.transitions == 1
        assert ANOMALY_STATE.labels("testdet").value() == 2
        values["v"] = 0.0
        eng.evaluate(now=3.0)
        assert eng.transitions == 2
        assert ANOMALY_STATE.labels("testdet").value() == 0
        assert [r["detector"] for r in eng.last()] == ["testdet"]
    finally:
        d.close()


def test_stuck_field_anomaly_round_trip(server, monkeypatch):
    """The acceptance-criteria path in-process: a field claimed repeatedly
    without canon pages the stuck_fields detector; promotion recovers it."""
    base_url, ctx = server
    from nice_tpu.client import api_client
    from nice_tpu.core.types import SearchMode

    monkeypatch.setenv("NICE_TPU_ANOMALY_STUCK_CLAIMS", "1")
    assert _states(ctx)["stuck_fields"] == "ok"

    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "tester", max_retries=0
    )
    assert _states(ctx)["stuck_fields"] == "page"
    # /status carries the anomaly block.
    status = _get(f"{base_url}/status")
    by_name = {a["detector"]: a for a in status["anomalies"]}
    assert by_name["stuck_fields"]["state"] == "page"

    # Submitting to canon clears the pathology on the next evaluation.
    from nice_tpu.client.main import compile_results, process_field
    results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
    sub = compile_results(data, results, SearchMode.DETAILED, "tester")
    api_client.submit_field_to_server(base_url, sub, max_retries=0)
    assert _states(ctx)["stuck_fields"] == "ok"

    # The ok -> page -> ok transitions landed in the flight ring.
    flips = [
        e for e in obs.flight.snapshot()
        if e["kind"] == "anomaly_transition"
        and e.get("detector") == "stuck_fields"
    ]
    pairs = [(e["from_state"], e["to_state"]) for e in flips]
    assert ("ok", "page") in pairs and ("page", "ok") in pairs


def _states(ctx):
    return {r["detector"]: r["state"] for r in ctx.anomaly.evaluate()}


# -- SIGKILL durability -----------------------------------------------------


def _pick_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_server(db_path, port):
    return subprocess.Popen(
        [sys.executable, "-m", "nice_tpu.server",
         "--db", db_path, "--host", "127.0.0.1", "--port", str(port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _wait_listening(port, proc, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return False
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return True
        except OSError:
            time.sleep(0.05)
    return False


def test_sigkill_leaves_gap_free_timelines(tmp_path):
    """Kill -9 mid-run, restart on the same ledger, keep working: every
    field's timeline stays contiguous (seq 1..N, no gaps) and causally
    ordered across the outage, because lifecycle events commit in the same
    transaction as the state change they describe."""
    db_path = str(tmp_path / "kill.db")
    d = Db(db_path)
    d.seed_base(10, field_size=20)
    d.close()
    port = _pick_port()
    base_url = f"http://127.0.0.1:{port}"

    server = _start_server(db_path, port)
    try:
        assert _wait_listening(port, server), "server never listened"
        first = _claim_and_submit(base_url)
        server.send_signal(signal.SIGKILL)
        server.wait(timeout=30)

        server = _start_server(db_path, port)
        assert _wait_listening(port, server), "restart never listened"
        second = _claim_and_submit(base_url)
    finally:
        server.kill()
        server.wait(timeout=30)

    d = Db(db_path)
    try:
        canon_fields = []
        for f in d.get_fields_in_base(10):
            events = d.get_field_timeline(f.field_id)
            kinds = [e["kind"] for e in events]
            seqs = [e["seq"] for e in events]
            # Gap-free: contiguous per-field sequence from 1.
            assert seqs == list(range(1, len(seqs) + 1)), (
                f"field {f.field_id} has seq gaps: {seqs}")
            assert kinds[0] == "generated"
            if "canon_promoted" in kinds:
                canon_fields.append(f.field_id)
                claim_idx = min(
                    kinds.index(k) for k in ("claimed", "block_claimed")
                    if k in kinds
                )
                assert claim_idx < kinds.index("submit_accepted")
                assert (kinds.index("submit_accepted")
                        < kinds.index("canon_promoted"))
        # Both the pre-kill and post-restart submissions reached canon with
        # full histories.
        assert len(canon_fields) >= 2
        assert first.claim_id != second.claim_id
    finally:
        d.close()
