"""Fleet observability tests: distributed per-field tracing across
client -> server -> engine, telemetry aggregation (POST /telemetry + the
/status fleet block), the crash flight recorder (ring semantics, dumps,
SIGUSR2, quarantine), and the local metrics server's fleet surfaces."""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from nice_tpu import obs
from nice_tpu.client import api_client
from nice_tpu.client.main import compile_results, process_field
from nice_tpu.core.types import SearchMode
from nice_tpu.obs import flight as obs_flight
from nice_tpu.obs import series
from nice_tpu.obs import telemetry as obs_telemetry
from nice_tpu.server import app as server_app
from nice_tpu.server.db import Db


@pytest.fixture()
def server(tmp_path):
    db_path = str(tmp_path / "fleet-test.db")
    db = Db(db_path)
    db.seed_base(10, field_size=20)  # [47,100) -> 3 fields
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    base_url = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base_url, db_path
    srv.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


# --- trace id plumbing -----------------------------------------------------


def test_claim_trace_id_is_deterministic_and_wellformed():
    a = obs.claim_trace_id(42)
    assert a == obs.claim_trace_id(42)  # client and server derive the same id
    assert a != obs.claim_trace_id(43)
    assert len(a) == 32 and int(a, 16) >= 0


def test_traceparent_roundtrip_and_malformed_rejection():
    tid = obs.claim_trace_id(7)
    header = obs.make_traceparent(tid)
    assert obs.parse_traceparent(header) == tid
    for bad in (None, "", "garbage", "00-short-beef-01",
                "00-" + "g" * 32 + "-" + "0" * 16 + "-01"):
        assert obs.parse_traceparent(bad) is None


def test_trace_context_is_thread_local_and_restores():
    assert obs.current_trace_id() is None
    with obs.trace_context("a" * 32):
        assert obs.current_trace_id() == "a" * 32
        assert obs.parse_traceparent(obs.current_traceparent()) == "a" * 32
        seen = []
        t = threading.Thread(target=lambda: seen.append(obs.current_trace_id()))
        t.start()
        t.join()
        assert seen == [None]  # context never leaks across threads
    assert obs.current_trace_id() is None
    assert obs.current_traceparent() is None


def test_one_trace_covers_claim_scan_submit(server, tmp_path, monkeypatch):
    """The acceptance path: one field's lifecycle yields client, engine, and
    server spans that all share the claim-derived trace id."""
    base_url, _ = server
    sink = tmp_path / "trace.jsonl"
    monkeypatch.setenv("NICE_TPU_TRACE", str(sink))
    monkeypatch.setenv("NICE_TPU_SHARD", "0")

    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "tracer", max_retries=0
    )
    tid = obs.claim_trace_id(data.claim_id)
    with obs.trace_context(tid):
        obs.trace_event("client.claim", claim=data.claim_id, base=data.base)
        results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
        submission = compile_results(
            data, results, SearchMode.DETAILED, "tracer"
        )
        api_client.submit_field_to_server(base_url, submission, max_retries=0)
    time.sleep(0.2)  # the server handler span flushes from its own thread

    events = [json.loads(line) for line in sink.read_text().splitlines()]
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)

    # client side: claim event, scan span, submit span
    assert any(e.get("trace_id") == tid for e in by_name["client.claim"])
    assert any(
        e.get("trace_id") == tid for e in by_name["client.process_field"]
    )
    assert any(e.get("trace_id") == tid for e in by_name["client.submit"])
    # engine side: the scan span inherits the ambient context (scalar
    # backend -> the host-scan span; device backends emit engine.detailed)
    assert any(e.get("trace_id") == tid for e in by_name["engine.scalar"])
    # server side: the handler continued the trace from the traceparent header
    assert any(e.get("trace_id") == tid for e in by_name["server.submit"])
    # span ids are present so the tree reconstructs exactly
    ends = [e for e in by_name["client.submit"] if e["event"] == "end"]
    assert ends and ends[0]["span_id"]


# --- telemetry aggregation -------------------------------------------------


def _snap(client_id, backend="jax", numbers=1000, rate=50.0, spool=0):
    return {
        "v": obs_telemetry.SNAPSHOT_VERSION,
        "client_id": client_id,
        "username": client_id.split("@")[0],
        "client_version": "test",
        "backend": backend,
        "ts": time.time(),
        "numbers": numbers,
        "numbers_per_sec": rate,
        "fields": {"detailed": 2, "niceonly": 1},
        "downgrades": {"pallas->jnp": 1},
        "downgrades_total": 1,
        "restores": 2,
        "faults": 3,
        "spool_depth": spool,
    }


def test_telemetry_heartbeat_feeds_fleet_block(server):
    base_url, _ = server
    api_client.post_telemetry(
        base_url, _snap("alice@h1/1", backend="jax", numbers=1000, rate=40.0)
    )
    api_client.post_telemetry(
        base_url, _snap("bob@h2/2", backend="tpu", numbers=500, rate=60.0,
                        spool=2)
    )

    fleet = _get(f"{base_url}/status")["fleet"]
    assert fleet["client_count"] == 2
    ids = {c["client_id"] for c in fleet["clients"]}
    assert ids == {"alice@h1/1", "bob@h2/2"}
    assert fleet["backends"] == {"jax": 1, "tpu": 1}
    assert fleet["numbers_total"] == "1500"
    assert fleet["numbers_per_sec"] == pytest.approx(100.0)
    assert fleet["fields"] == {"detailed": 4, "niceonly": 2}
    assert fleet["downgrades"] == 2
    assert fleet["checkpoint_restores"] == 4
    assert fleet["spool_depth"] == 2
    for key in ("claims_active", "claims_expired_unsubmitted",
                "submissions_total", "slowest_in_flight", "requests",
                "error_responses", "field_seconds_p50", "field_seconds_p95"):
        assert key in fleet

    # building the block refreshed the fleet gauges: /metrics agrees
    with urllib.request.urlopen(f"{base_url}/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "nice_fleet_clients 2" in text
    assert 'nice_fleet_fields_total{mode="detailed"} 4' in text
    assert "nice_fleet_numbers_per_sec 100" in text
    assert 'nice_server_telemetry_reports_total{source="heartbeat"} 2' in text


def test_telemetry_heartbeat_rejects_garbage(server):
    base_url, _ = server
    with pytest.raises(api_client.ApiError) as err:
        api_client.post_telemetry(base_url, {"nope": 1}, max_retries=0)
    assert "400" in str(err.value)


def test_telemetry_upsert_is_one_row_per_client(server):
    base_url, db_path = server
    for n in (100, 250):  # same client reporting twice
        api_client.post_telemetry(base_url, _snap("carol@h/9", numbers=n))
    db = Db(db_path)
    rows = db.get_client_telemetry()
    db.close()
    carol = [r for r in rows if r["client_id"] == "carol@h/9"]
    assert len(carol) == 1
    assert carol[0]["numbers_total"] == "250"  # later report wins
    assert carol[0]["first_seen"] <= carol[0]["last_seen"]


def test_submission_piggybacks_telemetry(server, monkeypatch):
    base_url, _ = server
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "piggy", max_retries=0
    )
    results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
    submission = compile_results(data, results, SearchMode.DETAILED, "piggy")
    # Telemetry is attached AFTER compile_results stamped submit_id, so the
    # snapshot never perturbs the exactly-once content hash.
    submission.telemetry = obs_telemetry.snapshot(
        username="piggy", backend="scalar"
    )
    api_client.submit_field_to_server(base_url, submission, max_retries=0)

    fleet = _get(f"{base_url}/status")["fleet"]
    ids = {c["client_id"] for c in fleet["clients"]}
    assert obs_telemetry.client_id("piggy") in ids
    assert fleet["submissions_total"] >= 1
    # the submission landed its elapsed-seconds sample for the percentiles
    assert fleet["field_seconds_p95"] >= 0.0


def test_snapshot_wire_format_tracks_registry():
    snap = obs_telemetry.snapshot(username="u", backend="jnp", spool_depth=3)
    assert snap["v"] == obs_telemetry.SNAPSHOT_VERSION
    assert snap["client_id"].startswith("u@")
    assert snap["client_id"].endswith(f"/{os.getpid()}")
    assert snap["backend"] == "jnp"
    assert snap["spool_depth"] == 3
    assert snap["numbers"] == int(sum(series.CLIENT_NUMBERS.values().values()))
    json.dumps(snap)  # must be JSON-safe as-is


# --- flight recorder -------------------------------------------------------


def test_flight_ring_is_bounded_and_ordered():
    fr = obs_flight.FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("retry", attempt=i)
    events = fr.snapshot()
    assert len(events) == 4  # bounded: oldest two evicted
    assert [e["attempt"] for e in events] == [2, 3, 4, 5]  # oldest first
    assert [e["seq"] for e in events] == [3, 4, 5, 6]
    assert fr.total_recorded() == 6
    assert all(e["kind"] == "retry" and e["ts"] > 0 for e in events)


def test_flight_dump_atomic_valid_json_and_overwrites(tmp_path, monkeypatch):
    monkeypatch.setenv("NICE_TPU_FLIGHT_DIR", str(tmp_path))
    fr = obs_flight.FlightRecorder(capacity=8)
    fr.record("fault", site="http.submit", action="500")
    path = fr.dump(reason="manual")
    assert path is not None and os.path.basename(path) == (
        f"nice-flight-{os.getpid()}-manual.json"
    )
    payload = json.loads(open(path).read())
    assert payload["reason"] == "manual"
    assert payload["pid"] == os.getpid()
    assert payload["events"][-1]["site"] == "http.submit"
    # same reason overwrites: a crash loop cannot fill the disk
    fr.record("fault", site="http.submit", action="conn_error")
    assert fr.dump(reason="manual") == path
    assert json.loads(open(path).read())["events"][-1]["action"] == "conn_error"
    assert len(list(tmp_path.glob("nice-flight-*"))) == 1


@pytest.mark.skipif(
    not hasattr(signal, "SIGUSR2"), reason="platform has no SIGUSR2"
)
def test_sigusr2_dumps_live_ring(tmp_path, monkeypatch):
    monkeypatch.setenv("NICE_TPU_FLIGHT_DIR", str(tmp_path))
    obs_flight.install()
    obs_flight.record("telemetry", note="pre-signal breadcrumb")
    os.kill(os.getpid(), signal.SIGUSR2)
    path = tmp_path / f"nice-flight-{os.getpid()}-sigusr2.json"
    deadline = time.monotonic() + 5.0
    while not path.exists() and time.monotonic() < deadline:
        time.sleep(0.05)  # handlers run at the next bytecode boundary
    assert path.exists()
    payload = json.loads(path.read_text())
    assert payload["reason"] == "sigusr2"
    assert any(
        e.get("note") == "pre-signal breadcrumb" for e in payload["events"]
    )


def test_spool_quarantine_dumps_ring(tmp_path, monkeypatch):
    from nice_tpu.faults.spool import SubmissionSpool

    monkeypatch.setenv("NICE_TPU_FLIGHT_DIR", str(tmp_path / "dumps"))
    spool = SubmissionSpool(str(tmp_path / "spool"))
    bad = tmp_path / "spool" / "corrupt.json"
    bad.write_text("{ not json")
    counts = spool.replay("http://127.0.0.1:9")  # api never reached
    assert counts["rejected"] == 1
    assert (tmp_path / "spool" / "corrupt.json.rejected").exists()
    dump = tmp_path / "dumps" / f"nice-flight-{os.getpid()}-quarantine.json"
    assert dump.exists()
    payload = json.loads(dump.read_text())
    assert payload["events"][-1]["kind"] == "quarantine"


def test_debug_flight_on_api_server(server):
    base_url, _ = server
    obs_flight.record("telemetry", note="api-ring-probe")
    body = _get(f"{base_url}/debug/flight")
    assert body["pid"] == os.getpid()
    assert body["capacity"] >= 16
    assert body["total_recorded"] >= 1
    assert any(e.get("note") == "api-ring-probe" for e in body["events"])


# --- local metrics server (serve.py satellites) ----------------------------


def test_metrics_server_flight_endpoint_404_and_bound_port():
    srv = obs.serve_metrics(0)
    port = srv.server_address[1]
    try:
        assert series.METRICS_BOUND_PORT.value() == port
        obs_flight.record("telemetry", note="local-ring-probe")
        body = _get(f"http://127.0.0.1:{port}/debug/flight")
        assert any(
            e.get("note") == "local-ring-probe" for e in body["events"]
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5
            )
        assert err.value.code == 404
    finally:
        srv.shutdown()


# --- trace sink rotation ---------------------------------------------------


def test_trace_sink_rotates_at_size_cap(tmp_path, monkeypatch):
    sink = tmp_path / "trace.jsonl"
    monkeypatch.setenv("NICE_TPU_TRACE", str(sink))
    monkeypatch.setenv("NICE_TPU_TRACE_MAX_BYTES", "400")
    for i in range(40):
        obs.trace_event("rotation-probe", i=i)
    backup = tmp_path / "trace.jsonl.1"
    assert backup.exists()  # rotated at the cap, one backup kept
    assert sink.exists() and sink.stat().st_size <= 400
    # every line in both files is still valid JSON (no torn rotation)
    for p in (sink, backup):
        for line in p.read_text().splitlines():
            json.loads(line)
