"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import scalar
from nice_tpu.ops.limbs import get_plan, int_to_limbs
from nice_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def cpu_mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual CPU devices"
    return pmesh.make_mesh(jax.devices()[:8])


def test_sharded_detailed_matches_scalar(cpu_mesh):
    base = 40
    plan = get_plan(base)
    br = base_range.get_base_range(base)
    per_dev = 256
    total = per_dev * 8
    step = pmesh.make_sharded_detailed_step(plan, per_dev, cpu_mesh)
    hist, nm = step(
        np.asarray(int_to_limbs(br[0], plan.limbs_n)), np.int32(total)
    )
    hist = np.asarray(hist)
    want = scalar.process_range_detailed(FieldSize(br[0], br[0] + total), base)
    want_hist = {d.num_uniques: d.count for d in want.distribution}
    for i in range(1, base + 1):
        assert hist[i] == want_hist.get(i, 0), i
    assert hist.sum() == total
    assert int(nm) == len(want.nice_numbers)


def test_sharded_detailed_tail_masking(cpu_mesh):
    base = 40
    plan = get_plan(base)
    br = base_range.get_base_range(base)
    per_dev = 256
    valid = 1000  # not a multiple of anything; tail lanes masked to bin 0
    step = pmesh.make_sharded_detailed_step(plan, per_dev, cpu_mesh)
    hist, _ = step(np.asarray(int_to_limbs(br[0], plan.limbs_n)), np.int32(valid))
    hist = np.asarray(hist)
    assert hist[1:].sum() == valid
    assert hist[0] == per_dev * 8 - valid


def test_sharded_niceonly_finds_69(cpu_mesh):
    base = 10
    plan = get_plan(base)
    per_dev = 8
    step = pmesh.make_sharded_niceonly_step(plan, per_dev, cpu_mesh)
    count = step(np.asarray(int_to_limbs(47, plan.limbs_n)), np.int32(53))
    assert int(count) == 1  # exactly 69


def test_sharded_histogram_replicated(cpu_mesh):
    """psum leaves the full histogram identical on every device."""
    base = 10
    plan = get_plan(base)
    step = pmesh.make_sharded_detailed_step(plan, 8, cpu_mesh)
    hist, nm = step(np.asarray(int_to_limbs(47, plan.limbs_n)), np.int32(53))
    # replicated output: single logical value
    assert np.asarray(hist).shape == (base + 2,)
    assert int(np.asarray(hist)[1:].sum()) == 53
