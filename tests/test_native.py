"""Differential tests: C++ native host engine vs the Python oracle — the
analog of the reference's fixed-width-vs-malachite cross-checks
(fixed_width.rs:259-335, msd_prefix_filter.rs:700-787)."""

import random

import pytest

from nice_tpu import native
from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, msd_filter, scalar, stride_filter

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no toolchain)"
)


def fresh_rng():
    return random.Random(1337)


def sample_points(base, rng, count=40):
    br = base_range.get_base_range(base)
    span = br[1] - br[0]
    pts = [br[0], br[1] - 1]
    pts += [br[0] + rng.randrange(span) for _ in range(count)]
    return pts


@pytest.mark.parametrize("base", [10, 17, 40, 50, 69, 80, 97])
def test_num_unique_digits_matches_scalar(base):
    rng = fresh_rng()
    for n in sample_points(base, rng):
        assert native.num_unique_digits(n, base) == scalar.get_num_unique_digits(
            n, base
        ), (base, n)


@pytest.mark.parametrize("base", [10, 40, 80])
def test_is_nice_matches_scalar(base):
    rng = fresh_rng()
    for n in sample_points(base, rng):
        assert native.is_nice(n, base) == scalar.get_is_nice(n, base), (base, n)
    assert native.is_nice(69, 10)


def test_native_detailed_b10_golden():
    got = engine.process_range_detailed(FieldSize(47, 100), 10, backend="native")
    want = scalar.process_range_detailed(FieldSize(47, 100), 10)
    assert got == want
    assert [(n.number, n.num_uniques) for n in got.nice_numbers] == [(69, 10)]


@pytest.mark.parametrize("base", [40, 80])
def test_native_detailed_matches_scalar_10k(base):
    br = base_range.get_base_range_field(base)
    rng_ = FieldSize(br.start(), br.start() + 10_000)
    got = engine.process_range_detailed(rng_, base, backend="native")
    want = scalar.process_range_detailed(rng_, base)
    assert got == want


def test_native_detailed_near_misses_b17():
    rng_ = FieldSize(4913, 9913)
    got = engine.process_range_detailed(rng_, 17, backend="native")
    want = scalar.process_range_detailed(rng_, 17)
    assert got == want
    assert len(want.nice_numbers) == 2


@pytest.mark.parametrize("base", [10, 17, 40, 62])
def test_msd_prefix_matches_python(base):
    rng = fresh_rng()
    br = base_range.get_base_range(base)
    span = br[1] - br[0]
    for _ in range(60):
        size = rng.choice([2, 5, 251, 1000, 100_000])
        if span <= size:
            continue
        start = br[0] + rng.randrange(span - size)
        fs = FieldSize(start, start + size)
        assert native.has_duplicate_msd_prefix(
            fs.start(), fs.end(), base
        ) == msd_filter.has_duplicate_msd_prefix(fs, base), (base, fs)


@pytest.mark.parametrize("base", [20, 40, 50])
def test_msd_valid_ranges_matches_python(base):
    br = base_range.get_base_range_field(base)
    fs = FieldSize(br.start(), br.start() + 3_000_000)
    got = msd_filter.get_valid_ranges(fs, base)  # native-backed
    want = msd_filter.get_valid_ranges_recursive(fs, base)  # pure Python
    assert [(r.start(), r.end()) for r in got] == [
        (r.start(), r.end()) for r in want
    ]


@pytest.mark.parametrize("base", [10, 20, 40])
def test_native_niceonly_matches_scalar(base):
    br = base_range.get_base_range_field(base)
    fs = FieldSize(br.start(), min(br.end(), br.start() + 50_000))
    got = engine.process_range_niceonly(fs, base, backend="native")
    want = scalar.process_range_niceonly(fs, base)
    assert sorted(n.number for n in got.nice_numbers) == sorted(
        n.number for n in want.nice_numbers
    )


def test_native_strided_iteration_wraparound():
    """Start mid-modulus so the first_valid search wraps (reference edge case,
    client_process_gpu.rs:1068-1075)."""
    base = 20
    table = stride_filter.get_stride_table(base, 1)
    br = base_range.get_base_range(base)
    start = br[0] + table.modulus - 3
    fs = FieldSize(start, start + 2 * table.modulus)
    first, idx = table.first_valid_at_or_after(fs.start())
    got = native.iterate_range_strided(first, idx, fs.end(), base, table.gap_table)
    want = [n.number for n in table.iterate_range(fs, base)]
    assert got == want


@pytest.mark.parametrize("base", [10, 25, 40, 50, 64])
def test_fast_strided_matches_generic(base):
    """The magic-divide + polynomial-residue fast filters (round 5) against
    the generic limb loop over identical ranges, both via the same entry
    point (nice_native.cpp routes internally; the hook forces the slow path).
    Spans several stride wraps so per-wrap constant recomputation is hit."""
    if not native.available():
        pytest.skip("no native toolchain")
    br = base_range.get_base_range(base)
    if br is None:
        pytest.skip("no base range")
    for k in (1, 3):
        table = stride_filter.get_stride_table(base, k)
        if table.num_residues == 0:
            continue
        start = br[0] + 17
        end = min(br[1], start + 3 * table.modulus + 50_000)
        first, idx = table.first_valid_at_or_after(start)
        if first >= end:
            continue
        args = (first, idx, end, base, table.gap_array)
        kwargs = dict(modulus=table.modulus, residues=table.residues_u32)
        prev = native.strided_fast_enabled(True)
        try:
            fast = native.iterate_range_strided(*args, **kwargs)
            native.strided_fast_enabled(False)
            slow = native.iterate_range_strided(*args, **kwargs)
        finally:
            native.strided_fast_enabled(prev)
        assert fast == slow, (base, k)


@pytest.mark.parametrize("base", [40, 50])
def test_fast_strided_accept_rich_low_range(base):
    """Accept-rich differential: below the base range (n far under b^(b/5))
    the square+cube digit count stays <= base, so digit-distinct survivors
    are plentiful — the fast/slow comparison can never pass on
    empty-vs-empty. start=1e8 keeps n >= base^4.5 (40^4.5≈1.6e7,
    50^4.5≈4.4e7) so the polynomial path stays eligible past its gate."""
    if not native.available():
        pytest.skip("no native toolchain")
    table = stride_filter.get_stride_table(base, 3)
    if table.num_residues == 0:
        pytest.skip("empty stride table")
    start = 100_000_000
    end = start + 3 * table.modulus
    first, idx = table.first_valid_at_or_after(start)
    assert first < end
    args = (first, idx, end, base, table.gap_array)
    kwargs = dict(modulus=table.modulus, residues=table.residues_u32)
    prev = native.strided_fast_enabled(True)
    try:
        fast = native.iterate_range_strided(*args, **kwargs)
        native.strided_fast_enabled(False)
        slow = native.iterate_range_strided(*args, **kwargs)
    finally:
        native.strided_fast_enabled(prev)
    assert slow, (base, "generic path found no digit-distinct survivors;"
                  " the differential would be vacuous")
    assert fast == slow, (base, len(fast), len(slow))


def test_fast_strided_finds_nice_numbers():
    """b10 golden: 69 is nice; the fast path must report it (guards against a
    fast filter that silently rejects everything)."""
    if not native.available():
        pytest.skip("no native toolchain")
    base = 10
    table = stride_filter.get_stride_table(base, 1)
    br = base_range.get_base_range(base)
    first, idx = table.first_valid_at_or_after(br[0])
    got = native.iterate_range_strided(
        first, idx, br[1], base, table.gap_array,
        modulus=table.modulus, residues=table.residues_u32,
    )
    assert 69 in got


def test_host_route_niceonly_small_field(monkeypatch):
    """Small niceonly fields route to the native host engine on the device
    path and return identical results to the scalar oracle. (conftest turns
    the route off suite-wide so device tests keep their coverage; this test
    opts back in.)"""
    monkeypatch.setenv("NICE_TPU_HOST_NICEONLY_MAX", str(1 << 25))
    base = 40
    br = base_range.get_base_range_field(base)
    fs = FieldSize(br.start(), min(br.end(), br.start() + 200_000))
    assert engine._host_route_niceonly(fs, base) == native.available()
    if not native.available():
        pytest.skip("no native toolchain")
    got = engine._native_niceonly(
        fs, base, None, 1, msd_floor=max(1 << 20, fs.size() // 8)
    )
    want = scalar.process_range_niceonly(fs, base)
    assert sorted(n.number for n in got.nice_numbers) == sorted(
        n.number for n in want.nice_numbers
    )


def test_host_route_integration_never_touches_device(monkeypatch):
    """With the route enabled, a small backend="pallas" niceonly field must
    resolve entirely on the host: poison the device kernel and expect exact
    results anyway."""
    if not native.available():
        pytest.skip("no native toolchain")
    from nice_tpu.ops import pallas_engine as pe

    monkeypatch.setenv("NICE_TPU_HOST_NICEONLY_MAX", str(1 << 25))

    def boom(*a, **k):
        raise AssertionError("device kernel dispatched for a host-routed field")

    monkeypatch.setattr(pe, "niceonly_strided_batch", boom)
    br = base_range.get_base_range_field(10)
    got = engine.process_range_niceonly(br, 10, backend="pallas", batch_size=128)
    assert [n.number for n in got.nice_numbers] == [69]
