"""Chaos-hardening tests: deterministic fault injection, exactly-once
submits, the on-disk submission spool, and the backend degradation chain."""

import glob
import os
import threading

import pytest

from nice_tpu import faults
from nice_tpu.ckpt.snapshot import SnapshotError, read_snapshot, write_snapshot
from nice_tpu.client import api_client
from nice_tpu.client.main import compile_results
from nice_tpu.core import base_range
from nice_tpu.core.types import (
    DataToClient,
    FieldSize,
    SearchMode,
)
from nice_tpu.faults.spool import SubmissionSpool
from nice_tpu.obs.series import CLIENT_RETRIES, SERVER_DUPLICATE_SUBMITS
from nice_tpu.ops import engine, scalar
from nice_tpu.server import app as server_app
from nice_tpu.server.db import Db

import numpy as np


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    """Every test starts and ends with no armed faults, whatever the env."""
    faults.configure(None)
    yield
    faults.configure(None)


# --- spec grammar + determinism ------------------------------------------


def test_parse_spec_selector_kinds():
    rules = faults.parse_spec(
        "http.submit:drop_response@0.3, server.claim:500@2,"
        "engine.dispatch:raise@batch=7, ckpt.write:truncate"
    )
    assert [r.site for r in rules] == [
        "http.submit", "server.claim", "engine.dispatch", "ckpt.write"
    ]
    assert rules[0].probability == 0.3
    assert rules[1].nth == 2
    assert rules[2].match == ("batch", "7")
    assert rules[3].always


@pytest.mark.parametrize(
    "spec",
    ["justasite", "site:", ":action", "s:a@1.5", "s:a@0", "s:a@nan"],
)
def test_parse_spec_rejects_malformed(spec):
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec(spec)


def test_probability_rules_are_seed_deterministic():
    def sequence(seed):
        faults.configure("x.y:boom@0.5", seed=seed)
        return [faults.fire("x.y") for _ in range(64)]

    a, b, c = sequence(7), sequence(7), sequence(8)
    assert a == b  # same seed + same call sequence -> same faults
    assert a != c  # a different seed perturbs the schedule
    assert "boom" in a and None in a  # p=0.5 over 64 calls fires both ways


def test_site_streams_are_independent():
    """Interleaving calls at another site must not perturb a site's draws."""
    faults.configure("x.y:boom@0.5", seed=3)
    alone = [faults.fire("x.y") for _ in range(32)]
    faults.configure("x.y:boom@0.5,other:zap@0.5", seed=3)
    interleaved = []
    for _ in range(32):
        faults.fire("other")
        interleaved.append(faults.fire("x.y"))
    assert alone == interleaved


def test_nth_and_match_selectors_fire_exactly_once():
    faults.configure("s:a@2,t:b@k=v", seed=0)
    assert [faults.fire("s") for _ in range(4)] == [None, "a", None, None]
    assert faults.fire("t", k="x") is None
    assert faults.fire("t", k="v") == "b"
    assert faults.fire("t", k="v") is None  # fired once, stays quiet


def test_unconfigured_fire_is_inert():
    assert faults.fire("no.such.site", anything=1) is None
    assert faults.active_sites() == ()


# --- client transport under injected faults ------------------------------


def test_injected_4xx_surfaces_detail_and_status():
    faults.configure("http.claim:404@1")
    with pytest.raises(api_client.ApiError) as ei:
        api_client.retry_request(
            "http://127.0.0.1:9/claim/detailed", max_retries=3,
            endpoint="claim",
        )
    assert ei.value.status == 404
    assert "injected fault" in str(ei.value)


def test_injected_500s_bump_retry_counter(monkeypatch):
    monkeypatch.setattr("time.sleep", lambda s: None)
    faults.configure("http.other:500")  # every call
    before = CLIENT_RETRIES.value(("other",))
    with pytest.raises(api_client.ApiError) as ei:
        api_client.retry_request("http://127.0.0.1:9/x", max_retries=3)
    # Exhausted retries preserve the last definite server answer (here the
    # injected 500), so callers can tell "server kept refusing" (e.g. a 429
    # rate limit to back off from) apart from a dead transport (None).
    assert ei.value.status == 500
    assert CLIENT_RETRIES.value(("other",)) == before + 3


# --- exactly-once submits + spool against a live server ------------------


@pytest.fixture()
def server(tmp_path):
    db_path = str(tmp_path / "faults-test.db")
    db = Db(db_path)
    db.seed_base(10, field_size=20)  # [47, 100) -> 3 tiny fields
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", db_path
    srv.shutdown()


def _claim_and_compile(base_url):
    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, "chaos", max_retries=0
    )
    results = scalar.process_range_detailed(data.to_field_size(), data.base)
    return data, compile_results(data, results, SearchMode.DETAILED, "chaos")


def test_submit_replay_is_idempotent(server):
    base_url, db_path = server
    data, submission = _claim_and_compile(base_url)
    assert submission.submit_id  # stamped by compile_results

    first = api_client.submit_field_to_server(base_url, submission, max_retries=0)
    assert not first.get("duplicate")
    before = SERVER_DUPLICATE_SUBMITS.value()
    replay = api_client.submit_field_to_server(base_url, submission, max_retries=0)
    assert replay.get("duplicate") is True
    assert SERVER_DUPLICATE_SUBMITS.value() == before + 1

    db = Db(db_path)
    claim = db.get_claim_by_id(data.claim_id)
    subs = db.get_detailed_submissions_by_field(claim.field_id)
    db.close()
    assert len(subs) == 1  # replay answered OK without a second row


def test_dropped_response_then_retry_is_exactly_once(server):
    """The drop_response fault: the server accepts the submit, the client
    sees a network error and retries — the retry must dedup, not double."""
    base_url, db_path = server
    data, submission = _claim_and_compile(base_url)
    faults.configure("http.submit:drop_response@1")
    try:
        resp = api_client.submit_field_to_server(
            base_url, submission, max_retries=3
        )
    finally:
        faults.configure(None)
    assert resp.get("duplicate") is True  # attempt 1 landed; attempt 2 deduped

    db = Db(db_path)
    claim = db.get_claim_by_id(data.claim_id)
    subs = db.get_detailed_submissions_by_field(claim.field_id)
    db.close()
    assert len(subs) == 1


def test_submit_id_is_content_addressed(server):
    base_url, _ = server
    data, submission = _claim_and_compile(base_url)
    results = scalar.process_range_detailed(data.to_field_size(), data.base)
    again = compile_results(data, results, SearchMode.DETAILED, "chaos")
    assert again.submit_id == submission.submit_id  # same results, same id
    other = compile_results(
        DataToClient(
            claim_id=data.claim_id + 1, base=data.base,
            range_start=data.range_start, range_end=data.range_end,
            range_size=data.range_size,
        ),
        results, SearchMode.DETAILED, "chaos",
    )
    assert other.submit_id != submission.submit_id


def test_spool_journal_and_replay(server, tmp_path):
    base_url, db_path = server
    data, submission = _claim_and_compile(base_url)
    spool = SubmissionSpool(str(tmp_path / "spool"))

    # Server unreachable: the entry defers and survives for the next pass.
    spool.add(submission)
    assert len(spool.pending()) == 1
    counts = spool.replay("http://127.0.0.1:9", max_retries=0)
    assert counts == {"delivered": 0, "rejected": 0, "deferred": 1}
    assert len(spool.pending()) == 1

    # Server back: delivered and retired; a second pass is a no-op.
    counts = spool.replay(base_url, max_retries=0)
    assert counts["delivered"] == 1
    assert spool.pending() == []
    assert spool.replay(base_url, max_retries=0) == {
        "delivered": 0, "rejected": 0, "deferred": 0
    }

    db = Db(db_path)
    claim = db.get_claim_by_id(data.claim_id)
    subs = db.get_detailed_submissions_by_field(claim.field_id)
    db.close()
    assert len(subs) == 1


def test_spool_quarantines_rejected_entries(server, tmp_path):
    base_url, _ = server
    data, submission = _claim_and_compile(base_url)
    submission.claim_id = 999_999  # no such claim -> definitive 4xx
    spool = SubmissionSpool(str(tmp_path / "spool"))
    spool.add(submission)
    counts = spool.replay(base_url, max_retries=0)
    assert counts == {"delivered": 0, "rejected": 1, "deferred": 0}
    assert spool.pending() == []
    assert glob.glob(os.path.join(str(tmp_path / "spool"), "*.rejected"))


def test_rejournaling_same_submission_overwrites(tmp_path, server):
    base_url, _ = server
    _, submission = _claim_and_compile(base_url)
    spool = SubmissionSpool(str(tmp_path / "spool"))
    p1 = spool.add(submission)
    p2 = spool.add(submission)
    assert p1 == p2
    assert len(spool.pending()) == 1


def test_server_side_injected_500_is_retryable(server, monkeypatch):
    base_url, _ = server
    monkeypatch.setattr("time.sleep", lambda s: None)
    faults.configure("server.status:500@1")
    got = api_client.retry_request(
        f"{base_url}/status", max_retries=2, endpoint="other"
    )
    assert got["status"] == "ok"


# --- backend degradation chain -------------------------------------------


BASE = 22


def _field(size):
    lo, _hi = base_range.get_base_range(BASE)
    return FieldSize(lo, lo + size)


def test_detailed_fallback_jnp_to_scalar_is_equivalent(monkeypatch):
    # raise@2 indexes per-BATCH dispatches; the megaloop would collapse this
    # field to one dispatch (megaloop fault fallback: test_megaloop.py).
    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")
    r = _field(40_000)
    canon = scalar.process_range_detailed(r, BASE)
    faults.configure("engine.dispatch:raise@2", seed=0)
    res = engine.process_range_detailed(r, BASE, backend="jnp", batch_size=1024)
    assert res.backend_downgrades == ("jnp->scalar",)
    assert res.distribution == canon.distribution
    assert res.nice_numbers == canon.nice_numbers


def test_detailed_fallback_full_chain_pallas_to_scalar():
    r = _field(20_000)
    canon = scalar.process_range_detailed(r, BASE)
    # Two one-shot rules: the first kills pallas's first dispatch, the second
    # (never consulted while the first fires) kills jnp's first dispatch.
    faults.configure("engine.dispatch:raise@1,engine.dispatch:raise@1", seed=0)
    res = engine.process_range_detailed(
        r, BASE, backend="pallas", batch_size=1024
    )
    assert res.backend_downgrades == ("pallas->jnp", "jnp->scalar")
    assert res.distribution == canon.distribution
    assert res.nice_numbers == canon.nice_numbers


def test_niceonly_fallback_chain_is_equivalent():
    r = _field(40_000)
    canon = scalar.process_range_niceonly(r, BASE)
    faults.configure("engine.dispatch:raise@1,engine.dispatch:raise@1", seed=0)
    res = engine.process_range_niceonly(
        r, BASE, backend="pallas", batch_size=1024
    )
    assert res.backend_downgrades == ("pallas->jnp", "jnp->scalar")
    assert res.nice_numbers == canon.nice_numbers


def test_fallback_resumes_rather_than_restarts(monkeypatch):
    """The fallback must re-dispatch only the failed batch onward: the
    scalar leg sees a resume cursor past the batches jnp completed."""
    r = _field(40_000)
    seen = {}
    orig = engine._chunked_host_scan

    def spy(range_, base, mode, chunk, progress, checkpoint_cb, resume,
            *args, **kwargs):
        seen["resume_cursor"] = None if resume is None else resume["cursor"]
        return orig(range_, base, mode, chunk, progress, checkpoint_cb,
                    resume, *args, **kwargs)

    monkeypatch.setenv("NICE_TPU_MEGALOOP", "0")  # per-batch dispatch indexing
    monkeypatch.setattr(engine, "_chunked_host_scan", spy)
    faults.configure("engine.dispatch:raise@3", seed=0)
    res = engine.process_range_detailed(r, BASE, backend="jnp", batch_size=1024)
    assert res.backend_downgrades == ("jnp->scalar",)
    assert seen["resume_cursor"] is not None
    assert seen["resume_cursor"] > r.start()  # kept jnp's completed batches


def test_no_fallback_env_escape_hatch(monkeypatch):
    monkeypatch.setenv("NICE_TPU_NO_FALLBACK", "1")
    faults.configure("engine.dispatch:raise@1", seed=0)
    with pytest.raises(engine.BackendDispatchError) as ei:
        engine.process_range_detailed(
            _field(10_000), BASE, backend="jnp", batch_size=1024
        )
    assert ei.value.backend == "jnp"
    assert ei.value.state is not None
    assert ei.value.state["cursor"] >= _field(10_000).start()


def test_chain_exhaustion_propagates():
    """An always-on dispatch fault takes down every backend; the scalar
    leg's failure must reach the caller, not loop forever."""
    faults.configure("engine.dispatch:raise")
    with pytest.raises(RuntimeError, match="injected engine.dispatch"):
        engine.process_range_detailed(
            _field(10_000), BASE, backend="jnp", batch_size=1024
        )


# --- checkpoint write truncation ------------------------------------------


def test_ckpt_truncate_fault_is_detected(tmp_path):
    path = str(tmp_path / "snap.ckpt")
    manifest = {"cursor": 123}
    arrays = {"hist": np.arange(24, dtype=np.int64)}
    faults.configure("ckpt.write:truncate@1")
    write_snapshot(path, manifest, arrays)
    with pytest.raises(SnapshotError):
        read_snapshot(path)
    # The hook fired once; the rewrite is clean and fully readable.
    write_snapshot(path, manifest, arrays)
    got_manifest, got_arrays = read_snapshot(path)
    assert got_manifest["cursor"] == 123
    assert np.array_equal(got_arrays["hist"], arrays["hist"])
