"""Replication & epoch-fenced failover tests.

Three layers:
  * Db-level: trigger capture, op apply, sequence continuity across
    promotion, retention pruning — no HTTP involved.
  * Server-pair: a real primary + hot standby replicating over HTTP,
    fencing (421 standby / 410 deposed), promotion, /status server lists.
  * Client-side: multi-server failover rotation, the spool replay across a
    promotion answering {"duplicate": true} exactly once, per-host
    connection eviction, and the persisted known-server list.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from nice_tpu.client import api_client
from nice_tpu.client.main import (
    _load_known_servers,
    _save_known_servers,
    compile_results,
    process_field,
)
from nice_tpu.core.types import SearchMode
from nice_tpu.faults import spool as spool_mod
from nice_tpu.server import app as server_app
from nice_tpu.server.db import Db


@pytest.fixture(autouse=True)
def _reset_client_state():
    """The transport's module state (learned epoch, failover cursor, dead
    hosts, pooled sockets) must not leak across tests — a stale epoch 2
    stamped at a fresh epoch-1 server would fence it."""

    def _reset():
        with api_client._epoch_lock:
            api_client._last_epoch = 0
        with api_client._failover_lock:
            api_client._failover_idx.clear()
            api_client._failover_gen.clear()
        with api_client._dead_hosts_lock:
            api_client._dead_hosts.clear()
        api_client.close_connections()

    _reset()
    yield
    _reset()


# ---------------------------------------------------------------------------
# Db-level: capture triggers + apply


def _seeded_db(tmp_path, name="primary.db"):
    path = str(tmp_path / name)
    db = Db(path)
    db.seed_base(10, field_size=20)  # [47,100) -> 3 fields
    return path, db


def test_oplog_captures_committed_writes(tmp_path):
    _, db = _seeded_db(tmp_path)
    ops = db.get_repl_ops_since(0, limit=10_000)
    assert ops, "seeding produced no replication ops"
    seqs = [op["seq"] for op in ops]
    assert seqs == list(range(1, len(seqs) + 1)), "op log has gaps"
    assert {op["epoch"] for op in ops} == {1}
    assert db.repl_max_seq() == seqs[-1]
    tables = {op["tbl"] for op in ops}
    assert "bases" in tables and "fields" in tables
    db.close()


def test_apply_roundtrip_and_standby_capture_off(tmp_path):
    _, primary = _seeded_db(tmp_path)
    ops = primary.get_repl_ops_since(0, limit=10_000)

    standby = Db(str(tmp_path / "standby.db"))
    standby.repl_set_standby()
    applied = standby.apply_repl_ops(ops)
    assert applied == len(ops)
    assert standby.repl_last_applied_seq() == ops[-1]["seq"]

    for tbl in ("bases", "fields"):
        want = primary._read().execute(f"SELECT COUNT(*) FROM {tbl}").fetchone()[0]
        got = standby._read().execute(f"SELECT COUNT(*) FROM {tbl}").fetchone()[0]
        assert got == want, f"{tbl}: replica has {got} rows, primary {want}"
    # Applying replicated rows must NOT be re-captured into the standby's
    # own op log (capture is off for the standby role).
    assert standby.get_repl_ops_since(0) == []
    primary.close()
    standby.close()


def test_promote_bumps_epoch_and_continues_sequence(tmp_path):
    _, primary = _seeded_db(tmp_path)
    ops = primary.get_repl_ops_since(0, limit=10_000)
    top = ops[-1]["seq"]

    standby = Db(str(tmp_path / "standby.db"))
    standby.repl_set_standby()
    standby.apply_repl_ops(ops)

    epoch = standby.repl_promote()
    assert epoch == 2
    assert standby.repl_role() == "primary"
    assert not standby.repl_fenced()

    # The first write after promotion continues the global sequence: no
    # seq reuse means a resumed standby of the OLD primary can never
    # silently interleave two lineages.
    standby.seed_base(17, field_size=30_000)
    new_ops = standby.get_repl_ops_since(top)
    assert new_ops, "post-promotion write captured no ops"
    assert new_ops[0]["seq"] == top + 1
    assert {op["epoch"] for op in new_ops} == {2}
    primary.close()
    standby.close()


def test_prune_keeps_recent_ops(tmp_path):
    _, db = _seeded_db(tmp_path)
    top = db.repl_max_seq()
    assert top > 2
    removed = db.prune_repl_ops(keep=2)
    assert removed == top - 2
    remaining = db.get_repl_ops_since(0)
    assert [op["seq"] for op in remaining] == [top - 1, top]
    db.close()


# ---------------------------------------------------------------------------
# Server pair: live replication, fencing, promotion


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start(db_path, port, standby_of=None, advertise=None):
    srv = server_app.serve(
        db_path, host="127.0.0.1", port=port,
        prefill=(standby_of is None),
        standby_of=standby_of, advertise=advertise,
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url, body=None):
    data = json.dumps(body or {}).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _wait(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


@pytest.fixture()
def pair(tmp_path, monkeypatch):
    """A live primary + hot standby, both with advertise URLs so the
    primary's /status server list names them both."""
    monkeypatch.setenv("NICE_TPU_REPL_POLL_SECS", "0.05")
    p_port, s_port = _free_port(), _free_port()
    purl = f"http://127.0.0.1:{p_port}"
    surl = f"http://127.0.0.1:{s_port}"

    p_path, db = _seeded_db(tmp_path)
    db.close()
    s_path = str(tmp_path / "standby.db")

    primary = _start(p_path, p_port, advertise=purl)
    standby = _start(s_path, s_port, standby_of=purl, advertise=surl)
    yield {
        "primary": primary, "standby": standby,
        "purl": purl, "surl": surl,
        "p_path": p_path, "s_path": s_path,
    }
    for srv in (primary, standby):
        srv.shutdown()
        srv.context.close()


def _applied_seq(surl) -> int:
    return int(_get(f"{surl}/status")["repl"].get("applied_seq") or 0)


def test_standby_replicates_and_rejects_writes(pair):
    purl, surl = pair["purl"], pair["surl"]
    p_status = _get(f"{purl}/status")
    assert p_status["repl"]["role"] == "primary"
    assert p_status["epoch"] == 1
    target = p_status["repl"]["seq"]
    assert target > 0

    assert _wait(lambda: _applied_seq(surl) >= target), (
        f"standby never caught up to seq {target}: at {_applied_seq(surl)}"
    )
    s_status = _get(f"{surl}/status")
    assert s_status["repl"]["role"] == "standby"
    assert s_status["status"] == "ok"

    # Read surface served from the replica.
    assert _get(f"{surl}/stats/bases")

    # Writes are misdirected: 421 rotates a failover client to the primary.
    with pytest.raises(api_client.ApiError) as exc:
        api_client.retry_request(
            f"{surl}/claim/detailed?username=tester", max_retries=0
        )
    assert exc.value.status == 421

    # The primary registers the polling standby and advertises both
    # endpoints for clients to learn.
    assert _wait(
        lambda: surl in _get(f"{purl}/status")["repl"]["servers"]
    ), "primary never registered the standby"
    assert purl in _get(f"{purl}/status")["repl"]["servers"]


def test_promotion_fences_deposed_primary(pair):
    purl, surl = pair["purl"], pair["surl"]
    target = _get(f"{purl}/status")["repl"]["seq"]
    assert _wait(lambda: _applied_seq(surl) >= target)

    # Client learns epoch 1 from the primary before the failover.
    api_client.retry_request(f"{purl}/status", max_retries=0)
    assert api_client.last_seen_epoch() == 1

    resp = _post(f"{surl}/repl/promote")
    assert resp["status"] == "OK" and resp["epoch"] == 2
    s_status = _get(f"{surl}/status")
    assert s_status["repl"]["role"] == "primary"
    assert s_status["epoch"] == 2

    # Talking to the promoted server teaches the client epoch 2 ...
    api_client.retry_request(f"{surl}/status", max_retries=0)
    assert api_client.last_seen_epoch() == 2

    # ... and the stamped epoch fences the old primary: first write 410s,
    # and the fence is sticky — an UNSTAMPED write afterwards 410s too.
    with pytest.raises(api_client.ApiError) as exc:
        api_client.retry_request(
            f"{purl}/claim/detailed?username=tester", max_retries=0
        )
    assert exc.value.status == 410
    req = urllib.request.Request(
        f"{purl}/claim/niceonly?username=bare", method="GET"
    )
    with pytest.raises(urllib.error.HTTPError) as bare:
        urllib.request.urlopen(req, timeout=10)
    assert bare.value.code == 410
    assert _get(f"{purl}/status")["repl"]["fenced"] is True

    # The promoted primary serves writes: a claim comes off its replica.
    data = api_client.get_field_from_server(
        SearchMode.DETAILED, surl, "tester", max_retries=0
    )
    assert data.claim_id > 0


def test_spool_replay_across_promotion_is_exactly_once(pair, tmp_path):
    """Satellite: a submission accepted by the old primary, journaled to
    the spool (client saw a dropped response), replayed after failover
    against the promoted standby must answer {"duplicate": true} exactly
    once — the replicated submissions table + submit_id carries
    exactly-once across the promotion."""
    purl, surl = pair["purl"], pair["surl"]

    data = api_client.get_field_from_server(
        SearchMode.DETAILED, purl, "tester", max_retries=0
    )
    results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
    submission = compile_results(data, results, SearchMode.DETAILED, "tester")
    first = api_client.submit_field_to_server(purl, submission, max_retries=0)
    assert first["status"] == "OK" and not first.get("duplicate")

    # The client never saw that 200: the submission sits in the spool.
    spool = spool_mod.SubmissionSpool(str(tmp_path / "spool"))
    spool.add(submission)

    target = _get(f"{purl}/status")["repl"]["seq"]
    assert _wait(lambda: _applied_seq(surl) >= target), "standby lagged"

    # Primary dies; the standby is promoted.
    pair["primary"].shutdown()
    assert _post(f"{surl}/repl/promote")["epoch"] == 2

    # Replay against the configured server list: the dead primary rotates
    # to the promoted standby, which recognizes the submit_id.
    counts = spool.replay(f"{purl},{surl}", max_retries=0)
    assert counts == {"delivered": 1, "rejected": 0, "deferred": 0}
    assert spool.pending() == []

    # Exactly once: a direct replay answers duplicate, and the promoted
    # ledger holds a single row for that submit_id.
    again = api_client.submit_field_to_server(surl, submission, max_retries=0)
    assert again.get("duplicate") is True
    db = Db(pair["s_path"])
    n = db._read().execute(
        "SELECT COUNT(*) FROM submissions WHERE submit_id = ?",
        (submission.submit_id,),
    ).fetchone()[0]
    db.close()
    assert n == 1


# ---------------------------------------------------------------------------
# Client transport: failover rotation + per-host socket hygiene


def test_failover_request_rotates_past_dead_server(pair):
    purl = pair["purl"]
    dead = f"http://127.0.0.1:{_free_port()}"
    api_base = f"{dead},{purl}"

    status = api_client.failover_request(api_base, "/status", max_retries=0)
    assert status["status"] == "ok"
    # The cursor sticks to the server that answered: the next request goes
    # straight to the live endpoint instead of re-probing the dead one.
    servers = api_client.split_servers(api_base)
    key = ",".join(servers)
    with api_client._failover_lock:
        assert servers[api_client._failover_idx[key]] == purl.rstrip("/")


def test_failover_request_single_server_is_plain_retry(pair):
    status = api_client.failover_request(
        pair["purl"], "/status", max_retries=0
    )
    assert status["status"] == "ok"
    with api_client._failover_lock:
        assert api_client._failover_idx == {}


def test_split_servers():
    assert api_client.split_servers(" http://a:1/ ,http://b:2,, ") == [
        "http://a:1", "http://b:2",
    ]
    assert api_client.split_servers("http://a:1") == ["http://a:1"]


def test_dead_host_mark_evicts_pooled_socket(pair):
    purl = pair["purl"]
    api_client.retry_request(f"{purl}/status", max_retries=0)
    key = ("http", purl.split("//", 1)[1])
    pool = api_client._conn_pool()
    assert key in pool
    stale = pool[key]

    api_client._mark_host_dead(key)
    api_client.retry_request(f"{purl}/status", max_retries=0)
    fresh = api_client._conn_pool()[key]
    assert fresh is not stale, "socket born before the dead-mark survived"
    assert fresh._nice_born > api_client._dead_hosts[key]


def test_close_connections_per_host():
    class FakeConn:
        closed = False

        def close(self):
            self.closed = True

    pool = api_client._conn_pool()
    a, b = FakeConn(), FakeConn()
    pool[("http", "a:1")] = a
    pool[("http", "b:2")] = b
    api_client.close_connections(netloc="a:1")
    assert a.closed and not b.closed
    assert ("http", "a:1") not in pool and ("http", "b:2") in pool
    api_client.close_connections()
    assert b.closed and pool == {}


# ---------------------------------------------------------------------------
# Known-server persistence beside the checkpoint dir


def test_known_servers_round_trip(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    assert _load_known_servers(ckpt) == []
    _save_known_servers(ckpt, ["http://a:1/", "http://b:2", "http://a:1"])
    assert _load_known_servers(ckpt) == ["http://a:1", "http://b:2"]
    # Corrupt file degrades to "no learned servers", never an exception.
    with open(tmp_path / "ckpt" / "servers.json", "w") as f:
        f.write("{not json")
    assert _load_known_servers(ckpt) == []
    assert _load_known_servers(None) == []
