"""Property-based differential tests: random (base, window) against the
scalar oracle.

The reference's test strategy leans on randomized differential checks
between its engines (SURVEY.md section 4); here hypothesis drives the same
cross-engine contract: for ANY base and ANY window inside the base range,
the vectorized jnp engine, the Pallas kernels (interpreter mode off-TPU),
and the native C++ engine must reproduce the scalar oracle bit-for-bit.
"""

import jax.numpy as jnp
import numpy as np
import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    # hypothesis is an optional dependency: without it the property tests
    # SKIP (visibly, instead of failing the whole module's collection and
    # silently taking the fixed-candidate differential tests below with it).
    class _AnyStrategy:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _AnyStrategy()

    def settings(**_kw):
        return lambda fn: fn

    def given(**_kw):
        def deco(fn):
            @pytest.mark.skip(reason="hypothesis not installed")
            def stub():
                pass

            stub.__name__ = fn.__name__
            stub.__doc__ = fn.__doc__
            return stub

        return deco


# Derandomized: interpreter-mode kernel compiles make unlucky random draws
# arbitrarily slow; a fixed example set keeps suite runtime bounded and CI
# reproducible while still sweeping base/offset/size combinations no
# hand-written table covers.

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar, vector_engine as ve
from nice_tpu.ops import lsd_filter, msd_filter, stride_filter
from nice_tpu.ops.limbs import (
    get_plan,
    ints_to_limb_arrays,
    limb_arrays_to_ints,
)


def _window(base: int, offset_frac: float, size: int) -> FieldSize:
    lo, hi = base_range.get_base_range(base)
    # Clamp: float multiplication can round past hi-1 at 1e16-scale ranges.
    start = min(lo + int((hi - lo - 1) * offset_frac), hi - 1)
    return FieldSize(start, min(start + size, hi))


# Bases with nonempty ranges and (for the pallas path) <= 4 u32 limbs.
_BASES = st.sampled_from([10, 14, 17, 20, 24, 30, 35, 40, 45, 50, 60, 70, 80, 95])


@settings(max_examples=8, deadline=None, derandomize=True)
@given(base=_BASES, frac=st.floats(0, 1), size=st.integers(1, 4000))
def test_detailed_jnp_matches_scalar(base, frac, size):
    fs = _window(base, frac, size)
    got = engine.process_range_detailed(fs, base, backend="jnp", batch_size=1 << 10)
    want = scalar.process_range_detailed(fs, base)
    assert got == want


@settings(max_examples=6, deadline=None, derandomize=True)
@given(base=st.sampled_from([10, 20, 40, 50]), frac=st.floats(0, 1), size=st.integers(1, 4000))
def test_niceonly_strided_matches_scalar(base, frac, size):
    fs = _window(base, frac, size)
    got = engine.process_range_niceonly(fs, base, backend="pallas", batch_size=1 << 10)
    want = scalar.process_range_niceonly(fs, base)
    assert [n.number for n in got.nice_numbers] == [
        n.number for n in want.nice_numbers
    ]


@settings(max_examples=20, deadline=None, derandomize=True)
@given(base=st.integers(5, 256), k=st.integers(1, 2))
def test_lsd_bitmap_oracle_property(base, k):
    if base ** k > 40_000:
        return  # keep the scalar transcription fast
    assert np.array_equal(
        lsd_filter._bitmap_scalar(base, k),
        lsd_filter.get_valid_multi_lsd_bitmap(base, k),
    )


# ---------------------------------------------------------------------------
# Carry-save multiply/square vs Python big-int ground truth.
#
# The carry-save kernels (ops/vector_engine.py mul_limbs/sqr_limbs) defer all
# carry propagation to one resolution pass; these tests prove the result
# limbs are BYTE-IDENTICAL to Python's arbitrary-precision n^2 / n^3 across
# the limb widths real plans use (1 limb at b10 up to 13 limbs for n^3 at
# b120), including engineered carry-edge candidates sitting at limb
# boundaries where wrap counting is maximally stressed.
# ---------------------------------------------------------------------------

_DIFF_BASES = [40, 80, 97, 120]


def _carry_edge_candidates(base: int) -> list[int]:
    """Candidates engineered to stress carry-save wrap accounting: range
    endpoints, values straddling 2^32k limb boundaries (max-1/max/min limb
    patterns produce the longest carry chains in a propagating scheme), and
    seeded randoms for breadth."""
    import random

    lo, hi = base_range.get_base_range(base)
    cands = {lo, hi - 1, (lo + hi) // 2}
    for k in range(1, 8):
        b = 1 << (32 * k)
        for n in (b - 1, b, b + 1, b - 2, (b - 1) // 3):  # 0x5555... pattern
            if lo <= n < hi:
                cands.add(n)
    # All-ones limbs below hi: the square's partial products are all maximal.
    ones = 0
    while True:
        ones = (ones << 32) | 0xFFFFFFFF
        if ones >= hi:
            break
        if ones >= lo:
            cands.add(ones)
    rng = random.Random(base)  # seeded: deterministic suite
    for _ in range(16):
        cands.add(rng.randrange(lo, hi))
    return sorted(cands)


def _bigint_limbs(x: int, num_limbs: int) -> list[int]:
    return [(x >> (32 * i)) & 0xFFFFFFFF for i in range(num_limbs)]


@pytest.mark.parametrize("base", _DIFF_BASES)
@pytest.mark.parametrize("carry_interval", [0, 1, 3])
def test_square_cube_limbs_match_bigint(base, carry_interval):
    """sqr_limbs(n) == n^2 and mul_limbs(n^2, n) == n^3 exactly, limb for
    limb, against Python big-int — for every engineered carry-edge candidate,
    at every carry-resolution cadence (the interval is a perf knob and must
    be bit-invisible)."""
    plan = get_plan(base)
    ns = _carry_edge_candidates(base)
    n_limbs = ints_to_limb_arrays(ns, plan.limbs_n)
    n_dev = [jnp.asarray(col) for col in n_limbs]
    sq = ve.sqr_limbs(n_dev, plan.limbs_sq, resolve_every=carry_interval)
    cu = ve.mul_limbs(sq, n_dev, plan.limbs_cu, resolve_every=carry_interval)
    sq_host = [np.asarray(col) for col in sq]
    cu_host = [np.asarray(col) for col in cu]
    for row, n in enumerate(ns):
        want_sq = _bigint_limbs(n * n, plan.limbs_sq)
        want_cu = _bigint_limbs(n * n * n, plan.limbs_cu)
        got_sq = [int(col[row]) for col in sq_host]
        got_cu = [int(col[row]) for col in cu_host]
        assert got_sq == want_sq, (base, n, carry_interval)
        assert got_cu == want_cu, (base, n, carry_interval)


def test_square_cube_limbs_match_bigint_b510_worst_cadence():
    """Runtime witness for the jaxlint J2 headroom theorem at its hardest
    point: base 510 is the widest sweep plan (29 u32 limbs — the deepest
    carry-save columns any supported base produces) and resolve_every =
    limbs_n is the laziest carry cadence the autotuner may pick, so wrap
    counters accumulate across a full limb pass before any resolution. The
    interval analysis proves this cannot overflow; this test executes it
    against Python big-int on engineered carry-edge candidates. A thinned
    candidate set keeps the eager 29-limb math inside the tier-1 budget."""
    base = 510
    plan = get_plan(base)
    all_cands = _carry_edge_candidates(base)
    # endpoints + the all-ones-limbs patterns + an evenly-thinned remainder
    ns = sorted(set(all_cands[:2] + all_cands[-2:] + all_cands[:: max(1, len(all_cands) // 6)]))
    n_dev = [jnp.asarray(col) for col in ints_to_limb_arrays(ns, plan.limbs_n)]
    for carry_interval in (0, plan.limbs_n):
        sq = ve.sqr_limbs(n_dev, plan.limbs_sq, resolve_every=carry_interval)
        cu = ve.mul_limbs(sq, n_dev, plan.limbs_cu, resolve_every=carry_interval)
        sq_host = [np.asarray(col) for col in sq]
        cu_host = [np.asarray(col) for col in cu]
        for row, n in enumerate(ns):
            want_sq = _bigint_limbs(n * n, plan.limbs_sq)
            want_cu = _bigint_limbs(n * n * n, plan.limbs_cu)
            got_sq = [int(col[row]) for col in sq_host]
            got_cu = [int(col[row]) for col in cu_host]
            assert got_sq == want_sq, (base, n, carry_interval)
            assert got_cu == want_cu, (base, n, carry_interval)


@pytest.mark.parametrize("base", _DIFF_BASES)
def test_sqr_equals_general_mul(base):
    """The squaring specialization (symmetry: each cross product accumulated
    twice) must agree with the general carry-save multiply on the same
    inputs — same out_len, same values, limb for limb."""
    plan = get_plan(base)
    ns = _carry_edge_candidates(base)
    n_dev = [jnp.asarray(col) for col in ints_to_limb_arrays(ns, plan.limbs_n)]
    via_sqr = ve.sqr_limbs(n_dev, plan.limbs_sq)
    via_mul = ve.mul_limbs(n_dev, n_dev, plan.limbs_sq)
    for a, b in zip(via_sqr, via_mul):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_limb_array_roundtrip():
    """Host packing helpers invert each other across widths."""
    xs = [0, 1, 0xFFFFFFFF, 1 << 32, (1 << 96) - 1, (1 << 128) - 5]
    cols = ints_to_limb_arrays(xs, 5)
    assert len(cols) == 5 and all(c.shape == (len(xs),) for c in cols)
    assert limb_arrays_to_ints(cols) == xs


# ---------------------------------------------------------------------------
# MXU banded-Toeplitz multiply vs Python big-int and vs the VPU carry-save
# path. Sweeps the carry-edge candidates at the bases the jaxlint sweep
# traces (510 = the widest plan: 29-limb operands, the deepest contraction
# any supported base feeds the i32 accumulator) — a runtime witness for the
# declared dot_bound theorem (ops/mxu.accum_bound).
# ---------------------------------------------------------------------------

_MXU_BASES = [40, 80, 510]


def _mxu_candidates(base: int) -> list[int]:
    cands = _carry_edge_candidates(base)
    if base >= 500:
        # Thin the widest plan: eager 29-limb math at every candidate would
        # blow the tier-1 budget; endpoints + all-ones + an even sample keep
        # the carry-edge coverage.
        cands = sorted(set(
            cands[:2] + cands[-2:] + cands[:: max(1, len(cands) // 6)]
        ))
    return cands


@pytest.mark.parametrize("base", _MXU_BASES)
def test_mxu_mul_sqr_limbs_match_bigint(base):
    """sqr_limbs_mxu(n) == n^2 and mul_limbs_mxu(n^2, n) == n^3 exactly,
    limb for limb, against Python big-int AND against the VPU carry-save
    kernels — the MXU arm is a bit-identical drop-in, not an approximation."""
    from nice_tpu.ops import mxu

    plan = get_plan(base)
    assert mxu.supports_plan(plan), base
    ns = _mxu_candidates(base)
    n_dev = [jnp.asarray(col) for col in ints_to_limb_arrays(ns, plan.limbs_n)]
    sq = mxu.sqr_limbs_mxu(n_dev, plan.limbs_sq)
    cu = mxu.mul_limbs_mxu(sq, n_dev, plan.limbs_cu)
    sq_vpu = ve.sqr_limbs(n_dev, plan.limbs_sq)
    cu_vpu = ve.mul_limbs(sq_vpu, n_dev, plan.limbs_cu)
    sq_host = [np.asarray(col) for col in sq]
    cu_host = [np.asarray(col) for col in cu]
    for row, n in enumerate(ns):
        got_sq = [int(col[row]) for col in sq_host]
        got_cu = [int(col[row]) for col in cu_host]
        assert got_sq == _bigint_limbs(n * n, plan.limbs_sq), (base, n)
        assert got_cu == _bigint_limbs(n * n * n, plan.limbs_cu), (base, n)
    for a, b in zip(sq, sq_vpu):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(cu, cu_vpu):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("base", [40, 80])
def test_mxu_num_uniques_matches_vpu(base):
    """The full digit-stats composition (sqr + mul + extraction) agrees
    lane-for-lane between the MXU and VPU arms."""
    plan = get_plan(base)
    ns = _carry_edge_candidates(base)
    n_dev = [jnp.asarray(col) for col in ints_to_limb_arrays(ns, plan.limbs_n)]
    u_vpu = ve.num_uniques_lanes(plan, n_dev)
    u_mxu = ve.num_uniques_lanes(plan, n_dev, use_mxu=True)
    np.testing.assert_array_equal(np.asarray(u_vpu), np.asarray(u_mxu))


# ---------------------------------------------------------------------------
# Fused residue filter: the on-device congruence mask must reproduce the
# host residue_filter membership exactly, and the fused (nice, pruned)
# kernel must agree with the unfused dense count at every MXU arm.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("base", [40, 80])
@pytest.mark.parametrize("use_mxu", [False, True])
def test_fused_filter_matches_scalar_oracle(base, use_mxu):
    from nice_tpu.ops import residue_filter

    plan = get_plan(base)
    lo, _hi = base_range.get_base_range(base)
    batch = 2048
    start = lo + 12345
    ns = list(range(start, start + batch))
    start_limbs = [jnp.asarray(c[:1]) for c in ints_to_limb_arrays([start], plan.limbs_n)]
    start_scalars = [c[0] for c in start_limbs]
    # Device congruence mask == host residue-set membership, lane for lane.
    lanes = [jnp.asarray(col) for col in ints_to_limb_arrays(ns, plan.limbs_n)]
    keep = np.asarray(ve.residue_keep_lanes(plan, lanes))
    allowed = set(residue_filter.get_residue_filter(base))
    want_keep = np.array([n % (base - 1) in allowed for n in ns])
    np.testing.assert_array_equal(keep, want_keep)
    # Fused (nice, pruned) vs the unfused dense count on the same window.
    valid = np.int32(batch - 7)  # exercise the valid-count mask too
    nice_f, pruned = ve.niceonly_filtered_batch(
        plan, batch, start_scalars, valid, use_mxu=use_mxu
    )
    nice_d = ve.niceonly_dense_batch(
        plan, batch, start_scalars, valid, use_mxu=use_mxu
    )
    assert int(nice_f) == int(nice_d), (base, use_mxu)
    want_pruned = int(sum(1 for n in ns[: int(valid)]
                          if n % (base - 1) not in allowed))
    assert int(pruned) == want_pruned, (base, use_mxu)


def test_pallas_fused_matches_dense_b40():
    """The pallas fused-filter stats kernel (interpreter mode off-TPU)
    agrees with the unfused pallas dense count and reports the same pruned
    tally as the host oracle."""
    from nice_tpu.ops import pallas_engine as pe, residue_filter

    base = 40
    plan = get_plan(base)
    lo, _hi = base_range.get_base_range(base)
    batch = 512
    start = lo + 998
    start_arr = np.asarray(
        [c[0] for c in ints_to_limb_arrays([start], plan.limbs_n)],
        dtype=np.uint32,
    )
    valid = np.int32(batch - 3)
    nice_f, pruned = pe.niceonly_fused_batch(plan, batch, start_arr, valid)
    nice_d = pe.niceonly_dense_batch(plan, batch, start_arr, valid)
    assert int(nice_f) == int(nice_d)
    allowed = set(residue_filter.get_residue_filter(base))
    want_pruned = sum(1 for n in range(start, start + int(valid))
                      if n % (base - 1) not in allowed)
    assert int(pruned) == want_pruned


@pytest.mark.slow
def test_widened_histogram_layout_past_510():
    """Base 513 needs 5 histogram rows — impossible under the old 4-row
    pallas cap. With the plan-derived 16-row cap the pallas stats kernel
    must execute it and lay the histogram out identically to the jnp
    engine (row-major 128-lane tile flattening, zero padding rows).

    Executes on a hand-built base-513 plan over a tiny sub-range window
    (d_sq=2, d_cu=3 digits, single-limb numbers): a real 29-limb 513 plan
    is correct but its interpreter-mode compile runs hours on a small CPU
    host, while the 5-row histogram scatter/layout — the surface this
    test exists for — only depends on base, not limb width. Both engines
    consume the same plan, so the differential stays apples-to-apples;
    the real-plan contract at 513 is covered by test_widened_hist_layout
    plus jaxlint's J6 trace probe. Marked slow (~2 min interpreter-mode
    compile), like the b127 widened-tile test in test_pallas_engine.py."""
    from nice_tpu.ops import pallas_engine as pe
    from nice_tpu.ops.limbs import BasePlan, halfwords_for, limbs_for

    base = 513
    d_sq, d_cu = 2, 3
    # n in [65, 512): n^2 spans [513, 513^2) = 2 digits, n^3 spans
    # [513^2, 513^3) = 3 digits, so the exact-digit-count plan contract
    # holds for the whole window.
    start, end = 65, 512
    chunk_e = 1
    while base ** (chunk_e + 1) <= 1 << 16:
        chunk_e += 1
    plan = BasePlan(
        base=base, range_start=start, range_end=end,
        d_sq=d_sq, d_cu=d_cu,
        limbs_n=limbs_for(end),
        limbs_sq=limbs_for(base**d_sq),
        limbs_cu=limbs_for(base**d_cu),
        hw_sq=halfwords_for(base**d_sq),
        hw_cu=halfwords_for(base**d_cu),
        chunk_div=base**chunk_e, chunk_e=chunk_e,
        n_masks=(base + 31) // 32,
        near_miss_cutoff=4,
    )
    assert pe.supports_base(plan), "16-row cap should admit base 513"
    rows = -(-(base + 2) // 128)
    assert rows == 5
    batch = 256
    start_arr = np.asarray(
        [c[0] for c in ints_to_limb_arrays([start], plan.limbs_n)],
        dtype=np.uint32,
    )
    valid = np.int32(end - start)
    hist_pe, nm_pe = pe.detailed_batch(plan, batch, start_arr, valid)
    hist_ve, nm_ve = ve.detailed_batch(
        plan, batch, [jnp.asarray(c) for c in start_arr], jnp.int32(valid)
    )
    hist_pe = np.asarray(hist_pe)
    assert hist_pe.shape == (128 * rows,)
    np.testing.assert_array_equal(
        hist_pe[: base + 2], np.asarray(hist_ve)
    )
    assert not hist_pe[base + 2:].any(), "padding rows must stay zero"
    assert int(nm_pe) == int(nm_ve)


@settings(max_examples=15, deadline=None, derandomize=True)
@given(base=_BASES, frac=st.floats(0, 1), size=st.integers(2, 20_000))
def test_msd_filter_drops_only_non_nice_spans(base, frac, size):
    """Soundness, exhaustively per example: every span the MSD filter DROPS
    from a window must contain zero nice numbers (checked via the stride
    table's early-exit scan — real nice numbers are too rare for random
    windows to contain one, so asserting on survivors alone would be
    vacuous; asserting on the dropped complement tests every example)."""
    fs = _window(base, frac, size)
    table = stride_filter.get_stride_table(base, 1)
    if table.num_residues == 0:
        return  # base provably has no nice numbers at all
    ranges = sorted(
        msd_filter.get_valid_ranges(fs, base, min_range_size=256),
        key=lambda r: r.start(),
    )
    dropped = []
    pos = fs.start()
    for r in ranges:
        if r.start() > pos:
            dropped.append((pos, r.start()))
        pos = max(pos, r.end())
    if pos < fs.end():
        dropped.append((pos, fs.end()))
    for lo, hi in dropped:
        found = table.iterate_range(FieldSize(lo, hi), base)
        assert not found, (base, lo, hi, [n.number for n in found])
