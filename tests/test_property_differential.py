"""Property-based differential tests: random (base, window) against the
scalar oracle.

The reference's test strategy leans on randomized differential checks
between its engines (SURVEY.md section 4); here hypothesis drives the same
cross-engine contract: for ANY base and ANY window inside the base range,
the vectorized jnp engine, the Pallas kernels (interpreter mode off-TPU),
and the native C++ engine must reproduce the scalar oracle bit-for-bit.
"""

import numpy as np
from hypothesis import given, settings, strategies as st

# Derandomized: interpreter-mode kernel compiles make unlucky random draws
# arbitrarily slow; a fixed example set keeps suite runtime bounded and CI
# reproducible while still sweeping base/offset/size combinations no
# hand-written table covers.

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar
from nice_tpu.ops import lsd_filter, msd_filter, stride_filter


def _window(base: int, offset_frac: float, size: int) -> FieldSize:
    lo, hi = base_range.get_base_range(base)
    # Clamp: float multiplication can round past hi-1 at 1e16-scale ranges.
    start = min(lo + int((hi - lo - 1) * offset_frac), hi - 1)
    return FieldSize(start, min(start + size, hi))


# Bases with nonempty ranges and (for the pallas path) <= 4 u32 limbs.
_BASES = st.sampled_from([10, 14, 17, 20, 24, 30, 35, 40, 45, 50, 60, 70, 80, 95])


@settings(max_examples=8, deadline=None, derandomize=True)
@given(base=_BASES, frac=st.floats(0, 1), size=st.integers(1, 4000))
def test_detailed_jnp_matches_scalar(base, frac, size):
    fs = _window(base, frac, size)
    got = engine.process_range_detailed(fs, base, backend="jnp", batch_size=1 << 10)
    want = scalar.process_range_detailed(fs, base)
    assert got == want


@settings(max_examples=6, deadline=None, derandomize=True)
@given(base=st.sampled_from([10, 20, 40, 50]), frac=st.floats(0, 1), size=st.integers(1, 4000))
def test_niceonly_strided_matches_scalar(base, frac, size):
    fs = _window(base, frac, size)
    got = engine.process_range_niceonly(fs, base, backend="pallas", batch_size=1 << 10)
    want = scalar.process_range_niceonly(fs, base)
    assert [n.number for n in got.nice_numbers] == [
        n.number for n in want.nice_numbers
    ]


@settings(max_examples=20, deadline=None, derandomize=True)
@given(base=st.integers(5, 256), k=st.integers(1, 2))
def test_lsd_bitmap_oracle_property(base, k):
    if base ** k > 40_000:
        return  # keep the scalar transcription fast
    assert np.array_equal(
        lsd_filter._bitmap_scalar(base, k),
        lsd_filter.get_valid_multi_lsd_bitmap(base, k),
    )


@settings(max_examples=15, deadline=None, derandomize=True)
@given(base=_BASES, frac=st.floats(0, 1), size=st.integers(2, 20_000))
def test_msd_filter_drops_only_non_nice_spans(base, frac, size):
    """Soundness, exhaustively per example: every span the MSD filter DROPS
    from a window must contain zero nice numbers (checked via the stride
    table's early-exit scan — real nice numbers are too rare for random
    windows to contain one, so asserting on survivors alone would be
    vacuous; asserting on the dropped complement tests every example)."""
    fs = _window(base, frac, size)
    table = stride_filter.get_stride_table(base, 1)
    if table.num_residues == 0:
        return  # base provably has no nice numbers at all
    ranges = sorted(
        msd_filter.get_valid_ranges(fs, base, min_range_size=256),
        key=lambda r: r.start(),
    )
    dropped = []
    pos = fs.start()
    for r in ranges:
        if r.start() > pos:
            dropped.append((pos, r.start()))
        pos = max(pos, r.end())
    if pos < fs.end():
        dropped.append((pos, fs.end()))
    for lo, hi in dropped:
        found = table.iterate_range(FieldSize(lo, hi), base)
        assert not found, (base, lo, hi, [n.number for n in found])
