"""Property-based differential tests: random (base, window) against the
scalar oracle.

The reference's test strategy leans on randomized differential checks
between its engines (SURVEY.md section 4); here hypothesis drives the same
cross-engine contract: for ANY base and ANY window inside the base range,
the vectorized jnp engine, the Pallas kernels (interpreter mode off-TPU),
and the native C++ engine must reproduce the scalar oracle bit-for-bit.
"""

import jax.numpy as jnp
import numpy as np
import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    # hypothesis is an optional dependency: without it the property tests
    # SKIP (visibly, instead of failing the whole module's collection and
    # silently taking the fixed-candidate differential tests below with it).
    class _AnyStrategy:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _AnyStrategy()

    def settings(**_kw):
        return lambda fn: fn

    def given(**_kw):
        def deco(fn):
            @pytest.mark.skip(reason="hypothesis not installed")
            def stub():
                pass

            stub.__name__ = fn.__name__
            stub.__doc__ = fn.__doc__
            return stub

        return deco


# Derandomized: interpreter-mode kernel compiles make unlucky random draws
# arbitrarily slow; a fixed example set keeps suite runtime bounded and CI
# reproducible while still sweeping base/offset/size combinations no
# hand-written table covers.

from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar, vector_engine as ve
from nice_tpu.ops import lsd_filter, msd_filter, stride_filter
from nice_tpu.ops.limbs import (
    get_plan,
    ints_to_limb_arrays,
    limb_arrays_to_ints,
)


def _window(base: int, offset_frac: float, size: int) -> FieldSize:
    lo, hi = base_range.get_base_range(base)
    # Clamp: float multiplication can round past hi-1 at 1e16-scale ranges.
    start = min(lo + int((hi - lo - 1) * offset_frac), hi - 1)
    return FieldSize(start, min(start + size, hi))


# Bases with nonempty ranges and (for the pallas path) <= 4 u32 limbs.
_BASES = st.sampled_from([10, 14, 17, 20, 24, 30, 35, 40, 45, 50, 60, 70, 80, 95])


@settings(max_examples=8, deadline=None, derandomize=True)
@given(base=_BASES, frac=st.floats(0, 1), size=st.integers(1, 4000))
def test_detailed_jnp_matches_scalar(base, frac, size):
    fs = _window(base, frac, size)
    got = engine.process_range_detailed(fs, base, backend="jnp", batch_size=1 << 10)
    want = scalar.process_range_detailed(fs, base)
    assert got == want


@settings(max_examples=6, deadline=None, derandomize=True)
@given(base=st.sampled_from([10, 20, 40, 50]), frac=st.floats(0, 1), size=st.integers(1, 4000))
def test_niceonly_strided_matches_scalar(base, frac, size):
    fs = _window(base, frac, size)
    got = engine.process_range_niceonly(fs, base, backend="pallas", batch_size=1 << 10)
    want = scalar.process_range_niceonly(fs, base)
    assert [n.number for n in got.nice_numbers] == [
        n.number for n in want.nice_numbers
    ]


@settings(max_examples=20, deadline=None, derandomize=True)
@given(base=st.integers(5, 256), k=st.integers(1, 2))
def test_lsd_bitmap_oracle_property(base, k):
    if base ** k > 40_000:
        return  # keep the scalar transcription fast
    assert np.array_equal(
        lsd_filter._bitmap_scalar(base, k),
        lsd_filter.get_valid_multi_lsd_bitmap(base, k),
    )


# ---------------------------------------------------------------------------
# Carry-save multiply/square vs Python big-int ground truth.
#
# The carry-save kernels (ops/vector_engine.py mul_limbs/sqr_limbs) defer all
# carry propagation to one resolution pass; these tests prove the result
# limbs are BYTE-IDENTICAL to Python's arbitrary-precision n^2 / n^3 across
# the limb widths real plans use (1 limb at b10 up to 13 limbs for n^3 at
# b120), including engineered carry-edge candidates sitting at limb
# boundaries where wrap counting is maximally stressed.
# ---------------------------------------------------------------------------

_DIFF_BASES = [40, 80, 97, 120]


def _carry_edge_candidates(base: int) -> list[int]:
    """Candidates engineered to stress carry-save wrap accounting: range
    endpoints, values straddling 2^32k limb boundaries (max-1/max/min limb
    patterns produce the longest carry chains in a propagating scheme), and
    seeded randoms for breadth."""
    import random

    lo, hi = base_range.get_base_range(base)
    cands = {lo, hi - 1, (lo + hi) // 2}
    for k in range(1, 8):
        b = 1 << (32 * k)
        for n in (b - 1, b, b + 1, b - 2, (b - 1) // 3):  # 0x5555... pattern
            if lo <= n < hi:
                cands.add(n)
    # All-ones limbs below hi: the square's partial products are all maximal.
    ones = 0
    while True:
        ones = (ones << 32) | 0xFFFFFFFF
        if ones >= hi:
            break
        if ones >= lo:
            cands.add(ones)
    rng = random.Random(base)  # seeded: deterministic suite
    for _ in range(16):
        cands.add(rng.randrange(lo, hi))
    return sorted(cands)


def _bigint_limbs(x: int, num_limbs: int) -> list[int]:
    return [(x >> (32 * i)) & 0xFFFFFFFF for i in range(num_limbs)]


@pytest.mark.parametrize("base", _DIFF_BASES)
@pytest.mark.parametrize("carry_interval", [0, 1, 3])
def test_square_cube_limbs_match_bigint(base, carry_interval):
    """sqr_limbs(n) == n^2 and mul_limbs(n^2, n) == n^3 exactly, limb for
    limb, against Python big-int — for every engineered carry-edge candidate,
    at every carry-resolution cadence (the interval is a perf knob and must
    be bit-invisible)."""
    plan = get_plan(base)
    ns = _carry_edge_candidates(base)
    n_limbs = ints_to_limb_arrays(ns, plan.limbs_n)
    n_dev = [jnp.asarray(col) for col in n_limbs]
    sq = ve.sqr_limbs(n_dev, plan.limbs_sq, resolve_every=carry_interval)
    cu = ve.mul_limbs(sq, n_dev, plan.limbs_cu, resolve_every=carry_interval)
    sq_host = [np.asarray(col) for col in sq]
    cu_host = [np.asarray(col) for col in cu]
    for row, n in enumerate(ns):
        want_sq = _bigint_limbs(n * n, plan.limbs_sq)
        want_cu = _bigint_limbs(n * n * n, plan.limbs_cu)
        got_sq = [int(col[row]) for col in sq_host]
        got_cu = [int(col[row]) for col in cu_host]
        assert got_sq == want_sq, (base, n, carry_interval)
        assert got_cu == want_cu, (base, n, carry_interval)


def test_square_cube_limbs_match_bigint_b510_worst_cadence():
    """Runtime witness for the jaxlint J2 headroom theorem at its hardest
    point: base 510 is the widest sweep plan (29 u32 limbs — the deepest
    carry-save columns any supported base produces) and resolve_every =
    limbs_n is the laziest carry cadence the autotuner may pick, so wrap
    counters accumulate across a full limb pass before any resolution. The
    interval analysis proves this cannot overflow; this test executes it
    against Python big-int on engineered carry-edge candidates. A thinned
    candidate set keeps the eager 29-limb math inside the tier-1 budget."""
    base = 510
    plan = get_plan(base)
    all_cands = _carry_edge_candidates(base)
    # endpoints + the all-ones-limbs patterns + an evenly-thinned remainder
    ns = sorted(set(all_cands[:2] + all_cands[-2:] + all_cands[:: max(1, len(all_cands) // 6)]))
    n_dev = [jnp.asarray(col) for col in ints_to_limb_arrays(ns, plan.limbs_n)]
    for carry_interval in (0, plan.limbs_n):
        sq = ve.sqr_limbs(n_dev, plan.limbs_sq, resolve_every=carry_interval)
        cu = ve.mul_limbs(sq, n_dev, plan.limbs_cu, resolve_every=carry_interval)
        sq_host = [np.asarray(col) for col in sq]
        cu_host = [np.asarray(col) for col in cu]
        for row, n in enumerate(ns):
            want_sq = _bigint_limbs(n * n, plan.limbs_sq)
            want_cu = _bigint_limbs(n * n * n, plan.limbs_cu)
            got_sq = [int(col[row]) for col in sq_host]
            got_cu = [int(col[row]) for col in cu_host]
            assert got_sq == want_sq, (base, n, carry_interval)
            assert got_cu == want_cu, (base, n, carry_interval)


@pytest.mark.parametrize("base", _DIFF_BASES)
def test_sqr_equals_general_mul(base):
    """The squaring specialization (symmetry: each cross product accumulated
    twice) must agree with the general carry-save multiply on the same
    inputs — same out_len, same values, limb for limb."""
    plan = get_plan(base)
    ns = _carry_edge_candidates(base)
    n_dev = [jnp.asarray(col) for col in ints_to_limb_arrays(ns, plan.limbs_n)]
    via_sqr = ve.sqr_limbs(n_dev, plan.limbs_sq)
    via_mul = ve.mul_limbs(n_dev, n_dev, plan.limbs_sq)
    for a, b in zip(via_sqr, via_mul):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_limb_array_roundtrip():
    """Host packing helpers invert each other across widths."""
    xs = [0, 1, 0xFFFFFFFF, 1 << 32, (1 << 96) - 1, (1 << 128) - 5]
    cols = ints_to_limb_arrays(xs, 5)
    assert len(cols) == 5 and all(c.shape == (len(xs),) for c in cols)
    assert limb_arrays_to_ints(cols) == xs


@settings(max_examples=15, deadline=None, derandomize=True)
@given(base=_BASES, frac=st.floats(0, 1), size=st.integers(2, 20_000))
def test_msd_filter_drops_only_non_nice_spans(base, frac, size):
    """Soundness, exhaustively per example: every span the MSD filter DROPS
    from a window must contain zero nice numbers (checked via the stride
    table's early-exit scan — real nice numbers are too rare for random
    windows to contain one, so asserting on survivors alone would be
    vacuous; asserting on the dropped complement tests every example)."""
    fs = _window(base, frac, size)
    table = stride_filter.get_stride_table(base, 1)
    if table.num_residues == 0:
        return  # base provably has no nice numbers at all
    ranges = sorted(
        msd_filter.get_valid_ranges(fs, base, min_range_size=256),
        key=lambda r: r.start(),
    )
    dropped = []
    pos = fs.start()
    for r in ranges:
        if r.start() > pos:
            dropped.append((pos, r.start()))
        pos = max(pos, r.end())
    if pos < fs.end():
        dropped.append((pos, fs.end()))
    for lo, hi in dropped:
        found = table.iterate_range(FieldSize(lo, hi), base)
        assert not found, (base, lo, hi, [n.number for n in found])
