"""Telemetry layer tests: metrics registry text output, span nesting/timing,
engine pipeline counters during real field runs, client/server /metrics
surfaces, and the simulated backend-init hang naming its stalled phase."""

import json
import urllib.request

import pytest

from nice_tpu import obs
from nice_tpu.core.types import FieldSize
from nice_tpu.obs import metrics as obs_metrics
from nice_tpu.obs import series
from nice_tpu.ops import engine, scalar


# --- metrics registry ------------------------------------------------------

def test_counter_gauge_text_output():
    reg = obs_metrics.Registry()
    c = reg.counter("t_requests_total", "help text", labelnames=("ep",))
    c.labels("a").inc()
    c.labels("a").inc(2)
    c.labels("b").inc()
    g = reg.gauge("t_depth", "queue depth")
    g.set(7)
    text = reg.render()
    assert "# HELP t_requests_total help text" in text
    assert "# TYPE t_requests_total counter" in text
    assert 't_requests_total{ep="a"} 3' in text
    assert 't_requests_total{ep="b"} 1' in text
    assert "# TYPE t_depth gauge" in text
    assert "t_depth 7" in text


def test_histogram_cumulative_buckets():
    reg = obs_metrics.Registry()
    h = reg.histogram(
        "t_seconds", "latency", labelnames=("op",), buckets=(0.1, 1.0, 10.0)
    )
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.labels("x").observe(v)
    text = reg.render()
    assert 't_seconds_bucket{op="x",le="0.1"} 1' in text
    assert 't_seconds_bucket{op="x",le="1.0"} 3' in text
    assert 't_seconds_bucket{op="x",le="10.0"} 4' in text
    assert 't_seconds_bucket{op="x",le="+Inf"} 5' in text
    assert 't_seconds_count{op="x"} 5' in text
    assert 't_seconds_sum{op="x"} 56.05' in text


def test_registration_is_idempotent():
    reg = obs_metrics.Registry()
    a = reg.counter("t_total", "x")
    b = reg.counter("t_total", "x")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("t_total", "wrong kind")
    with pytest.raises(ValueError):
        reg.counter("t_total", "x", labelnames=("other",))


def test_declared_series_render_before_any_activity():
    # Pre-seeded label combinations must render even when never touched
    # (values may be nonzero here: other tests share the global registry).
    text = obs.render()
    assert 'nice_engine_batch_kernel_seconds_bucket{path="strided",le="+Inf"}' in text
    assert "nice_engine_dispatch_window_occupancy" in text
    assert 'nice_engine_host_fallback_total{reason="host-route"}' in text
    assert "nice_engine_audit_total" in text
    # The zero-rendering guarantee itself, on a fresh registry:
    reg = obs_metrics.Registry()
    reg.counter("t_untouched_total", "x")
    reg.gauge("t_untouched", "x")
    fresh = reg.render()
    assert "t_untouched_total 0" in fresh
    assert "t_untouched 0" in fresh


# --- trace spans -----------------------------------------------------------

def test_span_nesting_and_timing(tmp_path, monkeypatch):
    sink = tmp_path / "trace.jsonl"
    monkeypatch.setenv("NICE_TPU_TRACE", str(sink))
    with obs.span("outer", base=40):
        with obs.span("inner"):
            pass
    events = [json.loads(line) for line in sink.read_text().splitlines()]
    assert [(e["name"], e["event"]) for e in events] == [
        ("outer", "begin"),
        ("inner", "begin"),
        ("inner", "end"),
        ("outer", "end"),
    ]
    assert events[0]["base"] == 40
    assert events[1]["parent"] == "outer" and events[1]["depth"] == 1
    inner_end = events[2]
    assert inner_end["status"] == "ok"
    assert inner_end["wall_secs"] >= 0.0
    assert "process_secs" in inner_end


def test_span_error_status_and_begin_before_body(tmp_path, monkeypatch):
    sink = tmp_path / "trace.jsonl"
    monkeypatch.setenv("NICE_TPU_TRACE", str(sink))
    with pytest.raises(RuntimeError):
        with obs.span("doomed"):
            # The begin event must already be durable: a hang (or crash)
            # inside the span still leaves evidence of what was running.
            events = [
                json.loads(line) for line in sink.read_text().splitlines()
            ]
            assert events and events[-1] == {
                **events[-1], "name": "doomed", "event": "begin",
            }
            raise RuntimeError("boom")
    events = [json.loads(line) for line in sink.read_text().splitlines()]
    assert events[-1]["event"] == "end"
    assert events[-1]["status"] == "error"


def test_trace_disabled_without_env(monkeypatch):
    monkeypatch.delenv("NICE_TPU_TRACE", raising=False)
    assert not obs.trace_enabled()
    with obs.span("silent"):
        pass  # no sink: must not raise


# --- engine counters during a real field run -------------------------------

def test_engine_counters_increment_scalar_vs_jax(monkeypatch):
    # Single-chip path: the conftest's 8-device virtual mesh would route
    # through jax.shard_map, unavailable in this jax build.
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    # Base 10's valid range is [47, 100): wholly in range, no slivers.
    base = 10
    rng = FieldSize(47, 100)
    numbers = series.ENGINE_NUMBERS.labels("detailed")
    kernel_hist = series.ENGINE_BATCH_KERNEL_SECONDS
    count_before = numbers.value()
    sums_before = kernel_hist.label_sums()[("detailed",)][1]
    got = engine.process_range_detailed(rng, base, backend="jax",
                                        batch_size=1 << 10)
    want = scalar.process_range_detailed(rng, base)
    assert got == want  # instrumentation must not perturb results
    assert numbers.value() == count_before + rng.range_size
    assert kernel_hist.label_sums()[("detailed",)][1] > sums_before


def test_engine_sliver_fallback_counter(monkeypatch):
    monkeypatch.setenv("NICE_TPU_SHARD", "0")
    fallback = series.ENGINE_HOST_FALLBACK.labels("sliver")
    before = fallback.value()
    # Range straddles the base-range start (47): [40, 47) is a pre sliver.
    rng = FieldSize(40, 100)
    engine.process_range_detailed(rng, 10, backend="jax", batch_size=1 << 10)
    assert fallback.value() == before + 1


# --- /metrics HTTP surfaces ------------------------------------------------

def _scrape(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as resp:
        return resp.read().decode()


def test_client_metrics_server_exposes_engine_series():
    server = obs.serve_metrics(0)
    try:
        body = _scrape(server.server_address[1])
    finally:
        server.shutdown()
    assert "# TYPE nice_engine_batch_kernel_seconds histogram" in body
    assert "nice_engine_dispatch_window_occupancy" in body
    assert "nice_engine_host_fallback_total" in body
    assert "nice_engine_audit_total" in body
    assert "nice_client_request_seconds" in body


def test_server_metrics_exposes_engine_series():
    from nice_tpu.server.app import Metrics

    m = Metrics()
    m.record("/submit", 200, 0.003)
    text = m.render()
    # API series (per-context registry)...
    assert 'nice_api_requests_total{endpoint="/submit",status="200"} 1' in text
    assert 'nice_api_request_seconds_bucket{endpoint="/submit",le="0.005"} 1' in text
    # ...deprecated alias...
    assert 'nice_api_request_seconds_total{endpoint="/submit"}' in text
    # ...plus the engine pipeline series from the global registry.
    assert "nice_engine_batch_kernel_seconds" in text
    assert "nice_engine_stride_window_occupancy" in text
    assert "nice_engine_host_fallback_total" in text


# --- simulated backend-init hang -------------------------------------------

def test_backend_init_hang_names_stalled_phase(tmp_path, monkeypatch):
    import time

    from nice_tpu.utils import platform as plat

    sink = tmp_path / "trace.jsonl"
    monkeypatch.setenv("NICE_TPU_TRACE", str(sink))

    def wedged_devices():
        time.sleep(30.0)
        return 0

    n, exc = plat.probe_backend(
        timeout_s=0.3, platform="cpu", _devices_fn=wedged_devices
    )
    assert n is None
    assert isinstance(exc, TimeoutError)
    assert "devices" in str(exc)  # names the stalled phase
    events = [json.loads(line) for line in sink.read_text().splitlines()]
    begun = [
        e for e in events
        if e["name"] == "backend-init.devices" and e["event"] == "begin"
    ]
    ended = [
        e for e in events
        if e["name"] == "backend-init.devices" and e["event"] == "end"
    ]
    assert begun and not ended  # begin-without-end: the hang left evidence
    timeouts = [
        e for e in events
        if e["name"] == "backend-init" and e["event"] == "timeout"
    ]
    assert timeouts and timeouts[0]["phase"] == "devices"


def test_probe_backend_success_records_phases():
    from nice_tpu.obs.series import BACKEND_INIT_SECONDS
    from nice_tpu.utils import platform as plat

    before = BACKEND_INIT_SECONDS.label_sums()[("devices",)][1]
    n, exc = plat.probe_backend(timeout_s=30.0, platform="cpu")
    assert exc is None and n >= 1
    assert BACKEND_INIT_SECONDS.label_sums()[("devices",)][1] == before + 1


def test_probe_backend_subprocess_kills_hung_init(monkeypatch):
    """The hard watchdog: a wedged init is killed with its child process —
    the parent gets a TimeoutError promptly instead of a zombie thread."""
    import time

    from nice_tpu.utils import platform as plat

    monkeypatch.setenv("NICE_PROBE_TEST_HANG", "30")
    t0 = time.monotonic()
    n, exc = plat.probe_backend_subprocess(timeout_s=0.5, platform="cpu")
    assert n is None
    assert isinstance(exc, TimeoutError)
    assert "killed" in str(exc)
    assert time.monotonic() - t0 < 10.0  # killed at the timeout, not 30s


def test_probe_backend_subprocess_counts_devices(monkeypatch):
    from nice_tpu.utils import platform as plat

    monkeypatch.delenv("NICE_PROBE_TEST_HANG", raising=False)
    n, exc = plat.probe_backend_subprocess(timeout_s=120.0, platform="cpu")
    assert exc is None and n >= 1
