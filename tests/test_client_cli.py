"""Client CLI flag plumbing (reference client/src/main.rs:64-196)."""

import logging

import pytest

from nice_tpu.client import main as cli
from nice_tpu.ops import engine


def test_threads_flag_round_trips(monkeypatch):
    monkeypatch.delenv("NICE_THREADS", raising=False)
    args = cli.build_parser().parse_args(["--threads", "7", "detailed"])
    assert args.threads == 7
    # main() wires the flag into NICE_THREADS; replicate that wiring and
    # confirm the native pool sizing sees it.
    import os

    monkeypatch.setenv("NICE_THREADS", str(args.threads))
    assert engine._native_threads() == 7


def test_threads_env_default(monkeypatch):
    monkeypatch.setenv("NICE_THREADS", "3")
    args = cli.build_parser().parse_args(["detailed"])
    assert args.threads == 3


def test_progress_logger_throttles_and_reports(monkeypatch, caplog):
    cb = cli._progress_logger(0.0)
    assert cb is None  # disabled
    cb = cli._progress_logger(1e-9)  # report on (almost) every call
    with caplog.at_level(logging.INFO, logger="nice_tpu.client"):
        cb(1, 100)
        cb(100, 100)  # terminal call suppressed (the summary line covers it)
    msgs = [r.message for r in caplog.records]
    assert any("progress" in m and "ETA" in m for m in msgs)
    assert not any("100.0%" in m for m in msgs)


def test_progress_flag_parses(monkeypatch):
    monkeypatch.setenv("NICE_PROGRESS_SECS", "2.5")
    args = cli.build_parser().parse_args(["detailed"])
    assert args.progress_secs == 2.5


def test_native_backend_reports_progress():
    from nice_tpu import native
    from nice_tpu.core import base_range
    from nice_tpu.core.types import FieldSize

    if not native.available():
        pytest.skip("native engine unavailable")
    br = base_range.get_base_range_field(10)
    seen = []
    engine.process_range_detailed(
        br, 10, backend="native", progress=lambda d, t: seen.append((d, t))
    )
    assert seen and seen[-1][0] == seen[-1][1] == br.size()
