# just recipes (reference justfile parity)

# run the full test suite (CPU-only, Pallas interpreter mode)
test:
    python -m pytest tests/ -q

# quick subset: core + filters + native differentials
test-fast:
    python -m pytest tests/test_base_range.py tests/test_core_misc.py \
        tests/test_filters.py tests/test_native.py -q

# project-invariant static analysis (nicelint) + optional ruff floor
lint:
    #!/usr/bin/env bash
    set -euo pipefail
    python scripts/nicelint.py --strict
    if command -v ruff >/dev/null 2>&1; then
        ruff check nice_tpu scripts tests
    else
        echo "lint: ruff not installed; skipped the generic floor"
    fi

# jaxpr-level kernel verification (traces real plans; CPU-only, ~7 min full
# sweep — use `just jaxlint-fast` while iterating)
jaxlint:
    JAX_PLATFORMS=cpu python scripts/jaxlint.py --strict

# jaxlint over the cheapest base only (seconds, catches most drift)
jaxlint-fast:
    JAX_PLATFORMS=cpu python scripts/jaxlint.py --strict --bases 40

# thread-ownership race analysis against the ThreadRegistry contract
racelint:
    JAX_PLATFORMS=cpu python scripts/racelint.py --strict

# deterministic interleaving explorer over the scenario pack
racecheck:
    JAX_PLATFORMS=cpu python scripts/racecheck_smoke.py

# regenerate the runtime lock-order graph racelint R2 cross-checks
lockorder:
    JAX_PLATFORMS=cpu python -m nice_tpu.utils.lockdep --dump-graph docs/lockorder.json

# rewrite the nicelint ratchet baseline (justify every entry you keep)
lint-baseline:
    python scripts/nicelint.py --update-baseline

# regenerate docs/KNOBS.md + README knob tables from the knob registry
knobs-docs:
    python scripts/nicelint.py --write-docs

# build the C++ native host engine
native:
    make -C nice_tpu/native

# real-chip benchmark, one JSON line (NICE_BENCH_MODE to pick the field)
bench:
    python bench.py

# offline client benchmark across the suite
benchmark mode="extra-large" backend="jax":
    python -m nice_tpu.client --benchmark {{mode}} --backend {{backend}}

# serve the API + dashboard on :8127 (seeds base 40 on first run)
serve db="nice.db":
    python -m nice_tpu.server --db {{db}} --init-base 40

# run one claim->process->submit iteration against a local server
client api="http://127.0.0.1:8127":
    python -m nice_tpu.client detailed --api-base {{api}}

# consensus + stats + cache refresh pass
jobs db="nice.db":
    python -m nice_tpu.jobs --db {{db}}

# filter effectiveness report (cached by parameter hash)
filter-effectiveness base="40":
    python scripts/filter_effectiveness.py --base {{base}}

# grouped survival chart from cached filter-effectiveness measurements
filter-chart out="/tmp/filters.png":
    python scripts/filter_effectiveness_chart.py --cache --out {{out}}

# inspect a number's niceness properties across bases
inspect number="69":
    python scripts/inspect_number.py {{number}}

# gaussian fit of per-base uniques distributions from the ledger
gaussian db="nice.db":
    python scripts/gaussian.py --db {{db}}

# daily + cumulative search-progress charts from the ledger
progress db="nice.db" out="/tmp/progress":
    python scripts/progress_charts.py --db {{db}} --out {{out}}

# audit the C++ MSD filter against the Python definition
msd-crosscheck:
    python scripts/msd_crosscheck.py

# profile the engine hot path with cProfile
profile mode="large":
    NICE_BENCH_MODE={{mode}} python -m cProfile -s cumtime bench.py | head -40

# tag and push a release: verifies the version is consistent everywhere
# (package, CHANGELOG) before tagging; the release workflow does the rest
tag-release:
    #!/usr/bin/env bash
    set -euo pipefail
    v="$(python -c 'import nice_tpu; print(nice_tpu.__version__)')"
    grep -q "\[$v\]" CHANGELOG.md || { echo "CHANGELOG.md missing [$v]"; exit 1; }
    [ -z "$(git status --porcelain)" ] || { echo "working tree dirty"; exit 1; }
    git tag "v$v"
    git push origin "v$v"
    echo "tagged v$v; release workflow publishes artifacts + image"
