# just recipes (reference justfile parity)

# run the full test suite (CPU-only, Pallas interpreter mode)
test:
    python -m pytest tests/ -q

# quick subset: core + filters + native differentials
test-fast:
    python -m pytest tests/test_base_range.py tests/test_core_misc.py \
        tests/test_filters.py tests/test_native.py -q

# build the C++ native host engine
native:
    make -C nice_tpu/native

# real-chip benchmark, one JSON line (NICE_BENCH_MODE to pick the field)
bench:
    python bench.py

# offline client benchmark across the suite
benchmark mode="extra-large" backend="jax":
    python -m nice_tpu.client --benchmark {{mode}} --backend {{backend}}

# serve the API + dashboard on :8127 (seeds base 40 on first run)
serve db="nice.db":
    python -m nice_tpu.server --db {{db}} --init-base 40

# run one claim->process->submit iteration against a local server
client api="http://127.0.0.1:8127":
    python -m nice_tpu.client detailed --api-base {{api}}

# consensus + stats + cache refresh pass
jobs db="nice.db":
    python -m nice_tpu.jobs --db {{db}}

# filter effectiveness report (cached by parameter hash)
filter-effectiveness base="40":
    python scripts/filter_effectiveness.py --base {{base}}

# audit the C++ MSD filter against the Python definition
msd-crosscheck:
    python scripts/msd_crosscheck.py

# profile the engine hot path with cProfile
profile mode="large":
    NICE_BENCH_MODE={{mode}} python -m cProfile -s cumtime bench.py | head -40
