// Worker pool: split a claimed field into contiguous BigInt sub-ranges across
// ~80% of cores, aggregate per-worker results, throttle progress updates,
// and abort when at least half the workers fail (reference
// web/search/worker-pool.js:116-339).

"use strict";

class WorkerPool {
  constructor(maxWorkers) {
    const cores = navigator.hardwareConcurrency || 4;
    this.maxWorkers = maxWorkers || Math.max(1, Math.floor(cores * 0.8));
  }

  // data: {base, range_start, range_end}; onProgress(processed, total)
  processClaimData(data, onProgress) {
    const start = BigInt(data.range_start);
    const end = BigInt(data.range_end);
    const total = end - start;
    const n = this.maxWorkers;
    const chunk = total / BigInt(n);

    return new Promise((resolve, reject) => {
      const workers = new Array(n).fill(null);
      const results = new Array(n).fill(null);
      const retried = new Array(n).fill(false);
      const workerProcessed = new Array(n).fill(0n);
      let done = 0;
      let failures = 0;
      let lastReport = 0;
      let settled = false;

      // A field submit must cover the WHOLE range: partial aggregates are
      // never valid results (the server recomputes and would reject — or
      // worse, record a wrong distribution). Every failed sub-range gets one
      // retry on a fresh worker (even in a 1-worker pool); a sub-range
      // failing twice aborts the field — which also bounds systemic failures
      // at one retry round.
      const finish = (err) => {
        if (settled) return;
        settled = true;
        workers.forEach((w) => w && w.terminate());
        if (err) reject(err);
        else resolve(WorkerPool.aggregate(results, data.base));
      };

      let maxProcessed = 0n; // keep the progress display monotonic: a retry
      // resets its worker's counter (the sub-range really is re-processed),
      // but the bar should not jump backwards while it catches up.
      const report = () => {
        const now = Date.now();
        if (now - lastReport > 250) {
          lastReport = now;
          const processed = workerProcessed.reduce((a, b) => a + b, 0n);
          if (processed > maxProcessed) maxProcessed = processed;
          onProgress && onProgress(maxProcessed, total);
        }
      };

      const launch = (i, subStart, subEnd) => {
        const w = new Worker("worker.js");
        workers[i] = w;
        w.onmessage = (e) => {
          const msg = e.data;
          if (msg.type === "progress") {
            workerProcessed[i] += BigInt(msg.processed);
            report();
          } else if (msg.type === "complete") {
            results[i] = msg.result;
            if (++done === n) finish();
          } else if (msg.type === "error") {
            onFailure(i, subStart, subEnd, msg.message);
          }
        };
        w.onerror = (err) => onFailure(i, subStart, subEnd, err.message);
        w.postMessage({
          type: "process",
          start: subStart.toString(),
          end: subEnd.toString(),
          base: data.base,
        });
      };

      const onFailure = (i, subStart, subEnd, message) => {
        console.error(`worker ${i} failed:`, message);
        workers[i].terminate();
        workerProcessed[i] = 0n; // the retry re-processes from the start
        failures++;
        if (!retried[i]) {
          retried[i] = true;
          launch(i, subStart, subEnd);
        } else {
          finish(
            new Error(
              `sub-range ${i} failed twice (${message}); ` +
              `${failures}/${n} total failures; aborting field`
            )
          );
        }
      };

      for (let i = 0; i < n; i++) {
        const subStart = start + BigInt(i) * chunk;
        const subEnd = i === n - 1 ? end : subStart + chunk;
        launch(i, subStart, subEnd);
      }
    });
  }

  // Merge per-worker {distribution, nice_numbers} (reference
  // worker-pool.js:427-466).
  static aggregate(results, base) {
    const distribution = {};
    for (let u = 1; u <= base; u++) distribution[u] = 0;
    const niceNumbers = [];
    for (const r of results) {
      for (const [u, count] of Object.entries(r.distribution)) {
        distribution[u] = (distribution[u] || 0) + count;
      }
      niceNumbers.push(...r.nice_numbers);
    }
    niceNumbers.sort((a, b) => (BigInt(a.number) < BigInt(b.number) ? -1 : 1));
    return { distribution, nice_numbers: niceNumbers };
  }
}

window.WorkerPool = WorkerPool;
