// Worker pool: split a claimed field into contiguous BigInt sub-ranges across
// ~80% of cores, aggregate per-worker results, throttle progress updates,
// and abort when at least half the workers fail (reference
// web/search/worker-pool.js:116-339).

"use strict";

class WorkerPool {
  constructor(maxWorkers) {
    const cores = navigator.hardwareConcurrency || 4;
    this.maxWorkers = maxWorkers || Math.max(1, Math.floor(cores * 0.8));
  }

  // data: {base, range_start, range_end}; onProgress(processed, total)
  processClaimData(data, onProgress) {
    const start = BigInt(data.range_start);
    const end = BigInt(data.range_end);
    const total = end - start;
    const n = this.maxWorkers;
    const chunk = total / BigInt(n);

    return new Promise((resolve, reject) => {
      const workers = [];
      const results = new Array(n).fill(null);
      let done = 0;
      let failed = 0;
      let processed = 0n;
      let lastReport = 0;

      const finish = () => {
        workers.forEach((w) => w.terminate());
        const ok = results.filter((r) => r !== null);
        if (failed * 2 >= n) {
          reject(new Error(`${failed}/${n} workers failed; aborting field`));
          return;
        }
        resolve(WorkerPool.aggregate(ok, data.base));
      };

      for (let i = 0; i < n; i++) {
        const subStart = start + BigInt(i) * chunk;
        const subEnd = i === n - 1 ? end : subStart + chunk;
        const w = new Worker("worker.js");
        workers.push(w);
        w.onmessage = (e) => {
          const msg = e.data;
          if (msg.type === "progress") {
            processed += BigInt(msg.processed);
            const now = Date.now();
            if (now - lastReport > 250) {
              lastReport = now;
              onProgress && onProgress(processed, total);
            }
          } else if (msg.type === "complete") {
            results[i] = msg.result;
            if (++done + failed === n) finish();
          } else if (msg.type === "error") {
            console.error("worker error:", msg.message);
            failed++;
            if (done + failed === n) finish();
          }
        };
        w.onerror = (err) => {
          console.error("worker crashed:", err.message);
          failed++;
          if (done + failed === n) finish();
        };
        w.postMessage({
          type: "process",
          start: subStart.toString(),
          end: subEnd.toString(),
          base: data.base,
        });
      }
    });
  }

  // Merge per-worker {distribution, nice_numbers} (reference
  // worker-pool.js:427-466).
  static aggregate(results, base) {
    const distribution = {};
    for (let u = 1; u <= base; u++) distribution[u] = 0;
    const niceNumbers = [];
    for (const r of results) {
      for (const [u, count] of Object.entries(r.distribution)) {
        distribution[u] = (distribution[u] || 0) + count;
      }
      niceNumbers.push(...r.nice_numbers);
    }
    niceNumbers.sort((a, b) => (BigInt(a.number) < BigInt(b.number) ? -1 : 1));
    return { distribution, nice_numbers: niceNumbers };
  }
}

window.WorkerPool = WorkerPool;
