// Browser search worker: processes a sub-range with a BigInt scalar engine.
//
// The reference ships a WASM build of its Rust engine (wasm-client/src/lib.rs)
// driven by this worker's twin (web/search/worker.js); here the engine is
// plain JS BigInt — the same digit-peel algorithm as the scalar oracle
// (nice_tpu/ops/scalar.py), bit-exact with every other backend.
//
// NOTE: the reference worker reads a differently-named result field than its
// WASM emits (a latent mismatch, reference web/search/worker.js:83). Both
// sides here agree on `distribution`.

"use strict";

const PROGRESS_CHUNK = 100000n;

function numUniqueDigits(n, base) {
  const sq = n * n;
  const cu = sq * n;
  let indicator = 0n;
  for (let v = sq; v !== 0n; v /= base) indicator |= 1n << v % base;
  for (let v = cu; v !== 0n; v /= base) indicator |= 1n << v % base;
  // popcount of a BigInt bitmask
  let count = 0;
  for (let m = indicator; m !== 0n; m &= m - 1n) count++;
  return count;
}

function processRange(startStr, endStr, baseInt) {
  const base = BigInt(baseInt);
  const cutoff = Math.floor(0.9 * baseInt); // near-miss cutoff (core/number_stats.py)
  const distribution = {};
  for (let u = 1; u <= baseInt; u++) distribution[u] = 0;
  const niceNumbers = [];

  let n = BigInt(startStr);
  const end = BigInt(endStr);
  let sinceProgress = 0n;
  while (n < end) {
    const u = numUniqueDigits(n, base);
    distribution[u] += 1;
    if (u > cutoff) {
      niceNumbers.push({ number: n.toString(), num_uniques: u });
    }
    n += 1n;
    sinceProgress += 1n;
    if (sinceProgress >= PROGRESS_CHUNK) {
      postMessage({ type: "progress", processed: sinceProgress.toString() });
      sinceProgress = 0n;
    }
  }
  if (sinceProgress > 0n) {
    postMessage({ type: "progress", processed: sinceProgress.toString() });
  }
  return { distribution, nice_numbers: niceNumbers };
}

onmessage = (e) => {
  const msg = e.data;
  if (msg.type !== "process") return;
  try {
    const result = processRange(msg.start, msg.end, msg.base);
    postMessage({ type: "complete", result });
  } catch (err) {
    postMessage({ type: "error", message: String(err) });
  }
};
