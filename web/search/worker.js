// Browser search worker: fixed-width fast engine + BigInt oracle fallback.
//
// The reference ships a WASM build of its Rust engine (wasm-client/src/lib.rs,
// ~2x the JS engine per its README:15) driven by this worker's twin
// (web/search/worker.js). This image has no WASM toolchain, so the compiled
// engine is replaced by a fixed-width 24-bit-limb engine in plain JS — the
// same design as the TPU vector engine (nice_tpu/ops/vector_engine.py:
// fixed limbs, exact f64 24x24->48-bit products, chunked-radix digit peel
// with a constant small divisor, u32 digit-presence masks + popcount) which
// avoids BigInt allocation/division in the hot loop entirely.
//
// Safety: the fast engine SELF-TESTS against the BigInt oracle on the first
// candidates of every field and falls back to the oracle on any mismatch
// (the probe-and-degrade pattern used across this codebase); detailed
// results are additionally recomputed server-side on submit.
//
// NOTE: the reference worker reads a differently-named result field than its
// WASM emits (a latent mismatch, reference web/search/worker.js:83). Both
// sides here agree on `distribution`.

"use strict";

const PROGRESS_CHUNK = 100000;

// ---------------------------------------------------------------------------
// BigInt oracle (previous engine; kept as self-test reference + fallback)
// ---------------------------------------------------------------------------

function numUniqueDigits(n, base) {
  const sq = n * n;
  const cu = sq * n;
  let indicator = 0n;
  for (let v = sq; v !== 0n; v /= base) indicator |= 1n << v % base;
  for (let v = cu; v !== 0n; v /= base) indicator |= 1n << v % base;
  let count = 0;
  for (let m = indicator; m !== 0n; m &= m - 1n) count++;
  return count;
}

// ---------------------------------------------------------------------------
// Fixed-width fast engine: 24-bit limbs in f64 (exact up to 2^53)
// ---------------------------------------------------------------------------

const LIMB = 1 << 24;

function popcount32(x) {
  x -= (x >>> 1) & 0x55555555;
  x = (x & 0x33333333) + ((x >>> 2) & 0x33333333);
  x = (x + (x >>> 4)) & 0x0f0f0f0f;
  return (x * 0x01010101) >>> 24;
}

class FastEngine {
  // Supports base <= 64 (two u32 digit masks); callers fall back to the
  // BigInt oracle beyond that.
  constructor(baseInt) {
    this.base = baseInt;
    // Largest e with base^e <= 2^24: every chunk-division intermediate
    // (rem * 2^24 + limb < chunkDiv * 2^24 <= 2^48) stays exact in f64.
    let e = 1;
    while (Math.pow(baseInt, e + 1) <= LIMB) e++;
    this.chunkE = e;
    this.chunkDiv = Math.pow(baseInt, e);
  }

  static fromBigInt(v) {
    const limbs = [];
    const mask = BigInt(LIMB - 1);
    while (v > 0n) {
      limbs.push(Number(v & mask));
      v >>= 24n;
    }
    if (limbs.length === 0) limbs.push(0);
    return limbs;
  }

  static toBigInt(limbs) {
    let v = 0n;
    for (let i = limbs.length - 1; i >= 0; i--) v = (v << 24n) | BigInt(limbs[i]);
    return v;
  }

  static addOne(limbs) {
    for (let i = 0; i < limbs.length; i++) {
      if (++limbs[i] < LIMB) return;
      limbs[i] = 0;
    }
    limbs.push(1);
  }

  // Schoolbook product; partial-product column sums stay < 2^53 for the
  // sizes used here (<= ~16 limbs).
  static mul(a, b) {
    const out = new Array(a.length + b.length).fill(0);
    for (let i = 0; i < a.length; i++) {
      let carry = 0;
      const ai = a[i];
      for (let j = 0; j < b.length; j++) {
        const t = out[i + j] + ai * b[j] + carry;
        carry = Math.floor(t / LIMB);
        out[i + j] = t - carry * LIMB;
      }
      out[i + b.length] += carry;
    }
    while (out.length > 1 && out[out.length - 1] === 0) out.pop();
    return out;
  }

  // In-place divide by a small constant (< 2^24); returns the remainder.
  // Every intermediate rem * 2^24 + limb < 2^48 is exact in f64.
  static divmodSmall(limbs, c) {
    let rem = 0;
    for (let i = limbs.length - 1; i >= 0; i--) {
      const cur = rem * LIMB + limbs[i];
      const q = Math.floor(cur / c);
      limbs[i] = q;
      rem = cur - q * c;
    }
    while (limbs.length > 1 && limbs[limbs.length - 1] === 0) limbs.pop();
    return rem;
  }

  static isZero(limbs) {
    return limbs.length === 1 && limbs[0] === 0;
  }

  // OR the base-digit presence bits of `value` into masks [lo32, hi32],
  // chunked-radix: peel chunkE digits per small division.
  orDigits(value, masks) {
    const v = value.slice();
    const base = this.base;
    while (!FastEngine.isZero(v)) {
      let rem = FastEngine.divmodSmall(v, this.chunkDiv);
      const last = FastEngine.isZero(v);
      for (let p = 0; p < this.chunkE; p++) {
        const d = rem % base;
        rem = (rem - d) / base;
        if (d < 32) masks[0] |= 1 << d;
        else masks[1] |= 1 << (d - 32);
        // Final chunk: stop at the value's true digit count (no phantom
        // leading zeros — interior zeros still emit because rem > 0 or
        // p-loop continues within a non-final chunk).
        if (last && rem === 0) break;
      }
    }
  }

  numUniques(nLimbs) {
    const sq = FastEngine.mul(nLimbs, nLimbs);
    const cu = FastEngine.mul(sq, nLimbs);
    const masks = [0, 0];
    this.orDigits(sq, masks);
    this.orDigits(cu, masks);
    return popcount32(masks[0]) + popcount32(masks[1]);
  }
}

// ---------------------------------------------------------------------------
// Range driver with startup self-test + fallback
// ---------------------------------------------------------------------------

const SELF_TEST_CANDIDATES = 256;

function processRange(startStr, endStr, baseInt) {
  const base = BigInt(baseInt);
  const cutoff = Math.floor(0.9 * baseInt); // near-miss cutoff (core/number_stats.py)
  const distribution = {};
  for (let u = 1; u <= baseInt; u++) distribution[u] = 0;
  const niceNumbers = [];

  const start = BigInt(startStr);
  const end = BigInt(endStr);

  let fast = null;
  if (baseInt <= 64) {
    fast = new FastEngine(baseInt);
    // Self-test the fast engine against the oracle on this field's first
    // candidates; any mismatch demotes the whole field to the oracle.
    const probeEnd = start + BigInt(Math.min(SELF_TEST_CANDIDATES, Number(end - start)));
    const probeLimbs = FastEngine.fromBigInt(start);
    for (let p = start; p < probeEnd; p++) {
      if (fast.numUniques(probeLimbs) !== numUniqueDigits(p, base)) {
        console.warn(`fast engine mismatch at ${p} (base ${baseInt}); using BigInt engine`);
        fast = null;
        break;
      }
      FastEngine.addOne(probeLimbs);
    }
  }

  let sinceProgress = 0;
  const report = (final) => {
    if (sinceProgress >= PROGRESS_CHUNK || (final && sinceProgress > 0)) {
      postMessage({ type: "progress", processed: String(sinceProgress) });
      sinceProgress = 0;
    }
  };

  if (fast !== null) {
    const nLimbs = FastEngine.fromBigInt(start);
    const total = Number(end - start);
    for (let i = 0; i < total; i++) {
      const u = fast.numUniques(nLimbs);
      distribution[u] += 1;
      if (u > cutoff) {
        niceNumbers.push({
          number: FastEngine.toBigInt(nLimbs).toString(),
          num_uniques: u,
        });
      }
      FastEngine.addOne(nLimbs);
      sinceProgress++;
      report(false);
    }
  } else {
    for (let n = start; n < end; n += 1n) {
      const u = numUniqueDigits(n, base);
      distribution[u] += 1;
      if (u > cutoff) {
        niceNumbers.push({ number: n.toString(), num_uniques: u });
      }
      sinceProgress++;
      report(false);
    }
  }
  report(true);
  // engine attribution: the self-test can demote a base<=64 field to the
  // BigInt oracle, so report which engine actually ran (bench.html reads it).
  return { distribution, nice_numbers: niceNumbers, engine: fast !== null ? "fast" : "bigint" };
}

onmessage = (e) => {
  const msg = e.data;
  if (msg.type !== "process") return;
  try {
    const result = processRange(msg.start, msg.end, msg.base);
    postMessage({ type: "complete", result });
  } catch (err) {
    postMessage({ type: "error", message: String(err) });
  }
};
