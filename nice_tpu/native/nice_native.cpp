// Native host engine for nice-tpu.
//
// The reference implements its host-side compute in native code (Rust:
// common/src/client_process.rs, fixed_width.rs, msd_prefix_filter.rs,
// stride_filter.rs); this is the TPU build's native equivalent, exposed to
// Python through a small extern "C" surface loaded with ctypes.  It covers
// the pieces that run on the HOST in the heterogeneous pipeline:
//
//   * scalar niceness checks (num_unique_digits / is_nice) used by the API
//     server's submission verification (reference api/src/main.rs:352-358)
//   * the detailed range loop (CPU fallback / non-TPU client parity,
//     reference client_process.rs:150-191)
//   * the recursive MSD prefix filter that feeds range descriptors to the
//     TPU niceonly kernels (reference msd_prefix_filter.rs:382-674, GPU
//     pipeline client_process_gpu.rs:589-709)
//   * CRT stride-table iteration with early-exit checks (reference
//     stride_filter.rs:139-155) for the native niceonly path
//
// Arithmetic: candidates n fit in 128 bits for every supported base
// (n < 2^110 at base 97); squares fit 256 bits, cubes 384.  Fixed-width
// u64-limb routines with __int128 intermediates mirror the reference's
// u64-limb / u128-accumulator scheme (fixed_width.rs:52-181).  All functions
// are pure and thread-safe; Python callers fan out across threads (ctypes
// releases the GIL), the analog of the reference's rayon par_iter.

#include <cstdint>
#include <cstring>
#include <vector>

using u64 = uint64_t;
using u128 = unsigned __int128;

namespace {

// ---------------------------------------------------------------------------
// Fixed-width helpers (LSW-first u64 limbs)
// ---------------------------------------------------------------------------

// out[0..4) = a[0..2) * a[0..2)  (exact 128x128 -> 256)
inline void mul_2x2(const u64 a[2], const u64 b[2], u64 out[4]) {
    u128 ll = (u128)a[0] * b[0];
    u128 lh = (u128)a[0] * b[1];
    u128 hl = (u128)a[1] * b[0];
    u128 hh = (u128)a[1] * b[1];
    u64 c0 = (u64)ll;
    u128 t1 = (ll >> 64) + (u64)lh + (u64)hl;
    u64 c1 = (u64)t1;
    u128 t2 = (t1 >> 64) + (lh >> 64) + (hl >> 64) + (u64)hh;
    u64 c2 = (u64)t2;
    u64 c3 = (u64)((t2 >> 64) + (hh >> 64));
    out[0] = c0; out[1] = c1; out[2] = c2; out[3] = c3;
}

// out[0..6) = a[0..4) * b[0..2)  (256x128 -> 384)
inline void mul_4x2(const u64 a[4], const u64 b[2], u64 out[6]) {
    u64 acc[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
        u128 carry = 0;
        for (int j = 0; j < 2; ++j) {
            u128 cur = (u128)a[i] * b[j] + acc[i + j] + carry;
            acc[i + j] = (u64)cur;
            carry = cur >> 64;
        }
        int k = i + 2;
        while (carry != 0 && k < 6) {
            u128 cur = (u128)acc[k] + carry;
            acc[k] = (u64)cur;
            carry = cur >> 64;
            ++k;
        }
    }
    std::memcpy(out, acc, sizeof(acc));
}

// value[0..len) /= divisor, returns remainder; trims trailing zero limbs.
inline u64 div_limbs_inplace(u64* value, int& len, u64 divisor) {
    u128 rem = 0;
    for (int i = len - 1; i >= 0; --i) {
        u128 cur = (rem << 64) | value[i];
        value[i] = (u64)(cur / divisor);
        rem = cur % divisor;
    }
    while (len > 0 && value[len - 1] == 0) --len;
    return (u64)rem;
}

inline bool limbs_nonzero(const u64* value, int len) { return len > 0; }

// add small constant to a 2-limb value
inline void add_2(u64 v[2], u64 x) {
    u64 s = v[0] + x;
    v[1] += (s < v[0]) ? 1 : 0;
    v[0] = s;
}

// compare 2-limb values
inline int cmp_2(const u64 a[2], const u64 b[2]) {
    if (a[1] != b[1]) return a[1] < b[1] ? -1 : 1;
    if (a[0] != b[0]) return a[0] < b[0] ? -1 : 1;
    return 0;
}

// OR the digits of value (destroyed) into a u128 indicator; digits peeled
// until the value is zero (the CPU rule, reference client_process.rs:76-127).
inline void or_digits(u64* value, int len, u64 base, u128& indicator) {
    while (limbs_nonzero(value, len)) {
        u64 d = div_limbs_inplace(value, len, base);
        indicator |= (u128)1 << d;
    }
}

// Early-exit variant: returns false as soon as a duplicate digit appears
// (reference client_process.rs:222-253).
inline bool or_digits_distinct(u64* value, int len, u64 base, u128& indicator) {
    while (limbs_nonzero(value, len)) {
        u64 d = div_limbs_inplace(value, len, base);
        u128 bit = (u128)1 << d;
        if (indicator & bit) return false;
        indicator |= bit;
    }
    return true;
}

inline int popcount128(u128 x) {
    return __builtin_popcountll((u64)x) + __builtin_popcountll((u64)(x >> 64));
}

inline int limb_len(const u64* v, int cap) {
    int len = cap;
    while (len > 0 && v[len - 1] == 0) --len;
    return len;
}

inline int num_unique_digits_impl(const u64 n[2], u64 base) {
    u64 sq[4], cu[6];
    mul_2x2(n, n, sq);
    mul_4x2(sq, n, cu);
    u128 indicator = 0;
    int sq_len = limb_len(sq, 4), cu_len = limb_len(cu, 6);
    or_digits(sq, sq_len, base, indicator);
    or_digits(cu, cu_len, base, indicator);
    return popcount128(indicator);
}

inline bool is_nice_impl(const u64 n[2], u64 base) {
    u64 sq[4], cu[6];
    mul_2x2(n, n, sq);
    u128 indicator = 0;
    int sq_len = limb_len(sq, 4);
    // Square scanned before the cube is ever multiplied (reference
    // nice_kernels.cu:270-299 ordering; most candidates die in the square).
    u64 sq_copy[4];
    std::memcpy(sq_copy, sq, sizeof(sq));
    if (!or_digits_distinct(sq_copy, sq_len, base, indicator)) return false;
    mul_4x2(sq, n, cu);
    int cu_len = limb_len(cu, 6);
    return or_digits_distinct(cu, cu_len, base, indicator);
}

// ---------------------------------------------------------------------------
// MSD prefix filter (mirrors nice_tpu/ops/msd_filter.py exactly; the
// reference's unsound cross MSD x LSD check is intentionally omitted there
// and therefore here — see that module's docstring)
// ---------------------------------------------------------------------------

constexpr int MAX_DIGITS = 200;  // cube of a 128-bit n in base >= 10

struct Digits {
    uint8_t d[MAX_DIGITS];  // LSD first
    int len = 0;
};

inline void to_digits_asc(const u64* value_in, int cap, u64 base, Digits& out) {
    u64 value[6];
    std::memcpy(value, value_in, cap * sizeof(u64));
    int len = limb_len(value, cap);
    out.len = 0;
    if (len == 0) {
        out.d[out.len++] = 0;
        return;
    }
    while (limbs_nonzero(value, len)) {
        out.d[out.len++] = (uint8_t)div_limbs_inplace(value, len, base);
    }
}

// Longest shared MSD prefix; writes into pre (MSD first).
inline int common_msd_prefix(const Digits& a, const Digits& b, uint8_t* pre) {
    int n = a.len < b.len ? a.len : b.len;
    int out = 0;
    for (int i = 0; i < n; ++i) {
        uint8_t x = a.d[a.len - 1 - i];
        if (x == b.d[b.len - 1 - i]) pre[out++] = x;
        else break;
    }
    return out;
}

inline bool has_duplicate_digits(const uint8_t* d, int len) {
    u128 seen = 0;
    for (int i = 0; i < len; ++i) {
        u128 bit = (u128)1 << d[i];
        if (seen & bit) return true;
        seen |= bit;
    }
    return false;
}

inline bool has_overlapping_digits(const uint8_t* d1, int l1, const uint8_t* d2,
                                   int l2) {
    u128 seen = 0;
    for (int i = 0; i < l1; ++i) seen |= (u128)1 << d1[i];
    for (int i = 0; i < l2; ++i)
        if (seen & ((u128)1 << d2[i])) return true;
    return false;
}

// Half-open [start, end); true when the whole range can be skipped.
bool has_duplicate_msd_prefix(const u64 start[2], const u64 end[2], u64 base) {
    u64 size_is_one[2] = {start[0] + 1, start[1] + (start[0] + 1 == 0 ? 1 : 0)};
    if (cmp_2(size_is_one, end) == 0) return false;

    u64 last[2] = {end[0] - 1, end[1] - (end[0] == 0 ? 1 : 0)};

    u64 sq_first[4], sq_last[4];
    mul_2x2(start, start, sq_first);
    mul_2x2(last, last, sq_last);
    Digits dsq_first, dsq_last;
    to_digits_asc(sq_first, 4, base, dsq_first);
    to_digits_asc(sq_last, 4, base, dsq_last);
    if (dsq_first.len != dsq_last.len) return false;

    uint8_t sq_prefix[MAX_DIGITS];
    int sq_prefix_len = common_msd_prefix(dsq_first, dsq_last, sq_prefix);
    if (has_duplicate_digits(sq_prefix, sq_prefix_len)) return true;

    u64 cu_first[6], cu_last[6];
    mul_4x2(sq_first, start, cu_first);
    mul_4x2(sq_last, last, cu_last);
    Digits dcu_first, dcu_last;
    to_digits_asc(cu_first, 6, base, dcu_first);
    to_digits_asc(cu_last, 6, base, dcu_last);
    if (dcu_first.len != dcu_last.len) return false;

    uint8_t cu_prefix[MAX_DIGITS];
    int cu_prefix_len = common_msd_prefix(dcu_first, dcu_last, cu_prefix);
    if (has_duplicate_digits(cu_prefix, cu_prefix_len)) return true;

    return has_overlapping_digits(sq_prefix, sq_prefix_len, cu_prefix,
                                  cu_prefix_len);
}

struct RangeVec {
    std::vector<u64> flat;  // (start_lo, start_hi, end_lo, end_hi) per range
};

void valid_ranges_recursive(u64 start_lo, u64 start_hi, u64 end_lo, u64 end_hi,
                            u64 base, int depth, int max_depth,
                            u64 min_range_size, int subdivision_factor,
                            RangeVec& out) {
    u128 start = ((u128)start_hi << 64) | start_lo;
    u128 end = ((u128)end_hi << 64) | end_lo;
    u128 size = end - start;
    u64 s[2] = {start_lo, start_hi};
    u64 e[2] = {end_lo, end_hi};
    if (depth >= max_depth || size <= min_range_size) {
        out.flat.insert(out.flat.end(), {start_lo, start_hi, end_lo, end_hi});
        return;
    }
    if (has_duplicate_msd_prefix(s, e, base)) return;
    if (size < (u128)min_range_size * subdivision_factor) {
        out.flat.insert(out.flat.end(), {start_lo, start_hi, end_lo, end_hi});
        return;
    }
    u128 chunk = size / subdivision_factor;
    for (int i = 0; i < subdivision_factor; ++i) {
        u128 sub_start = start + (u128)i * chunk;
        u128 sub_end = (i == subdivision_factor - 1) ? end : sub_start + chunk;
        if (sub_start < sub_end) {
            valid_ranges_recursive((u64)sub_start, (u64)(sub_start >> 64),
                                   (u64)sub_end, (u64)(sub_end >> 64), base,
                                   depth + 1, max_depth, min_range_size,
                                   subdivision_factor, out);
        }
    }
}

}  // namespace

extern "C" {

int nice_num_unique_digits(u64 n_lo, u64 n_hi, u64 base) {
    u64 n[2] = {n_lo, n_hi};
    return num_unique_digits_impl(n, base);
}

int nice_is_nice(u64 n_lo, u64 n_hi, u64 base) {
    u64 n[2] = {n_lo, n_hi};
    return is_nice_impl(n, base) ? 1 : 0;
}

// Detailed range loop over [start, start+count). hist must hold base+2 u64
// slots. Near misses (num_uniques > cutoff) append (n_lo, n_hi, uniques)
// triples to out_misses (capacity cap triples); the true count is returned
// via *miss_count (callers re-run with a bigger buffer if it exceeds cap —
// the reference treats overflow as a hard error, client_process_gpu.rs:859).
void nice_process_range_detailed(u64 start_lo, u64 start_hi, u64 count,
                                 u64 base, u64 cutoff, u64* hist,
                                 u64* out_misses, u64 cap, u64* miss_count) {
    u64 n[2] = {start_lo, start_hi};
    u64 misses = 0;
    for (u64 i = 0; i < count; ++i) {
        int uniques = num_unique_digits_impl(n, base);
        hist[uniques] += 1;
        if ((u64)uniques > cutoff) {
            if (misses < cap) {
                out_misses[misses * 3] = n[0];
                out_misses[misses * 3 + 1] = n[1];
                out_misses[misses * 3 + 2] = (u64)uniques;
            }
            ++misses;
        }
        add_2(n, 1);
    }
    *miss_count = misses;
}

// Niceonly stride iteration over [start, end): start at the first valid
// candidate at-or-after start (residue index start_idx, computed host-side
// by the Python stride table), jump via the gap table, early-exit check each
// candidate. Returns number of nice numbers found (also capped appends).
void nice_iterate_range_strided(u64 first_lo, u64 first_hi, u64 start_idx,
                                u64 end_lo, u64 end_hi, u64 base,
                                const u64* gap_table, u64 num_residues,
                                u64* out_nice, u64 cap, u64* nice_count) {
    u64 n[2] = {first_lo, first_hi};
    u64 end[2] = {end_lo, end_hi};
    u64 idx = start_idx;
    u64 found = 0;
    while (cmp_2(n, end) < 0) {
        if (is_nice_impl(n, base)) {
            if (found < cap) {
                out_nice[found * 2] = n[0];
                out_nice[found * 2 + 1] = n[1];
            }
            ++found;
        }
        add_2(n, gap_table[idx]);
        if (++idx == num_residues) idx = 0;
    }
    *nice_count = found;
}

int nice_has_duplicate_msd_prefix(u64 start_lo, u64 start_hi, u64 end_lo,
                                  u64 end_hi, u64 base) {
    u64 s[2] = {start_lo, start_hi};
    u64 e[2] = {end_lo, end_hi};
    return has_duplicate_msd_prefix(s, e, base) ? 1 : 0;
}

// Recursive MSD filter. Returns an opaque handle; read size + data, then free.
void* nice_msd_valid_ranges(u64 start_lo, u64 start_hi, u64 end_lo, u64 end_hi,
                            u64 base, int max_depth, u64 min_range_size,
                            int subdivision_factor) {
    auto* out = new RangeVec();
    valid_ranges_recursive(start_lo, start_hi, end_lo, end_hi, base, 0,
                           max_depth, min_range_size, subdivision_factor,
                           *out);
    return out;
}

u64 nice_ranges_count(void* handle) {
    return ((RangeVec*)handle)->flat.size() / 4;
}

void nice_ranges_copy(void* handle, u64* out) {
    auto* rv = (RangeVec*)handle;
    std::memcpy(out, rv->flat.data(), rv->flat.size() * sizeof(u64));
}

void nice_ranges_free(void* handle) { delete (RangeVec*)handle; }

}  // extern "C"
