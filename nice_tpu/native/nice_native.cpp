// Native host engine for nice-tpu.
//
// The reference implements its host-side compute in native code (Rust:
// common/src/client_process.rs, fixed_width.rs, msd_prefix_filter.rs,
// stride_filter.rs); this is the TPU build's native equivalent, exposed to
// Python through a small extern "C" surface loaded with ctypes.  It covers
// the pieces that run on the HOST in the heterogeneous pipeline:
//
//   * scalar niceness checks (num_unique_digits / is_nice) used by the API
//     server's submission verification (reference api/src/main.rs:352-358)
//   * the detailed range loop (CPU fallback / non-TPU client parity,
//     reference client_process.rs:150-191)
//   * the recursive MSD prefix filter that feeds range descriptors to the
//     TPU niceonly kernels (reference msd_prefix_filter.rs:382-674, GPU
//     pipeline client_process_gpu.rs:589-709)
//   * CRT stride-table iteration with early-exit checks (reference
//     stride_filter.rs:139-155) for the native niceonly path
//
// Arithmetic: candidates n fit in 128 bits for every supported base
// (n < 2^110 at base 97); squares fit 256 bits, cubes 384.  Fixed-width
// u64-limb routines with __int128 intermediates mirror the reference's
// u64-limb / u128-accumulator scheme (fixed_width.rs:52-181).  All functions
// are pure and thread-safe; Python callers fan out across threads (ctypes
// releases the GIL), the analog of the reference's rayon par_iter.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

using u32 = uint32_t;
using u64 = uint64_t;
using u128 = unsigned __int128;

namespace {

// ---------------------------------------------------------------------------
// Fixed-width helpers (LSW-first u64 limbs)
// ---------------------------------------------------------------------------

// out[0..4) = a[0..2) * a[0..2)  (exact 128x128 -> 256)
inline void mul_2x2(const u64 a[2], const u64 b[2], u64 out[4]) {
    u128 ll = (u128)a[0] * b[0];
    u128 lh = (u128)a[0] * b[1];
    u128 hl = (u128)a[1] * b[0];
    u128 hh = (u128)a[1] * b[1];
    u64 c0 = (u64)ll;
    u128 t1 = (ll >> 64) + (u64)lh + (u64)hl;
    u64 c1 = (u64)t1;
    u128 t2 = (t1 >> 64) + (lh >> 64) + (hl >> 64) + (u64)hh;
    u64 c2 = (u64)t2;
    u64 c3 = (u64)((t2 >> 64) + (hh >> 64));
    out[0] = c0; out[1] = c1; out[2] = c2; out[3] = c3;
}

// out[0..6) = a[0..4) * b[0..2)  (256x128 -> 384)
inline void mul_4x2(const u64 a[4], const u64 b[2], u64 out[6]) {
    u64 acc[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
        u128 carry = 0;
        for (int j = 0; j < 2; ++j) {
            u128 cur = (u128)a[i] * b[j] + acc[i + j] + carry;
            acc[i + j] = (u64)cur;
            carry = cur >> 64;
        }
        int k = i + 2;
        while (carry != 0 && k < 6) {
            u128 cur = (u128)acc[k] + carry;
            acc[k] = (u64)cur;
            carry = cur >> 64;
            ++k;
        }
    }
    std::memcpy(out, acc, sizeof(acc));
}

// value[0..len) /= divisor, returns remainder; trims trailing zero limbs.
inline u64 div_limbs_inplace(u64* value, int& len, u64 divisor) {
    u128 rem = 0;
    for (int i = len - 1; i >= 0; --i) {
        u128 cur = (rem << 64) | value[i];
        value[i] = (u64)(cur / divisor);
        rem = cur % divisor;
    }
    while (len > 0 && value[len - 1] == 0) --len;
    return (u64)rem;
}

inline bool limbs_nonzero(const u64* value, int len) { return len > 0; }

// add small constant to a 2-limb value
inline void add_2(u64 v[2], u64 x) {
    u64 s = v[0] + x;
    v[1] += (s < v[0]) ? 1 : 0;
    v[0] = s;
}

// compare 2-limb values
inline int cmp_2(const u64 a[2], const u64 b[2]) {
    if (a[1] != b[1]) return a[1] < b[1] ? -1 : 1;
    if (a[0] != b[0]) return a[0] < b[0] ? -1 : 1;
    return 0;
}

// OR the digits of value (destroyed) into a u128 indicator; digits peeled
// until the value is zero (the CPU rule, reference client_process.rs:76-127).
inline void or_digits(u64* value, int len, u64 base, u128& indicator) {
    while (limbs_nonzero(value, len)) {
        u64 d = div_limbs_inplace(value, len, base);
        indicator |= (u128)1 << d;
    }
}

// Early-exit variant: returns false as soon as a duplicate digit appears
// (reference client_process.rs:222-253).
inline bool or_digits_distinct(u64* value, int len, u64 base, u128& indicator) {
    while (limbs_nonzero(value, len)) {
        u64 d = div_limbs_inplace(value, len, base);
        u128 bit = (u128)1 << d;
        if (indicator & bit) return false;
        indicator |= bit;
    }
    return true;
}

inline int popcount128(u128 x) {
    return __builtin_popcountll((u64)x) + __builtin_popcountll((u64)(x >> 64));
}

inline int limb_len(const u64* v, int cap) {
    int len = cap;
    while (len > 0 && v[len - 1] == 0) --len;
    return len;
}

inline int num_unique_digits_impl(const u64 n[2], u64 base) {
    u64 sq[4], cu[6];
    mul_2x2(n, n, sq);
    mul_4x2(sq, n, cu);
    u128 indicator = 0;
    int sq_len = limb_len(sq, 4), cu_len = limb_len(cu, 6);
    or_digits(sq, sq_len, base, indicator);
    or_digits(cu, cu_len, base, indicator);
    return popcount128(indicator);
}

inline bool is_nice_impl(const u64 n[2], u64 base) {
    u64 sq[4], cu[6];
    mul_2x2(n, n, sq);
    u128 indicator = 0;
    int sq_len = limb_len(sq, 4);
    // Square scanned before the cube is ever multiplied (reference
    // nice_kernels.cu:270-299 ordering; most candidates die in the square).
    u64 sq_copy[4];
    std::memcpy(sq_copy, sq, sizeof(sq));
    if (!or_digits_distinct(sq_copy, sq_len, base, indicator)) return false;
    mul_4x2(sq, n, cu);
    int cu_len = limb_len(cu, 6);
    return or_digits_distinct(cu, cu_len, base, indicator);
}

// ---------------------------------------------------------------------------
// MSD prefix filter (mirrors nice_tpu/ops/msd_filter.py exactly; the
// reference's unsound cross MSD x LSD check is intentionally omitted there
// and therefore here — see that module's docstring)
// ---------------------------------------------------------------------------

constexpr int MAX_DIGITS = 200;  // cube of a 128-bit n in base >= 10

struct Digits {
    uint8_t d[MAX_DIGITS];  // LSD first
    int len = 0;
};

inline void to_digits_asc(const u64* value_in, int cap, u64 base, Digits& out) {
    u64 value[6];
    std::memcpy(value, value_in, cap * sizeof(u64));
    int len = limb_len(value, cap);
    out.len = 0;
    if (len == 0) {
        out.d[out.len++] = 0;
        return;
    }
    while (limbs_nonzero(value, len)) {
        out.d[out.len++] = (uint8_t)div_limbs_inplace(value, len, base);
    }
}

// Longest shared MSD prefix; writes into pre (MSD first).
inline int common_msd_prefix(const Digits& a, const Digits& b, uint8_t* pre) {
    int n = a.len < b.len ? a.len : b.len;
    int out = 0;
    for (int i = 0; i < n; ++i) {
        uint8_t x = a.d[a.len - 1 - i];
        if (x == b.d[b.len - 1 - i]) pre[out++] = x;
        else break;
    }
    return out;
}

inline bool has_duplicate_digits(const uint8_t* d, int len) {
    u128 seen = 0;
    for (int i = 0; i < len; ++i) {
        u128 bit = (u128)1 << d[i];
        if (seen & bit) return true;
        seen |= bit;
    }
    return false;
}

inline bool has_overlapping_digits(const uint8_t* d1, int l1, const uint8_t* d2,
                                   int l2) {
    u128 seen = 0;
    for (int i = 0; i < l1; ++i) seen |= (u128)1 << d1[i];
    for (int i = 0; i < l2; ++i)
        if (seen & ((u128)1 << d2[i])) return true;
    return false;
}

// Half-open [start, end); true when the whole range can be skipped.
bool has_duplicate_msd_prefix(const u64 start[2], const u64 end[2], u64 base) {
    u64 size_is_one[2] = {start[0] + 1, start[1] + (start[0] + 1 == 0 ? 1 : 0)};
    if (cmp_2(size_is_one, end) == 0) return false;

    u64 last[2] = {end[0] - 1, end[1] - (end[0] == 0 ? 1 : 0)};

    u64 sq_first[4], sq_last[4];
    mul_2x2(start, start, sq_first);
    mul_2x2(last, last, sq_last);
    Digits dsq_first, dsq_last;
    to_digits_asc(sq_first, 4, base, dsq_first);
    to_digits_asc(sq_last, 4, base, dsq_last);
    if (dsq_first.len != dsq_last.len) return false;

    uint8_t sq_prefix[MAX_DIGITS];
    int sq_prefix_len = common_msd_prefix(dsq_first, dsq_last, sq_prefix);
    if (has_duplicate_digits(sq_prefix, sq_prefix_len)) return true;

    u64 cu_first[6], cu_last[6];
    mul_4x2(sq_first, start, cu_first);
    mul_4x2(sq_last, last, cu_last);
    Digits dcu_first, dcu_last;
    to_digits_asc(cu_first, 6, base, dcu_first);
    to_digits_asc(cu_last, 6, base, dcu_last);
    if (dcu_first.len != dcu_last.len) return false;

    uint8_t cu_prefix[MAX_DIGITS];
    int cu_prefix_len = common_msd_prefix(dcu_first, dcu_last, cu_prefix);
    if (has_duplicate_digits(cu_prefix, cu_prefix_len)) return true;

    return has_overlapping_digits(sq_prefix, sq_prefix_len, cu_prefix,
                                  cu_prefix_len);
}

struct RangeVec {
    std::vector<u64> flat;  // (start_lo, start_hi, end_lo, end_hi) per range
};

void valid_ranges_recursive(u64 start_lo, u64 start_hi, u64 end_lo, u64 end_hi,
                            u64 base, int depth, int max_depth,
                            u64 min_range_size, int subdivision_factor,
                            RangeVec& out) {
    u128 start = ((u128)start_hi << 64) | start_lo;
    u128 end = ((u128)end_hi << 64) | end_lo;
    u128 size = end - start;
    u64 s[2] = {start_lo, start_hi};
    u64 e[2] = {end_lo, end_hi};
    if (depth >= max_depth || size <= min_range_size) {
        out.flat.insert(out.flat.end(), {start_lo, start_hi, end_lo, end_hi});
        return;
    }
    if (has_duplicate_msd_prefix(s, e, base)) return;
    if (size < (u128)min_range_size * subdivision_factor) {
        out.flat.insert(out.flat.end(), {start_lo, start_hi, end_lo, end_hi});
        return;
    }
    u128 chunk = size / subdivision_factor;
    for (int i = 0; i < subdivision_factor; ++i) {
        u128 sub_start = start + (u128)i * chunk;
        u128 sub_end = (i == subdivision_factor - 1) ? end : sub_start + chunk;
        if (sub_start < sub_end) {
            valid_ranges_recursive((u64)sub_start, (u64)(sub_start >> 64),
                                   (u64)sub_end, (u64)(sub_end >> 64), base,
                                   depth + 1, max_depth, min_range_size,
                                   subdivision_factor, out);
        }
    }
}

// ---------------------------------------------------------------------------
// Fast strided niceness filter (round 5)
//
// The generic is_nice_impl peels one digit per div_limbs_inplace call, and
// each peel costs a u128 software division (~100 cycles) — fine for the rare
// re-scan path behind the TPU pipeline, but the host fast path for SMALL
// niceonly fields (engine.py routes sub-RTT fields here; the reference picks
// its backend per field the same way, client_process_gpu.rs:515-531) needs
// ~20 ns per candidate. Three changes buy the ~50x:
//
//   * division by invariant constants via precomputed magic multipliers
//     (Granlund-Warren "magicu": q = mulhi(x, M) >> s, with the overflow
//     "add" variant when needed) — ~5 cycles instead of ~100,
//   * THREE digits per step: divide by base^3 and classify the 3-digit
//     remainder through a precomputed mask table (mask == 0 marks an
//     intra-block duplicate), so the serial quotient chain is 3x shorter,
//   * four candidates interleaved per loop so independent quotient chains
//     overlap in the pipeline (the scalar analog of the GPU kernel's
//     warp-parallel checks, reference nice_kernels.cu:270-299).
//
// The fast filter is EXACT for rejections (a duplicate digit is a duplicate
// digit); candidates that survive every block are re-verified with
// is_nice_impl, so a (hypothetical) fast-path bug can only cost speed on
// rejects it misses, never correctness of accepts — and the differential
// test suite drives both paths over the same ranges.
//
// Scope: n < 2^64 and 4 <= base <= 64 (digit masks fit u64; the mask table
// is base^3 * 8 bytes <= 2 MiB). Out-of-scope calls fall back to the
// generic loop.
// ---------------------------------------------------------------------------

namespace {

struct Magic {
    u64 mul;
    int shift;
    bool add;  // overflow variant: q = ((x - mulhi) >> 1 + mulhi) >> (s - 1)
};

// Unsigned magic-number computation (Hacker's Delight 10-7, W = 64).
Magic magicu(u64 d) {
    Magic mag;
    mag.add = false;
    int p = 63;
    u64 nc = (u64)-1 - (u64)(-(u128)d) % d;
    u64 q1 = 0x8000000000000000ULL / nc;
    u64 r1 = 0x8000000000000000ULL - q1 * nc;
    u64 q2 = 0x7FFFFFFFFFFFFFFFULL / d;
    u64 r2 = 0x7FFFFFFFFFFFFFFFULL - q2 * d;
    u64 delta;
    do {
        ++p;
        if (r1 >= nc - r1) {
            q1 = 2 * q1 + 1;
            r1 = 2 * r1 - nc;
        } else {
            q1 = 2 * q1;
            r1 = 2 * r1;
        }
        if (r2 + 1 >= d - r2) {
            if (q2 >= 0x7FFFFFFFFFFFFFFFULL) mag.add = true;
            q2 = 2 * q2 + 1;
            r2 = 2 * r2 + 1 - d;
        } else {
            if (q2 >= 0x8000000000000000ULL) mag.add = true;
            q2 = 2 * q2;
            r2 = 2 * r2 + 1;
        }
        delta = d - 1 - r2;
    } while (p < 128 && (q1 < delta || (q1 == delta && r1 == 0)));
    mag.mul = q2 + 1;
    mag.shift = p - 64;
    return mag;
}

inline u64 magic_div(u64 x, const Magic& m) {
    u64 q = (u64)(((u128)x * m.mul) >> 64);
    if (m.add) {
        return (((x - q) >> 1) + q) >> (m.shift - 1);
    }
    return q >> m.shift;
}

constexpr u64 FAST_BASE_MAX = 64;  // digit masks in u64

struct FastCtx {
    u64 base;
    u64 b2;  // base^2
    u64 d3;  // base^3
    Magic m_base;
    Magic m_b2;
    Magic m_d3;
    std::vector<u64> table3;  // [v] -> digit mask of (v%b, v/b%b, v/b^2); 0=dup
    std::vector<u64> table2;  // [v] -> digit mask of (v%b, v/b); 0=dup. Fits
                              // L1 (base^2 * 8 B <= 32 KiB), so the hot
                              // tracking path splits a 3-digit block into
                              // table2[r % b^2] | (1 << r / b^2) instead of
                              // paying table3's L2/L3-sized random loads.
    bool ok = false;
};

FastCtx* build_fast_ctx(u64 base) {
    auto* c = new FastCtx();
    c->base = base;
    c->b2 = base * base;
    c->d3 = base * base * base;
    c->m_base = magicu(base);
    c->m_b2 = magicu(c->b2);
    c->m_d3 = magicu(c->d3);
    c->table3.resize(c->d3);
    for (u64 v = 0; v < c->d3; ++v) {
        u64 d0 = v % base, d1 = (v / base) % base, d2 = v / (base * base);
        u64 mask = (1ULL << d0) | (1ULL << d1) | (1ULL << d2);
        c->table3[v] = (d0 == d1 || d0 == d2 || d1 == d2) ? 0 : mask;
    }
    c->table2.resize(c->b2);
    for (u64 v = 0; v < c->b2; ++v) {
        u64 d0 = v % base, d1 = v / base;
        c->table2[v] = (d0 == d1) ? 0 : ((1ULL << d0) | (1ULL << d1));
    }
    // Self-verify the magic multipliers before trusting them: boundary and
    // pseudo-random numerators against hardware division. A failure (which
    // would indicate a magicu bug) disables the fast path entirely rather
    // than risking a wrong reject.
    u64 x = 0x9E3779B97F4A7C15ULL;
    bool ok = true;
    for (int i = 0; i < 4096 && ok; ++i) {
        x ^= x << 13;
        x ^= x >> 7;
        x ^= x << 17;
        ok = magic_div(x, c->m_d3) == x / c->d3 &&
             magic_div(x, c->m_b2) == x / c->b2 &&
             magic_div(x, c->m_base) == x / base;
    }
    for (u64 v : {(u64)0, (u64)1, c->d3 - 1, c->d3, c->d3 + 1, ~(u64)0,
                  ~(u64)0 - 1, (u64)1 << 63}) {
        ok = ok && magic_div(v, c->m_d3) == v / c->d3 &&
             magic_div(v, c->m_b2) == v / c->b2 &&
             magic_div(v, c->m_base) == v / base;
    }
    c->ok = ok;
    return c;
}

std::mutex g_fast_mutex;
FastCtx* g_fast_cache[FAST_BASE_MAX + 1] = {};
bool g_fast_enabled = true;

const FastCtx* get_fast_ctx(u64 base) {
    if (base < 4 || base > FAST_BASE_MAX) return nullptr;
    std::lock_guard<std::mutex> lock(g_fast_mutex);
    if (!g_fast_enabled) return nullptr;
    FastCtx*& slot = g_fast_cache[base];
    if (slot == nullptr) slot = build_fast_ctx(base);
    return slot->ok ? slot : nullptr;
}

// Peel the <= 3 most-significant digits of a value v < base^3 (top block:
// phantom leading zeros must NOT count as digits). Returns false on dup.
inline bool peel_top_block(u64 v, const FastCtx& c, u64& seen) {
    while (v != 0) {
        u64 q = magic_div(v, c.m_base);
        u64 d = v - q * c.base;
        u64 bit = 1ULL << d;
        if (seen & bit) return false;
        seen |= bit;
        v = q;
    }
    return true;
}

// Digit-distinctness filter over a value held as up to 3 u64 limbs (cube of
// a u64 candidate). Exact: long division by base^3 in 2^32-limb steps, each
// quotient via one magic multiply; full 3-digit blocks classify through
// table3, the top partial block peels per-digit.
inline bool peel_value(u64 l0, u64 l1, u64 l2, const FastCtx& c, u64& seen) {
    constexpr u64 LO32 = 0xFFFFFFFFULL;
    while (l2 != 0) {
        u64 q4 = magic_div(l2, c.m_d3);
        u64 r = l2 - q4 * c.d3;
        u64 t3 = (r << 32) | (l1 >> 32);
        u64 q3 = magic_div(t3, c.m_d3);
        r = t3 - q3 * c.d3;
        u64 t2 = (r << 32) | (l1 & LO32);
        u64 q2 = magic_div(t2, c.m_d3);
        r = t2 - q2 * c.d3;
        u64 t1 = (r << 32) | (l0 >> 32);
        u64 q1 = magic_div(t1, c.m_d3);
        r = t1 - q1 * c.d3;
        u64 t0 = (r << 32) | (l0 & LO32);
        u64 q0 = magic_div(t0, c.m_d3);
        r = t0 - q0 * c.d3;
        u64 mask = c.table3[r];
        if (mask == 0 || (seen & mask)) return false;
        seen |= mask;
        l2 = q4;
        l1 = (q3 << 32) | q2;
        l0 = (q1 << 32) | q0;
    }
    while (l1 != 0) {
        u64 q2 = magic_div(l1, c.m_d3);
        u64 r = l1 - q2 * c.d3;
        u64 t1 = (r << 32) | (l0 >> 32);
        u64 q1 = magic_div(t1, c.m_d3);
        r = t1 - q1 * c.d3;
        u64 t0 = (r << 32) | (l0 & LO32);
        u64 q0 = magic_div(t0, c.m_d3);
        r = t0 - q0 * c.d3;
        u64 mask = c.table3[r];
        if (mask == 0 || (seen & mask)) return false;
        seen |= mask;
        l1 = q2;
        l0 = (q1 << 32) | q0;
    }
    while (l0 >= c.d3) {
        u64 q = magic_div(l0, c.m_d3);
        u64 r = l0 - q * c.d3;
        u64 mask = c.table3[r];
        if (mask == 0 || (seen & mask)) return false;
        seen |= mask;
        l0 = q;
    }
    return peel_top_block(l0, c, seen);
}

// Necessary condition for niceness of candidate n (n < 2^64): every digit of
// n^2 and n^3 distinct. Accepts may be over-approximate ONLY in theory (they
// are exact too), but callers re-verify accepts with is_nice_impl anyway.
inline bool fast_sqube_distinct(u64 n, const FastCtx& c) {
    u128 sq = (u128)n * n;
    u64 seen = 0;
    if (!peel_value((u64)sq, (u64)(sq >> 64), 0, c, seen)) return false;
    // cube = sq * n as 3 u64 limbs
    u128 t = (u128)(u64)sq * n;
    u64 c0 = (u64)t;
    u128 t2 = (u128)(u64)(sq >> 64) * n + (u64)(t >> 64);
    return peel_value(c0, (u64)t2, (u64)(t2 >> 64), c, seen);
}

// Lockstep square filter over LANES candidates: every lane advances one
// 3-digit block per round regardless of its own state (dead lanes hold
// zeros), so the four independent magic-divide quotient chains — each
// latency-bound at ~6 cycles per dependent divide — overlap in the
// pipeline instead of running serially. This is the scalar-core analog of
// the reference GPU kernel's warp-parallel digit checks
// (nice_kernels.cu:270-299): predication instead of divergence.
// Returns the bitmask of lanes whose square digits are fully distinct;
// seen[] carries their accumulated digit masks into the cube check.
// Max 3-digit blocks a square can span: a u64 candidate's square has < 2^128
// ~ 39 base-10 digits; for the smallest fast-path base (4) blocks are capped
// by the u64 value range instead (64 / (3*log2 4) = 11 for the low limb plus
// the high limb's worth) — 24 covers every base >= 4 with margin.
constexpr int SQ_BLOCKS_MAX = 24;

inline int square_lanes(const u64 n[4], const FastCtx& c, u64 seen[4]) {
    constexpr u64 LO32 = 0xFFFFFFFFULL;
    u64 l0[4], l1[4];
    u64 rs[4][SQ_BLOCKS_MAX];  // per-lane 3-digit block remainders, LSD first
    u32 vbits[4] = {0, 0, 0, 0};  // bit i: lane recorded a FULL block round i
    for (int j = 0; j < 4; ++j) {
        u128 sq = (u128)n[j] * n[j];
        l0[j] = (u64)sq;
        l1[j] = (u64)(sq >> 64);
    }
    // Phase 1 — pure divide rounds, all four quotient chains in flight.
    // NOTHING here consults the mask table or any accumulated digit state:
    // the round latency is the divide chain alone, while the remainders are
    // parked for phase 2 (whose table loads then all overlap instead of
    // serializing round-by-round through a seen-mask dependency).
    // `pr` guards lanes whose value already fell below base^3: their top
    // block has phantom leading zeros and must only be peeled digit-wise.
    int rounds = 0;
    while ((l1[0] | l1[1] | l1[2] | l1[3]) != 0) {
        for (int j = 0; j < 4; ++j) {
            u64 v1 = l1[j], v0 = l0[j];
            u64 q2 = magic_div(v1, c.m_d3);
            u64 r = v1 - q2 * c.d3;
            u64 t1 = (r << 32) | (v0 >> 32);
            u64 q1 = magic_div(t1, c.m_d3);
            r = t1 - q1 * c.d3;
            u64 t0 = (r << 32) | (v0 & LO32);
            u64 q0 = magic_div(t0, c.m_d3);
            r = t0 - q0 * c.d3;
            u64 pr = (u64)0 - (u64)((v1 != 0) | (v0 >= c.d3));
            rs[j][rounds] = r;
            vbits[j] |= (u32)(pr & 1) << rounds;
            l1[j] = q2;
            l0[j] = (((q1 << 32) | q0) & pr) | (v0 & ~pr);
        }
        ++rounds;
    }
    while ((l0[0] >= c.d3) | (l0[1] >= c.d3) | (l0[2] >= c.d3) |
           (l0[3] >= c.d3)) {
        for (int j = 0; j < 4; ++j) {
            u64 v = l0[j];
            u64 q = magic_div(v, c.m_d3);
            u64 r = v - q * c.d3;
            u64 ge = (u64)0 - (u64)(v >= c.d3);
            rs[j][rounds] = r;
            vbits[j] |= (u32)(ge & 1) << rounds;
            l0[j] = (q & ge) | (v & ~ge);
        }
        ++rounds;
    }
    // Phase 2 — replay each lane's blocks LSD-first, accumulating digit
    // masks and detecting duplicates. Early break on death keeps the
    // expected walk short (~block 3-4); the table loads for several blocks
    // are already in flight by then.
    int alive = 0;
    for (int j = 0; j < 4; ++j) {
        u64 s = 0;
        bool ok = true;
        u32 vb = vbits[j];
        for (int i = 0; i < rounds; ++i) {
            if (!((vb >> i) & 1)) continue;  // lane was past its top block
            u64 mask = c.table3[rs[j][i]];
            if (mask == 0 || (s & mask)) {
                ok = false;
                break;
            }
            s |= mask;
        }
        if (ok && peel_top_block(l0[j], c, s)) {
            seen[j] = s;
            alive |= 1 << j;
        }
    }
    return alive;
}

// Cube-phase continuation for a square survivor (~3% of candidates after
// the CRT prefilter): same exact block peeling over the 3-limb cube.
inline bool cube_survives(u64 n, const FastCtx& c, u64 seen) {
    u128 sq = (u128)n * n;
    u128 t = (u128)(u64)sq * n;
    u64 c0 = (u64)t;
    u128 t2 = (u128)(u64)(sq >> 64) * n + (u64)(t >> 64);
    return peel_value(c0, (u64)t2, (u64)(t2 >> 64), c, seen);
}

// ---------------------------------------------------------------------------
// Polynomial-residue fast path (k >= 3 stride tables)
//
// When the CRT stride modulus M is a multiple of d3 = base^3 (true for every
// table of depth k >= 3, M = (base-1) * base^k), a candidate n = q*M + res
// has
//     n^2 = q^2 M^2 + 2 q M res + res^2,   M = (base-1) * d3 * base^(k-3)
// so n^2 mod d3 = res^2 mod d3 — the square's LOW 3-digit block depends only
// on the residue and is PRECOMPUTED per table entry (likewise the cube's;
// their joint distinctness is already guaranteed by the CRT table
// construction, so the per-candidate work starts at block 1 with a seeded
// digit mask). The remaining square blocks follow from an all-u64 peeling of
//     n^2 / d3 = d3*(F q^2) + C,   F = (M/d3)^2,  C = 2(M/d3) q res + res^2/d3
// where q (and therefore the q-split F*Q1 / F*R1 constants below) only
// changes when the residue index wraps — once per M-span, amortized over
// num_residues candidates. Per candidate that leaves ONE multiply and ~6
// single u64 magic divides, about 3x fewer dependent operations than the
// generic 2^32-limb long division above.
// ---------------------------------------------------------------------------

struct PolyCtx {
    const FastCtx* fc;
    u64 modulus;
    u64 mdiv;  // M / d3  (= (base-1) * base^(k-3))
    // Packed per-residue stream: low 32 bits the residue, high 32 bits
    // floor(res^2 / d3) — one load per candidate instead of two.
    std::vector<u64> rr;
    std::vector<u64> seed;  // digit mask of sq/cube low blocks; 0 = reject
    bool ok = false;
};

PolyCtx* build_poly_ctx(const FastCtx* fc, u64 modulus, const u32* residues,
                        u64 num) {
    auto* p = new PolyCtx();
    p->fc = fc;
    p->modulus = modulus;
    p->mdiv = modulus / fc->d3;
    p->rr.resize(num);
    p->seed.resize(num);
    for (u64 i = 0; i < num; ++i) {
        u64 r = residues[i];
        u128 r2 = (u128)r * r;
        u64 sq0 = (u64)(r2 % fc->d3);
        p->rr[i] = r | ((u64)(r2 / fc->d3) << 32);
        u64 cu0 = (u64)(((r2 % fc->d3) * (r % fc->d3)) % fc->d3);
        // Low 3-digit blocks of the candidate's square and cube, exact.
        // The CRT table's LSD filter mirrors the reference's WEAKER rule
        // (stop-at-zero digit extraction, cross sq/cube overlap only,
        // lsd_filter.py:62-84) — so residues with an intra-block duplicate
        // or a zero-digit collision DO appear in the table. Those can never
        // produce a nice number (for in-range candidates both blocks are
        // full: sq >= base^4, cube >= base^6 — eligibility requires
        // first >= base^2); seed == 0 marks them and the gather loop skips
        // their candidates outright, a ~10-25%% free kill the per-candidate
        // filters would otherwise pay full price for.
        u64 m1 = fc->table3[sq0], m2 = fc->table3[cu0];
        p->seed[i] = (m1 == 0 || m2 == 0 || (m1 & m2)) ? 0 : (m1 | m2);
    }
    p->ok = true;
    return p;
}

std::vector<std::pair<std::pair<u64, u64>, PolyCtx*>> g_poly_cache;

const PolyCtx* get_poly_ctx(u64 base, u64 modulus, const u32* residues,
                            u64 num) {
    const FastCtx* fc = get_fast_ctx(base);
    if (fc == nullptr) return nullptr;
    u64 d3 = fc->d3;
    if (modulus % d3 != 0 || modulus >= ((u64)1 << 32)) return nullptr;
    std::lock_guard<std::mutex> lock(g_fast_mutex);
    for (auto& e : g_poly_cache) {
        if (e.first.first == base && e.first.second == modulus) {
            return e.second->ok ? e.second : nullptr;
        }
    }
    PolyCtx* p = build_poly_ctx(fc, modulus, residues, num);
    g_poly_cache.push_back({{base, modulus}, p});
    return p->ok ? p : nullptr;
}

// Cube check for a square survivor with the LOW block skipped (its digits
// are in the seed mask already): one discarded block step, then the generic
// exact peel.
inline bool cube_survives_skip0(u64 n, const FastCtx& c, u64 seen) {
    constexpr u64 LO32 = 0xFFFFFFFFULL;
    u128 sq = (u128)n * n;
    u128 t = (u128)(u64)sq * n;
    u64 l0 = (u64)t;
    u128 t2 = (u128)(u64)(sq >> 64) * n + (u64)(t >> 64);
    u64 l1 = (u64)t2, l2 = (u64)(t2 >> 64);
    // one 3-limb block step, remainder (block 0) discarded
    u64 q4 = magic_div(l2, c.m_d3);
    u64 r = l2 - q4 * c.d3;
    u64 ta = (r << 32) | (l1 >> 32);
    u64 q3 = magic_div(ta, c.m_d3);
    r = ta - q3 * c.d3;
    u64 tb = (r << 32) | (l1 & LO32);
    u64 q2 = magic_div(tb, c.m_d3);
    r = tb - q2 * c.d3;
    u64 tc = (r << 32) | (l0 >> 32);
    u64 q1 = magic_div(tc, c.m_d3);
    r = tc - q1 * c.d3;
    u64 td = (r << 32) | (l0 & LO32);
    u64 q0 = magic_div(td, c.m_d3);
    return peel_value((q1 << 32) | q0, (q3 << 32) | q2, q4, c, seen);
}

// Lockstep width: enough independent quotient chains to cover the ~6-cycle
// magic-divide latency at the core's issue width. Swept on the bench host
// (Xeon 2.7 GHz, b50 1e7 field): 4 -> 446, 8 -> 425, 16 -> 399 M n/s — the
// kernel is issue-bound, not latency-bound, so wider only adds spills.
#ifndef POLY_LANES
#define POLY_LANES 4
#endif

// Digit mask of a whole value (full blocks + top partial block).
// ok_out: all-ones when the value's digits are internally distinct.
inline void value_digit_mask(u64 v, const FastCtx& c, u64* mask_out,
                             u64* ok_out) {
    u64 s = 0;
    bool ok = true;
    while (v >= c.d3) {
        u64 q = magic_div(v, c.m_d3);
        u64 r = v - q * c.d3;
        u64 m = c.table3[r];
        if (m == 0 || (s & m)) ok = false;
        s |= m;
        v = q;
    }
    if (!peel_top_block(v, c, s)) ok = false;
    *mask_out = s;
    *ok_out = ok ? ~(u64)0 : 0;
}

template <int PL>
void iterate_strided_poly(u64 first, u64 start_idx, u64 end, const PolyCtx& p,
                          u64* out_nice, u64 cap, u64* nice_count) {
    const FastCtx& c = *p.fc;
    const u64 M = p.modulus, d3 = c.d3;
    const u64 F = p.mdiv * p.mdiv;
    const u64 num = p.rr.size();
    u64 found = 0;
    u64 q = first / M;
    // High-digit shortcut: Z = F*Q1 + t3 where F*Q1 is a per-wrap constant
    // and t3 < ~2*(M/d3)*end/d3^2. Splitting F*Q1 = d3^2*H + hiL, the
    // candidate-varying part Y = hiL + t3 spans exactly two 3-digit blocks
    // plus a carry c into H of at most 1 (guaranteed by the gate below), so
    // the per-candidate peel is TWO divides plus a lookup of the per-wrap
    // digit masks of H and H+1 — instead of a variable lockstep round loop
    // over ~4 more blocks. H >= 1 keeps those two blocks full-width.
    u64 d3sq = d3 * d3;
    u64 t3_max = (u64)((u128)2 * p.mdiv * (end + M) / d3 / d3) + 2 * F + 2;
    bool use_hi = t3_max < d3sq && first / d3 / d3sq >= 1;
    u64 FQ1 = 0, FR1 = 0, q2m = 0;
    u64 hiL = 0, hi_mask[2] = {0, 0}, hi_okf[2] = {0, 0};
    auto wrap_setup = [&]() {
        u64 a = magic_div(q, c.m_d3), r = q - a * d3;
        u64 rr = r * r;
        u64 t = magic_div(rr, c.m_d3), R1 = rr - t * d3;
        u64 Q1 = d3 * a * a + 2 * a * r + t;
        FQ1 = F * Q1;
        FR1 = F * R1;
        q2m = 2 * p.mdiv * q;
        if (use_hi) {
            u64 H = FQ1 / d3sq;
            hiL = FQ1 - H * d3sq;
            value_digit_mask(H, c, &hi_mask[0], &hi_okf[0]);
            value_digit_mask(H + 1, c, &hi_mask[1], &hi_okf[1]);
        }
    };
    wrap_setup();
    // use_hi also requires H >= 1 on every wrap; q (hence FQ1) only grows,
    // so probing the FIRST wrap suffices — but FQ1 is only known after
    // wrap_setup, so re-check and recompute once if the probe was wrong.
    if (use_hi && FQ1 / d3sq < 1) {
        use_hi = false;
        wrap_setup();
    }
    u64 idx = start_idx;
    u64 n = first;
    u64 lanes[PL], lidx[PL];
    constexpr u64 LO32 = 0xFFFFFFFFULL;
    auto advance = [&]() {
        if (++idx == num) {
            idx = 0;
            ++q;
            wrap_setup();
            n = q * M + (p.rr[0] & LO32);
        } else {
            n += (p.rr[idx] & LO32) - (p.rr[idx - 1] & LO32);
        }
    };
    u64 seen[PL], okm[PL], Z[PL];
    while (n < end) {
        int kk = 0;
        u64 lC[PL], lFR1[PL], lFQ1[PL];
        while (kk < PL && n < end) {
            u64 sd = p.seed[idx];
            u64 rrv = p.rr[idx];
            if (sd == 0) {  // residue provably dead: skip the lane slot
                advance();
                continue;
            }
            lanes[kk] = n;
            lidx[kk] = idx;
            lC[kk] = q2m * (rrv & LO32) + (rrv >> 32);
            seen[kk] = sd;
            lFR1[kk] = FR1;
            lFQ1[kk] = FQ1;
            ++kk;
            advance();
        }
        for (int j = kk; j < PL; ++j) {  // tail: idle lanes peel zeros
            lC[j] = lFR1[j] = lFQ1[j] = seen[j] = 0;
        }
        // Blocks 1 and 2 (block 0 came precomputed in the seed): one magic
        // divide each, all four lanes' chains interleaving as straight-line
        // code. Tracking is branch-free: a duplicate clears the lane's okm
        // word; seen keeps accumulating harmlessly afterwards. The 3-digit
        // block classifies through the L1-resident table2 plus one extra
        // divide for its top digit — table3's base^3-sized random loads sat
        // on the serial seen-chain and dominated the whole kernel.
        auto track = [&](int j, u64 r) {
            u64 d2 = magic_div(r, c.m_b2);
            u64 m2 = c.table2[r - d2 * c.b2];
            u64 bit = (u64)1 << d2;
            u64 mask = m2 | bit;
            u64 bad = (u64)0 - (u64)((m2 == 0) | ((m2 & bit) != 0) |
                                     ((seen[j] & mask) != 0));
            okm[j] &= ~bad;
            seen[j] |= mask;
        };
        if (use_hi) {
            // Blocks 1-4 are four straight-line divides per lane; the
            // square's remaining high digits come from the per-wrap H masks
            // (carry selected by whether Y overflowed its two blocks).
            for (int j = 0; j < PL; ++j) {
                okm[j] = ~(u64)0;
                u64 X = lC[j];
                u64 t2 = magic_div(X, c.m_d3);
                track(j, X - t2 * d3);
                u64 X2 = lFR1[j] + t2;
                u64 t3 = magic_div(X2, c.m_d3);
                track(j, X2 - t3 * d3);
                u64 Y = hiL + t3;
                u64 y1 = magic_div(Y, c.m_d3);
                track(j, Y - y1 * d3);
                u64 cf = (u64)(y1 >= d3);
                track(j, y1 - (d3 & ((u64)0 - cf)));
                u64 hm = hi_mask[cf];
                u64 bad = (~hi_okf[cf]) |
                          ((u64)0 - (u64)((seen[j] & hm) != 0));
                okm[j] &= ~bad;
                seen[j] |= hm;
            }
        } else {
            for (int j = 0; j < PL; ++j) {
                okm[j] = ~(u64)0;
                u64 X = lC[j];
                u64 t2 = magic_div(X, c.m_d3);
                track(j, X - t2 * d3);
                u64 X2 = lFR1[j] + t2;
                u64 t3 = magic_div(X2, c.m_d3);
                track(j, X2 - t3 * d3);
                Z[j] = lFQ1[j] + t3;
            }
            // Remaining full blocks in lockstep rounds so the four quotient
            // chains overlap; lanes below base^3 hold their value (top
            // partial block, peeled digit-wise afterwards).
            for (;;) {
                u64 any_z = 0, any_ok = 0;
                for (int j = 0; j < PL; ++j) {
                    any_z |= (u64)(Z[j] >= d3);
                    any_ok |= okm[j];
                }
                if (!any_z || !any_ok) break;
                for (int j = 0; j < PL; ++j) {
                    u64 v = Z[j];
                    u64 q0 = magic_div(v, c.m_d3);
                    u64 r = v - q0 * d3;
                    u64 ge = (u64)0 - (u64)(v >= d3);
                    u64 d2 = magic_div(r, c.m_b2);
                    u64 m2 = c.table2[r - d2 * c.b2];
                    u64 bit = (u64)1 << d2;
                    u64 mask = m2 | bit;
                    u64 bad = ((u64)0 -
                               (u64)((m2 == 0) | ((m2 & bit) != 0) |
                                     ((seen[j] & mask) != 0))) &
                              ge;
                    okm[j] &= ~bad;
                    seen[j] |= mask & ge;
                    Z[j] = (q0 & ge) | (v & ~ge);
                }
            }
        }
        for (int j = 0; j < kk; ++j) {
            if (okm[j] != 0 &&
                (use_hi || peel_top_block(Z[j], c, seen[j])) &&
                cube_survives_skip0(lanes[j], c, seen[j])) {
                u64 c2[2] = {lanes[j], 0};
                if (is_nice_impl(c2, c.base)) {
                    if (found < cap) {
                        out_nice[found * 2] = lanes[j];
                        out_nice[found * 2 + 1] = 0;
                    }
                    ++found;
                }
            }
        }
    }
    *nice_count = found;
}

void iterate_strided_fast(u64 first, u64 start_idx, u64 end, u64 base,
                          const u64* gap_table, u64 num_residues,
                          const FastCtx& ctx, u64* out_nice, u64 cap,
                          u64* nice_count) {
    u64 found = 0;
    u64 idx = start_idx;
    u64 n = first;
    u64 lanes[4];
    u64 seen[4];
    auto emit = [&](u64 cand) {
        u64 c2[2] = {cand, 0};
        if (is_nice_impl(c2, base)) {
            if (found < cap) {
                out_nice[found * 2] = cand;
                out_nice[found * 2 + 1] = 0;
            }
            ++found;
        }
    };
    while (n < end) {
        int k = 0;
        while (k < 4 && n < end) {
            lanes[k++] = n;
            n += gap_table[idx];
            if (++idx == num_residues) idx = 0;
        }
        if (k == 4) {
            int alive = square_lanes(lanes, ctx, seen);
            while (alive) {
                int j = __builtin_ctz(alive);
                alive &= alive - 1;
                if (cube_survives(lanes[j], ctx, seen[j])) emit(lanes[j]);
            }
        } else {
            for (int j = 0; j < k; ++j) {
                if (fast_sqube_distinct(lanes[j], ctx)) emit(lanes[j]);
            }
        }
    }
    *nice_count = found;
}

}  // namespace

}  // namespace

extern "C" {

int nice_num_unique_digits(u64 n_lo, u64 n_hi, u64 base) {
    u64 n[2] = {n_lo, n_hi};
    return num_unique_digits_impl(n, base);
}

int nice_is_nice(u64 n_lo, u64 n_hi, u64 base) {
    u64 n[2] = {n_lo, n_hi};
    return is_nice_impl(n, base) ? 1 : 0;
}

// Detailed range loop over [start, start+count). hist must hold base+2 u64
// slots. Near misses (num_uniques > cutoff) append (n_lo, n_hi, uniques)
// triples to out_misses (capacity cap triples); the true count is returned
// via *miss_count (callers re-run with a bigger buffer if it exceeds cap —
// the reference treats overflow as a hard error, client_process_gpu.rs:859).
void nice_process_range_detailed(u64 start_lo, u64 start_hi, u64 count,
                                 u64 base, u64 cutoff, u64* hist,
                                 u64* out_misses, u64 cap, u64* miss_count) {
    u64 n[2] = {start_lo, start_hi};
    u64 misses = 0;
    for (u64 i = 0; i < count; ++i) {
        int uniques = num_unique_digits_impl(n, base);
        hist[uniques] += 1;
        if ((u64)uniques > cutoff) {
            if (misses < cap) {
                out_misses[misses * 3] = n[0];
                out_misses[misses * 3 + 1] = n[1];
                out_misses[misses * 3 + 2] = (u64)uniques;
            }
            ++misses;
        }
        add_2(n, 1);
    }
    *miss_count = misses;
}

// Niceonly stride iteration over [start, end): start at the first valid
// candidate at-or-after start (residue index start_idx, computed host-side
// by the Python stride table), jump via the gap table, early-exit check each
// candidate. Returns number of nice numbers found (also capped appends).
void nice_iterate_range_strided(u64 first_lo, u64 first_hi, u64 start_idx,
                                u64 end_lo, u64 end_hi, u64 base,
                                const u64* gap_table, u64 num_residues,
                                u64* out_nice, u64 cap, u64* nice_count) {
    if (first_hi == 0 && end_hi == 0) {
        // Whole range below 2^64: the magic-divide fast filter applies
        // (bases 4..64; get_fast_ctx returns null outside its scope or when
        // its self-verification failed, falling through to the generic loop).
        const FastCtx* ctx = get_fast_ctx(base);
        if (ctx != nullptr) {
            iterate_strided_fast(first_lo, start_idx, end_lo, base, gap_table,
                                 num_residues, *ctx, out_nice, cap,
                                 nice_count);
            return;
        }
    }
    u64 n[2] = {first_lo, first_hi};
    u64 end[2] = {end_lo, end_hi};
    u64 idx = start_idx;
    u64 found = 0;
    while (cmp_2(n, end) < 0) {
        if (is_nice_impl(n, base)) {
            if (found < cap) {
                out_nice[found * 2] = n[0];
                out_nice[found * 2 + 1] = n[1];
            }
            ++found;
        }
        add_2(n, gap_table[idx]);
        if (++idx == num_residues) idx = 0;
    }
    *nice_count = found;
}

// Polynomial-residue strided iteration (k >= 3 stride tables; see PolyCtx
// above). Sets *used_poly to 1 and fills results when eligible; leaves it 0
// (results untouched) when the caller should use the generic entry point.
// Eligibility guards the u64 arithmetic: modulus a multiple of base^3 and
// < 2^32; first/end below 2^64; 2*(M/d3)*q*res and F*Q1 must fit u64.
void nice_iterate_range_strided_poly(u64 first_lo, u64 first_hi, u64 start_idx,
                                     u64 end_lo, u64 end_hi, u64 base,
                                     u64 modulus, const u32* residues,
                                     u64 num_residues, u64* out_nice, u64 cap,
                                     u64* nice_count, int* used_poly) {
    *used_poly = 0;
    if (first_hi != 0 || end_hi != 0 || base < 4 || base > FAST_BASE_MAX ||
        num_residues == 0 || first_lo < base * base) {
        return;  // first >= base^2 keeps the low sq/cube blocks full-width
    }
    u64 d3 = base * base * base;
    if (modulus % d3 != 0 || modulus >= ((u64)1 << 32)) return;
    // Require n >= base^4.5 (first^2 >= d3^3 == base^9): below that, n^2 has
    // fewer than three full base^3 blocks and the fixed block-1/2 decompose
    // misclassifies digits. Small n fall back to the generic limb loop.
    if ((u128)first_lo * first_lo < (u128)d3 * d3 * d3) return;
    // 2*(M/d3)*q*res < 2*(base-1)*base^(k-3)*...*n stays under 2^63 when
    // end * 2 * (M/d3) * (d3 margin) does; and F*Q1 ~ end^2 / d3^3 < 2^62.
    u64 mdiv = modulus / d3;
    u128 e = end_lo;
    // X = F*R1 + 2*(M/d3)*q*res + r2d must fit u64: q*res < n < end, and
    // F*R1 < (M/d3)^2 * d3.
    if ((((u128)2 * mdiv) * (e + modulus) + (u128)mdiv * mdiv * d3) >> 64)
        return;
    // Z = F*Q1 + t3 ~ end^2/d3^3 + 2^47 must stay comfortably inside u64.
    if ((e * e) / ((u128)d3 * d3 * d3) + ((u128)1 << 48) >= ((u128)1 << 63))
        return;
    const PolyCtx* p = get_poly_ctx(base, modulus, residues, num_residues);
    if (p == nullptr || !g_fast_enabled) return;
    if (start_idx >= p->rr.size() ||
        first_lo % modulus != (p->rr[start_idx] & 0xFFFFFFFFULL)) {
        return;  // caller/table mismatch: use the generic loop
    }
    iterate_strided_poly<POLY_LANES>(first_lo, start_idx, end_lo, *p,
                                     out_nice, cap, nice_count);
    *used_poly = 1;
}

// Test hook: force the generic strided loop (differential tests compare the
// fast filter against it over identical ranges). Returns the previous value.
int nice_strided_fast_enabled(int enable) {
    std::lock_guard<std::mutex> lock(g_fast_mutex);
    int prev = g_fast_enabled ? 1 : 0;
    g_fast_enabled = enable != 0;
    return prev;
}

int nice_has_duplicate_msd_prefix(u64 start_lo, u64 start_hi, u64 end_lo,
                                  u64 end_hi, u64 base) {
    u64 s[2] = {start_lo, start_hi};
    u64 e[2] = {end_lo, end_hi};
    return has_duplicate_msd_prefix(s, e, base) ? 1 : 0;
}

// Recursive MSD filter. Returns an opaque handle; read size + data, then free.
void* nice_msd_valid_ranges(u64 start_lo, u64 start_hi, u64 end_lo, u64 end_hi,
                            u64 base, int max_depth, u64 min_range_size,
                            int subdivision_factor) {
    auto* out = new RangeVec();
    valid_ranges_recursive(start_lo, start_hi, end_lo, end_hi, base, 0,
                           max_depth, min_range_size, subdivision_factor,
                           *out);
    return out;
}

u64 nice_ranges_count(void* handle) {
    return ((RangeVec*)handle)->flat.size() / 4;
}

void nice_ranges_copy(void* handle, u64* out) {
    auto* rv = (RangeVec*)handle;
    std::memcpy(out, rv->flat.data(), rv->flat.size() * sizeof(u64));
}

void nice_ranges_free(void* handle) { delete (RangeVec*)handle; }

}  // extern "C"
