"""ctypes bindings for the native host engine (nice_native.cpp).

The library is built on first import (g++, cached as libnice_native.so next
to the source; rebuilt when the source is newer). Every entry point has a
pure-Python fallback, so the framework degrades gracefully where no C++
toolchain exists; `available()` reports which path is active and the
`NICE_NO_NATIVE=1` env var forces the fallback (used by differential tests
to compare both implementations).

All natives are pure functions; ctypes releases the GIL for the duration of
a call, so Python-level thread pools achieve real parallelism over field
chunks — the analog of the reference's rayon par_iter (client/src/main.rs:194)
and of its CPU-threaded MSD filter feeding the GPU (client_process_gpu.rs:624).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from functools import lru_cache

from nice_tpu.utils import lockdep

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "nice_native.cpp")
_LIB = os.path.join(_HERE, "libnice_native.so")
_U64 = ctypes.c_uint64
_MASK64 = (1 << 64) - 1

_build_lock = lockdep.make_lock("native._build_lock")


def _build() -> bool:
    with _build_lock:
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return True
        # Compile to a process-unique temp path and atomically rename: another
        # process may be dlopen-ing the current .so while we rebuild. CXX and
        # CXXFLAGS match the Makefile's single recipe.
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        cxx = os.environ.get("CXX", "g++")
        # -march=native is worth ~15% on the strided fast kernels (mulx/shlx
        # for the magic-divide chains); retried without it for toolchains or
        # build sandboxes where it is unsupported.
        flags = os.environ.get(
            "CXXFLAGS", "-O3 -march=native -fPIC -shared -std=c++17"
        ).split()
        attempts = [flags]
        if "-march=native" in flags:
            attempts.append([f for f in flags if f != "-march=native"])
        for attempt in attempts:
            try:
                subprocess.run(
                    [cxx, *attempt, _SRC, "-o", tmp],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, _LIB)
                return True
            except (OSError, subprocess.SubprocessError) as exc:
                log.warning("native build (%s) failed: %s", " ".join(attempt), exc)
        log.warning("native build failed, using Python fallbacks")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    # The env check sits OUTSIDE the cache so flipping NICE_NO_NATIVE after a
    # first call still takes effect (tests toggle it per-case).
    if os.environ.get("NICE_NO_NATIVE"):
        return None
    return _load_lib()


@lru_cache(maxsize=1)
def _load_lib():
    if not _build():
        return None
    lib = ctypes.CDLL(_LIB)
    lib.nice_num_unique_digits.restype = ctypes.c_int
    lib.nice_num_unique_digits.argtypes = [_U64, _U64, _U64]
    lib.nice_is_nice.restype = ctypes.c_int
    lib.nice_is_nice.argtypes = [_U64, _U64, _U64]
    lib.nice_process_range_detailed.restype = None
    lib.nice_process_range_detailed.argtypes = [
        _U64, _U64, _U64, _U64, _U64,
        ctypes.POINTER(_U64), ctypes.POINTER(_U64), _U64, ctypes.POINTER(_U64),
    ]
    lib.nice_iterate_range_strided.restype = None
    lib.nice_iterate_range_strided.argtypes = [
        _U64, _U64, _U64, _U64, _U64, _U64,
        ctypes.POINTER(_U64), _U64, ctypes.POINTER(_U64), _U64,
        ctypes.POINTER(_U64),
    ]
    lib.nice_iterate_range_strided_poly.restype = None
    lib.nice_iterate_range_strided_poly.argtypes = [
        _U64, _U64, _U64, _U64, _U64, _U64, _U64,
        ctypes.POINTER(ctypes.c_uint32), _U64, ctypes.POINTER(_U64), _U64,
        ctypes.POINTER(_U64), ctypes.POINTER(ctypes.c_int),
    ]
    lib.nice_strided_fast_enabled.restype = ctypes.c_int
    lib.nice_strided_fast_enabled.argtypes = [ctypes.c_int]
    lib.nice_has_duplicate_msd_prefix.restype = ctypes.c_int
    lib.nice_has_duplicate_msd_prefix.argtypes = [_U64, _U64, _U64, _U64, _U64]
    lib.nice_msd_valid_ranges.restype = ctypes.c_void_p
    lib.nice_msd_valid_ranges.argtypes = [
        _U64, _U64, _U64, _U64, _U64, ctypes.c_int, _U64, ctypes.c_int,
    ]
    lib.nice_ranges_count.restype = _U64
    lib.nice_ranges_count.argtypes = [ctypes.c_void_p]
    lib.nice_ranges_copy.restype = None
    lib.nice_ranges_copy.argtypes = [ctypes.c_void_p, ctypes.POINTER(_U64)]
    lib.nice_ranges_free.restype = None
    lib.nice_ranges_free.argtypes = [ctypes.c_void_p]
    return lib


def available() -> bool:
    return _load() is not None


def _split(n: int) -> tuple[int, int]:
    if n < 0 or n >= 1 << 128:
        raise ValueError(f"{n} does not fit in u128")
    return n & _MASK64, n >> 64


def _base_ok(base: int) -> bool:
    """Bases the C++ arithmetic supports: digit indicators are u128 bitmasks
    (base <= 128) and digit buffers are sized for base >= 4 (a cube of a
    128-bit value has up to ~192 base-4 digits). Out-of-bounds bases use the
    Python fallbacks, which the oracle allows up to 256."""
    return 4 <= base <= 128


def num_unique_digits(num: int, base: int) -> int:
    """Native-or-fallback scalar niceness check (server verification path)."""
    lib = _load()
    if lib is None or num >= 1 << 128 or not _base_ok(base):
        from nice_tpu.ops import scalar

        return scalar.get_num_unique_digits(num, base)
    lo, hi = _split(num)
    return lib.nice_num_unique_digits(lo, hi, base)


def is_nice(num: int, base: int) -> bool:
    lib = _load()
    if lib is None or num >= 1 << 128 or not _base_ok(base):
        from nice_tpu.ops import scalar

        return scalar.get_is_nice(num, base)
    lo, hi = _split(num)
    return bool(lib.nice_is_nice(lo, hi, base))


def process_range_detailed(start: int, count: int, base: int, cutoff: int):
    """(histogram list[base+2], [(n, num_uniques), ...]) for [start, start+count).

    Returns None when the native library is unavailable (callers fall back to
    the scalar oracle).
    """
    lib = _load()
    if lib is None or start + count >= 1 << 128 or not _base_ok(base):
        return None
    lo, hi = _split(start)
    hist = (_U64 * (base + 2))()
    cap = 4096
    while True:
        misses = (_U64 * (3 * cap))()
        miss_count = _U64(0)
        for i in range(base + 2):
            hist[i] = 0
        lib.nice_process_range_detailed(
            lo, hi, count, base, cutoff, hist, misses, cap,
            ctypes.byref(miss_count),
        )
        if miss_count.value <= cap:
            break
        cap = int(miss_count.value)
    out_misses = [
        (misses[i * 3] | (misses[i * 3 + 1] << 64), int(misses[i * 3 + 2]))
        for i in range(min(int(miss_count.value), cap))
    ]
    return list(hist), out_misses


def strided_fast_enabled(enable: bool) -> bool:
    """Test hook: toggle the native fast strided filters (poly + magic-div);
    returns the previous setting. No-op (returns True) without the library."""
    lib = _load()
    if lib is None:
        return True
    return bool(lib.nice_strided_fast_enabled(1 if enable else 0))


def iterate_range_strided(first: int, start_idx: int, end: int, base: int,
                          gap_table, modulus: int | None = None,
                          residues=None) -> list[int] | None:
    """Nice numbers among stride candidates in [first, end), starting from
    candidate `first` at residue index start_idx. None => no native library.

    gap_table may be a Python list or a numpy uint64 array (the latter avoids
    a per-call ctypes copy — at depth k=3 the table has ~1e5-1e6 entries, and
    rebuilding it per MSD range once dominated the whole native path).
    Passing the table's (modulus, residues_array) as well routes eligible
    calls through the polynomial-residue fast kernel (see nice_native.cpp).
    """
    lib = _load()
    if lib is None or end >= 1 << 128 or not _base_ok(base):
        return None
    import numpy as np

    flo, fhi = _split(first)
    elo, ehi = _split(end)
    cap = 1024
    poly = (
        modulus is not None
        and residues is not None
        and isinstance(residues, np.ndarray)
        and residues.dtype == np.uint32
    )
    if poly:
        res_ptr = residues.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
        num = len(residues)
        while True:
            out = (_U64 * (2 * cap))()
            count = _U64(0)
            used = ctypes.c_int(0)
            lib.nice_iterate_range_strided_poly(
                flo, fhi, start_idx, elo, ehi, base, modulus, res_ptr, num,
                out, cap, ctypes.byref(count), ctypes.byref(used),
            )
            if not used.value:
                break  # ineligible: fall through to the generic loop
            if count.value <= cap:
                return [
                    out[i * 2] | (out[i * 2 + 1] << 64)
                    for i in range(int(count.value))
                ]
            cap = int(count.value)
    if isinstance(gap_table, np.ndarray) and gap_table.dtype == np.uint64:
        num = len(gap_table)
        gaps = gap_table.ctypes.data_as(ctypes.POINTER(_U64))
    else:
        num = len(gap_table)
        gaps = (_U64 * num)(*gap_table)
    while True:
        out = (_U64 * (2 * cap))()
        count = _U64(0)
        lib.nice_iterate_range_strided(
            flo, fhi, start_idx, elo, ehi, base, gaps, num, out, cap,
            ctypes.byref(count),
        )
        if count.value <= cap:
            break
        cap = int(count.value)
    return [out[i * 2] | (out[i * 2 + 1] << 64) for i in range(int(count.value))]


def has_duplicate_msd_prefix(start: int, end: int, base: int) -> bool | None:
    lib = _load()
    if lib is None or end >= 1 << 128 or not _base_ok(base):
        return None
    slo, shi = _split(start)
    elo, ehi = _split(end)
    return bool(lib.nice_has_duplicate_msd_prefix(slo, shi, elo, ehi, base))


def msd_valid_ranges(start: int, end: int, base: int, max_depth: int,
                     min_range_size: int, subdivision_factor: int):
    """[(sub_start, sub_end), ...] surviving the recursive MSD filter.
    None => no native library (callers use the Python implementation)."""
    lib = _load()
    if lib is None or end >= 1 << 128 or not _base_ok(base):
        return None
    slo, shi = _split(start)
    elo, ehi = _split(end)
    handle = lib.nice_msd_valid_ranges(
        slo, shi, elo, ehi, base, max_depth, min_range_size, subdivision_factor
    )
    try:
        n = int(lib.nice_ranges_count(handle))
        buf = (_U64 * (4 * n))()
        if n:
            lib.nice_ranges_copy(handle, buf)
        return [
            (
                buf[i * 4] | (buf[i * 4 + 1] << 64),
                buf[i * 4 + 2] | (buf[i * 4 + 3] << 64),
            )
            for i in range(n)
        ]
    finally:
        lib.nice_ranges_free(handle)
