"""Field-ledger data access layer and atomic claim engine.

SQLite-backed (stdlib) equivalent of the reference's Diesel/Postgres layer
(common/src/db_util/*). The SQL stays engine-portable; u128 quantities are
stored as 40-char zero-padded decimal TEXT (lexicographic == numeric order),
timestamps as ISO-8601 UTC TEXT.

Atomicity: the reference relies on single-statement `FOR UPDATE SKIP LOCKED`
claims (db_util/fields.rs:204-536). SQLite has a single writer, so the same
guarantee comes from running each claim as one `BEGIN IMMEDIATE` transaction
under a process-level lock; the claim-strategy semantics (Next / Random-pivot
with wraparound / Thin under-explored chunk, expired-lease predicate,
check_level = 0 special case) are preserved exactly.
"""

from __future__ import annotations

import base64
import json
import math
import os
import random
import sqlite3
import threading
from contextlib import contextmanager
from datetime import datetime, timedelta, timezone
from typing import Optional

from nice_tpu.core import base_range, generate_chunks, generate_fields
from nice_tpu.core.constants import CLAIM_DURATION_HOURS, DOWNSAMPLE_CUTOFF_PERCENT
from nice_tpu.obs.series import (
    SERVER_CLAIM_EXPIRY,
    SERVER_CLAIM_RENEWALS,
    SERVER_FIELDS_RELEASED,
    SERVER_JOURNAL_EVENTS,
    SERVER_JOURNAL_PRUNED,
    SERVER_LEASES_EXPIRED,
    SERVER_SQLITE_BUSY_RETRIES,
)
from nice_tpu.utils import knobs, lockdep
from nice_tpu.core.types import (
    ClaimRecord,
    FieldClaimStrategy,
    FieldRecord,
    NiceNumber,
    SearchMode,
    SubmissionRecord,
    UniquesDistribution,
    ValidationData,
)

U128_WIDTH = 40  # fits 2^128-1 (39 digits) with margin


def pad(x: int) -> str:
    """u128 -> fixed-width decimal TEXT preserving order."""
    if x < 0:
        raise ValueError("negative value in u128 column")
    s = str(x)
    if len(s) > U128_WIDTH:
        raise ValueError(f"{x} too wide for u128 column")
    return s.zfill(U128_WIDTH)


def unpad(s: str) -> int:
    return int(s)


def ts(dt: datetime) -> str:
    return dt.astimezone(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def parse_ts(s: Optional[str]) -> Optional[datetime]:
    if not s:
        return None
    return datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%fZ").replace(tzinfo=timezone.utc)


def now_utc() -> datetime:
    return datetime.now(timezone.utc)


def _dist_to_json(dist: Optional[list[UniquesDistribution]]) -> Optional[str]:
    if dist is None:
        return None
    return json.dumps(
        [
            {
                "num_uniques": d.num_uniques,
                "count": d.count,
                "niceness": d.niceness,
                "density": d.density,
            }
            for d in dist
        ]
    )


def _dist_from_json(s: Optional[str]) -> Optional[list[UniquesDistribution]]:
    if s is None:
        return None
    return [
        UniquesDistribution(
            num_uniques=int(d["num_uniques"]),
            count=int(d["count"]),
            niceness=float(d["niceness"]),
            density=float(d["density"]),
        )
        for d in json.loads(s)
    ]


def _numbers_to_json(numbers: list[NiceNumber]) -> str:
    return json.dumps(
        [
            {
                "number": str(n.number),
                "num_uniques": n.num_uniques,
                "base": n.base,
                "niceness": n.niceness,
            }
            for n in numbers
        ]
    )


def _numbers_from_json(s: str) -> list[NiceNumber]:
    return [
        NiceNumber(
            number=int(n["number"]),
            num_uniques=int(n["num_uniques"]),
            base=int(n["base"]),
            niceness=float(n["niceness"]),
        )
        for n in json.loads(s)
    ]


class Db:
    """Thread-safe ledger handle: one RLock-guarded write connection (atomic
    claim engine) plus a per-thread WAL read-connection pool."""

    def __init__(self, path: str = None):
        self.path = path or os.environ.get("NICE_DATABASE_PATH", "nice.db")
        self._lock = lockdep.make_rlock("server.db.Db._lock")
        self._conn = self._connect()  # write connection (claim path)
        # Read pool: one connection per server thread (WAL readers never
        # block each other or the writer), so analytics endpoints and submit
        # verification reads don't serialize behind the claim path — the
        # SQLite analog of the reference's r2d2 Postgres pool
        # (db_util/mod.rs:39-61). The write connection stays single and
        # RLock-guarded; BEGIN IMMEDIATE in _txn provides claim-path mutual
        # exclusion, and busy_timeout makes writers from OTHER processes
        # (multi-worker deployments, jobs runner alongside the API) wait out
        # short bursts instead of failing with "database is locked" (the
        # analog of FOR UPDATE SKIP LOCKED claims, db_util/fields.rs:204-536).
        self._local = threading.local()
        # (owner thread, conn); owner None = the write connection. Entries of
        # dead threads are pruned on the next _read() — ThreadingHTTPServer
        # spawns a thread per TCP connection, so without pruning the pool
        # would leak one sqlite connection per request thread.
        self._pool: list[tuple[Optional[threading.Thread], sqlite3.Connection]] = [
            (None, self._conn)
        ]
        self._pool_lock = lockdep.make_lock("server.db.Db._pool_lock")
        self._closed = False
        # Savepoint-nesting depth of the write connection. Only read/written
        # with _lock held (RLock, so nested _txn() blocks on one thread are
        # fine): 0 means the next _txn opens a real BEGIN IMMEDIATE; deeper
        # levels open SAVEPOINTs, which is what lets the writer actor wrap a
        # whole batch of ordinary Db method calls in ONE durable transaction
        # while each call keeps per-operation atomicity.
        self._txn_depth = 0
        self.init_schema()

    def _connect(self) -> sqlite3.Connection:
        conn = sqlite3.connect(
            self.path, check_same_thread=False, isolation_level=None
        )
        conn.row_factory = sqlite3.Row
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA foreign_keys=ON")
        conn.execute("PRAGMA busy_timeout=10000")
        return conn

    def _read(self) -> sqlite3.Connection:
        """This thread's read connection (created on first use)."""
        if self._closed:
            raise sqlite3.ProgrammingError("Db is closed")
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._local.conn = self._connect()
            with self._pool_lock:
                # Prune connections whose owner thread has exited (cross-
                # thread close is safe: connections are opened with
                # check_same_thread=False).
                for owner, stale in [
                    e for e in self._pool if e[0] is not None and not e[0].is_alive()
                ]:
                    stale.close()
                    self._pool.remove((owner, stale))
                self._pool.append((threading.current_thread(), conn))
        return conn

    @contextmanager
    def _read_conn(self):
        """Read-only access: the calling thread's pooled connection, no lock
        (WAL readers are concurrent with each other and the writer)."""
        yield self._read()

    def init_schema(self) -> None:
        schema_path = os.path.join(os.path.dirname(__file__), "schema.sql")
        with open(schema_path) as f:
            with self._lock:
                self._conn.executescript(f.read())
                # Legacy-DB migration: CREATE TABLE IF NOT EXISTS leaves a
                # pre-submit_id submissions table untouched, so add the
                # column before the partial unique index that enforces
                # exactly-once submits (NULL submit_ids — legacy clients —
                # stay outside the index and never collide).
                cols = {
                    r["name"]
                    for r in self._conn.execute(
                        "PRAGMA table_info(submissions)"
                    ).fetchall()
                }
                if "submit_id" not in cols:
                    self._conn.execute(
                        "ALTER TABLE submissions ADD COLUMN submit_id TEXT"
                    )
                self._conn.execute(
                    "CREATE UNIQUE INDEX IF NOT EXISTS idx_submissions_submit_id"
                    " ON submissions(submit_id) WHERE submit_id IS NOT NULL"
                )
                # Block claim leases (same migration pattern): claims minted
                # by /claim_block share a block_id so one /renew_claim can
                # re-arm every member and expiry releases the block whole.
                claim_cols = {
                    r["name"]
                    for r in self._conn.execute(
                        "PRAGMA table_info(claims)"
                    ).fetchall()
                }
                if "block_id" not in claim_cols:
                    self._conn.execute(
                        "ALTER TABLE claims ADD COLUMN block_id TEXT"
                    )
                self._conn.execute(
                    "CREATE INDEX IF NOT EXISTS idx_claims_block_id"
                    " ON claims(block_id) WHERE block_id IS NOT NULL"
                )
                # Untrusted-client hardening: claims carry the client's trust
                # token plus an explicit lease window (NULL on rows minted by
                # pre-trust servers — those stay outside the lease sweep and
                # keep the legacy claim_expiry_cutoff behavior); submissions
                # carry the token so consensus can weigh trust.
                for col, decl in (
                    ("client_token", "TEXT"),
                    ("lease_expiry", "TEXT"),
                    ("lease_secs", "REAL"),
                ):
                    if col not in claim_cols:
                        self._conn.execute(
                            f"ALTER TABLE claims ADD COLUMN {col} {decl}"
                        )
                if "client_token" not in cols:
                    self._conn.execute(
                        "ALTER TABLE submissions ADD COLUMN client_token TEXT"
                    )
                self._conn.execute(
                    "CREATE INDEX IF NOT EXISTS idx_claims_lease_expiry"
                    " ON claims(lease_expiry) WHERE lease_expiry IS NOT NULL"
                )
                self._conn.execute(
                    "CREATE INDEX IF NOT EXISTS idx_claims_client_token"
                    " ON claims(client_token) WHERE client_token IS NOT NULL"
                )
                # The aggregate per-IP outstanding-claims ceiling counts
                # leased claims by source address.
                self._conn.execute(
                    "CREATE INDEX IF NOT EXISTS idx_claims_user_ip"
                    " ON claims(user_ip) WHERE lease_expiry IS NOT NULL"
                )
                # Multi-tenant scheduler routing: claims carry the tenant
                # name they were issued for (NULL on single-workload claims);
                # submissions inherit it through their claim at query time.
                if "tenant" not in claim_cols:
                    self._conn.execute(
                        "ALTER TABLE claims ADD COLUMN tenant TEXT"
                    )
                self._conn.execute(
                    "CREATE INDEX IF NOT EXISTS idx_claims_tenant"
                    " ON claims(tenant) WHERE tenant IS NOT NULL"
                )
                # Replication capture last: the triggers are regenerated
                # from PRAGMA table_info AFTER every migration above, so a
                # column added by a newer server version is captured from
                # its first write.
                self._init_repl()

    def close(self) -> None:
        with self._lock, self._pool_lock:
            self._closed = True
            for _, conn in self._pool:
                conn.close()
            self._pool.clear()
            self._local = threading.local()

    # -- seeding ----------------------------------------------------------

    def seed_base(self, base: int, field_size: int = 1_000_000_000) -> int:
        """Create the base row, fields, and chunks for a base (the reference's
        insert_new_fields / generate_fields / generate_chunks flow). Returns
        the number of fields created."""
        br = base_range.get_base_range(base)
        if br is None:
            raise ValueError(f"base {base} has no valid range")
        fields = generate_fields.break_range_into_fields(br[0], br[1], field_size)
        chunks = generate_chunks.group_fields_into_chunks(list(fields))
        with self._lock, self._txn():
            self._conn.execute(
                "INSERT OR REPLACE INTO bases (id, range_start, range_end, range_size)"
                " VALUES (?, ?, ?, ?)",
                (base, pad(br[0]), pad(br[1]), pad(br[1] - br[0])),
            )
            chunk_ids = []
            for c in chunks:
                cur = self._conn.execute(
                    "INSERT INTO chunks (base_id, range_start, range_end, range_size)"
                    " VALUES (?, ?, ?, ?)",
                    (base, pad(c.range_start), pad(c.range_end), pad(c.size())),
                )
                chunk_ids.append((cur.lastrowid, c))
            # Fields and chunks are both sorted and contiguous, so a
            # two-pointer walk assigns chunk ids in O(F + C) — the per-field
            # scan it replaces was O(F * C), minutes for the ~10^5-field
            # bases the load harness seeds. Streamed through executemany so
            # the row tuples never all exist at once.
            def _rows():
                ci = 0
                for f in fields:
                    while (
                        ci < len(chunk_ids)
                        and f.range_start >= chunk_ids[ci][1].range_end
                    ):
                        ci += 1
                    if ci >= len(chunk_ids) or not (
                        chunk_ids[ci][1].range_start
                        <= f.range_start
                        < chunk_ids[ci][1].range_end
                    ):
                        raise ValueError(
                            f"field at {f.range_start} not covered by any chunk"
                        )
                    yield (
                        base,
                        chunk_ids[ci][0],
                        pad(f.range_start),
                        pad(f.range_end),
                        pad(f.size()),
                    )

            self._conn.executemany(
                "INSERT INTO fields (base_id, chunk_id, range_start, range_end,"
                " range_size) VALUES (?, ?, ?, ?, ?)",
                _rows(),
            )
            # Journal birth: every field's timeline starts at seq 1 with a
            # "generated" event, written in the same transaction as the field
            # rows (one SELECT-driven insert, fast even for ~10^5-field
            # bases). OR IGNORE keeps a re-seed of an existing base from
            # tripping the (field_id, seq) uniqueness of the first run.
            self._conn.execute(
                "INSERT OR IGNORE INTO field_events"
                " (field_id, seq, ts, kind, detail)"
                " SELECT id, 1, ?, 'generated', '{}' FROM fields"
                " WHERE base_id = ?",
                (ts(now_utc()), base),
            )
        return len(fields)

    # -- transactions -----------------------------------------------------

    # BEGIN IMMEDIATE takes the write lock up front; when ANOTHER process
    # holds it (multi-worker deployments, the jobs runner) past busy_timeout,
    # sqlite surfaces SQLITE_BUSY as OperationalError. A short bounded retry
    # absorbs claim/renew/submit write bursts instead of bubbling them up as
    # 500s; in-process writers never hit this (the RLock serializes them).
    TXN_BUSY_RETRIES = 5
    TXN_BUSY_SLEEP_SECS = 0.05

    class _Txn:
        """Write transaction with savepoint nesting.

        The outermost level (depth 0) is a real BEGIN IMMEDIATE with the
        bounded SQLITE_BUSY retry; nested levels open SAVEPOINTs instead.
        Nesting is what lets the single-writer actor wrap a whole batch of
        unmodified Db method calls (each doing `with self._lock, self._txn()`)
        in one durable transaction — per-call failures (e.g. a duplicate
        submit_id's IntegrityError) roll back only their own savepoint, the
        rest of the batch commits with one fsync. Depth lives on the Db and
        is only touched with _lock held (RLock, re-entrant on one thread)."""

        def __init__(self, db: "Db"):
            self.db = db
            self.level = None

        @staticmethod
        def _is_busy(e: sqlite3.OperationalError) -> bool:
            msg = str(e).lower()
            return "locked" in msg or "busy" in msg

        def __enter__(self):
            import time as _time

            conn = self.db._conn
            self.level = self.db._txn_depth
            if self.level > 0:
                conn.execute(f"SAVEPOINT nice_sp_{self.level}")
                self.db._txn_depth += 1
                return self
            for attempt in range(Db.TXN_BUSY_RETRIES + 1):
                try:
                    conn.execute("BEGIN IMMEDIATE")
                    self.db._txn_depth += 1
                    return self
                except sqlite3.OperationalError as e:
                    if not self._is_busy(e) or attempt >= Db.TXN_BUSY_RETRIES:
                        raise
                    SERVER_SQLITE_BUSY_RETRIES.inc()
                    _time.sleep(Db.TXN_BUSY_SLEEP_SECS * (attempt + 1))
            raise AssertionError("unreachable")

        def __exit__(self, exc_type, *a):
            conn = self.db._conn
            self.db._txn_depth -= 1
            if self.level == 0:
                conn.execute("COMMIT" if exc_type is None else "ROLLBACK")
            else:
                name = f"nice_sp_{self.level}"
                if exc_type is None:
                    conn.execute(f"RELEASE {name}")
                else:
                    conn.execute(f"ROLLBACK TO {name}")
                    conn.execute(f"RELEASE {name}")

    def _txn(self) -> "Db._Txn":
        return Db._Txn(self)

    # -- field access -----------------------------------------------------

    def _row_to_field(self, row: sqlite3.Row) -> FieldRecord:
        return FieldRecord(
            field_id=row["id"],
            base=row["base_id"],
            chunk_id=row["chunk_id"],
            range_start=unpad(row["range_start"]),
            range_end=unpad(row["range_end"]),
            range_size=unpad(row["range_size"]),
            last_claim_time=parse_ts(row["last_claim_time"]),
            canon_submission_id=row["canon_submission_id"],
            check_level=row["check_level"],
            prioritize=bool(row["prioritize"]),
        )

    def get_field_by_id(self, field_id: int) -> FieldRecord:
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT * FROM fields WHERE id = ?", (field_id,)
            ).fetchone()
        if row is None:
            raise KeyError(f"no field {field_id}")
        return self._row_to_field(row)

    def get_fields_in_base(self, base: int) -> list[FieldRecord]:
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT * FROM fields WHERE base_id = ? ORDER BY id ASC", (base,)
            ).fetchall()
        return [self._row_to_field(r) for r in rows]

    def get_bases(self) -> list[int]:
        with self._read_conn() as conn:
            rows = conn.execute("SELECT id FROM bases ORDER BY id ASC").fetchall()
        return [r["id"] for r in rows]

    def update_field_canon_and_cl(
        self, field_id: int, canon_submission_id: Optional[int], check_level: int
    ) -> None:
        with self._lock, self._txn():
            self._conn.execute(
                "UPDATE fields SET canon_submission_id = ?, check_level = ?"
                " WHERE id = ?",
                (canon_submission_id, check_level, field_id),
            )

    # -- claim engine -----------------------------------------------------

    @staticmethod
    def _cl_predicate(maximum_check_level: int) -> tuple[str, list]:
        # check_level = 0 special case targets the partial index, mirroring
        # the reference optimization (db_util/fields.rs:218-229).
        if maximum_check_level == 0:
            return "check_level = 0", []
        return "check_level <= ?", [maximum_check_level]

    # The possibly-active fallback's ordering: hand out the least-checked,
    # longest-abandoned field first, so a dead client's stale cl-0 lease is
    # re-issued before a completed field gets a redundant re-check.
    PREFER_ABANDONED = "check_level ASC, COALESCE(last_claim_time, '') ASC, id ASC"

    def _claim_rows(
        self,
        where: str,
        params: list,
        count: int,
        claim_time: datetime,
        order_by: str = "id ASC",
    ) -> list[FieldRecord]:
        """Single-transaction SELECT..LIMIT + UPDATE last_claim_time."""
        with self._lock, self._txn():
            rows = self._conn.execute(
                f"SELECT * FROM fields WHERE {where} ORDER BY {order_by} LIMIT ?",
                (*params, count),
            ).fetchall()
            if rows:
                self._conn.executemany(
                    "UPDATE fields SET last_claim_time = ? WHERE id = ?",
                    [(ts(claim_time), r["id"]) for r in rows],
                )
        return [self._row_to_field(r) for r in rows]

    def try_claim_field(
        self,
        claim_strategy: FieldClaimStrategy,
        maximum_timestamp: datetime,
        maximum_check_level: int,
        maximum_size: int,
        base_min: Optional[int] = None,
        base_max: Optional[int] = None,
    ) -> Optional[FieldRecord]:
        """Claim one field (reference db_util/fields.rs:204-484)."""
        got = self._claim_batch(
            claim_strategy, maximum_timestamp, maximum_check_level, maximum_size, 1,
            base_min=base_min, base_max=base_max,
        )
        return got[0] if got else None

    def _claim_batch(
        self,
        claim_strategy: FieldClaimStrategy,
        maximum_timestamp: datetime,
        maximum_check_level: int,
        maximum_size: int,
        count: int,
        order_by: str = "id ASC",
        base_min: Optional[int] = None,
        base_max: Optional[int] = None,
    ) -> list[FieldRecord]:
        now = now_utc()
        cl_sql, cl_params = self._cl_predicate(maximum_check_level)
        base_where = (
            f"COALESCE(last_claim_time, '') <= ? AND {cl_sql} AND range_size <= ?"
        )
        base_params = [ts(maximum_timestamp), *cl_params, pad(maximum_size)]
        # Tenant base predicates (multi-tenant claim routing): restrict the
        # claim to the tenant's base window so e.g. a bases>510 sweep tenant
        # never drains low-base inventory.
        if base_min is not None:
            base_where += " AND base_id >= ?"
            base_params.append(base_min)
        if base_max is not None:
            base_where += " AND base_id <= ?"
            base_params.append(base_max)

        if claim_strategy == FieldClaimStrategy.NEXT:
            return self._claim_rows(
                base_where, base_params, count, now, order_by=order_by
            )

        if claim_strategy == FieldClaimStrategy.RANDOM:
            max_id = self._max_field_id()
            if max_id == 0:
                return []
            pivot = random.randint(1, max_id)
            got = self._claim_rows(
                f"id >= ? AND {base_where}", [pivot, *base_params], count, now
            )
            if got:
                return got
            return self._claim_rows(base_where, base_params, count, now)

        if claim_strategy == FieldClaimStrategy.THIN:
            chunk_id, min_id, max_id = self._find_thin_chunk(maximum_check_level)
            if chunk_id is None:
                return []
            pivot = min_id if min_id == max_id else random.randint(min_id, max_id)
            got = self._claim_rows(
                f"chunk_id = ? AND id >= ? AND {base_where}",
                [chunk_id, pivot, *base_params],
                count,
                now,
            )
            if got:
                return got
            return self._claim_rows(
                f"chunk_id = ? AND {base_where}", [chunk_id, *base_params], count, now
            )

        raise ValueError(f"unknown strategy {claim_strategy}")

    def _max_field_id(self) -> int:
        with self._read_conn() as conn:
            row = conn.execute("SELECT MAX(id) AS m FROM fields").fetchone()
        return row["m"] or 0

    def _find_thin_chunk(self, maximum_check_level: int):
        """First chunk with < DOWNSAMPLE_CUTOFF_PERCENT checked for the mode,
        in ONE SQL statement (reference db_util/fields.rs:349-380).

        The counts are zero-padded decimal TEXT (u128-capable); CAST(... AS
        REAL) is approximate above 2^53 (hi-base chunks reach ~1e28), so the
        SQL predicate runs with a 1-ulp-widened cutoff as a PREFILTER and
        the returned candidates are re-checked exactly in Python with
        integer arithmetic (advisor r4: a pure-REAL predicate could
        permanently misclassify a chunk sitting within a float ulp of the
        20% boundary). The win over a full Python scan remains: SQL rejects
        all clearly-checked chunks; Python only sees boundary candidates,
        virtually always exactly one row."""
        from fractions import Fraction

        cutoff = Fraction(str(DOWNSAMPLE_CUTOFF_PERCENT))
        col = "checked_niceonly" if maximum_check_level == 0 else "checked_detailed"
        with self._read_conn() as conn:
            rows = conn.execute(
                f"""
                SELECT c.id AS chunk_id,
                       c.range_size AS range_size,
                       c.{col} AS checked,
                       (SELECT MIN(id) FROM fields WHERE chunk_id = c.id) AS lo,
                       (SELECT MAX(id) FROM fields WHERE chunk_id = c.id) AS hi
                FROM chunks c
                WHERE CAST(c.range_size AS REAL) > 0
                  AND CAST(c.{col} AS REAL)
                      < ? * CAST(c.range_size AS REAL)
                  AND EXISTS (SELECT 1 FROM fields WHERE chunk_id = c.id)
                ORDER BY c.id ASC
                """,
                (DOWNSAMPLE_CUTOFF_PERCENT * (1.0 + 1e-9),),
            )
            for row in rows:
                size = int(row["range_size"])
                if size > 0 and int(row["checked"]) * cutoff.denominator < (
                    cutoff.numerator * size
                ):
                    return row["chunk_id"], row["lo"], row["hi"]
        return None, None, None

    def bulk_claim_fields(
        self,
        count: int,
        maximum_timestamp: datetime,
        maximum_check_level: int,
        maximum_size: int,
    ) -> list[FieldRecord]:
        """Claim up to count fields in one transaction for queue prefill
        (reference db_util/fields.rs:488-536)."""
        return self._claim_batch(
            FieldClaimStrategy.NEXT,
            maximum_timestamp,
            maximum_check_level,
            maximum_size,
            count,
        )

    def bulk_claim_thin_fields(
        self,
        count: int,
        maximum_timestamp: datetime,
        maximum_check_level: int,
        maximum_size: int,
    ) -> list[FieldRecord]:
        """Bulk claim from the first under-explored chunk
        (reference db_util/fields.rs:544-609)."""
        now = now_utc()
        cl_sql, cl_params = self._cl_predicate(maximum_check_level)
        chunk_id, _, _ = self._find_thin_chunk(maximum_check_level)
        if chunk_id is None:
            return []
        where = (
            f"chunk_id = ? AND COALESCE(last_claim_time, '') <= ? AND {cl_sql}"
            " AND range_size <= ?"
        )
        return self._claim_rows(
            where, [chunk_id, ts(maximum_timestamp), *cl_params, pad(maximum_size)],
            count, now,
        )

    def claim_expiry_cutoff(self) -> datetime:
        """Leases older than this are re-claimable. NICE_TPU_CLAIM_EXPIRY_SECS
        overrides the CLAIM_DURATION_HOURS default so deployments with long
        fields (or aggressive clients) can widen/narrow the window without a
        code change; the active window is surfaced in /metrics."""
        secs = knobs.CLAIM_EXPIRY_SECS.get(default=CLAIM_DURATION_HOURS * 3600)
        SERVER_CLAIM_EXPIRY.set(secs)
        return now_utc() - timedelta(seconds=secs)

    def release_field_claims(self, field_ids: list[int]) -> int:
        """Clear the claim lease on fields so they are immediately
        re-claimable (queue shutdown returns its pre-claimed inventory).
        Returns how many rows actually held a lease."""
        if not field_ids:
            return 0
        released = 0
        with self._lock, self._txn():
            for fid in field_ids:
                cur = self._conn.execute(
                    "UPDATE fields SET last_claim_time = NULL"
                    " WHERE id = ? AND last_claim_time IS NOT NULL",
                    (fid,),
                )
                released += cur.rowcount
        SERVER_FIELDS_RELEASED.inc(released)
        return released

    def release_expired_leases(self) -> list[int]:
        """Background sweep (writer-actor periodic): clear the field lease
        behind every claim whose explicit lease_expiry has passed without a
        submission, so abandoned micro-field claims re-enter the claim pool
        in seconds instead of waiting out the global expiry cutoff. A field
        is left alone while ANY unexpired unsubmitted claim still covers it
        (a re-issued field's second lease must not be swept by the first
        client's corpse). Returns the released field ids (the caller journals
        a lease_expired event per field); legacy NULL-expiry claims are never
        touched."""
        now = ts(now_utc())
        with self._lock, self._txn():
            rows = self._conn.execute(
                """
                SELECT f.id FROM fields f
                WHERE f.last_claim_time IS NOT NULL AND f.id IN (
                  SELECT c.field_id FROM claims c
                  WHERE c.lease_expiry IS NOT NULL AND c.lease_expiry < :now
                    AND NOT EXISTS (SELECT 1 FROM submissions s
                                    WHERE s.claim_id = c.id)
                    AND NOT EXISTS (
                      SELECT 1 FROM claims c2
                      WHERE c2.field_id = c.field_id
                        AND c2.lease_expiry >= :now
                        AND NOT EXISTS (SELECT 1 FROM submissions s2
                                        WHERE s2.claim_id = c2.id)))
                """,
                {"now": now},
            ).fetchall()
            released = [int(r["id"]) for r in rows]
            if released:
                self._conn.executemany(
                    "UPDATE fields SET last_claim_time = NULL WHERE id = ?",
                    [(fid,) for fid in released],
                )
        if released:
            SERVER_LEASES_EXPIRED.inc(len(released))
        return released

    def release_orphaned_inventory(self) -> int:
        """Startup sweep: release lease stamps left by a DEAD server's
        in-memory queue inventory. The refiller bulk-claims fields (stamping
        fields.last_claim_time) without minting claims rows — claims are
        minted at pop time — so a SIGKILL strands up to a full refill batch
        of stamped-but-never-issued fields until the global expiry cutoff
        (FieldQueue.close() handles graceful shutdown; this is the crash
        counterpart). A field actually issued to a client always has a
        claims row minted in the same writer operation as its stamp, so the
        orphan test is: no claims row within 2s at-or-after the stamp (the
        stamp and claim-row clocks are read milliseconds apart, in either
        order). A renewed claim re-stamps the field while claim_time stays
        at the original claim, so a live unsubmitted lease also keeps its
        field — including a renewed LEGACY claim (lease_expiry NULL, from a
        pre-trust server), which keeps its field as long as its claim_time
        is inside the global claim-expiry window. Must run before this
        process's own FieldQueue starts refilling."""
        now = ts(now_utc())
        cutoff = ts(self.claim_expiry_cutoff())
        with self._lock, self._txn():
            cur = self._conn.execute(
                """
                UPDATE fields SET last_claim_time = NULL
                WHERE last_claim_time IS NOT NULL
                  AND NOT EXISTS (
                    SELECT 1 FROM claims c
                    WHERE c.field_id = fields.id
                      AND (julianday(c.claim_time)
                             >= julianday(fields.last_claim_time) - 2.0 / 86400.0
                           OR (c.lease_expiry IS NOT NULL
                               AND c.lease_expiry >= :now
                               AND NOT EXISTS (SELECT 1 FROM submissions s
                                               WHERE s.claim_id = c.id))
                           OR (c.lease_expiry IS NULL
                               AND c.claim_time >= :cutoff
                               AND NOT EXISTS (SELECT 1 FROM submissions s
                                               WHERE s.claim_id = c.id))))
                """,
                {"now": now, "cutoff": cutoff},
            )
            released = cur.rowcount
        if released:
            SERVER_FIELDS_RELEASED.inc(released)
        return released

    def count_open_claims(self, client_token: str) -> int:
        """Outstanding unexpired, unsubmitted claims held by one client
        (the per-client outstanding-claims cap for untrusted profiles)."""
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT COUNT(*) AS n FROM claims c"
                " WHERE c.client_token = ? AND c.lease_expiry >= ?"
                " AND NOT EXISTS (SELECT 1 FROM submissions s"
                "                 WHERE s.claim_id = c.id)",
                (client_token, ts(now_utc())),
            ).fetchone()
        return int(row["n"])

    def count_open_claims_by_ip(self, user_ip: str) -> int:
        """Outstanding unexpired, unsubmitted claims from one source IP,
        across every client identity behind it (the aggregate ceiling that
        makes per-identity caps meaningful when identities are free)."""
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT COUNT(*) AS n FROM claims c"
                " WHERE c.user_ip = ? AND c.lease_expiry >= ?"
                " AND NOT EXISTS (SELECT 1 FROM submissions s"
                "                 WHERE s.claim_id = c.id)",
                (user_ip, ts(now_utc())),
            ).fetchone()
        return int(row["n"])

    def has_conflicting_claim(
        self, field_id: int, claim_id: int, since: datetime
    ) -> bool:
        """True when the field was re-issued (a different claim minted) at or
        after `since` — the conflict test behind the late-submit rejection:
        results arriving on a lease that expired AND whose field went to
        another client are discarded; a late submit with no conflict is still
        accepted (legacy behavior for slow-but-honest clients)."""
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT 1 FROM claims WHERE field_id = ? AND id != ?"
                " AND claim_time >= ? LIMIT 1",
                (field_id, claim_id, ts(since)),
            ).fetchone()
        return row is not None

    # -- claims ------------------------------------------------------------

    def renew_claim(self, claim_id: int) -> datetime:
        """Re-arm the lease on the field behind an active claim (client
        heartbeat): bumps fields.last_claim_time to now so a long-running
        scan is not re-claimed out from under the client. claims.claim_time
        is untouched — submission elapsed accounting still measures from the
        original claim. Claims minted with an explicit lease window also get
        lease_expiry pushed out by the same window the claim was issued
        with. Raises KeyError on an unknown claim."""
        when = now_utc()
        claim = self.get_claim_by_id(claim_id)
        with self._lock, self._txn():
            self._conn.execute(
                "UPDATE fields SET last_claim_time = ? WHERE id = ?",
                (ts(when), claim.field_id),
            )
            if claim.lease_secs:
                self._conn.execute(
                    "UPDATE claims SET lease_expiry = ? WHERE id = ?",
                    (ts(when + timedelta(seconds=claim.lease_secs)), claim_id),
                )
        SERVER_CLAIM_RENEWALS.inc()
        return when

    def insert_claim(
        self,
        field_id: int,
        search_mode: SearchMode,
        user_ip: str,
        client_token: Optional[str] = None,
        lease_secs: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> ClaimRecord:
        when = now_utc()
        mode = "detailed" if search_mode == SearchMode.DETAILED else "niceonly"
        expiry = (
            when + timedelta(seconds=lease_secs) if lease_secs else None
        )
        with self._lock, self._txn():
            cur = self._conn.execute(
                "INSERT INTO claims (field_id, search_mode, claim_time,"
                " user_ip, client_token, lease_expiry, lease_secs, tenant)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
                (
                    field_id, mode, ts(when), user_ip, client_token,
                    None if expiry is None else ts(expiry), lease_secs, tenant,
                ),
            )
            claim_id = cur.lastrowid
        return ClaimRecord(
            claim_id=claim_id,
            field_id=field_id,
            search_mode=search_mode,
            claim_time=when,
            user_ip=user_ip,
            client_token=client_token,
            lease_expiry=expiry,
            lease_secs=lease_secs,
            tenant=tenant,
        )

    # -- block claim leases (one lease covering N fields; /claim_block) -----

    def insert_claims_block(
        self,
        field_ids: list[int],
        search_mode: SearchMode,
        user_ip: str,
        block_id: str,
        client_token: Optional[str] = None,
        lease_secs: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> list[ClaimRecord]:
        """Mint one claim row per field, all stamped with block_id, in one
        transaction. The per-field last_claim_time was already stamped by the
        claim engine, and renew_block re-arms every member together, so the
        whole block shares one lease lifecycle: it renews together and — via
        the ordinary expiry predicate — expires together."""
        when = now_utc()
        mode = "detailed" if search_mode == SearchMode.DETAILED else "niceonly"
        expiry = (
            when + timedelta(seconds=lease_secs) if lease_secs else None
        )
        out = []
        with self._lock, self._txn():
            for fid in field_ids:
                cur = self._conn.execute(
                    "INSERT INTO claims (field_id, search_mode, claim_time,"
                    " user_ip, block_id, client_token, lease_expiry,"
                    " lease_secs, tenant) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
                    (
                        fid, mode, ts(when), user_ip, block_id, client_token,
                        None if expiry is None else ts(expiry), lease_secs,
                        tenant,
                    ),
                )
                out.append(
                    ClaimRecord(
                        claim_id=cur.lastrowid,
                        field_id=fid,
                        search_mode=search_mode,
                        claim_time=when,
                        user_ip=user_ip,
                        client_token=client_token,
                        lease_expiry=expiry,
                        lease_secs=lease_secs,
                        tenant=tenant,
                    )
                )
        return out

    def _row_to_claim(self, row: sqlite3.Row) -> ClaimRecord:
        keys = row.keys()
        return ClaimRecord(
            claim_id=row["id"],
            field_id=row["field_id"],
            search_mode=SearchMode.DETAILED
            if row["search_mode"] == "detailed"
            else SearchMode.NICEONLY,
            claim_time=parse_ts(row["claim_time"]),
            user_ip=row["user_ip"],
            client_token=row["client_token"] if "client_token" in keys else None,
            lease_expiry=parse_ts(row["lease_expiry"])
            if "lease_expiry" in keys
            else None,
            lease_secs=row["lease_secs"] if "lease_secs" in keys else None,
            tenant=row["tenant"] if "tenant" in keys else None,
        )

    def get_block_claims(self, block_id: str) -> list[ClaimRecord]:
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT * FROM claims WHERE block_id = ? ORDER BY id ASC",
                (block_id,),
            ).fetchall()
        return [self._row_to_claim(r) for r in rows]

    def renew_block(self, block_id: str) -> tuple[datetime, int]:
        """Re-arm the lease on EVERY field behind a block claim (one client
        heartbeat covers the whole block). Returns (renewed_at, members)."""
        when = now_utc()
        with self._lock, self._txn():
            cur = self._conn.execute(
                "UPDATE fields SET last_claim_time = ? WHERE id IN"
                " (SELECT field_id FROM claims WHERE block_id = ?)",
                (ts(when), block_id),
            )
            count = cur.rowcount
            for r in self._conn.execute(
                "SELECT id, lease_secs FROM claims WHERE block_id = ?"
                " AND lease_secs IS NOT NULL",
                (block_id,),
            ).fetchall():
                self._conn.execute(
                    "UPDATE claims SET lease_expiry = ? WHERE id = ?",
                    (ts(when + timedelta(seconds=r["lease_secs"])), r["id"]),
                )
        if count:
            SERVER_CLAIM_RENEWALS.inc(count)
        return when, count

    def get_claim_by_id(self, claim_id: int) -> ClaimRecord:
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT * FROM claims WHERE id = ?", (claim_id,)
            ).fetchone()
        if row is None:
            raise KeyError(f"no claim {claim_id}")
        return self._row_to_claim(row)

    def tenant_rollup(self) -> list[dict]:
        """Per-(tenant, mode, base) claim/submission counts for /status and
        the fleet dashboard's tenant-occupancy strip. Submissions attribute
        through their claim; only rows minted under a named tenant appear.
        Grouping includes base so interleaved tenant submissions never
        conflate into one progress line (search_progress relies on this)."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT c.tenant AS tenant, c.search_mode AS mode,"
                " f.base_id AS base,"
                " COUNT(DISTINCT c.id) AS claims,"
                " COUNT(DISTINCT s.id) AS submissions"
                " FROM claims c"
                " JOIN fields f ON c.field_id = f.id"
                " LEFT JOIN submissions s ON s.claim_id = c.id"
                " WHERE c.tenant IS NOT NULL"
                " GROUP BY c.tenant, c.search_mode, f.base_id"
                " ORDER BY c.tenant ASC, f.base_id ASC",
            ).fetchall()
        return [
            {
                "tenant": r["tenant"],
                "mode": r["mode"],
                "base": r["base"],
                "claims": r["claims"],
                "submissions": r["submissions"],
            }
            for r in rows
        ]

    def get_submissions_by_tenant(self, tenant: str) -> list[SubmissionRecord]:
        """Every submission made under a tenant's claims, in field order —
        the per-tenant ledger sched_smoke diffs against its single-tenant
        oracle."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT s.*, c.tenant AS tenant FROM submissions s"
                " JOIN claims c ON s.claim_id = c.id WHERE c.tenant = ?"
                " ORDER BY s.field_id ASC, s.id ASC",
                (tenant,),
            ).fetchall()
        return [self._row_to_submission(r) for r in rows]

    # -- submissions -------------------------------------------------------

    def insert_submission(
        self,
        claim: ClaimRecord,
        username: str,
        client_version: str,
        user_ip: str,
        distribution: Optional[list[UniquesDistribution]],
        numbers: list[NiceNumber],
        elapsed_secs: float = 0.0,
        submit_id: Optional[str] = None,
        client_token: Optional[str] = None,
    ) -> int:
        """Insert one submission row. A duplicate submit_id raises
        sqlite3.IntegrityError (the partial unique index) — callers treat
        that as "already accepted", not as data loss."""
        when = now_utc()
        mode = "detailed" if claim.search_mode == SearchMode.DETAILED else "niceonly"
        with self._lock, self._txn():
            cur = self._conn.execute(
                "INSERT INTO submissions (claim_id, field_id, search_mode,"
                " submit_time, elapsed_secs, username, user_ip, client_version,"
                " disqualified, distribution, numbers, submit_id, client_token)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?, 0, ?, ?, ?, ?)",
                (
                    claim.claim_id,
                    claim.field_id,
                    mode,
                    ts(when),
                    elapsed_secs,
                    username,
                    user_ip,
                    client_version,
                    _dist_to_json(distribution),
                    _numbers_to_json(numbers),
                    submit_id,
                    client_token if client_token is not None else claim.client_token,
                ),
            )
            return cur.lastrowid

    def get_submission_by_submit_id(
        self, submit_id: str
    ) -> Optional[SubmissionRecord]:
        """The already-accepted submission carrying this idempotency key, if
        any (the exactly-once replay check)."""
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT * FROM submissions WHERE submit_id = ?", (submit_id,)
            ).fetchone()
        return None if row is None else self._row_to_submission(row)

    def _row_to_submission(self, row: sqlite3.Row) -> SubmissionRecord:
        keys = row.keys()
        return SubmissionRecord(
            submission_id=row["id"],
            claim_id=row["claim_id"],
            field_id=row["field_id"],
            search_mode=SearchMode.DETAILED
            if row["search_mode"] == "detailed"
            else SearchMode.NICEONLY,
            submit_time=parse_ts(row["submit_time"]),
            elapsed_secs=row["elapsed_secs"],
            username=row["username"],
            user_ip=row["user_ip"],
            client_version=row["client_version"],
            disqualified=bool(row["disqualified"]),
            distribution=_dist_from_json(row["distribution"]),
            numbers=_numbers_from_json(row["numbers"]),
            client_token=row["client_token"]
            if "client_token" in keys
            else None,
            # Populated only by queries that join claims and alias
            # c.tenant AS tenant; plain SELECT * rows leave it None.
            tenant=row["tenant"] if "tenant" in keys else None,
        )

    def get_submission_by_id(self, submission_id: int) -> SubmissionRecord:
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT * FROM submissions WHERE id = ?", (submission_id,)
            ).fetchone()
        if row is None:
            raise KeyError(f"no submission {submission_id}")
        return self._row_to_submission(row)

    def get_detailed_submissions_by_field(self, field_id: int) -> list[SubmissionRecord]:
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT s.*, c.tenant AS tenant FROM submissions s"
                " LEFT JOIN claims c ON s.claim_id = c.id"
                " WHERE s.field_id = ? AND s.search_mode = 'detailed'"
                " AND s.disqualified = 0 ORDER BY s.id ASC",
                (field_id,),
            ).fetchall()
        return [self._row_to_submission(r) for r in rows]

    def get_fields_with_detailed_submissions(self, base: int) -> list[FieldRecord]:
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT DISTINCT f.* FROM fields f JOIN submissions s"
                " ON f.id = s.field_id WHERE f.base_id = ? AND"
                " s.search_mode = 'detailed' ORDER BY f.id ASC",
                (base,),
            ).fetchall()
        return [self._row_to_field(r) for r in rows]

    # -- validation --------------------------------------------------------

    def get_validation_field(self, base: Optional[int] = None) -> ValidationData:
        """A random double-checked field plus its canonical results
        (reference db_util/fields.rs:611-679). base filters to one base —
        an extension the CLI's --base validation flag relies on."""
        max_id = self._max_field_id()
        if max_id == 0:
            raise KeyError("no fields")
        pivot = random.randint(1, max_id)
        base_pred = "" if base is None else " AND base_id = ?"
        base_args = [] if base is None else [base]
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT * FROM fields WHERE id >= ? AND check_level >= 2 AND"
                f" canon_submission_id IS NOT NULL{base_pred}"
                " ORDER BY id ASC LIMIT 1",
                [pivot, *base_args],
            ).fetchone()
            if row is None:
                row = conn.execute(
                    "SELECT * FROM fields WHERE check_level >= 2 AND"
                    f" canon_submission_id IS NOT NULL{base_pred}"
                    " ORDER BY id ASC LIMIT 1",
                    base_args,
                ).fetchone()
        if row is None:
            raise KeyError("no double-checked field with canonical submission")
        field = self._row_to_field(row)
        sub = self.get_submission_by_id(field.canon_submission_id)
        if sub.distribution is None:
            raise ValueError("canonical submission has no distribution")
        from nice_tpu.core import distribution_stats, number_stats

        return ValidationData(
            base=field.base,
            field_id=field.field_id,
            range_start=field.range_start,
            range_end=field.range_end,
            range_size=field.range_size,
            unique_distribution=distribution_stats.shrink_distribution(
                sub.distribution
            ),
            nice_numbers=number_stats.shrink_numbers(sub.numbers),
        )

    # -- analytics updates (jobs) -----------------------------------------

    def update_chunk_stats(self, chunk_id: int, **cols) -> None:
        self._update_stats_row("chunks", chunk_id, cols)

    def update_base_stats(self, base: int, **cols) -> None:
        self._update_stats_row("bases", base, cols)

    def _update_stats_row(self, table: str, row_id: int, cols: dict) -> None:
        sets, params = [], []
        for key, val in cols.items():
            sets.append(f"{key} = ?")
            params.append(val)
        params.append(row_id)
        with self._lock, self._txn():
            self._conn.execute(
                f"UPDATE {table} SET {', '.join(sets)} WHERE id = ?", params
            )

    def get_chunks_in_base(self, base: int) -> list[sqlite3.Row]:
        with self._read_conn() as conn:
            return conn.execute(
                "SELECT * FROM chunks WHERE base_id = ? ORDER BY id ASC", (base,)
            ).fetchall()

    # -- public ad-hoc query surface (the reference exposes its DB through
    # PostgREST with a read-only web_anon role, schema/schema.sql:82-87 —
    # third parties can run arbitrary SELECTs; this is the SQLite analog) ---

    # Tables third parties may read. claims/submissions are included (the
    # reference grants web_anon the whole public schema) but their user_ip
    # column reads as NULL via the authorizer's SQLITE_IGNORE.
    PUBLIC_QUERY_TABLES = frozenset(
        {
            "bases",
            "chunks",
            "fields",
            "claims",
            "submissions",
            "cache_search_rate_daily",
            "cache_search_leaderboard",
            "sqlite_master",  # lets clients discover the schema, like
            # PostgREST's OpenAPI root
        }
    )
    PUBLIC_QUERY_MAX_ROWS = 1000
    PUBLIC_QUERY_MAX_VM_STEPS = 50_000_000  # aborts runaway scans (~100 ms)
    PUBLIC_QUERY_MAX_LENGTH = 1 << 20  # 1 MiB cap on any string/blob value

    @staticmethod
    def _public_value(v):
        """Coerce one result cell to something json.dumps can emit. sqlite
        can synthesize values JSON has no spelling for (zeroblob() bytes,
        nan/inf floats); returning a tagged repr beats a 500."""
        if v is None or isinstance(v, (int, str)):
            return v
        if isinstance(v, float):
            return v if math.isfinite(v) else repr(v)
        if isinstance(v, (bytes, bytearray, memoryview)):
            b = bytes(v)
            return {"blob_base64": base64.b64encode(b).decode("ascii")}
        return repr(v)

    def public_query(self, sql: str, params: tuple = ()) -> dict:
        """Run one read-only SELECT with third-party privileges.

        Defense in depth, mirroring web_anon's capabilities: a fresh
        read-only (mode=ro) connection with PRAGMA query_only, an authorizer
        that allows SELECT over PUBLIC_QUERY_TABLES only (user_ip columns
        read as NULL), a VM-step budget against runaway scans, and a row cap.
        Raises sqlite3 errors for invalid/unauthorized SQL (mapped to 400 by
        the API layer).
        """
        # client_token is a bearer credential (trust identity): like user_ip
        # it reads as NULL for third parties. client_trust itself stays out
        # of PUBLIC_QUERY_TABLES entirely.
        deny_cols = {"user_ip", "client_token"}

        def authorize(action, arg1, arg2, dbname, trigger):
            if action == sqlite3.SQLITE_SELECT:
                return sqlite3.SQLITE_OK
            if action == sqlite3.SQLITE_READ:
                if arg1 in self.PUBLIC_QUERY_TABLES:
                    if arg2 in deny_cols:
                        return sqlite3.SQLITE_IGNORE  # reads as NULL
                    return sqlite3.SQLITE_OK
                return sqlite3.SQLITE_DENY
            if action == sqlite3.SQLITE_FUNCTION:
                return sqlite3.SQLITE_OK  # query_only blocks side effects
            return sqlite3.SQLITE_DENY

        conn = sqlite3.connect(
            f"file:{self.path}?mode=ro", uri=True, isolation_level=None
        )
        try:
            if hasattr(conn, "setlimit"):  # Python 3.11+
                # Caps any single string/blob the VM materializes — closes
                # the zeroblob(1e9) memory-amplification hole (oversized
                # values raise SQLITE_TOOBIG -> DataError -> 400).
                conn.setlimit(
                    sqlite3.SQLITE_LIMIT_LENGTH, self.PUBLIC_QUERY_MAX_LENGTH
                )
            conn.execute("PRAGMA query_only=1")
            conn.execute("PRAGMA busy_timeout=2000")
            # First callback fires after MAX_VM_STEPS instructions; returning
            # nonzero aborts the statement with SQLITE_INTERRUPT.
            conn.set_progress_handler(
                lambda: 1, self.PUBLIC_QUERY_MAX_VM_STEPS
            )
            conn.set_authorizer(authorize)
            cur = conn.execute(sql, params)
            columns = [d[0] for d in cur.description] if cur.description else []
            rows = cur.fetchmany(self.PUBLIC_QUERY_MAX_ROWS)
            truncated = cur.fetchone() is not None
            return {
                "columns": columns,
                "rows": [[self._public_value(v) for v in r] for r in rows],
                "truncated": truncated,
            }
        finally:
            conn.close()

    # -- fleet telemetry (client_telemetry table; /telemetry heartbeat and
    # submission piggyback feed it, the /status fleet block reads it) -------

    def upsert_client_telemetry(self, snap: dict, user_ip: str = "") -> None:
        """Persist one client's snapshot (obs.telemetry wire format), keyed
        by its process-stable client_id. Later reports win; first_seen is
        preserved across updates."""
        client_id = str(snap.get("client_id") or "")[:256]
        if not client_id:
            raise ValueError("telemetry snapshot missing client_id")

        def _i(key):
            try:
                return int(snap.get(key, 0) or 0)
            except (TypeError, ValueError):
                return 0

        fields = snap.get("fields") or {}
        if not isinstance(fields, dict):
            fields = {}
        try:
            rate = float(snap.get("numbers_per_sec", 0.0) or 0.0)
        except (TypeError, ValueError):
            rate = 0.0
        when = ts(now_utc())
        row = (
            client_id,
            str(snap.get("username") or "")[:256],
            user_ip,
            str(snap.get("client_version") or "")[:64],
            str(snap.get("backend") or "")[:32],
            when,
            when,
            int(fields.get("detailed", 0) or 0),
            int(fields.get("niceonly", 0) or 0),
            pad(max(0, _i("numbers"))),
            rate,
            _i("downgrades_total"),
            _i("restores"),
            _i("faults"),
            _i("spool_depth"),
            json.dumps(snap)[: 64 * 1024],
        )
        with self._lock, self._txn():
            self._conn.execute(
                "INSERT INTO client_telemetry (client_id, username, user_ip,"
                " client_version, backend, first_seen, last_seen,"
                " fields_detailed, fields_niceonly, numbers_total,"
                " numbers_per_sec, downgrades, restores, faults, spool_depth,"
                " snapshot)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)"
                " ON CONFLICT(client_id) DO UPDATE SET"
                " username = excluded.username,"
                " user_ip = excluded.user_ip,"
                " client_version = excluded.client_version,"
                " backend = excluded.backend,"
                " last_seen = excluded.last_seen,"
                " fields_detailed = excluded.fields_detailed,"
                " fields_niceonly = excluded.fields_niceonly,"
                " numbers_total = excluded.numbers_total,"
                " numbers_per_sec = excluded.numbers_per_sec,"
                " downgrades = excluded.downgrades,"
                " restores = excluded.restores,"
                " faults = excluded.faults,"
                " spool_depth = excluded.spool_depth,"
                " snapshot = excluded.snapshot",
                row,
            )

    def get_client_telemetry(self, active_secs: float = 900.0) -> list[dict]:
        """Per-client rows whose last report is fresher than active_secs,
        newest first (the fleet dashboard's client table)."""
        cutoff = ts(now_utc() - timedelta(seconds=active_secs))
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT * FROM client_telemetry WHERE last_seen >= ?"
                " ORDER BY last_seen DESC",
                (cutoff,),
            ).fetchall()
        out = []
        for r in rows:
            # Mesh stats ride only in the JSON snapshot column (no schema
            # migration for a sub-dict that older clients never send).
            mesh = {}
            try:
                snap = json.loads(r["snapshot"] or "{}")
                if isinstance(snap.get("mesh"), dict):
                    mesh = snap["mesh"]
            except (ValueError, TypeError):
                pass

            def _mi(key):
                try:
                    return int(mesh.get(key, 0) or 0)
                except (TypeError, ValueError):
                    return 0

            def _mf(m):
                try:
                    return float(m.get("feed_idle_sum", 0.0) or 0.0)
                except (TypeError, ValueError):
                    return 0.0

            out.append(
                {
                    "client_id": r["client_id"],
                    "username": r["username"],
                    "user_ip": r["user_ip"],
                    "client_version": r["client_version"],
                    "backend": r["backend"],
                    "first_seen": r["first_seen"],
                    "last_seen": r["last_seen"],
                    "fields_detailed": r["fields_detailed"],
                    "fields_niceonly": r["fields_niceonly"],
                    "numbers_total": str(unpad(r["numbers_total"])),
                    "numbers_per_sec": r["numbers_per_sec"],
                    "downgrades": r["downgrades"],
                    "restores": r["restores"],
                    "faults": r["faults"],
                    "spool_depth": r["spool_depth"],
                    "mesh_devices": _mi("devices"),
                    "mesh_reshards": _mi("reshards"),
                    "mesh_feed_idle_sum": _mf(mesh),
                    "mesh_feed_idle_count": _mi("feed_idle_count"),
                }
            )
        return out

    def get_client_resource_snapshots(
        self, active_secs: float = 900.0
    ) -> list[dict]:
        """client_id + the resource-observatory payloads (pyprof rollup,
        memwatch watermarks) parsed out of each active client's latest
        snapshot. Clients running with both knobs at 0 send neither key and
        are skipped."""
        cutoff = ts(now_utc() - timedelta(seconds=active_secs))
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT client_id, snapshot FROM client_telemetry"
                " WHERE last_seen >= ? ORDER BY last_seen DESC",
                (cutoff,),
            ).fetchall()
        out = []
        for r in rows:
            try:
                snap = json.loads(r["snapshot"] or "{}")
            except (ValueError, TypeError):
                continue
            entry: dict = {"client_id": r["client_id"]}
            if isinstance(snap.get("pyprof"), dict):
                entry["pyprof"] = snap["pyprof"]
            if isinstance(snap.get("mem"), dict):
                entry["mem"] = snap["mem"]
            if len(entry) > 1:
                out.append(entry)
        return out

    def get_fleet_claim_stats(self, slowest_limit: int = 10) -> dict:
        """Claim-side fleet health: active leases, expired-but-unsubmitted
        claims (lost work the expiry predicate will hand out again), total
        submissions, and the longest-running in-flight claims."""
        cutoff = self.claim_expiry_cutoff()
        now = now_utc()
        with self._read_conn() as conn:
            active = conn.execute(
                "SELECT COUNT(*) FROM fields WHERE last_claim_time >= ?",
                (ts(cutoff),),
            ).fetchone()[0]
            expired = conn.execute(
                "SELECT COUNT(*) FROM claims c"
                " LEFT JOIN submissions s ON s.claim_id = c.id"
                " WHERE s.id IS NULL AND c.claim_time < ?",
                (ts(cutoff),),
            ).fetchone()[0]
            submissions = conn.execute(
                "SELECT COUNT(*) FROM submissions"
            ).fetchone()[0]
            slow_rows = conn.execute(
                "SELECT c.id AS claim_id, f.base_id AS base, c.claim_time,"
                " c.search_mode, c.user_ip"
                " FROM claims c JOIN fields f ON f.id = c.field_id"
                " LEFT JOIN submissions s ON s.claim_id = c.id"
                " WHERE s.id IS NULL AND f.last_claim_time >= ?"
                " ORDER BY c.claim_time ASC LIMIT ?",
                (ts(cutoff), slowest_limit),
            ).fetchall()
        slowest = [
            {
                "claim_id": r["claim_id"],
                "base": r["base"],
                "mode": r["search_mode"],
                "user_ip": r["user_ip"],
                "in_flight_secs": round(
                    max(
                        0.0,
                        (now - parse_ts(r["claim_time"])).total_seconds(),
                    ),
                    1,
                ),
            }
            for r in slow_rows
        ]
        return {
            "claims_active": active,
            "claims_expired_unsubmitted": expired,
            "submissions_total": submissions,
            "slowest_in_flight": slowest,
        }

    # -- performance-observatory history (obs/history.py) ------------------
    # Durable mirror of the in-memory ring store, written in batches by the
    # writer actor's history periodic. /history reads stay in-memory; these
    # tables exist for post-restart analysis and ROADMAP item 5's
    # incremental analytics.

    def insert_metric_history(self, rows: list[tuple]) -> int:
        """Batch-persist (series, tier, ts, value, vmin, vmax, n) rows in
        one transaction. INSERT OR REPLACE: a re-sampled bucket (in-progress
        coarse tier finalized later) updates in place."""
        if not rows:
            return 0
        packed = [
            (str(s)[:512], str(t), float(at), float(v), float(mn),
             float(mx), int(n))
            for s, t, at, v, mn, mx, n in rows
        ]
        with self._lock, self._txn():
            self._conn.executemany(
                "INSERT OR REPLACE INTO metric_history"
                " (series, tier, ts, value, vmin, vmax, n)"
                " VALUES (?, ?, ?, ?, ?, ?, ?)",
                packed,
            )
        return len(packed)

    def get_metric_history(
        self,
        series: str,
        since: float = 0.0,
        tier: Optional[str] = None,
        limit: int = 5000,
    ) -> list[dict]:
        """Persisted points for one series, ascending by time."""
        sql = (
            "SELECT series, tier, ts, value, vmin, vmax, n"
            " FROM metric_history WHERE series = ? AND ts >= ?"
        )
        params: list = [str(series), float(since)]
        if tier is not None:
            sql += " AND tier = ?"
            params.append(str(tier))
        sql += " ORDER BY ts ASC LIMIT ?"
        params.append(int(limit))
        with self._read_conn() as conn:
            rows = conn.execute(sql, params).fetchall()
        return [
            {
                "series": r["series"], "tier": r["tier"],
                "ts": float(r["ts"]), "value": float(r["value"]),
                "vmin": float(r["vmin"]), "vmax": float(r["vmax"]),
                "n": int(r["n"]),
            }
            for r in rows
        ]

    def get_metric_history_series(self) -> list[str]:
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT DISTINCT series FROM metric_history ORDER BY series"
            ).fetchall()
        return [r["series"] for r in rows]

    def prune_metric_history(self, cutoff_ts: float) -> int:
        """Drop points older than cutoff (retention sweep; returns rows
        deleted)."""
        with self._lock, self._txn():
            cur = self._conn.execute(
                "DELETE FROM metric_history WHERE ts < ?",
                (float(cutoff_ts),),
            )
            return cur.rowcount

    # -- field lifecycle audit journal ------------------------------------
    # Append-only event rows written through the writer actor (or inside an
    # existing write transaction: _txn nests as a savepoint, so emission
    # sites inside claim/submit ops commit atomically with the state change
    # they describe). Row shape comes from obs/journal.py:event_row.

    def append_field_events(self, rows: list[dict]) -> list[dict]:
        """Append journal events; assigns each row the next per-field seq.

        The per-field MAX(seq)+1 read is race-free because every write path
        runs under self._lock (single-writer actor); rows for the same field
        within one batch sequence correctly because each insert lands before
        the next row's MAX runs.

        Returns the rows enriched with their assigned global ``id``, per-
        field ``seq``, and effective ``ts`` — the exact wire shape the
        /events feed serves — so the caller can stage them for the stream
        plane's post-commit publish without re-reading the table. Note the
        ids are NOT durable until the enclosing batch commits (this runs
        as a savepoint under the writer actor): staging must wait for the
        on_batch_end(committed=True) signal before publishing."""
        if not rows:
            return []
        enriched: list[dict] = []
        with self._lock, self._txn():
            for row in rows:
                fid = int(row["field_id"])
                seq = self._conn.execute(
                    "SELECT COALESCE(MAX(seq), 0) + 1 FROM field_events"
                    " WHERE field_id = ?",
                    (fid,),
                ).fetchone()[0]
                at = row.get("ts") or ts(now_utc())
                cur = self._conn.execute(
                    "INSERT INTO field_events (field_id, seq, ts, kind,"
                    " trace_id, client, tier, check_level, detail)"
                    " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
                    (
                        fid,
                        seq,
                        at,
                        str(row["kind"]),
                        row.get("trace_id"),
                        row.get("client"),
                        row.get("tier"),
                        row.get("check_level"),
                        json.dumps(row.get("detail") or {}, sort_keys=True),
                    ),
                )
                enriched.append(
                    {
                        "id": int(cur.lastrowid),
                        "field_id": fid,
                        "seq": int(seq),
                        "ts": at,
                        "kind": str(row["kind"]),
                        "trace_id": row.get("trace_id"),
                        "client": row.get("client"),
                        "tier": row.get("tier"),
                        "check_level": row.get("check_level"),
                        "detail": dict(row.get("detail") or {}),
                    }
                )
        for row in rows:
            SERVER_JOURNAL_EVENTS.labels(str(row["kind"])).inc()
        return enriched

    @staticmethod
    def _event_row_to_dict(r) -> dict:
        try:
            detail = json.loads(r["detail"] or "{}")
        except (ValueError, TypeError):
            detail = {}
        return {
            "id": int(r["id"]),
            "field_id": int(r["field_id"]),
            "seq": int(r["seq"]),
            "ts": r["ts"],
            "kind": r["kind"],
            "trace_id": r["trace_id"],
            "client": r["client"],
            "tier": r["tier"],
            "check_level": r["check_level"],
            "detail": detail,
        }

    def get_field_timeline(self, field_id: int) -> list[dict]:
        """One field's full journal, causally ordered by per-field seq."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT * FROM field_events WHERE field_id = ?"
                " ORDER BY seq ASC",
                (int(field_id),),
            ).fetchall()
        return [self._event_row_to_dict(r) for r in rows]

    def get_events_since(self, since_id: int = 0, limit: int = 500) -> list[dict]:
        """Cursor-paginated global feed: events with id > since_id, ascending
        (pass the last row's id back as the next cursor)."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT * FROM field_events WHERE id > ?"
                " ORDER BY id ASC LIMIT ?",
                (int(since_id), int(limit)),
            ).fetchall()
        return [self._event_row_to_dict(r) for r in rows]

    def get_recent_canon_fields(self, limit: int = 200) -> list[int]:
        """Field ids of the most recent canon promotions, newest first —
        the critical-path engine's rolling attribution window."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT field_id, MAX(id) AS latest FROM field_events"
                " WHERE kind = 'canon_promoted'"
                " GROUP BY field_id ORDER BY latest DESC LIMIT ?",
                (int(limit),),
            ).fetchall()
        return [int(r["field_id"]) for r in rows]

    def get_fleet_phase_totals(self, active_secs: float = 900.0) -> dict:
        """Sum of active clients' cumulative stepprof phase breakdowns
        ({phase: secs, "wall": secs, "fields": n}), read out of the
        client_telemetry snapshot JSON (phase_breakdown rides only there —
        no schema column for a dict older clients never send). Feeds the
        critical-path USE rollup's device-busy / feed-idle fractions."""
        cutoff = ts(now_utc() - timedelta(seconds=active_secs))
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT snapshot FROM client_telemetry WHERE last_seen >= ?",
                (cutoff,),
            ).fetchall()
        totals: dict[str, float] = {}
        for r in rows:
            try:
                snap = json.loads(r["snapshot"] or "{}")
            except (ValueError, TypeError):
                continue
            pb = snap.get("phase_breakdown")
            if not isinstance(pb, dict):
                continue
            for entry in pb.values():
                if not isinstance(entry, dict):
                    continue
                for k, v in entry.items():
                    try:
                        totals[k] = totals.get(k, 0.0) + float(v or 0.0)
                    except (TypeError, ValueError):
                        continue
        return totals

    def count_field_events(self, kinds: tuple, since_iso: str) -> int:
        """How many journal events of the given kinds landed since the ISO
        timestamp (anomaly-detector window counts)."""
        if not kinds:
            return 0
        marks = ",".join("?" for _ in kinds)
        with self._read_conn() as conn:
            row = conn.execute(
                f"SELECT COUNT(*) FROM field_events"
                f" WHERE kind IN ({marks}) AND ts >= ?",
                (*[str(k) for k in kinds], str(since_iso)),
            ).fetchone()
        return int(row[0])

    def count_stuck_fields(self, min_claims: int, since_iso: str) -> int:
        """Fields claimed >= min_claims times inside the window that have
        never reached canon (no canon_promoted event on their timeline)."""
        with self._read_conn() as conn:
            row = conn.execute(
                """
                SELECT COUNT(*) FROM (
                  SELECT field_id, COUNT(*) AS n FROM field_events
                  WHERE kind IN ('claimed', 'block_claimed') AND ts >= ?
                  GROUP BY field_id HAVING n >= ?
                ) g
                WHERE NOT EXISTS (
                  SELECT 1 FROM field_events e
                  WHERE e.field_id = g.field_id
                    AND e.kind = 'canon_promoted')
                """,
                (str(since_iso), int(min_claims)),
            ).fetchone()
        return int(row[0])

    def prune_field_events(self, cutoff_iso: str) -> int:
        """Retention sweep: drop journal rows older than the ISO cutoff
        (lexicographic comparison == time order for our fixed format)."""
        with self._lock, self._txn():
            cur = self._conn.execute(
                "DELETE FROM field_events WHERE ts < ?",
                (str(cutoff_iso),),
            )
            pruned = cur.rowcount
        if pruned:
            SERVER_JOURNAL_PRUNED.inc(pruned)
        return pruned

    def get_recent_field_elapsed(self, limit: int = 200) -> list[float]:
        """elapsed_secs of the most recent submissions (for the fleet p50/p95
        field-latency gauges)."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT elapsed_secs FROM submissions ORDER BY id DESC"
                " LIMIT ?",
                (int(limit),),
            ).fetchall()
        return [float(r["elapsed_secs"]) for r in rows]

    # -- analytics (dashboard REST surface; reference serves these via
    # PostgREST views over the same tables, web/index.html:203-276) ---------

    def get_base_stats(self) -> list[dict]:
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT * FROM bases ORDER BY id ASC"
            ).fetchall()
        out = []
        for r in rows:
            out.append(
                {
                    "base": r["id"],
                    "range_start": str(unpad(r["range_start"])),
                    "range_end": str(unpad(r["range_end"])),
                    "range_size": str(unpad(r["range_size"])),
                    "checked_detailed": str(unpad(r["checked_detailed"])),
                    "checked_niceonly": str(unpad(r["checked_niceonly"])),
                    "minimum_cl": r["minimum_cl"],
                    "niceness_mean": r["niceness_mean"],
                    "niceness_stdev": r["niceness_stdev"],
                    "distribution": json.loads(r["distribution"]),
                    "numbers": json.loads(r["numbers"]),
                }
            )
        return out

    def get_leaderboard(self, search_mode: Optional[str] = None) -> list[dict]:
        """All-time numbers-searched per (search_mode, username) — the
        reference's cache_search_leaderboard shape (schema.sql:121-131)."""
        q = "SELECT * FROM cache_search_leaderboard"
        args: list = []
        if search_mode:
            q += " WHERE search_mode = ?"
            args.append(search_mode)
        with self._read_conn() as conn:
            rows = conn.execute(q, args).fetchall()
        out = [
            {
                "search_mode": r["search_mode"],
                "username": r["username"],
                "total_range": str(unpad(r["total_range"])),
                "submissions": r["submissions"],
                "last_submission": r["last_submission"],
            }
            for r in rows
        ]
        out.sort(key=lambda r: int(r["total_range"]), reverse=True)
        return out

    def get_search_rate(self, search_mode: Optional[str] = None) -> list[dict]:
        """Daily numbers-searched per (date, search_mode, username) over the
        cache window — the reference's cache_search_rate_daily shape."""
        q = "SELECT * FROM cache_search_rate_daily"
        args: list = []
        if search_mode:
            q += " WHERE search_mode = ?"
            args.append(search_mode)
        q += " ORDER BY date ASC, search_mode ASC, username ASC"
        with self._read_conn() as conn:
            rows = conn.execute(q, args).fetchall()
        return [
            {
                "date": r["date"],
                "search_mode": r["search_mode"],
                "username": r["username"],
                "total_range": str(unpad(r["total_range"])),
            }
            for r in rows
        ]

    # -- caches ------------------------------------------------------------

    CACHE_RATE_WINDOW_DAYS = 90

    def refresh_search_caches(self) -> None:
        """Rebuild the per-user/per-mode numbers-searched caches (reference
        db_util/cache.rs:3-40): daily totals over a 90-day window and the
        all-time leaderboard.

        One pass over a single submissions-join-fields query; the aggregation
        runs in Python because range sizes are padded u128 TEXT (SQLite's
        integer SUM is i64 and would overflow on hi-base fields — the
        reference leans on Postgres DECIMAL here)."""
        from datetime import timedelta

        cutoff = ts(now_utc() - timedelta(days=self.CACHE_RATE_WINDOW_DAYS))[:10]
        with self._lock, self._txn():
            rows = self._conn.execute(
                "SELECT s.search_mode, s.username, s.submit_time, f.range_size"
                " FROM submissions s JOIN fields f ON s.field_id = f.id"
                " WHERE s.disqualified = 0"
            ).fetchall()
            daily: dict[tuple, int] = {}
            alltime: dict[tuple, list] = {}  # -> [total, subs, last]
            for r in rows:
                size = unpad(r["range_size"])
                date = r["submit_time"][:10]
                key = (r["search_mode"], r["username"])
                if date >= cutoff:
                    dkey = (date, *key)
                    daily[dkey] = daily.get(dkey, 0) + size
                entry = alltime.setdefault(key, [0, 0, ""])
                entry[0] += size
                entry[1] += 1
                entry[2] = max(entry[2], r["submit_time"])
            self._conn.execute("DELETE FROM cache_search_rate_daily")
            self._conn.executemany(
                "INSERT INTO cache_search_rate_daily"
                " (date, search_mode, username, total_range) VALUES (?, ?, ?, ?)",
                [(d, m, u, pad(t)) for (d, m, u), t in daily.items()],
            )
            self._conn.execute("DELETE FROM cache_search_leaderboard")
            self._conn.executemany(
                "INSERT INTO cache_search_leaderboard"
                " (search_mode, username, total_range, submissions,"
                " last_submission) VALUES (?, ?, ?, ?, ?)",
                [
                    (m, u, pad(t), subs, last)
                    for (m, u), (t, subs, last) in alltime.items()
                ],
            )

    # -- disqualification --------------------------------------------------

    def disqualify_submission(self, submission_id: int) -> int:
        """Mark one submission disqualified. Returns rows changed. The next
        consensus pass recomputes canon without it (consensus and the caches
        both filter disqualified = 0)."""
        with self._lock, self._txn():
            cur = self._conn.execute(
                "UPDATE submissions SET disqualified = 1 WHERE id = ?",
                (submission_id,),
            )
            return cur.rowcount

    def disqualify_user(self, username: str) -> int:
        """Disqualify every submission by a user (the reference's abuse
        story: disqualification removes a user's results from consensus and
        the leaderboard without deleting the audit trail)."""
        with self._lock, self._txn():
            cur = self._conn.execute(
                "UPDATE submissions SET disqualified = 1 WHERE username = ?",
                (username,),
            )
            return cur.rowcount

    def requeue_disqualified_fields(
        self,
        submission_ids: Optional[list[int]] = None,
        username: Optional[str] = None,
    ) -> int:
        """Reset fields stranded by disqualification so the claim strategies
        pick them back up: for every field touched by the named disqualified
        submissions (or all of a user's), if its canon submission is gone or
        disqualified, clear canon, drop check_level to 1 when a live detailed
        submission remains (else 0), and release the lease. Returns fields
        requeued."""
        if submission_ids is None and username is None:
            return 0
        with self._lock, self._txn():
            if username is not None:
                rows = self._conn.execute(
                    "SELECT DISTINCT field_id FROM submissions"
                    " WHERE username = ? AND disqualified = 1",
                    (username,),
                ).fetchall()
            else:
                if not submission_ids:
                    return 0
                marks = ",".join("?" * len(submission_ids))
                rows = self._conn.execute(
                    f"SELECT DISTINCT field_id FROM submissions"
                    f" WHERE id IN ({marks}) AND disqualified = 1",
                    submission_ids,
                ).fetchall()
            requeued = 0
            for r in rows:
                fid = r["field_id"]
                field = self._conn.execute(
                    "SELECT canon_submission_id, check_level FROM fields"
                    " WHERE id = ?",
                    (fid,),
                ).fetchone()
                if field is None:
                    continue
                canon = field["canon_submission_id"]
                if canon is not None:
                    live = self._conn.execute(
                        "SELECT 1 FROM submissions WHERE id = ?"
                        " AND disqualified = 0",
                        (canon,),
                    ).fetchone()
                    if live is not None:
                        continue  # canon survives; nothing to requeue
                remaining = self._conn.execute(
                    "SELECT 1 FROM submissions WHERE field_id = ?"
                    " AND search_mode = 'detailed' AND disqualified = 0"
                    " LIMIT 1",
                    (fid,),
                ).fetchone()
                new_cl = 1 if remaining is not None else 0
                self._conn.execute(
                    "UPDATE fields SET canon_submission_id = NULL,"
                    " check_level = ?, last_claim_time = NULL WHERE id = ?",
                    (new_cl, fid),
                )
                requeued += 1
            return requeued

    # -- client trust ledger (server/trust.py reads through a cache;
    # mutations run through the writer actor) ------------------------------

    def get_client_trust(self, client_token: str) -> Optional[dict]:
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT * FROM client_trust WHERE client_token = ?",
                (client_token,),
            ).fetchone()
        return None if row is None else dict(row)

    def upsert_client_trust(
        self,
        client_token: str,
        trust_delta: float = 0.0,
        accepted_delta: int = 0,
        passed_delta: int = 0,
        failed_delta: int = 0,
        slash: bool = False,
        suspect: Optional[bool] = None,
    ) -> dict:
        """The ONE trust write on the hot accept path: accumulate counters
        and the trust delta in a single upsert (first_seen preserved, the
        upsert_client_telemetry idiom). slash=True zeroes the score instead
        of adding the delta. Returns the updated row."""
        when = ts(now_utc())
        with self._lock, self._txn():
            self._conn.execute(
                "INSERT INTO client_trust (client_token, trust,"
                " submissions_accepted, spot_checks_passed,"
                " spot_checks_failed, suspect, first_seen, last_seen)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?)"
                " ON CONFLICT(client_token) DO UPDATE SET"
                " trust = CASE WHEN ? THEN 0 ELSE trust + ? END,"
                " submissions_accepted = submissions_accepted + ?,"
                " spot_checks_passed = spot_checks_passed + ?,"
                " spot_checks_failed = spot_checks_failed + ?,"
                " suspect = COALESCE(?, suspect),"
                " last_seen = excluded.last_seen",
                (
                    client_token,
                    0.0 if slash else trust_delta,
                    accepted_delta,
                    passed_delta,
                    failed_delta,
                    1 if suspect else 0,
                    when,
                    when,
                    slash,
                    trust_delta,
                    accepted_delta,
                    passed_delta,
                    failed_delta,
                    None if suspect is None else (1 if suspect else 0),
                ),
            )
            row = self._conn.execute(
                "SELECT * FROM client_trust WHERE client_token = ?",
                (client_token,),
            ).fetchone()
        return dict(row)

    def get_trust_summary(self, threshold: float) -> dict:
        """Tier counts for the fleet block / nice_server_trust_clients."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT trust, suspect FROM client_trust"
            ).fetchall()
        tiers = {"trusted": 0, "untrusted": 0, "suspect": 0}
        for r in rows:
            if r["suspect"]:
                tiers["suspect"] += 1
            elif threshold > 0 and r["trust"] < threshold:
                tiers["untrusted"] += 1
            else:
                tiers["trusted"] += 1
        return tiers

    # -- replication (nice_tpu/server/repl.py) -----------------------------
    # Physical row-level replication: AFTER INSERT/UPDATE/DELETE triggers on
    # every replicated table append (seq, epoch, tbl, op, rowid, row-JSON)
    # to repl_ops INSIDE the mutating transaction — the op log commits
    # atomically with the change, so a crash-consistent snapshot is always
    # gap-free. Standbys pull ops over HTTP (?since=seq resume) and apply
    # them with capture OFF so replays are not re-logged.

    # Tables whose rows replicate. repl_meta / repl_ops themselves never do
    # (each replica owns its identity and log); sqlite_sequence is derived.
    REPL_TABLES = (
        "bases",
        "chunks",
        "fields",
        "claims",
        "submissions",
        "cache_search_rate_daily",
        "cache_search_leaderboard",
        "client_telemetry",
        "metric_history",
        "field_events",
        "client_trust",
    )

    def _init_repl(self) -> None:
        """Seed repl_meta defaults and (re)generate the capture triggers.
        Runs with self._lock held, at the tail of init_schema — AFTER the
        Python column migrations, so the json_object() row image always
        covers the live column set. INSERT OR IGNORE keeps a promoted
        standby's persisted role/epoch across restarts."""
        conn = self._conn
        conn.executemany(
            "INSERT OR IGNORE INTO repl_meta (key, value) VALUES (?, ?)",
            [
                ("epoch", "1"),
                ("role", "primary"),
                ("capture", "1"),
                ("fenced", "0"),
                ("last_applied_seq", "0"),
            ],
        )
        for tbl in self.REPL_TABLES:
            cols = [
                r["name"]
                for r in conn.execute(f"PRAGMA table_info({tbl})").fetchall()
            ]
            if not cols:
                continue
            for suffix, verb, ref in (
                ("i", "INSERT", "NEW"),
                ("u", "UPDATE", "NEW"),
                ("d", "DELETE", "OLD"),
            ):
                name = f"repl_{tbl}_{suffix}"
                conn.execute(f"DROP TRIGGER IF EXISTS {name}")
                if suffix == "d":
                    row_expr = "NULL"
                else:
                    pairs = ", ".join(f"'{c}', {ref}.{c}" for c in cols)
                    row_expr = f"json_object({pairs})"
                conn.execute(
                    f"CREATE TRIGGER {name} AFTER {verb} ON {tbl}"
                    " WHEN (SELECT value FROM repl_meta WHERE key='capture')"
                    "      = '1'"
                    " BEGIN"
                    "   INSERT INTO repl_ops (epoch, tbl, op, rid, row)"
                    "   VALUES ((SELECT CAST(value AS INTEGER) FROM repl_meta"
                    "            WHERE key='epoch'),"
                    f"          '{tbl}', '{suffix.upper()}', {ref}.rowid,"
                    f"          {row_expr});"
                    " END"
                )

    def repl_meta_get(self, key: str, default: str = "") -> str:
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT value FROM repl_meta WHERE key = ?", (key,)
            ).fetchone()
        return default if row is None else str(row[0])

    def repl_meta_set(self, key: str, value: str) -> None:
        with self._lock, self._txn():
            self._conn.execute(
                "INSERT INTO repl_meta (key, value) VALUES (?, ?)"
                " ON CONFLICT(key) DO UPDATE SET value = excluded.value",
                (key, str(value)),
            )

    def repl_epoch(self) -> int:
        return int(self.repl_meta_get("epoch", "1"))

    def repl_role(self) -> str:
        return self.repl_meta_get("role", "primary")

    def repl_fenced(self) -> bool:
        return self.repl_meta_get("fenced", "0") == "1"

    def repl_last_applied_seq(self) -> int:
        return int(self.repl_meta_get("last_applied_seq", "0"))

    def repl_max_seq(self) -> int:
        with self._read_conn() as conn:
            row = conn.execute(
                "SELECT COALESCE(MAX(seq), 0) FROM repl_ops"
            ).fetchone()
        return int(row[0])

    def get_repl_ops_since(self, since: int, limit: int = 500) -> list[dict]:
        """One page of the op log: ops with seq > since, ascending (the
        standby passes the last applied seq back — the /events?since=
        cursor contract, over the durable log)."""
        with self._read_conn() as conn:
            rows = conn.execute(
                "SELECT seq, epoch, tbl, op, rid, row FROM repl_ops"
                " WHERE seq > ? ORDER BY seq ASC LIMIT ?",
                (int(since), max(1, int(limit))),
            ).fetchall()
        return [dict(r) for r in rows]

    def repl_set_standby(self) -> None:
        """Flip this replica to standby: capture OFF (applying streamed ops
        must not re-log them) and the role persisted for restart."""
        with self._lock, self._txn():
            self._conn.execute(
                "UPDATE repl_meta SET value = 'standby' WHERE key = 'role'"
            )
            self._conn.execute(
                "UPDATE repl_meta SET value = '0' WHERE key = 'capture'"
            )

    def apply_repl_ops(self, ops: list[dict]) -> int:
        """Apply one page of streamed ops to this standby replica in ONE
        transaction, advancing last_applied_seq and the locally-known epoch
        with them — a torn page can never be half-applied. Must run with
        capture off (repl_set_standby); unknown tables are skipped so a
        newer primary's tables degrade gracefully."""
        if not ops:
            return 0
        applied = 0
        with self._lock, self._txn():
            for op in ops:
                tbl = op["tbl"]
                if tbl not in self.REPL_TABLES:
                    continue
                if op["op"] == "D":
                    self._conn.execute(
                        f"DELETE FROM {tbl} WHERE rowid = ?",
                        (int(op["rid"]),),
                    )
                else:
                    row = json.loads(op["row"])
                    cols = list(row.keys())
                    marks = ", ".join("?" for _ in cols)
                    self._conn.execute(
                        f"INSERT OR REPLACE INTO {tbl}"
                        f" (rowid, {', '.join(cols)})"
                        f" VALUES (?, {marks})",
                        [int(op["rid"]), *row.values()],
                    )
                applied += 1
            last = max(int(op["seq"]) for op in ops)
            self._conn.execute(
                "UPDATE repl_meta SET value = ?"
                " WHERE key = 'last_applied_seq'"
                " AND CAST(value AS INTEGER) < ?",
                (str(last), last),
            )
            epoch = max(int(op["epoch"]) for op in ops)
            self._conn.execute(
                "UPDATE repl_meta SET value = ? WHERE key = 'epoch'"
                " AND CAST(value AS INTEGER) < ?",
                (str(epoch), epoch),
            )
        return applied

    def repl_promote(self) -> int:
        """Epoch-fenced promotion: bump the monotonic epoch, become primary
        with capture on, clear any fence, and seed the op-log AUTOINCREMENT
        so the new lineage's seq continues from the applied watermark — a
        rejoining replica's ?since= cursor stays meaningful across the
        promotion. Returns the new epoch. One transaction: a crash mid-
        promote leaves the replica either fully standby or fully primary."""
        with self._lock, self._txn():
            epoch = int(
                self._conn.execute(
                    "SELECT value FROM repl_meta WHERE key = 'epoch'"
                ).fetchone()[0]
            ) + 1
            for key, value in (
                ("epoch", str(epoch)),
                ("role", "primary"),
                ("capture", "1"),
                ("fenced", "0"),
            ):
                self._conn.execute(
                    "INSERT INTO repl_meta (key, value) VALUES (?, ?)"
                    " ON CONFLICT(key) DO UPDATE SET value = excluded.value",
                    (key, value),
                )
            applied = int(
                self._conn.execute(
                    "SELECT value FROM repl_meta"
                    " WHERE key = 'last_applied_seq'"
                ).fetchone()[0]
            )
            cur_max = int(
                self._conn.execute(
                    "SELECT COALESCE(MAX(seq), 0) FROM repl_ops"
                ).fetchone()[0]
            )
            base = max(applied, cur_max)
            self._conn.execute(
                "INSERT OR IGNORE INTO sqlite_sequence (name, seq)"
                " VALUES ('repl_ops', 0)"
            )
            self._conn.execute(
                "UPDATE sqlite_sequence SET seq = ?"
                " WHERE name = 'repl_ops' AND seq < ?",
                (base, base),
            )
        return epoch

    def prune_repl_ops(self, keep: int) -> int:
        """Retention: keep the newest `keep` ops (a standby further behind
        than that must re-seed from a snapshot). Returns rows dropped."""
        with self._lock, self._txn():
            cur = self._conn.execute(
                "DELETE FROM repl_ops WHERE seq <="
                " (SELECT COALESCE(MAX(seq), 0) FROM repl_ops) - ?",
                (max(0, int(keep)),),
            )
            return cur.rowcount
