"""Replication & epoch-fenced failover for the coordination plane.

One primary, N hot standbys, one SQLite file each. The primary's capture
triggers (db.py:_init_repl) append every committed row change to a durable
sequence-numbered op log *inside the mutating transaction*, so the log is
crash-consistent with the ledger by construction. Standbys pull pages of
that log over HTTP (``GET /repl/ops?since=SEQ`` — the same cursor-resume
contract the SSE journal feed uses) and apply them to their own replica,
serving the whole read-only surface locally while advertising applied-seq
lag.

Fencing: a monotonic **epoch** lives in the ledger (repl_meta). Promotion
bumps it. Clients stamp the highest epoch they have seen on every request
(``X-Nice-Epoch``); a server that sees a *higher* epoch than its own knows
it has been deposed and fences itself — persistently — so every later
write, stamped or not, is answered ``410 Gone``. Writes reaching a standby
get ``421 Misdirected Request``. Both are non-retryable at that endpoint
but rotate the client's multi-server failover, and the submit_id
exactly-once machinery makes the replayed write safe on the new primary.

Threading: ``repl-applier`` (standby only) is the single thread touching
the upstream socket; all replica mutations go through the writer actor so
the single-writer discipline holds on standbys too.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from nice_tpu import faults
from nice_tpu.obs.series import (
    REPL_APPLIED_SEQ,
    REPL_EPOCH,
    REPL_LAG,
    REPL_OPS_APPLIED,
    REPL_SEQ,
    REPL_STANDBYS,
    REPL_STREAM_ERRORS,
)
from nice_tpu.server.db import Db
from nice_tpu.utils import knobs, lockdep

log = logging.getLogger("nice.repl")

# A standby that hasn't polled for this many poll intervals is considered
# gone (dropped from /status's server list and the standby gauge).
STANDBY_LIVENESS_POLLS = 10


class ReplState:
    """Per-server replication identity: role, epoch, fence, standby registry.

    Epoch and fence are cached in memory for the per-request hot path and
    persisted through the writer so they survive restart; the fence is
    STICKY — once a request proves a newer epoch exists, this replica never
    accepts another write until an explicit promotion clears it.
    """

    def __init__(
        self,
        db: Db,
        writer,
        role: str = "primary",
        upstream: Optional[str] = None,
        advertise: Optional[str] = None,
        hub=None,
    ):
        self._lock = lockdep.make_lock("server.repl.ReplState._lock")
        self.db = db
        self.writer = writer
        self.hub = hub
        self.upstream = upstream.rstrip("/") if upstream else None
        self.advertise = advertise.rstrip("/") if advertise else None
        self._role = role
        self._epoch = db.repl_epoch()
        self._fenced = db.repl_fenced()
        self._last_seq = db.repl_max_seq()
        # url -> (applied_seq, monotonic ts of last poll)
        self._standbys: dict[str, tuple[int, float]] = {}
        REPL_EPOCH.set(self._epoch)
        if role == "primary":
            REPL_SEQ.set(self._last_seq)

    # -- identity ----------------------------------------------------------

    @property
    def role(self) -> str:
        with self._lock:
            return self._role

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def fenced(self) -> bool:
        with self._lock:
            return self._fenced

    # -- fencing -----------------------------------------------------------

    def note_client_epoch(self, header: Optional[str]) -> None:
        """A request carried ``X-Nice-Epoch``. Seeing a higher epoch than
        our own is proof a promotion happened elsewhere: fence permanently.
        The persist goes through the writer fire-and-forget — the in-memory
        fence already rejects this request and every one after it."""
        if not header:
            return
        try:
            seen = int(header)
        except ValueError:
            return
        with self._lock:
            if seen <= self._epoch or self._fenced:
                return
            self._fenced = True
        log.warning(
            "epoch fence: client presented epoch %d > local %d; "
            "refusing all writes until explicit promotion", seen, self._epoch
        )
        try:
            self.writer.submit(self.db.repl_meta_set, "fenced", "1")
        except Exception:  # noqa: BLE001 — the in-memory fence holds anyway
            log.exception("failed to persist fence flag")

    def check_write(self) -> Optional[tuple[int, str]]:
        """(status, message) to reject this write with, or None to allow.
        Called for every mutating request before any handler runs."""
        with self._lock:
            if self._role == "standby":
                return (
                    421,
                    "standby replica: writes must go to the primary",
                )
            if self._fenced:
                return (
                    410,
                    "fenced deposed primary: a newer epoch exists;"
                    " retry against the promoted server",
                )
        return None

    # -- promotion ---------------------------------------------------------

    def promote(self) -> int:
        """Become primary: bump the epoch (fencing the old primary's
        lineage), re-enable capture, clear any fence. The ledger flip is
        one transaction; callers re-arm primary duties afterwards."""
        epoch = self.writer.call(self.db.repl_promote)
        with self._lock:
            self._role = "primary"
            self._epoch = epoch
            self._fenced = False
            self._last_seq = self.db.repl_max_seq()
        REPL_EPOCH.set(epoch)
        REPL_SEQ.set(self._last_seq)
        log.warning("promoted to primary at epoch %d", epoch)
        if self.hub is not None:
            self.hub.publish(
                "repl", {"event": "promoted", "epoch": epoch,
                         "seq": self._last_seq}
            )
        return epoch

    def note_applied(self, applied_seq: int, upstream_epoch: int,
                     upstream_max: int) -> None:
        """Standby applier progress (gauges + epoch cache)."""
        with self._lock:
            if upstream_epoch > self._epoch:
                self._epoch = upstream_epoch
        REPL_APPLIED_SEQ.set(applied_seq)
        REPL_LAG.set(max(0, upstream_max - applied_seq))
        REPL_EPOCH.set(self.epoch)

    # -- primary-side bookkeeping ------------------------------------------

    def attach_writer_listener(self) -> None:
        """Publish the op-log high-water mark after every committed batch
        (post-commit, same guarantee as the journal stream flush)."""
        self.writer.add_batch_end_listener(self._on_batch_end)

    def _on_batch_end(self, committed: bool) -> None:
        if not committed or self.role != "primary":
            return
        seq = self.db.repl_max_seq()
        with self._lock:
            if seq == self._last_seq:
                return
            self._last_seq = seq
        REPL_SEQ.set(seq)
        if self.hub is not None:
            self.hub.publish(
                "repl", {"event": "commit", "seq": seq, "epoch": self.epoch}
            )

    def prune_tick(self) -> None:
        """Writer periodic on the primary: bound op-log retention."""
        if self.role != "primary":
            return
        keep = knobs.REPL_RETENTION_OPS.get()
        if keep and keep > 0:
            # nicelint: allow W1 (writer periodic: already runs on the writer thread between batches)
            self.db.prune_repl_ops(keep)

    # -- standby registry (primary side) -----------------------------------

    def record_standby_poll(self, url: Optional[str],
                            applied: Optional[int]) -> None:
        if not url:
            return
        now = time.monotonic()
        with self._lock:
            self._standbys[url.rstrip("/")] = (int(applied or 0), now)
        REPL_STANDBYS.set(len(self.live_standbys()))

    def live_standbys(self) -> dict[str, int]:
        """url -> applied_seq for standbys seen within the liveness window."""
        window = STANDBY_LIVENESS_POLLS * max(
            0.05, knobs.REPL_POLL_SECS.get()
        )
        cutoff = time.monotonic() - window
        with self._lock:
            return {
                url: applied
                for url, (applied, ts) in self._standbys.items()
                if ts >= cutoff
            }

    def known_servers(self) -> list[str]:
        """Every endpoint a client could fail over to, primary first —
        served in /status so clients can persist the list (satellite:
        learned-server failover survives a dead configured primary)."""
        servers: list[str] = []
        if self.role == "primary":
            if self.advertise:
                servers.append(self.advertise)
            servers.extend(self.live_standbys())
        else:
            if self.upstream:
                servers.append(self.upstream)
            if self.advertise:
                servers.append(self.advertise)
        return list(dict.fromkeys(servers))

    def status_block(self) -> dict:
        with self._lock:
            role, epoch, fenced = self._role, self._epoch, self._fenced
        block = {
            "role": role,
            "epoch": epoch,
            "fenced": fenced,
            "servers": self.known_servers(),
        }
        if role == "primary":
            block["seq"] = self.db.repl_max_seq()
            block["standbys"] = self.live_standbys()
        else:
            applied = self.db.repl_last_applied_seq()
            block["applied_seq"] = applied
        return block


class ReplApplier:
    """Standby-side op-log puller: one thread, plain urllib (the server
    package must not depend on the client transport), all DB mutation via
    the writer actor. Fault sites: ``repl.stream`` fires before each fetch
    (conn_error/raise → injected URLError; numeric → sleep), ``repl.apply``
    before each apply transaction."""

    def __init__(self, db: Db, writer, state: ReplState, hub=None):
        self.db = db
        self.writer = writer
        self.state = state
        self.hub = hub
        self._stop = threading.Event()
        self._rng = random.Random()
        self._thread = threading.Thread(
            target=self._run, name="repl-applier", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    # -- loop --------------------------------------------------------------

    def _run(self) -> None:
        errors = 0
        while not self._stop.is_set():
            try:
                full_page = self._poll_once()
                errors = 0
            except Exception:  # noqa: BLE001 — the applier must survive
                REPL_STREAM_ERRORS.inc()
                errors += 1
                if errors <= 3 or errors % 50 == 0:
                    log.exception("repl stream poll failed (x%d)", errors)
                # Full-jitter backoff, bounded: the upstream being down is
                # the NORMAL state right before a promotion.
                self._stop.wait(
                    self._rng.uniform(0, min(2.0, 0.1 * (2 ** min(errors, 5))))
                )
                continue
            if not full_page:
                self._stop.wait(max(0.05, knobs.REPL_POLL_SECS.get()))

    def _poll_once(self) -> bool:
        """One fetch+apply round. Returns True when the page was full
        (more ops are likely waiting — re-poll immediately)."""
        act = faults.fire("repl.stream")
        if act is not None:
            if act in ("conn_error", "raise"):
                raise urllib.error.URLError("injected repl.stream fault")
            try:
                time.sleep(float(act))
            except (TypeError, ValueError):
                pass

        since = self.db.repl_last_applied_seq()
        limit = max(1, knobs.REPL_BATCH_OPS.get())
        page = self._fetch(since, limit)
        ops = page.get("ops") or []

        if ops:
            act = faults.fire("repl.apply")
            if act is not None:
                if act in ("conn_error", "raise"):
                    raise RuntimeError("injected repl.apply fault")
                try:
                    time.sleep(float(act))
                except (TypeError, ValueError):
                    pass
            applied = self.writer.call(self.db.apply_repl_ops, ops)
            REPL_OPS_APPLIED.inc(applied)
            self._publish_journal(ops)
            since = int(ops[-1]["seq"])

        self.state.note_applied(
            since,
            int(page.get("epoch") or 0),
            int(page.get("max_seq") or since),
        )
        return len(ops) >= limit

    def _fetch(self, since: int, limit: int) -> dict:
        params = {"since": str(since), "limit": str(limit)}
        if self.state.advertise:
            params["standby"] = self.state.advertise
            params["applied"] = str(since)
        url = (
            f"{self.state.upstream}/repl/ops?"
            + urllib.parse.urlencode(params)
        )
        req = urllib.request.Request(url)
        key = knobs.REPL_KEY.get()
        if key:
            req.add_header("X-Repl-Key", key)
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def _publish_journal(self, ops: list[dict]) -> None:
        """Mirror replicated field_events inserts into the local SSE hub so
        a standby's /events/stream consumers see the same live feed (resume
        replay comes from the replica's own field_events table)."""
        if self.hub is None:
            return
        rows = []
        for op in ops:
            if op.get("tbl") != "field_events" or op.get("op") != "I":
                continue
            try:
                row = json.loads(op["row"])
            except (TypeError, ValueError):
                continue
            try:
                row["detail"] = json.loads(row.get("detail") or "{}")
            except (TypeError, ValueError):
                row["detail"] = {}
            row.setdefault("id", int(op["rid"]))
            rows.append(row)
        if rows:
            self.hub.publish_journal_rows(rows)
