"""Client trust tiers + seeded spot verification.

Every client carries a persisted trust score (server/db.py client_trust
table), keyed by its trust token: the telemetry client_id for CLI clients,
a server-issued anonymous token for browser clients (POST /token), or
username@ip as the legacy fallback. An X-Client-Token header is honored
only when the server knows the token (issued via POST /token or an earned
trust history) — an arbitrary bearer string minted by the client falls
back to the ip-keyed identity, so fresh tokens cannot reset per-client
claim caps or the trust ledger. On each accepted submission the server
re-runs a random sample of the claimed range on the trusted scalar engine;
the sampling rate scales inversely with trust (~100% for brand-new clients
down to the NICE_TPU_SPOT_RATE floor for veterans), and the RNG is seeded
per submission (spot seed + the submit key) so the decision and the
sampled slice are deterministic regardless of thread interleaving. The
spot seed is a SECRET generated fresh at process start: the submit key is
client-chosen, so a predictable seed would let an adversary precompute
the sampled slice and forge everything outside it. NICE_TPU_SPOT_SEED
overrides it for deterministic tests only — never set it in production.

A passed check adds +1 trust through ONE writer-actor upsert (the only DB
write spot verification adds to the hot accept path). A failed check
slashes trust to zero, marks the client suspect, disqualifies the
submission, and requeues the field — all off the accept path.

Trust feeds check_level: with NICE_TPU_TRUST_THRESHOLD > 0, submissions
from below-threshold clients never promote canon directly; the field is
held at "needs consensus" (check_level 1) until an independent client
agrees (app.py hooks the per-field streaming consensus on the submit
path). The threshold defaults to 0 — gating OFF — so trusted-fleet
deployments keep the original single-submission promotion semantics.
"""

from __future__ import annotations

import logging
import random
import secrets
from typing import Optional

from nice_tpu.core import number_stats
from nice_tpu.core.types import NiceNumber, UniquesDistribution
from nice_tpu.obs.series import SERVER_SPOT_CHECKS
from nice_tpu.ops import scalar
from nice_tpu.server.db import Db
from nice_tpu.utils import knobs, lockdep

log = logging.getLogger("nice_tpu.server.trust")


def trust_threshold() -> float:
    """Trust score below which a client is untrusted (0 disables gating)."""
    return knobs.TRUST_THRESHOLD.get()


def spot_rate_floor() -> float:
    """Veteran-client sampling-rate floor (~1% by default)."""
    return min(1.0, max(0.0, knobs.SPOT_RATE.get()))


# Secret per-process default for the spot-check RNG seed. The other seed
# input (the submit key) is chosen by the client, so the seed itself must be
# unpredictable or the whole verification scheme is precomputable.
_RUNTIME_SPOT_SEED = secrets.token_hex(16)


def spot_seed() -> str:
    """NICE_TPU_SPOT_SEED is a TEST override; unset (the production
    default) uses a random secret generated at process start."""
    return knobs.SPOT_SEED.get() or _RUNTIME_SPOT_SEED


def spot_slice_len() -> int:
    """Numbers re-scanned per sampled submission (0 disables spot checks)."""
    return knobs.SPOT_SLICE.get()


def sample_rate(trust: float) -> float:
    """Inverse-trust sampling: trust 0 -> 1.0, trust 99 -> ~0.01, floored
    at spot_rate_floor so veterans stay spot-checked forever."""
    return max(spot_rate_floor(), min(1.0, 1.0 / (1.0 + max(0.0, trust))))


def submission_rng(submit_key: str) -> random.Random:
    """Deterministic per-submission RNG: seeded from the global spot seed
    plus the submission's idempotency key, so tests (and replays) see the
    same sample decision and slice regardless of scheduling."""
    return random.Random(f"{spot_seed()}:{submit_key}")


def resolve_token(
    payload: dict, headers, username: str, user_ip: str, store=None,
) -> str:
    """The client's trust identity, most-specific first: an explicit
    X-Client-Token header (server-issued anonymous tokens), the telemetry
    client_id piggybacked on the payload, then username@ip.

    When a TrustStore is provided, a header token is honored only if the
    server KNOWS it (a client_trust row exists — minted by POST /token or
    earned by submission history). An unvalidated bearer string would let a
    client reset every per-token control (claim caps, trust, rate buckets)
    by inventing a fresh token per request."""
    token = headers.get("X-Client-Token") if headers is not None else None
    if token:
        token = str(token)[:256]
        if store is None or store.known(token):
            return token
    tel = payload.get("telemetry") if isinstance(payload, dict) else None
    if isinstance(tel, dict) and tel.get("client_id"):
        return str(tel["client_id"])[:256]
    return f"{username or 'anon'}@{user_ip or 'unknown'}"[:256]


class TrustStore:
    """Read-through in-memory view of the client_trust table.

    Reads (claim profile selection, limiter bucket sizing, sampling rate)
    hit the cache — the rate limiter peeks at trust ON THE EVENT LOOP
    thread, where sqlite is forbidden. Writes go through the writer actor
    (ctx.write) and refresh the cache from the returned row."""

    def __init__(self, db: Db):
        self.db = db
        self._cache: dict[str, dict] = {}
        self._lock = lockdep.make_lock("server.trust.TrustLedger._lock")

    def get(self, client_token: str) -> dict:
        with self._lock:
            row = self._cache.get(client_token)
        if row is not None:
            return row
        row = self.db.get_client_trust(client_token) or {
            "client_token": client_token,
            "trust": 0.0,
            "suspect": 0,
        }
        with self._lock:
            self._cache[client_token] = row
        return row

    def peek(self, client_token: str) -> Optional[dict]:
        """Cache-only read (event-loop safe; None = not yet cached)."""
        with self._lock:
            return self._cache.get(client_token)

    def peek_known(self, client_token: str) -> bool:
        """Cache-only known() (event-loop safe): False when the token is
        unknown OR simply not cached yet. get()'s fabricated defaults are
        cached too, so a probed-but-unregistered token stays False."""
        row = self.peek(client_token)
        return bool(row) and "first_seen" in row

    def update(self, row: dict) -> None:
        with self._lock:
            self._cache[row["client_token"]] = row

    def known(self, client_token: str) -> bool:
        """True when the token has a persisted trust row (minted by POST
        /token or earned by submission history). The fabricated default
        from get() carries no first_seen, so it never counts as known;
        upserts refresh the cache through update(), clearing the negative
        entry."""
        return "first_seen" in self.get(client_token)

    def trust(self, client_token: str) -> float:
        return float(self.get(client_token).get("trust", 0.0))

    def is_trusted(self, client_token: str) -> bool:
        threshold = trust_threshold()
        if threshold <= 0:
            return True
        row = self.get(client_token)
        return not row.get("suspect") and float(row.get("trust", 0.0)) >= threshold

    def should_sample(self, client_token: str, rng: random.Random) -> bool:
        if spot_slice_len() <= 0:
            return False
        return rng.random() < sample_rate(self.trust(client_token))


def spot_check(
    base: int,
    range_start: int,
    range_end: int,
    distribution: Optional[list[UniquesDistribution]],
    numbers: list[NiceNumber],
    rng: random.Random,
) -> tuple[bool, str]:
    """Re-run a random slice of the claimed range on the trusted scalar
    engine and cross-check it against the claimed results. Returns
    (ok, detail). Runs on the handler thread; pure compute, no DB access.

    Checks, cheapest first:
      1. every CLAIMED nice number lies in the range and recomputes to its
         claimed num_uniques (nice numbers are rare, so this is cheap; it is
         the only verification niceonly submissions ever get);
      2. a seeded random slice of the range is rescanned — any slice number
         above the near-miss cutoff (detailed) or fully nice (niceonly) must
         appear in the claimed numbers, and per-bucket slice counts must not
         exceed the claimed distribution (detailed).
    """
    for n in numbers:
        if not (range_start <= n.number < range_end):
            return False, f"claimed number {n.number} outside range"
        calculated = scalar.get_num_unique_digits(n.number, base)
        if calculated != n.num_uniques:
            return (
                False,
                f"claimed number {n.number} has {calculated} uniques,"
                f" not {n.num_uniques}",
            )

    slice_len = min(spot_slice_len(), range_end - range_start)
    if slice_len <= 0:
        return True, "empty slice"
    start = range_start + rng.randrange(
        max(1, (range_end - range_start) - slice_len + 1)
    )
    claimed_numbers = {n.number: n.num_uniques for n in numbers}
    claimed_counts = (
        {d.num_uniques: d.count for d in distribution}
        if distribution is not None
        else None
    )
    cutoff = number_stats.get_near_miss_cutoff(base)
    slice_counts: dict[int, int] = {}
    for x in range(start, start + slice_len):
        uniques = scalar.get_num_unique_digits(x, base)
        slice_counts[uniques] = slice_counts.get(uniques, 0) + 1
        if claimed_counts is not None:
            # Detailed: everything above the cutoff must be in the claimed
            # numbers list (the distribution cross-check below bounds the
            # rest).
            if uniques > cutoff and claimed_numbers.get(x) != uniques:
                return (
                    False,
                    f"{x} has {uniques} uniques but is missing from the"
                    f" claimed nice numbers",
                )
        else:
            # Niceonly: only 100% nice numbers are reportable.
            if uniques == base and x not in claimed_numbers:
                return False, f"nice number {x} missing from claimed numbers"
    if claimed_counts is not None:
        for uniques, count in slice_counts.items():
            if count > claimed_counts.get(uniques, 0):
                return (
                    False,
                    f"slice holds {count} numbers with {uniques} uniques;"
                    f" claimed distribution has {claimed_counts.get(uniques, 0)}",
                )
    return True, f"slice [{start}, {start + slice_len}) ok"


def run_spot_check(
    store: TrustStore,
    client_token: str,
    submit_key: str,
    base: int,
    range_start: int,
    range_end: int,
    distribution: Optional[list[UniquesDistribution]],
    numbers: list[NiceNumber],
) -> tuple[str, str]:
    """Sampling decision + verification for one accepted submission.
    Returns (verdict, detail) with verdict in pass/fail/skipped and bumps
    nice_server_spot_checks_total. No DB writes happen here — the caller
    routes the consequences (trust upsert / slash) through the writer."""
    rng = submission_rng(submit_key)
    if not store.should_sample(client_token, rng):
        SERVER_SPOT_CHECKS.labels("skipped").inc()
        return "skipped", "not sampled"
    ok, detail = spot_check(
        base, range_start, range_end, distribution, numbers, rng
    )
    verdict = "pass" if ok else "fail"
    SERVER_SPOT_CHECKS.labels(verdict).inc()
    if not ok:
        log.warning(
            "spot check FAILED for client %s (%s): %s",
            client_token, submit_key, detail,
        )
    return verdict, detail
