"""Async request core: one event loop for I/O, a bounded worker pool for
handlers.

The stdlib ThreadingHTTPServer spends one OS thread per open CONNECTION,
which caps the fleet at a few hundred clients. This core accepts and parses
HTTP/1.1 keep-alive connections on a single asyncio event loop (10k open
sockets are cheap there) and dispatches each complete request to a bounded
ThreadPoolExecutor running the transport-agnostic router from
nice_tpu.server.app — the selector-driven, bounded-worker shape of the
reference's Rocket/tokio host loop. DB writes inside the handlers are
further funneled through the single-writer actor (server/writer.py), so
worker-thread count never multiplies SQLite writers.

The public surface deliberately mimics socketserver: serve() returns an
object with serve_forever(), shutdown(), and server_address, because every
test fixture and smoke script drives the server exactly that way.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import socket
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from http.client import responses as _REASONS
from typing import Callable, Optional

from nice_tpu.utils import knobs, lockdep

log = logging.getLogger(__name__)

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 256 * 1024 * 1024


class TokenBucketLimiter:
    """Dependency-free per-client token buckets, keyed by (client token,
    endpoint class).

    This is the per-CLIENT admission control (429 + Retry-After) as opposed
    to the global overload shed (503): a single flooding client drains only
    its own buckets while honest clients keep their latency. allow() is a
    couple of dict operations under an uncontended lock, cheap enough for the
    event-loop thread, and the bucket table is LRU-bounded so an attacker
    minting fresh tokens cannot grow it without bound.

    NICE_TPU_RATE_BUCKET="capacity:refill_per_sec" sizes the claim/submit
    buckets (reads get 4x). Limiting is opt-in: the server only constructs
    a limiter when that env var is set, because the fallback bucket key is
    the client IP and an always-on limiter would throttle NAT'd fleets.
    multiplier, when provided, maps a token to a bucket-size factor (trusted
    clients earn bigger buckets); it MUST be loop-thread safe — an in-memory
    lookup, never a database read."""

    def __init__(
        self,
        capacity: Optional[float] = None,
        refill_per_sec: Optional[float] = None,
        max_keys: int = 10_000,
        multiplier: Optional[Callable[[str], float]] = None,
    ):
        spec = knobs.RATE_BUCKET.get() or "300:100"
        cap_s, _, refill_s = spec.partition(":")
        self.capacity = float(capacity if capacity is not None else cap_s or 300)
        self.refill = float(
            refill_per_sec if refill_per_sec is not None else refill_s or 100
        )
        self.max_keys = max_keys
        self.multiplier = multiplier
        self._buckets: OrderedDict = OrderedDict()
        self._lock = lockdep.make_lock("server.async_core.TokenBucketLimiter._lock")

    @staticmethod
    def classify(path: str) -> str:
        """Per-endpoint budgets by class: claim-side, submit-side, reads."""
        seg = path.lstrip("/").split("/", 1)[0]
        if seg in ("claim", "claim_block", "renew_claim", "token"):
            return "claim"
        if seg in ("submit", "submit_block", "telemetry"):
            return "submit"
        return "read"

    def allow(
        self, token: str, path: str, cost: float = 1.0,
        now: Optional[float] = None,
    ) -> tuple[bool, float]:
        """(allowed, retry_after_secs). retry_after is 0 when allowed."""
        if now is None:
            now = time.monotonic()
        mult = 1.0
        if self.multiplier is not None:
            try:
                mult = max(1.0, float(self.multiplier(token)))
            except Exception:
                mult = 1.0
        klass = self.classify(path)
        cap = self.capacity * mult * (4.0 if klass == "read" else 1.0)
        refill = self.refill * mult * (4.0 if klass == "read" else 1.0)
        key = (token, klass)
        with self._lock:
            bucket = self._buckets.pop(key, None)
            if bucket is None:
                tokens = cap
                if len(self._buckets) >= self.max_keys:
                    self._buckets.popitem(last=False)
            else:
                tokens = min(cap, bucket[0] + (now - bucket[1]) * refill)
            if tokens >= cost:
                self._buckets[key] = [tokens - cost, now]
                return True, 0.0
            self._buckets[key] = [tokens, now]
            return False, (cost - tokens) / refill if refill > 0 else 1.0


class Headers:
    """Case-insensitive header view (the subset handlers actually use)."""

    def __init__(self, pairs):
        self._d = {}
        for k, v in pairs:
            self._d[k.lower()] = v

    def get(self, key: str, default=None):
        return self._d.get(key.lower(), default)

    def items(self):
        return self._d.items()


@dataclass
class Request:
    method: str
    target: str  # raw path + query, as received
    headers: Headers
    body: bytes
    client_ip: str


@dataclass
class Response:
    status: int = 200
    headers: dict = field(default_factory=dict)
    body: bytes = b""
    drop: bool = False  # close the connection without writing anything
    close: bool = False  # write the response, then close
    # Streaming responses (SSE): an async callable awaited ON THE LOOP
    # THREAD with the raw StreamWriter after the head is written. The
    # router returns one from a pool thread without blocking that pool
    # slot for the stream's lifetime — long-lived subscribers are
    # loop-serviced, not worker-occupying. Content-Length is omitted and
    # the connection always closes when the callable returns.
    stream: Optional[Callable] = None


Router = Callable[[Request], Response]


class AsyncHTTPServer:
    """Event-loop front end + bounded-worker dispatch.

    router runs on a pool thread and must return a Response. shed, when
    provided, is consulted on the LOOP thread once more than max_inflight
    requests are dispatched-but-unfinished; returning a Response answers
    immediately without touching the pool (the overload path must not queue
    behind the very backlog it exists to shed), returning None lets the
    request through regardless (exempt endpoints like /metrics). limiter has
    the same shape but is consulted on EVERY request (per-client rate
    limiting must fire before a flooder ever reaches the pool); like shed it
    runs on the loop thread and must never block."""

    def __init__(
        self,
        host: str,
        port: int,
        router: Router,
        max_workers: Optional[int] = None,
        max_inflight: Optional[int] = None,
        shed: Optional[Callable[[Request], Optional[Response]]] = None,
        limiter: Optional[Callable[[Request], Optional[Response]]] = None,
    ):
        self.router = router
        self.shed = shed
        self.limiter = limiter
        self.max_inflight = max_inflight or 0
        self._sock = socket.create_server(
            (host, port), backlog=1024, reuse_port=False
        )
        self._sock.setblocking(False)
        self.server_address = self._sock.getsockname()[:2]
        workers = max_workers or knobs.SERVER_WORKERS.get()
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="nice-srv"
        )
        self._loop = asyncio.new_event_loop()
        self._stop = asyncio.Event()
        self._started = threading.Event()
        self._done = threading.Event()
        self._inflight = 0  # loop-thread only

    # -- socketserver-compatible surface -----------------------------------

    def serve_forever(self) -> None:
        asyncio.set_event_loop(self._loop)
        # Lockdep long-hold attribution: any project lock held too long on
        # THIS thread starves every open connection at once.
        lockdep.mark_loop_thread()
        self._started.set()
        try:
            self._loop.run_until_complete(self._main())
            pending = asyncio.all_tasks(self._loop)
            for t in pending:
                t.cancel()
            if pending:
                self._loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
        finally:
            with contextlib.suppress(Exception):
                self._loop.close()
            self._pool.shutdown(wait=False)
            self._done.set()

    def shutdown(self) -> None:
        if not self._started.is_set():
            # serve_forever never ran; just release the port.
            with contextlib.suppress(OSError):
                self._sock.close()
            self._done.set()
            return
        with contextlib.suppress(RuntimeError):
            self._loop.call_soon_threadsafe(self._stop.set)
        self._done.wait(timeout=10)

    def server_close(self) -> None:
        with contextlib.suppress(OSError):
            self._sock.close()

    # -- event loop ---------------------------------------------------------

    async def _main(self) -> None:
        server = await asyncio.start_server(
            self._handle_conn, sock=self._sock, limit=MAX_HEADER_BYTES
        )
        await self._stop.wait()
        server.close()
        with contextlib.suppress(Exception):
            await asyncio.wait_for(server.wait_closed(), timeout=2)

    async def _handle_conn(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")
        client_ip = peer[0] if isinstance(peer, tuple) and peer else ""
        loop = asyncio.get_running_loop()
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (
                    asyncio.IncompleteReadError,
                    asyncio.LimitOverrunError,
                    ConnectionError,
                    OSError,
                ):
                    return
                parsed = self._parse_head(head)
                if parsed is None:
                    await self._write_response(
                        writer,
                        Response(400, body=b'{"error":"malformed request"}'),
                        keep_alive=False,
                    )
                    return
                method, target, version, headers = parsed
                try:
                    length = int(headers.get("content-length", 0) or 0)
                except ValueError:
                    length = -1
                if length < 0 or length > MAX_BODY_BYTES:
                    await self._write_response(
                        writer,
                        Response(400, body=b'{"error":"bad content-length"}'),
                        keep_alive=False,
                    )
                    return
                body = b""
                if length:
                    try:
                        body = await reader.readexactly(length)
                    except (asyncio.IncompleteReadError, ConnectionError):
                        return
                request = Request(method, target, headers, body, client_ip)
                response = None
                if self.limiter is not None:
                    response = self.limiter(request)
                if response is None and (
                    self.shed is not None
                    and self.max_inflight
                    and self._inflight >= self.max_inflight
                ):
                    response = self.shed(request)
                if response is None:
                    self._inflight += 1
                    try:
                        response = await loop.run_in_executor(
                            self._pool, self._safe_route, request
                        )
                    finally:
                        self._inflight -= 1
                if response.drop:
                    return  # chaos "drop": vanish without a response
                if response.stream is not None:
                    await self._serve_stream(writer, response)
                    return
                keep = self._keep_alive(version, headers) and not response.close
                await self._write_response(writer, response, keep)
                if not keep:
                    return
        finally:
            with contextlib.suppress(Exception):
                writer.close()

    def _safe_route(self, request: Request) -> Response:
        try:
            return self.router(request)
        except Exception as e:  # the router has its own 500 path; last resort
            log.exception("router crashed on %s %s", request.method, request.target)
            return Response(
                500,
                body=(
                    b'{"error":{"code":500,"message":"Internal server error: '
                    + str(e).encode(errors="replace")[:200]
                    + b'"}}'
                ),
            )

    @staticmethod
    def _parse_head(head: bytes):
        try:
            text = head.decode("latin-1")
            request_line, *header_lines = text.split("\r\n")
            method, target, version = request_line.split(" ", 2)
            pairs = []
            for line in header_lines:
                if not line:
                    continue
                name, _, value = line.partition(":")
                pairs.append((name.strip(), value.strip()))
            return method.upper(), target, version.strip(), Headers(pairs)
        except ValueError:
            return None

    @staticmethod
    def _keep_alive(version: str, headers: Headers) -> bool:
        conn = (headers.get("connection") or "").lower()
        if version == "HTTP/1.1":
            return conn != "close"
        return conn == "keep-alive"

    @staticmethod
    async def _serve_stream(writer, response: Response) -> None:
        """Write the head sans Content-Length, then hand the socket to the
        response's stream coroutine (runs on the loop thread until the
        subscriber disconnects or is evicted). The connection never
        keep-alives: SSE owns the socket until it dies."""
        reason = _REASONS.get(response.status, "Unknown")
        lines = [f"HTTP/1.1 {response.status} {reason}"]
        headers = dict(response.headers)
        headers.setdefault("Content-Type", "text/event-stream")
        headers.setdefault("Cache-Control", "no-cache")
        headers["Connection"] = "close"
        for name, value in headers.items():
            lines.append(f"{name}: {value}")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        try:
            writer.write(head)
            await writer.drain()
        except (ConnectionError, OSError):
            return
        try:
            await response.stream(writer)
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        except Exception:  # noqa: BLE001 — a stream bug must not kill the loop
            log.exception("stream responder crashed")

    @staticmethod
    async def _write_response(writer, response: Response, keep_alive: bool):
        reason = _REASONS.get(response.status, "Unknown")
        lines = [f"HTTP/1.1 {response.status} {reason}"]
        headers = dict(response.headers)
        headers.setdefault("Content-Type", "application/json")
        headers["Content-Length"] = str(len(response.body))
        headers["Connection"] = "keep-alive" if keep_alive else "close"
        for name, value in headers.items():
            lines.append(f"{name}: {value}")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        try:
            writer.write(head + response.body)
            await writer.drain()
        except (ConnectionError, OSError):
            pass
