"""Coordination HTTP API server.

The request core is async (nice_tpu/server/async_core.py): one event loop
owns every socket, a bounded worker pool runs the transport-agnostic router
below, and ALL database mutations funnel through a single-writer actor
(nice_tpu/server/writer.py) that coalesces them into batched SQLite
transactions — the stdlib equivalent of the reference's Rocket app over a
pooled Postgres (api/src/main.rs), re-shaped for SQLite's one-writer
reality. Claim endpoints keep the 80/15/4/1 detailed strategy mix and the
in-memory pre-claim queues; /claim_block, /submit_block, and block-aware
/renew_claim amortize one HTTP round-trip and one lease over N fields,
while the original per-field endpoints remain as the compatibility path for
the WASM/browser client. Submit-side verification still recomputes every
submitted number with the trusted engine. /status serves its fleet block
from a short-TTL read snapshot; /metrics is a Prometheus exporter with
per-endpoint request timing.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import logging
import os
import random
import secrets
import sqlite3
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from nice_tpu import faults, obs
from nice_tpu.core import consensus, distribution_stats, number_stats
from nice_tpu.core.constants import (
    CLAIM_DURATION_HOURS,
    DETAILED_SEARCH_MAX_FIELD_SIZE,
)
from nice_tpu.core.types import (
    DataToClient,
    DataToServer,
    FieldClaimStrategy,
    SearchMode,
)
from nice_tpu.obs.series import (
    REPL_FENCED_WRITES,
    FLEET_CLIENTS,
    FLEET_DOWNGRADES,
    FLEET_FAULTS,
    FLEET_FIELD_LATENCY,
    FLEET_FIELDS,
    FLEET_MESH_DEVICES,
    FLEET_MESH_RESHARDS,
    FLEET_NUMBERS,
    FLEET_RATE,
    FLEET_RESTORES,
    FLEET_SPOOL_DEPTH,
    SERVER_BLOCK_LEASE_SIZE,
    HISTORY_PERSISTED_ROWS,
    HISTORY_SAMPLES,
    SERVER_CONSENSUS_HOLDS,
    SERVER_DUPLICATE_SUBMITS,
    SERVER_FIELD_ELAPSED,
    SERVER_JOURNAL_WRITE_FAILURES,
    SERVER_LEASES_EXPIRED,
    SERVER_OVERLOAD_RESPONSES,
    SERVER_RATE_LIMITED,
    SERVER_SPOT_CHECKS,
    SERVER_STATUS_CACHE_EVENTS,
    SERVER_TELEMETRY_REPORTS,
    SERVER_TRUST_CLIENTS,
    SERVER_TRUST_SLASHES,
)
from nice_tpu.ops import scalar
from nice_tpu.server import repl as repl_mod
from nice_tpu.server import trust as trust_mod
from nice_tpu.server.async_core import (
    AsyncHTTPServer,
    Request,
    Response,
    TokenBucketLimiter,
)
from nice_tpu.server.db import Db
from nice_tpu.server.field_queue import U128_MAX, FieldQueue
from nice_tpu.server import writer as writer_mod
from nice_tpu.server.writer import DirectWriter, WriteActor
from nice_tpu.utils import knobs, lockdep

log = logging.getLogger("nice_tpu.server")


class Metrics:
    """Per-endpoint request counters and latency histograms (Prometheus text).

    Built on the shared nice_tpu.obs registry machinery; each ApiContext
    keeps a private Registry so parallel test servers don't cross-count,
    while render() appends the process-global registry so the server's
    /metrics also exposes the engine pipeline series (batch kernel time,
    dispatch-window occupancy, host-fallback/audit counters — at zero when
    this process never runs the engine, which is the normal server case).

    Histogram buckets mirror rocket_prometheus's defaults (reference
    api/src/main.rs:438-459 exposes per-endpoint response-time histograms),
    giving p50/p99 visibility rather than just cumulative sums."""

    BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self):
        self.registry = obs.Registry()
        self._requests = self.registry.counter(
            "nice_api_requests_total",
            "Requests by endpoint and status.",
            labelnames=("endpoint", "status"),
        )
        self._latency = self.registry.histogram(
            "nice_api_request_seconds",
            "Request latency by endpoint.",
            labelnames=("endpoint",),
            buckets=self.BUCKETS,
        )

    def record(self, endpoint: str, status: int, elapsed: float) -> None:
        self._requests.labels(endpoint, str(status)).inc()
        self._latency.labels(endpoint).observe(elapsed)

    def request_counts(self) -> dict:
        """{(endpoint, status): count} snapshot (the /status fleet block's
        request/error rollup reads the same counters /metrics renders)."""
        return self._requests.values()

    def render(self) -> str:
        lines = [self.registry.render().rstrip("\n")]
        # Back-compat: the round-3 metric name, kept for one release so
        # scrape rules keyed on it keep working (advisor r4; the rename
        # is also called out in CHANGELOG.md). Same value as
        # nice_api_request_seconds_sum.
        lines.append(
            "# HELP nice_api_request_seconds_total DEPRECATED alias of "
            "nice_api_request_seconds_sum; remove after one release."
        )
        lines.append("# TYPE nice_api_request_seconds_total counter")
        for (endpoint,), (total, _count) in sorted(
            self._latency.label_sums().items()
        ):
            lines.append(
                f'nice_api_request_seconds_total{{endpoint="{endpoint}"}}'
                f" {total:.6f}"
            )
        # Engine pipeline + span series live in the process-global registry.
        lines.append(obs.render().rstrip("\n"))
        return "\n".join(lines) + "\n"


class ApiContext:
    def __init__(self, db: Db, role: str = "primary",
                 upstream: str | None = None, advertise: str | None = None):
        self.db = db
        # Replication role. A "standby" context serves only the read
        # surface from its replica, runs no ledger-mutating background
        # work (refills, sweeps, history persistence — their rows arrive
        # via the op log), and answers writes 421 until promoted.
        self.role = role
        # Single-writer DB actor: every mutation (claims, submits, renewals,
        # telemetry upserts) is enqueued here and coalesced into batched
        # transactions. NICE_TPU_WRITER=0 falls back to direct per-call
        # transactions (useful for debugging; semantics are identical).
        if knobs.WRITER.get_bool():
            self.writer = WriteActor(db)
        else:
            self.writer = DirectWriter(db)
        # Push-based live telemetry: the SSE hub behind GET /events/stream.
        # Journal events are STAGED in journal_now (which may run inside an
        # uncommitted writer batch) and published only from the writer's
        # on_batch_end(committed=True) hook — a rolled-back batch's events
        # are discarded, so subscribers never see a transition that didn't
        # durably happen. Wired before FieldQueue below: its bulk pre-claims
        # journal through the writer while __init__ is still running.
        self.stream = obs.stream.StreamHub()
        self._stream_staged: list = []
        self._stream_stage_lock = lockdep.make_lock(
            "server.app.ApiContext._stream_stage_lock"
        )
        self.writer.on_batch_end = self._flush_stream_staged
        # Replication state: epoch fencing + standby registry (primary) or
        # upstream identity (standby). Wired before the FieldQueue so the
        # op-log high-water gauge covers its bulk pre-claims too.
        self.repl = repl_mod.ReplState(
            db, self.writer, role=role, upstream=upstream,
            advertise=advertise, hub=self.stream,
        )
        self.repl.attach_writer_listener()
        self.repl_applier = None
        if role == "primary":
            # Crash counterpart of FieldQueue.close(): a SIGKILLed server's
            # in-memory inventory left lease stamps with no claims rows;
            # release them before this process's queue starts bulk-claiming.
            # nicelint: allow W1 (sanctioned init: crash recovery runs before the writer accepts work)
            orphaned = db.release_orphaned_inventory()
            if orphaned:
                log.info(
                    "released %d orphaned pre-claimed fields from a dead"
                    " server's queue inventory", orphaned,
                )
            self.writer.add_periodic(self.repl.prune_tick, 30.0)
        self.queue = FieldQueue(
            db, writer=self.writer, journal=self.journal,
            start_thread=(role == "primary"),
        )
        self.metrics = Metrics()
        # Untrusted-client hardening: the trust ledger cache (spot-check
        # sampling rates, claim profiles) and the per-client token-bucket
        # rate limiter (429s, distinct from the global 503 shed). The
        # limiter is opt-in via NICE_TPU_RATE_BUCKET="capacity:refill" —
        # with no client token the fallback key is the client IP, which
        # would throttle NAT'd fleets and the load harness if it were
        # always on. The limiter's trust multiplier reads ONLY the
        # in-memory cache — it is consulted on the event-loop thread.
        self.trust = trust_mod.TrustStore(db)
        self.limiter = None
        if knobs.RATE_BUCKET.get():
            self.limiter = TokenBucketLimiter(
                multiplier=self._bucket_multiplier
            )
        # Lease-expiry sweep: abandoned micro-field claims are released on
        # the writer thread so re-issue never waits out the global claim
        # expiry cutoff. NICE_TPU_LEASE_SWEEP_SECS=0 disables.
        sweep_secs = knobs.LEASE_SWEEP_SECS.get()
        if sweep_secs > 0 and role == "primary":
            self.writer.add_periodic(self._sweep_leases, sweep_secs)
        # Overload shed: when more than max_inflight requests are being
        # handled at once, new ones (except /metrics) get 503 + Retry-After
        # instead of queueing unboundedly behind the worker pool. Clients
        # honor the hint in retry_request.
        self.max_inflight = knobs.MAX_INFLIGHT.get()
        self.retry_after_secs = knobs.RETRY_AFTER_SECS.get()
        self._inflight = 0
        self._inflight_lock = lockdep.make_lock("server.app.ApiContext._inflight_lock")
        # Read-snapshot cache for the /status fleet block: dashboard polling
        # is served from this instead of re-running the fleet queries every
        # poll. Writes that change what the block reports (submissions,
        # telemetry) invalidate it, so tests and operators never see stale
        # data after their own write.
        self.status_cache_ttl = knobs.STATUS_CACHE_SECS.get()
        self._status_cache: dict = {}
        # Invalidation generation: bumped under the lock on every
        # invalidate so a rebuild that started before the invalidation
        # cannot store its stale block back (racelint R5; replayed by the
        # schedex status_cache_invalidate_vs_rebuild scenario).
        self._status_cache_gen = 0
        self._status_cache_lock = lockdep.make_lock("server.app.ApiContext._status_cache_lock")
        # Performance observatory: one writer-actor periodic samples every
        # nice_* series (process-global registry + this context's private
        # API-latency registry) into the in-memory ring history, persists
        # the new points into metric_history, evaluates SLO burn rates and
        # occasionally prunes retention. /history reads serve from the ring
        # — they never touch SQLite. NICE_TPU_HISTORY_SECS=0 disables.
        self.history = obs.history.HistoryStore()
        self.slo = obs.slo.SloEngine(self.history)
        # Anomaly engine: fleet-pathology detectors over the audit journal
        # + history store, evaluated on the same observatory beat.
        self.anomaly = obs.anomaly.AnomalyEngine(db, self.history)
        self.history_retention_secs = knobs.HISTORY_RETENTION_SECS.get()
        self.journal_retention_secs = knobs.JOURNAL_RETENTION_SECS.get()
        self._last_history_prune = time.monotonic()
        # Fleet critical-path engine: waterfalls + USE rollup + dominant-
        # segment classifier, evaluated on the history tick and served at
        # GET /critpath. Bottleneck shifts fan out to the stream.
        self.critpath = obs.critpath.CritpathEngine(
            db, self.writer,
            on_event=lambda kind, data: self.stream.publish(kind, data),
        )
        # SLO / anomaly state snapshots from the previous tick: history_tick
        # diffs against them to publish ONLY transitions to the stream (the
        # full states keep being served by /slo and /anomalies pulls).
        self._last_slo_states: dict = {}
        self._last_anomaly_states: dict = {}
        # Resource observatory: the ledger file is the server's dominant
        # on-disk footprint; registering it lets memwatch's disk series and
        # the exhaustion forecaster cover it. Sampling itself piggybacks on
        # the history tick below (maybe_sample throttles internally to
        # NICE_TPU_MEMWATCH_SECS) — no extra thread on the server.
        obs.memwatch.watch_path("ledger", db.path)
        # The statistical profiler serves GET /debug/profile below; with
        # NICE_TPU_PYPROF_HZ=0 this is a no-op and no thread exists.
        obs.pyprof.maybe_start()
        history_secs = obs.history.sample_interval_secs()
        if history_secs > 0 and role == "primary":
            # Standbys skip the observatory beat: metric_history rows
            # replicate in from the primary, and locally-minted rowids
            # would collide with them.
            self.writer.add_periodic(self.history_tick, history_secs)
        if role == "standby" and upstream:
            self.repl_applier = repl_mod.ReplApplier(
                db, self.writer, self.repl, hub=self.stream
            )
            self.repl_applier.start()

    def history_tick(self) -> None:
        """One observatory beat. Runs on the writer thread between batches
        (its own transaction; exceptions are logged, never fatal). Tests
        with a DirectWriter call this directly to advance history."""
        # Critical-path gauges refresh FIRST so this tick's registry sample
        # below captures them fresh instead of one interval stale.
        try:
            self.critpath.evaluate()
        except Exception:  # noqa: BLE001 — attribution must not stop the beat
            log.exception("critpath evaluation failed")
        # Resource gauges refresh before the registry sample for the same
        # reason; maybe_sample() throttles itself to NICE_TPU_MEMWATCH_SECS
        # and is a no-op (zero overhead) when the knob is 0.
        mem_summary = obs.memwatch.maybe_sample()
        if mem_summary:
            self.stream.publish("resource", mem_summary)
        self.history.sample_registries(
            [obs.REGISTRY, self.metrics.registry]
        )
        HISTORY_SAMPLES.inc()
        rows = self.history.drain_rows()
        if rows:
            HISTORY_PERSISTED_ROWS.inc(self.db.insert_metric_history(rows))
        self._publish_transitions("slo", self.slo.evaluate(), "slo",
                                  self._last_slo_states)
        self._publish_transitions("anomaly", self.anomaly.evaluate(),
                                  "detector", self._last_anomaly_states)
        now = time.monotonic()
        if now - self._last_history_prune >= 600.0:
            self._last_history_prune = now
            if self.history_retention_secs > 0:
                self.db.prune_metric_history(
                    time.time() - self.history_retention_secs
                )
            if self.journal_retention_secs > 0:
                from datetime import timedelta

                from nice_tpu.server.db import now_utc, ts

                cutoff = now_utc() - timedelta(
                    seconds=self.journal_retention_secs
                )
                self.db.prune_field_events(ts(cutoff))

    def write(self, fn, *args, **kwargs):
        """Run one mutation through the writer actor, blocking for its
        result (exceptions — notably IntegrityError — re-raise here)."""
        return self.writer.call(fn, *args, **kwargs)

    def journal(self, rows: list) -> None:
        """Append audit-journal rows through the writer actor, fire and
        forget: the audit plane never blocks a request and never fails
        one. Emission sites that already run inside a writer op call
        journal_now instead (their events commit atomically with the state
        change they describe)."""
        if not rows:
            return
        try:
            self.writer.submit(self.journal_now, rows)
        except Exception:  # noqa: BLE001 — WriterClosed during shutdown
            pass

    def journal_now(self, rows: list) -> None:
        """Append journal rows in the current transaction context (writer
        thread). Failure is contained here: append_field_events's nested
        savepoint rolls back only the journal rows, the metric + flight
        event record that evidence went missing, and the enclosing
        operation proceeds untouched."""
        if not rows:
            return
        try:
            enriched = self.db.append_field_events(rows)
        except Exception:  # noqa: BLE001 — the journal must never take
            # down the mutation it annotates
            SERVER_JOURNAL_WRITE_FAILURES.inc()
            obs.flight.record("journal_write_failed", count=len(rows))
            log.exception("audit journal append failed (%d events)", len(rows))
            return
        # Stage for the stream plane: rows fan out to SSE subscribers only
        # once the enclosing batch commits (on_batch_end flushes).
        if enriched:
            with self._stream_stage_lock:
                self._stream_staged.extend(enriched)

    def _flush_stream_staged(self, committed: bool) -> None:
        """Writer on_batch_end hook: publish staged journal rows to the SSE
        hub after COMMIT, discard them after rollback — stream subscribers
        see exactly the events that became durable."""
        with self._stream_stage_lock:
            staged, self._stream_staged[:] = list(self._stream_staged), []
        if committed and staged:
            self.stream.publish_journal_rows(staged)

    def _publish_transitions(self, kind: str, results: list, name_key: str,
                             last_states: dict) -> None:
        """Diff one engine's evaluate() output against its previous tick
        and push only the state CHANGES to the stream (dashboards get the
        edge; steady state stays pull-only)."""
        for res in results or []:
            name = res.get(name_key)
            if name is None:
                continue
            prev = last_states.get(name)
            state = res.get("state")
            if prev is not None and state != prev:
                self.stream.publish(
                    kind,
                    {"name": name, "from": prev, "to": state, **res},
                )
            last_states[name] = state

    def _bucket_multiplier(self, key: str) -> float:
        """Trusted veterans earn bigger rate-limit buckets (up to 4x).
        Cache-only read: this runs on the event-loop thread. The bucket key
        is "ip|token" for validated tokens, the bare IP otherwise."""
        row = self.trust.peek(key.rsplit("|", 1)[-1])
        if not row or row.get("suspect"):
            return 1.0
        return 1.0 + min(3.0, float(row.get("trust", 0.0)) / 25.0)

    def _sweep_leases(self) -> None:
        released = self.db.release_expired_leases()
        if released:
            self.journal_now(
                [
                    obs.journal.event_row(fid, "lease_expired")
                    for fid in released
                ]
            )
            self.invalidate_status_cache()

    def cached_fleet_block(self) -> dict:
        now = time.monotonic()
        with self._status_cache_lock:
            entry = self._status_cache.get("fleet")
            if entry is not None and now - entry[0] < self.status_cache_ttl:
                SERVER_STATUS_CACHE_EVENTS.labels("hit").inc()
                return entry[1]
            gen = self._status_cache_gen
        SERVER_STATUS_CACHE_EVENTS.labels("miss").inc()
        block = build_fleet_block(self)
        with self._status_cache_lock:
            # Store only if no invalidation landed while we built outside
            # the lock — otherwise a write that invalidated mid-build
            # would be masked by this stale block for a full TTL,
            # breaking the "never see stale data after your own write"
            # contract documented on _status_cache.
            if self._status_cache_gen == gen:  # nicelint: allow R5 (generation-checked store; schedex scenario status_cache_invalidate_vs_rebuild replays the window)
                self._status_cache["fleet"] = (time.monotonic(), block)
        return block

    def invalidate_status_cache(self) -> None:
        with self._status_cache_lock:
            self._status_cache_gen += 1
            self._status_cache.pop("fleet", None)

    def enter_request(self) -> bool:
        """Register an in-flight request; False means shed it (503).
        Used by the legacy thread-per-connection core; the async core
        tracks dispatch depth on its event loop instead."""
        with self._inflight_lock:
            self._inflight += 1
            return self._inflight <= self.max_inflight

    def exit_request(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    def promote_to_primary(self) -> int:
        """Standby → primary (POST /repl/promote, or restart without
        --standby-of). Stops the applier, epoch-bumps the ledger (fencing
        the old primary's lineage), then re-arms every primary duty the
        standby context skipped: orphan release, queue refills, lease
        sweep, observatory beat, op-log retention. Idempotent."""
        if self.repl.role == "primary":
            return self.repl.epoch
        if self.repl_applier is not None:
            self.repl_applier.stop()
            self.repl_applier = None
        epoch = self.repl.promote()
        self.role = "primary"
        orphaned = self.write(self.db.release_orphaned_inventory)
        if orphaned:
            log.info(
                "promotion released %d orphaned pre-claimed fields from"
                " the dead primary's queue inventory", orphaned,
            )
        self.queue.start()
        self.queue.refill_niceonly()
        self.queue.refill_detailed_thin()
        sweep_secs = knobs.LEASE_SWEEP_SECS.get()
        if sweep_secs > 0:
            self.writer.add_periodic(self._sweep_leases, sweep_secs)
        history_secs = obs.history.sample_interval_secs()
        if history_secs > 0:
            self.writer.add_periodic(self.history_tick, history_secs)
        self.writer.add_periodic(self.repl.prune_tick, 30.0)
        self.invalidate_status_cache()
        return epoch

    def close(self) -> None:
        if self.repl_applier is not None:
            self.repl_applier.stop()
        self.queue.close()
        self.writer.close()


class ApiError(Exception):
    def __init__(self, status: int, message: str, headers: dict | None = None):
        super().__init__(message)
        self.status = status
        self.message = message
        # Extra response headers (Retry-After on 429s); merged into the
        # error response by route_request.
        self.headers = headers or {}


def _max_claim_block() -> int:
    return max(1, knobs.MAX_CLAIM_BLOCK.get())


def _untrusted_lease_secs() -> float:
    """Lease window for claims issued to below-threshold clients: short, so
    an abandoner's fields recycle in seconds."""
    return knobs.UNTRUSTED_LEASE_SECS.get()


def _claim_lease_secs(untrusted: bool) -> float:
    """Every new claim now carries an explicit lease window (the sweep only
    touches claims that have one): trusted clients get the global claim
    expiry window, untrusted ones the short micro-lease."""
    if untrusted:
        return _untrusted_lease_secs()
    return knobs.CLAIM_EXPIRY_SECS.get(default=CLAIM_DURATION_HOURS * 3600)


def _trust_tier(ctx: ApiContext, client_token) -> str:
    """Resolved trust tier for journal events (cache-only read)."""
    if client_token is None:
        return "trusted"
    row = ctx.trust.peek(client_token)
    if row and row.get("suspect"):
        return "suspect"
    return "trusted" if ctx.trust.is_trusted(client_token) else "untrusted"


def _untrusted_max_field() -> int:
    """Range-size cap for untrusted claims (micro-fields): a forged or
    abandoned result costs at most this much honest recomputation."""
    return knobs.UNTRUSTED_MAX_FIELD.get()


def _untrusted_max_claims() -> int:
    return knobs.UNTRUSTED_MAX_CLAIMS.get()


def _untrusted_max_claims_per_ip() -> int:
    return knobs.UNTRUSTED_MAX_CLAIMS_PER_IP.get()


def _enforce_claim_cap(
    ctx: ApiContext, client_token: str, user_ip: str, requested: int
) -> int:
    """Cap outstanding (unexpired, unsubmitted) claims per untrusted client
    so a hoarder cannot lock up the frontier. A second, aggregate ceiling
    applies per source IP: identities are cheap (telemetry client_id,
    username@ip variants), so without it a single machine could hoard
    NICE_TPU_UNTRUSTED_MAX_CLAIMS once per minted identity. Returns how
    many of the requested claims fit; raises 429 when none do."""
    cap = _untrusted_max_claims()
    ip_cap = _untrusted_max_claims_per_ip()
    open_claims = ctx.db.count_open_claims(client_token)
    open_ip = ctx.db.count_open_claims_by_ip(user_ip) if user_ip else 0
    allowed = max(0, min(cap - open_claims, ip_cap - open_ip))
    if allowed == 0:
        raise ApiError(
            429,
            f"too many outstanding claims ({open_claims} open for this"
            f" client, cap {cap}; {open_ip} open for this address, cap"
            f" {ip_cap}); submit results or let the leases expire",
            headers={
                "Retry-After": str(
                    max(1, min(int(_untrusted_lease_secs()), 30))
                )
            },
        )
    return min(requested, allowed)


def _roll_claim_strategy(search_mode: SearchMode, untrusted: bool = False):
    """The 80/15/4/1 detailed strategy mix (reference api/src/main.rs:66-229);
    one roll covers a whole block. The untrusted profile keeps the mix but
    clamps the field size to micro-fields — cheap to re-issue when the
    short lease expires or a spot check disqualifies the result."""
    if search_mode == SearchMode.NICEONLY:
        max_range_size = _untrusted_max_field() if untrusted else U128_MAX
        return FieldClaimStrategy.NEXT, 0, max_range_size
    roll = random.randint(1, 100)
    if roll <= 80:
        claim_strategy, max_check_level = FieldClaimStrategy.THIN, 1
    elif roll <= 95:
        claim_strategy, max_check_level = FieldClaimStrategy.NEXT, 1
    elif roll <= 99:
        claim_strategy, max_check_level = FieldClaimStrategy.NEXT, 2
    else:
        claim_strategy, max_check_level = FieldClaimStrategy.RANDOM, 1
    max_range_size = DETAILED_SEARCH_MAX_FIELD_SIZE
    if untrusted:
        max_range_size = min(max_range_size, _untrusted_max_field())
    return claim_strategy, max_check_level, max_range_size


def _claim_fields(
    ctx: ApiContext,
    search_mode: SearchMode,
    claim_strategy: FieldClaimStrategy,
    max_check_level: int,
    max_range_size: int,
    count: int,
    base_min: int | None = None,
    base_max: int | None = None,
):
    """Pick up to count fields: queue fast path first, then the claim engine,
    then the possibly-active fallback (reference api/src/main.rs:150-168).
    Runs inside a writer-actor operation, so the pops + lease stamps of one
    block are a single transaction. Tenant base predicates (base_min /
    base_max) bypass the prefilled queues — those hold an unpredicated mix —
    and go straight to the claim engine's SQL window."""
    predicated = base_min is not None or base_max is not None
    fields = []
    if search_mode == SearchMode.NICEONLY:
        if not predicated:
            fields = ctx.queue.claim_niceonly_many(count)
        if len(fields) < count:
            if not fields and not predicated:
                log.warning("niceonly queue exhausted; direct database claim")
            fields += ctx.db._claim_batch(
                FieldClaimStrategy.NEXT,
                ctx.db.claim_expiry_cutoff(),
                0,
                max_range_size,
                count - len(fields),
                base_min=base_min,
                base_max=base_max,
            )
    else:
        if claim_strategy == FieldClaimStrategy.THIN and not predicated:
            fields = ctx.queue.claim_detailed_thin_many(count)
        if len(fields) < count:
            fields += ctx.db._claim_batch(
                claim_strategy,
                ctx.db.claim_expiry_cutoff(),
                max_check_level,
                max_range_size,
                count - len(fields),
                base_min=base_min,
                base_max=base_max,
            )
    if not fields:
        # Everything is recently claimed: fall back to possibly-active fields
        # (reference api/src/main.rs:150-168). Prefer the least-checked,
        # longest-abandoned field — re-issuing a dead client's stale cl-0
        # lease beats a redundant re-check of a completed field.
        from nice_tpu.server.db import now_utc

        fields = ctx.db._claim_batch(
            FieldClaimStrategy.NEXT, now_utc(), max_check_level,
            max_range_size, count, order_by=ctx.db.PREFER_ABANDONED,
            base_min=base_min, base_max=base_max,
        )
    return fields


def _parse_tenant_args(args: dict) -> tuple[str | None, int | None, int | None]:
    """Extract (tenant, base_min, base_max) from query params / payload.
    Tenant names are length-capped free text (they label journal rows and
    metrics); base bounds must be integers when present."""
    tenant = args.get("tenant")
    if tenant is not None:
        tenant = str(tenant).strip()[:64] or None
    bounds = []
    for key in ("base_min", "base_max"):
        raw = args.get(key)
        if raw is None or raw == "":
            bounds.append(None)
            continue
        try:
            bounds.append(int(raw))
        except (TypeError, ValueError):
            raise ApiError(400, f"{key} must be an integer, got {raw!r}")
    return tenant, bounds[0], bounds[1]


def claim_helper(
    ctx: ApiContext,
    search_mode: SearchMode,
    user_ip: str,
    client_token: str | None = None,
    tenant: str | None = None,
    base_min: int | None = None,
    base_max: int | None = None,
) -> DataToClient:
    """Claim one field (the per-field compatibility path)."""
    untrusted = client_token is not None and not ctx.trust.is_trusted(
        client_token
    )
    if untrusted:
        _enforce_claim_cap(ctx, client_token, user_ip, 1)
    claim_strategy, max_check_level, max_range_size = _roll_claim_strategy(
        search_mode, untrusted
    )
    lease_secs = _claim_lease_secs(untrusted)
    tier = _trust_tier(ctx, client_token)

    def op():
        fields = _claim_fields(
            ctx, search_mode, claim_strategy, max_check_level, max_range_size,
            1, base_min=base_min, base_max=base_max,
        )
        if not fields:
            raise ApiError(
                500,
                f"Could not find any field with maximum check level"
                f" {max_check_level} and maximum size {max_range_size}!",
            )
        field = fields[0]
        claim = ctx.db.insert_claim(
            field.field_id, search_mode, user_ip,
            client_token=client_token, lease_secs=lease_secs, tenant=tenant,
        )
        # Writer-queue wait measured at the actor (critical-path segment):
        # the claim's slice of writer_wait, mirroring submit_accepted's.
        extra = {}
        if tenant is not None:
            extra["tenant"] = tenant
        wait = writer_mod.current_op_wait_secs()
        if wait is not None:
            extra["writer_wait"] = round(wait, 6)
        ctx.journal_now([
            obs.journal.event_row(
                field.field_id, "claimed", claim_id=claim.claim_id,
                client=client_token, tier=tier,
                check_level=field.check_level, mode=search_mode.value,
                **extra,
            )
        ])
        return field, claim

    field, claim = ctx.write(op)
    if tenant is not None:
        ctx.stream.publish("sched", {
            "event": "tenant_claim", "tenant": tenant,
            "field_id": field.field_id, "claim_id": claim.claim_id,
            "mode": search_mode.value, "base": field.base,
        })
    log.info(
        "New Claim: mode=%s strategy=%s field=%d claim=%d",
        search_mode,
        claim_strategy.value,
        field.field_id,
        claim.claim_id,
    )
    return DataToClient(
        claim_id=claim.claim_id,
        base=field.base,
        range_start=field.range_start,
        range_end=field.range_end,
        range_size=field.range_size,
    )


def handle_claim_block(
    ctx: ApiContext, payload: dict, user_ip: str, headers=None
) -> dict:
    """POST /claim_block: N fields per round-trip under ONE block lease.

    The strategy mix rolls once per block; every member claim row carries the
    same block_id, so one /renew_claim {block_id} heartbeat re-arms all of
    them and — because their last_claim_time is stamped and renewed together
    — expiry releases the whole block at once. A partial block (fewer fields
    than asked) is success, not an error. Untrusted clients get the
    micro-field profile: clamped field size, short lease, and a cap on
    outstanding claims (429 once they hoard up to it)."""
    mode_arg = payload.get("mode") or payload.get("search_mode")
    if mode_arg not in ("detailed", "niceonly"):
        raise ApiError(400, f"mode must be detailed or niceonly, got {mode_arg!r}")
    search_mode = (
        SearchMode.DETAILED if mode_arg == "detailed" else SearchMode.NICEONLY
    )
    try:
        count = int(payload.get("count", 8))
    except (TypeError, ValueError):
        raise ApiError(400, f"count must be an integer, got {payload.get('count')!r}")
    count = max(1, min(count, _max_claim_block()))
    client_token = trust_mod.resolve_token(
        payload, headers, str(payload.get("username") or ""), user_ip,
        store=ctx.trust,
    )
    untrusted = not ctx.trust.is_trusted(client_token)
    if untrusted:
        count = _enforce_claim_cap(ctx, client_token, user_ip, count)
    claim_strategy, max_check_level, max_range_size = _roll_claim_strategy(
        search_mode, untrusted
    )
    lease_secs = _claim_lease_secs(untrusted)
    tier = _trust_tier(ctx, client_token)
    tenant, base_min, base_max = _parse_tenant_args(payload)

    def op():
        fields = _claim_fields(
            ctx, search_mode, claim_strategy, max_check_level, max_range_size,
            count, base_min=base_min, base_max=base_max,
        )
        if not fields:
            raise ApiError(
                500,
                f"Could not find any field with maximum check level"
                f" {max_check_level} and maximum size {max_range_size}!",
            )
        block_id = secrets.token_hex(12)
        claims = ctx.db.insert_claims_block(
            [f.field_id for f in fields], search_mode, user_ip, block_id,
            client_token=client_token, lease_secs=lease_secs, tenant=tenant,
        )
        extra = {}
        if tenant is not None:
            extra["tenant"] = tenant
        wait = writer_mod.current_op_wait_secs()
        if wait is not None:
            extra["writer_wait"] = round(wait, 6)
        ctx.journal_now([
            obs.journal.event_row(
                field.field_id, "block_claimed", claim_id=claim.claim_id,
                client=client_token, tier=tier,
                check_level=field.check_level, block=block_id,
                mode=search_mode.value,
                **extra,
            )
            for field, claim in zip(fields, claims)
        ])
        return block_id, fields, claims

    block_id, fields, claims = ctx.write(op)
    if tenant is not None:
        ctx.stream.publish("sched", {
            "event": "tenant_block_claim", "tenant": tenant,
            "block_id": block_id, "fields": len(fields),
            "mode": search_mode.value,
        })
    SERVER_BLOCK_LEASE_SIZE.observe(len(fields))
    log.info(
        "New Block Claim: mode=%s strategy=%s block=%s fields=%d",
        search_mode, claim_strategy.value, block_id, len(fields),
    )
    return {
        "block_id": block_id,
        "fields": [
            DataToClient(
                claim_id=claim.claim_id,
                base=field.base,
                range_start=field.range_start,
                range_end=field.range_end,
                range_size=field.range_size,
            ).to_json()
            for claim, field in zip(claims, fields)
        ],
    }


@dataclasses.dataclass
class PreparedSubmission:
    """Everything _verify_submission learns about one submission, carried to
    the persist step and the post-accept trust flow (spot check, trust
    upsert, streaming consensus). persist is None for the exactly-once
    replay read-hit; otherwise it returns the new submission id."""

    data: DataToServer
    claim: object = None
    persist: object = None
    elapsed_secs: float = 0.0
    mode_label: str = ""
    client_token: str = ""
    trusted: bool = True
    field: object = None
    distribution_expanded: object = None
    numbers_expanded: object = None
    submit_key: str = ""


def _submit_duplicate_reply(ctx: ApiContext, data: DataToServer) -> dict:
    SERVER_DUPLICATE_SUBMITS.inc()
    try:
        claim = ctx.db.get_claim_by_id(data.claim_id)
    except KeyError:
        claim = None
    if claim is not None:
        ctx.journal([
            obs.journal.event_row(
                claim.field_id, "submit_duplicate", claim_id=data.claim_id,
                submit_id=data.submit_id,
            )
        ])
    log.info(
        "Duplicate Submission replay: claim=%d submit_id=%s answered "
        "idempotently", data.claim_id, data.submit_id,
    )
    return {"status": "OK", "duplicate": True}


def _verify_submission(
    ctx: ApiContext, payload: dict, user_ip: str, headers=None
) -> PreparedSubmission:
    """Read-side verification of one submission; returns a
    PreparedSubmission whose persist closure is the mutation to run through
    the writer (None = already accepted, the exactly-once replay read-hit).
    Raises ApiError on rejection.

    Exactly-once: when the payload carries a submit_id (claim + content
    hash) that is already persisted, the reply is {"duplicate": true} and no
    second row is inserted — a client that lost the first 200 (dropped
    response, crash between submit and ack) can replay safely. The fast
    path is a read; the partial unique index on submissions.submit_id closes
    the check-then-insert race between two concurrent replays."""
    data = DataToServer.from_json(payload)
    if data.submit_id:
        if ctx.db.get_submission_by_submit_id(data.submit_id) is not None:
            return PreparedSubmission(data=data)
    try:
        claim = ctx.db.get_claim_by_id(data.claim_id)
    except KeyError as e:
        raise ApiError(400, f"Invalid claim_id {data.claim_id}: {e}")
    field = ctx.db.get_field_by_id(claim.field_id)
    base = field.base
    numbers_expanded = number_stats.expand_numbers(data.nice_numbers, base)
    # Wall-clock the client spent on the field (claim -> submit), recorded
    # for the per-field performance analytics the schema column exists for.
    from nice_tpu.server.db import now_utc

    elapsed_secs = max(0.0, (now_utc() - claim.claim_time).total_seconds())
    # Late-submit conflict: results on an expired lease whose field was
    # already re-issued to another client are discarded (409) — the second
    # lease owns the field now, and accepting both would double-count the
    # range. A late submit with NO conflict is still accepted, preserving
    # the legacy slow-but-honest path.
    if (
        claim.lease_expiry is not None
        and now_utc() > claim.lease_expiry
        and ctx.db.has_conflicting_claim(
            claim.field_id, claim.claim_id, claim.lease_expiry
        )
    ):
        raise ApiError(
            409,
            f"claim {claim.claim_id} lease expired and field"
            f" {claim.field_id} was re-issued; results discarded",
        )
    client_token = trust_mod.resolve_token(
        payload, headers, data.username, user_ip, store=ctx.trust
    )
    trusted = ctx.trust.is_trusted(client_token)
    submit_key = data.submit_id or f"claim-{data.claim_id}"

    if claim.search_mode == SearchMode.NICEONLY:
        # Honor system at accept time (reference api/src/main.rs:278-300);
        # the post-accept spot check is the only verification this mode
        # ever gets.
        def persist():
            sid = ctx.db.insert_submission(
                claim, data.username, data.client_version, user_ip, None,
                numbers_expanded, elapsed_secs=elapsed_secs,
                submit_id=data.submit_id, client_token=client_token,
            )
            if field.check_level == 0:
                ctx.db.update_field_canon_and_cl(
                    field.field_id, field.canon_submission_id, 1
                )
            _journal_submit_accepted(
                ctx, field, data.claim_id, client_token, trusted,
                "niceonly", sid,
            )
            return sid

        return PreparedSubmission(
            data=data, claim=claim, persist=persist,
            elapsed_secs=elapsed_secs, mode_label="niceonly",
            client_token=client_token, trusted=trusted, field=field,
            distribution_expanded=None, numbers_expanded=numbers_expanded,
            submit_key=submit_key,
        )

    if data.unique_distribution is None:
        raise ApiError(
            422, "Unique distribution must be present for detailed searches."
        )
    distribution = data.unique_distribution
    distribution_expanded = distribution_stats.expand_distribution(
        distribution, base
    )
    dist_total = sum(d.count for d in distribution)
    if dist_total != field.range_size:
        raise ApiError(
            422,
            f"Total distribution count is incorrect (submitted {dist_total},"
            f" range was {field.range_size}).",
        )
    cutoff = number_stats.get_near_miss_cutoff(base)
    for d in distribution_expanded:
        if d.num_uniques > cutoff:
            count_numbers = sum(
                1 for n in numbers_expanded if n.num_uniques == d.num_uniques
            )
            if count_numbers != d.count:
                raise ApiError(
                    422,
                    f"Count of nice numbers with {d.num_uniques} uniques does"
                    f" not match distribution (submitted {count_numbers},"
                    f" distribution claimed {d.count}).",
                )
    above_cutoff = sum(d.count for d in distribution if d.num_uniques > cutoff)
    if len(numbers_expanded) != above_cutoff:
        raise ApiError(
            422,
            f"Count of nice numbers does not match distribution (submitted"
            f" {len(numbers_expanded)}, distribution claimed {above_cutoff}).",
        )
    # Server-side recomputation of every submitted number with the trusted
    # engine (reference api/src/main.rs:350-359).
    for n in numbers_expanded:
        calculated = scalar.get_num_unique_digits(n.number, base)
        if calculated != n.num_uniques:
            raise ApiError(
                422,
                f"Unique count for {n.number} is incorrect (submitted as"
                f" {n.num_uniques}, server calculated {calculated}).",
            )

    def persist():
        sid = ctx.db.insert_submission(
            claim,
            data.username,
            data.client_version,
            user_ip,
            distribution_expanded,
            numbers_expanded,
            elapsed_secs=elapsed_secs,
            submit_id=data.submit_id,
            client_token=client_token,
        )
        if trusted:
            if field.check_level < 2:
                ctx.db.update_field_canon_and_cl(
                    field.field_id, field.canon_submission_id, 2
                )
        else:
            # Needs consensus: an untrusted client alone never makes canon.
            # check_level 1 keeps the field below the detailed bar, and
            # clearing the lease puts it straight back in the claim pool so
            # an independent client picks it up; the post-accept streaming
            # consensus promotes canon once two submissions agree.
            if field.check_level == 0:
                ctx.db.update_field_canon_and_cl(
                    field.field_id, field.canon_submission_id, 1
                )
            if field.check_level <= 1:
                ctx.db.release_field_claims([field.field_id])
        _journal_submit_accepted(
            ctx, field, data.claim_id, client_token, trusted,
            "detailed", sid,
        )
        return sid

    return PreparedSubmission(
        data=data, claim=claim, persist=persist, elapsed_secs=elapsed_secs,
        mode_label="detailed", client_token=client_token, trusted=trusted,
        field=field, distribution_expanded=distribution_expanded,
        numbers_expanded=numbers_expanded, submit_key=submit_key,
    )


def _journal_submit_accepted(
    ctx: ApiContext, field, claim_id: int, client_token, trusted: bool,
    mode_label: str, submission_id: int,
) -> None:
    """Journal rows for one accepted submission, called from INSIDE the
    persist closure so the events commit atomically with the ledger change.
    A trusted detailed submission that advances the field past the detailed
    bar also lands its canon_promoted event here — the promotion and its
    evidence are one commit."""
    tier = _trust_tier(ctx, client_token)
    # Critical-path stamp: running inside the persist closure means we are
    # ON the writer thread, mid-op — current_op_wait_secs() is this very
    # submission's measured enqueue->begin queue wait, the writer_wait
    # segment of the field's waterfall (measured at the actor, not inferred
    # from endpoint latency).
    extra = {}
    wait = writer_mod.current_op_wait_secs()
    if wait is not None:
        extra["writer_wait"] = round(wait, 6)
    rows = [
        obs.journal.event_row(
            field.field_id, "submit_accepted",
            claim_id=claim_id, client=client_token,
            tier=tier, check_level=field.check_level,
            submission=submission_id, mode=mode_label,
            **extra,
        )
    ]
    if mode_label == "detailed" and trusted and field.check_level < 2:
        rows.append(
            obs.journal.event_row(
                field.field_id, "canon_promoted",
                claim_id=claim_id, client=client_token,
                tier=tier, check_level=2, submission=submission_id,
                via="trusted_submit",
            )
        )
    ctx.journal_now(rows)


def _journal_submit_rejected(ctx: ApiContext, payload, err: ApiError) -> None:
    """Best-effort submit_rejected event: the field is resolved through the
    payload's claim id; an unresolvable claim has no timeline to annotate
    and is skipped silently."""
    try:
        claim = ctx.db.get_claim_by_id(int(payload.get("claim_id")))
    except (KeyError, TypeError, ValueError):
        return
    ctx.journal([
        obs.journal.event_row(
            claim.field_id, "submit_rejected", claim_id=claim.claim_id,
            status=err.status, reason=err.message[:200],
        )
    ])


def _submit_accounting(
    ctx: ApiContext, data: DataToServer, claim, mode_label: str,
    elapsed_secs: float, user_ip: str,
) -> None:
    """Post-commit metrics / telemetry / flight-record for one accepted
    submission (runs on the handler thread, never the writer)."""
    SERVER_FIELD_ELAPSED.labels(mode_label).observe(elapsed_secs)
    if data.telemetry is not None:
        # Piggybacked fleet snapshot: persisted after the submission so a
        # malformed snapshot can never reject valid results.
        _persist_telemetry(ctx, data.telemetry, user_ip, "submission")
    obs.flight.record(
        "submit", claim=data.claim_id, field=claim.field_id,
        mode=mode_label, elapsed_secs=round(elapsed_secs, 3),
    )
    log.info(
        "New Submission: mode=%s field=%d claim=%d username=%s%s",
        claim.search_mode,
        claim.field_id,
        claim.claim_id,
        data.username,
        f" backend_downgrades={data.backend_downgrades}"
        if data.backend_downgrades else "",
    )


def _streaming_consensus(ctx: ApiContext, field_id: int) -> None:
    """Submit-path consensus for untrusted submissions: re-evaluate the
    field immediately (reads committed state, one conditional write) so
    agreement between two independent clients promotes canon without
    waiting for the jobs runner. A hold — untrusted data still awaiting
    corroboration — bumps nice_server_consensus_holds_total."""
    field = ctx.db.get_field_by_id(field_id)
    subs = ctx.db.get_detailed_submissions_by_field(field_id)
    untrusted_ids = frozenset(
        s.submission_id
        for s in subs
        if s.client_token is not None
        and not ctx.trust.is_trusted(s.client_token)
    )
    canon, cl = consensus.evaluate_consensus(field, subs, untrusted_ids)
    canon_id = canon.submission_id if canon is not None else None
    if canon_id != field.canon_submission_id or cl != field.check_level:
        ctx.write(
            ctx.db.update_field_canon_and_cl, field_id, canon_id, cl
        )
        if canon_id is not None and canon_id != field.canon_submission_id:
            ctx.journal([
                obs.journal.event_row(
                    field_id, "canon_promoted", check_level=cl,
                    submission=canon_id, via="consensus",
                )
            ])
        ctx.invalidate_status_cache()
        log.info(
            "streaming consensus: field=%d canon=%s cl=%d (%d submissions)",
            field_id, canon_id, cl, len(subs),
        )
    else:
        SERVER_CONSENSUS_HOLDS.inc()
        obs.flight.record(
            "consensus_hold", field=field_id, cl=field.check_level,
            submissions=len(subs), untrusted=len(untrusted_ids),
        )
        ctx.journal([
            obs.journal.event_row(
                field_id, "consensus_hold", check_level=field.check_level,
                submissions=len(subs), untrusted=len(untrusted_ids),
            )
        ])


def _post_accept_trust(
    ctx: ApiContext, prep: PreparedSubmission, submission_id: int
) -> None:
    """Spot verification + trust accounting for one ACCEPTED submission.

    The check itself is pure compute on the handler thread (a seeded random
    slice re-run on the trusted scalar engine). Pass/skip costs exactly one
    DB write — the trust upsert through the writer actor. Fail is off the
    hot path by definition: slash trust, mark suspect, disqualify the
    submission, and requeue the field, all in one writer op."""
    verdict, detail = trust_mod.run_spot_check(
        ctx.trust, prep.client_token, prep.submit_key, prep.field.base,
        prep.field.range_start, prep.field.range_end,
        prep.distribution_expanded, prep.numbers_expanded,
    )
    if verdict == "fail":
        SERVER_TRUST_SLASHES.inc()
        obs.flight.record(
            "trust_slash", client=prep.client_token,
            submission=submission_id, field=prep.field.field_id,
        )

        def slash_op():
            row = ctx.db.upsert_client_trust(
                prep.client_token, accepted_delta=1, failed_delta=1,
                slash=True, suspect=True,
            )
            ctx.db.disqualify_submission(submission_id)
            ctx.db.requeue_disqualified_fields(
                submission_ids=[submission_id]
            )
            return row

        row = ctx.write(slash_op)
        ctx.trust.update(row)
        ctx.invalidate_status_cache()
        ctx.journal([
            obs.journal.event_row(
                prep.field.field_id, "spot_check",
                claim_id=prep.data.claim_id, client=prep.client_token,
                tier="suspect", verdict="fail", submission=submission_id,
            ),
            obs.journal.event_row(
                prep.field.field_id, "disqualified",
                claim_id=prep.data.claim_id, client=prep.client_token,
                tier="suspect", submission=submission_id,
                reason="spot_check_fail",
            ),
            obs.journal.event_row(
                prep.field.field_id, "requeued",
                claim_id=prep.data.claim_id, client=prep.client_token,
                tier="suspect",
            ),
        ])
        obs.flight.record(
            "spot_check_fail", client=prep.client_token,
            submission=submission_id, field=prep.field.field_id,
            detail=detail[:200],
        )
        log.warning(
            "submission %d disqualified by spot check (client %s): %s",
            submission_id, prep.client_token, detail,
        )
        return
    row = ctx.write(
        ctx.db.upsert_client_trust, prep.client_token,
        trust_delta=1.0 if verdict == "pass" else 0.0,
        accepted_delta=1,
        passed_delta=1 if verdict == "pass" else 0,
    )
    ctx.trust.update(row)
    if verdict == "pass":
        ctx.journal([
            obs.journal.event_row(
                prep.field.field_id, "spot_check",
                claim_id=prep.data.claim_id, client=prep.client_token,
                tier=_trust_tier(ctx, prep.client_token), verdict="pass",
                submission=submission_id,
            )
        ])
    if not prep.trusted and prep.mode_label == "detailed":
        _streaming_consensus(ctx, prep.field.field_id)


def handle_submit(
    ctx: ApiContext, payload: dict, user_ip: str, headers=None
) -> dict:
    """Verify + persist a submission (reference api/src/main.rs:241-404)."""
    try:
        prep = _verify_submission(ctx, payload, user_ip, headers)
    except ApiError as e:
        _journal_submit_rejected(ctx, payload, e)
        raise
    if prep.persist is None:
        return _submit_duplicate_reply(ctx, prep.data)
    try:
        submission_id = ctx.write(prep.persist)
    except sqlite3.IntegrityError:
        return _submit_duplicate_reply(ctx, prep.data)
    ctx.invalidate_status_cache()
    _submit_accounting(
        ctx, prep.data, prep.claim, prep.mode_label, prep.elapsed_secs,
        user_ip,
    )
    _post_accept_trust(ctx, prep, submission_id)
    return {"status": "OK"}


def handle_submit_block(
    ctx: ApiContext, payload: dict, user_ip: str, headers=None
) -> dict:
    """POST /submit_block: batched results for a block claim.

    Verification runs per item on the handler thread; all surviving persists
    execute as ONE writer-actor operation, each under its own savepoint, so
    a duplicate or failure in one item never rolls back its siblings
    (exactly-once submit_id semantics hold per field inside the block). The
    reply carries one result per submitted item, in order."""
    subs = payload.get("submissions")
    if not isinstance(subs, list) or not subs:
        raise ApiError(400, "submissions must be a non-empty list")
    if len(subs) > _max_claim_block():
        raise ApiError(
            400, f"too many submissions in one block (max {_max_claim_block()})"
        )
    prepared: list = []
    for item in subs:
        if not isinstance(item, dict):
            prepared.append(ApiError(400, "each submission must be an object"))
            continue
        try:
            prepared.append(_verify_submission(ctx, item, user_ip, headers))
        except ApiError as e:
            _journal_submit_rejected(ctx, item, e)
            prepared.append(e)

    def batch_op():
        outcomes = []
        for prep in prepared:
            if isinstance(prep, ApiError):
                outcomes.append(("rejected", None))
                continue
            if prep.persist is None:
                outcomes.append(("duplicate", None))
                continue
            try:
                # Per-item savepoint: a duplicate replay (IntegrityError)
                # rolls back this item only.
                with ctx.db._lock, ctx.db._txn():
                    sid = prep.persist()
                outcomes.append(("accepted", sid))
            except sqlite3.IntegrityError:
                outcomes.append(("duplicate", None))
        return outcomes

    outcomes = ctx.write(batch_op)
    ctx.invalidate_status_cache()
    results = []
    counts = {"accepted": 0, "duplicates": 0, "rejected": 0}
    for prep, (outcome, sid) in zip(prepared, outcomes):
        if isinstance(prep, ApiError):
            counts["rejected"] += 1
            results.append(
                {"status": "error", "code": prep.status, "message": prep.message}
            )
            continue
        if outcome == "duplicate":
            counts["duplicates"] += 1
            results.append(_submit_duplicate_reply(ctx, prep.data))
        else:
            counts["accepted"] += 1
            _submit_accounting(
                ctx, prep.data, prep.claim, prep.mode_label,
                prep.elapsed_secs, user_ip,
            )
            _post_accept_trust(ctx, prep, sid)
            results.append({"status": "OK"})
    if isinstance(payload.get("telemetry"), dict):
        # Block-level piggyback: one snapshot per block, not per field.
        _persist_telemetry(ctx, payload["telemetry"], user_ip, "submission")
    return {"status": "OK", "results": results, **counts}


def handle_renew_claim(ctx: ApiContext, payload: dict) -> dict:
    """Claim-lease heartbeat: a client mid-scan re-arms its field's lease so
    the expiry predicate never hands the field to another client while this
    one is (provably) still alive. Submission elapsed time still measures
    from the original claim (renewal touches only fields.last_claim_time).

    With {"block_id": ...} the heartbeat renews EVERY member of a block
    claim in one statement."""
    from nice_tpu.server.db import ts

    block_id = payload.get("block_id")
    if block_id is not None:
        if not isinstance(block_id, str) or not block_id:
            raise ApiError(400, "block_id must be a non-empty string")
        renewed_at, count = ctx.write(ctx.db.renew_block, block_id)
        if count == 0:
            raise ApiError(404, f"Invalid block_id {block_id!r}")
        ctx.journal([
            obs.journal.event_row(
                c.field_id, "renewed", claim_id=c.claim_id, block=block_id,
            )
            for c in ctx.db.get_block_claims(block_id)
        ])
        return {
            "status": "OK", "renewed_at": ts(renewed_at), "renewed": count,
        }
    claim_id = payload.get("claim_id")
    if not isinstance(claim_id, int):
        raise ApiError(400, "claim_id must be an integer")
    try:
        renewed_at = ctx.write(ctx.db.renew_claim, claim_id)
    except KeyError as e:
        raise ApiError(404, f"Invalid claim_id {claim_id}: {e}")
    try:
        claim = ctx.db.get_claim_by_id(claim_id)
        ctx.journal([
            obs.journal.event_row(
                claim.field_id, "renewed", claim_id=claim_id,
            )
        ])
    except KeyError:
        pass
    return {"status": "OK", "renewed_at": ts(renewed_at)}


def _persist_telemetry(
    ctx: ApiContext, snap, user_ip: str, source: str
) -> bool:
    """Upsert one client snapshot (through the writer actor); False (never
    an error) when the snapshot is unusable — telemetry is best-effort on
    both sides of the wire."""
    if not isinstance(snap, dict):
        return False
    try:
        ctx.write(ctx.db.upsert_client_telemetry, snap, user_ip)
    except (ValueError, sqlite3.Error) as e:
        log.warning("discarding bad telemetry snapshot (%s): %s", source, e)
        return False
    # Client-side lifecycle events (ckpt save/resume, downgrades, spool
    # replays) piggyback on the snapshot; merge them into the same
    # field_events timelines, keyed claim -> field (clients never learn
    # raw field ids).
    rows = obs.journal.client_event_rows(
        snap,
        client=str(snap.get("client_id") or "") or None,
        resolve_claim=lambda cid: _field_for_claim(ctx, cid),
    )
    if rows:
        ctx.journal(rows)
    SERVER_TELEMETRY_REPORTS.labels(source).inc()
    ctx.invalidate_status_cache()
    return True


def _field_for_claim(ctx: ApiContext, claim_id: int):
    try:
        return ctx.db.get_claim_by_id(claim_id).field_id
    except KeyError:
        return None


def handle_telemetry(ctx: ApiContext, payload: dict, user_ip: str) -> dict:
    """POST /telemetry — the fleet heartbeat. Body is one obs.telemetry
    snapshot; the row is upserted by client_id, so a client reporting every
    minute costs one row, not one per report."""
    if not _persist_telemetry(ctx, payload, user_ip, "heartbeat"):
        raise ApiError(400, "body must be a telemetry snapshot with client_id")
    return {"status": "OK"}


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return round(float(sorted_vals[idx]), 3)


def fleet_active_secs() -> float:
    return knobs.FLEET_ACTIVE_SECS.get()


def build_fleet_block(ctx: ApiContext) -> dict:
    """The /status `fleet` block: claim health + per-client telemetry rolled
    up across the fleet. Side effect: refreshes the nice_fleet_* gauges so a
    /metrics scrape right after /status agrees with it. Served through
    ctx.cached_fleet_block (short TTL + invalidation on submissions and
    telemetry), so dashboard polling does not re-run these queries."""
    clients = ctx.db.get_client_telemetry(fleet_active_secs())
    claim_stats = ctx.db.get_fleet_claim_stats()
    elapsed = sorted(ctx.db.get_recent_field_elapsed())
    p50 = _percentile(elapsed, 0.50)
    p95 = _percentile(elapsed, 0.95)

    backends: dict = {}
    fields_by_mode = {"detailed": 0, "niceonly": 0}
    numbers = 0
    rate = downgrades = restores = faults_total = spool_depth = 0
    mesh_devices = mesh_reshards = mesh_idle_count = 0
    mesh_idle_sum = 0.0
    for c in clients:
        if c["backend"]:
            backends[c["backend"]] = backends.get(c["backend"], 0) + 1
        fields_by_mode["detailed"] += c["fields_detailed"]
        fields_by_mode["niceonly"] += c["fields_niceonly"]
        numbers += int(c["numbers_total"])
        rate += c["numbers_per_sec"]
        downgrades += c["downgrades"]
        restores += c["restores"]
        faults_total += c["faults"]
        spool_depth += c["spool_depth"]
        mesh_devices += c.get("mesh_devices", 0)
        mesh_reshards += c.get("mesh_reshards", 0)
        mesh_idle_sum += c.get("mesh_feed_idle_sum", 0.0)
        mesh_idle_count += c.get("mesh_feed_idle_count", 0)

    FLEET_CLIENTS.set(len(clients))
    FLEET_FIELDS.labels("detailed").set(fields_by_mode["detailed"])
    FLEET_FIELDS.labels("niceonly").set(fields_by_mode["niceonly"])
    FLEET_NUMBERS.set(float(numbers))
    FLEET_RATE.set(rate)
    FLEET_DOWNGRADES.set(downgrades)
    FLEET_RESTORES.set(restores)
    FLEET_FAULTS.set(faults_total)
    FLEET_SPOOL_DEPTH.set(spool_depth)
    FLEET_MESH_DEVICES.set(mesh_devices)
    FLEET_MESH_RESHARDS.set(mesh_reshards)
    FLEET_FIELD_LATENCY.labels("0.5").set(p50)
    FLEET_FIELD_LATENCY.labels("0.95").set(p95)

    requests: dict = {}
    errors = 0
    for (endpoint, status), count in ctx.metrics.request_counts().items():
        requests[endpoint] = requests.get(endpoint, 0) + int(count)
        if status.startswith(("4", "5")):
            errors += int(count)

    threshold = trust_mod.trust_threshold()
    tiers = ctx.db.get_trust_summary(threshold)
    for tier, n in tiers.items():
        SERVER_TRUST_CLIENTS.labels(tier).set(n)
    spot_checks = {
        verdict: int(count)
        for (verdict,), count in SERVER_SPOT_CHECKS.values().items()
    }
    trust_block = {
        "threshold": threshold,
        "tiers": tiers,
        "spot_checks": spot_checks,
        "trust_slashes": int(SERVER_TRUST_SLASHES.value()),
        "consensus_holds": int(SERVER_CONSENSUS_HOLDS.value()),
        "rate_limited": int(SERVER_RATE_LIMITED.value()),
        "leases_expired": int(SERVER_LEASES_EXPIRED.value()),
    }
    return {
        "active_secs": fleet_active_secs(),
        "clients": clients,
        "client_count": len(clients),
        "backends": backends,
        "fields": fields_by_mode,
        "numbers_total": str(numbers),
        "numbers_per_sec": round(rate, 3),
        "downgrades": downgrades,
        "checkpoint_restores": restores,
        "faults_injected": faults_total,
        "spool_depth": spool_depth,
        "mesh_devices": mesh_devices,
        "mesh_reshards": mesh_reshards,
        "mesh_feed_idle_mean_ms": round(
            1000.0 * mesh_idle_sum / mesh_idle_count, 3
        ) if mesh_idle_count else 0.0,
        "field_seconds_p50": p50,
        "field_seconds_p95": p95,
        "requests": requests,
        "error_responses": errors,
        "trust": trust_block,
        **claim_stats,
    }


def handle_disqualify(ctx: ApiContext, payload: dict, headers) -> dict:
    """Admin disqualification: removes a user's (or one submission's) results
    from consensus and the caches without deleting the audit trail (the
    reference's abuse/consensus story depends on this flag). Gated by a
    shared secret: requests must carry X-Admin-Key matching NICE_ADMIN_KEY;
    with no key configured the endpoint is disabled."""
    import hmac
    import os

    configured = os.environ.get("NICE_ADMIN_KEY", "")
    provided = headers.get("X-Admin-Key", "")
    if not configured or not hmac.compare_digest(configured, provided):
        raise ApiError(403, "admin endpoint disabled or bad key")
    if "submission_id" in payload:
        try:
            submission_id = int(payload["submission_id"])
        except (TypeError, ValueError):
            raise ApiError(
                400, f"Invalid submission_id {payload['submission_id']!r}"
            )

        def op():
            try:
                field_id = ctx.db.get_submission_by_id(
                    submission_id
                ).field_id
            except KeyError:
                field_id = None
            changed = ctx.db.disqualify_submission(submission_id)
            requeued = ctx.db.requeue_disqualified_fields(
                submission_ids=[submission_id]
            )
            if changed and field_id is not None:
                ctx.journal_now([
                    obs.journal.event_row(
                        field_id, "disqualified", submission=submission_id,
                        reason="admin",
                    ),
                    obs.journal.event_row(
                        field_id, "requeued", reason="admin",
                    ),
                ])
            return changed, requeued

    elif "username" in payload:
        username = str(payload["username"])

        def op():
            changed = ctx.db.disqualify_user(username)
            requeued = ctx.db.requeue_disqualified_fields(username=username)
            return changed, requeued

    else:
        raise ApiError(400, "body must contain submission_id or username")
    # Requeue rides in the same writer op as the disqualification: fields
    # whose canon was just disqualified drop back to the claim pool instead
    # of staying stranded at a check_level their live submissions no longer
    # support.
    changed, requeued = ctx.write(op)
    ctx.write(ctx.db.refresh_search_caches)
    ctx.invalidate_status_cache()
    return {"status": "OK", "disqualified": changed, "requeued": requeued}


NOT_FOUND_MESSAGE = (
    "The requested resource could not be found. Available resources include"
    " /claim/detailed, /claim/niceonly, /claim/validate, and /submit."
)

# Path segments that may name a handler span. Everything else collapses to
# "static" (file-like) or "other" so arbitrary 404 probes cannot mint
# unbounded label values in the span-duration histogram.
_SPAN_SEGS = frozenset(
    {"claim", "claim_block", "submit", "submit_block", "renew_claim",
     "status", "metrics", "stats", "query", "telemetry", "debug", "admin",
     "root", "token", "history", "fields", "events", "critpath", "repl",
     "profile"}
)


def _check_repl_key(request: Request) -> None:
    """Optional shared-secret gate for the replication surface: op rows
    carry raw user_ip (which public_query redacts), so NICE_TPU_REPL_KEY
    should be set before exposing /repl/* beyond a trusted network."""
    key = knobs.REPL_KEY.get()
    if key and request.headers.get("X-Repl-Key") != key:
        raise ApiError(403, "replication surface requires X-Repl-Key")


def _is_write(method: str, path: str) -> bool:
    """Requests the epoch fence applies to: everything that mutates the
    ledger. /query POST is read-only SQL; /claim/validate hands out a
    shared validation field without claiming; /repl/* is the replication
    control surface itself (promotion must work on a standby)."""
    if method == "POST":
        return path != "/query" and not path.startswith("/repl/")
    if method == "GET":
        return path.startswith("/claim/") and path != "/claim/validate"
    return False

_CORS_HEADERS = {
    # CORS fairing parity (reference helpers.rs:95-126)
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
    "Access-Control-Allow-Headers": "Content-Type",
}


def _json_response(
    status: int, body, content_type: str = "application/json",
    extra_headers: dict | None = None,
) -> Response:
    raw = body.encode() if isinstance(body, str) else json.dumps(body).encode()
    headers = {"Content-Type": content_type, **_CORS_HEADERS}
    if extra_headers:
        headers.update(extra_headers)
    return Response(status=status, headers=headers, body=raw)


def _stamp_epoch(ctx: ApiContext, body: dict) -> dict:
    """Write responses carry the server's fencing epoch so clients learn a
    promotion from their very next successful write (from_json parsers read
    keys by name — the extra key is inert for old clients)."""
    if isinstance(body, dict):
        body.setdefault("epoch", ctx.repl.epoch)
    return body


def _error_response(status: int, message: str, extra_headers=None) -> Response:
    return _json_response(
        status, {"error": {"code": status, "message": message}},
        extra_headers=extra_headers,
    )


def overload_response(ctx: ApiContext, endpoint: str) -> Response:
    SERVER_OVERLOAD_RESPONSES.inc()
    ctx.metrics.record(endpoint, 503, 0.0)
    return _error_response(
        503,
        f"server overloaded (> {ctx.max_inflight} requests in flight);"
        " retry later",
        extra_headers={"Retry-After": str(ctx.retry_after_secs)},
    )


def rate_limit_check(ctx: ApiContext, request: Request):
    """Per-client token-bucket admission, consulted on EVERY request (loop
    thread on the async core, handler thread on the legacy core): None =
    pass, else the 429 + Retry-After response. Distinct from the global 503
    shed — a single flooder exhausts only its own buckets. /metrics and CORS
    preflights are exempt, mirroring the shed. No-op unless the operator
    enabled limiting with NICE_TPU_RATE_BUCKET."""
    if ctx.limiter is None:
        return None
    path = urlparse(request.target).path.rstrip("/")
    if path == "/metrics" or request.method == "OPTIONS":
        return None
    ip = request.client_ip or "anon"
    token = request.headers.get("X-Client-Token")
    # A header token earns its own bucket only when the server knows it
    # (cache-only check — this runs on the event-loop thread, where the DB
    # is off-limits), and the bucket is still scoped by source IP. Unknown
    # bearer strings all share the plain per-IP bucket, so minting fresh
    # tokens cannot mint fresh rate-limit budget.
    if token and ctx.trust.peek_known(str(token)[:256]):
        key = f"{ip}|{str(token)[:256]}"
    else:
        key = ip
    allowed, retry_after = ctx.limiter.allow(key, path)
    if allowed:
        return None
    SERVER_RATE_LIMITED.inc()
    ctx.metrics.record(path or "/", 429, 0.0)
    return _error_response(
        429,
        "rate limit exceeded for this client; slow down",
        extra_headers={"Retry-After": str(max(1, int(retry_after + 0.999)))},
    )


def _parse_json_body(request: Request) -> dict:
    try:
        return json.loads(request.body)
    except json.JSONDecodeError as e:
        raise ApiError(400, f"Invalid JSON body: {e}")


def _static_response(path: str):
    """Serve the analytics dashboard + browser search page from web/
    (the reference hosts these as a separate static site; co-hosting
    them keeps the single-binary deployment simple).

    The web/ tree ships in checkouts, the sdist, and the docker
    image, but NOT the wheel (it lives outside the package); a
    wheel-installed server degrades to API-only with one logged
    warning rather than silently 404ing."""
    candidates = [
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            "web",
        ),
    ]
    # A cwd-relative web/ is served ONLY when the operator opts in
    # via NICE_WEB_ROOT (advisor r4: an implicit cwd fallback would
    # publish whatever ./web happens to exist in the launch
    # directory, with CORS *). NICE_WEB_ROOT also allows pointing at
    # any custom static tree.
    explicit = os.environ.get("NICE_WEB_ROOT")
    if explicit:
        candidates.insert(0, explicit)
    web_root = next((c for c in candidates if os.path.isdir(c)), None)
    if web_root is None:
        if not getattr(_static_response, "_warned_no_web", False):
            _static_response._warned_no_web = True
            log.warning(
                "no web/ directory found (wheel install?): dashboard "
                "disabled, API-only — run from a checkout, the sdist, "
                "or the docker image to serve the static site"
            )
        return None
    rel = path.lstrip("/") or "index.html"
    full = os.path.realpath(os.path.join(web_root, rel))
    if os.path.isdir(full):
        full = os.path.join(full, "index.html")
    if not full.startswith(os.path.realpath(web_root) + os.sep):
        return None
    if not os.path.isfile(full):
        return None
    ctype = {
        ".html": "text/html",
        ".js": "application/javascript",
        ".css": "text/css",
        ".json": "application/json",
    }.get(os.path.splitext(full)[1], "application/octet-stream")
    with open(full, "rb") as f:
        raw = f.read()
    return Response(
        200,
        headers={"Content-Type": ctype, "Access-Control-Allow-Origin": "*"},
        body=raw,
    )


def route_request(ctx: ApiContext, request: Request) -> Response:
    """Transport-agnostic request router: the same function serves the async
    core's worker pool and the legacy thread-per-connection handler."""
    t0 = time.monotonic()
    parsed = urlparse(request.target)
    path = parsed.path.rstrip("/")
    endpoint = path or "/"
    method = request.method
    status = 200
    seg = (path.lstrip("/").split("/", 1)[0]) or "root"
    # Distributed-trace continuation: a request stamped with a traceparent
    # header (every api_client call inside a field's trace_context) gets its
    # handler span joined to the client's trace — grep both JSON sinks for
    # one trace_id and the whole claim -> scan -> submit lifecycle
    # reconstructs.
    span_seg = (
        seg if seg in _SPAN_SEGS else ("static" if "." in seg else "other")
    )
    span_ctx = contextlib.ExitStack()
    span_ctx.enter_context(
        obs.trace_context(
            obs.parse_traceparent(request.headers.get("traceparent"))
        )
    )
    span_ctx.enter_context(obs.span(f"server.{span_seg}", method=method))
    try:
        # Chaos hook: server.<first path segment> (server.submit,
        # server.claim, ...). Numeric actions inject that status before the
        # real handler runs; "drop" closes the connection without a response
        # (the client sees a mid-request crash).
        act = faults.fire(f"server.{seg}", path=path, method=method)
        if act is not None:
            if act == "drop":
                status = 0  # no response ever written
                return Response(drop=True)
            try:
                code = int(act)
            except ValueError:
                code = 500
            raise ApiError(code, f"injected fault: {act}")
        user_ip = request.client_ip
        if method == "OPTIONS":
            return Response(204, headers=dict(_CORS_HEADERS))
        # Epoch fence: clients stamp the highest epoch they have seen on
        # every request; a stamp NEWER than ours proves a promotion
        # happened elsewhere and permanently fences this replica. Writes to
        # a standby get 421, writes to a fenced deposed primary 410 — both
        # rotate the client's multi-server failover, and submit_id
        # exactly-once makes the replayed write safe on the new primary.
        ctx.repl.note_client_epoch(request.headers.get("X-Nice-Epoch"))
        if _is_write(method, path):
            rejected = ctx.repl.check_write()
            if rejected is not None:
                REPL_FENCED_WRITES.inc()
                raise ApiError(rejected[0], rejected[1])
        if method == "GET" and path in ("/claim/detailed", "/claim/niceonly"):
            mode = (
                SearchMode.DETAILED
                if path == "/claim/detailed"
                else SearchMode.NICEONLY
            )
            client_token = trust_mod.resolve_token(
                {}, request.headers, "", user_ip, store=ctx.trust
            )
            qs = parse_qs(parsed.query)
            tenant, base_min, base_max = _parse_tenant_args(
                {k: v[0] for k, v in qs.items() if v}
            )
            claim_body = claim_helper(
                ctx, mode, user_ip, client_token,
                tenant=tenant, base_min=base_min, base_max=base_max,
            ).to_json()
            claim_body.setdefault("epoch", ctx.repl.epoch)
            return _json_response(200, claim_body)
        if method == "GET" and path == "/claim/validate":
            qs = parse_qs(parsed.query)
            base_arg = qs.get("base", [None])[0]
            try:
                base_filter = int(base_arg) if base_arg else None
            except ValueError:
                raise ApiError(400, f"Invalid base {base_arg!r}")
            try:
                return _json_response(
                    200, ctx.db.get_validation_field(base_filter).to_json()
                )
            except KeyError as e:
                raise ApiError(404, f"No validation field available: {e}")
        if method == "GET" and path == "/status":
            return _json_response(
                200,
                {
                    "status": "ok",
                    "epoch": ctx.repl.epoch,
                    "niceonly_queue_size": ctx.queue.niceonly_queue_size(),
                    "detailed_thin_queue_size":
                        ctx.queue.detailed_thin_queue_size(),
                    "writer_queue_depth": ctx.writer.queue_depth(),
                    "fleet": ctx.cached_fleet_block(),
                    "slo": ctx.slo.last(),
                    "anomalies": ctx.anomaly.last(),
                    "resources": obs.memwatch.summary(),
                    "tenants": ctx.db.tenant_rollup(),
                    "repl": ctx.repl.status_block(),
                },
            )
        if method == "GET" and path == "/history":
            h_status, h_body = obs.history.handle_query(
                ctx.history, parsed.query
            )
            if h_status >= 400:
                # Bypass ApiError so the JSON body keeps its known-series
                # sample (satellite: real 404 bodies for unknown series).
                status = h_status
                return _json_response(h_status, h_body)
            return _json_response(200, h_body)
        if (
            method == "GET"
            and path.startswith("/fields/")
            and path.endswith("/timeline")
        ):
            # Field drill-down: the causally-ordered audit waterfall for
            # one field (per-field seq is the order; the ts column is
            # advisory).
            fid_arg = path[len("/fields/"):-len("/timeline")]
            try:
                field_id = int(fid_arg)
            except ValueError:
                raise ApiError(400, f"Invalid field id {fid_arg!r}")
            events = ctx.db.get_field_timeline(field_id)
            if not events:
                raise ApiError(404, f"no journal events for field {field_id}")
            return _json_response(
                200, {"field_id": field_id, "events": events},
            )
        if method == "GET" and path == "/events":
            # Cursor-paginated global journal feed: ?since=<id> returns
            # events with id > since, ascending; pass the reply's "cursor"
            # back as the next since. limit is clamped server-side.
            qs = parse_qs(parsed.query)
            try:
                since = int(qs.get("since", ["0"])[0])
                limit = int(
                    qs.get("limit", [str(knobs.JOURNAL_FEED_LIMIT.get())])[0]
                )
            except ValueError:
                raise ApiError(400, "since and limit must be integers")
            limit = max(1, min(limit, knobs.JOURNAL_FEED_LIMIT.get()))
            events = ctx.db.get_events_since(since, limit)
            return _json_response(
                200,
                {
                    "events": events,
                    "cursor": events[-1]["id"] if events else since,
                    "more": len(events) == limit,
                },
            )
        if method == "GET" and path == "/events/stream":
            # Push-based live feed (SSE): journal events + slo/anomaly
            # transitions + critpath bottleneck shifts. Resume via
            # Last-Event-ID (or ?since=) over the same durable journal
            # cursor /events?since= uses. Served on the event loop — the
            # Response carries a stream coroutine, no worker thread is
            # held. The legacy thread core answers 501 (make_handler), so
            # dashboards fall back to polling cleanly.
            qs = parse_qs(parsed.query)
            raw_since = request.headers.get("Last-Event-ID") or qs.get(
                "since", ["0"]
            )[0]
            try:
                since = max(0, int(raw_since))
            except (TypeError, ValueError):
                raise ApiError(400, "Last-Event-ID/since must be an integer")
            cap = int(knobs.STREAM_MAX_SUBSCRIBERS.get())
            if ctx.stream.subscriber_count() >= cap:
                raise ApiError(
                    503,
                    f"stream subscriber cap reached ({cap}); retry later",
                    headers={"Retry-After": str(ctx.retry_after_secs)},
                )
            return Response(
                200,
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                    **_CORS_HEADERS,
                },
                stream=obs.stream.make_sse_responder(
                    ctx.stream, ctx.db.get_events_since, since
                ),
            )
        if method == "GET" and path == "/critpath":
            # Fleet critical-path attribution: per-segment p50/p95 + shares
            # over the recent canon window, USE utilization, the dominant
            # segment, and (?fields=N, default 10) the newest per-field
            # waterfalls with their reconciliation verdicts.
            qs = parse_qs(parsed.query)
            try:
                nfields = int(qs.get("fields", ["10"])[0])
            except ValueError:
                raise ApiError(400, "fields must be an integer")
            snap = dict(ctx.critpath.snapshot())
            snap["waterfalls"] = snap["waterfalls"][: max(0, nfields)]
            return _json_response(200, snap)
        if method == "GET" and path == "/debug/flight":
            return _json_response(
                200,
                {
                    "pid": os.getpid(),
                    "capacity": obs.flight.RECORDER.capacity,
                    "total_recorded": obs.flight.RECORDER.total_recorded(),
                    "events": obs.flight.snapshot(),
                },
            )
        if method == "GET" and path == "/debug/profile":
            # This process's statistical profile (obs/pyprof.py):
            # ?fmt=folded for flamegraph.pl input, ?fmt=json (default) for
            # the fleet.html flamegraph pane.
            status, body, ctype = obs.pyprof.handle_query(parsed.query)
            return Response(
                status=status,
                headers={"Content-Type": ctype, **_CORS_HEADERS},
                body=body,
            )
        if method == "GET" and path == "/profile/fleet":
            # Fleet profile rollup: the server's own snapshot plus the
            # top-K stacks each active client piggybacked on telemetry.
            local = obs.pyprof.snapshot(top_k=50)
            clients = ctx.db.get_client_resource_snapshots(
                fleet_active_secs()
            )
            merged: dict = {}
            for c in clients:
                for entry in (c.get("pyprof") or {}).get("top") or []:
                    key = (entry.get("root", ""), entry.get("stack", ""))
                    merged[key] = merged.get(key, 0) + int(
                        entry.get("count", 0)
                    )
            top = sorted(
                (
                    {"root": root, "stack": stack, "count": count}
                    for (root, stack), count in merged.items()
                ),
                key=lambda e: (-e["count"], e["root"], e["stack"]),
            )[:50]
            return _json_response(
                200,
                {
                    "server": local,
                    "clients": clients,
                    "fleet_top": top,
                },
            )
        if method == "GET" and path == "/metrics":
            return _json_response(
                200, ctx.metrics.render(), content_type="text/plain"
            )
        if method == "GET" and path == "/stats/bases":
            return _json_response(200, ctx.db.get_base_stats())
        if method == "GET" and path == "/stats/leaderboard":
            qs = parse_qs(parsed.query)
            return _json_response(
                200, ctx.db.get_leaderboard(qs.get("mode", [None])[0])
            )
        if method == "GET" and path == "/stats/search_rate":
            qs = parse_qs(parsed.query)
            return _json_response(
                200, ctx.db.get_search_rate(qs.get("mode", [None])[0])
            )
        if method in ("GET", "POST") and path == "/query":
            # Public read-only ad-hoc SQL, the PostgREST-equivalent surface
            # (reference schema/schema.sql:82-87 grants a web_anon role
            # SELECT over the whole schema). GET takes ?sql=...; POST takes
            # {"sql": ..., "params": [...]}. Hard-sandboxed in
            # Db.public_query (read-only conn, authorizer, row/step caps).
            if method == "GET":
                qs = parse_qs(parsed.query)
                sql = qs.get("sql", [None])[0]
                qparams: list = []
            else:
                payload = _parse_json_body(request)
                sql = payload.get("sql")
                qparams = payload.get("params", [])
                if not isinstance(qparams, list):
                    raise ApiError(400, "params must be a list")
            if not sql or not isinstance(sql, str):
                raise ApiError(400, "missing sql")
            try:
                return _json_response(
                    200, ctx.db.public_query(sql, tuple(qparams))
                )
            except sqlite3.Error as e:
                raise ApiError(400, f"query rejected: {e}")
        if method == "POST" and path == "/submit":
            return _json_response(
                200,
                _stamp_epoch(ctx, handle_submit(
                    ctx, _parse_json_body(request), user_ip, request.headers
                )),
            )
        if method == "POST" and path == "/claim_block":
            return _json_response(
                200,
                _stamp_epoch(ctx, handle_claim_block(
                    ctx, _parse_json_body(request), user_ip, request.headers
                )),
            )
        if method == "POST" and path == "/submit_block":
            return _json_response(
                200,
                _stamp_epoch(ctx, handle_submit_block(
                    ctx, _parse_json_body(request), user_ip, request.headers
                )),
            )
        if method == "POST" and path == "/token":
            # Anonymous trust identity for browser/WASM clients with no
            # telemetry client_id: the token is a bearer credential the
            # client sends back as X-Client-Token. Its trust row is minted
            # HERE — only registered tokens are honored as identity, so a
            # client cannot reset per-token claim caps or the trust ledger
            # by inventing bearer strings (minting itself is rate-limited
            # under the per-IP bucket).
            token = "anon-" + secrets.token_hex(16)
            row = ctx.write(ctx.db.upsert_client_trust, token)
            ctx.trust.update(row)
            return _json_response(200, {"client_token": token})
        if method == "POST" and path == "/telemetry":
            return _json_response(
                200, handle_telemetry(ctx, _parse_json_body(request), user_ip)
            )
        if method == "POST" and path == "/renew_claim":
            return _json_response(
                200,
                _stamp_epoch(
                    ctx, handle_renew_claim(ctx, _parse_json_body(request))
                ),
            )
        if method == "GET" and path == "/repl/ops":
            # Standby pull feed: one page of the durable op log, seq >
            # ?since ascending — the /events?since= cursor contract over
            # repl_ops. Standbys advertise themselves (+ applied seq) so
            # /status can serve the failover server list.
            _check_repl_key(request)
            qs = parse_qs(parsed.query)
            try:
                r_since = int(qs.get("since", ["0"])[0])
                r_limit = int(
                    qs.get("limit", [str(knobs.REPL_BATCH_OPS.get())])[0]
                )
            except ValueError:
                raise ApiError(400, "since and limit must be integers")
            r_limit = max(1, min(r_limit, 5000))
            ctx.repl.record_standby_poll(
                qs.get("standby", [None])[0], qs.get("applied", ["0"])[0]
            )
            return _json_response(
                200,
                {
                    "ops": ctx.db.get_repl_ops_since(r_since, r_limit),
                    "epoch": ctx.repl.epoch,
                    "max_seq": ctx.db.repl_max_seq(),
                    "role": ctx.repl.role,
                },
            )
        if method == "POST" and path == "/repl/promote":
            _check_repl_key(request)
            new_epoch = ctx.promote_to_primary()
            return _json_response(
                200, {"status": "OK", "role": "primary", "epoch": new_epoch}
            )
        if method == "POST" and path == "/admin/disqualify":
            return _json_response(
                200,
                handle_disqualify(
                    ctx, _parse_json_body(request), request.headers
                ),
            )
        if method == "GET":
            static = _static_response(path)
            if static is not None:
                return static
        status = 404
        return _error_response(404, NOT_FOUND_MESSAGE)
    except ApiError as e:
        status = e.status
        return _error_response(
            e.status, e.message, extra_headers=e.headers or None
        )
    except Exception as e:  # 500 with JSON body, never a stack dump
        status = 500
        log.exception("internal error handling %s %s", method, path)
        return _error_response(500, f"Internal server error: {e}")
    finally:
        span_ctx.close()
        ctx.metrics.record(endpoint, status, time.monotonic() - t0)


def make_handler(ctx: ApiContext):
    """Legacy thread-per-connection adapter over route_request (the
    NICE_TPU_SERVER_CORE=thread escape hatch; shares every handler with the
    async core)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging
            log.debug("%s " + fmt, self.address_string(), *args)

        def _dispatch(self, method: str):
            from nice_tpu.server.async_core import Headers

            length = int(self.headers.get("Content-Length", 0) or 0)
            body = self.rfile.read(length) if length else b""
            request = Request(
                method=method,
                target=self.path,
                headers=Headers(self.headers.items()),
                body=body,
                client_ip=self.client_address[0],
            )
            path = urlparse(self.path).path.rstrip("/")
            within_cap = ctx.enter_request()
            try:
                # Per-client rate limit first, then the global overload
                # shed: past the in-flight cap, answer 503 with a
                # Retry-After hint instead of queueing unboundedly. /metrics
                # stays exempt — overload is exactly when scrapes matter.
                limited = rate_limit_check(ctx, request)
                if limited is not None:
                    resp = limited
                elif (
                    not within_cap
                    and path != "/metrics"
                    and method != "OPTIONS"
                ):
                    resp = overload_response(ctx, path or "/")
                else:
                    resp = route_request(ctx, request)
            finally:
                ctx.exit_request()
            if resp.drop:
                self.close_connection = True
                return
            if resp.stream is not None:
                # The thread core has no event loop to service a long-lived
                # SSE socket; a clean 501 is the dashboard's documented cue
                # to fall back to polling.
                resp = _error_response(
                    501,
                    "event streaming requires the async server core"
                    " (NICE_TPU_SERVER_CORE=async)",
                )
            self.send_response(resp.status)
            headers_out = dict(resp.headers)
            headers_out.setdefault("Content-Type", "application/json")
            headers_out["Content-Length"] = str(len(resp.body))
            for name, value in headers_out.items():
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(resp.body)
            if resp.close:
                self.close_connection = True

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def do_OPTIONS(self):
            self._dispatch("OPTIONS")

    return Handler


def serve(db_path: str, host: str = "0.0.0.0", port: int = 8127, prefill=True,
          standby_of: str | None = None, advertise: str | None = None):
    """Build the server (async core by default; NICE_TPU_SERVER_CORE=thread
    selects the legacy ThreadingHTTPServer). The returned object exposes
    serve_forever() / shutdown() / server_address either way.

    standby_of: primary URL — serve as a read-only hot standby replicating
    from it. advertise: this server's client-reachable URL (published in
    /status server lists and to the upstream's standby registry)."""
    db = Db(db_path)
    if standby_of:
        role = "standby"
        # nicelint: allow W1 (sanctioned init: role flips before the writer exists)
        db.repl_set_standby()
    else:
        role = "primary"
        if db.repl_role() == "standby":
            # Restarting a standby-marked replica WITHOUT --standby-of is
            # an explicit promotion: bump the epoch so the old lineage is
            # fenced rather than silently forked.
            # nicelint: allow W1 (sanctioned init: promotion runs before the writer exists)
            epoch = db.repl_promote()
            log.warning(
                "standby-marked db restarted as primary: promoted to"
                " epoch %d", epoch,
            )
    ctx = ApiContext(db, role=role, upstream=standby_of, advertise=advertise)
    if prefill and role == "primary":
        ctx.queue.refill_niceonly()
        ctx.queue.refill_detailed_thin()
    core = (knobs.SERVER_CORE.get() or "async").lower()
    if core == "thread":
        server = ThreadingHTTPServer((host, port), make_handler(ctx))
    else:
        def _shed(request: Request):
            p = urlparse(request.target).path.rstrip("/")
            if p == "/metrics" or request.method == "OPTIONS":
                return None
            return overload_response(ctx, p or "/")

        server = AsyncHTTPServer(
            host,
            port,
            router=lambda req: route_request(ctx, req),
            max_inflight=ctx.max_inflight,
            shed=_shed,
            limiter=lambda req: rate_limit_check(ctx, req),
        )
    server.context = ctx  # reachable for tests / debugging
    log.info(
        "nice-tpu API listening on %s:%d (db=%s, core=%s)",
        host, server.server_address[1], db_path, core,
    )
    return server


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="nice-tpu-server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8127)
    p.add_argument("--db", default="nice.db", help="sqlite database path")
    p.add_argument(
        "--init-base",
        type=int,
        action="append",
        default=None,
        help="seed fields for a base then continue serving (repeatable)",
    )
    p.add_argument(
        "--field-size",
        type=int,
        default=1_000_000_000,
        help="field width when seeding bases",
    )
    p.add_argument(
        "--standby-of",
        default=None,
        metavar="URL",
        help="serve as a read-only hot standby replicating from this"
        " primary URL (promote via POST /repl/promote)",
    )
    p.add_argument(
        "--advertise",
        default=None,
        metavar="URL",
        help="client-reachable URL of THIS server, published in /status"
        " server lists for client failover",
    )
    p.add_argument("--log-level", default="info")
    args = p.parse_args(argv)
    # Unified JSON log sink (trace_id-stamped lines; NICE_TPU_LOG_LEVEL /
    # NICE_TPU_LOG_FILE override the CLI default).
    obs.logsink.install(default_level=args.log_level)
    # Crash/SIGUSR2 flight-recorder dumps (NICE_TPU_FLIGHT_DIR); the live
    # ring is also served at GET /debug/flight.
    obs.flight.install()
    if args.init_base:
        db = Db(args.db)
        for base in args.init_base:
            # nicelint: allow W1 (sanctioned init: --init-base seeds before the server exists)
            n = db.seed_base(base, args.field_size)
            log.info("seeded base %d with %d fields", base, n)
        db.close()
    server = serve(
        args.db, args.host, args.port,
        standby_of=args.standby_of, advertise=args.advertise,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
