-- nice-tpu field ledger schema.
-- Structure mirrors the reference (schema/schema.sql): bases -> chunks ->
-- fields -> claims -> submissions, plus leaderboard/search-rate cache tables.
-- Engine-portable SQL (SQLite by default; types chosen to also run on
-- Postgres). u128 quantities are stored as 40-char zero-padded decimal TEXT so
-- lexicographic comparison == numeric comparison (SQLite INTEGER is only i64).

CREATE TABLE IF NOT EXISTS bases (
    id              INTEGER PRIMARY KEY,
    range_start     TEXT NOT NULL,
    range_end       TEXT NOT NULL,
    range_size      TEXT NOT NULL,
    checked_detailed TEXT NOT NULL DEFAULT '0',
    checked_niceonly TEXT NOT NULL DEFAULT '0',
    minimum_cl      INTEGER NOT NULL DEFAULT 0,
    niceness_mean   REAL,
    niceness_stdev  REAL,
    distribution    TEXT NOT NULL DEFAULT '[]',   -- JSON
    numbers         TEXT NOT NULL DEFAULT '[]'    -- JSON
);

CREATE TABLE IF NOT EXISTS chunks (
    id              INTEGER PRIMARY KEY AUTOINCREMENT,
    base_id         INTEGER NOT NULL REFERENCES bases(id),
    range_start     TEXT NOT NULL,
    range_end       TEXT NOT NULL,
    range_size      TEXT NOT NULL,
    checked_detailed TEXT NOT NULL DEFAULT '0',
    checked_niceonly TEXT NOT NULL DEFAULT '0',
    minimum_cl      INTEGER NOT NULL DEFAULT 0,
    niceness_mean   REAL,
    niceness_stdev  REAL,
    distribution    TEXT NOT NULL DEFAULT '[]',
    numbers         TEXT NOT NULL DEFAULT '[]'
);

CREATE TABLE IF NOT EXISTS fields (
    id              INTEGER PRIMARY KEY AUTOINCREMENT,
    base_id         INTEGER NOT NULL REFERENCES bases(id),
    chunk_id        INTEGER REFERENCES chunks(id),
    range_start     TEXT NOT NULL,
    range_end       TEXT NOT NULL,
    range_size      TEXT NOT NULL,
    last_claim_time TEXT,                          -- ISO-8601 UTC
    canon_submission_id INTEGER,
    check_level     INTEGER NOT NULL DEFAULT 0,
    prioritize      INTEGER NOT NULL DEFAULT 0
);

CREATE TABLE IF NOT EXISTS claims (
    id              INTEGER PRIMARY KEY AUTOINCREMENT,
    field_id        INTEGER NOT NULL REFERENCES fields(id),
    search_mode     TEXT NOT NULL,                 -- 'detailed' | 'niceonly'
    claim_time      TEXT NOT NULL,
    user_ip         TEXT NOT NULL,
    block_id        TEXT,                          -- /claim_block lease group
    client_token    TEXT,                          -- trust identity (NULL =
                                                   -- legacy/anonymous-by-ip)
    lease_expiry    TEXT,                          -- ISO-8601 UTC; NULL =
                                                   -- legacy open-ended claim
    lease_secs      REAL                           -- window the expiry was
                                                   -- minted/renewed with
);

CREATE TABLE IF NOT EXISTS submissions (
    id              INTEGER PRIMARY KEY AUTOINCREMENT,
    claim_id        INTEGER NOT NULL REFERENCES claims(id),
    field_id        INTEGER NOT NULL REFERENCES fields(id),
    search_mode     TEXT NOT NULL,
    submit_time     TEXT NOT NULL,
    elapsed_secs    REAL NOT NULL DEFAULT 0,
    username        TEXT NOT NULL,
    user_ip         TEXT NOT NULL,
    client_version  TEXT NOT NULL,
    disqualified    INTEGER NOT NULL DEFAULT 0,
    distribution    TEXT,                          -- JSON or NULL (niceonly)
    numbers         TEXT NOT NULL DEFAULT '[]',    -- JSON
    submit_id       TEXT,                          -- exactly-once idempotency
                                                   -- key (claim + content
                                                   -- hash); NULL from legacy
                                                   -- clients
    client_token    TEXT                           -- trust identity the
                                                   -- submission arrived under
);
-- The partial unique index behind the submit_id dedup lives in
-- Db.init_schema (Python), after the legacy-DB ALTER TABLE migration —
-- executescript on a pre-submit_id database would fail here otherwise.

-- Claim-path indexes (reference schema.sql:99-101): a partial index for the
-- hot niceonly predicate and a composite for the detailed path.
CREATE INDEX IF NOT EXISTS idx_fields_unchecked
    ON fields(id) WHERE check_level = 0;
CREATE INDEX IF NOT EXISTS idx_fields_claim_path
    ON fields(check_level, last_claim_time, id);
CREATE INDEX IF NOT EXISTS idx_fields_chunk ON fields(chunk_id);
CREATE INDEX IF NOT EXISTS idx_fields_base ON fields(base_id);
CREATE INDEX IF NOT EXISTS idx_claims_field ON claims(field_id);
CREATE INDEX IF NOT EXISTS idx_submissions_field ON submissions(field_id);
CREATE INDEX IF NOT EXISTS idx_submissions_claim ON submissions(claim_id);

-- Leaderboard / search-rate caches refreshed by the jobs runner, with the
-- reference's semantics (reference schema.sql:111-131, db_util/cache.rs:3-40):
-- numbers searched (sum of field range sizes), per user AND per search mode;
-- daily buckets over a 90-day window plus an all-time leaderboard.
CREATE TABLE IF NOT EXISTS cache_search_rate_daily (
    date            TEXT NOT NULL,                 -- ISO date bucket
    search_mode     TEXT NOT NULL,
    username        TEXT NOT NULL,
    total_range     TEXT NOT NULL,                 -- padded u128 decimal
    PRIMARY KEY (date, search_mode, username)
);

CREATE TABLE IF NOT EXISTS cache_search_leaderboard (
    search_mode     TEXT NOT NULL,
    username        TEXT NOT NULL,
    total_range     TEXT NOT NULL,                 -- padded u128 decimal
    submissions     INTEGER NOT NULL,
    last_submission TEXT NOT NULL,
    PRIMARY KEY (search_mode, username)
);

CREATE INDEX IF NOT EXISTS idx_cache_rate_daily_mode_date
    ON cache_search_rate_daily(search_mode, date);
CREATE INDEX IF NOT EXISTS idx_cache_leaderboard_mode
    ON cache_search_leaderboard(search_mode, total_range DESC);

-- Fleet telemetry: one row per running client process, upserted from the
-- POST /telemetry heartbeat and from the snapshot piggybacked on each
-- submission. Aggregated into the /status fleet block and re-exported as
-- nice_fleet_* gauges. client_id is user@host/pid (process-stable).
CREATE TABLE IF NOT EXISTS client_telemetry (
    client_id       TEXT PRIMARY KEY,
    username        TEXT NOT NULL DEFAULT '',
    user_ip         TEXT NOT NULL DEFAULT '',
    client_version  TEXT NOT NULL DEFAULT '',
    backend         TEXT NOT NULL DEFAULT '',
    first_seen      TEXT NOT NULL,                 -- ISO-8601 UTC
    last_seen       TEXT NOT NULL,                 -- ISO-8601 UTC
    fields_detailed INTEGER NOT NULL DEFAULT 0,
    fields_niceonly INTEGER NOT NULL DEFAULT 0,
    numbers_total   TEXT NOT NULL DEFAULT '0',     -- padded u128 decimal
    numbers_per_sec REAL NOT NULL DEFAULT 0,
    downgrades      INTEGER NOT NULL DEFAULT 0,
    restores        INTEGER NOT NULL DEFAULT 0,
    faults          INTEGER NOT NULL DEFAULT 0,
    spool_depth     INTEGER NOT NULL DEFAULT 0,
    snapshot        TEXT NOT NULL DEFAULT '{}'     -- full JSON snapshot
);

CREATE INDEX IF NOT EXISTS idx_client_telemetry_last_seen
    ON client_telemetry(last_seen);

-- Untrusted-client trust ledger: one row per client identity (telemetry
-- client_id, a server-issued anonymous token, or username@ip). Spot-check
-- outcomes move the score; the score drives the spot-sampling rate, the
-- claim profile (micro-fields + short leases below NICE_TPU_TRUST_THRESHOLD)
-- and the rate-limit bucket multiplier. NOT exposed via /query — tokens act
-- as bearer credentials.
-- Performance-observatory history: downsampled samples of every nice_*
-- series, persisted through the writer actor from the in-memory ring
-- (obs/history.py). tier is 'raw' | '1m' | '15m'; coarse tiers carry the
-- bucket aggregate (value = mean) while raw rows have vmin = vmax = value,
-- n = 1. Pruned by retention sweep (NICE_TPU_HISTORY_RETENTION_SECS).
-- This is the historical-tables backbone ROADMAP item 5 reads from.
CREATE TABLE IF NOT EXISTS metric_history (
    series          TEXT NOT NULL,
    tier            TEXT NOT NULL,
    ts              REAL NOT NULL,                 -- unix seconds
    value           REAL NOT NULL,                 -- sample / bucket mean
    vmin            REAL NOT NULL,
    vmax            REAL NOT NULL,
    n               INTEGER NOT NULL DEFAULT 1,
    PRIMARY KEY (series, tier, ts)
);

CREATE INDEX IF NOT EXISTS idx_metric_history_ts ON metric_history(ts);

-- Field lifecycle audit journal: one append-only row per field-state
-- transition (generated -> queued -> claimed -> ... -> canon_promoted),
-- written through the writer actor. id is the global feed cursor
-- (GET /events?since=<id>); (field_id, seq) is the per-field monotonic
-- timeline order (GET /fields/<id>/timeline). trace_id joins the claim's
-- distributed trace; client/tier/check_level snapshot the resolved
-- identity at event time. detail is a small JSON blob of kind-specific
-- context. Pruned by retention sweep (NICE_TPU_JOURNAL_RETENTION_SECS).
CREATE TABLE IF NOT EXISTS field_events (
    id              INTEGER PRIMARY KEY AUTOINCREMENT,
    field_id        INTEGER NOT NULL,
    seq             INTEGER NOT NULL,              -- per-field monotonic
    ts              TEXT NOT NULL,                 -- ISO-8601 UTC
    kind            TEXT NOT NULL,
    trace_id        TEXT,
    client          TEXT,
    tier            TEXT,
    check_level     INTEGER,
    detail          TEXT NOT NULL DEFAULT '{}',    -- JSON
    UNIQUE (field_id, seq)
);

CREATE INDEX IF NOT EXISTS idx_field_events_field
    ON field_events(field_id, seq);
CREATE INDEX IF NOT EXISTS idx_field_events_ts ON field_events(ts);
CREATE INDEX IF NOT EXISTS idx_field_events_kind_ts ON field_events(kind, ts);

CREATE TABLE IF NOT EXISTS client_trust (
    client_token    TEXT PRIMARY KEY,
    trust           REAL NOT NULL DEFAULT 0,
    submissions_accepted INTEGER NOT NULL DEFAULT 0,
    spot_checks_passed   INTEGER NOT NULL DEFAULT 0,
    spot_checks_failed   INTEGER NOT NULL DEFAULT 0,
    suspect         INTEGER NOT NULL DEFAULT 0,
    first_seen      TEXT NOT NULL,                 -- ISO-8601 UTC
    last_seen       TEXT NOT NULL                  -- ISO-8601 UTC
);

-- Replication plane (nice_tpu/server/repl.py). repl_meta holds the
-- replication identity of THIS database file: monotonic promotion epoch,
-- role (primary/standby), whether the capture triggers log mutations
-- (primary yes, standby no — applying streamed ops must not re-log them),
-- the sticky write fence, and the standby's applied-seq watermark.
-- repl_ops is the sequence-numbered durable op log: AFTER INSERT/UPDATE/
-- DELETE triggers (generated in Db._init_repl from PRAGMA table_info so
-- later column migrations are picked up automatically) append one
-- physical-row op per mutation, inside the mutating transaction — the log
-- commits atomically with the change it describes, so seq is gap-free on
-- any crash-consistent snapshot.
CREATE TABLE IF NOT EXISTS repl_meta (
    key             TEXT PRIMARY KEY,
    value           TEXT NOT NULL
);

CREATE TABLE IF NOT EXISTS repl_ops (
    seq             INTEGER PRIMARY KEY AUTOINCREMENT,
    epoch           INTEGER NOT NULL,              -- ledger epoch at capture
    tbl             TEXT NOT NULL,                 -- replicated table name
    op              TEXT NOT NULL,                 -- 'I' | 'U' | 'D'
    rid             INTEGER NOT NULL,              -- source rowid
    row             TEXT                           -- JSON row image (NULL on D)
);
