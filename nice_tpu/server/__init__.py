"""Coordination server (L3+L4): field ledger DB, claim engine, HTTP API."""
