from nice_tpu.server.app import main

if __name__ == "__main__":
    raise SystemExit(main())
