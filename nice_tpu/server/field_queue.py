"""In-memory pre-claimed field queues.

Serving claims from memory cuts claim latency from a DB round-trip to a deque
pop (the reference measured 90-100ms -> 3-5ms, CHANGELOG.md:42). Queues refill
by bulk-claiming when they drop to the threshold (reference
api/src/field_queue.rs:16-23, 49-62), and the refill thread also wakes on a
low-water poll timer so inventory recovers even when no claim traffic trips
the threshold signal — this is the continuously running field pre-generation
pipeline feeding block claims.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Optional

from nice_tpu.core.constants import DETAILED_SEARCH_MAX_FIELD_SIZE
from nice_tpu.core.types import FieldRecord
from nice_tpu.obs.series import SERVER_FIELD_QUEUE_REFILLS
from nice_tpu.server.db import Db
from nice_tpu.utils import knobs, lockdep

log = logging.getLogger(__name__)

REFILL_THRESHOLD = 50
REFILL_AMOUNT = 200
DETAILED_REFILL_THRESHOLD = 50
DETAILED_REFILL_AMOUNT = 100

U128_MAX = (1 << 128) - 1


def _poll_secs() -> float:
    return knobs.QUEUE_POLL_SECS.get()


class FieldQueue:
    """Thread-safe niceonly + detailed-thin pre-claim queues.

    Refills run on a BACKGROUND thread: a claim that dips below the threshold
    only signals the refiller and pops immediately, so no claimant ever pays
    bulk-claim latency (the whole point of the queues — the reference's
    90-100 ms -> 3-5 ms win, CHANGELOG.md:42 — which an inline refill would
    hand right back to whichever client drew the short straw). An EMPTY queue
    returns None (or a short list from the _many variants) and the caller
    falls back to a direct DB claim.

    When constructed with a writer (the single-writer DB actor), refill
    bulk-claims run through it, so their lease-stamp transactions coalesce
    with the rest of the server's write traffic instead of competing for
    BEGIN IMMEDIATE."""

    def __init__(self, db: Db, start_thread: bool = True, writer=None,
                 journal=None):
        self.db = db
        self.writer = writer
        # Optional audit-journal sink (ApiContext.journal): refills append a
        # "queued" event per pre-claimed field, fire-and-forget.
        self.journal = journal
        self._niceonly: deque[FieldRecord] = deque()
        self._detailed_thin: deque[FieldRecord] = deque()
        self._lock = lockdep.make_lock("server.field_queue.FieldQueue._lock")
        self._refill_wanted = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start_thread:
            self.start()

    def start(self) -> None:
        """Start the refill thread. A standby replica builds its queue with
        start_thread=False (refills would mutate the replicated ledger) and
        calls this when it is promoted to primary."""
        if self._thread is not None or self._stop.is_set():
            return
        self._thread = threading.Thread(
            target=self._refill_loop, name="field-queue-refill", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._refill_wanted.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Return the pre-claimed inventory: bulk-claiming stamped a lease on
        # every queued field, so without this a shutdown strands up to
        # REFILL_AMOUNT fields per queue until the lease expires (an hour of
        # un-claimable work after every restart).
        with self._lock:
            stranded = [f.field_id for f in self._niceonly]
            stranded += [f.field_id for f in self._detailed_thin]
            self._niceonly.clear()
            self._detailed_thin.clear()
        if not stranded:
            return
        try:
            # Direct DB call on purpose: close() may run after (or during)
            # writer shutdown, and the release must not depend on actor
            # ordering.
            # nicelint: allow W1 (shutdown path must not depend on writer-actor ordering)
            released = self.db.release_field_claims(stranded)
            log.info(
                "released %d pre-claimed queue fields back to the DB", released
            )
        except Exception:
            # The DB may already be closed during teardown; stranded leases
            # simply expire on schedule.
            log.exception("failed to release queued field claims on close")

    def _refill_loop(self) -> None:
        while not self._stop.is_set():
            # Event OR low-water poll: block claims can drain a queue between
            # threshold signals, and an idle server should rebuild inventory
            # without waiting for the next claimant.
            self._refill_wanted.wait(timeout=_poll_secs())
            self._refill_wanted.clear()
            if self._stop.is_set():
                return
            with self._lock:
                need_no = len(self._niceonly) <= REFILL_THRESHOLD
                need_dt = len(self._detailed_thin) <= DETAILED_REFILL_THRESHOLD
            if need_no:
                self.refill_niceonly()
            if need_dt:
                self.refill_detailed_thin()

    def niceonly_queue_size(self) -> int:
        with self._lock:
            return len(self._niceonly)

    def detailed_thin_queue_size(self) -> int:
        with self._lock:
            return len(self._detailed_thin)

    def claim_niceonly(self) -> Optional[FieldRecord]:
        got = self.claim_niceonly_many(1)
        return got[0] if got else None

    def claim_detailed_thin(self) -> Optional[FieldRecord]:
        got = self.claim_detailed_thin_many(1)
        return got[0] if got else None

    def claim_niceonly_many(self, count: int) -> list[FieldRecord]:
        """Pop up to count fields (block claims); short list when low."""
        with self._lock:
            fields = [
                self._niceonly.popleft()
                for _ in range(min(count, len(self._niceonly)))
            ]
            low = len(self._niceonly) <= REFILL_THRESHOLD
        if low:
            self._refill_wanted.set()
        return fields

    def claim_detailed_thin_many(self, count: int) -> list[FieldRecord]:
        with self._lock:
            fields = [
                self._detailed_thin.popleft()
                for _ in range(min(count, len(self._detailed_thin)))
            ]
            low = len(self._detailed_thin) <= DETAILED_REFILL_THRESHOLD
        if low:
            self._refill_wanted.set()
        return fields

    def _bulk_claim(self, fn, *args):
        if self.writer is not None:
            return self.writer.call(fn, *args)
        return fn(*args)

    def refill_niceonly(self) -> None:
        try:
            fields = self._bulk_claim(
                self.db.bulk_claim_fields,
                REFILL_AMOUNT,
                self.db.claim_expiry_cutoff(),
                0,
                U128_MAX,
            )
        except Exception:
            log.exception("niceonly queue refill failed")
            return
        with self._lock:
            self._niceonly.extend(fields)
        SERVER_FIELD_QUEUE_REFILLS.labels("niceonly").inc()
        self._journal_queued(fields, "niceonly")
        log.info("refilled niceonly queue with %d fields", len(fields))

    def refill_detailed_thin(self) -> None:
        try:
            fields = self._bulk_claim(
                self.db.bulk_claim_thin_fields,
                DETAILED_REFILL_AMOUNT,
                self.db.claim_expiry_cutoff(),
                1,
                DETAILED_SEARCH_MAX_FIELD_SIZE,
            )
        except Exception:
            log.exception("detailed-thin queue refill failed")
            return
        with self._lock:
            self._detailed_thin.extend(fields)
        SERVER_FIELD_QUEUE_REFILLS.labels("detailed_thin").inc()
        self._journal_queued(fields, "detailed_thin")
        log.info("refilled detailed-thin queue with %d fields", len(fields))

    def _journal_queued(self, fields, queue_name: str) -> None:
        if self.journal is None or not fields:
            return
        from nice_tpu.obs import journal as journal_mod

        self.journal([
            journal_mod.event_row(f.field_id, "queued", queue=queue_name)
            for f in fields
        ])
