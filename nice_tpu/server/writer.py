"""Single-writer DB actor: coalesces mutations into batched transactions.

All server-side mutations (claims, submits, renewals, telemetry upserts) are
enqueued to ONE writer thread, which drains the queue and wraps each drained
batch in a single BEGIN IMMEDIATE transaction. Every operation inside the
batch runs under its own SAVEPOINT (Db._Txn nests automatically), so a
per-operation failure — a duplicate submit_id's IntegrityError is the
important one — rolls back only that operation while the rest of the batch
commits with one fsync. Under load this turns N fsync-bound transactions into
one, which is where SQLite write throughput actually comes from; it is the
SQLite analog of the reference's Postgres connection pool absorbing
concurrent writers.

Callers block on a Future for their result, so the API surface of the Db
methods is unchanged — handle_submit still sees IntegrityError raised from
insert_submission, just via the future.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

from nice_tpu import faults
from nice_tpu.obs.series import (
    SERVER_WRITE_BATCH_SIZE,
    SERVER_WRITER_OP_EXEC_SECONDS,
    SERVER_WRITER_OP_WAIT_SECONDS,
    SERVER_WRITER_QUEUE_DEPTH,
)
from nice_tpu.server.db import Db
from nice_tpu.utils import knobs

log = logging.getLogger(__name__)

_STOP = object()

# Writer-thread-local context for the op currently executing: its measured
# queue wait (enqueue -> batch begin). Emission sites running INSIDE a
# writer op (the submit persist closures journaling submit_accepted) read
# it to stamp the writer-queue-wait segment onto the event they append —
# measured at the source, not inferred from endpoint latency.
_op_ctx = threading.local()


def current_op_wait_secs() -> float | None:
    """Queue wait of the writer op executing on THIS thread (None when not
    called from inside a writer op — e.g. under DirectWriter, where there
    is no queue and the wait is zero by construction)."""
    return getattr(_op_ctx, "wait", None)


class WriterClosed(RuntimeError):
    pass


class WriteActor:
    """One writer thread draining a mutation queue into batched transactions.

    max_batch bounds how many operations share one transaction;
    coalesce_secs is how long the drain loop lingers for stragglers after the
    queue momentarily empties (amortizing the fsync further under bursty
    load without adding latency when idle — the first op in a batch never
    waits).
    """

    def __init__(
        self,
        db: Db,
        max_batch: int | None = None,
        coalesce_secs: float | None = None,
        start: bool = True,
    ):
        self.db = db
        self.max_batch = max_batch or knobs.WRITER_MAX_BATCH.get()
        self.coalesce_secs = (
            knobs.WRITER_COALESCE_SECS.get()
            if coalesce_secs is None
            else coalesce_secs
        )
        self._q: queue.Queue = queue.Queue()
        self._closed = False
        self._periodics: list[dict] = []
        self._thread: threading.Thread | None = None
        # Post-batch hook (writer thread): called with committed=True after
        # the batch transaction commits, False after it rolls back. The
        # stream plane uses it to publish journal events only once they are
        # durable. Exceptions are contained — never fatal to the writer.
        self.on_batch_end: Callable[[bool], None] | None = None
        # Additional post-batch listeners (replication publishes the new
        # op-log high-water mark here). Same contract as on_batch_end.
        self._batch_end_listeners: list[Callable[[bool], None]] = []
        # USE rollup inputs: cumulative wall time this actor spent executing
        # batches, against its uptime (busy fraction = how saturated the
        # single-writer resource is).
        self._busy_secs = 0.0
        self._started_monotonic = time.monotonic()
        if start:
            self._thread = threading.Thread(
                target=self._run, name="db-writer", daemon=True
            )
            self._thread.start()

    def submit(self, fn: Callable, *args, **kwargs) -> Future:
        """Enqueue one mutation; the Future resolves to fn's return value
        (or its exception) once the batch containing it has committed."""
        if self._closed:
            raise WriterClosed("writer actor is closed")
        fut: Future = Future()
        self._q.put((fut, fn, args, kwargs, time.monotonic()))
        return fut

    def call(self, fn: Callable, *args, **kwargs) -> Any:
        """Enqueue and block for the result (the common handler-thread path)."""
        return self.submit(fn, *args, **kwargs).result()

    def add_periodic(self, fn: Callable[[], Any], interval_secs: float) -> None:
        """Run fn() on the writer thread roughly every interval_secs (the
        lease-expiry sweep lives here so background maintenance shares the
        single-writer discipline instead of adding a second mutating thread).
        fn runs BETWEEN batches, owns its own transaction, and its exceptions
        are logged, never fatal to the writer. Best-effort cadence: a long
        batch delays the next tick."""
        self._periodics.append(
            {
                "fn": fn,
                "interval": float(interval_secs),
                "next": time.monotonic() + float(interval_secs),
            }
        )

    def queue_depth(self) -> int:
        return self._q.qsize()

    def busy_stats(self) -> tuple[float, float]:
        """(cumulative batch-execution seconds, uptime seconds) — the
        critical-path engine diffs consecutive samples into a writer busy
        fraction for the USE rollup."""
        return self._busy_secs, max(
            1e-9, time.monotonic() - self._started_monotonic
        )

    def close(self) -> None:
        """Stop accepting work, drain what's queued, and join the thread."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_STOP)
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- writer thread ------------------------------------------------------

    def _next_periodic_delay(self) -> float | None:
        """Seconds until the earliest periodic is due (None = no periodics,
        block indefinitely on the queue as before)."""
        if not self._periodics:
            return None
        return max(0.0, min(p["next"] for p in self._periodics) - time.monotonic())

    def _run_periodics(self) -> None:
        now = time.monotonic()
        for p in self._periodics:
            if now < p["next"]:
                continue
            try:
                p["fn"]()
            except Exception:
                log.exception("writer periodic %r failed", p["fn"])
            p["next"] = time.monotonic() + p["interval"]

    def _run(self) -> None:
        stopping = False
        while not stopping:
            try:
                item = self._q.get(timeout=self._next_periodic_delay())
            except queue.Empty:
                self._run_periodics()
                continue
            if item is _STOP:
                return
            batch = [item]
            deadline = time.monotonic() + self.coalesce_secs
            while len(batch) < self.max_batch:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    if (
                        self.coalesce_secs <= 0
                        or time.monotonic() >= deadline
                    ):
                        break
                    time.sleep(min(0.0005, self.coalesce_secs))
                    continue
                if nxt is _STOP:
                    stopping = True
                    break
                batch.append(nxt)
            SERVER_WRITER_QUEUE_DEPTH.set(self._q.qsize())
            SERVER_WRITE_BATCH_SIZE.observe(len(batch))
            t_batch = time.monotonic()
            self._run_batch(batch)
            self._busy_secs += time.monotonic() - t_batch
            self._run_periodics()

    def _run_batch(self, batch: list) -> None:
        # Futures resolve only AFTER the outer transaction commits: an
        # operation that "succeeded" into a savepoint is not durable until
        # then, and telling the caller OK before COMMIT would break the
        # exactly-once story if the commit failed.
        settled: list[tuple[Future, Any, BaseException | None]] = []
        # Chaos site writer.batch: a numeric action stalls the single-writer
        # actor for that many seconds before the batch runs — the deliberate
        # writer-actor stall the critical-path smoke injects to prove the
        # writer_wait segment is attributed, not inferred.
        act = faults.fire("writer.batch", size=len(batch))
        if act is not None:
            try:
                time.sleep(float(act))
            except (TypeError, ValueError):
                pass
        t_begin = time.monotonic()
        try:
            with self.db._lock, self.db._txn():
                for fut, fn, args, kwargs, t_enq in batch:
                    SERVER_WRITER_OP_WAIT_SECONDS.observe(
                        max(0.0, t_begin - t_enq)
                    )
                    _op_ctx.wait = max(0.0, t_begin - t_enq)
                    t_exec = time.monotonic()
                    try:
                        with self.db._txn():
                            out = fn(*args, **kwargs)
                        settled.append((fut, out, None))
                    except BaseException as e:
                        settled.append((fut, None, e))
                    finally:
                        SERVER_WRITER_OP_EXEC_SECONDS.observe(
                            time.monotonic() - t_exec
                        )
                        _op_ctx.wait = None
        except BaseException as outer:
            log.exception("writer batch transaction failed (%d ops)", len(batch))
            self._notify_batch_end(False)
            done = {id(f) for f, _, _ in settled}
            for fut, _, err in settled:
                fut.set_exception(err if err is not None else outer)
            for fut, _fn, _a, _k, _t in batch:
                if id(fut) not in done:
                    fut.set_exception(outer)
            return
        self._notify_batch_end(True)
        for fut, out, err in settled:
            if err is None:
                fut.set_result(out)
            else:
                fut.set_exception(err)

    def add_batch_end_listener(self, fn: Callable[[bool], None]) -> None:
        """Register an extra post-batch hook (fires after on_batch_end)."""
        self._batch_end_listeners.append(fn)

    def _notify_batch_end(self, committed: bool) -> None:
        for hook in [self.on_batch_end, *self._batch_end_listeners]:
            if hook is None:
                continue
            try:
                hook(committed)
            except Exception:  # noqa: BLE001 — observability must not kill the writer
                log.exception("writer on_batch_end hook failed")


class DirectWriter:
    """Writer-shaped pass-through used when the actor is disabled
    (NICE_TPU_WRITER=0) or in unit tests: same interface, no thread, each
    call is its own ordinary transaction."""

    def __init__(self, db: Db):
        self.db = db
        self.on_batch_end: Callable[[bool], None] | None = None
        self._batch_end_listeners: list[Callable[[bool], None]] = []

    def add_batch_end_listener(self, fn: Callable[[bool], None]) -> None:
        self._batch_end_listeners.append(fn)

    def _notify(self, committed: bool) -> None:
        # Each call is its own "batch": the stream plane's post-commit
        # publish hook fires symmetrically with the actor path.
        for hook in [self.on_batch_end, *self._batch_end_listeners]:
            if hook is None:
                continue
            try:
                hook(committed)
            except Exception:  # noqa: BLE001 — same containment as the actor
                log.exception("direct-writer on_batch_end hook failed")

    def submit(self, fn: Callable, *args, **kwargs) -> Future:
        fut: Future = Future()
        try:
            fut.set_result(fn(*args, **kwargs))
        except BaseException as e:
            self._notify(False)
            fut.set_exception(e)
        else:
            self._notify(True)
        return fut

    def call(self, fn: Callable, *args, **kwargs) -> Any:
        try:
            out = fn(*args, **kwargs)
        except BaseException:
            self._notify(False)
            raise
        self._notify(True)
        return out

    def busy_stats(self) -> tuple[float, float]:
        return 0.0, 1.0

    def add_periodic(self, fn: Callable[[], Any], interval_secs: float) -> None:
        """No background thread here: periodics (the lease sweep) simply
        don't run. Tests driving DirectWriter call the swept function
        directly when they need its effect."""

    def queue_depth(self) -> int:
        return 0

    def close(self) -> None:
        pass
