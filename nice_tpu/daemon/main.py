"""Idle-compute daemon.

Watches system CPU usage and spawns a search client when the machine has been
idle long enough, killing it when the machine gets busy and restarting it
forever otherwise. Mirrors the reference daemon's CpuMonitor / ProcessManager
split (daemon/src/main.rs:39-215).

CPU sampling is portable: /proc/stat jiffy deltas where available (Linux,
no deps), then psutil.cpu_percent if psutil is importable (macOS/Windows),
then a 1-minute loadavg estimate (any POSIX), then a constant-idle stub —
the daemon must run on a dev laptop, not only on the TPU host image. The
sampler itself lives in utils/resources.py (memwatch shares it); the names
are re-exported here unchanged.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Optional

from nice_tpu import obs
from nice_tpu.obs.series import (
    DAEMON_CPU,
    DAEMON_HEARTBEAT,
    DAEMON_RESTART_BACKOFF,
    DAEMON_RESTARTS,
)
from nice_tpu.utils import resources

log = logging.getLogger("nice_tpu.daemon")

# Re-exported from the shared home so existing imports (and the tests that
# monkeypatch ``daemon.read_cpu_times``) keep working.
read_cpu_times = resources.read_cpu_times
pick_cpu_backend = resources.pick_cpu_backend


class CpuMonitor(resources.CpuMonitor):
    """resources.CpuMonitor with "proc" reads routed through THIS module's
    ``read_cpu_times`` global, so tests can stub the reader on the daemon
    module exactly as before the shared-sampler refactor."""

    def __init__(self, interval_secs: float = 5.0, backend: str | None = None):
        super().__init__(
            interval_secs, backend, reader=lambda: read_cpu_times()
        )


# Crash-loop protection defaults (ProcessManager): a client that keeps dying
# within HEALTHY_SECS of spawn (broken config, dead server, bad install)
# would otherwise be respawned every sample interval forever, hammering the
# server's claim endpoint and burning the daemon's own CPU budget.
RESTART_BACKOFF_BASE_SECS = 5.0
RESTART_BACKOFF_CAP_SECS = 600.0
HEALTHY_RUN_SECS = 60.0  # env NICE_DAEMON_HEALTHY_SECS


class ProcessManager:
    """Spawns/stops/restarts the client (reference daemon/src/main.rs:124-215).

    Crash-loop protection: a nonzero exit within healthy_secs of spawn
    escalates an exponential restart backoff (base 5s, doubling, capped at
    10 min, published on nice_daemon_restart_backoff_secs); a run that lasts
    healthy_secs — or any clean exit — resets it."""

    def __init__(
        self, client_args: list[str], healthy_secs: Optional[float] = None
    ):
        self.client_args = client_args
        self.proc: Optional[subprocess.Popen] = None
        self.healthy_secs = (
            float(os.environ.get("NICE_DAEMON_HEALTHY_SECS", HEALTHY_RUN_SECS))
            if healthy_secs is None else healthy_secs
        )
        self.consecutive_crashes = 0
        self._started_at: Optional[float] = None
        self._backoff_until = 0.0

    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def restart_delay(self) -> float:
        """Seconds until crash-loop backoff allows another start (0 = now)."""
        return max(0.0, self._backoff_until - time.monotonic())

    def start(self) -> None:
        if self.running():
            return
        cmd = [sys.executable, "-m", "nice_tpu.client", *self.client_args]
        log.info("starting client: %s", " ".join(cmd))
        self.proc = subprocess.Popen(cmd)
        self._started_at = time.monotonic()
        DAEMON_RESTARTS.inc()

    def stop(self) -> None:
        if not self.running():
            return
        log.info("stopping client (pid %d)", self.proc.pid)
        self.proc.send_signal(signal.SIGINT)
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()

    def reap(self) -> bool:
        """True if the client exited since last check."""
        if self.proc is not None and self.proc.poll() is not None:
            code = self.proc.returncode
            ran = (
                time.monotonic() - self._started_at
                if self._started_at is not None else float("inf")
            )
            log.info("client exited with code %s", code)
            self.proc = None
            if code != 0 and ran < self.healthy_secs:
                self.consecutive_crashes += 1
                delay = min(
                    RESTART_BACKOFF_BASE_SECS
                    * 2 ** (self.consecutive_crashes - 1),
                    RESTART_BACKOFF_CAP_SECS,
                )
                self._backoff_until = time.monotonic() + delay
                DAEMON_RESTART_BACKOFF.set(delay)
                log.warning(
                    "client crashed %.1fs after spawn (crash %d in a row); "
                    "holding next spawn for %.0fs",
                    ran, self.consecutive_crashes, delay,
                )
            elif self.consecutive_crashes:
                self.consecutive_crashes = 0
                self._backoff_until = 0.0
                DAEMON_RESTART_BACKOFF.set(0)
                log.info("client ran healthily; restart backoff reset")
            return True
        return False


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="nice-tpu-daemon")
    p.add_argument(
        "--min-cpu",
        type=float,
        default=float(os.environ.get("NICE_DAEMON_MIN_CPU", 0.3)),
        help="spawn the client when usage stays below this fraction",
    )
    p.add_argument(
        "--wait-time",
        type=float,
        default=float(os.environ.get("NICE_DAEMON_WAIT_TIME", 30)),
        help="seconds of idleness required before spawning",
    )
    p.add_argument(
        "--sample-interval", type=float, default=5.0, help="seconds per CPU sample"
    )
    p.add_argument("--log-level", default="info")
    p.add_argument(
        "--checkpoint-dir",
        default=os.environ.get("NICE_CHECKPOINT_DIR"),
        help="passed through to the client: snapshot directory so a client "
        "the daemon kills (busy CPU) or that crashes resumes its field on "
        "the next spawn instead of abandoning the claim",
    )
    p.add_argument(
        "client_args",
        nargs="*",
        default=["--repeat"],
        help="arguments passed through to the client",
    )
    args = p.parse_args(argv)
    # Unified JSON-line sink (NICE_TPU_LOG_LEVEL / NICE_TPU_LOG_FILE
    # override the CLI flag).
    obs.logsink.install(default_level=args.log_level)

    # Local /metrics (NICE_TPU_METRICS_PORT): heartbeat gauge + restart
    # counter make a silently-dead supervisor loop externally detectable.
    obs.maybe_serve_metrics()
    # Crash/SIGUSR2 flight-recorder dumps (NICE_TPU_FLIGHT_DIR).
    obs.flight.install()
    # Resource observatory: RSS/disk watermarks + the statistical wall-clock
    # profiler (both no-ops — zero threads — when their knobs are 0).
    obs.memwatch.maybe_start_sampler()
    obs.pyprof.maybe_start()
    monitor = CpuMonitor(args.sample_interval)
    log.info("cpu sampler backend: %s", monitor.backend)
    client_args = list(args.client_args or ["--repeat"])
    if args.checkpoint_dir and "--checkpoint-dir" not in client_args:
        client_args += ["--checkpoint-dir", args.checkpoint_dir]
    manager = ProcessManager(client_args)
    idle_since: Optional[float] = None

    try:
        while True:
            usage = monitor.sample()
            DAEMON_HEARTBEAT.set(time.time())
            DAEMON_CPU.set(usage)
            manager.reap()
            if manager.running():
                # While our client runs the CPU is busy by design; only stop it
                # if something *else* is keeping the machine busy after a stop.
                continue
            if usage < args.min_cpu:
                if idle_since is None:
                    idle_since = time.monotonic()
                if time.monotonic() - idle_since >= args.wait_time:
                    # Crash-loop protection: idle_since stays set, so the
                    # spawn happens on the first tick after backoff expiry.
                    if manager.restart_delay() <= 0:
                        manager.start()
                        idle_since = None
            else:
                idle_since = None
                log.debug("cpu busy (%.0f%%), holding off", usage * 100)
    except KeyboardInterrupt:
        log.info("interrupted; stopping client")
        manager.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
