from nice_tpu.daemon.main import main

if __name__ == "__main__":
    raise SystemExit(main())
