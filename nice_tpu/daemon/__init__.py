"""Idle-compute babysitter: runs clients when the machine is otherwise idle."""
