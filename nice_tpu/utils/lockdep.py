"""Runtime lock-order instrumentation (the dynamic half of nicelint X1).

``NICE_TPU_LOCKDEP=1`` swaps every project lock constructed through
:func:`make_lock` / :func:`make_rlock` for an instrumented wrapper that
records, per thread, the stack of currently held locks. Each time a thread
acquires lock B while holding lock A, the directed edge A->B enters a
process-global order graph; an acquisition that would close a cycle
(B ⟶* A already exists) is recorded as an ``order-cycle`` violation with
both acquisition sites. The test suite's autouse guard (tests/conftest.py)
fails any test that produced a cycle, which is how an ABBA deadlock is
caught deterministically in CI without ever having to actually deadlock.

Secondary check: a lock held for longer than ``NICE_TPU_LOCKDEP_HOLD_SECS``
on a thread registered via :func:`mark_loop_thread` (the async core's event
loop) is recorded as a ``long-hold`` violation — the event loop must never
sit behind a lock for macroscopic time. Long-holds only fail tests under
``NICE_TPU_LOCKDEP=strict`` (or ``2``) because wall-time thresholds are
load-sensitive on shared CI machines.

Everything here is conventional threading underneath: the wrappers delegate
to a real ``threading.Lock``/``RLock``, so blocking, timeout, and ownership
semantics are unchanged. When lockdep is disabled the factories return the
plain stdlib objects — zero overhead on the production path.

Cycle detection is NAME-level (the label passed to make_lock), matching the
static lock graph nicelint X1 extracts, so the two reports line up.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List, Optional, Set

from nice_tpu.utils import knobs

__all__ = [
    "enabled",
    "strict",
    "make_lock",
    "make_rlock",
    "mark_loop_thread",
    "violations",
    "violation_count",
    "order_edges",
    "reset",
    "set_factory_hook",
    "factory_hook",
    "dump_graph",
]


def enabled() -> bool:
    """Read at call time so tests can flip the knob per-process; note locks
    constructed before the flip stay whatever they were built as."""
    return knobs.LOCKDEP.get_bool() or _is_strict_raw()


def _is_strict_raw() -> bool:
    raw = (knobs.LOCKDEP.raw() or "").strip().lower()
    return raw in ("2", "strict")


def strict() -> bool:
    return _is_strict_raw()


# Internal state. _state_lock is a PLAIN threading.Lock on purpose — the
# instrumentation must never instrument itself.
_state_lock = threading.Lock()
_tls = threading.local()

# name -> set of names acquired while holding <name>
_graph: Dict[str, Set[str]] = {}
# (outer, inner) -> first-observed acquisition site (formatted stack tail)
_edge_sites: Dict[tuple, str] = {}
_violations: List[dict] = []
_loop_thread_ids: Set[int] = set()


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def mark_loop_thread(ident: Optional[int] = None) -> None:
    """Register the calling (or given) thread as an event-loop thread for
    long-hold attribution. Cheap no-op when lockdep is off."""
    if not enabled():
        return
    with _state_lock:
        _loop_thread_ids.add(
            threading.get_ident() if ident is None else ident
        )


def _site(skip: int = 3) -> str:
    """A compact one-line acquisition site, e.g. 'writer.py:179 in _run_batch'."""
    for frame in reversed(traceback.extract_stack(limit=skip + 4)[: -skip]):
        fn = frame.filename
        if "lockdep" in fn:
            continue
        return f"{fn.rsplit('/', 1)[-1]}:{frame.lineno} in {frame.name}"
    return "<unknown>"


def _path_exists(src: str, dst: str) -> bool:
    """DFS: does src reach dst in the order graph? Caller holds _state_lock."""
    seen = {src}
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        for nxt in _graph.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return False


def _record_acquire(name: str) -> None:
    stack = _held_stack()
    if any(entry[0] == name for entry in stack):
        # Re-entrant hold of the same named lock (RLock recursion, or two
        # sibling instances sharing a name): no ordering information.
        stack.append((name, time.monotonic(), False))
        return
    if stack:
        outer = stack[-1][0]
        site = _site()
        with _state_lock:
            if name not in _graph.get(outer, ()):
                # New edge outer->name: a cycle exists iff name already
                # reaches outer.
                if _path_exists(name, outer):
                    _violations.append({
                        "kind": "order-cycle",
                        "edge": (outer, name),
                        "site": site,
                        "reverse_site": _edge_sites.get((name, outer))
                        or _first_site_reaching(name, outer),
                        "thread": threading.current_thread().name,
                        "held": [e[0] for e in stack],
                    })
                _graph.setdefault(outer, set()).add(name)
                _edge_sites.setdefault((outer, name), site)
    stack.append((name, time.monotonic(), True))


def _first_site_reaching(src: str, dst: str) -> Optional[str]:
    """Best-effort site of the first edge on some src⟶dst path (for the
    cycle report). Caller holds _state_lock."""
    for nxt in _graph.get(src, ()):
        if nxt == dst or _path_exists(nxt, dst):
            return _edge_sites.get((src, nxt))
    return None


def _record_release(name: str) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == name:
            _, t0, outermost = stack.pop(i)
            if outermost:
                held_for = time.monotonic() - t0
                threshold = knobs.LOCKDEP_HOLD_SECS.get()
                if held_for > threshold:
                    ident = threading.get_ident()
                    with _state_lock:
                        if ident in _loop_thread_ids:
                            _violations.append({
                                "kind": "long-hold",
                                "lock": name,
                                "held_secs": round(held_for, 4),
                                "threshold_secs": threshold,
                                "thread": threading.current_thread().name,
                                "site": _site(),
                            })
            return
    # Release of a lock this thread never recorded (acquired pre-flip or
    # handed across threads): ignore — delegation below still releases.


class _DepLock:
    """Instrumented Lock/RLock wrapper: same acquire/release/context-manager
    surface, recording order edges and hold times around the real lock."""

    __slots__ = ("_name", "_lock")

    def __init__(self, name: str, lock):
        self._name = name
        self._lock = lock

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            _record_acquire(self._name)
        return got

    def release(self) -> None:
        self._lock.release()
        _record_release(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<DepLock {self._name} wrapping {self._lock!r}>"


# Factory hook: schedex (analysis/schedex.py) swaps project locks for its
# deterministically scheduled wrappers DURING an instrument() window. None
# on the production path — make_lock's only added cost is this one global
# load, so NICE_TPU_SCHEDEX=0 installs nothing (asserted by test, same
# discipline as stepprof's no-sync guarantee).
_factory_hook = None


def set_factory_hook(hook) -> None:
    """Install (or clear, with None) the schedex lock factory hook."""
    global _factory_hook
    _factory_hook = hook


def factory_hook():
    return _factory_hook


def make_lock(name: str):
    """A threading.Lock, instrumented when NICE_TPU_LOCKDEP is on. ``name``
    labels the lock in the order graph; use a stable dotted id matching the
    attribute path (e.g. "server.db.Db._lock") so runtime reports line up
    with the static X1 graph."""
    if _factory_hook is not None:
        return _factory_hook(name, "lock")
    return _DepLock(name, threading.Lock()) if enabled() else threading.Lock()


def make_rlock(name: str):
    """A threading.RLock, instrumented when NICE_TPU_LOCKDEP is on."""
    if _factory_hook is not None:
        return _factory_hook(name, "rlock")
    return (
        _DepLock(name, threading.RLock()) if enabled() else threading.RLock()
    )


def violations() -> List[dict]:
    with _state_lock:
        return list(_violations)


def violation_count() -> int:
    with _state_lock:
        return len(_violations)


def order_edges() -> Dict[str, Set[str]]:
    """Snapshot of the observed acquisition-order graph."""
    with _state_lock:
        return {k: set(v) for k, v in _graph.items()}


def reset() -> None:
    """Drop all recorded state (tests)."""
    with _state_lock:
        _graph.clear()
        _edge_sites.clear()
        _violations.clear()
        _loop_thread_ids.clear()


def dump_graph(path: str, merge: bool = True) -> dict:
    """Write the observed name-level order graph as JSON (the artifact
    racelint R2 cross-checks against the static X1 graph).

    ``merge=True`` unions with an existing file so regenerating from a
    partial exercise never FORGETS an edge another run observed — the
    graph only grows, matching the ratchet discipline. Returns the edge
    dict that was written."""
    import json
    import os

    edges = {k: sorted(v) for k, v in order_edges().items()}
    if merge and os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                old = json.load(f).get("edges", {})
        except (OSError, ValueError):
            old = {}
        for outer, inners in old.items():
            edges[outer] = sorted(set(edges.get(outer, [])) | set(inners))
    payload = {
        "comment": "observed lockdep acquisition-order graph; regenerate "
                   "with `python -m nice_tpu.utils.lockdep --dump-graph "
                   "docs/lockorder.json` (merges, never forgets edges)",
        "edges": dict(sorted(edges.items())),
    }
    with open(path, "w", encoding="utf-8") as f:  # nicelint: allow A1 (dev-only analysis artifact, not crash-safety state)
        json.dump(payload, f, indent=1)
        f.write("\n")
    return edges


def _exercise() -> List[str]:
    """Drive representative coordination-plane flows in-process so the
    order graph has real edges to dump: server context construction, field
    queue refills, status-cache read/invalidate, lease sweep, history
    tick, and the engine mesh-cache invalidation. Each step is best-effort
    — a missing optional dep skips the step, never the dump."""
    import tempfile

    ran: List[str] = []

    def step(name, fn):
        try:
            fn()
            ran.append(name)
        except Exception as e:  # pragma: no cover - environment-dependent
            ran.append(f"{name}:SKIPPED({type(e).__name__})")

    ctx_box = {}

    def _build():
        from nice_tpu.server.app import ApiContext
        from nice_tpu.server.db import Db

        tmp = tempfile.mkdtemp(prefix="lockdep-exercise-")
        ctx_box["ctx"] = ApiContext(Db(f"{tmp}/exercise.db"))

    step("api-context", _build)
    ctx = ctx_box.get("ctx")
    if ctx is not None:
        step("refill", lambda: (ctx.queue.refill_niceonly(),
                                ctx.queue.refill_detailed_thin()))
        step("status-cache", lambda: (ctx.cached_fleet_block(),
                                      ctx.invalidate_status_cache(),
                                      ctx.cached_fleet_block()))
        step("inflight", lambda: (ctx.enter_request(), ctx.exit_request()))
        step("lease-sweep", lambda: ctx._sweep_leases())
        step("history-tick", lambda: ctx.history_tick())
        step("writer-roundtrip",
             lambda: ctx.writer.call(lambda: None))
        step("close", lambda: (ctx.close(), ctx.db.close()))
    step("mesh-cache", lambda: __import__(
        "nice_tpu.ops.engine", fromlist=["engine"]
    )._invalidate_mesh_cache())
    return ran


def _main(argv=None) -> int:  # pragma: no cover - exercised via CLI tests
    import argparse
    import os

    ap = argparse.ArgumentParser(
        description="lockdep runtime: exercise coordination flows and "
                    "dump the observed lock-order graph")
    ap.add_argument("--dump-graph", metavar="PATH", required=True,
                    help="write the order graph JSON here "
                         "(docs/lockorder.json in CI)")
    ap.add_argument("--no-merge", action="store_true",
                    help="overwrite instead of unioning with the existing "
                         "file")
    ap.add_argument("--no-exercise", action="store_true",
                    help="dump only what this process already observed")
    args = ap.parse_args(argv)

    os.environ["NICE_TPU_LOCKDEP"] = "1"
    if not args.no_exercise:
        ran = _exercise()
        print("lockdep: exercised " + ", ".join(ran))
    edges = dump_graph(args.dump_graph, merge=not args.no_merge)
    n = sum(len(v) for v in edges.values())
    print(f"lockdep: wrote {len(edges)} nodes / {n} edges "
          f"to {args.dump_graph}")
    for v in violations():
        print(f"lockdep: VIOLATION {v}")
    return 1 if violations() else 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    # Under `python -m` this file runs as the __main__ module, a SECOND
    # instance separate from the `nice_tpu.utils.lockdep` every project
    # lock records into — dispatch to the canonical instance or the dump
    # reads an empty graph.
    from nice_tpu.utils import lockdep as _canonical

    sys.exit(_canonical._main())
