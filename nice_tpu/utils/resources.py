"""Portable host-resource sampling, shared by the daemon and memwatch.

One home for the "read something about this machine without assuming the
TPU host image" samplers. The CPU side (``read_cpu_times`` /
``pick_cpu_backend`` / :class:`CpuMonitor`) moved here verbatim from
``daemon/main.py`` — /proc/stat jiffy deltas where available (Linux, no
deps), then ``psutil.cpu_percent`` if psutil is importable (macOS/Windows),
then a 1-minute loadavg estimate (any POSIX), then a constant-idle stub.
The memory/disk side follows the same backend-ladder discipline so
``obs/memwatch.py`` gets host RSS and on-disk footprints on a dev laptop,
not only on Linux:

* ``rss_bytes()``      — current resident set (/proc/self/status -> psutil
                         -> ru_maxrss peak as a last resort -> None);
* ``peak_rss_bytes()`` — process-lifetime peak RSS via getrusage;
* ``host_memory_total_bytes()`` — physical RAM (exhaustion headroom);
* ``dir_bytes()``      — recursive on-disk footprint of a directory;
* ``fs_free_bytes()``  — free bytes on the filesystem holding a path.

Import-light on purpose (stdlib only, psutil strictly optional): the
jax-free server and conftest import this transitively through obs.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Callable, Optional

__all__ = [
    "read_cpu_times",
    "pick_cpu_backend",
    "CpuMonitor",
    "pick_rss_backend",
    "rss_bytes",
    "peak_rss_bytes",
    "host_memory_total_bytes",
    "dir_bytes",
    "fs_free_bytes",
]


# --- CPU (moved from daemon/main.py; behavior byte-identical) -------------


def read_cpu_times() -> tuple[int, int]:
    """(idle, total) jiffies from /proc/stat (Linux backend)."""
    with open("/proc/stat") as f:
        parts = f.readline().split()
    values = [int(v) for v in parts[1:]]
    idle = values[3] + (values[4] if len(values) > 4 else 0)  # idle + iowait
    return idle, sum(values)


def pick_cpu_backend() -> str:
    """Best available whole-machine CPU sampler for this platform.

    Deliberately does NOT call read_cpu_times() (only stats the path) so
    tests can stub the reader with a finite sequence of readings.
    """
    if os.path.exists("/proc/stat"):
        return "proc"
    try:
        import psutil  # noqa: F401

        return "psutil"
    except ImportError:
        pass
    return "loadavg" if hasattr(os, "getloadavg") else "none"


class CpuMonitor:
    """Rolling CPU utilization sampler (reference daemon/src/main.rs:39-122).

    backend: "proc" (jiffy deltas), "psutil" (cpu_percent), "loadavg"
    (1-min load / cores, clipped to 1.0), or "none" (always idle — the
    daemon degrades to an unconditional supervisor rather than refusing to
    run). Default: pick_cpu_backend().

    ``reader`` lets the daemon route "proc" reads through its own module
    global, keeping ``monkeypatch.setattr(daemon, "read_cpu_times", ...)``
    working after the move here.
    """

    def __init__(self, interval_secs: float = 5.0, backend: str | None = None,
                 reader: Optional[Callable[[], tuple]] = None):
        self.interval = interval_secs
        self.backend = backend or pick_cpu_backend()
        self._reader = reader or read_cpu_times
        if self.backend == "proc":
            self._last = self._reader()
        elif self.backend == "psutil":
            import psutil

            self._psutil = psutil
            psutil.cpu_percent(interval=None)  # prime the rolling window

    def sample(self) -> float:
        """Blocking sample: CPU usage fraction over the interval."""
        time.sleep(self.interval)
        if self.backend == "proc":
            idle, total = self._reader()
            last_idle, last_total = self._last
            self._last = (idle, total)
            d_total = total - last_total
            if d_total <= 0:
                return 0.0
            return 1.0 - (idle - last_idle) / d_total
        if self.backend == "psutil":
            return self._psutil.cpu_percent(interval=None) / 100.0
        if self.backend == "loadavg":
            try:
                load1 = os.getloadavg()[0]
            except OSError:
                return 0.0
            return min(1.0, load1 / (os.cpu_count() or 1))
        return 0.0  # "none": report idle; spawning is the safe default


# --- memory ---------------------------------------------------------------


def pick_rss_backend() -> str:
    """Best available resident-set reader for this platform. Mirrors
    pick_cpu_backend: stat the proc path, never read it, so tests can stub
    the file contents independently of selection."""
    if os.path.exists("/proc/self/status"):
        return "proc"
    try:
        import psutil  # noqa: F401

        return "psutil"
    except ImportError:
        pass
    try:
        import resource  # noqa: F401

        return "rusage"
    except ImportError:
        return "none"


def _rusage_scale() -> int:
    # ru_maxrss is KiB on Linux, bytes on macOS.
    return 1 if sys.platform == "darwin" else 1024


def rss_bytes(backend: str | None = None) -> Optional[int]:
    """Current resident set size of THIS process in bytes, or None when no
    backend can answer. The "rusage" fallback reports the lifetime PEAK
    (the kernel keeps no current-RSS counter there) — still monotone
    evidence for leak trends, just conservative."""
    backend = backend or pick_rss_backend()
    if backend == "proc":
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1]) * 1024
        except (OSError, ValueError, IndexError):
            return None
        return None
    if backend == "psutil":
        try:
            import psutil

            return int(psutil.Process().memory_info().rss)
        except Exception:  # noqa: BLE001 — process table races
            return None
    if backend == "rusage":
        return peak_rss_bytes()
    return None


def peak_rss_bytes() -> Optional[int]:
    """Lifetime peak resident set of this process (getrusage; POSIX)."""
    try:
        import resource

        return int(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            * _rusage_scale()
        )
    except Exception:  # noqa: BLE001 — non-POSIX
        return None


def host_memory_total_bytes() -> Optional[int]:
    """Physical RAM on this host (the RSS exhaustion ceiling), or None."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import psutil

        return int(psutil.virtual_memory().total)
    except Exception:  # noqa: BLE001 — psutil absent or broken
        return None


# --- disk -----------------------------------------------------------------


def dir_bytes(path: str) -> Optional[int]:
    """Recursive on-disk footprint of ``path`` in bytes (0 for an empty
    dir, the file's size for a plain file, None when the path is absent).
    Files that vanish mid-walk are skipped, not errors."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    if not os.path.isdir(path):
        return int(st.st_size)
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for name in filenames:
            try:
                total += os.lstat(os.path.join(dirpath, name)).st_size
            except OSError:
                continue
    return total


def fs_free_bytes(path: str) -> Optional[int]:
    """Free bytes (non-root-reserved) on the filesystem holding ``path``."""
    try:
        sv = os.statvfs(path)
    except (OSError, AttributeError):
        return None
    return int(sv.f_bavail) * int(sv.f_frsize)
