"""Shared host-side utilities (platform forcing, watchdog probes)."""
