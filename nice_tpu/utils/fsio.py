"""Atomic state-file writes: same-directory temp file + fsync + rename.

Every module that persists state (checkpoint snapshots, the autotune
winners table, spool journals, flight-recorder dumps) must write through
this helper — the A1 nicelint rule flags any other write-mode ``open()``
inside the package. Centralizing the recipe keeps the three load-bearing
properties from drifting per call site:

* the temp file lives in the TARGET directory (``os.replace`` across
  filesystems is not atomic);
* file contents are fsync'd before the rename, so the rename can never
  publish a partially written file after power loss;
* the directory entry is fsync'd after the rename (best-effort — skipped
  quietly on filesystems that refuse O_RDONLY directory fds), so the
  rename itself survives power loss.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

__all__ = ["atomic_write_bytes", "atomic_write_text", "atomic_write_json",
           "fsync_directory"]


def fsync_directory(path: str) -> None:
    """Best-effort fsync of the directory containing ``path``."""
    try:
        dfd = os.open(
            os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY
        )
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def atomic_write_bytes(path: str, data: bytes, *,
                       sync_directory: bool = True) -> int:
    """Atomically replace ``path`` with ``data``; returns len(data).

    On any failure the temp file is removed and the original ``path`` is
    left untouched (the error propagates)."""
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:  # nicelint: allow A1 (the helper itself)
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if sync_directory:
        fsync_directory(path)
    return len(data)


def atomic_write_text(path: str, text: str, *, encoding: str = "utf-8",
                      sync_directory: bool = True) -> int:
    return atomic_write_bytes(
        path, text.encode(encoding), sync_directory=sync_directory
    )


def atomic_write_json(path: str, obj: Any, *, indent: Optional[int] = None,
                      sort_keys: bool = False, default=None,
                      sync_directory: bool = True) -> int:
    return atomic_write_text(
        path,
        json.dumps(obj, indent=indent, sort_keys=sort_keys, default=default),
        sync_directory=sync_directory,
    )
