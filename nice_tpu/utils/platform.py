"""Host platform helpers shared by tests/conftest.py, bench.py and
__graft_entry__.py.

Two recurring needs around the axon tunnel (one real TPU chip shared with the
driver) are centralized here so the recipe cannot diverge between the test
suite, the benchmark runner, and the multichip dryrun:

- forcing a VIRTUAL CPU device mesh before jax backend init. The env var
  JAX_PLATFORMS=cpu alone is not enough: the axon PJRT plugin overrides it at
  import time, so callers must also jax.config.update("jax_platforms", "cpu")
  after import; and --xla_force_host_platform_device_count must be in
  XLA_FLAGS before the CPU backend initializes.
- probing backend init under a watchdog. Init can HANG indefinitely (a wedged
  device lease on the tunnel), not just raise, so a plain try/except never
  returns; the probe runs in a daemon thread with a timeout.

This module must stay import-light (no jax at module import) so conftest can
use it before any jax import.
"""

from __future__ import annotations

from typing import MutableMapping


def force_virtual_cpu(env: MutableMapping[str, str], n_devices: int = 8) -> None:
    """Mutate env (os.environ or a subprocess env dict) so the NEXT jax import
    in that environment sees >= n_devices virtual CPU devices."""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)


def probe_backend(timeout_s: float = 60.0, platform: str | None = None):
    """(device_count | None, error | None): import jax, optionally force a
    platform via jax.config, and count devices — inside a watchdog thread.

    Returns (n, None) on success; (None, exc) on an init exception; and
    (None, TimeoutError) when init hangs past timeout_s. The hung daemon
    thread cannot be joined — callers that need a clean retry should re-exec
    or subprocess (jax also caches a FAILED backend, so in-process retries
    see the same error)."""
    import threading

    result: dict = {}

    def probe():
        try:
            import jax

            if platform:
                jax.config.update("jax_platforms", platform)
            result["n"] = len(jax.devices())
        except Exception as exc:  # noqa: BLE001 — callers decide retryability
            result["exc"] = exc

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "n" in result:
        return result["n"], None
    return None, result.get(
        "exc",
        TimeoutError(
            f"jax backend init hung >{timeout_s:.0f}s (wedged device lease?)"
        ),
    )
