"""Host platform helpers shared by tests/conftest.py, bench.py and
__graft_entry__.py.

Two recurring needs around the axon tunnel (one real TPU chip shared with the
driver) are centralized here so the recipe cannot diverge between the test
suite, the benchmark runner, and the multichip dryrun:

- forcing a VIRTUAL CPU device mesh before jax backend init. The env var
  JAX_PLATFORMS=cpu alone is not enough: the axon PJRT plugin overrides it at
  import time, so callers must also jax.config.update("jax_platforms", "cpu")
  after import; and --xla_force_host_platform_device_count must be in
  XLA_FLAGS before the CPU backend initializes.
- probing backend init under a watchdog. Init can HANG indefinitely (a wedged
  device lease on the tunnel), not just raise, so a plain try/except never
  returns; the probe runs in a daemon thread with a timeout.

This module must stay import-light (no jax at module import) so conftest can
use it before any jax import.
"""

from __future__ import annotations

from typing import MutableMapping


def force_virtual_cpu(env: MutableMapping[str, str], n_devices: int = 8) -> None:
    """Mutate env (os.environ or a subprocess env dict) so the NEXT jax import
    in that environment sees >= n_devices virtual CPU devices."""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)


def probe_backend(
    timeout_s: float = 60.0,
    platform: str | None = None,
    _devices_fn=None,
):
    """(device_count | None, error | None): import jax, optionally force a
    platform via jax.config, and count devices — inside a watchdog thread.

    Returns (n, None) on success; (None, exc) on an init exception; and
    (None, TimeoutError) when init hangs past timeout_s. The TimeoutError
    message names the phase that was running when the watchdog fired
    (import-jax / configure / devices), and each phase runs inside an
    obs span, so a wedged device lease leaves a begin-without-end trace
    record identifying exactly where init stalled. The hung daemon thread
    cannot be joined — callers that need a clean retry should re-exec or
    subprocess (jax also caches a FAILED backend, so in-process retries
    see the same error).

    _devices_fn is a test hook replacing the `len(jax.devices())` step so a
    hang can be simulated without wedging a real backend."""
    import threading
    import time

    from nice_tpu import obs
    from nice_tpu.obs.series import BACKEND_INIT_SECONDS

    result: dict = {"phase": "import-jax"}

    def phase(name):
        result["phase"] = name
        result["t_phase"] = time.perf_counter()
        return obs.span("backend-init." + name, platform=platform or "default")

    def observe_phase():
        BACKEND_INIT_SECONDS.observe(
            time.perf_counter() - result["t_phase"], (result["phase"],)
        )

    def probe():
        try:
            with phase("import-jax"):
                import jax
            observe_phase()
            if platform:
                with phase("configure"):
                    jax.config.update("jax_platforms", platform)
                observe_phase()
            with phase("devices"):
                if _devices_fn is not None:
                    result["n"] = _devices_fn()
                else:
                    result["n"] = len(jax.devices())
            observe_phase()
        except Exception as exc:  # noqa: BLE001 — callers decide retryability
            result["exc"] = exc

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "n" in result:
        return result["n"], None
    if "exc" in result:
        return None, result["exc"]
    stalled = result["phase"]
    obs.trace_event(
        "backend-init", "timeout", phase=stalled, timeout_s=timeout_s
    )
    return None, TimeoutError(
        f"jax backend init hung >{timeout_s:.0f}s in phase"
        f" '{stalled}' (wedged device lease?)"
    )


# The probe child is tiny enough to inline: optionally simulate a hang (test
# hook), import jax, force the platform, print the device count. Everything
# jax touches stays in the child.
_PROBE_CHILD = """\
import os, sys
hang = float(os.environ.get("NICE_PROBE_TEST_HANG", "0") or 0)
if hang:
    import time
    time.sleep(hang)
import jax
plat = sys.argv[1]
if plat:
    jax.config.update("jax_platforms", plat)
sys.stdout.write(str(len(jax.devices())))
"""


def probe_backend_subprocess(
    timeout_s: float = 60.0,
    platform: str | None = None,
):
    """HARD-watchdog variant of probe_backend: init runs in a child process
    that is killed outright on timeout.

    The daemon-thread watchdog above detects a hang but cannot reclaim it —
    the thread is unjoinable and jax has cached a failed backend, so the
    only clean retry is re-exec'ing the whole process. Here the parent never
    imports jax: a wedged init is SIGKILLed with the child, leaving the
    caller jax-clean and free to retry in-process. Same (count | None,
    error | None) contract. The NICE_PROBE_TEST_HANG env var (seconds)
    makes the child sleep before importing jax so tests can exercise the
    kill path without wedging a real backend."""
    import subprocess
    import sys

    from nice_tpu import obs

    with obs.span(
        "backend-init.subprocess-probe", platform=platform or "default"
    ):
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_CHILD, platform or ""],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            out, err_text = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            obs.trace_event(
                "backend-init", "timeout", phase="subprocess-probe",
                timeout_s=timeout_s,
            )
            return None, TimeoutError(
                f"jax backend init hung >{timeout_s:.0f}s"
                f" (probe subprocess killed; wedged device lease?)"
            )
    if proc.returncode == 0:
        try:
            return int(out.strip().split()[-1]), None
        except (ValueError, IndexError):
            pass
    tail = (err_text or out or "").strip().splitlines()
    detail = tail[-1] if tail else f"exit code {proc.returncode}"
    return None, RuntimeError(f"backend probe subprocess failed: {detail}")
