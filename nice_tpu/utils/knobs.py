"""Central registry of NICE_TPU_* environment knobs.

Every NICE_TPU_* environment variable the project reads is declared here
exactly once, with its type, canonical default, owning module, and one-line
doc. Call sites read through the returned :class:`Knob` (``knob.get()``,
``knob.get_bool()``, ``knob.raw()``) instead of touching ``os.environ``
directly — the K1 nicelint rule enforces that statically, and
``docs/KNOBS.md`` plus the README knob tables are generated from this
catalog (drift is a K1 violation too).

Design constraints:

* **Import-light.** This module imports only the stdlib (``os``), so the
  jax-free server, conftest (pre-jax), and the analysis suite can all use
  it freely.
* **Call-time reads.** ``get()`` consults ``os.environ`` on every call —
  never caches — because tests monkeypatch the environment mid-process and
  several knobs are documented as flippable at runtime (NICE_TPU_STEPPROF,
  NICE_TPU_TRACE).
* **Behavior-preserving coercion.** ``get()`` coerces exactly like the
  historical inline ``int(os.environ.get(...))`` sites did (a malformed
  value raises ValueError); sites that historically guarded with
  try/except keep their guards around ``get()``. Boolean knobs accept the
  unified spelling sets ``{"1","true","on","yes"}`` / ``{"0","false",
  "off","no"}``; a default-on knob stays on for unrecognized values, a
  default-off knob stays off.
* **Computed defaults stay at the call site.** A knob whose default is
  derived from another module's constant (e.g. NICE_TPU_CLAIM_EXPIRY_SECS
  defaulting to CLAIM_DURATION_HOURS) passes ``default=`` to ``get()``;
  the registry carries a human-readable ``default_doc`` for the tables.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = [
    "Knob",
    "PrefixFamily",
    "REGISTRY",
    "PREFIXES",
    "lookup",
    "is_declared",
    "all_knobs",
    "render_markdown",
    "render_group_markdown",
]

_UNSET = object()

_TRUE_SET = ("1", "true", "on", "yes")
_FALSE_SET = ("0", "false", "off", "no")


class Knob:
    """One declared environment knob. Immutable after registration."""

    __slots__ = ("name", "kind", "default", "doc", "owner", "group",
                 "default_doc")

    def __init__(self, name: str, kind: str, default: Any, doc: str,
                 owner: str, group: str, default_doc: Optional[str]):
        self.name = name
        self.kind = kind  # "int" | "float" | "str" | "bool" | "spec"
        self.default = default
        self.doc = doc
        self.owner = owner
        self.group = group
        self.default_doc = default_doc

    def raw(self) -> Optional[str]:
        """The uninterpreted environment value (None when unset)."""
        return os.environ.get(self.name)

    def is_set(self) -> bool:
        return self.name in os.environ

    def get(self, default: Any = _UNSET) -> Any:
        """Coerced value: env wins, else ``default`` (call-site override),
        else the registry default. Coercion errors propagate (ValueError),
        matching the historical inline-read behavior."""
        fallback = self.default if default is _UNSET else default
        value = os.environ.get(self.name)
        if value is None:
            return fallback
        if self.kind == "int":
            return int(value)
        if self.kind == "float":
            return float(value)
        if self.kind == "bool":
            return self.get_bool(
                bool(fallback) if fallback is not None else False
            )
        return value

    def get_bool(self, default: Any = _UNSET) -> bool:
        """Unified boolean parse. The empty string counts as unset, and
        unrecognized spellings keep the default, so a default-on knob only
        turns off for an explicit falsy value and vice versa."""
        fallback = bool(self.default if default is _UNSET else default)
        value = os.environ.get(self.name)
        if value is None:
            return fallback
        v = value.strip().lower()
        if v in _TRUE_SET:
            return True
        if v in _FALSE_SET:
            return False
        return fallback

    @property
    def default_text(self) -> str:
        if self.default_doc:
            return self.default_doc
        if self.default is None:
            return "unset"
        if self.kind == "bool":
            return "on" if self.default else "off"
        return repr(self.default).strip("'\"") or '""'

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Knob({self.name}, {self.kind}, default={self.default!r})"


class PrefixFamily:
    """A family of dynamically named knobs sharing a prefix (the per-SLO
    NICE_TPU_SLO_<NAME>_THRESHOLD / _OBJECTIVE overrides). ``matches``
    makes the K1 literal check accept any member name."""

    __slots__ = ("prefix", "suffixes", "kind", "doc", "owner", "group")

    def __init__(self, prefix: str, suffixes: tuple, kind: str, doc: str,
                 owner: str, group: str):
        self.prefix = prefix
        self.suffixes = suffixes
        self.kind = kind
        self.doc = doc
        self.owner = owner
        self.group = group

    def matches(self, name: str) -> bool:
        return name.startswith(self.prefix) and (
            not self.suffixes or name.endswith(self.suffixes)
        )

    def get_float(self, name: str, default: float) -> float:
        if not self.matches(name):
            raise KeyError(
                f"{name} is not a member of knob family {self.prefix}*"
            )
        try:
            return float(os.environ.get(name, default))
        except (TypeError, ValueError):
            return default

    @property
    def name(self) -> str:
        suff = "|".join(self.suffixes) if self.suffixes else "*"
        return f"{self.prefix}<NAME>{{{suff}}}"


REGISTRY: Dict[str, Knob] = {}
PREFIXES: List[PrefixFamily] = []


def _k(name: str, kind: str, default: Any, doc: str, *, owner: str,
       group: str = "general", default_doc: Optional[str] = None) -> Knob:
    if name in REGISTRY:
        raise ValueError(f"duplicate knob declaration: {name}")
    knob = Knob(name, kind, default, doc, owner, group, default_doc)
    REGISTRY[name] = knob
    return knob


def _family(prefix: str, suffixes: tuple, kind: str, doc: str, *,
            owner: str, group: str = "general") -> PrefixFamily:
    fam = PrefixFamily(prefix, suffixes, kind, doc, owner, group)
    PREFIXES.append(fam)
    return fam


def lookup(name: str) -> Knob:
    """The declared knob for ``name``; KeyError for undeclared names (the
    runtime arm of the K1 discipline — dynamic lookups can't bypass the
    catalog either)."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not a declared knob; add it to"
            " nice_tpu/utils/knobs.py"
        ) from None


def is_declared(name: str) -> bool:
    if name in REGISTRY:
        return True
    return any(f.matches(name) for f in PREFIXES)


def all_knobs() -> List[Knob]:
    return sorted(REGISTRY.values(), key=lambda k: (k.group, k.name))


# ---------------------------------------------------------------------------
# The catalog. Grouped the way docs/KNOBS.md renders them.
# ---------------------------------------------------------------------------

# -- engine / device pipeline (ops/) ---------------------------------------
BATCH = _k(
    "NICE_TPU_BATCH", "int", None,
    "Per-dispatch batch size override (env > autotuned > default).",
    owner="ops/autotune.py", group="engine",
    default_doc="autotuned per (mode, base, backend)",
)
BLOCK_ROWS = _k(
    "NICE_TPU_BLOCK_ROWS", "int", None,
    "Pallas kernel block-rows override (env > autotuned > default).",
    owner="ops/autotune.py", group="engine",
    default_doc="autotuned per (mode, base, backend)",
)
CARRY_INTERVAL = _k(
    "NICE_TPU_CARRY_INTERVAL", "int", None,
    "Carry-save limb-product carry interval override (env > autotuned >"
    " default).",
    owner="ops/autotune.py", group="engine",
    default_doc="autotuned per (mode, base, backend)",
)
MXU = _k(
    "NICE_TPU_MXU", "int", None,
    "Limb-multiply engine override: 1 routes mul/sqr through the banded"
    " Toeplitz dot_general MXU path (ops/mxu.py), 0 pins the VPU carry-save"
    " path (env > autotuned > default off).",
    owner="ops/autotune.py", group="engine",
    default_doc="autotuned per (mode, base, backend)",
)
MEGALOOP = _k(
    "NICE_TPU_MEGALOOP", "bool", True,
    "Device-resident megaloop: fuse NICE_TPU_MEGALOOP_SEGMENT batch"
    " iterations into one lax.scan dispatch with an in-program field cursor"
    " (0 reverts to the per-batch feed loop).",
    owner="ops/engine.py", group="engine",
)
MEGALOOP_SEGMENT = _k(
    "NICE_TPU_MEGALOOP_SEGMENT", "int", None,
    "Megaloop segment length override — batch iterations fused per dispatch;"
    " also the checkpoint/readback cadence (env > autotuned > default 8).",
    owner="ops/autotune.py", group="engine",
    default_doc="autotuned per (mode, base, backend)",
)
FUSED_FILTER = _k(
    "NICE_TPU_FUSED_FILTER", "bool", True,
    "Fuse the residue filter into the dense niceonly device kernel so"
    " pruned candidates never enter limb math (0 = filter stays on the"
    " host/native paths only).",
    owner="ops/engine.py", group="engine",
)
AUTOTUNE_FILE = _k(
    "NICE_TPU_AUTOTUNE_FILE", "str", None,
    "Path of the persisted autotuner winners table (falls back to"
    " JAX_COMPILATION_CACHE_DIR, then ~/.cache/nice_tpu/).",
    owner="ops/autotune.py", group="engine",
)
NO_FALLBACK = _k(
    "NICE_TPU_NO_FALLBACK", "bool", False,
    "Disable the pallas -> jnp -> scalar mid-field backend fallback chain"
    " (dispatch failures become fatal).",
    owner="ops/engine.py", group="engine",
)
SHARD = _k(
    "NICE_TPU_SHARD", "bool", True,
    "Multi-chip sharded dispatch (0 forces single-device execution).",
    owner="ops/engine.py", group="engine",
)
ELASTIC = _k(
    "NICE_TPU_ELASTIC", "bool", True,
    "Elastic mesh downshift: reshard a field onto surviving devices on"
    " device loss instead of degrading down the backend chain.",
    owner="ops/engine.py", group="engine",
)
FEED_DEPTH = _k(
    "NICE_TPU_FEED_DEPTH", "int", 2,
    "Depth of the double-buffered host->device feed queue (0 = synchronous"
    " feed on the dispatch thread; clamped to 64).",
    owner="ops/engine.py", group="engine",
)
HOST_NICEONLY_MAX_KNOB = _k(
    "NICE_TPU_HOST_NICEONLY_MAX", "int", 1 << 25,
    "Small-field host-route threshold for niceonly scans (0 disables the"
    " native host route).",
    owner="ops/engine.py", group="engine",
    default_doc="HOST_NICEONLY_MAX (2^25)",
)
AUDIT_EVERY = _k(
    "NICE_TPU_AUDIT_EVERY", "int", 1024,
    "Device-vs-host audit cadence for strided batches (every Nth batch).",
    owner="ops/engine.py", group="engine",
    default_doc="STRIDE_AUDIT_EVERY (1024)",
)
MSD_FLOOR = _k(
    "NICE_TPU_MSD_FLOOR", "str", None,
    "Pin the adaptive niceonly MSD host-filter floor for every pipeline"
    " (integer; unset = adaptive controller).",
    owner="ops/adaptive_floor.py", group="engine",
)
CKPT_BATCHES = _k(
    "NICE_TPU_CKPT_BATCHES", "int", 256,
    "Checkpoint cadence in dispatch batches (0 disables this trigger).",
    owner="ops/engine.py", group="engine",
    default_doc="CKPT_EVERY_BATCHES (256)",
)
CKPT_SECS = _k(
    "NICE_TPU_CKPT_SECS", "float", 30.0,
    "Checkpoint cadence in seconds (0 disables this trigger).",
    owner="ops/engine.py", group="engine",
    default_doc="CKPT_EVERY_SECS (30)",
)
COMPILE_CACHE_MAX_EXECUTABLES = _k(
    "NICE_TPU_COMPILE_CACHE_MAX_EXECUTABLES", "int", 64,
    "LRU cap on the in-process AOT executable cache: past this many"
    " distinct (mode, backend, plan, shape) keys the least-recently-hit"
    " executable is dropped (counted as layer=executable, event=evicted in"
    " nice_compile_cache_events_total; 0 = unbounded).",
    owner="ops/compile_cache.py", group="engine",
)

# -- client ----------------------------------------------------------------
CLAIM_BLOCK = _k(
    "NICE_TPU_CLAIM_BLOCK", "int", 1,
    "Fields requested per /claim_block lease (client-side block size).",
    owner="client/main.py", group="client",
)
PREFETCH = _k(
    "NICE_TPU_PREFETCH", "bool", True,
    "AOT-warm the next field's executable while the current one scans.",
    owner="client/main.py", group="client",
)
SPOOL_QUARANTINE_MAX_BYTES = _k(
    "NICE_TPU_SPOOL_QUARANTINE_MAX_BYTES", "int", 64 * 1024 * 1024,
    "Retention cap on quarantined (.rejected) spool entries: oldest"
    " entries are pruned once their total size exceeds this many bytes"
    " (0 = keep forever). Pruned bytes land in"
    " nice_spool_quarantine_pruned_bytes_total plus a quarantine_pruned"
    " flight event.",
    owner="faults/spool.py", group="client",
)
SPOOL_QUARANTINE_MAX_AGE_SECS = _k(
    "NICE_TPU_SPOOL_QUARANTINE_MAX_AGE_SECS", "float", 7 * 24 * 3600.0,
    "Age bound on quarantined (.rejected) spool entries: entries older"
    " than this are pruned on the next quarantine or replay pass"
    " (0 = no age bound).",
    owner="faults/spool.py", group="client",
)

# -- server coordination tier ----------------------------------------------
SERVER_CORE = _k(
    "NICE_TPU_SERVER_CORE", "str", "async",
    "Request core: 'async' (event loop + bounded worker pool) or 'thread'"
    " (legacy thread-per-connection).",
    owner="server/app.py", group="server",
)
SERVER_WORKERS = _k(
    "NICE_TPU_SERVER_WORKERS", "int", 32,
    "Bounded handler worker-pool size of the async core.",
    owner="server/async_core.py", group="server",
)
MAX_INFLIGHT = _k(
    "NICE_TPU_MAX_INFLIGHT", "int", 128,
    "In-flight request ceiling before the loop sheds with 503 +"
    " Retry-After.",
    owner="server/app.py", group="server",
)
RETRY_AFTER_SECS = _k(
    "NICE_TPU_RETRY_AFTER_SECS", "int", 2,
    "Retry-After hint attached to 503 overload sheds.",
    owner="server/app.py", group="server",
)
WRITER = _k(
    "NICE_TPU_WRITER", "bool", True,
    "Single-writer DB actor (0 = direct per-call transactions, debugging"
    " only; semantics identical).",
    owner="server/app.py", group="server",
)
WRITER_MAX_BATCH = _k(
    "NICE_TPU_WRITER_MAX_BATCH", "int", 64,
    "Max mutations coalesced into one writer-actor transaction.",
    owner="server/writer.py", group="server",
)
WRITER_COALESCE_SECS = _k(
    "NICE_TPU_WRITER_COALESCE_SECS", "float", 0.002,
    "How long the writer drain loop lingers for stragglers after the queue"
    " empties.",
    owner="server/writer.py", group="server",
)
STATUS_CACHE_SECS = _k(
    "NICE_TPU_STATUS_CACHE_SECS", "float", 2.0,
    "TTL of the /status fleet-block read-snapshot cache.",
    owner="server/app.py", group="server",
)
MAX_CLAIM_BLOCK = _k(
    "NICE_TPU_MAX_CLAIM_BLOCK", "int", 128,
    "Server-side cap on fields per /claim_block lease.",
    owner="server/app.py", group="server",
)
CLAIM_EXPIRY_SECS = _k(
    "NICE_TPU_CLAIM_EXPIRY_SECS", "float", None,
    "Claim-lease window; leases older than this are re-claimable.",
    owner="server/db.py", group="server",
    default_doc="CLAIM_DURATION_HOURS * 3600 (1h)",
)
QUEUE_POLL_SECS = _k(
    "NICE_TPU_QUEUE_POLL_SECS", "float", 5.0,
    "Low-water poll cadence of the field pre-generation pipeline.",
    owner="server/field_queue.py", group="server",
)
FLEET_ACTIVE_SECS = _k(
    "NICE_TPU_FLEET_ACTIVE_SECS", "float", 900.0,
    "Telemetry freshness window for counting a client as active in the"
    " fleet block.",
    owner="server/app.py", group="server",
)

# -- untrusted-client hardening --------------------------------------------
TRUST_THRESHOLD = _k(
    "NICE_TPU_TRUST_THRESHOLD", "float", 0.0,
    "Trust needed to make canon directly (0 = consensus gating off).",
    owner="server/trust.py", group="untrusted",
)
SPOT_RATE = _k(
    "NICE_TPU_SPOT_RATE", "float", 0.01,
    "Spot-check sampling floor for veteran clients.",
    owner="server/trust.py", group="untrusted",
)
SPOT_SEED = _k(
    "NICE_TPU_SPOT_SEED", "str", None,
    "Spot-check RNG seed override — tests only.",
    owner="server/trust.py", group="untrusted",
    default_doc="random per-process secret",
)
SPOT_SLICE = _k(
    "NICE_TPU_SPOT_SLICE", "int", 256,
    "Numbers re-run per spot check (0 disables slices).",
    owner="server/trust.py", group="untrusted",
)
UNTRUSTED_LEASE_SECS = _k(
    "NICE_TPU_UNTRUSTED_LEASE_SECS", "float", 120.0,
    "Lease window for untrusted claims.",
    owner="server/app.py", group="untrusted",
)
UNTRUSTED_MAX_FIELD = _k(
    "NICE_TPU_UNTRUSTED_MAX_FIELD", "int", 1_000_000,
    "Range-size cap (micro-fields) for untrusted claims.",
    owner="server/app.py", group="untrusted",
)
UNTRUSTED_MAX_CLAIMS = _k(
    "NICE_TPU_UNTRUSTED_MAX_CLAIMS", "int", 16,
    "Outstanding-claim cap per untrusted client.",
    owner="server/app.py", group="untrusted",
)
UNTRUSTED_MAX_CLAIMS_PER_IP = _k(
    "NICE_TPU_UNTRUSTED_MAX_CLAIMS_PER_IP", "int", 256,
    "Aggregate outstanding-claim ceiling per source IP.",
    owner="server/app.py", group="untrusted",
)
LEASE_SWEEP_SECS = _k(
    "NICE_TPU_LEASE_SWEEP_SECS", "float", 5.0,
    "Cadence of the writer-thread expired-lease sweep (0 disables).",
    owner="server/app.py", group="untrusted",
)
RATE_BUCKET = _k(
    "NICE_TPU_RATE_BUCKET", "spec", None,
    'Opt-in per-client token buckets, "capacity:refill_per_sec" (reads get'
    " 4x; unset = limiter off).",
    owner="server/async_core.py", group="untrusted",
    default_doc='off (opt-in; "300:100" once set empty)',
)

# -- observability ---------------------------------------------------------
METRICS_PORT = _k(
    "NICE_TPU_METRICS_PORT", "str", None,
    "Serve the local /metrics endpoint on this port (0 = ephemeral; unset ="
    " off).",
    owner="obs/serve.py", group="obs",
)
TRACE = _k(
    "NICE_TPU_TRACE", "str", None,
    'Structured trace sink: "stderr" or a file path (unset = tracing off).',
    owner="obs/trace.py", group="obs",
)
TRACE_MAX_BYTES = _k(
    "NICE_TPU_TRACE_MAX_BYTES", "int", 64 * 1024 * 1024,
    "File trace sink size cap before one-shot rotation to <path>.1.",
    owner="obs/trace.py", group="obs",
    default_doc="DEFAULT_MAX_SINK_BYTES (64 MiB)",
)
PROFILE = _k(
    "NICE_TPU_PROFILE", "str", None,
    "jax.profiler capture output directory (unset = no capture).",
    owner="obs/trace.py", group="obs",
)
STEPPROF = _k(
    "NICE_TPU_STEPPROF", "bool", False,
    "Device-step profiler: per-field phase-attributed wall time with zero"
    " added device syncs while disabled.",
    owner="obs/stepprof.py", group="obs",
)
FLIGHT_DIR = _k(
    "NICE_TPU_FLIGHT_DIR", "str", None,
    "Directory for flight-recorder dumps.",
    owner="obs/flight.py", group="obs",
    default_doc="system temp dir",
)
FLIGHT_EVENTS = _k(
    "NICE_TPU_FLIGHT_EVENTS", "int", 512,
    "Flight-recorder ring capacity (min 16).",
    owner="obs/flight.py", group="obs",
    default_doc="DEFAULT_CAPACITY (512)",
)
HISTORY_SECS = _k(
    "NICE_TPU_HISTORY_SECS", "float", 15.0,
    "History sampling cadence (0 disables the sampler).",
    owner="obs/history.py", group="obs",
)
HISTORY_RAW_CAP = _k(
    "NICE_TPU_HISTORY_RAW_CAP", "int", 240,
    "Raw-tier ring capacity per history series.",
    owner="obs/history.py", group="obs",
)
HISTORY_1M_CAP = _k(
    "NICE_TPU_HISTORY_1M_CAP", "int", 360,
    "1-minute-tier ring capacity per history series.",
    owner="obs/history.py", group="obs",
)
HISTORY_15M_CAP = _k(
    "NICE_TPU_HISTORY_15M_CAP", "int", 672,
    "15-minute-tier ring capacity per history series.",
    owner="obs/history.py", group="obs",
)
HISTORY_1M_SECS = _k(
    "NICE_TPU_HISTORY_1M_SECS", "float", 60.0,
    "Width of the first coarse history tier's buckets (env-scalable for"
    " short harness runs).",
    owner="obs/history.py", group="obs",
)
HISTORY_15M_SECS = _k(
    "NICE_TPU_HISTORY_15M_SECS", "float", 900.0,
    "Width of the second coarse history tier's buckets.",
    owner="obs/history.py", group="obs",
)
HISTORY_RETENTION_SECS = _k(
    "NICE_TPU_HISTORY_RETENTION_SECS", "float", 7 * 24 * 3600.0,
    "Server-side metric_history table retention (pruned on the writer"
    " periodic).",
    owner="server/app.py", group="obs",
)
SLO_WINDOW_SCALE = _k(
    "NICE_TPU_SLO_WINDOW_SCALE", "float", 1.0,
    "Scales every SLO burn-rate window (short harness runs exercise real"
    " transitions in seconds).",
    owner="obs/slo.py", group="obs",
)
SLO_OVERRIDES = _family(
    "NICE_TPU_SLO_", ("_THRESHOLD", "_OBJECTIVE"), "float",
    "Per-SLO threshold/objective overrides, e.g."
    " NICE_TPU_SLO_CLAIM_P99_THRESHOLD.",
    owner="obs/slo.py", group="obs",
)
LOG_LEVEL = _k(
    "NICE_TPU_LOG_LEVEL", "str", None,
    "Root log level for the unified JSON log sink (trace/debug/info/warn/"
    "error; unset = the installing main's default).",
    owner="obs/logsink.py", group="obs",
)
LOG_FILE = _k(
    "NICE_TPU_LOG_FILE", "str", None,
    "Append JSON log lines to this file in addition to stderr (unset ="
    " stderr only).",
    owner="obs/logsink.py", group="obs",
)
JOURNAL_RETENTION_SECS = _k(
    "NICE_TPU_JOURNAL_RETENTION_SECS", "float", 7 * 24 * 3600.0,
    "field_events audit-journal retention (pruned on the writer periodic;"
    " 0 disables pruning).",
    owner="server/app.py", group="obs",
)
JOURNAL_FEED_LIMIT = _k(
    "NICE_TPU_JOURNAL_FEED_LIMIT", "int", 500,
    "Max rows per GET /events page (the cursor feed's server-side clamp).",
    owner="server/app.py", group="obs",
)
ANOMALY_WINDOW_SECS = _k(
    "NICE_TPU_ANOMALY_WINDOW_SECS", "float", 900.0,
    "Look-back window the anomaly detectors evaluate over.",
    owner="obs/anomaly.py", group="obs",
)
ANOMALY_WINDOW_SCALE = _k(
    "NICE_TPU_ANOMALY_WINDOW_SCALE", "float", 1.0,
    "Scales every anomaly-detector window (short harness runs exercise"
    " real ok->page->ok transitions in seconds).",
    owner="obs/anomaly.py", group="obs",
)
ANOMALY_STUCK_CLAIMS = _k(
    "NICE_TPU_ANOMALY_STUCK_CLAIMS", "int", 5,
    "Claims inside the window after which a never-canon field counts as"
    " stuck.",
    owner="obs/anomaly.py", group="obs",
)
ANOMALY_OVERRIDES = _family(
    "NICE_TPU_ANOMALY_", ("_WARN", "_PAGE"), "float",
    "Per-detector warn/page threshold overrides, e.g."
    " NICE_TPU_ANOMALY_CLAIM_CHURN_PAGE.",
    owner="obs/anomaly.py", group="obs",
)
CRITPATH = _k(
    "NICE_TPU_CRITPATH", "bool", True,
    "Fleet critical-path engine: per-field latency waterfalls + dominant-"
    "segment classification served at GET /critpath and re-evaluated on"
    " every observatory beat.",
    owner="obs/critpath.py", group="obs",
)
CRITPATH_TOLERANCE = _k(
    "NICE_TPU_CRITPATH_TOLERANCE", "float", 0.15,
    "Reconciliation tolerance as a fraction of end-to-end wall-clock:"
    " a waterfall whose |wall - sum(segments)| exceeds"
    " max(fraction * wall, 0.25s) is reported as unreconciled (the residual"
    " is always visible in the unaccounted segment either way).",
    owner="obs/critpath.py", group="obs",
)
CRITPATH_WINDOW_FIELDS = _k(
    "NICE_TPU_CRITPATH_WINDOW_FIELDS", "int", 200,
    "How many recently canon-promoted fields the fleet-wide per-segment"
    " p50/p95 aggregation reads.",
    owner="obs/critpath.py", group="obs",
)
CRITPATH_SHIFT_RATIO = _k(
    "NICE_TPU_CRITPATH_SHIFT_RATIO", "float", 0.25,
    "Dominant-segment share change (absolute fraction of total) that"
    " counts as a bottleneck shift: emits the bottleneck_shift flight"
    " event and a critpath stream event.",
    owner="obs/critpath.py", group="obs",
)
STREAM_QUEUE = _k(
    "NICE_TPU_STREAM_QUEUE", "int", 256,
    "Per-subscriber event-queue capacity for GET /events/stream; a full"
    " queue drops the oldest events (counted per subscriber and fleet-"
    "wide).",
    owner="obs/stream.py", group="obs",
)
STREAM_HEARTBEAT_SECS = _k(
    "NICE_TPU_STREAM_HEARTBEAT_SECS", "float", 15.0,
    "SSE heartbeat cadence: an idle stream still writes one heartbeat"
    " event per interval (liveness signal + disconnect detection bound).",
    owner="obs/stream.py", group="obs",
)
STREAM_MAX_SUBSCRIBERS = _k(
    "NICE_TPU_STREAM_MAX_SUBSCRIBERS", "int", 64,
    "Concurrent GET /events/stream subscribers; past the cap new"
    " subscriptions get 503 (the dashboard falls back to polling).",
    owner="obs/stream.py", group="obs",
)
STREAM_MAX_DROPS = _k(
    "NICE_TPU_STREAM_MAX_DROPS", "int", 1024,
    "Slow-consumer eviction threshold: a subscriber that has dropped this"
    " many events is disconnected (it can resume via Last-Event-ID).",
    owner="obs/stream.py", group="obs",
)
MEMWATCH_SECS = _k(
    "NICE_TPU_MEMWATCH_SECS", "float", 30.0,
    "Resource-watch sampling cadence: device memory, host RSS and watched"
    " on-disk footprints land in the nice_mem_* / nice_disk_* series each"
    " interval (0 disables — zero threads, zero samples). The server"
    " samples on its observatory beat instead of a thread.",
    owner="obs/memwatch.py", group="obs",
)
MEMWATCH_HORIZON_SECS = _k(
    "NICE_TPU_MEMWATCH_HORIZON_SECS", "float", 3600.0,
    "Time-to-exhaustion forecast horizon: the resource_exhaustion detector"
    " pages when the observed leak slope would exhaust HBM/RSS/disk"
    " headroom within this many seconds.",
    owner="obs/memwatch.py", group="obs",
)
MEMWATCH_DISK_CAPACITY = _k(
    "NICE_TPU_MEMWATCH_DISK_CAPACITY", "int", None,
    "Override the watched filesystem's capacity in bytes for the"
    " exhaustion forecaster (unset = statvfs free space). Lets harness"
    " runs inject a deterministic headroom.",
    owner="obs/memwatch.py", group="obs",
    default_doc="statvfs free bytes",
)
PYPROF_HZ = _k(
    "NICE_TPU_PYPROF_HZ", "float", 5.0,
    "Statistical wall-clock profiler sampling rate: a sampler thread walks"
    " sys._current_frames() this many times per second and aggregates"
    " folded stacks per threadspec root (0 disables — zero threads, zero"
    " per-batch overhead).",
    owner="obs/pyprof.py", group="obs",
)
PYPROF_TOPK = _k(
    "NICE_TPU_PYPROF_TOPK", "int", 10,
    "How many of the hottest folded stacks ride on each telemetry snapshot"
    " for the fleet profile rollup (GET /profile/fleet).",
    owner="obs/pyprof.py", group="obs",
)
PYPROF_MAX_STACKS = _k(
    "NICE_TPU_PYPROF_MAX_STACKS", "int", 2000,
    "Bound on distinct folded stacks retained across all roots; past the"
    " cap new stacks collapse into the per-root (other) bucket (counted in"
    " nice_pyprof_overflow_total).",
    owner="obs/pyprof.py", group="obs",
)
PYPROF_DEPTH = _k(
    "NICE_TPU_PYPROF_DEPTH", "int", 24,
    "Deepest frames kept per sampled stack (outermost frames beyond the"
    " cap are elided).",
    owner="obs/pyprof.py", group="obs",
)

# -- chaos / fault injection -----------------------------------------------
FAULTS = _k(
    "NICE_TPU_FAULTS", "spec", None,
    'Fault-injection spec, "site:action@prob,..." (unset = chaos off).',
    owner="faults/injector.py", group="faults",
)
FAULTS_SEED = _k(
    "NICE_TPU_FAULTS_SEED", "int", 0,
    "Deterministic seed for the per-site fault RNGs.",
    owner="faults/injector.py", group="faults",
)

# -- lock diagnostics ------------------------------------------------------
LOCKDEP = _k(
    "NICE_TPU_LOCKDEP", "bool", False,
    "Runtime lock-order instrumentation: record cross-thread lock"
    " acquisition order, fail tests on cycles ('2'/'strict' additionally"
    " fails on long holds under a loop thread).",
    owner="utils/lockdep.py", group="lockdep",
)
LOCKDEP_HOLD_SECS = _k(
    "NICE_TPU_LOCKDEP_HOLD_SECS", "float", 0.25,
    "Hold-duration threshold above which a lock held on an event-loop"
    " thread is recorded as a long-hold violation.",
    owner="utils/lockdep.py", group="lockdep",
)

# -- static analysis (nicelint / jaxlint) ----------------------------------
JAXLINT_BASES = _k(
    "NICE_TPU_JAXLINT_BASES", "str", "40,80,510",
    "Comma-separated base sweep jaxlint traces kernel plans at (overridden"
    " by --bases).",
    owner="scripts/jaxlint.py", group="analysis",
)
JAXLINT_TRACE_BUDGET_SECS = _k(
    "NICE_TPU_JAXLINT_TRACE_BUDGET_SECS", "float", 3600.0,
    "Wall-clock budget for the jaxpr trace sweep; traces past the budget"
    " are skipped and reported (a skip fails --strict).",
    owner="scripts/jaxlint.py", group="analysis",
)
JAXLINT_RULES = _k(
    "NICE_TPU_JAXLINT_RULES", "str", None,
    "Comma-separated J-rule subset jaxlint runs (unset = all).",
    owner="scripts/jaxlint.py", group="analysis",
    default_doc="all rules",
)
JAXLINT_MAX_VARIANTS = _k(
    "NICE_TPU_JAXLINT_MAX_VARIANTS", "int", 1024,
    "Ceiling on the static-argument variant count J5 tolerates across the"
    " trace sweep before declaring the recompile surface unbounded.",
    owner="scripts/jaxlint.py", group="analysis",
)
RACELINT_RULES = _k(
    "NICE_TPU_RACELINT_RULES", "str", None,
    "Comma-separated R-rule subset racelint runs (unset = all).",
    owner="scripts/racelint.py", group="analysis",
    default_doc="all rules",
)
SCHEDEX = _k(
    "NICE_TPU_SCHEDEX", "bool", False,
    "Deterministic interleaving explorer: allow schedex to install its"
    " instrumented lock/queue/future wrappers. Off means no wrapper is"
    " ever installed — lockdep.make_lock stays on its zero-overhead path"
    " (asserted by test, same discipline as stepprof's no-sync"
    " guarantee).",
    owner="analysis/schedex.py", group="analysis",
)
SCHEDEX_SEEDS = _k(
    "NICE_TPU_SCHEDEX_SEEDS", "int", 8,
    "Number of seeded random schedules the explorer runs per scenario on"
    " top of the systematic preemption-bounded set.",
    owner="analysis/schedex.py", group="analysis",
)
SCHEDEX_PREEMPTIONS = _k(
    "NICE_TPU_SCHEDEX_PREEMPTIONS", "int", 2,
    "Preemption bound k for the systematic schedule enumeration (DPOR-"
    "lite): every schedule with at most k forced preemptions is explored"
    " up to the schedule cap.",
    owner="analysis/schedex.py", group="analysis",
)
SCHEDEX_MAX_SCHEDULES = _k(
    "NICE_TPU_SCHEDEX_MAX_SCHEDULES", "int", 256,
    "Cap on systematic schedules per scenario; past it the preemption-"
    "point pairs are stride-sampled deterministically.",
    owner="analysis/schedex.py", group="analysis",
)
SCHEDEX_TIMEOUT_SECS = _k(
    "NICE_TPU_SCHEDEX_TIMEOUT_SECS", "float", 30.0,
    "Watchdog timeout for one scheduled scenario run; a hang (against"
    " schedex's blocked-predicate design) fails the run rather than CI.",
    owner="analysis/schedex.py", group="analysis",
)

# --- Multi-tenant scheduler (nice_tpu/sched/) ------------------------------

TENANTS = _k(
    "NICE_TPU_TENANTS", "str", None,
    "Tenant spec list for the multi-tenant scheduler: semicolon-separated"
    " `name:mode:base[:opt...]` entries where mode is detailed, niceonly,"
    " near-miss, or hi-base and opts are prio=N, slo=SECS, bases=LO-HI,"
    " batch=N, backend=NAME (see README 'Multi-tenant scheduling'). Unset"
    " means the client runs single-workload as before.",
    owner="sched/tenants.py", group="sched",
    default_doc="single-workload mode",
)
SCHED_PAGE_BATCHES = _k(
    "NICE_TPU_SCHED_PAGE_BATCHES", "int", 4,
    "Page size in megaloop segments: one device page spans this many"
    " batch-aligned segments of the owning tenant's tuned"
    " batch_size*megaloop quantum, so every page boundary is an elastic"
    " interruption point.",
    owner="sched/pagetable.py", group="sched",
)
SCHED_QUANTUM_SECS = _k(
    "NICE_TPU_SCHED_QUANTUM_SECS", "float", 5.0,
    "Time-slice per tenant turn; the scheduler preempts at the next page"
    " boundary after this many seconds and rotates per policy. <=0"
    " disables time-based preemption (tenants drain a whole field per"
    " turn).",
    owner="sched/scheduler.py", group="sched",
)
SCHED_POLICY = _k(
    "NICE_TPU_SCHED_POLICY", "str", "deficit",
    "Tenant selection policy: deficit (priority-weighted deficit"
    " round-robin, default), priority (strict highest-priority-first),"
    " or rr (plain round-robin ignoring priorities).",
    owner="sched/scheduler.py", group="sched",
)
SCHED_STARVATION_ROUNDS = _k(
    "NICE_TPU_SCHED_STARVATION_ROUNDS", "int", 8,
    "Anti-starvation bound: a runnable tenant skipped this many"
    " consecutive scheduling rounds is force-scheduled next (emitting a"
    " tenant_starved flight event). <=0 disables the bound.",
    owner="sched/scheduler.py", group="sched",
)
SCHED_SLO_BOOST = _k(
    "NICE_TPU_SCHED_SLO_BOOST", "int", 2,
    "Priority points temporarily added to a tenant whose page-latency SLO"
    " is burning (warn state adds this once, page state twice), letting"
    " burn rates from obs/slo.py pull a lagging tenant forward.",
    owner="sched/scheduler.py", group="sched",
)

# -- replication & failover ------------------------------------------------
SERVERS = _k(
    "NICE_TPU_SERVERS", "str", None,
    "Comma-separated server endpoints for client failover"
    ' ("http://a:8000,http://b:8000"). Folded into --api-base; on'
    " conn_error/timeout/fence the client rotates to the next endpoint"
    " with the existing full-jitter backoff. Unset = single-server.",
    owner="client/api_client.py", group="repl",
)
REPL_POLL_SECS = _k(
    "NICE_TPU_REPL_POLL_SECS", "float", 0.5,
    "Standby op-log poll cadence against the upstream primary's"
    " /repl/ops. A full page triggers an immediate re-poll regardless.",
    owner="server/repl.py", group="repl",
)
REPL_BATCH_OPS = _k(
    "NICE_TPU_REPL_BATCH_OPS", "int", 500,
    "Max ops per /repl/ops page (one standby apply transaction).",
    owner="server/repl.py", group="repl",
)
REPL_RETENTION_OPS = _k(
    "NICE_TPU_REPL_RETENTION_OPS", "int", 200000,
    "Op-log retention: the primary periodically prunes repl_ops to the"
    " newest N rows; a standby further behind must re-seed from a"
    " snapshot of the primary's DB file. <=0 disables pruning.",
    owner="server/repl.py", group="repl",
)
REPL_KEY = _k(
    "NICE_TPU_REPL_KEY", "str", None,
    "Shared secret for the replication surface: when set, /repl/ops and"
    " /repl/promote require a matching X-Repl-Key header (op rows carry"
    " raw user_ip, which public_query redacts — gate before exposing"
    " beyond a trusted network). Unset = open (dev/smoke).",
    owner="server/repl.py", group="repl",
)


# ---------------------------------------------------------------------------
# Documentation rendering (docs/KNOBS.md + README tables). nicelint's K1
# rule regenerates these and diffs against the committed files.
# ---------------------------------------------------------------------------

_GROUP_TITLES = {
    "engine": "Engine / device pipeline",
    "client": "Client",
    "server": "Server coordination tier",
    "untrusted": "Untrusted-client hardening",
    "obs": "Observability",
    "faults": "Chaos / fault injection",
    "lockdep": "Lock diagnostics",
    "analysis": "Static analysis",
    "sched": "Multi-tenant scheduler",
    "repl": "Replication & failover",
    "general": "General",
}


def _table(knobs: List[Knob], families: List[PrefixFamily]) -> List[str]:
    lines = [
        "| Knob | Type | Default | Description |",
        "| --- | --- | --- | --- |",
    ]
    for k in knobs:
        lines.append(
            f"| `{k.name}` | {k.kind} | `{k.default_text}` | {k.doc} |"
        )
    for f in families:
        lines.append(f"| `{f.name}` | {f.kind} | per-spec | {f.doc} |")
    return lines


def render_group_markdown(group: str) -> str:
    """One group's knob table (the README embeds the 'untrusted' group)."""
    knobs = [k for k in all_knobs() if k.group == group]
    fams = [f for f in PREFIXES if f.group == group]
    return "\n".join(_table(knobs, fams))


def render_markdown() -> str:
    """The full docs/KNOBS.md body."""
    lines = [
        "# Environment knobs",
        "",
        "Generated from `nice_tpu/utils/knobs.py` by"
        " `python scripts/nicelint.py --write-docs` — do not edit by hand;"
        " the K1 lint rule fails when this file drifts from the registry.",
        "",
        "All knobs are read at call time (never cached at import), so tests"
        " and operators can flip them on a live process where the owning"
        " module documents that.",
    ]
    groups: Dict[str, List[Knob]] = {}
    for k in all_knobs():
        groups.setdefault(k.group, []).append(k)
    for f in PREFIXES:
        groups.setdefault(f.group, [])
    for group in sorted(groups, key=lambda g: list(_GROUP_TITLES).index(g)
                        if g in _GROUP_TITLES else 99):
        lines += ["", f"## {_GROUP_TITLES.get(group, group.title())}", ""]
        lines += _table(
            groups[group], [f for f in PREFIXES if f.group == group]
        )
        owners = sorted({k.owner for k in groups[group]}
                        | {f.owner for f in PREFIXES if f.group == group})
        lines += ["", f"Owning modules: {', '.join(f'`{o}`' for o in owners)}"]
    return "\n".join(lines) + "\n"
