"""Tenant specs and the registry for the multi-tenant scheduler.

A tenant is one named (mode, base) workload with its own priority, page-
latency SLO budget, optional base window (claim routing predicate), and its
own kernel-shape winners: the scheduler applies ``resolve_tuning`` per
tenant, so a hi-base detailed tenant and a low-base niceonly tenant each
run their tuned batch/megaloop shape while sharing one mesh.

Spec grammar (NICE_TPU_TENANTS / --tenants): semicolon-separated entries

    name:mode:base[:opt...]

where mode is ``detailed``, ``niceonly``, or one of the two built-in
scenario kinds — ``near-miss`` (standing low-priority NEAR_MISS_CUTOFF
re-scan of canon fields, runs the detailed engine) and ``hi-base``
(bases>510 sweep exercising the widened histogram tile) — and opts are
``prio=N``, ``slo=SECS``, ``bases=LO-HI``, ``batch=N``, ``backend=NAME``.

Example::

    canon:detailed:40:prio=3:slo=5;mining:near-miss:40;sweep:hi-base:520
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

from nice_tpu.utils import knobs

# Bases at or below this fit the pre-widening histogram tile; the hi-base
# sweep kind exists to exercise bases ABOVE it (ops/pallas_engine._hist_rows
# geometry: ceil((base+2)/128) rows, 4 rows <=> base 510).
HI_BASE_FLOOR = 510

_MODES = ("detailed", "niceonly")
_KINDS = ("standard", "near_miss", "hi_base_sweep")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One named workload. ``base`` is the claim default (and the engine
    plan when the source runs local fields); ``base_min``/``base_max``
    widen the claim window for sweep tenants. ``slo_page_secs`` <= 0 means
    no latency objective (the tenant never earns an SLO boost)."""

    name: str
    mode: str
    base: int
    priority: int = 1
    slo_page_secs: float = 0.0
    base_min: Optional[int] = None
    base_max: Optional[int] = None
    backend: str = "jax"
    batch_size: Optional[int] = None
    kind: str = "standard"

    def __post_init__(self) -> None:
        if not self.name or any(c in self.name for c in ":;= \t\n"):
            raise ValueError(f"bad tenant name {self.name!r}")
        if self.mode not in _MODES:
            raise ValueError(
                f"tenant {self.name}: mode must be one of {_MODES}, got"
                f" {self.mode!r}"
            )
        if self.kind not in _KINDS:
            raise ValueError(f"tenant {self.name}: unknown kind {self.kind!r}")
        if self.base < 4:
            raise ValueError(f"tenant {self.name}: base {self.base} < 4")
        if self.kind == "hi_base_sweep" and self.base <= HI_BASE_FLOOR:
            raise ValueError(
                f"tenant {self.name}: hi-base sweep needs base >"
                f" {HI_BASE_FLOOR}, got {self.base}"
            )
        if (
            self.base_min is not None
            and self.base_max is not None
            and self.base_min > self.base_max
        ):
            raise ValueError(
                f"tenant {self.name}: bases window {self.base_min}-"
                f"{self.base_max} is empty"
            )

    @property
    def claim_base_min(self) -> int:
        """Claim routing lower bound: the window when set, else the pinned
        base (a tenant never drains another tenant's base inventory)."""
        return self.base if self.base_min is None else self.base_min

    @property
    def claim_base_max(self) -> int:
        return self.base if self.base_max is None else self.base_max


def near_miss_tenant(
    base: int, name: str = "near-miss", priority: int = 0,
    slo_page_secs: float = 0.0,
) -> TenantSpec:
    """The standing near-miss mining tenant: a low-priority detailed
    re-scan of canon fields whose value is the NEAR_MISS_CUTOFF list (the
    detailed engine already emits every number at or above the cutoff);
    priority 0 means it only runs when higher tenants leave the mesh
    idle under the deficit policy."""
    return TenantSpec(
        name=name, mode="detailed", base=base, priority=priority,
        slo_page_secs=slo_page_secs, kind="near_miss",
    )


def hi_base_sweep_tenant(
    base: int = 520, name: str = "hi-base", priority: int = 1,
    slo_page_secs: float = 0.0,
) -> TenantSpec:
    """The bases>510 sweep tenant: detailed scans above the pre-widening
    histogram-tile floor, exercising the widened (up to 16-row) tile."""
    return TenantSpec(
        name=name, mode="detailed", base=base, priority=priority,
        slo_page_secs=slo_page_secs, kind="hi_base_sweep",
    )


def _parse_one(entry: str) -> TenantSpec:
    parts = [p.strip() for p in entry.split(":")]
    if len(parts) < 3:
        raise ValueError(
            f"tenant entry {entry!r}: want name:mode:base[:opt...]"
        )
    name, mode_arg, base_arg = parts[0], parts[1].lower(), parts[2]
    try:
        base = int(base_arg)
    except ValueError:
        raise ValueError(f"tenant {name}: base must be an integer, got"
                         f" {base_arg!r}")
    opts: dict = {}
    for opt in parts[3:]:
        if not opt:
            continue
        key, _, val = opt.partition("=")
        if key == "prio":
            opts["priority"] = int(val)
        elif key == "slo":
            opts["slo_page_secs"] = float(val)
        elif key == "bases":
            lo, _, hi = val.partition("-")
            opts["base_min"] = int(lo)
            opts["base_max"] = int(hi) if hi else int(lo)
        elif key == "batch":
            opts["batch_size"] = int(val)
        elif key == "backend":
            opts["backend"] = val
        else:
            raise ValueError(f"tenant {name}: unknown option {key!r}")
    if mode_arg == "near-miss":
        opts.setdefault("priority", 0)
        return TenantSpec(name=name, mode="detailed", base=base,
                          kind="near_miss", **opts)
    if mode_arg == "hi-base":
        return TenantSpec(name=name, mode="detailed", base=base,
                          kind="hi_base_sweep", **opts)
    return TenantSpec(name=name, mode=mode_arg, base=base, **opts)


def parse_tenants(text: str) -> list[TenantSpec]:
    """Parse the NICE_TPU_TENANTS grammar into specs (see module doc)."""
    specs = []
    for entry in text.split(";"):
        entry = entry.strip()
        if entry:
            specs.append(_parse_one(entry))
    return specs


class TenantRegistry:
    """Ordered set of uniquely-named tenants. Iteration order is
    registration order — the round-robin baseline every policy falls back
    to on ties."""

    def __init__(self, specs=()):
        self._specs: dict[str, TenantSpec] = {}
        for spec in specs:
            self.add(spec)

    def add(self, spec: TenantSpec) -> TenantSpec:
        if spec.name in self._specs:
            raise ValueError(f"duplicate tenant name {spec.name!r}")
        self._specs[spec.name] = spec
        return spec

    def replace(self, spec: TenantSpec) -> TenantSpec:
        """Swap in a new spec under an existing name (the mid-run priority
        flip sched_smoke exercises). The name must already be registered."""
        if spec.name not in self._specs:
            raise KeyError(f"no tenant {spec.name!r}")
        self._specs[spec.name] = spec
        return spec

    def get(self, name: str) -> TenantSpec:
        return self._specs[name]

    def names(self) -> list[str]:
        return list(self._specs)

    def __iter__(self) -> Iterator[TenantSpec]:
        return iter(self._specs.values())

    def __len__(self) -> int:
        return len(self._specs)

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    def slo_pairs(self) -> list[tuple[str, float]]:
        """(name, page budget secs) pairs for obs.slo.tenant_specs."""
        return [(s.name, s.slo_page_secs) for s in self]

    @classmethod
    def from_env(cls) -> "TenantRegistry":
        """Registry from NICE_TPU_TENANTS; empty when unset (the client
        then runs single-workload exactly as before)."""
        raw = knobs.TENANTS.raw()
        return cls(parse_tenants(raw) if raw else ())
