"""Field sources for the multi-tenant scheduler.

A source answers two questions per tenant: "what field should this tenant
run next?" and "what happens to a finished field's results?". StaticSource
serves a pre-built local list and collects results (tests, bench);
ServerSource claims from a live coordination server with tenant routing
(the claim row carries the tenant name, the claim engine restricts to the
tenant's base window) and submits results through the ordinary ledger
path, so a scheduler field is indistinguishable from a single-workload
client's field downstream of /submit.
"""

from __future__ import annotations

import logging
from typing import Optional

from nice_tpu.core.types import FieldResults, SearchMode
from nice_tpu.sched.tenants import TenantSpec

log = logging.getLogger("nice_tpu.sched")

# (field_key, base, range_start, range_end)
FieldHandle = tuple[str, int, int, int]


class StaticSource:
    """Local fields per tenant; completed results are collected for the
    caller to inspect. ``fields`` maps tenant name to a list of
    (field_key, base, start, end) tuples."""

    def __init__(self, fields: dict[str, list[FieldHandle]]):
        self._pending = {name: list(items) for name, items in fields.items()}
        self.results: dict[str, dict[str, FieldResults]] = {
            name: {} for name in fields
        }

    def next_field(self, spec: TenantSpec) -> Optional[FieldHandle]:
        queue = self._pending.get(spec.name)
        if not queue:
            return None
        return queue.pop(0)

    def complete(self, spec: TenantSpec, field_key: str,
                 results: FieldResults) -> None:
        self.results.setdefault(spec.name, {})[field_key] = results


class ServerSource:
    """Claims and submits against a live server, one claim per field.

    ``fields_per_tenant`` bounds how many fields each tenant will claim
    (None = until the server runs dry); a failed claim marks the tenant
    exhausted rather than crashing the scheduler — other tenants keep the
    mesh busy."""

    def __init__(self, api_base: str, username: str,
                 fields_per_tenant: Optional[int] = None,
                 max_retries: int = 3):
        self.api_base = api_base
        self.username = username
        self.fields_per_tenant = fields_per_tenant
        self.max_retries = max_retries
        self._claims: dict[str, object] = {}
        self._claimed_count: dict[str, int] = {}
        self.submitted: dict[str, list[int]] = {}

    def _mode(self, spec: TenantSpec) -> SearchMode:
        return (
            SearchMode.DETAILED if spec.mode == "detailed"
            else SearchMode.NICEONLY
        )

    def next_field(self, spec: TenantSpec) -> Optional[FieldHandle]:
        from nice_tpu.client import api_client

        taken = self._claimed_count.get(spec.name, 0)
        if (
            self.fields_per_tenant is not None
            and taken >= self.fields_per_tenant
        ):
            return None
        try:
            data = api_client.get_field_from_server(
                self._mode(spec), self.api_base, self.username,
                max_retries=self.max_retries,
                tenant=spec.name,
                base_min=spec.claim_base_min,
                base_max=spec.claim_base_max,
            )
        except api_client.ApiError as e:
            log.warning("tenant %s: claim failed (%s); marking exhausted",
                        spec.name, e)
            return None
        self._claimed_count[spec.name] = taken + 1
        field_key = f"{spec.name}/claim{data.claim_id}"
        self._claims[field_key] = data
        return field_key, data.base, data.range_start, data.range_end

    def complete(self, spec: TenantSpec, field_key: str,
                 results: FieldResults) -> None:
        from nice_tpu.client import api_client
        from nice_tpu.client.main import compile_results

        data = self._claims.pop(field_key)
        payload = compile_results(
            data, results, self._mode(spec), self.username
        )
        api_client.submit_field_to_server(
            self.api_base, payload, max_retries=self.max_retries
        )
        self.submitted.setdefault(spec.name, []).append(data.claim_id)
