"""Priority/deficit round-robin over tenant pages.

The scheduler sits above the elastic gang scheduler (parallel/mesh) and
below the field sources: each *round* it picks one tenant (policy +
SLO-burn boost + anti-starvation bound), runs that tenant's pages until
its time quantum expires, and preempts at the next page boundary — which
the PageTable guarantees is a megaloop segment boundary, i.e. one of the
elastic downshift's existing interruption points. Compile warms run off
the critical path before the dispatch loop (the compile-cache AOT layer),
so switching tenants re-enters warm executables with zero recompile
stalls.

Per-tenant SLO budgets feed back into scheduling: every page's wall time
lands in a scheduler-local HistoryStore under
``nice_sched_page_seconds{tenant="..."}``; an SloEngine built from
``obs.slo.tenant_specs`` evaluates burn rates, and a burning tenant earns
a temporary priority boost (NICE_TPU_SCHED_SLO_BOOST points per burn
level) that can preempt the incumbent at the next boundary.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from nice_tpu.core.types import FieldResults, FieldSize
from nice_tpu.obs import flight
from nice_tpu.obs.history import HistoryStore
from nice_tpu.obs.series import (
    SCHED_FIELDS,
    SCHED_MESH_OCCUPANCY,
    SCHED_OCCUPANCY,
    SCHED_PAGE_SECONDS,
    SCHED_PAGES,
    SCHED_PREEMPTIONS,
    SCHED_SLO_BURN,
    SCHED_STARVED,
)
from nice_tpu.obs.slo import SloEngine, tenant_specs
from nice_tpu.parallel.mesh import OccupancyMeter
from nice_tpu.sched.pagetable import PageTable
from nice_tpu.sched.tenants import TenantRegistry, TenantSpec
from nice_tpu.utils import knobs, lockdep

import logging

log = logging.getLogger("nice_tpu.sched")

_POLICIES = ("deficit", "priority", "rr")
_BURN_LEVELS = {"ok": 0, "warn": 1, "page": 2}


class MultiTenantScheduler:
    """Runs a TenantRegistry's workloads interleaved on one mesh.

    Injectable clocks keep the tests deterministic: ``clock`` (monotonic)
    drives quantum/occupancy accounting, ``wall`` (epoch) stamps history
    points for the SLO windows."""

    def __init__(
        self,
        registry: TenantRegistry,
        source,
        *,
        policy: Optional[str] = None,
        page_batches: Optional[int] = None,
        quantum_secs: Optional[float] = None,
        starvation_rounds: Optional[int] = None,
        slo_boost: Optional[int] = None,
        history: Optional[HistoryStore] = None,
        meter: Optional[OccupancyMeter] = None,
        clock=time.monotonic,
        wall=time.time,
    ):
        self.registry = registry
        self.source = source
        self.table = PageTable(page_batches)
        self.policy = policy if policy is not None else knobs.SCHED_POLICY.get()
        if self.policy not in _POLICIES:
            raise ValueError(
                f"unknown scheduler policy {self.policy!r}; want one of"
                f" {_POLICIES}"
            )
        self.quantum_secs = (
            quantum_secs if quantum_secs is not None
            else knobs.SCHED_QUANTUM_SECS.get()
        )
        self.starvation_rounds = (
            starvation_rounds if starvation_rounds is not None
            else knobs.SCHED_STARVATION_ROUNDS.get()
        )
        self.slo_boost = (
            slo_boost if slo_boost is not None else knobs.SCHED_SLO_BOOST.get()
        )
        self.history = history if history is not None else HistoryStore()
        self.slo = SloEngine(self.history, tenant_specs(registry.slo_pairs()))
        self.meter = meter if meter is not None else OccupancyMeter()
        self._clock = clock
        self._wall = wall
        # Guards the mutable per-tenant maps below: the run loop mutates
        # them while the optional sched-slo periodic and stats() readers
        # look on.
        self._lock = lockdep.make_lock(
            "sched.scheduler.MultiTenantScheduler._lock"
        )
        self._deficit = {s.name: 0.0 for s in registry}
        self._skipped = {s.name: 0 for s in registry}
        self._exhausted: set[str] = set()
        self._boost = {s.name: 0 for s in registry}
        self._rr_next = 0
        self.rounds = 0
        self.pages_run = {s.name: 0 for s in registry}
        self.fields_done = {s.name: 0 for s in registry}
        self.preemptions = {s.name: 0 for s in registry}
        self.starved = {s.name: 0 for s in registry}
        self._slo_thread: Optional[threading.Thread] = None
        self._slo_stop = threading.Event()

    # -- compile warm (off the critical path) ------------------------------

    def warm(self) -> None:
        """AOT-warm each tenant's executables before the dispatch loop so
        no tenant switch pays a compile stall. Warm failures degrade to
        first-dispatch compiles instead of killing the run."""
        import jax

        from nice_tpu.core import base_range
        from nice_tpu.ops import engine

        for spec in self.registry:
            try:
                if spec.mode == "detailed":
                    engine.warm_detailed(
                        spec.base, batch_size=spec.batch_size,
                        backend=spec.backend,
                    )
                elif jax.default_backend() == "tpu":
                    engine.warm_niceonly(spec.base)
                else:
                    # Off-TPU niceonly runs the dense path, which
                    # warm_niceonly does not compile — a 1-number probe
                    # through the tenant's own backend warms the kernel
                    # its pages will actually dispatch (bench.py's idiom).
                    br = base_range.get_base_range(spec.base)
                    start = br[0] if br else 1
                    engine.process_range_niceonly(
                        FieldSize(start, start + 1), spec.base,
                        backend=spec.backend, batch_size=spec.batch_size,
                    )
            except Exception as e:  # noqa: BLE001 — warm is best-effort
                log.warning("tenant %s: compile warm failed (%s)",
                            spec.name, e)

    # -- work feed ---------------------------------------------------------

    def _ensure_work(self, spec: TenantSpec) -> bool:
        """True when the tenant has at least one page queued (claiming a
        fresh field from the source if needed)."""
        if self.table.has_pages(spec.name):
            return True
        if spec.name in self._exhausted:
            return False
        handle = self.source.next_field(spec)
        if handle is None:
            with self._lock:
                self._exhausted.add(spec.name)
            return False
        field_key, base, start, end = handle
        self.table.add_field(spec, field_key, base, start, end)
        return True

    def _runnable(self) -> list[TenantSpec]:
        return [s for s in self.registry if self._ensure_work(s)]

    # -- tenant selection --------------------------------------------------

    def effective_priority(self, spec: TenantSpec) -> int:
        with self._lock:
            return spec.priority + self._boost.get(spec.name, 0)

    def _pick(self, runnable: list[TenantSpec]) -> TenantSpec:
        # Anti-starvation bound beats every policy: a tenant skipped past
        # the bound runs next, whatever its priority.
        if self.starvation_rounds > 0:
            with self._lock:
                overdue = [
                    s for s in runnable
                    if self._skipped[s.name] >= self.starvation_rounds
                ]
            if overdue:
                victim = max(overdue, key=lambda s: self._skipped[s.name])
                with self._lock:
                    self.starved[victim.name] += 1
                SCHED_STARVED.labels(victim.name).inc()
                flight.record(
                    "tenant_starved", tenant=victim.name,
                    skipped_rounds=self._skipped[victim.name],
                    policy=self.policy,
                )
                return victim
        if self.policy == "rr":
            names = [s.name for s in self.registry]
            for _ in range(len(names)):
                cand = names[self._rr_next % len(names)]
                self._rr_next += 1
                for s in runnable:
                    if s.name == cand:
                        return s
            return runnable[0]
        if self.policy == "priority":
            return max(runnable, key=self.effective_priority)
        # deficit: every runnable tenant accrues its (boosted) priority
        # weight each round; the largest accumulated deficit runs and
        # resets. Weight is priority+1 so a priority-0 tenant still
        # accrues and cannot starve outright.
        with self._lock:
            for s in runnable:
                boosted = s.priority + self._boost.get(s.name, 0)
                self._deficit[s.name] += boosted + 1
            chosen = max(runnable, key=lambda s: self._deficit[s.name])
            self._deficit[chosen.name] = 0.0
        return chosen

    # -- SLO feedback ------------------------------------------------------

    def _slo_tick(self, now: Optional[float] = None) -> None:
        """Evaluate per-tenant burn rates and refresh priority boosts."""
        results = self.slo.evaluate(now=self._wall() if now is None else now)
        boosts = {}
        for res in results:
            name = res["slo"]
            if not name.startswith("tenant_"):
                continue
            tenant = name[len("tenant_"):]
            level = _BURN_LEVELS.get(res["state"], 0)
            boosts[tenant] = level * self.slo_boost
            burn = res.get("burn_short")
            if burn is not None:
                SCHED_SLO_BURN.labels(tenant).set(burn)
        with self._lock:
            for tenant, boost in boosts.items():
                if tenant in self._boost:
                    self._boost[tenant] = boost

    def start_slo_thread(self, interval: float = 5.0) -> None:
        """Periodic burn evaluation for long runs (tests call _slo_tick
        synchronously instead). Declared in analysis/threadspec.py."""
        if self._slo_thread is not None:
            return
        self._slo_stop.clear()

        def _slo_run():
            while not self._slo_stop.wait(interval):
                self._slo_tick()

        self._slo_thread = threading.Thread(
            target=_slo_run, name="sched-slo", daemon=True
        )
        self._slo_thread.start()

    def stop_slo_thread(self) -> None:
        if self._slo_thread is None:
            return
        self._slo_stop.set()
        self._slo_thread.join(timeout=10)
        self._slo_thread = None

    # -- page execution ----------------------------------------------------

    def _execute_page(self, spec: TenantSpec, page) -> FieldResults:
        from nice_tpu.ops import engine

        range_ = FieldSize(page.start, page.end)
        if spec.mode == "detailed":
            return engine.process_range_detailed(
                range_, page.base, backend=spec.backend,
                batch_size=spec.batch_size,
            )
        return engine.process_range_niceonly(
            range_, page.base, backend=spec.backend,
            batch_size=spec.batch_size,
        )

    def _preempt_reason(self, spec: TenantSpec, turn_started: float) -> str:
        """Why the incumbent should yield at this page boundary, or ''."""
        if (
            self.quantum_secs > 0
            and self._clock() - turn_started >= self.quantum_secs
        ):
            return "quantum"
        if self.policy != "rr":
            mine = self.effective_priority(spec)
            with self._lock:
                burning = [
                    name for name, boost in self._boost.items()
                    if boost > 0 and name != spec.name
                    and name not in self._exhausted
                ]
            for name in burning:
                other = self.registry.get(name)
                if (
                    self.effective_priority(other) > mine
                    and self.table.has_pages(name)
                ):
                    return "slo_boost"
        return ""

    def _run_turn(self, spec: TenantSpec) -> None:
        turn_started = self._clock()
        while True:
            nxt = self.table.next_page(spec.name)
            if nxt is None:
                if not self._ensure_work(spec):
                    return  # tenant drained mid-turn
                continue
            work, page = nxt
            t0 = self._clock()
            results = self._execute_page(spec, page)
            busy = self._clock() - t0
            drained = self.table.fold(work, page, results)
            with self._lock:
                self.pages_run[spec.name] += 1
            SCHED_PAGES.labels(spec.name).inc()
            SCHED_PAGE_SECONDS.labels(spec.name).observe(busy)
            self.meter.add_busy(spec.name, busy)
            self.history.add(
                f'nice_sched_page_seconds{{tenant="{spec.name}"}}',
                busy, ts=self._wall(),
            )
            if drained:
                with self._lock:
                    self.fields_done[spec.name] += 1
                SCHED_FIELDS.labels(spec.name).inc()
                self.source.complete(spec, work.field_key, work.result())
            self._slo_tick()
            reason = self._preempt_reason(spec, turn_started)
            if reason:
                # Only a preemption if the tenant actually had more work
                # queued — draining out on the same boundary is a clean
                # turn end.
                if self.table.has_pages(spec.name):
                    with self._lock:
                        self.preemptions[spec.name] += 1
                    SCHED_PREEMPTIONS.labels(spec.name, reason).inc()
                    flight.record(
                        "sched_preemption", tenant=spec.name, reason=reason,
                        field=work.field_key, cursor=work.cursor,
                    )
                return

    # -- main loop ---------------------------------------------------------

    def run(self, max_rounds: Optional[int] = None) -> dict:
        """Dispatch until every tenant drains (or max_rounds turns ran).
        Returns the stats() summary."""
        self.warm()
        self.meter.start(self._clock())
        try:
            while max_rounds is None or self.rounds < max_rounds:
                runnable = self._runnable()
                if not runnable:
                    break
                chosen = self._pick(runnable)
                with self._lock:
                    for s in runnable:
                        if s.name == chosen.name:
                            self._skipped[s.name] = 0
                        else:
                            self._skipped[s.name] += 1
                self._run_turn(chosen)
                self.rounds += 1
                self._publish_occupancy()
        finally:
            self.meter.stop(self._clock())
            self._publish_occupancy()
        return self.stats()

    def _publish_occupancy(self) -> None:
        now = self._clock()
        for tenant, share in self.meter.shares().items():
            SCHED_OCCUPANCY.labels(tenant).set(share)
        SCHED_MESH_OCCUPANCY.set(self.meter.occupancy(now))

    def stats(self) -> dict:
        with self._lock:
            per_tenant = {
                s.name: {
                    "pages": self.pages_run[s.name],
                    "fields": self.fields_done[s.name],
                    "preemptions": self.preemptions[s.name],
                    "starved": self.starved[s.name],
                    "busy_secs": self.meter.busy_secs(s.name),
                    "priority": s.priority,
                    "boost": self._boost[s.name],
                }
                for s in self.registry
            }
        return {
            "policy": self.policy,
            "rounds": self.rounds,
            "occupancy": self.meter.occupancy(self._clock()),
            "busy_secs": self.meter.busy_secs(),
            "wall_secs": self.meter.wall_secs(self._clock()),
            "tenants": per_tenant,
        }
