"""Fixed-size device pages over variable-size tenant fields.

The ragged-paging idea: tenants bring fields of arbitrary size, the mesh
wants fixed-shape dispatches. A page is a batch-aligned *segment quantum* —
``NICE_TPU_SCHED_PAGE_BATCHES`` megaloop segments of the owning tenant's
tuned ``batch_size * megaloop`` shape (ops/engine.page_quantum) — so every
page boundary lands exactly on a fused-scan segment boundary: a handoff
between tenants is an elastic interruption point, never a mid-dispatch cut,
and switching tenants re-enters an already-warm executable instead of
recompiling.

Each field's pages run in ascending order; per-page FieldResults fold into
the field accumulator (histogram counts add per num_uniques, nice numbers
concatenate and sort by number over disjoint sub-ranges), so the assembled
field result is byte-identical to one uninterrupted run. A preempted field
exports its accumulator in the engine's checkpoint-contract form, so the
standing crash-resume machinery (FieldCheckpointer + ``resume=``) carries
scheduler handoffs too.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from nice_tpu.core.types import (
    FieldResults,
    NiceNumberSimple,
    UniquesDistributionSimple,
)
from nice_tpu.sched.tenants import TenantSpec
from nice_tpu.utils import knobs


@dataclasses.dataclass(frozen=True)
class Page:
    """One fixed-quantum slice of one tenant's field: [start, end) with
    end - start a multiple of the tenant's segment quantum except for the
    field's final partial page."""

    tenant: str
    field_key: str
    base: int
    start: int
    end: int
    seq: int

    @property
    def size(self) -> int:
        return self.end - self.start


class FieldWork:
    """One field's pages plus its fold accumulator."""

    def __init__(self, spec: TenantSpec, field_key: str, base: int,
                 start: int, end: int, pages: list[Page]):
        self.spec = spec
        self.field_key = field_key
        self.base = base
        self.start = start
        self.end = end
        self.pages = pages
        self.next_page = 0
        # Histogram bins 0..base+1, matching the engine's checkpoint hist.
        self._hist = np.zeros(base + 2, dtype=np.int64)
        self._nice: list[NiceNumberSimple] = []
        self._downgrades: list[str] = []
        self.cursor = start  # first number NOT yet folded

    @property
    def done(self) -> bool:
        return self.next_page >= len(self.pages)

    def peek_page(self) -> Optional[Page]:
        return None if self.done else self.pages[self.next_page]

    def fold(self, page: Page, results: FieldResults) -> None:
        """Fold one executed page. Pages must arrive in order — the
        accumulator is a prefix of the field."""
        if self.done or page is not self.pages[self.next_page]:
            raise ValueError(
                f"page {page.seq} folded out of order for {self.field_key}"
            )
        for row in results.distribution:
            self._hist[row.num_uniques] += row.count
        self._nice.extend(results.nice_numbers)
        for d in results.backend_downgrades:
            if d not in self._downgrades:
                self._downgrades.append(d)
        self.next_page += 1
        self.cursor = page.end

    def result(self) -> FieldResults:
        """The assembled field result, byte-identical to one uninterrupted
        engine run: detailed distributions are the 1..base rows of the
        summed histogram; nice numbers sort by value (sub-ranges are
        disjoint, so there are no ties to break)."""
        if not self.done:
            raise ValueError(f"field {self.field_key} still has pages")
        if self.spec.mode == "detailed":
            dist = tuple(
                UniquesDistributionSimple(num_uniques=i, count=int(self._hist[i]))
                for i in range(1, self.base + 1)
            )
        else:
            dist = ()
        nice = tuple(sorted(self._nice, key=lambda x: x.number))
        return FieldResults(
            distribution=dist,
            nice_numbers=nice,
            backend_downgrades=tuple(self._downgrades),
        )

    def resume_state(self) -> dict:
        """The accumulator in the engine's checkpoint-contract form: feed
        it to ``process_range_detailed/niceonly(resume=...)`` (or persist
        it through FieldCheckpointer) and the field completes byte-
        identically from the preemption point."""
        return {
            "cursor": self.cursor,
            "hist": self._hist.copy() if self.spec.mode == "detailed" else None,
            "nice_numbers": [(n.number, n.num_uniques) for n in self._nice],
            "remaining": (
                [] if self.cursor >= self.end else [[self.cursor, self.end]]
            ),
            "filtered": False,
        }


class PageTable:
    """Packs tenant fields into pages and tracks per-tenant page queues."""

    def __init__(self, page_batches: Optional[int] = None):
        self.page_batches = (
            page_batches if page_batches is not None
            else max(1, knobs.SCHED_PAGE_BATCHES.get())
        )
        self._fields: dict[str, FieldWork] = {}
        # Per-tenant FIFO of field keys with pages left.
        self._queues: dict[str, list[str]] = {}

    def quantum_for(self, spec: TenantSpec, base: Optional[int] = None) -> int:
        """Page size in numbers for one tenant workload: page_batches
        segment quanta of the tenant's OWN tuned shape (resolve_tuning per
        tenant, not per process)."""
        from nice_tpu.ops import engine

        return self.page_batches * engine.page_quantum(
            spec.mode, base if base is not None else spec.base,
            spec.backend, spec.batch_size,
        )

    def add_field(self, spec: TenantSpec, field_key: str, base: int,
                  start: int, end: int) -> FieldWork:
        if end <= start:
            raise ValueError(f"empty field {field_key}: [{start}, {end})")
        if field_key in self._fields:
            raise ValueError(f"field {field_key} already paged")
        quantum = self.quantum_for(spec, base)
        pages = []
        cursor = start
        seq = 0
        while cursor < end:
            page_end = min(cursor + quantum, end)
            pages.append(Page(
                tenant=spec.name, field_key=field_key, base=base,
                start=cursor, end=page_end, seq=seq,
            ))
            cursor = page_end
            seq += 1
        work = FieldWork(spec, field_key, base, start, end, pages)
        self._fields[field_key] = work
        self._queues.setdefault(spec.name, []).append(field_key)
        return work

    def has_pages(self, tenant: str) -> bool:
        return bool(self._queues.get(tenant))

    def pending_pages(self, tenant: str) -> int:
        return sum(
            len(self._fields[k].pages) - self._fields[k].next_page
            for k in self._queues.get(tenant, ())
        )

    def next_page(self, tenant: str) -> Optional[tuple[FieldWork, Page]]:
        """The tenant's next page (front field, ascending page order), or
        None when the tenant has no queued work."""
        queue = self._queues.get(tenant)
        if not queue:
            return None
        work = self._fields[queue[0]]
        page = work.peek_page()
        if page is None:  # defensive: drained fields leave the queue in fold
            queue.pop(0)
            return self.next_page(tenant)
        return work, page

    def fold(self, work: FieldWork, page: Page,
             results: FieldResults) -> bool:
        """Fold an executed page; returns True when its field just
        drained (and left the tenant queue)."""
        work.fold(page, results)
        if work.done:
            self._queues[work.spec.name].remove(work.field_key)
            return True
        return False

    def field(self, field_key: str) -> FieldWork:
        return self._fields[field_key]

    def check_invariants(self) -> list[str]:
        """Packing invariants, as violation strings (tests assert empty):
        pages of a field are contiguous, non-overlapping, cover [start,
        end) exactly, carry one (tenant, base) — one limb plan — per page
        list, and only the final page may be quantum-short."""
        problems = []
        for key, work in self._fields.items():
            if not work.pages:
                problems.append(f"{key}: no pages")
                continue
            quantum = self.quantum_for(work.spec, work.base)
            cursor = work.start
            for page in work.pages:
                if page.start != cursor:
                    problems.append(
                        f"{key} page {page.seq}: starts at {page.start},"
                        f" expected {cursor} (gap/overlap)"
                    )
                if page.tenant != work.spec.name or page.base != work.base:
                    problems.append(
                        f"{key} page {page.seq}: crosses limb plans"
                        f" ({page.tenant}/{page.base} in a"
                        f" {work.spec.name}/{work.base} field)"
                    )
                if page.size != quantum and page is not work.pages[-1]:
                    problems.append(
                        f"{key} page {page.seq}: interior page of size"
                        f" {page.size}, quantum {quantum}"
                    )
                if page.size <= 0 or page.size > quantum:
                    problems.append(
                        f"{key} page {page.seq}: size {page.size} outside"
                        f" (0, {quantum}]"
                    )
                cursor = page.end
            if cursor != work.end:
                problems.append(
                    f"{key}: pages end at {cursor}, field ends at {work.end}"
                )
        return problems
