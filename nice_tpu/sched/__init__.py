"""Multi-tenant ragged scheduler: pack heterogeneous (mode, base)
workloads onto one pod.

Layering: sched sits above ops/engine (pages run through the ordinary
process_range_* entry points, so crash-resume, elastic downshift, and the
megaloop all apply unchanged) and above parallel/mesh (occupancy
accounting); obs provides the per-tenant SLO burn feedback. Nothing under
nice_tpu/ imports sched — the client opts in via NICE_TPU_TENANTS /
--tenants, and the server only sees the tenant name on claim rows.
"""

from nice_tpu.sched.pagetable import FieldWork, Page, PageTable
from nice_tpu.sched.scheduler import MultiTenantScheduler
from nice_tpu.sched.source import ServerSource, StaticSource
from nice_tpu.sched.tenants import (
    TenantRegistry,
    TenantSpec,
    hi_base_sweep_tenant,
    near_miss_tenant,
    parse_tenants,
)

__all__ = [
    "FieldWork",
    "Page",
    "PageTable",
    "MultiTenantScheduler",
    "ServerSource",
    "StaticSource",
    "TenantRegistry",
    "TenantSpec",
    "hi_base_sweep_tenant",
    "near_miss_tenant",
    "parse_tenants",
]
