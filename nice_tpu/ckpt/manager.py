"""Per-field checkpoint lifecycle: save / validate / resume / delete.

The engine produces opaque resume states ({cursor, hist, nice_numbers} — see
ops/engine.py's checkpoint_cb contract); this module binds one such stream to
a claimed field and a checkpoint directory:

  * FieldCheckpointer.save is the engine's checkpoint_cb — each call writes
    one atomic snapshot (ckpt/snapshot.py) carrying the field identity, the
    plan signature, and the scan state;
  * load() re-validates everything before any resume happens: CRC/version at
    the format layer, then the plan signature (mode, base, batch size,
    backend, jax fingerprint) and the field identity. A stale or mismatched
    snapshot is rejected (counted by reason, file removed) and the caller
    restarts the scan cleanly — never a silent resume into wrong state;
  * find_resumable() is the client's startup scan: the newest valid snapshot
    in the directory wins, so a restarted client picks up the same claim it
    died holding instead of claiming a fresh field.

Numbers that can exceed u64 (candidates run past 2^64 at bases 60+) travel
as decimal strings in the manifest; only the histogram rides in the binary
payload.
"""

from __future__ import annotations

import glob
import logging
import os
import time
from typing import Optional

import numpy as np

from nice_tpu.ckpt.snapshot import SnapshotError, read_snapshot, write_snapshot
from nice_tpu.core.types import DataToClient, SearchMode
from nice_tpu.obs import flight, journal
from nice_tpu.obs.series import CKPT_BYTES, CKPT_REJECTED, CKPT_WRITES

log = logging.getLogger("nice_tpu.ckpt")


def plan_signature(mode: SearchMode, base: int, backend: str,
                   batch_size: int | None) -> dict:
    """The compatibility fingerprint a snapshot must match to be resumed.

    Everything that changes what a batch cursor MEANS (mode, base, backend,
    batch size) plus the jax runtime fingerprint for device backends — a
    snapshot from a different jax build or platform is rejected rather than
    trusted across an upgrade boundary. batch_size None means "autotuned":
    the cursor is an absolute number position either way, so two autotuned
    runs match each other even if the tuned batch changed between them."""
    if backend in ("jax", "jnp", "pallas"):
        import jax

        runtime = f"jax-{jax.__version__}-{jax.default_backend()}"
    else:
        runtime = "host"
    return {
        "mode": "detailed" if mode == SearchMode.DETAILED else "niceonly",
        "base": base,
        "backend": backend,
        "batch_size": batch_size,
        "runtime": runtime,
        # State-contract version. 2 = per-slice "remaining" segment states
        # (pod-sliced subfields): a v2 snapshot's cursor alone does NOT
        # imply a covered prefix, so pre-slice consumers must reject it —
        # and v1 snapshots (no "state" key) are rejected here symmetrically
        # by plain signature inequality. 3 = megaloop segment states: the
        # remaining-set granularity is a whole megaloop segment
        # (batch_size * NICE_TPU_MEGALOOP_SEGMENT lanes per device), and
        # the folded histogram covers every SEGMENT before the marker —
        # a v2 consumer replaying a v3 snapshot at batch granularity (or
        # vice versa) would mis-split the remaining set, so v2 <-> v3
        # snapshots reject cleanly (reason "state_version").
        "state": 3,
    }


def _state_to_snapshot(state: dict) -> tuple[dict, dict[str, np.ndarray]]:
    manifest = {
        "cursor": str(int(state["cursor"])),
        "nice_numbers": [
            [str(int(n)), int(u)] for n, u in state["nice_numbers"]
        ],
        "near_miss_count": len(state["nice_numbers"]),
    }
    if state.get("remaining") is not None:
        # Per-slice cursors: the uncovered [start, end) segments (decimal
        # strings — candidates exceed u64 at bases 60+). "filtered" marks a
        # niceonly remaining-set whose gaps are provably empty.
        manifest["remaining"] = [
            [str(int(s)), str(int(e))] for s, e in state["remaining"]
        ]
        manifest["filtered"] = bool(state.get("filtered"))
    arrays: dict[str, np.ndarray] = {}
    if state.get("hist") is not None:
        arrays["hist"] = np.asarray(state["hist"], dtype=np.int64)
    return manifest, arrays


def _snapshot_to_state(manifest: dict, arrays: dict[str, np.ndarray]) -> dict:
    state = {
        "cursor": int(manifest["cursor"]),
        "hist": arrays.get("hist"),
        "nice_numbers": [
            (int(n), int(u)) for n, u in manifest["nice_numbers"]
        ],
    }
    if manifest.get("remaining") is not None:
        state["remaining"] = [
            (int(s), int(e)) for s, e in manifest["remaining"]
        ]
        state["filtered"] = bool(manifest.get("filtered"))
    return state


class FieldCheckpointer:
    """Checkpoint stream for one claimed field.

    save() is safe to hand to the engine as checkpoint_cb (it is invoked from
    the collector thread); load()/delete() run on the client main thread
    between fields, never concurrently with save().
    """

    def __init__(
        self,
        ckpt_dir: str,
        data: DataToClient,
        mode: SearchMode,
        backend: str,
        batch_size: int,
    ):
        self.dir = ckpt_dir
        self.data = data
        self.mode = mode
        self.signature = plan_signature(mode, data.base, backend, batch_size)
        os.makedirs(ckpt_dir, exist_ok=True)
        self.path = os.path.join(ckpt_dir, f"claim-{data.claim_id}.ckpt")

    # -- write side (engine checkpoint_cb) --------------------------------

    def save(self, state: dict) -> None:
        manifest, arrays = _state_to_snapshot(state)
        manifest["signature"] = self.signature
        manifest["field"] = self.data.to_json()
        nbytes = write_snapshot(self.path, manifest, arrays)
        CKPT_WRITES.inc()
        CKPT_BYTES.inc(nbytes)
        flight.record(
            "checkpoint", claim=self.data.claim_id,
            cursor=str(manifest["cursor"]), bytes=nbytes,
        )
        journal.record_client_event(
            "ckpt_save", claim_id=self.data.claim_id,
            cursor=str(manifest["cursor"]), bytes=nbytes,
        )
        log.debug(
            "checkpoint: claim %d cursor %s (%d bytes)",
            self.data.claim_id, manifest["cursor"], nbytes,
        )

    # -- read side ---------------------------------------------------------

    def load(self) -> Optional[dict]:
        """Validated resume state, or None (no snapshot / rejected one).

        A rejected snapshot is deleted so the scan restarts cleanly and the
        next checkpoint overwrites nothing stale."""
        t0 = time.monotonic()
        try:
            manifest, arrays = read_snapshot(self.path)
        except FileNotFoundError:
            return None
        except SnapshotError as e:
            log.warning("rejecting snapshot %s: %s", self.path, e)
            CKPT_REJECTED.labels(e.reason).inc()
            self.delete()
            return None
        if (
            manifest.get("signature") != self.signature
            or manifest.get("field") != self.data.to_json()
        ):
            log.warning(
                "rejecting snapshot %s: plan signature/field mismatch "
                "(snapshot %s/%s, current %s/%s)",
                self.path, manifest.get("signature"), manifest.get("field"),
                self.signature, self.data.to_json(),
            )
            snap_sig = manifest.get("signature")
            reason = "signature"
            if (
                isinstance(snap_sig, dict)
                and manifest.get("field") == self.data.to_json()
                and {k: v for k, v in snap_sig.items() if k != "state"}
                == {k: v for k, v in self.signature.items() if k != "state"}
            ):
                # Same plan, older/newer state contract (e.g. a pre-megaloop
                # v2 snapshot under a v3 engine): counted separately so a
                # fleet upgrade's restart cost is visible as such.
                reason = "state_version"
            CKPT_REJECTED.labels(reason).inc()
            self.delete()
            return None
        flight.record(
            "restore", claim=self.data.claim_id,
            cursor=str(manifest.get("cursor")),
        )
        state = _snapshot_to_state(manifest, arrays)
        # secs covers read + validation + state reconstruction — the
        # ckpt_resume segment of the field's critical-path waterfall.
        journal.record_client_event(
            "ckpt_resume", claim_id=self.data.claim_id,
            cursor=str(manifest.get("cursor")),
            secs=round(time.monotonic() - t0, 6),
        )
        return state

    def delete(self) -> None:
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass


def find_resumable(
    ckpt_dir: str, mode: SearchMode, backend: str, batch_size: int
) -> Optional[tuple[DataToClient, dict, "FieldCheckpointer"]]:
    """Startup scan: newest snapshot in ckpt_dir whose plan signature matches
    the current configuration. Returns (field, resume_state, checkpointer) or
    None. Snapshots that fail structural validation are rejected and removed;
    signature mismatches (e.g. a niceonly snapshot found by a detailed
    client) are left alone — another configuration may still resume them."""
    paths = sorted(
        glob.glob(os.path.join(ckpt_dir, "claim-*.ckpt")),
        key=os.path.getmtime,
        reverse=True,
    )
    for path in paths:
        try:
            manifest, arrays = read_snapshot(path)
        except FileNotFoundError:
            continue
        except SnapshotError as e:
            log.warning("rejecting snapshot %s: %s", path, e)
            CKPT_REJECTED.labels(e.reason).inc()
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
            continue
        try:
            data = DataToClient.from_json(manifest["field"])
        except (KeyError, TypeError, ValueError):
            log.warning("rejecting snapshot %s: malformed field record", path)
            CKPT_REJECTED.labels("corrupt").inc()
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
            continue
        ckptr = FieldCheckpointer(ckpt_dir, data, mode, backend, batch_size)
        if manifest.get("signature") != ckptr.signature:
            log.info(
                "snapshot %s has a different plan signature; not resuming it "
                "under this configuration", path,
            )
            continue
        return data, _snapshot_to_state(manifest, arrays), ckptr
    return None
