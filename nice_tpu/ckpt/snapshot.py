"""Versioned, CRC-guarded, atomic-rename snapshot files.

One snapshot is ONE file: a fixed magic/version header, a JSON manifest
(small structured state: plan signature, cursor, survivor list — values that
can exceed u64 are carried as decimal strings), an npz payload (the
host-folded histogram accumulator and any other arrays), and a trailing
CRC-32 over everything after the magic. The shape mirrors Orbax-style
training-state snapshots (manifest + array payload) scaled down to a single
field scan.

Durability contract:
  * writes go to a same-directory temp file, fsync, then os.replace — a
    reader never observes a half-written snapshot, and a crash mid-write
    leaves the previous snapshot intact;
  * reads re-verify magic, version, section lengths, and the CRC before any
    payload bytes are interpreted; every corruption mode raises
    SnapshotError (callers decide whether that means "restart cleanly").
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

import numpy as np

from nice_tpu import faults
from nice_tpu.utils import fsio

MAGIC = b"NICECKPT"
FORMAT_VERSION = 1

_LEN = struct.Struct("<I")  # little-endian u32 section length / CRC


class SnapshotError(Exception):
    """Unreadable snapshot: bad magic, unknown version, truncation, or CRC
    mismatch. The snapshot must be discarded, never partially trusted.

    reason: "corrupt" (CRC/truncation/parse) or "version" (format version
    this build cannot read) — label value for the rejected-snapshots counter.
    """

    def __init__(self, message: str, reason: str = "corrupt"):
        super().__init__(message)
        self.reason = reason


def write_snapshot(path: str, manifest: dict, arrays: dict[str, np.ndarray]) -> int:
    """Atomically write manifest + arrays to `path`; returns bytes written.

    The manifest gets `format_version` stamped in; arrays are packed as an
    uncompressed npz (the histogram is ~KBs — rename atomicity matters more
    than compression here).
    """
    manifest = dict(manifest)
    manifest["format_version"] = FORMAT_VERSION
    manifest_bytes = json.dumps(manifest, sort_keys=True).encode("utf-8")
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()

    body = (
        _LEN.pack(FORMAT_VERSION)
        + _LEN.pack(len(manifest_bytes))
        + manifest_bytes
        + _LEN.pack(len(payload))
        + payload
    )
    blob = MAGIC + body + _LEN.pack(zlib.crc32(body))

    # Chaos hook (ckpt.write): "truncate" persists only half the blob — a
    # power-loss-mid-write stand-in that read_snapshot must reject via the
    # CRC, proving the corrupt-snapshot detection path end to end.
    if faults.fire("ckpt.write", path=path) == "truncate":
        blob = blob[: len(blob) // 2]

    return fsio.atomic_write_bytes(path, blob)


def read_snapshot(path: str) -> tuple[dict, dict[str, np.ndarray]]:
    """Read and fully validate a snapshot; returns (manifest, arrays).

    Raises SnapshotError on any structural defect; raises FileNotFoundError
    if the file does not exist (distinct: "no snapshot" vs "bad snapshot").
    """
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(MAGIC) + 3 * _LEN.size or not blob.startswith(MAGIC):
        raise SnapshotError(f"{path}: not a snapshot (bad magic or truncated)")
    body, trailer = blob[len(MAGIC):-_LEN.size], blob[-_LEN.size:]
    if zlib.crc32(body) != _LEN.unpack(trailer)[0]:
        raise SnapshotError(f"{path}: CRC mismatch (corrupt or truncated)")
    off = 0
    (version,) = _LEN.unpack_from(body, off)
    off += _LEN.size
    if version != FORMAT_VERSION:
        raise SnapshotError(
            f"{path}: unsupported snapshot format version {version} "
            f"(this build reads {FORMAT_VERSION})",
            reason="version",
        )
    (mlen,) = _LEN.unpack_from(body, off)
    off += _LEN.size
    if off + mlen + _LEN.size > len(body):
        raise SnapshotError(f"{path}: manifest length exceeds file")
    try:
        manifest = json.loads(body[off:off + mlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise SnapshotError(f"{path}: manifest is not valid JSON: {e}") from e
    off += mlen
    (plen,) = _LEN.unpack_from(body, off)
    off += _LEN.size
    if off + plen != len(body):
        raise SnapshotError(f"{path}: payload length does not match file")
    try:
        with np.load(io.BytesIO(body[off:off + plen]), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    except (OSError, ValueError, KeyError) as e:
        raise SnapshotError(f"{path}: payload is not a valid npz: {e}") from e
    if manifest.get("format_version") != FORMAT_VERSION:
        raise SnapshotError(f"{path}: manifest/header version disagree")
    return manifest, arrays
